"""Checkpoint / resume: layout-independent on-disk snapshots.

The reference has NO checkpointing in its framework (SURVEY §5.4 — only its
PyTorch baseline script saves weights for divergence comparison). Here it is
a first-class subsystem, designed around the same principle as init and
hashing: checkpoints store the *logical* per-layer (W, b) blocks in global
layer order, so a model trained DP=2 x PP=4 can be saved and resumed
sequentially, or vice versa — the layout is a property of the run, not of
the checkpoint.

That principle is load-bearing for the ZeRO lattice (docs/performance.md):
a run whose parameters, gradients and optimizer state live as per-rank
block-cyclic shards (``--zero 2``/``3``) snapshots the SAME logical .npz
as everyone else — the session rehydrates the full logical tree on save
and re-deals it on load. Nothing layout-shaped touches disk, so elastic
re-sharding is free: kill a zero2-dp2 run and resume it zero1-dp4, or a
zero3-dp2 run sequentially, bitwise at restore
(tests/test_recovery.py::test_kill_resume_elastic_resharding).

Format: a single .npz (atomic rename on save) with arrays ``w{i}``/``b{i}``
per global layer, optional optimizer-state arrays ``ow{i}``/``ob{i}`` in the
same logical order (for stateful optimizers, e.g. momentum velocity), plus a
JSON metadata blob (sizes, global batch size, epoch, optimizer config).

Format v2 (additive; v1 files load unchanged) makes checkpoints the
RESUMABLE unit of fault tolerance (docs/robustness.md):

- a step cursor: ``global_step`` / ``step_in_epoch`` — a snapshot taken
  mid-epoch resumes exactly at its step, not at the last epoch boundary;
- a content ``checksum`` (sha256 over every array's bytes, name-sorted):
  a torn or bit-flipped file is DETECTED on load instead of silently
  training on garbage;
- an ``all_finite`` flag, so resume discovery can skip a snapshot flushed
  mid-blow-up (the health monitor's halt path) without re-reading it.

Step-checkpoint directories (``step-<global_step>.npz``, rotating retention)
plus ``find_latest_good`` — newest-first discovery that VERIFIES each
candidate and falls back past corrupt ones — are what ``--resume auto``
runs on. Loader errors surface as ``CheckpointError`` naming the path and
the suspected cause (zero-byte / truncated / wrong format / checksum
mismatch), never a raw NumPy/zipfile traceback.

The write path is staged so the ASYNC writer (``AsyncCheckpointWriter``)
and the synchronous ``save_checkpoint`` share one discipline
(docs/robustness.md "The async writer's crash windows"):

    build (host arrays + metadata, no verification)
      -> verify (sha256 content checksum + finiteness, stamped into the
         metadata INSIDE the file)
      -> mkstemp write -> fsync(file) -> atomic rename -> fsync(dir)
      -> rotation

in exactly that order, so a kill at ANY instant leaves only
fully-verifying snapshots rename-visible: a torn temp never matches
``STEP_CHECKPOINT_RE`` and is invisible to discovery, and rotation —
the only destructive stage — runs strictly after the new snapshot is
durable. The async writer is a single background thread behind a
BOUNDED in-flight queue: ``submit`` blocks when the queue is full
(backpressure — a snapshot is never silently dropped), ``drain`` blocks
until everything in flight is durable, and writer-side failures are
re-raised on the submitting thread at the next ``submit``/``drain`` —
never swallowed. Save-anchored fault injections (``die@save=N``,
``slow@save=N:ms=``, ``corrupt@save=N`` — faults.py) land at pinned
stages of this state machine so the chaos harness can kill a writer
INSIDE the write/verify/rename window deterministically.
"""

import hashlib
import json
import os
import queue as queue_mod
import re
import tempfile
import threading
import time
import zipfile
from pathlib import Path

import numpy as np

from shallowspeed_tpu import retry
from shallowspeed_tpu.model import ModelSpec, make_model_spec

FORMAT_VERSION = 2
SUPPORTED_VERSIONS = (1, 2)

STEP_CHECKPOINT_RE = re.compile(r"^step-(\d+)\.npz$")


class CheckpointError(RuntimeError):
    """A checkpoint file that cannot be trusted: unreadable, truncated,
    wrong format, or failing its content checksum. Carries the ``path``
    and a human ``cause`` so the error names what to look at."""

    def __init__(self, path, cause):
        self.path = str(path)
        self.cause = cause
        super().__init__(f"checkpoint {self.path}: {cause}")


def _flatten_logical(params_list):
    """Per-stage ragged params -> flat global layer list (host numpy)."""
    import jax

    out = []
    for stage in params_list:
        for layer in stage:
            out.append(
                (
                    np.asarray(jax.device_get(layer["W"]), np.float32),
                    np.asarray(jax.device_get(layer["b"]), np.float32).reshape(1, -1),
                )
            )
    return out


def _opt_prefix(key):
    """Array-name prefix for an optimizer-state part. The unnamed part
    (momentum's whole-state mirror) keeps the original ``ow{i}``/``ob{i}``
    names, so round-1 checkpoints load unchanged; named parts (Adam's m/v)
    get ``o_{key}_w{i}``."""
    return ("ow", "ob") if key == "" else (f"o_{key}_w", f"o_{key}_b")


def content_checksum(arrays):
    """sha256 over every non-meta array's name, dtype, shape and bytes, in
    name-sorted order — the torn/corrupt-file detector format v2 stores in
    (and verifies against) the metadata blob."""
    h = hashlib.sha256()
    for name in sorted(arrays):
        if name == "meta":
            continue
        a = np.ascontiguousarray(arrays[name])
        h.update(name.encode())
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


def build_snapshot(
    params_list,
    spec: ModelSpec,
    epoch: int,
    extra=None,
    opt_state=None,
    step_in_epoch=None,
    global_step=None,
):
    """Stage 1 of the write discipline: flatten the logical state into the
    ``(arrays, meta)`` pair a snapshot file holds — WITHOUT verification
    (no checksum, no finiteness scan). This is the only stage that touches
    device state (``_flatten_logical`` -> ``jax.device_get``), so it is
    the on-path cost of an async save; everything after it runs on host
    numpy and can move to the background writer."""
    flat = _flatten_logical(params_list)
    if len(flat) != len(spec.sizes) - 1:
        raise ValueError(
            f"param count {len(flat)} does not match spec sizes {spec.sizes}"
        )
    parts = (opt_state or {}).get("parts", {})
    scalars = (opt_state or {}).get("scalars", {})
    meta = {
        "format_version": FORMAT_VERSION,
        "sizes": list(spec.sizes),
        "act": spec.act,
        "global_batch_size": spec.global_batch_size,
        "epoch": int(epoch),
        "step_in_epoch": None if step_in_epoch is None else int(step_in_epoch),
        "global_step": None if global_step is None else int(global_step),
        "has_opt_state": "" in parts,  # legacy momentum flag (round-1 readers)
        "opt_parts": sorted(parts),
        "opt_scalars": {k: float(v) for k, v in scalars.items()},
        "extra": extra or {},
    }
    arrays = {}
    for i, (w, b) in enumerate(flat):
        arrays[f"w{i}"] = w
        arrays[f"b{i}"] = b
    for key, ragged in parts.items():
        pw, pb = _opt_prefix(key)
        flat_opt = _flatten_logical(ragged)
        if len(flat_opt) != len(flat):
            raise ValueError(
                f"optimizer-state part {key!r} layer count {len(flat_opt)} != "
                f"param count {len(flat)}"
            )
        for i, (ow, ob) in enumerate(flat_opt):
            if ow.shape != flat[i][0].shape or ob.shape != flat[i][1].shape:
                raise ValueError(
                    f"optimizer-state part {key!r} layer {i} shape "
                    f"{ow.shape}/{ob.shape} does not mirror the params "
                    f"{flat[i][0].shape}/{flat[i][1].shape}"
                )
            arrays[f"{pw}{i}"] = ow
            arrays[f"{pb}{i}"] = ob
    return arrays, meta


def stamp_verification(arrays, meta):
    """Stage 2: sha256 content checksum + finiteness scan over the EXACT
    arrays that will be written, stamped into the metadata (which lands
    inside the same atomic file). Returns the ``all_finite`` flag. Off the
    step path under the async writer — this is the stage whose cost the
    ``checkpoint`` record's ``verify_s`` field measures."""
    meta["checksum"] = content_checksum(arrays)
    meta["all_finite"] = bool(
        all(np.isfinite(a).all() for a in arrays.values())
    )
    return meta["all_finite"]


def write_snapshot(path, arrays, meta, fsync=True, pre_rename_hook=None):
    """Stage 3: the durable atomic write — mkstemp INSIDE the retried body
    (each attempt owns, and on any failure removes, its own temp file, so
    a mid-stream exception never leaks a ``*.npz.tmp`` beside the target),
    ``np.savez``, ``fsync`` of the file, atomic ``os.replace``, then
    ``fsync`` of the directory so the rename itself is durable — in that
    order, which is what makes a kill at any instant leave either the old
    directory state or the new fully-written file, never a torn
    rename-visible snapshot. Transient ``OSError`` retries under the
    shared bounded backoff. ``pre_rename_hook`` (fault injection only)
    runs after the temp file is durable and BEFORE the rename — the
    chaos harness's deterministic kill point inside the window. Returns
    bytes written."""
    path = Path(path)
    payload = dict(arrays)
    payload["meta"] = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    path.parent.mkdir(parents=True, exist_ok=True)

    def write_once():
        return atomic_write(
            path, lambda f: np.savez(f, **payload),
            suffix=".npz.tmp", fsync=fsync, pre_rename_hook=pre_rename_hook,
        )

    return retry.retry_call(write_once, attempts=3, retry_on=(OSError,))


def atomic_write(path, write_cb, suffix=".tmp", fsync=True,
                 pre_rename_hook=None):
    """The ONE durable-atomic-write sequence every on-disk artifact in this
    repo shares (step checkpoints here, AOT cache entries in
    aot_cache.py — a second hand-maintained copy would drift): mkstemp in
    the target directory, ``write_cb(file)``, ``fsync(file)``, atomic
    ``os.replace``, ``fsync(dir)`` — with the temp file removed on ANY
    failure, so a mid-stream exception never leaks a temp beside the
    target. ``pre_rename_hook(tmp)`` (fault injection only) runs after
    the temp is durable and before the rename — the deterministic kill
    point inside the window. Returns bytes written."""
    path = Path(path)
    fd, tmp = tempfile.mkstemp(dir=path.parent, suffix=suffix)
    try:
        with os.fdopen(fd, "wb") as f:
            write_cb(f)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        if pre_rename_hook is not None:
            pre_rename_hook(tmp)
        os.replace(tmp, path)
        if fsync:
            _fsync_dir(path.parent)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return os.path.getsize(path)


def _fsync_dir(dirpath):
    """fsync a directory so a just-renamed entry survives power loss —
    best-effort on filesystems/platforms that refuse O_RDONLY dir fds."""
    try:
        fd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_checkpoint(
    path,
    params_list,
    spec: ModelSpec,
    epoch: int,
    extra=None,
    opt_state=None,
    step_in_epoch=None,
    global_step=None,
):
    """Atomically write params (+ metadata) to ``path`` (.npz).

    ``opt_state``: optional logical optimizer state, as
    ``{"parts": {key: ragged_list}, "scalars": {key: float}}`` where each
    ragged_list has the SAME structure as ``params_list`` (state parts
    mirror the params — momentum velocity, Adam moments) — stored in the
    same logical layer order, so it is exactly as layout-independent as the
    weights; scalars (Adam's step count) go into the metadata blob.

    ``step_in_epoch`` / ``global_step``: the v2 resumable cursor — with
    them set, ``epoch`` means "the epoch IN PROGRESS" and resume restarts
    at exactly this optimizer step; without them (the legacy epoch-boundary
    save), ``epoch`` means "last COMPLETED epoch" and resume restarts at
    ``epoch + 1``. A mid-stream failure never leaves a temp file behind,
    and transient ``OSError`` on the write path is retried with bounded
    backoff (retry.retry_call) before surfacing.

    Returns ``(bytes_written, all_finite)`` — the finiteness flag that was
    stamped into the metadata, so callers can gate retention on it without
    re-scanning the arrays (a non-finite snapshot must never rotate the
    last healthy one away).
    """
    arrays, meta = build_snapshot(
        params_list, spec, epoch, extra=extra, opt_state=opt_state,
        step_in_epoch=step_in_epoch, global_step=global_step,
    )
    finite = stamp_verification(arrays, meta)
    nbytes = write_snapshot(path, arrays, meta)
    return nbytes, finite


def _partition(flat, spec: ModelSpec):
    """Flat global layer list -> per-stage ragged list for ``spec``."""
    out, k = [], 0
    for sspec in spec.stages:
        layers = []
        for _ in range(sspec.n_linears):
            w, b = flat[k]
            layers.append({"W": w, "b": b})
            k += 1
        out.append(layers)
    return out


def _read_arrays(path):
    """Open ``path`` and return ``(meta, arrays)`` with every failure mode
    translated into a ``CheckpointError`` naming the path and the suspected
    cause (raw NumPy/zipfile tracebacks name neither). Verifies the v2
    content checksum when the metadata carries one."""
    path = Path(path)
    try:
        size = path.stat().st_size
    except OSError as e:
        raise CheckpointError(path, f"cannot stat file ({e})") from e
    if size == 0:
        raise CheckpointError(
            path, "file is empty (zero bytes — torn write or placeholder)"
        )
    try:
        with np.load(path) as z:
            arrays = {name: z[name] for name in z.files}
    except zipfile.BadZipFile as e:
        raise CheckpointError(
            path,
            f"truncated or corrupt .npz archive ({e}) — the write likely "
            "died mid-stream",
        ) from e
    except (OSError, EOFError) as e:
        raise CheckpointError(path, f"unreadable ({e})") from e
    except ValueError as e:
        raise CheckpointError(
            path, f"not a .npz checkpoint (wrong format: {e})"
        ) from e
    if "meta" not in arrays:
        raise CheckpointError(
            path, "no metadata blob — not a shallowspeed checkpoint"
        )
    try:
        meta = json.loads(bytes(arrays["meta"]).decode())
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CheckpointError(
            path, f"metadata blob is not valid JSON ({e}) — corrupt file"
        ) from e
    if meta.get("format_version") not in SUPPORTED_VERSIONS:
        raise CheckpointError(
            path,
            f"unsupported format version {meta.get('format_version')!r} "
            f"(this reader understands {SUPPORTED_VERSIONS})",
        )
    saved_sum = meta.get("checksum")
    if saved_sum is not None:
        actual = content_checksum(arrays)
        if actual != saved_sum:
            raise CheckpointError(
                path,
                f"content checksum mismatch (stored {saved_sum[:12]}…, "
                f"recomputed {actual[:12]}…) — torn or corrupted write",
            )
    return meta, arrays


def verify_checkpoint(path, require_finite=False, with_arrays=False):
    """Full verification pass (read + parse + checksum): returns the
    metadata dict of a trustworthy checkpoint, raises ``CheckpointError``
    otherwise. ``require_finite=True`` additionally rejects snapshots whose
    arrays contain NaN/Inf (resume discovery uses this so a checkpoint
    flushed mid-blow-up is skipped in favor of the last healthy one).
    ``with_arrays=True`` returns ``(meta, arrays)`` — the verified read
    itself, so a caller that will load this snapshot does not read and
    checksum the file a second time (``assemble_checkpoint``)."""
    meta, arrays = _read_arrays(path)
    if require_finite:
        finite = meta.get("all_finite")
        if finite is None:  # v1 file: flag absent, check the arrays
            finite = all(
                np.isfinite(a).all()
                for name, a in arrays.items()
                if name != "meta" and np.issubdtype(a.dtype, np.floating)
            )
        if not finite:
            raise CheckpointError(
                path, "contains non-finite values (snapshot of a blown-up run)"
            )
    if with_arrays:
        return meta, arrays
    return meta


def load_checkpoint(path, n_stages: int, global_batch_size=None, with_opt_state=False):
    """Load a checkpoint and re-partition it for an ``n_stages`` layout.

    ``global_batch_size``: the CURRENT run's global batch size — it feeds the
    loss-scaling spec, so resurrecting the saved value when the run uses a
    different batch size would silently mis-scale every gradient. Defaults to
    the saved value for same-configuration resumes.

    Returns (params_list, spec, meta): params_list is per-stage ragged host
    numpy ready for ``jax.tree.map(jnp.asarray, ...)`` (sequential) or
    ``executor.stack_params`` (pipeline). With ``with_opt_state=True``,
    returns (params_list, spec, meta, opt_state) where opt_state is
    ``{"parts": {key: ragged_list}, "scalars": {key: float}}`` (each part
    mirrors params_list), or None when the checkpoint stored none.

    An unreadable / truncated / checksum-failing file raises
    ``CheckpointError`` naming the path and the suspected cause.
    """
    meta, z = _read_arrays(path)
    return assemble_checkpoint(
        path, meta, z, n_stages,
        global_batch_size=global_batch_size, with_opt_state=with_opt_state,
    )


def assemble_checkpoint(
    path, meta, z, n_stages: int, global_batch_size=None, with_opt_state=False
):
    """``load_checkpoint``'s second half: turn ALREADY-VERIFIED ``(meta,
    arrays)`` — e.g. the pair a ``with_arrays=True`` discovery returned —
    into the re-partitioned ``(params_list, spec, meta[, opt_state])``
    without re-reading the file. This is the single-verified-read resume
    path: discovery read and checksummed the snapshot once, and the
    discovery->load TOCTOU window (the file rotting, or a concurrent
    writer rotating it away, between the verify and a second read) is
    closed by construction because there IS no second read. ``path`` is
    used only to name errors."""
    try:
        n_layers = len(meta["sizes"]) - 1
        flat = [(z[f"w{i}"], z[f"b{i}"]) for i in range(n_layers)]
        # opt_parts supersedes has_opt_state; round-1 files have only the
        # latter (and only the unnamed part)
        part_keys = meta.get("opt_parts")
        if part_keys is None:
            part_keys = [""] if meta.get("has_opt_state") else []
        flat_parts = {}
        for key in part_keys:
            pw, pb = _opt_prefix(key)
            flat_parts[key] = [(z[f"{pw}{i}"], z[f"{pb}{i}"]) for i in range(n_layers)]
    except KeyError as e:
        raise CheckpointError(
            path, f"missing array {e} — truncated or foreign file"
        ) from e
    if global_batch_size is None:
        global_batch_size = meta["global_batch_size"]
    # pre-zoo snapshots carry no "act": every one of them is a relu MLP
    spec = make_model_spec(
        meta["sizes"], n_stages, global_batch_size,
        act=meta.get("act", "relu"),
    )
    params_list = _partition(flat, spec)
    # shape sanity against the re-partitioned spec
    for sspec, layers in zip(spec.stages, params_list):
        for l, layer in enumerate(layers):
            want = (sspec.local_sizes[l + 1], sspec.local_sizes[l])
            if layer["W"].shape != want:
                raise ValueError(
                    f"checkpoint layer shape {layer['W'].shape} != spec {want}"
                )
    if not with_opt_state:
        return params_list, spec, meta
    opt_state = None
    if flat_parts or meta.get("opt_scalars"):
        opt_state = {
            "parts": {k: _partition(v, spec) for k, v in flat_parts.items()},
            "scalars": dict(meta.get("opt_scalars", {})),
        }
    return params_list, spec, meta, opt_state


# ---------------------------------------------------------------------------
# step-checkpoint directories: rotation + crash-recovery discovery
# ---------------------------------------------------------------------------


def step_checkpoint_path(ckpt_dir, global_step):
    """Canonical name of the snapshot at ``global_step``: zero-padded so
    lexical order == step order (``step-00000042.npz``)."""
    return Path(ckpt_dir) / f"step-{int(global_step):08d}.npz"


def list_step_checkpoints(ckpt_dir):
    """``[(global_step, path), ...]`` ascending by step; [] for a missing
    directory (a fresh run's ``--resume auto`` finds nothing, starts clean)."""
    d = Path(ckpt_dir)
    if not d.is_dir():
        return []
    out = []
    for p in d.iterdir():
        m = STEP_CHECKPOINT_RE.match(p.name)
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out)


def rotate_step_checkpoints(ckpt_dir, keep, trusted=()):
    """Delete all but ``keep`` step snapshots; returns the removed paths.
    Retention is the corrupt-newest safety margin: fallback needs older
    snapshots to still exist.

    Ranking is usability-first, then step: a snapshot that fully verifies
    (checksum intact, all values finite — exactly ``find_latest_good``'s
    resume criteria) always outranks one that does not, regardless of step
    number. A blown-up or bit-rotted run leaves high-step unusable
    snapshots behind (a blow-up's own saves skip rotation — see
    ``save_step_checkpoint``); ranked purely by step they would crowd the
    healthy snapshots out of the keep window and rotation would delete the
    only ``resume='auto'`` targets — permanently unrecoverable. Instead
    the stale unusable pile is what rotation reclaims. Verification reads
    each candidate once per rotation; a caller that just wrote (and
    checksummed) snapshots in-process can list them in ``trusted`` to skip
    re-reading them (``TrainingSession`` passes the paths it wrote finite
    this run)."""
    if keep < 1:
        raise ValueError("keep must be >= 1")
    snaps = list_step_checkpoints(ckpt_dir)
    if len(snaps) <= keep:
        return []
    trusted = {Path(p).resolve() for p in trusted}

    def rank(item):
        step, path = item
        if path.resolve() in trusted:
            return (True, step)
        try:
            verify_checkpoint(path, require_finite=True)
        except CheckpointError:
            return (False, step)
        return (True, step)

    victims = [p for _, p in sorted(snaps, key=rank)[:-keep]]
    for p in victims:
        try:
            p.unlink()
        except OSError:
            pass  # retention is best-effort; a stale extra snapshot is harmless
    return victims


def find_newer_good(ckpt_dir, than_step=None, require_finite=True,
                    with_arrays=False):
    """Checkpoint-dir WATCHER discovery: the newest verifying step snapshot
    STRICTLY newer than ``than_step`` (``None`` accepts any step). Returns
    ``(step, path, meta, skipped)`` — ``skipped`` lists ``(path, cause)``
    for every newer candidate that failed verification — or
    ``(None, None, None, skipped)`` when nothing newer verifies. This is
    ``find_latest_good`` with a freshness floor: the serving engine's hot
    weight reload polls it between dispatches to pick up snapshots a
    concurrent training run keeps writing, without ever re-loading the
    snapshot it already serves.

    ``with_arrays=True`` returns ``(step, path, meta, arrays, skipped)``:
    the verified arrays themselves, so the reload that follows is the SAME
    read discovery verified — one read, no discovery->load TOCTOU window
    (exactly the property the watcher needs, since it polls a directory a
    concurrent trainer keeps writing and rotating)."""
    skipped = []
    for step, p in reversed(list_step_checkpoints(ckpt_dir)):
        if than_step is not None and step <= than_step:
            break  # list is step-ascending: nothing older can be newer
        try:
            got = verify_checkpoint(
                p, require_finite=require_finite, with_arrays=with_arrays
            )
        except CheckpointError as e:
            skipped.append((p, e.cause))
            continue
        if with_arrays:
            meta, arrays = got
            return step, p, meta, arrays, skipped
        return step, p, got, skipped
    if with_arrays:
        return None, None, None, None, skipped
    return None, None, None, skipped


def find_latest_good(ckpt_dir, require_finite=True, with_arrays=False):
    """Crash-recovery discovery: walk the step snapshots NEWEST FIRST,
    verify each (read + checksum + optional finiteness), and return
    ``(path, meta, skipped)`` for the first one that verifies — ``skipped``
    lists ``(path, cause)`` for every newer snapshot that failed (the
    evidence the recovery record carries). Returns ``(None, None, skipped)``
    when nothing in the directory verifies (or it is empty/missing).

    ``with_arrays=True`` returns ``(path, meta, arrays, skipped)`` — the
    verified read itself, for the single-verified-read resume/reload path
    (``assemble_checkpoint`` / ``TrainingSession.load_weights``): the
    caller loads exactly the bytes discovery checksummed, so nothing can
    rot or rotate away between the verify and the load."""
    skipped = []
    for _, p in reversed(list_step_checkpoints(ckpt_dir)):
        try:
            got = verify_checkpoint(
                p, require_finite=require_finite, with_arrays=with_arrays
            )
        except CheckpointError as e:
            skipped.append((p, e.cause))
            continue
        if with_arrays:
            meta, arrays = got
            return p, meta, arrays, skipped
        return p, got, skipped
    if with_arrays:
        return None, None, None, skipped
    return None, None, skipped


def find_step_at_or_before(ckpt_dir, step, require_finite=True):
    """Bisect-replay discovery (observability/divergence.py --bisect):
    the NEWEST verifying step snapshot with ``global_step <= step``.
    Returns ``(found_step, path, meta, skipped)`` — ``skipped`` lists
    ``(path, cause)`` for every candidate in range that failed
    verification — or ``(None, None, None, skipped)`` when nothing at or
    before ``step`` verifies. The digest at step N covers the params
    AFTER step N's update (= the ``step-(N+1)`` snapshot's contents), so
    the replayer restores at-or-before the last AGREEING step and trains
    forward to the first divergent one."""
    skipped = []
    for s, p in reversed(list_step_checkpoints(ckpt_dir)):
        if s > step:
            continue
        try:
            meta = verify_checkpoint(p, require_finite=require_finite)
        except CheckpointError as e:
            skipped.append((p, e.cause))
            continue
        return s, p, meta, skipped
    return None, None, None, skipped


# ---------------------------------------------------------------------------
# the async checkpoint writer
# ---------------------------------------------------------------------------


class AsyncCheckpointWriter:
    """One background thread that runs stages 2-4 of the write discipline
    (verify -> write-fsync-rename -> rotate) off the training step path.

    The step path keeps only stage 1 (device->host snapshot) plus the
    enqueue; everything that made the synchronous save expensive — the
    sha256 over every array, the finiteness scan, the zip write, the
    fsyncs — happens here, overlapped with the next dispatches. The
    crash-consistency contract is IDENTICAL to the synchronous path
    because the stages and their order are identical (shared helpers):
    a kill at any instant leaves only fully-verifying snapshots
    rename-visible, and rotation runs strictly after the new snapshot
    is durable.

    Concurrency contract:

    - ``submit`` BLOCKS while ``max_in_flight`` jobs are queued or being
      written — bounded backpressure; a snapshot is never dropped to
      keep the step loop fast (dropping would silently widen the replay
      window past the configured cadence);
    - jobs are processed strictly in submit order by ONE thread, so
      snapshots rename into place in step order and rotation never
      races a write;
    - ``drain`` blocks until the queue is empty and the in-flight job
      is durable; a writer-side exception is captured and re-raised
      (wrapped in ``CheckpointError`` when it isn't one) on the NEXT
      ``submit``/``drain`` call — the failure surfaces on the thread
      that owns the training loop, never into a daemon-thread
      traceback;
    - ``on_complete(result)`` (when given) runs ON THE WRITER THREAD
      after each successful save with a dict of path/bytes/finite and
      the per-stage timings — the session uses it to emit the
      ``checkpoint`` record and update its trusted-snapshot set.

    Fault injection (``faults.FaultPlan``, ``@save=N`` anchors): each
    job carries its save sequence number; ``due_at_save`` faults fire at
    pinned stages — ``corrupt`` flips the in-flight buffer after the
    checksum is stamped (the written file renames but never verifies),
    ``slow`` sleeps and ``die`` kills after the temp file is durable and
    BEFORE the rename (the torn-temp window: the kill leaves a
    ``*.npz.tmp`` that discovery cannot see).
    """

    def __init__(self, max_in_flight=2, faults=None, on_complete=None):
        if max_in_flight < 1:
            raise ValueError("max_in_flight must be >= 1")
        self._queue = queue_mod.Queue(maxsize=int(max_in_flight))
        self._faults = faults
        self._on_complete = on_complete
        # the one cross-thread mutable: failures append on the writer
        # thread and swap-drain on the submitting thread. The lock makes
        # the discipline explicit (and machine-checked — the house-rule
        # linter's SSP006 pass flags any unlocked touch) instead of
        # leaning on CPython list-op atomicity.
        self._errors_lock = threading.Lock()
        self._errors = []  # EVERY writer-side failure, in job order
        # completed trusted paths, writer-thread-confined: merged into
        # each job's (submit-time) trusted tuple so rotation never
        # re-verifies a snapshot that was still in flight when the next
        # one was submitted
        self._recent_trusted = []
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="ckpt-writer", daemon=True
        )
        self._thread.start()

    @property
    def queue_depth(self):
        """Jobs queued but not yet picked up by the writer (the
        backpressure signal the ``checkpoint`` record samples at enqueue
        time)."""
        return self._queue.qsize()

    def _raise_pending(self):
        """Surface writer-side failures on the submitting thread. EVERY
        failed job is kept (a disk-full burst fails several in a row, and
        swallowing the tail would let the caller believe those snapshots
        are durable); the first raises, carrying the rest by name."""
        with self._errors_lock:
            if not self._errors:
                return
            errs, self._errors = self._errors, []
        first = errs[0]
        if len(errs) > 1:
            rest = "; ".join(
                f"{type(e).__name__}: {e}"[:120] for e in errs[1:]
            )
            raise CheckpointError(
                "async-writer",
                f"{len(errs)} saves failed — first: "
                f"{type(first).__name__}: {first}; also: {rest}",
            ) from first
        raise first

    def submit(self, path, arrays, meta, save_seq, rotate_dir=None,
               rotate_keep=None, trusted=(), on_complete=None, build=None):
        """Enqueue one snapshot (stage-1 output) for background
        verify+write+rotate; blocks while the in-flight window is full.
        ``save_seq`` is the session's save sequence number — the fault
        anchor. ``rotate_dir``/``rotate_keep`` arm post-rename rotation
        (skipped automatically for non-finite snapshots, like the sync
        path); ``trusted`` is passed through to the rotation ranking —
        pass an IMMUTABLE snapshot (a tuple), never a live set another
        thread keeps mutating.
        ``on_complete`` rides WITH the job (falling back to the writer's
        default), so a record callback can never be applied to the wrong
        in-flight snapshot.

        ``build`` (instead of ``arrays``/``meta``): a zero-argument
        callable returning ``(arrays, meta)``, run ON THE WRITER THREAD
        before the save stages — the deferred logical-unstacking hook.
        The step path then carries only the raw device->host readback
        (which must stay on-path for consistency); the host-side
        reshaping of params/opt-state into the layout-independent
        snapshot form happens off-path, and its wall is reported as
        ``unstack_s`` in the completion dict. The callable must capture
        IMMUTABLE copies only (the training loop keeps mutating session
        state while the writer drains)."""
        self._raise_pending()
        if self._closed:
            raise ValueError("writer is closed")
        if (build is None) == (arrays is None):
            raise ValueError("submit takes arrays+meta or build, not both")
        self._queue.put(
            {
                "path": Path(path),
                "arrays": arrays,
                "meta": meta,
                "build": build,
                "save_seq": int(save_seq),
                "rotate_dir": rotate_dir,
                "rotate_keep": rotate_keep,
                "trusted": trusted,
                "on_complete": on_complete,
                "enqueue_t": time.perf_counter(),
            }
        )

    def drain(self):
        """Block until every submitted snapshot is durable (or the writer
        failed — the failure re-raises here). Safe to call repeatedly;
        the session's close/halt path and ``train.py``'s exit both run
        it, so a clean exit never leaves a snapshot in flight."""
        self._queue.join()
        self._raise_pending()

    def close(self):
        """Drain, then stop the writer thread. Idempotent."""
        if self._closed:
            self._queue.join()
            self._raise_pending()
            return
        self._queue.join()
        self._closed = True
        self._queue.put(None)  # wake the thread past the blocking get
        self._thread.join(timeout=30)
        self._raise_pending()

    # -- the writer thread ---------------------------------------------------

    def _run(self):
        while True:
            job = self._queue.get()
            if job is None:
                self._queue.task_done()
                return
            try:
                self._process(job)
            except BaseException as e:  # noqa: BLE001 — surfaced on drain
                with self._errors_lock:
                    self._errors.append(e)
            finally:
                self._queue.task_done()

    def _process(self, job):
        t0 = time.perf_counter()
        arrays, meta = job["arrays"], job["meta"]
        unstack_s = 0.0
        if job.get("build") is not None:
            # deferred logical unstacking (off the step path): the raw
            # device->host snapshot becomes the layout-independent
            # arrays+meta here, overlapped with training dispatches
            tb = time.perf_counter()
            arrays, meta = job["build"]()
            unstack_s = time.perf_counter() - tb
        result = run_save_stages(
            job["path"], arrays, meta,
            faults=self._faults, save_seq=job["save_seq"],
            rotate_dir=job["rotate_dir"], rotate_keep=job["rotate_keep"],
            # the job's submit-time tuple may predate an in-flight save
            # that has since completed; the writer-confined recent list
            # closes that gap so rotation never re-verifies it
            trusted=(*job["trusted"], *self._recent_trusted),
        )
        if result["trusted"]:
            self._recent_trusted.append(str(job["path"]))
        result["queued_s"] = t0 - job["enqueue_t"]
        result["unstack_s"] = unstack_s
        callback = job.get("on_complete") or self._on_complete
        if callback is not None:
            callback(result)


def run_save_stages(path, arrays, meta, faults=None, save_seq=0,
                    rotate_dir=None, rotate_keep=None, trusted=()):
    """Stages 2-4 of one save, with the save-anchored fault injections
    landed at their pinned points — shared VERBATIM by the async writer
    thread and the synchronous ``save_step_checkpoint`` path, so the two
    paths can never drift in stage order or crash windows:

    1. verify: checksum + finiteness stamped into the metadata;
    2. ``corrupt@save=N`` fires HERE — after the stamp, so the written
       file renames into place but can never verify (the bit-rot shape
       discovery must fall back past);
    3. mkstemp write + fsync; then ``slow@save=N`` sleeps and
       ``die@save=N`` kills — temp durable, rename NOT yet visible (the
       torn-temp window: the kill leaves nothing discovery can see);
    4. atomic rename + dir fsync;
    5. rotation (finite snapshots only — the non-finite pile must never
       rotate the last healthy snapshot away).

    Returns the completion dict (path/bytes/all_finite + per-stage
    timings) the ``checkpoint`` record is built from."""
    from shallowspeed_tpu import faults as F

    pending = faults.due_at_save(save_seq) if faults else ()
    t0 = time.perf_counter()
    finite = stamp_verification(arrays, meta)
    verify_s = time.perf_counter() - t0
    corrupted = False
    for f in pending:
        if f.kind == "corrupt" and not f.fired:
            f.fired = True
            corrupted = True
            F.corrupt_buffer(arrays)

    def window_hook(tmp):
        for f in pending:
            if f.fired:
                continue
            if f.kind == "slow":
                f.fired = True
                time.sleep(f.ms / 1000.0)
            elif f.kind == "die":
                faults.fire_die(f)  # sigkill never returns; exc raises

    t1 = time.perf_counter()
    nbytes = write_snapshot(path, arrays, meta, pre_rename_hook=window_hook)
    write_s = time.perf_counter() - t1
    # a corrupt-injected snapshot renamed into place but can never verify:
    # it must count as UNUSABLE everywhere the finite flag gates — rotation
    # must not run off it (it would rank as usable and could delete the
    # last good snapshot, the exact fallback the injection exists to
    # prove), and the caller must not add it to the trusted set
    usable = finite and not corrupted
    rotated = []
    if rotate_dir is not None and usable:
        # the snapshot JUST written (finite, checksummed in-process) joins
        # the trusted set for THIS rotation — without it every rotating
        # save would re-read and re-checksum the file it just produced,
        # exactly the redundant verify-read the trusted ranking exists to
        # skip. ``trusted`` itself must be an immutable snapshot taken by
        # the caller (tuple), never a live set another thread mutates:
        # rotation iterates it with syscalls in between.
        rotated = rotate_step_checkpoints(
            rotate_dir, rotate_keep,
            trusted=(*tuple(trusted), str(path)),
        )
    return {
        "path": Path(path),
        "meta": meta,
        "bytes": int(nbytes),
        "all_finite": finite,
        "trusted": usable,
        "verify_s": verify_s,
        "write_s": write_s,
        "queued_s": 0.0,
        "rotated": [str(p) for p in rotated],
    }
