"""Benchmark: MNIST-MLP training samples/sec/chip vs the NumPy reference.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "samples/s", "vs_baseline": N,
     "config": <headline-config label>,
     "value_fp32_highest": N|null, "vs_baseline_fp32_highest": N|null,
     "peak_hbm_bytes": N|null  (compiled headline program's peak memory,
      via the shared observability/program_audit.memory_stats path)}

The headline ``value`` is the fused+DEFAULT-precision config
(convergence-verified against the fp32 recipe — see main()); the
``*_fp32_highest`` companions carry the bitwise-NumPy-parity fp32 HIGHEST
measurement from the same process (null if only the headline cell survived
a mid-run tunnel failure).

Protocol (BASELINE.md: the reference publishes no numbers, so the baseline is
measured here): train the flagship 7-layer MLP (sizes [784,128,...,10],
GLOBAL_BATCH=128, 4 microbatches, SGD lr=0.006) on MNIST-sized data and
report end-to-end training throughput.

- baseline: an independent NumPy implementation of the identical training
  step (microbatch grad accumulation, global-batch loss scaling) timed on
  this host's CPU — the reference's compute engine (NumPy+BLAS) doing the
  reference's exact work.
- value: this framework's jitted whole-epoch lax.scan on the default JAX
  device (the TPU chip when run by the driver).
- vs_baseline: value / baseline  (>1 = faster than the NumPy reference).

Timing protocol: two-point slope with forced host readbacks (see
slope_epoch_seconds) — required because on the remote-TPU tunnel dispatch is
fully async and jax.block_until_ready can return before execution finishes,
which would otherwise measure dispatch latency and report physically
impossible throughput.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np


def _ensure_responsive_backend(probe_timeout_s=180, patience_s=None):
    """Never hang the benchmark on a wedged accelerator tunnel.

    Backend init for a remote-tunneled TPU can block indefinitely if the
    chip's claim is held by a dead client. When the tunnel plugin is active
    (PALLAS_AXON_POOL_IPS — the only configuration where the hang exists),
    probe device init in a subprocess; on timeout or init failure, fall back
    to the CPU platform. Returns ``(tag, diag)``: tag '' = healthy, else the
    metric-name suffix labeling the failure mode; ``diag`` is a JSON-able
    probe log (per-probe outcome + seconds) so an empty-chip round is
    self-describing in the published record, not just on stderr.

    Wedges are transient (observed recovery: tens of minutes) and a tagged
    CPU number is worth far less than a late chip number, so an unresponsive
    tunnel is re-probed until ``patience_s`` of wall clock is spent. The
    default is 600 s — deliberately well under the driver's window, because
    the caller has ALREADY published a complete CPU-fallback record before
    spending any patience here (round 3 burned a 1800 s default on probes
    and the driver's timeout killed bench.py before it printed anything).
    Override with SHALLOWSPEED_BENCH_PROBE_BUDGET_S (0 = single probe).
    A retry is launched only when a FULL probe still fits the budget, so
    total probe wall time cannot overshoot ``patience_s`` by more than the
    final sleep. A backend that fails FAST (init error, not a hang) is not
    retried — the real run would die the same way.

    stdout goes to DEVNULL and stderr to a temp FILE (never a pipe): a tunnel
    helper grandchild surviving the timeout kill would keep a captured pipe
    open and make the probe itself hang in communicate(), while a file lets
    us still report the backend's last error line.
    """
    diag = {"probes": [], "patience_s": None}
    if not os.environ.get("PALLAS_AXON_POOL_IPS"):
        return "", diag  # no tunnel plugin, nothing to guard (and nothing to pay)
    if patience_s is None:
        patience_s = float(os.environ.get("SHALLOWSPEED_BENCH_PROBE_BUDGET_S", "600"))
    diag["patience_s"] = patience_s
    # stderr goes to a FILE, not a pipe: a tunnel-helper grandchild surviving
    # the timeout kill would hold a pipe open and hang the probe itself
    import tempfile

    deadline = time.monotonic() + patience_s
    attempt = 0
    while True:
        attempt += 1
        t_probe = time.monotonic()
        with tempfile.TemporaryFile() as errf:
            # start_new_session: a timed-out probe must not leak a tunnel-
            # helper grandchild — the tunnel is single-client, so a surviving
            # helper would hold the claim and make every RETRY time out too
            # (the retry loop would then convert a transient wedge into a
            # guaranteed CPU fallback). Killing the whole process group
            # before the next attempt keeps the retries meaningful.
            proc = subprocess.Popen(
                [sys.executable, "-c", "import jax; jax.devices()"],
                stdout=subprocess.DEVNULL,
                stderr=errf,
                start_new_session=True,
            )
            try:
                rc = proc.wait(timeout=probe_timeout_s)
            except subprocess.TimeoutExpired:
                rc = None
                import signal

                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                proc.wait()
            probe_s = round(time.monotonic() - t_probe, 1)
            if rc == 0:
                diag["probes"].append({"outcome": "ok", "seconds": probe_s})
                return "", diag
            if rc is None:
                diag["probes"].append({"outcome": "timeout", "seconds": probe_s})
                detail = f"unresponsive (> {probe_timeout_s}s to init)"
                tag = "_CPU_FALLBACK_TUNNEL_UNRESPONSIVE"
                # retry only when a FULL probe still fits the budget — a
                # retry launched just before the deadline would overshoot
                # patience_s by up to probe_timeout_s (ADVICE r03)
                if deadline - time.monotonic() >= probe_timeout_s:
                    # bounded exponential backoff + jitter between probes
                    # (the shared retry policy — scripts/tunnel_watch.sh and
                    # the checkpoint writer use the same helper), clamped so
                    # the last probe still fits the patience budget
                    from shallowspeed_tpu import retry as _retry

                    delay = _retry.backoff_delay(
                        attempt - 1, base=20.0, factor=2.0, max_delay=120.0,
                        jitter=0.2, seed=os.getpid(),
                    )
                    print(
                        f"bench: tunnel probe {attempt} {detail}; retrying "
                        f"in {delay:.0f}s "
                        f"({deadline - time.monotonic():.0f}s of patience left)",
                        file=sys.stderr,
                    )
                    time.sleep(
                        min(delay,
                            max(0, deadline - time.monotonic() - probe_timeout_s))
                    )
                    continue
            else:
                # e.g. "UNAVAILABLE: TPU backend setup/compile error" — the
                # real run would die the same way; a degraded CPU number
                # beats none. Fail-fast errors are deterministic: no retry.
                errf.seek(0)
                tail = errf.read().decode(errors="replace").strip().splitlines()
                detail = f"failed to initialize ({tail[-1] if tail else 'no stderr'})"
                diag["probes"].append(
                    {"outcome": "init_failed", "seconds": probe_s, "error": detail}
                )
                tag = "_CPU_FALLBACK_BACKEND_INIT_FAILED"
        break
    print(f"bench: accelerator backend {detail}; falling back to CPU", file=sys.stderr)
    diag["failure"] = detail
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    return tag, diag

from shallowspeed_tpu.api import (  # the reference's canonical config
    FLAGSHIP_BATCH as B,
    FLAGSHIP_LR as LR,
    FLAGSHIP_MUBATCHES as M,
    FLAGSHIP_SIZES as SIZES,
)
N_SAMPLES = 59392  # MNIST train size after drop-last to 128-multiples


def flops_per_sample():
    """~FLOPs per training sample: fwd 2P + bwd 4P for P = sum(in*out).
    Delegates to the observability cost model so the benchmark, the MFU
    gauges and the run reports can never disagree on the definition."""
    from shallowspeed_tpu.observability.costmodel import mlp_train_flops_per_sample

    return mlp_train_flops_per_sample(SIZES)


def sync_readback(tree):
    """Force device completion by reading back the smallest leaf.

    On the axon remote-TPU tunnel, dispatch is fully asynchronous AND
    jax.block_until_ready can return before execution finishes (observed:
    5 dispatched epochs "ready" in 0.35 ms, then a 7 s readback). A host
    readback cannot lie — materializing an output's bytes requires the whole
    dependency chain to have executed — so every timing boundary here ends
    in one.
    """
    import jax

    leaves = jax.tree.leaves(tree)
    np.asarray(min(leaves, key=lambda a: a.nbytes))


_probe_jit = None


def probe_constants(tree):
    """Measure-able dispatch+readback: run a trivial jitted computation on
    the smallest leaf and read the FRESH result back. Re-reading an
    already-materialized array is free (jax.Array caches its host copy), so
    a zero-epoch "leg" must dispatch something new or it measures nothing.
    """
    global _probe_jit
    import jax

    if _probe_jit is None:
        _probe_jit = jax.jit(lambda x: x + 0.0)
    leaves = jax.tree.leaves(tree)
    np.asarray(_probe_jit(min(leaves, key=lambda a: a.nbytes)))


def slope_epoch_seconds(run_k, k1=2, k2=8, trials=3, min_delta_s=0.25):
    """Honest seconds-per-epoch via a two-point slope.

    ``run_k(k)`` must dispatch k epochs (advancing its own state) and end
    with a forced readback (sync_readback). Timing k1 and k2 epochs and
    taking (t2-t1)/(k2-k1) cancels both the constant dispatch cost and the
    constant readback/tunnel-RTT cost, leaving pure per-epoch device time —
    robust even when block_until_ready is untrustworthy (see sync_readback).

    The chip pool shows transient multi-tenant contention (observed 3.3 ms
    to 131 ms per epoch for identical work across claim windows), so each
    leg is measured `trials` times and the MINIMUM PER LEG is taken BEFORE
    differencing: each leg's minimum converges to its least-contended cost
    and the constants still cancel. (Taking min over per-trial slopes
    instead would be biased fast whenever a trial's k1 leg was contended
    while its k2 leg was not.)

    k==0 CONTRACT: with ``min_delta_s > 0`` the adaptation phase calls
    ``run_k(0)`` as a constants probe; run_k MUST respond by dispatching a
    fresh trivial computation and reading it back (what probe_constants
    does — see make_run_k), NOT by returning without touching the device.
    A no-op k==0 yields c0~0 and silently weakens leg adaptation.
    """
    return slope_epoch_seconds_many(
        {"_": run_k}, k1=k1, k2=k2, trials=trials, min_delta_s=min_delta_s
    )["_"]


def slope_epoch_seconds_many(
    run_ks, k1=2, k2=8, trials=3, min_delta_s=0.25, k_max=4096, failures=None
):
    """Interleaved two-point slopes for several configs at once.

    ``run_ks`` is ``{name: run_k}``. Each trial times the small and large
    legs of EVERY config back-to-back before the next trial, so all configs
    sample the same contention windows — measuring configs sequentially
    (minutes apart) lets pool contention invert a comparison (observed: the
    default-precision cell measuring 0.6x the fp32 cell it beats 1.8x in
    same-window pairs). Per-config estimation is then identical to
    slope_epoch_seconds (per-leg minima before differencing).

    ``min_delta_s`` > 0 enables LEG-SIZE ADAPTATION, which is what makes
    the estimate trustworthy on a high-RTT tunnel: dispatched epochs
    overlap the readback round-trip, so if a whole leg's device time fits
    inside the transport constants the k2-vs-k1 wall delta is pure noise
    and the slope explodes (observed: matrix cells "measuring" 1.65e9
    samples/s ~= 1.8 PFLOP/s when 8 epochs fit inside one ~80 ms RTT).
    Per config: measure the zero-epoch wall c0 (pure dispatch+readback
    constants), grow k1 until a k1-leg's device time is resolvable ABOVE
    those constants (wall - c0 >= min_delta_s — an unhidden small leg is
    what makes the constants actually cancel in the subtraction), and use
    k2 = 4*k1. If a cleaner later window shrinks the resolved delta back
    under min_delta_s, re-adapt (bounded) rather than publish an
    under-resolved slope.

    k==0 CONTRACT (when ``min_delta_s > 0``): each run_k must treat
    ``run_k(0)`` as a measurable constants probe — dispatch one fresh
    trivial computation and read it back (probe_constants), never a plain
    no-op return, or c0 is ~0 and the adaptation under-sizes the legs.
    run_ks built by make_run_k implement this.
    """
    names = list(run_ks)

    def leg(name, k):
        t0 = time.perf_counter()
        run_ks[name](k)
        return time.perf_counter() - t0

    k1s = {n: k1 for n in names}
    k2s = {n: k2 for n in names}
    t_smalls = {n: [] for n in names}
    t_larges = {n: [] for n in names}

    def adapt(name, k_start):
        """Grow the small leg until its device time clears the constants.
        Adaptation probes are sequential per config and are NOT recorded as
        trial data — only the interleaved trials below are, preserving the
        same-window property of every recorded sample."""
        c0 = min(leg(name, 0), leg(name, 0))
        k = min(max(2, k_start), k_max // 4)
        while True:
            t = leg(name, k)
            excess = t - c0
            if excess >= min_delta_s or k >= k_max // 4:
                break
            grow = (min_delta_s * 1.5) / excess if excess > 0 else 2.0
            k = min(k_max // 4, max(k * 2, int(k * grow) + 1))
        k1s[name], k2s[name] = k, 4 * k

    if min_delta_s > 0:
        for n in names:
            adapt(n, k1)
    for _ in range(trials):
        for n in names:
            t_smalls[n].append(leg(n, k1s[n]))
            t_larges[n].append(leg(n, k2s[n]))

    if min_delta_s > 0:
        # resolution recheck: if the least-contended legs resolve to less
        # than min_delta_s (the probe ran in a contended window, so the
        # chosen legs are too short for a clean window), re-adapt — an
        # under-resolved delta inflates throughput, never deflates it
        for _ in range(2):
            unresolved = [
                n
                for n in names
                if min(t_larges[n]) - min(t_smalls[n]) < min_delta_s
                and k2s[n] < k_max
            ]
            if not unresolved:
                break
            for n in unresolved:
                t_smalls[n].clear()
                t_larges[n].clear()
                adapt(n, k1s[n] * 2)
            for _ in range(trials):
                for n in unresolved:
                    t_smalls[n].append(leg(n, k1s[n]))
                    t_larges[n].append(leg(n, k2s[n]))

    out = {}
    for name in names:
        delta = min(t_larges[name]) - min(t_smalls[name])
        err = None
        if delta <= 0:
            err = (
                "slope timing failed: the large leg never measurably slower "
                f"than the small leg for {name!r} (device not actually "
                "executing the work?)"
            )
        elif min_delta_s > 0 and delta < min_delta_s:
            err = (
                f"slope timing failed: could not resolve {name!r} above "
                f"transport constants even at {k2s[name]} epochs/leg "
                "(extreme contention variance?) — refusing to publish an "
                "under-resolved (inflated) throughput"
            )
        if err is not None:
            # With a `failures` dict the caller keeps every healthy config's
            # result (one bad cell must not discard a whole chip-claim's
            # measurements); without one, refusing loudly is the contract.
            if failures is None:
                raise RuntimeError(err)
            failures[name] = err
            continue
        out[name] = delta / (k2s[name] - k1s[name])
    return out


def make_run_k(epoch_fn, params, opt_state, X, Y):
    """Build the timing harness for one epoch function: a ``run_k(k)`` that
    dispatches k epochs (advancing captured state, so donation stays legal)
    and ends in a forced readback. Compiles + warms up (one synced epoch)
    before returning — THE single definition of the measurement discipline,
    used by every path (measured_epoch_sps, jax_sps_many, the capture
    scripts)."""
    state = {"p": params, "s": opt_state}

    def run_k(k):
        p, s = state["p"], state["s"]
        if k == 0:
            # zero-epoch leg: measure the dispatch+readback constants with a
            # FRESH trivial computation — re-reading the already-materialized
            # params is served from the host cache and measures nothing
            probe_constants(p)
            return
        for _ in range(k):
            p, s, _ = epoch_fn(p, s, X, Y)
        state["p"], state["s"] = p, s
        sync_readback(p)

    run_k(1)  # compile + warmup, synced
    run_k(0)  # compile the constants probe too, outside any timed leg
    return run_k


def measured_epoch_sps(epoch_fn, params, opt_state, X, Y, trials=3):
    """Honest samples/sec for a compiled-or-compilable whole-epoch function.

    Shared timing-protocol entry point (bench.py, scripts/bench_tpu_matrix.py
    and scripts/tpu_capture.py all measure through here so the protocol is
    defined once). ``epoch_fn(params, opt_state, X, Y) -> (params, opt_state,
    loss)`` with donated params/opt_state; X is (num_batches, M, mb, D).
    """
    run_k = make_run_k(epoch_fn, params, opt_state, X, Y)
    samples_per_epoch = X.shape[0] * X.shape[1] * X.shape[2]
    return samples_per_epoch / slope_epoch_seconds(run_k, trials=trials)


def numpy_baseline_sps(n_batches=40):
    """Fresh NumPy training step (reference-equivalent math), timed."""
    from shallowspeed_tpu.init import linear_init

    params = [linear_init(SIZES[i], SIZES[i + 1]) for i in range(len(SIZES) - 1)]
    rng = np.random.RandomState(0)
    xb = rng.randn(M, B // M, SIZES[0]).astype(np.float32)
    yb = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (M, B // M))]

    def train_batch(params):
        acc = [(np.zeros_like(w), np.zeros_like(b)) for w, b in params]
        n = len(params)
        for x, t in zip(xb, yb):
            caches = []
            for i, (w, b) in enumerate(params):
                z = x @ w.T + b
                if i < n - 1:
                    caches.append((x, z > 0))
                    x = np.maximum(z, 0.0)
                else:
                    caches.append((x, None))
                    x = z
            ze = np.exp(x - np.max(x))
            p = ze / (ze.sum(axis=1, keepdims=True) + 1e-7)
            g = -2.0 * (t - p) / B
            gz = p * g
            g = gz - p * gz.sum(axis=1, keepdims=True)
            for i in reversed(range(n)):
                xi, mask = caches[i]
                if mask is not None:
                    g = g * mask
                acc[i] = (acc[i][0] + g.T @ xi, acc[i][1] + g.sum(0, keepdims=True))
                g = g @ params[i][0]
        return [
            (w - LR * gw, b - LR * gb) for (w, b), (gw, gb) in zip(params, acc)
        ]

    params = train_batch(params)  # warm BLAS
    t0 = time.perf_counter()
    for _ in range(n_batches):
        params = train_batch(params)
    dt = time.perf_counter() - t0
    return n_batches * B / dt


def _headline_data():
    """The headline measurement's model + data: ``(spec, params, X, Y)`` —
    the single definition shared by the slope measurement and the whole-run
    cross-check, so both provably measure the same model on the same data."""
    import jax
    import jax.numpy as jnp

    from shallowspeed_tpu import model as Mo

    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    nb = N_SAMPLES // B
    rng = np.random.RandomState(0)
    X = jnp.asarray(rng.rand(nb, M, B // M, SIZES[0]).astype(np.float32))
    Y = jnp.asarray(
        np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], (nb, M, B // M))]
    )
    return spec, params, X, Y


def _jax_epoch_setup(precision, unroll=None, megakernel=None, epoch_kernel=None):
    """Build the headline measurement setup (fused sequential epoch) at the
    named matmul precision: returns ``(epoch_fn, params, X, Y)``."""
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.api import PRECISIONS
    from shallowspeed_tpu.optimizer import SGD

    spec, params, X, Y = _headline_data()
    # fuse_mubatches: identical training (sum-gradient ledger), one full-batch
    # forward/backward per step — the TPU-shaped way to run the sequential
    # path. unroll: batch-scan unroll factor (bit-identical numerics); the
    # default can be overridden with the value scripts/tpu_capture.py measures
    # best on the chip. megakernel: the whole batch as ONE Pallas kernel;
    # epoch_kernel: the whole EPOCH as one kernel (bit-identical math,
    # shortest possible serial op chain — see docs/performance.md roofline);
    # both opt-in via env until chip-proven.
    if unroll is None:
        unroll = int(os.environ.get("SHALLOWSPEED_BENCH_UNROLL", "1"))
    if megakernel is None:
        megakernel = os.environ.get("SHALLOWSPEED_BENCH_MEGAKERNEL", "0") == "1"
    if epoch_kernel is None:
        epoch_kernel = os.environ.get("SHALLOWSPEED_BENCH_EPOCH_KERNEL", "0") == "1"
    epoch = trainer.make_train_epoch(
        spec, SGD(LR), precision=PRECISIONS[precision], fuse_mubatches=True,
        unroll=unroll, megakernel=megakernel, epoch_kernel=epoch_kernel,
    )
    return epoch, params, X, Y


def jax_sps(precision="highest", trials=5, unroll=None):
    """Measure the headline config at one matmul precision. The single
    definition of the measurement setup — the convergence-experiment script
    (scripts/tpu_default_precision.py) calls this too, so its same-window
    throughput pairs use the exact code path the published headline does."""
    return jax_sps_many((precision,), trials=trials, unroll=unroll)[precision]


def jax_sps_many(precisions, trials=5, unroll=None):
    """Measure several precision configs with INTERLEAVED trials (see
    slope_epoch_seconds_many: sequential cells minutes apart let pool
    contention invert a comparison). Returns ``{precision: samples/s}``."""
    run_ks = {}
    samples_per_epoch = None
    for precision in precisions:
        epoch, params, X, Y = _jax_epoch_setup(precision, unroll=unroll)
        run_ks[precision] = make_run_k(epoch, params, (), X, Y)
        samples_per_epoch = X.shape[0] * X.shape[1] * X.shape[2]
    slopes = slope_epoch_seconds_many(run_ks, trials=trials)
    return {p: samples_per_epoch / s for p, s in slopes.items()}


# Per-config physical plausibility ceiling for the timing guard: a v5e-class
# chip peaks ~100 TFLOP/s for fp32-accumulate-with-fp32-inputs (HIGHEST) and
# ~200 TFLOP/s for bf16-input MXU passes (DEFAULT). Anything above means the
# timing protocol was defeated (e.g. block_until_ready returning early) and
# the metric must be tagged, not published as-is.
_PLAUSIBLE_TFLOPS = {"highest": 100e12, "default": 200e12}


def crosscheck_whole_run_sps(precision="default", measured_sps=None, trials=3):
    """Independent cross-check: time N epochs as ONE device program
    (epochs-outer scan, single dispatch + single readback) by plain
    wall-clock. With ~2 s of device work per call, the one RTT+dispatch
    constant bounds the error to a few percent, and NO slope/estimator
    logic is involved — a protocol bug that inflates the slope-based
    headline cannot inflate this number, so the headline must stay within
    a small factor of it. Best-of-``trials`` (least-contended window) to be
    comparable with the min-based slope estimate.

    ``measured_sps`` (the slope-based estimate being cross-checked) sizes
    the run to ~2 s of expected device work — a fixed epoch count would be
    milliseconds on the chip but many minutes on a CPU-fallback backend."""
    from shallowspeed_tpu import trainer
    from shallowspeed_tpu.api import PRECISIONS
    from shallowspeed_tpu.optimizer import SGD

    spec, params, X, Y = _headline_data()
    samples_per_epoch = X.shape[0] * X.shape[1] * X.shape[2]
    if measured_sps:
        epochs = int(min(1000, max(20, 2.0 * measured_sps / samples_per_epoch)))
    else:
        epochs = 300
    run = trainer.make_train_run(
        spec, SGD(LR), precision=PRECISIONS[precision], fuse_mubatches=True,
        with_eval=False,
    )
    params, opt_state, losses = run(params, (), X, Y, epochs)  # compile+warm
    sync_readback(losses)
    best = None
    for _ in range(trials):
        t0 = time.perf_counter()
        params, opt_state, losses = run(params, opt_state, X, Y, epochs)
        sync_readback(losses)
        wall = time.perf_counter() - t0
        best = wall if best is None else min(best, wall)
    return samples_per_epoch * epochs / best


def _observed_backend():
    """The platform that ACTUALLY measured, asked of the live backend in the
    child — not inferred from env vars by the parent: the tunnel plugin's
    sitecustomize forces jax_platforms='axon,cpu', so a child whose tunnel
    init fails can silently fall back to host CPU while the parent's env
    still says the accelerator was in play."""
    import jax

    plat = jax.devices()[0].platform
    return "tpu" if plat in ("tpu", "axon") else plat


def _measure_child(precisions):
    """Child mode: measure the precisions with interleaved trials (so the
    published pair shares contention windows), printing one flushed JSON
    line per result so a parent that must kill a wedged child can still
    salvage output. Each line carries the OBSERVED backend platform. If the
    interleaved pass fails (e.g. slope refusal in one cell aborts it), fall
    back to independent per-cell measurement so one cell's deterministic
    failure cannot take the others down."""
    try:
        res = jax_sps_many(precisions)
        backend = _observed_backend()
        for precision, sps in res.items():
            print(
                json.dumps(
                    {
                        "precision": precision,
                        "sps": sps,
                        # a cell is same-window pairable only if THIS pass
                        # measured more than one precision: a retry child
                        # that measured a lone missing cell is in a
                        # different contention window than its partner
                        "interleaved": len(res) > 1,
                        "backend": backend,
                    }
                ),
                flush=True,
            )
        try:
            lb = crosscheck_whole_run_sps(
                "default", measured_sps=res.get("default")
            )
            print(json.dumps({"crosscheck_whole_run_sps": lb}), flush=True)
        except Exception as e:  # noqa: BLE001 — the cross-check is optional
            print(f"bench child: whole-run cross-check failed ({e!r})",
                  file=sys.stderr)
        try:
            # memory audit of the headline epoch program — the SAME shared
            # memory_analysis path the capture script and the session
            # audits read (observability/program_audit.memory_stats), so
            # the published peak_hbm_bytes cannot drift from theirs.
            # This is an extra AOT compile (the jit cache's executable is
            # not reachable from here), run LAST on purpose: every
            # measurement line is already flushed, so a watchdog kill
            # during this compile loses only the memory field
            from shallowspeed_tpu.observability.program_audit import memory_stats

            epoch, params, X, Y = _jax_epoch_setup("default")
            mem = memory_stats(epoch.lower(params, (), X, Y).compile())
            if mem and mem.get("peak_hbm_bytes") is not None:
                print(
                    json.dumps({"peak_hbm_bytes": mem["peak_hbm_bytes"]}),
                    flush=True,
                )
        except Exception as e:  # noqa: BLE001 — the audit is optional
            print(f"bench child: memory audit failed ({e!r})", file=sys.stderr)
        sys.exit(0)
    except Exception as e:  # noqa: BLE001 — isolate cells below
        print(
            f"bench child: interleaved pass failed ({e!r}); "
            "re-measuring cells independently",
            file=sys.stderr,
        )
    ok = True
    for precision in precisions:
        try:
            sps = jax_sps(precision)
        except Exception as e:  # noqa: BLE001 — report, continue, flag
            print(
                json.dumps({"precision": precision, "error": repr(e)}), flush=True
            )
            ok = False
            continue
        # interleaved=False: this cell was re-measured alone, so the
        # default/highest pair no longer shares contention windows — a
        # consumer must not trust the RATIO between such cells
        print(
            json.dumps(
                {
                    "precision": precision,
                    "sps": sps,
                    "interleaved": False,
                    "backend": _observed_backend(),
                }
            ),
            flush=True,
        )
    sys.exit(0 if ok else 4)


def _run_measurements(precisions, timeout_s, attempts=2, force_cpu=False):
    """Run the JAX measurements in a watchdog subprocess.

    The tunnel has been observed to wedge MID-RUN (after a healthy probe) —
    an in-process measurement would then hang the benchmark forever and the
    driver would record nothing. Isolating it in a killable child with
    per-result flushed output bounds the damage to ``attempts * timeout_s``
    and keeps any results completed before the wedge. Returns
    ``{precision: sps}`` for whatever succeeded, plus per-cell provenance
    in ``meta`` (``interleaved``: whether the cell came from the interleaved
    same-window pass; ``backend``: which platform measured it).

    stdout/stderr go to FILES, never pipes (same grandchild-survives-kill
    hazard as in _ensure_responsive_backend).
    """
    import tempfile

    env = dict(os.environ)
    if force_cpu:
        env.pop("PALLAS_AXON_POOL_IPS", None)  # ungate the tunnel plugin
        env["JAX_PLATFORMS"] = "cpu"
    backend = "cpu" if (force_cpu or not env.get("PALLAS_AXON_POOL_IPS")) else "tpu"
    results, errors, meta = {}, {}, {}
    saw_timeout = False
    for _ in range(attempts):
        missing = [p for p in precisions if p not in results]
        if not missing:
            break
        with tempfile.TemporaryFile() as outf, tempfile.TemporaryFile() as errf:
            try:
                subprocess.run(
                    [sys.executable, __file__, "--_measure", ",".join(missing)],
                    timeout=timeout_s,
                    stdout=outf,
                    stderr=errf,
                    env=env,
                )
            except subprocess.TimeoutExpired:
                saw_timeout = True
                print(
                    f"bench: measurement subprocess exceeded {timeout_s}s "
                    "(tunnel wedged mid-run?); salvaging completed results",
                    file=sys.stderr,
                )
            outf.seek(0)
            for line in outf.read().decode(errors="replace").splitlines():
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue  # non-JSON noise (e.g. plugin warnings)
                if not isinstance(rec, dict):
                    continue  # JSON-shaped noise (bare numbers/strings)
                if "crosscheck_whole_run_sps" in rec:
                    results["_crosscheck"] = rec["crosscheck_whole_run_sps"]
                elif "peak_hbm_bytes" in rec:
                    results["_peak_hbm_bytes"] = rec["peak_hbm_bytes"]
                elif "sps" in rec:
                    results[rec["precision"]] = rec["sps"]
                    meta[rec["precision"]] = {
                        "interleaved": bool(rec.get("interleaved", True)),
                        # prefer the child's OBSERVED platform; the env-based
                        # guess only covers legacy lines without the field
                        "backend": rec.get("backend", backend),
                    }
                    errors.pop(rec["precision"], None)
                elif "error" in rec:
                    errors[rec["precision"]] = rec["error"]
            if any(p not in results for p in precisions):
                errf.seek(0)
                tail = errf.read().decode(errors="replace").strip().splitlines()
                if tail:
                    print(f"bench: child stderr: {tail[-1]}", file=sys.stderr)
    for precision, err in errors.items():
        print(f"bench: {precision} measurement raised: {err}", file=sys.stderr)
    return results, saw_timeout, errors, meta


def _emit(record, warnings):
    for w in warnings:
        print(f"bench: {w}", file=sys.stderr)
    if record is not None:
        print(json.dumps(record), flush=True)
    return record is not None


def main():
    """Wedge-proof publication order (the round-3 lesson: BENCH_r03 was
    EMPTY because probe patience outlived the driver's window before any
    record was printed):

      1. With the tunnel env active, measure everything on the host CPU
         FIRST — the tunnel is never touched — and print a complete,
         labeled preliminary record. Whatever happens after this line
         (wedged probes, a mid-run tunnel hang, the driver's kill), a
         parseable record exists on stdout.
      2. Only then spend bounded probe patience on the tunnel (default
         600 s, well under the driver window).
      3. If the chip answers, measure there and print the upgraded record
         as the LAST stdout line (the driver parses the last JSON line);
         otherwise re-print the CPU record with the accurate failure tag.
         Either way the final record carries the probe diagnostics in a
         ``tunnel`` field, so an empty-chip round is self-describing.

    Headline config: fused microbatches + DEFAULT matmul precision
    (bf16-input, fp32-accumulate MXU passes). Convergence-equivalence of
    this config to the fp32-HIGHEST reference recipe is chip-verified:
    20-epoch flagship run reaches 99.40% val accuracy / 0.0168 final loss,
    epoch-for-epoch matching the HIGHEST trajectory (99.39% / 0.0168) —
    TPU_DEFAULT_PRECISION_r02.json, scripts/tpu_default_precision.py.
    The fp32-HIGHEST number (the bitwise-NumPy-parity config) is also
    measured and reported alongside.
    """
    tunnel_active = bool(os.environ.get("PALLAS_AXON_POOL_IPS"))
    baseline = numpy_baseline_sps()
    precisions = ("default", "highest")

    if not tunnel_active:
        # plain host run: no hang hazard to guard, nothing to pre-publish —
        # but a failed headline cell still falls back to a forced-CPU
        # re-measure so a tagged record beats no record
        results, saw_timeout, errors, meta = _run_measurements(
            precisions, timeout_s=900
        )
        tag = ""
        if "default" not in results:
            tag = (
                "_CPU_FALLBACK_TUNNEL_WEDGED_MIDRUN"
                if saw_timeout and "default" not in errors
                else "_CPU_FALLBACK_MEASUREMENT_FAILED"
            )
            print(
                f"bench: falling back to CPU for missing cells ({tag})",
                file=sys.stderr,
            )
            missing = tuple(p for p in precisions if p not in results)
            cpu_results, _, _, cpu_meta = _run_measurements(
                missing, timeout_s=900, attempts=1, force_cpu=True
            )
            results.update(cpu_results)
            meta.update(cpu_meta)
        record, warnings = build_record(
            results, meta, baseline, tag, tunnel_env_active=False
        )
        sys.exit(0 if _emit(record, warnings) else 1)

    # -- phase 0: a parseable stub BEFORE any measurement (ADVICE r04) -------
    # phase 1's CPU cells each carry a 900 s subprocess timeout, so "guaranteed
    # publication" previously began only after ~15-30 min of CPU measurement; a
    # driver window shorter than that still ended with empty stdout. The stub's
    # null value is honest — nothing measured yet — and it is superseded by
    # every later record line on any path that survives phase 1. Its metric
    # tag is the NEUTRAL _STUB_NOT_MEASURED (ADVICE r05: the tunnel has not
    # been probed at this point, so a _CPU_FALLBACK_TUNNEL_UNRESPONSIVE tag
    # would claim a tunnel state that was never tested), and build_record
    # stamps a machine-readable "stub": true key alongside.
    stub, stub_warnings = build_record(
        {}, {}, baseline, "_STUB_NOT_MEASURED",
        tunnel_env_active=True,
        tunnel={
            "state": "stub — printed before ANY measurement; authoritative "
            "only if no later record line follows (bench was killed during "
            "the phase-1 CPU measurement)"
        },
        preliminary=True, stub=True,
    )
    _emit(stub, stub_warnings)

    # -- phase 1: guaranteed publication (tunnel never touched) -------------
    cpu_results, _, _, cpu_meta = _run_measurements(
        precisions, timeout_s=900, attempts=1, force_cpu=True
    )
    prelim, warnings = build_record(
        cpu_results,
        cpu_meta,
        baseline,
        "_CPU_FALLBACK_TUNNEL_UNRESPONSIVE",
        tunnel_env_active=True,
        tunnel={
            "state": "preliminary — printed before probing the tunnel; "
            "authoritative only if no later record line follows (bench was "
            "killed while waiting on the tunnel)"
        },
        preliminary=True,
    )
    _emit(prelim, warnings)

    # -- phase 2: bounded tunnel patience ------------------------------------
    fallback_tag, tunnel_diag = _ensure_responsive_backend()

    # -- phase 3: chip measurement, else the CPU record with the true tag ----
    results, meta = dict(cpu_results), dict(cpu_meta)
    if not fallback_tag:
        # interim re-emit before the (killable) chip measurement: if the
        # driver's window expires DURING measurement — the tunnel's known
        # wedge-mid-run mode — the last stdout line must not claim the
        # tunnel was unresponsive when it answered the probe. Superseded
        # by the final record below on every path that survives.
        interim, iw = build_record(
            cpu_results,
            cpu_meta,
            baseline,
            "_CPU_FALLBACK_TUNNEL_WEDGED_MIDRUN",
            tunnel_env_active=True,
            tunnel={
                **tunnel_diag,
                "state": "interim — probe healthy, chip measurement in "
                "progress; authoritative only if no later record line "
                "follows (bench was killed mid-measurement)",
            },
            preliminary=True,
        )
        _emit(interim, iw)
        chip_results, saw_timeout, errors, chip_meta = _run_measurements(
            precisions, timeout_s=900
        )
        if "default" in chip_results:
            results, meta = chip_results, chip_meta
            # fill a missing non-headline cell from phase 1 (provenance keeps
            # it honest: value_fp32_backend='cpu', same_window=False). The
            # CPU cross-check is NOT carried over — comparing a chip headline
            # against a CPU wall-clock bound would be meaningless.
            for p in precisions:
                if p not in results and p in cpu_results:
                    results[p] = cpu_results[p]
                    meta[p] = cpu_meta[p]
        else:
            # keep any cells the chip DID measure (per-cell provenance marks
            # the mixed backends); only the cells the chip failed stay CPU
            for p in precisions:
                if p in chip_results:
                    results[p] = chip_results[p]
                    meta[p] = chip_meta[p]
            # probe said healthy but the measurement itself failed: a
            # recorded in-measurement error for the headline cell (e.g. the
            # slope protocol refusing untrustworthy timing) is the
            # definitive cause and wins over a timeout on some attempt.
            fallback_tag = (
                "_CPU_FALLBACK_TUNNEL_WEDGED_MIDRUN"
                if saw_timeout and "default" not in errors
                else "_CPU_FALLBACK_MEASUREMENT_FAILED"
            )
            tunnel_diag["failure"] = (
                "probe healthy but chip measurement produced no headline "
                f"cell (saw_timeout={saw_timeout}, errors={errors})"
            )
            print(
                f"bench: chip measurement failed after healthy probe "
                f"({fallback_tag}); publishing the phase-1 CPU record",
                file=sys.stderr,
            )
    record, warnings = build_record(
        results,
        meta,
        baseline,
        fallback_tag,
        tunnel_env_active=True,
        tunnel=tunnel_diag,
    )
    sys.exit(0 if _emit(record, warnings) else 1)


def build_record(
    results, meta, baseline, fallback_tag, tunnel_env_active,
    tunnel=None, preliminary=False, stub=False,
):
    """Assemble the published one-line record from raw measurements — every
    honesty rule in one pure, unit-tested place (tests/test_tools.py):

    - the OBSERVED backend outranks the probe: a child whose tunnel init
      failed after a healthy probe silently measures on host CPU; that
      degraded number must carry a fallback tag even though no parent-side
      probe or timeout ever fired;
    - a degraded run is unmistakable in the metric NAME itself;
    - physical-plausibility guard: an implied FLOP rate above the single-
      chip ceiling means the timing protocol was defeated — label it;
    - whole-run cross-check guard: the slope headline must stay within 2x
      of the protocol-independent wall-clock bound;
    - per-cell provenance fields (value_backend, same_window): a
      same_window=false pair's RATIO is untrustworthy even when both
      values are;
    - MFU companions (``mfu``, ``mfu_fp32_highest``): each cell's model-
      FLOP utilization against ITS backend's per-chip peak
      (observability/costmodel.py), with the peak and its source recorded
      alongside — an MFU computed against the nominal CPU default is
      self-describing, never mistakable for a datasheet number;
    - ``tunnel``: probe diagnostics (per-probe outcome/seconds, failure
      mode) embedded in the record itself so a fallback round is
      self-describing; ``preliminary``: marks the phase-1 record printed
      before the tunnel was probed (superseded by any later record line).

    ``stub=True``: emit a record-SHAPED line with null values even when
    nothing is measured yet (the phase-0 stub printed before the phase-1 CPU
    cells) — deriving it here keeps the stub's schema and config claim from
    drifting out of sync with the published record's. The record carries a
    machine-readable ``"stub": true`` key (and the caller passes the neutral
    ``_STUB_NOT_MEASURED`` tag) so no consumer can misread an untested
    tunnel as a probed-unresponsive one (ADVICE r05).

    Returns ``(record_dict | None, warnings)``; None = nothing measured.
    """
    warnings = []
    value = results.get("default")
    value_fp32 = results.get("highest")
    if value is None and not stub:
        return None, ["no measurement succeeded on any backend"]
    if (
        not fallback_tag
        and tunnel_env_active
        and meta.get("default", {}).get("backend") == "cpu"
    ):
        fallback_tag = "_CPU_FALLBACK_CHILD_BACKEND_DEGRADED"
        warnings.append(
            "measurement child reported backend=cpu despite an active "
            "tunnel env; tagging metric as a CPU fallback"
        )
    metric = "mnist_mlp_train_samples_per_sec_per_chip" + fallback_tag
    crosscheck = results.get("_crosscheck")
    implausible = []
    if value is not None and value * flops_per_sample() > _PLAUSIBLE_TFLOPS["default"]:
        implausible.append(("default", value))
    if (
        value_fp32 is not None
        and value_fp32 * flops_per_sample() > _PLAUSIBLE_TFLOPS["highest"]
    ):
        implausible.append(("highest", value_fp32))
    if implausible:
        metric += "_SUSPECT_TIMING"
        for precision, v in implausible:
            warnings.append(
                f"{precision} cell implies "
                f"{v * flops_per_sample() / 1e12:.0f} TFLOP/s, above its "
                f"{_PLAUSIBLE_TFLOPS[precision] / 1e12:.0f} TFLOP/s "
                "single-chip ceiling; tagging metric"
            )
    if crosscheck is not None and value is not None and value > 2.0 * crosscheck:
        if "_SUSPECT_TIMING" not in metric:
            metric += "_SUSPECT_TIMING"
        warnings.append(
            f"headline {value:,.0f} samples/s exceeds 2x the whole-run "
            f"wall-clock cross-check ({crosscheck:,.0f}); tagging metric"
        )
    def _mfu(v, cell_meta, precision):
        """(mfu, peak, source) for one cell against its OWN backend's
        per-chip peak; (None, None, reason) when no peak is known."""
        if v is None:
            return None, None, None
        from shallowspeed_tpu.observability.costmodel import peak_flops_per_chip

        peak, source = peak_flops_per_chip(
            cell_meta.get("backend") or "unknown", precision
        )
        if not peak:
            return None, None, source
        return round(v * flops_per_sample() / peak, 6), peak, source

    mfu, mfu_peak, mfu_src = _mfu(value, meta.get("default", {}), "default")
    mfu32, _, _ = _mfu(value_fp32, meta.get("highest", {}), "highest")
    record = {
        "metric": metric,
        "value": None if value is None else round(value, 1),
        "unit": "samples/s",
        "vs_baseline": None if value is None else round(value / baseline, 2),
        "mfu": mfu,
        "mfu_fp32_highest": mfu32,
        "mfu_peak_flops": mfu_peak,
        "mfu_peak_source": mfu_src,
        # compiled headline epoch program's peak memory, from the shared
        # program_audit.memory_analysis path (null when the child's audit
        # failed or a stub/preliminary record never measured)
        "peak_hbm_bytes": results.get("_peak_hbm_bytes"),
        "config": "fused+default_precision (bf16-input MXU, fp32 accum; "
        "convergence-verified vs fp32 recipe)",
        "value_fp32_highest": (
            None if value_fp32 is None else round(value_fp32, 1)
        ),
        "vs_baseline_fp32_highest": (
            None if value_fp32 is None else round(value_fp32 / baseline, 2)
        ),
        "whole_run_crosscheck_sps": (
            None if crosscheck is None else round(crosscheck, 1)
        ),
        "value_backend": meta.get("default", {}).get("backend"),
        "value_fp32_backend": meta.get("highest", {}).get("backend"),
        "same_window": bool(
            value_fp32 is not None
            and meta.get("default", {}).get("interleaved")
            and meta.get("highest", {}).get("interleaved")
            and meta.get("default", {}).get("backend")
            == meta.get("highest", {}).get("backend")
        ),
    }
    if tunnel:
        record["tunnel"] = tunnel
    if preliminary:
        record["preliminary"] = True
    if stub:
        record["stub"] = True
    return record, warnings


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--_measure":
        _measure_child(sys.argv[2].split(","))
    else:
        main()
