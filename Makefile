# Convenience targets. The CPU_MESH prefix runs any layout on 8 emulated
# devices (and keeps the TPU tunnel plugin out of CPU-only processes).
CPU_MESH = env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
           XLA_FLAGS=--xla_force_host_platform_device_count=8

# verify needs bash (pipefail / PIPESTATUS)
SHELL := /bin/bash

.PHONY: test verify metrics-smoke report-smoke data train train-mesh bench \
        bench-scaling schedules clean

test:
	python -m pytest tests/ -q

# the ROADMAP tier-1 command, verbatim — the gate every PR must keep green
verify:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# telemetry end-to-end smoke: 1 CPU epoch with --metrics-out, then assert
# the file is non-empty valid JSONL with a per-epoch record (needs data:
# `make data` first, or point SHALLOWSPEED_DATA_DIR at a prepared dir)
metrics-smoke:
	rm -f /tmp/metrics.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --metrics-out /tmp/metrics.jsonl
	python -c "import json; lines = [json.loads(l) for l in open('/tmp/metrics.jsonl') if l.strip()]; assert lines, 'metrics file is empty'; assert any(r.get('kind') == 'event' and r.get('name') == 'epoch' for r in lines), 'no per-epoch record'; print(f'metrics-smoke OK: {len(lines)} valid JSONL records')"

# run-report end-to-end smoke: 1 CPU epoch with telemetry + health
# recording, then render the run report (throughput, MFU, span breakdown,
# step-loss sparkline, health verdict) — a nonzero report exit fails the
# target, which is the CI gate contract (needs data, like metrics-smoke)
report-smoke:
	rm -f /tmp/report_smoke.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --health record \
	    --metrics-out /tmp/report_smoke.jsonl
	python -m shallowspeed_tpu.observability.report /tmp/report_smoke.jsonl \
	    --format md

data:
	python prepare_data.py

train:
	python train.py --epochs 5

train-mesh:
	$(CPU_MESH) python train.py --dp 2 --pp 4 --schedule gpipe --epochs 2

bench:
	python bench.py

bench-scaling:
	$(CPU_MESH) python scripts/bench_scaling.py

bench-matrix:
	python scripts/bench_tpu_matrix.py

# one-shot full TPU measurement (baseline, unroll sweeps at both precision
# classes, interleaved matrix + full-epoch pallas/xla cells, convergence,
# profiler trace) — run when the chip is healthy
tpu-capture:
	python scripts/tpu_capture.py

# bank only the tier-0 verdict cells (headline pair + kernel ladder +
# equality probes) — for a chip window too short for the full matrix
tpu-capture-tier0:
	python scripts/tpu_capture.py --tier0-only

# unattended: probe the tunnel every 10 min, run the resumable capture on
# the first healthy probe (see scripts/tunnel_watch.sh)
tpu-watch:
	bash scripts/tunnel_watch.sh

# the convergence-equivalence experiment behind the default-precision
# bench headline (20-epoch run at --precision default + same-window pair)
tpu-default-precision:
	python scripts/tpu_default_precision.py

schedules:
	$(CPU_MESH) python scripts/show_schedule.py --all

clean:
	rm -rf .pytest_cache */__pycache__ __pycache__ tests/__pycache__
