"""Profiling spans: wall-clock + jax.profiler annotation context managers.

A span marks a named phase of host-side work — schedule lowering, jit
compile, device put, an epoch's execution — in BOTH observability planes at
once:

- wall-clock: the duration lands in the bound metrics recorder as a
  ``span`` record carrying the span's nesting path (``"train_run/epoch"``)
  and depth, so phase timings are queryable from the JSONL stream;
- device traces: the span body runs under ``jax.profiler.TraceAnnotation``,
  so when a capture is active (``capture(logdir)`` /
  ``jax.profiler.trace``) the phase appears as a labeled region on the
  host timeline of the ``*.trace.json.gz`` that
  ``observability.trace_stats`` analyzes.

Nesting is tracked per-thread: entering a span pushes its name on a
thread-local stack, so concurrently-profiled threads never corrupt each
other's paths.
"""

import contextlib
import threading
import time

try:  # jax is a hard dependency of the framework, but spans must degrade to
    # pure wall-clock timers if the profiler surface is ever unavailable
    from jax.profiler import TraceAnnotation as _TraceAnnotation
except Exception:  # noqa: BLE001 — degrade to wall-clock-only spans on crippled installs (pragma: no cover)
    _TraceAnnotation = None

_tls = threading.local()


def _stack():
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class Span:
    """Context manager timing one named phase (optionally into a recorder).

    Usable standalone (``with span("lower"): ...`` then ``.seconds``) or
    bound to a ``MetricsRecorder`` via ``metrics.span(name)``, which records
    a ``span`` record on exit. Re-entrant use of one instance is not
    supported — create one per ``with``.
    """

    __slots__ = ("name", "metrics", "path", "depth", "seconds", "_t0", "_ann")

    def __init__(self, name, metrics=None):
        self.name = name
        self.metrics = metrics
        self.path = None
        self.depth = None
        self.seconds = None

    def __enter__(self):
        stack = _stack()
        self.depth = len(stack)
        self.path = "/".join(stack + [self.name])
        # enter the annotation BEFORE pushing: if it raises, __exit__ never
        # runs, and a pushed-but-never-popped name would corrupt every later
        # span's path in this thread for the rest of the process
        if _TraceAnnotation is not None:
            self._ann = _TraceAnnotation(self.name)
            self._ann.__enter__()
        else:
            self._ann = None
        stack.append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.seconds = time.perf_counter() - self._t0
        if self._ann is not None:
            self._ann.__exit__(exc_type, exc, tb)
        stack = _stack()
        # tolerate a corrupted stack (an unexited inner span after an
        # exception mid-body) rather than raising during unwinding
        if stack and stack[-1] == self.name:
            stack.pop()
        if self.metrics is not None:
            self.metrics._record_span(self)
        return False


def span(name, metrics=None):
    """Free-function spelling: ``with span("jit_compile"): ...``."""
    return Span(name, metrics=metrics)


def capture(logdir, metrics=None):
    """``jax.profiler.trace`` integration: a context manager starting a
    profiler capture into ``logdir`` (None = no-op, so call sites need no
    conditional). When a recorder is given, a ``profiler_capture`` event
    (with the logdir and the capture's wall seconds) is recorded on exit —
    the metrics stream then names the trace artifact that
    ``observability.trace_stats`` can analyze.
    """
    if not logdir:
        return contextlib.nullcontext()
    return _Capture(str(logdir), metrics)


class _Capture:
    __slots__ = ("logdir", "metrics", "_trace", "_t0")

    def __init__(self, logdir, metrics):
        self.logdir = logdir
        self.metrics = metrics

    def __enter__(self):
        import jax.profiler

        self._trace = jax.profiler.trace(self.logdir)
        self._trace.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        seconds = time.perf_counter() - self._t0
        out = self._trace.__exit__(exc_type, exc, tb)
        if self.metrics is not None:
            self.metrics.event(
                "profiler_capture", logdir=self.logdir, seconds=seconds
            )
        return out
