"""Checkpoint tests: round-trip fidelity + cross-layout resume.

The design property under test: a checkpoint stores logical per-layer blocks
in global layer order, so save-from-one-layout / resume-into-another is exact
(the reference framework has no checkpointing at all, SURVEY §5.4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer
from shallowspeed_tpu.checkpoint import load_checkpoint, save_checkpoint
from shallowspeed_tpu.optimizer import SGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
B, M = 32, 4


def _train_sequential(params, spec, n=2, seed=0):
    rng = np.random.RandomState(seed)
    step = trainer.make_train_step(spec, SGD(0.01))
    st = ()
    for _ in range(n):
        x = jnp.asarray(rng.randn(M, B // M, SIZES[0]).astype(np.float32))
        y = jnp.asarray(
            np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, (M, B // M))]
        )
        params, st = step(params, st, x, y)
    return params


def test_round_trip_exact(tmp_path):
    spec = Mo.make_model_spec(SIZES, 1, B)
    params = _train_sequential(jax.tree.map(jnp.asarray, Mo.init_model(spec)), spec)
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec, epoch=3, extra={"note": "t"})
    loaded, spec2, meta = load_checkpoint(p, 1)
    assert meta["epoch"] == 3 and meta["extra"]["note"] == "t"
    assert spec2.sizes == spec.sizes
    for a, b in zip(
        [l for s in params for l in s], [l for s in loaded for l in s]
    ):
        np.testing.assert_array_equal(np.asarray(a["W"]), b["W"])
        np.testing.assert_array_equal(np.asarray(a["b"]).reshape(1, -1), b["b"])


def test_cross_layout_resume_sequential_to_pipeline(tmp_path):
    """Train sequentially, save, resume DP=2 x PP=4 — trained weights must
    land in the right stacked blocks and keep training correctly."""
    spec1 = Mo.make_model_spec(SIZES, 1, B)
    params = _train_sequential(jax.tree.map(jnp.asarray, Mo.init_model(spec1)), spec1)
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec1, epoch=0)

    loaded, spec4, _ = load_checkpoint(p, 4)
    mesh = make_mesh(2, 4)
    stacked, flags = E.put_stacked(*E.stack_params(loaded, spec4), mesh)

    # continue training one batch in BOTH layouts; results must agree
    rng = np.random.RandomState(42)
    xb = rng.randn(B, SIZES[0]).astype(np.float32)
    yb = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, B)]

    step1 = trainer.make_train_step(spec1, SGD(0.01))
    seq_params, _ = step1(
        params,
        (),
        jnp.asarray(xb.reshape(M, B // M, -1)),
        jnp.asarray(yb.reshape(M, B // M, -1)),
    )

    prog = lower_schedule(S.GPipeSchedule, M, 4)
    step4 = E.make_pipeline_step(mesh, spec4, prog, B // 2 // M, SGD(0.01))
    stacked, _, _ = step4(stacked, flags, (), jnp.asarray(xb), jnp.asarray(yb))

    want = [l for s in seq_params for l in s]
    got = [l for s in E.unstack_params(stacked, spec4) for l in s]
    for a, b in zip(want, got):
        np.testing.assert_allclose(np.asarray(a["W"]), b["W"], rtol=3e-4, atol=3e-6)
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1), rtol=3e-4, atol=3e-6
        )


def test_cross_layout_resume_pipeline_to_sequential(tmp_path):
    mesh = make_mesh(2, 4)
    spec4 = Mo.make_model_spec(SIZES, 4, B)
    prog = lower_schedule(S.GPipeSchedule, M, 4)
    stacked, flags = E.init_stacked(spec4, mesh)
    rng = np.random.RandomState(1)
    xb = rng.randn(B, SIZES[0]).astype(np.float32)
    yb = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, B)]
    step4 = E.make_pipeline_step(mesh, spec4, prog, B // 2 // M, SGD(0.01))
    stacked, _, _ = step4(stacked, flags, (), jnp.asarray(xb), jnp.asarray(yb))

    p = tmp_path / "ck.npz"
    save_checkpoint(p, E.unstack_params(stacked, spec4), spec4, epoch=1)
    loaded, spec1, _ = load_checkpoint(p, 1)

    got = [l for s in loaded for l in s]
    want = [l for s in E.unstack_params(stacked, spec4) for l in s]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a["W"], b["W"])


def test_save_is_atomic_and_overwrites(tmp_path):
    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec, epoch=0)
    save_checkpoint(p, params, spec, epoch=1)  # overwrite path
    _, _, meta = load_checkpoint(p, 1)
    assert meta["epoch"] == 1
    assert not list(tmp_path.glob("*.tmp"))


def test_wrong_stage_count_shape_check(tmp_path):
    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    p = tmp_path / "ck.npz"
    save_checkpoint(p, params, spec, epoch=0)
    with pytest.raises(ValueError):
        load_checkpoint(p, 3)  # 8 sizes not divisible by 3 stages
