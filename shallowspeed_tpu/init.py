"""Deterministic, layout-independent parameter initialization.

The reference guarantees that model initialization is identical no matter how
the model is partitioned across DP replicas / PP stages, by seeding a fresh
MT19937 stream per Linear layer from its (in, out) dims
(/root/reference/shallowspeed/layers.py:103-113). We reproduce that scheme
bit-for-bit on host NumPy, then device_put — it is what makes "TPU run reaches
the NumPy reference's loss" a checkable statement, and what makes the
layout-independent model hash (utils.py) meaningful.
"""

import numpy as np


def linear_init(in_dim: int, out_dim: int):
    """Weights N(0,1)/sqrt(in) fp32 with per-layer seed in + 1337*out; zero bias.

    Matches reference layers.py:106-113 exactly (same bit-stream, same dtype
    ops: normal -> astype(float32) -> divide by float64 sqrt).
    """
    rs = np.random.RandomState(
        np.random.MT19937(np.random.SeedSequence(in_dim + out_dim * 1337))
    )
    w = rs.normal(0.0, 1.0, size=(out_dim, in_dim)).astype(np.float32) / np.sqrt(
        in_dim
    )
    b = np.zeros((1, out_dim), dtype=np.float32)
    return np.asarray(w, dtype=np.float32), b
