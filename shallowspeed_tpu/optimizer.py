"""Optimizers over parameter pytrees, applied on-device inside the jitted step.

Capability parity: the reference ships plain stateless SGD
(/root/reference/shallowspeed/optimizer.py:4-13, ``param.data -= lr * grad``).
Here the update is a pytree map that XLA fuses into the training step — no
host round-trip per parameter.
"""

import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class SGD:
    """Stateless SGD. ``apply`` returns new params; grads are SUMS over the
    global batch (the loss is pre-scaled by the global batch size), so no
    averaging happens here — same ledger as the reference."""

    lr: float

    def init(self, params):
        return ()  # no optimizer state

    def apply(self, params, grads, state=()):
        new = jax.tree.map(lambda p, g: p - self.lr * g, params, grads)
        return new, state
