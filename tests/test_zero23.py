"""ZeRO-2/3 on the dp axis: persistent gradient shards and JIT-gathered
parameters (arXiv 2004.13336 stages 2-3 on the zero1 checkpoint substrate).

The numerics contract the stages ship under (docs/performance.md):

- **anchor ZeRO-2** reduce-scatters PER TICK into a persistent per-rank
  shard carry — that is what earns the grads÷dp residency row on the
  memory scoreboard (scripts/bench_zero.py). The shard sums
  microbatch-outer where zero-1's full-slab accumulator sums dp-outer, a
  different (equally valid) float reduction tree: bitwise-equal to
  zero-1 exactly at ``mubatches=1`` (one contribution per element — the
  psum_scatter value IS the psum chunk), tolerance-plus-determinism
  above it;
- **bucketed ZeRO-2** (``grad_bucket_bytes``) keeps the full-slab
  accumulators and buckets the TAIL reduce-scatter: bitwise-equal to
  zero-1 at ANY microbatch count — the overlap-vs-residency trade;
- **ZeRO-3** shards parameters at rest and all-gathers them just in time
  per tick; it shares the anchor stage-2 scatter tree, so it carries the
  same tolerance contract plus same-layout A/B bit-determinism.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu.api import TrainingSession
from shallowspeed_tpu.optimizer import SGD, Adam, MomentumSGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
B, M, LR, NB = 64, 4, 0.01, 3


def _data(seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(NB, B, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, (NB, B))]
    return X, Y


def _run(opt, dp, pp, zero, virtual=1, split=False, bucket=0, mub=M):
    X, Y = _data()
    mesh = make_mesh(dp, pp)
    spec = Mo.make_model_spec(SIZES, pp * virtual, B)
    order = E.interleave_order(pp * virtual, pp) if virtual > 1 else None
    sched = S.InterleavedSchedule if virtual > 1 else (
        S.PipeDreamFlushSchedule if split else S.GPipeSchedule)
    prog = lower_schedule(sched, mub, pp, virtual=virtual,
                          backward_split=split)
    stacked, flags = E.init_stacked(spec, mesh, order=order)
    if zero == 0:
        st = opt.init(stacked)
    elif zero == 1:
        st = E.zero1_init_state(opt, spec, mesh)
    else:
        st = E.zero_block_init_state(opt, spec, mesh)
    if zero == 3:
        host = jax.device_get(stacked)
        rows = E.zero_block_flatten_rows(host, spec, mesh)
        stacked = {"P": jax.device_put(rows, E.zero1_part_sharding(mesh))}
    step = E.make_pipeline_step(
        mesh, spec, prog, B // dp // mub, opt, zero=zero,
        grad_bucket_bytes=bucket)
    for i in range(NB):
        stacked, st, loss = step(
            stacked, flags, st, jnp.asarray(X[i]), jnp.asarray(Y[i]))
    if zero == 3:
        host = E.zero_block_unflatten_rows(
            np.asarray(jax.device_get(stacked["P"])), spec, mesh)
        flat = [l for s in E.unstack_params(host, spec, order=order)
                for l in s]
    else:
        flat = [l for s in E.unstack_params(stacked, spec, order=order)
                for l in s]
    return flat, st, float(loss), (spec, mesh, order)


def _assert_layers(a, b, exact, rtol=1e-5, atol=1e-6):
    for x, y in zip(a, b):
        for k in ("W", "b"):
            if exact:
                np.testing.assert_array_equal(
                    np.asarray(x[k]), np.asarray(y[k]))
            else:
                np.testing.assert_allclose(
                    np.asarray(x[k]), np.asarray(y[k]), rtol=rtol, atol=atol)


@pytest.mark.slow
@pytest.mark.parametrize("opt", [MomentumSGD(LR, 0.9), Adam(LR)])
@pytest.mark.parametrize("dp,pp,virtual", [(2, 2, 1), (2, 2, 2)])
def test_zero2_anchor_tracks_zero1(opt, dp, pp, virtual):
    """Anchor stage 2's per-tick scatter sums microbatch-outer where
    zero-1 sums dp-outer: same math, reassociated — tolerance at M>1.
    (Slow tier, wall budget: tier-1 pins the chain z1 ~ z3 (tolerance,
    test_zero3_tracks_zero1) == z2 (bitwise, the session census test)
    plus z2 == z1 exactly at mubatches=1.)"""
    z1, _, _, _ = _run(opt, dp, pp, 1, virtual=virtual)
    z2, _, _, _ = _run(opt, dp, pp, 2, virtual=virtual)
    _assert_layers(z1, z2, exact=False)


@pytest.mark.slow
def test_zero23_deterministic():
    """Same layout, same data -> the reassociated tree is FIXED: two
    stage-2 (or stage-3) runs must agree bitwise, so the M>1 tolerance
    above is a reassociation allowance, not nondeterminism laundering.
    (Slow tier: the 1-core tier-1 wall budget is tight; the session
    census test pins z2==z3 bitwise in tier-1.)"""
    opt = MomentumSGD(LR, 0.9)
    a, _, _, _ = _run(opt, 2, 2, 2)
    b, _, _, _ = _run(opt, 2, 2, 2)
    _assert_layers(a, b, exact=True)
    c, _, _, _ = _run(opt, 2, 2, 3)
    d, _, _, _ = _run(opt, 2, 2, 3)
    _assert_layers(c, d, exact=True)


@pytest.mark.parametrize(
    "opt", [MomentumSGD(LR, 0.9),
            pytest.param(Adam(LR), marks=pytest.mark.slow)])
def test_zero2_anchor_bitwise_at_single_microbatch(opt):
    """mubatches=1: one contribution per shard element, so the per-tick
    psum_scatter value IS the corresponding psum chunk — bitwise zero-1
    (the fixed-layout hash pin the bench and zero-smoke assert)."""
    z1, _, _, _ = _run(opt, 2, 2, 1, mub=1)
    z2, _, _, _ = _run(opt, 2, 2, 2, mub=1)
    _assert_layers(z1, z2, exact=True)


@pytest.mark.parametrize(
    "opt,exact", [pytest.param(SGD(LR), True, marks=pytest.mark.slow),
                  (MomentumSGD(LR, 0.9), True),
                  pytest.param(Adam(LR), False, marks=pytest.mark.slow)])
def test_zero2_bucketed_bitwise_any_microbatches(opt, exact):
    """A grad_bucket_bytes plan keeps the full-slab accumulators (dp-outer
    sum, zero-1's tree) and buckets only the tail scatter: bitwise at
    M=4. Adam's sqrt/divide chain fuses per shape -> rounding tolerance,
    as for zero-1 itself (test_zero1.py)."""
    z1, _, _, _ = _run(opt, 2, 2, 1)
    z2b, _, _, _ = _run(opt, 2, 2, 2, bucket=256)
    _assert_layers(z1, z2b, exact=exact, rtol=1e-6, atol=1e-7)


@pytest.mark.parametrize(
    "dp,pp,virtual", [(2, 2, 1),
                      pytest.param(2, 2, 2, marks=pytest.mark.slow)])
def test_zero3_tracks_zero1(dp, pp, virtual):
    opt = MomentumSGD(LR, 0.9)
    z1, _, _, _ = _run(opt, dp, pp, 1, virtual=virtual)
    z3, _, _, _ = _run(opt, dp, pp, 3, virtual=virtual)
    _assert_layers(z1, z3, exact=False)


@pytest.mark.slow
def test_split_backward_zero23():
    """PipeDream backward-split composes with both stages: the B-weight
    tick contributes its grads through the same per-tick scatter.
    (Slow tier: the r5 fuzz lattice crosses split-backward with the zero
    dimension in tier-1.)"""
    opt = MomentumSGD(LR, 0.9)
    z1, _, _, _ = _run(opt, 2, 2, 1, split=True)
    z2b, _, _, _ = _run(opt, 2, 2, 2, split=True, bucket=256)
    z3, _, _, _ = _run(opt, 2, 2, 3, split=True)
    _assert_layers(z1, z2b, exact=True)
    _assert_layers(z1, z3, exact=False)


@pytest.mark.slow
def test_zero2_state_is_block_cyclic_sharded():
    opt = MomentumSGD(LR, 0.9)
    _, st, _, (spec, mesh, _) = _run(opt, 4, 2, 2)
    _, csz3 = E.zero_block_len(spec, mesh)
    vel = st[""]  # momentum's single params-shaped state part
    assert vel.shape == (2, 4 * csz3)
    assert all(s.data.shape == (1, csz3) for s in vel.addressable_shards)
    assert float(jnp.abs(vel).sum()) > 0


def test_zero3_params_at_rest_are_sharded():
    opt = MomentumSGD(LR, 0.9)
    _, _, _, (spec, mesh, _) = _run(opt, 2, 2, 3)
    # the executor's at-rest layout: one (1, csz3) row block per dp rank
    _, csz3 = E.zero_block_len(spec, mesh)
    rows = E.zero_block_flatten_rows(
        jax.device_get(E.init_stacked(spec, mesh)[0]), spec, mesh)
    assert rows.shape == (2, 2 * csz3)


def _write_dataset(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 64)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)


def test_session_zero23_audited_epochs(tmp_path):
    """TrainingSession surface under audit=True (census enforced at jit
    time): stages 2-3 train, track zero-1 within tolerance, and the
    recorded forecast prices the stage ladder from the shared layout
    math."""
    _write_dataset(tmp_path)
    kw = dict(
        sizes=SIZES, global_batch_size=B, lr=0.01, data_dir=tmp_path,
        optimizer="momentum", dp=2, pp=2, schedule="gpipe", audit=True,
    )
    runs = {}
    for zero in (2, 3):
        s = TrainingSession(zero=zero, **kw)
        s.train_epoch()
        s.assert_replicas_in_sync()
        runs[zero] = s
    # stages 2 and 3 run the SAME per-tick scatter tree (stage 3 only
    # adds the param gathers, which are exact) -> bitwise-equal weights;
    # tracking zero-1 itself is pinned at executor level and by the r5
    # fuzz lattice's sequential oracle
    p2 = [l for st in runs[2].params() for l in st]
    p3 = [l for st in runs[3].params() for l in st]
    _assert_layers(p2, p3, exact=True)
    zf = runs[2]._expected_comms["zero_forecast"]
    t = {k: v["total_bytes"] for k, v in zf["stages"].items()}
    assert t["2"] < t["1"] <= t["0"]
    # stage 2's dp axis declares the per-tick scatter schedule the census
    # (and the report's Comms line) render
    dp_axis = runs[2]._expected_comms["axes"]["dp"]
    assert dp_axis["zero"] == 2
    assert dp_axis["scatter_schedule"] == "per_tick"
    g3 = runs[3]._expected_comms["axes"]["dp"]["gather"]
    assert g3["schedule"] == "per_tick" and g3["passes"] >= 2


@pytest.mark.slow
def test_session_zero2_hash_pin_at_single_microbatch(tmp_path):
    """Slow tier: the same pin runs at executor level in tier-1
    (test_zero2_anchor_bitwise_at_single_microbatch) and end-to-end in
    `make zero-smoke` + the CLI leg."""
    _write_dataset(tmp_path)
    kw = dict(
        sizes=SIZES, global_batch_size=B, mubatches=1, lr=0.01,
        data_dir=tmp_path, optimizer="momentum", dp=2, pp=2,
        schedule="gpipe",
    )
    hashes = {}
    for zero in (1, 2):
        s = TrainingSession(zero=zero, **kw)
        s.train_epoch()
        hashes[zero] = s.model_hash()
    assert hashes[2] == hashes[1]


def test_session_zero3_checkpoint_reloads_everywhere(tmp_path):
    """Stage-3 snapshots are LOGICAL (the zero1 substrate): a z3 save
    hot-reloads into a plain session bitwise — elastic re-sharding for
    free."""
    _write_dataset(tmp_path)
    kw = dict(
        sizes=SIZES, global_batch_size=B, lr=0.01, data_dir=tmp_path,
        optimizer="momentum",
    )
    z3 = TrainingSession(dp=2, pp=2, schedule="gpipe", zero=3, **kw)
    z3.train_epoch()
    ck = tmp_path / "z3.npz"
    z3.save(ck)
    plain = TrainingSession(**kw)
    plain.load_weights(ck)
    assert plain.model_hash() == z3.model_hash()


def test_session_refusals():
    base = dict(sizes=SIZES, data_dir="/nonexistent")
    with pytest.raises(ValueError, match="zero must be one of"):
        TrainingSession(zero=5, **base)
    with pytest.raises(ValueError, match="conflicting dp-stage"):
        TrainingSession(zero1=True, zero=2, **base)
    with pytest.raises(ValueError, match="shards the update"):
        TrainingSession(zero=2, **base)  # sequential: no dp axis
    with pytest.raises(ValueError, match="digests"):
        TrainingSession(zero=2, dp=2, digests=True, **base)
    with pytest.raises(ValueError, match="pallas"):
        TrainingSession(zero=3, dp=2, kernel_backend="pallas", **base)
    with pytest.raises(ValueError, match="per tick"):
        TrainingSession(zero=3, dp=2, grad_bucket_bytes=1024, **base)
    with pytest.raises(ValueError, match="mpmd"):
        TrainingSession(zero=2, dp=2, pp=2, runtime="mpmd", **base)
