"""Numerics health monitor: unit checks (NaN/Inf, divergence regression,
grad spikes), policy behaviour (record/warn/halt), and the TrainingSession
integration — a NaN-poisoned batch is detected and halted with a ``health``
record naming the step, while the NullMetrics default stays uninstrumented.
"""

import math

import numpy as np
import pytest

from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl
from shallowspeed_tpu.observability.health import (
    HealthError,
    HealthMonitor,
    make_monitor,
)
from shallowspeed_tpu.observability.metrics import MetricsRecorder

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
N, GBS = 256, 64  # 4 batches per epoch


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 96)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


def _poison_batch(data_dir, batch):
    """Inject one NaN feature into the given global batch (the data layer
    is deliberately unshuffled, so batch identity is deterministic)."""
    x = np.load(data_dir / "x_train.npy")
    x[batch * GBS + 3, 5] = np.nan
    np.save(data_dir / "x_train.npy", x)


# ---------------------------------------------------------------------------
# monitor unit behaviour
# ---------------------------------------------------------------------------


def test_non_finite_detection_names_step_and_field():
    m = HealthMonitor(policy="record", min_history=2, window=8)
    found = m.check_epoch(
        0,
        losses=[1.0, float("nan"), 1.0],
        grad_norms=[0.1, 0.1, float("inf")],
        first_step=10,
    )
    assert [(f["check"], f["step"], f["field"]) for f in found] == [
        ("non_finite", 11, "loss"),
        ("non_finite", 12, "grad_norm"),
    ]
    assert m.findings == found


def test_nan_does_not_poison_rolling_windows():
    """A NaN step is reported but NOT ingested: the next finite step is
    judged against a finite baseline, not a NaN-poisoned one."""
    m = HealthMonitor(policy="record", min_history=2, window=8)
    m.check_epoch(0, [1.0, 1.0, float("nan"), 1.0], first_step=0)
    assert all(math.isfinite(v) for v in m._losses)


def test_loss_divergence_regression():
    m = HealthMonitor(
        policy="record", min_history=4, window=8, divergence_factor=3.0
    )
    # flat losses: no finding
    assert m.check_epoch(0, [1.0] * 8, first_step=0) == []
    # DEcreasing losses never diverge even across a big range
    m2 = HealthMonitor(policy="record", min_history=4, window=8)
    assert m2.check_epoch(0, [9.0, 7.0, 5.0, 3.0, 2.0, 1.0], first_step=0) == []
    # geometric growth crosses 3x the window min with a positive slope
    found = m.check_epoch(1, [1.2, 1.5, 2.0, 3.5, 6.0], first_step=8)
    assert any(f["check"] == "loss_divergence" for f in found)
    f = next(f for f in found if f["check"] == "loss_divergence")
    assert f["slope"] > 0 and f["step"] is not None


def test_grad_spike_detection():
    m = HealthMonitor(policy="record", min_history=4, window=8, spike_factor=10.0)
    gns = [1.0, 1.1, 0.9, 1.0, 1.05, 50.0]
    found = m.check_epoch(0, [0.5] * len(gns), grad_norms=gns, first_step=0)
    spikes = [f for f in found if f["check"] == "grad_spike"]
    assert len(spikes) == 1 and spikes[0]["step"] == 5
    assert spikes[0]["value"] == 50.0


def test_policy_dispatch_record_warn_halt(capsys):
    rec = MetricsRecorder()
    emitted = []
    rec._emit = emitted.append
    m = HealthMonitor(policy="record", min_history=2, window=4)
    findings = m.check_epoch(0, [float("nan")], first_step=0)
    m.dispatch(findings, rec)  # record: emits, no raise, no print
    assert [e["kind"] for e in emitted] == ["health"]
    assert emitted[0]["name"] == "non_finite" and emitted[0]["action"] == "record"
    assert "step" in emitted[0] and "epoch" in emitted[0]

    warn = HealthMonitor(policy="warn", min_history=2, window=4)
    warn.dispatch(warn.check_epoch(0, [float("inf")], first_step=3), None)
    assert "non_finite" in capsys.readouterr().err

    halt = HealthMonitor(policy="halt", min_history=2, window=4)
    with pytest.raises(HealthError, match="step 7"):
        halt.dispatch(halt.check_epoch(2, [float("nan")], first_step=7), rec)


def test_monitor_constructor_validation_and_make_monitor():
    with pytest.raises(ValueError, match="policy"):
        HealthMonitor(policy="explode")
    with pytest.raises(ValueError, match="window"):
        HealthMonitor(window=2, min_history=8)
    assert make_monitor(None) is None
    m = HealthMonitor(policy="warn")
    assert make_monitor(m) is m
    assert make_monitor("halt").policy == "halt"


def test_check_run_epoch_granularity():
    """Fused runs only have per-epoch scalars: findings carry the epoch and
    a null step."""
    m = HealthMonitor(policy="record", min_history=2, window=4)
    found = m.check_run(5, [0.5, float("nan"), 0.5])
    assert [(f["check"], f["epoch"], f["step"]) for f in found] == [
        ("non_finite", 6, None)
    ]


# ---------------------------------------------------------------------------
# TrainingSession integration
# ---------------------------------------------------------------------------


def test_session_halts_on_nan_batch_with_health_record(data_dir, tmp_path):
    """The acceptance contract: a NaN-poisoned batch halts the run under
    health='halt' and the JSONL carries a health record naming the step —
    flushed BEFORE the raise, so the evidence survives the abort."""
    from shallowspeed_tpu.api import TrainingSession

    _poison_batch(data_dir, 1)
    path = tmp_path / "halt.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m, health="halt",
        )
        with pytest.raises(HealthError, match="step 1"):
            run.train_epoch()
    recs = read_jsonl(path)
    health = [r for r in recs if r["kind"] == "health"]
    assert health, "no health record survived the halt"
    assert health[0]["name"] == "non_finite"
    assert health[0]["step"] == 1 and health[0]["action"] == "halt"
    # the flight ring holds the poisoned step for post-mortem
    sample = run.flight.last(run.batches_per_epoch)[1]
    assert sample["step"] == 1 and math.isnan(sample["loss"])


def test_session_warn_policy_does_not_halt(data_dir, tmp_path, capsys):
    from shallowspeed_tpu.api import TrainingSession

    _poison_batch(data_dir, 2)
    path = tmp_path / "warn.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m, health="warn",
        )
        run.train_epoch()  # must NOT raise
    assert "non_finite" in capsys.readouterr().err
    health = [r for r in read_jsonl(path) if r["kind"] == "health"]
    assert health and health[0]["step"] == 2 and health[0]["action"] == "warn"


def test_session_health_works_without_metrics(data_dir):
    """health= alone (NullMetrics default) still detects and halts: the
    monitor consumes the fused aux directly, recording is orthogonal."""
    from shallowspeed_tpu.api import TrainingSession

    _poison_batch(data_dir, 0)
    run = TrainingSession(
        sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
        health="halt",
    )
    assert run._step_aux  # the aux is threaded for the monitor
    with pytest.raises(HealthError, match="step 0"):
        run.train_epoch()


def test_default_session_stays_uninstrumented(data_dir):
    """NullMetrics default + no health monitor: no step aux, no flight
    recorder — the hot path builds the exact 3-output epoch program."""
    from shallowspeed_tpu.api import TrainingSession

    run = TrainingSession(
        sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir
    )
    assert run._step_aux is False and run.flight is None
    out = run._epoch_fn(*run._epoch_args())
    assert len(out) == 3  # params, opt_state, loss — no aux slot


def test_session_mesh_halts_on_nan_batch(data_dir, tmp_path):
    """Same detection through the SPMD pipeline executor's fused aux."""
    from shallowspeed_tpu.api import TrainingSession

    _poison_batch(data_dir, 1)
    path = tmp_path / "mesh.jsonl"
    with JsonlMetrics(path) as m:
        run = TrainingSession(
            sizes=SIZES, global_batch_size=GBS, lr=0.01, data_dir=data_dir,
            metrics=m, dp=2, pp=2, schedule="gpipe", health="halt",
        )
        with pytest.raises(HealthError, match="step 1"):
            run.train_epoch()
    health = [r for r in read_jsonl(path) if r["kind"] == "health"]
    assert health and health[0]["step"] == 1
