"""Structured metrics recording: counters, gauges, timers, histograms, JSONL.

Three recorders share one surface:

- ``NullMetrics``     the default everywhere a ``metrics=`` hook exists.
                      Every method is a no-op and the hot-path methods
                      (``counter``/``gauge``/``observe``/``timer``/``span``)
                      allocate nothing — recording disabled must cost nothing
                      measurable inside a training loop (tested:
                      tests/test_observability.py asserts zero net
                      allocations over thousands of calls).
- ``MetricsRecorder`` in-memory aggregation (counter sums, last-value
                      gauges, per-name histogram samples) with a
                      ``summary()`` snapshot — the base class; also directly
                      useful in tests and benchmarks.
- ``JsonlMetrics``    MetricsRecorder + a versioned JSONL sink: one
                      self-describing JSON object per line, schema pinned by
                      ``SCHEMA_VERSION`` and stamped both in the header
                      record and in every record's ``"v"`` field, so a
                      consumer can hard-fail on records it doesn't
                      understand instead of misreading them (the BENCH_r0x
                      lesson: unlabeled records cost more than no records).

Record shapes (all lines share ``v``/``ts``/``kind``/``name``):

    {"v": 2, "ts": ..., "kind": "meta",      "name": "metrics",
     "schema": "shallowspeed_tpu.metrics", "created": "..."}
    {"v": 2, "ts": ..., "kind": "counter",   "name": ..., "value": total,
     "inc": delta}
    {"v": 2, "ts": ..., "kind": "gauge",     "name": ..., "value": ...}
    {"v": 2, "ts": ..., "kind": "histogram", "name": ..., "value": sample}
    {"v": 2, "ts": ..., "kind": "timer",     "name": ..., "seconds": ...}
    {"v": 2, "ts": ..., "kind": "span",      "name": ..., "path": "a/b",
     "depth": n, "seconds": ...}
    {"v": 2, "ts": ..., "kind": "event",     "name": ..., **fields}
    {"v": 2, "ts": ..., "kind": "step",      "name": ..., "step": i,
     "epoch": e, "loss": ..., "grad_norm": ..., "param_norm": ...}   [v2+]
    {"v": 2, "ts": ..., "kind": "health",    "name": <check>, "epoch": e,
     "step": i|null, "action": "record"|"warn"|"halt", **finding}    [v2+]
    {"v": 3, "ts": ..., "kind": "xla_audit", "name": <program>,
     "census": {...}, "memory": {...}, "expected": {...},
     "census_ok": bool|null, **audit}                                [v3+]
    {"v": 4, "ts": ..., "kind": "checkpoint", "name": <reason>,
     "path": ..., "epoch": e, "step_in_epoch": s, "global_step": g,
     "bytes": n, "wall_s": ..., "async": bool [v8], "queue_depth": n
     [v8], "verify_s": ... [v8], "write_s": ... [v8], "queued_s": ...
     [v8]}                                                           [v4+]
    {"v": 4, "ts": ..., "kind": "recovery",  "name": <verdict>,
     "resumed_from": path|null, "epoch": e, "step_in_epoch": s,
     "global_step": g, "skipped": [...], **fields}                   [v4+]
    {"v": 5, "ts": ..., "kind": "request",   "name": <verdict: "ok"|
     "dropped"; v6 adds "expired"|"error"|"unhealthy">, "id": i,
     "rows": n, "slots": k, "enqueue_ts": ..., "dispatch_ts": ...,
     "complete_ts": ..., "latency_s": ..., "queue_s": ...,
     "deadline_ms": ..., "slo_ok": bool|null, "attempts": k [v6],
     "reason": ... [v6]}                                             [v5+]
    {"v": 5, "ts": ..., "kind": "serving",   "name": "summary",
     "completed": n, "dropped": n, "offered_rps": ..., "p50_latency_s":
     ..., "p99_latency_s": ..., "goodput_rps": ..., "padding_waste":
     ..., "queue_depth_max": ..., **fields}                          [v5+]
    {"v": 6, "ts": ..., "kind": "serving_health", "name": <event:
     "breaker_open"|"breaker_closed"|"unhealthy_dispatch"|
     "dispatch_error"|"fault_injected">, "dispatch": n,
     "consecutive_failures": k, **fields}                            [v6+]
    {"v": 6, "ts": ..., "kind": "reload",    "name": <verdict: "ok"|
     "failed"|"none_newer">, "path": ..., "step": ..., "reason":
     "breaker"|"watch"|"manual", "wall_s": ..., "programs_cached": n,
     **fields}                                                       [v6+]
    {"v": 7, "ts": ..., "kind": "fleet",     "name": "summary",
     "completed": n, "dropped": n, "failovers": n, "reroutes": n,
     "routing_skew": ..., "routing": {replica_id: routed},
     "per_replica": {replica_id: {...}}, **fields}                   [v7+]
    {"v": 7, "ts": ..., "kind": "fleet_health", "name": <event:
     "replica_spawned"|"replica_ready"|"replica_dead"|"failover"|
     "replica_degraded"|"replica_recovered"|"replica_draining"|
     "replica_retired"|"scale_up"|"scale_down"|"fleet_degraded"|
     "fleet_recovered"|"reload_broadcast">, "replica_id": r,
     **fields}                                                       [v7+]
    {"v": 8, "ts": ..., "kind": "aot_cache", "name": <event: "hit"|
     "miss"|"store"|"stale"|"corrupt"|"audit_mismatch"|"fallback"|
     "disabled">, "program": ..., "key": ..., "wall_s": ...,
     "reason": ..., **fields}                                        [v8+]
    {"v": 9, "ts": ..., "kind": "static_analysis", "name": <program |
     "lint">, "passes": [...], "findings": n, **verdict}             [v9+]
    {"v": 10, "ts": ..., "kind": "trace",    "name": <span:
     "fleet.queue"|"route"|"worker.queue"|"pack"|"dispatch"|"verify"|
     "failover.requeue"|"ack" — or "clock_offset">, "trace_id": ...,
     "span_id": ..., "parent_id": ...|null, "t0": ..., "t1": ...,
     "clock": "parent"|"worker", "replica_id": r|null,
     "terminal": bool, **fields}                                    [v10+]
    {"v": 11, "ts": ..., "kind": "rollup",   "name": <source:
     "serving"|"fleet"|"train"|...>, "window_start": ...,
     "window_end": ..., "window_s": ..., "seq": i, "counters":
     {metric: total}, "rates": {metric: {"rate": ..., "ewma": ...}},
     "gauges": {metric: last}, "quantiles": {metric: {"count": n,
     "sum": ..., "min": ..., "max": ..., "p50": ..., "p90": ...,
     "p99": ...}}, "sketches": {metric: <QuantileSketch.to_dict()>},
     "late": n, "replica_id": r|null}                               [v11+]
    {"v": 11, "ts": ..., "kind": "alert",    "name": <rule>,
     "state": "firing"|"resolved", "severity": "page"|"ticket",
     "t": ..., "value": ..., "threshold": ..., "burn_fast": ...,
     "burn_slow": ..., "reason": ..., "replica_id": r|null}         [v11+]
    {"v": 12, "ts": ..., "kind": "digest",   "name": <source: "train">,
     "step": <global step>, "epoch": ..., "layers": n,
     "crc_w": [uint32 ...], "crc_b": [...], "pnorm_w": [float ...],
     "pnorm_b": [...], "gnorm_w": [...], "gnorm_b": [...]}          [v12+]
    {"v": 13, "ts": ..., "kind": "autoscale", "name": <decision:
     "scale_out"|"scale_in"|"replace"|"backpressure_on"|
     "backpressure_off">, "direction": "out"|"in"|"hold", "rule":
     <triggering rule|poll>, "t": ..., "replicas_before": n,
     "replicas_after": n, "reason": ..., "window_end": ...|null,
     "queue_depth": n, "value": ...|null, "threshold": ...|null,
     "flap": bool, **evidence}                                      [v13+]

Schema compatibility rules (SCHEMA_VERSION history):

- v1  initial schema: meta/counter/gauge/histogram/timer/span/event.
- v2  ADDITIVE: the ``step`` (flight-recorder per-step sample) and
  ``health`` (numerics-monitor finding) kinds. No v1 kind or field
  changed meaning, so a v2 READER accepts v1 files unchanged (and the
  ``read_jsonl`` strict check is one-directional: it refuses records
  NEWER than the reader, never older). A v1 reader fed a v2 file will
  refuse it loudly — that is the point of the stamp.
- v3  ADDITIVE: the ``xla_audit`` kind (compiled-program collective
  census + memory analysis + comms-contract verdict, emitted at jit
  time — observability/program_audit.py). Again no existing kind or
  field changed meaning, so the v3 reader accepts v1 AND v2 files
  unchanged and the strict refusal stays one-directional.
- v4  ADDITIVE: the ``checkpoint`` (one step/epoch/halt snapshot write,
  named by its reason, carrying the step cursor + bytes + wall clock)
  and ``recovery`` (one resume decision, named by its verdict —
  ``resumed``/``fresh_start`` — carrying what was restored and every
  corrupt snapshot skipped on the way) kinds, the evidence stream behind
  the report CLI's Reliability section. No existing kind or field
  changed meaning; the v4 reader accepts v1–v3 files unchanged.
- v5  ADDITIVE: the ``request`` (one served request's accounting —
  enqueue/dispatch/complete timestamps, rows vs padded slots, latency
  and queue wait, SLO verdict; named by its outcome) and ``serving``
  (one load run's aggregate — completion counts, latency percentiles,
  goodput, padding waste, queue-depth stats) kinds, the evidence
  stream behind the report CLI's Serving section
  (shallowspeed_tpu/serving/, docs/serving.md). No existing kind or
  field changed meaning; the v5 reader accepts v1–v4 files unchanged
  and the strict refusal stays one-directional (a v6 file is refused).
- v6  ADDITIVE: the ``serving_health`` (one serving degradation event —
  a failed or non-finite dispatch, a breaker trip or recovery, an
  injected chaos fault — named by the event) and ``reload`` (one hot
  weight-reload decision, named by its verdict, carrying the snapshot
  path/step, the trigger reason and the surviving compiled-program
  count) kinds — the evidence stream behind the report CLI's
  Degradation subsection (docs/robustness.md "Serving faults"). The
  ``request`` kind additionally gains the terminal verdicts
  ``expired``/``error``/``unhealthy`` as record NAMES plus the additive
  ``attempts``/``reason`` fields (new names/fields on an existing kind
  — lawful under the ignore-unknown-fields rule; no existing
  name/field changed meaning). The v6 reader accepts v1–v5 files
  unchanged; a v7 file is refused.
- v7  ADDITIVE: the ``fleet`` (one fleet run's aggregate — per-replica
  verdict counts, routing assignments + skew, failover/reroute counts,
  availability, the measured recovery and scale-up times) and
  ``fleet_health`` (one fleet lifecycle event — a replica spawned/ready/
  dead/degraded/retired, a failover requeue, a scale decision, a
  fleet-level quorum transition — every one tagged ``replica_id``) kinds,
  the evidence stream behind the report CLI's Fleet section
  (shallowspeed_tpu/serving/fleet.py, docs/serving.md "Fleet"). No
  existing kind or field changed meaning; the v7 reader accepts v1–v6
  files unchanged and the strict refusal stays one-directional (a v8
  file is refused).

- v8  ADDITIVE: the ``aot_cache`` kind (one ahead-of-time executable
  cache decision, named by the event — ``hit``/``miss``/``store``/
  ``stale``/``corrupt``/``audit_mismatch``/``fallback``/``disabled`` —
  carrying the program label, cache key, wall time and the recorded
  reason; shallowspeed_tpu/aot_cache.py), plus additive fields on the
  EXISTING ``checkpoint`` kind for the async writer (``async``,
  ``queue_depth`` at enqueue, off-path ``verify_s``/``write_s``/
  ``queued_s`` — for async saves ``wall_s`` is the ON-PATH cost only:
  snapshot + enqueue) and ``verify_s`` on the ``reload`` kind (the
  discovery-verification time of the single-verified-read reload).
  Lawful under the ignore-unknown-fields rule; no existing name/field
  changed meaning. The v8 reader accepts v1-v7 files unchanged and the
  strict refusal stays one-directional (a v9 file is refused).

- v9  ADDITIVE: the ``static_analysis`` kind (one static-analysis
  verdict, named by the program it covers for the compile-time passes —
  send/recv match, MPMD deadlock-freedom, stash lifetime over the
  lowered tick tables, plus the HLO dispatch-safety pass — or ``lint``
  for a house-rule lint run; carries the pass list, per-pass stats and
  the finding count; shallowspeed_tpu/analysis/,
  docs/static-analysis.md), plus the ``SCHEMA_KINDS`` registry below —
  the machine-readable half of this docstring, which the house-rule
  linter enforces: a record kind not registered here cannot be emitted.
  No existing kind or field changed meaning; the v9 reader accepts
  v1-v8 files unchanged and the strict refusal stays one-directional
  (a v10 file is refused).

- v10 ADDITIVE: the ``trace`` kind (distributed request tracing,
  observability/tracing.py, docs/observability.md § Tracing): one CLOSED
  span per record — named by the span type (``fleet.queue``/``route``/
  ``worker.queue``/``pack``/``dispatch``/``verify``/``failover.requeue``/
  ``ack``), carrying the ``trace_id`` every record of one request shares
  across processes, a process-unique ``span_id``, the ``parent_id``
  linkage (carried over the worker pipe alongside the request, so chains
  stay connected across the process hop), raw ``t0``/``t1`` perf_counter
  endpoints in the emitting process's clock domain (``clock``:
  ``parent`` or ``worker``), the emitting ``replica_id``, and
  ``terminal`` marking the one span that ends the request. The special
  name ``clock_offset`` records the fleet handshake's per-replica
  round-trip clock estimate (``offset_s``/``rtt_s``/``uncertainty_s``) —
  what lets a reader place every shard on the parent timeline. The
  EXISTING ``request`` kind additionally gains the ``trace_id`` field
  (the join key from a request's terminal verdict to its span chain —
  additive field on a known kind, lawful under the ignore-unknown-fields
  rule). No existing kind or field changed meaning; the v10 reader
  accepts v1–v9 files unchanged and the strict refusal stays
  one-directional (a v11 file is refused).

- v11 ADDITIVE: the ``rollup`` (one CLOSED tumbling telemetry window,
  observability/rollup.py, docs/observability.md § Live telemetry:
  named by the emitting source — ``serving``/``fleet``/``train`` —
  carrying the window bounds in the emitter's record-timestamp domain,
  per-metric counter totals, per-window + EWMA rates, last-value
  gauges, quantile summaries AND the full mergeable ``QuantileSketch``
  state so shard rollups can be re-merged exactly, the late-sample
  count, and the emitting ``replica_id`` — the existing shard join
  key) and ``alert`` (one SLO alert lifecycle TRANSITION,
  observability/slo.py: named by the rule, carrying ``state``
  ``firing``/``resolved``, severity, the observed value vs threshold,
  the fast/slow burn rates for burn-rate rules, and the human
  ``reason``) kinds — the sensor-and-alarm evidence stream behind
  ``observability.watch``, the report CLI's Alerts section and the
  autoscaler (serving/autoscaler.py, since v13). No existing kind or
  field changed
  meaning; the v11 reader accepts v1–v10 files unchanged and the
  strict refusal stays one-directional (a v12 file is refused).

- v12 ADDITIVE: the ``digest`` kind (one per optimizer step, named by
  the emitting source — ``train`` — carrying ``step`` (the 0-based
  GLOBAL step index), ``epoch``, ``layers`` and parallel
  per-global-layer lists: ``crc_w``/``crc_b`` — the uint32 wrap-around
  sums of each logical (W, b) block's POST-update float32 bytes
  reinterpreted as uint32 words, computed in-program as fused scan aux
  and psum'd over the mesh so the value is layout-independent — plus
  ``pnorm_w``/``pnorm_b`` (post-update per-block L2 norms) and
  ``gnorm_w``/``gnorm_b`` (post-sync, PRE-clip per-block gradient L2
  norms)) — the numerics-provenance stream behind
  ``observability.divergence`` (first-divergence attribution and
  checkpoint-bisect replay) and the report CLI's Divergence section.
  No existing kind or field changed meaning; the v12 reader accepts
  v1–v11 files unchanged and the strict refusal stays one-directional
  (a v13 file is refused).

- v13 ADDITIVE: the ``autoscale`` kind (one closed-loop capacity
  decision, serving/autoscaler.py, docs/serving.md § Autoscaling:
  named by the decision — ``scale_out``/``scale_in``/``replace``/
  ``backpressure_on``/``backpressure_off`` — carrying ``direction``
  (``out``/``in``/``hold``), the triggering ``rule`` (an alert rule
  name, or ``poll`` for a between-edges status decision), the decision
  time ``t``, the fleet size ``replicas_before``/``replicas_after``,
  the evidence it acted on (``value``/``threshold`` from the alert or
  rollup window, ``window_end`` of the rollup window consulted,
  ``queue_depth`` at decision time), a human ``reason``, and ``flap``
  — True when this decision reverses the previous direction inside
  the policy's flap window, the scoreboard's zero-flap gate) — the
  evidence stream behind the capacity scoreboard
  (serving/bench_replay.py, AUTOSCALE_r01.json) and the report CLI's
  Capacity section. No existing kind or field changed meaning; the
  v13 reader accepts v1–v12 files unchanged and the strict refusal
  stays one-directional (a v14 file is refused).

The contract for future bumps: additive kinds/fields bump the version and
must keep old records readable; any change to an EXISTING kind's meaning
requires a new kind name instead. Consumers must ignore unknown fields on
known kinds.

Multihost: a ``JsonlMetrics`` constructed under ``jax.process_count() > 1``
appends a ``.p{process_index}`` suffix to its path — concurrent hosts
each own one shard and can never interleave writes into one file.
Fleet workers reuse the same convention with an ``.r{replica_id}``
suffix (``replica_shard_path``): every serving replica process owns its
shard, the parent fleet process owns the bare path, and ``replica_id``
is the join key between the parent's ``fleet``/``fleet_health`` records
and each shard's ``request``/``serving_health`` stream
(docs/observability.md). ``read_jsonl`` accepts a glob
(``run.jsonl.p*``, ``fleet.jsonl*``) and, given a bare path that does
not exist, falls back to its ``.p*`` (multihost) or ``.r*`` (fleet)
shards automatically.

The span taxonomy and the metric names the framework itself emits are
documented in docs/observability.md.
"""

import glob as _glob
import json
import math
import os
import threading
import time

from shallowspeed_tpu.observability.spans import Span

SCHEMA_VERSION = 13
SCHEMA_NAME = "shallowspeed_tpu.metrics"

# The schema table: every record kind this schema version can write,
# mapped to the SCHEMA_VERSION that introduced it (the machine-readable
# half of the docstring above). This is a REGISTRY, not documentation:
# the house-rule linter (shallowspeed_tpu/analysis/rules.py, rule
# SSP005) parses it by AST and refuses any ``_emit`` whose "kind"
# literal is absent — so adding a kind forces the schema-version
# discipline (additive bump + history entry) instead of quietly leaking
# an undocumented record shape into published JSONL. Keep it a pure
# literal: the linter reads it with ast.literal_eval, without importing
# (or depending on) this module's jax-adjacent imports.
SCHEMA_KINDS = {
    "meta": 1,
    "counter": 1,
    "gauge": 1,
    "histogram": 1,
    "timer": 1,
    "span": 1,
    "event": 1,
    "step": 2,
    "health": 2,
    "xla_audit": 3,
    "checkpoint": 4,
    "recovery": 4,
    "request": 5,
    "serving": 5,
    "serving_health": 6,
    "reload": 6,
    "fleet": 7,
    "fleet_health": 7,
    "aot_cache": 8,
    "static_analysis": 9,
    "trace": 10,
    "rollup": 11,
    "alert": 11,
    "digest": 12,
    "autoscale": 13,
}


class _NullContext:
    """Reusable allocation-free no-op context manager (module singleton)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False


_NULL_CONTEXT = _NullContext()


class NullMetrics:
    """The no-op backend: the hot-path methods take fixed positional
    arguments (no ``**kwargs`` — an empty kwargs dict is still a dict
    allocation per call) and return module-level singletons."""

    __slots__ = ()
    enabled = False

    def counter(self, name, value=1.0):
        pass

    def gauge(self, name, value):
        pass

    def observe(self, name, value):
        pass

    def timer(self, name):
        return _NULL_CONTEXT

    def span(self, name):
        return _NULL_CONTEXT

    def event(self, name, **fields):
        pass

    def step(self, name, **fields):
        pass

    def health(self, name, **fields):
        pass

    def audit(self, name, **fields):
        pass

    def checkpoint(self, name, **fields):
        pass

    def recovery(self, name, **fields):
        pass

    def request(self, name, **fields):
        pass

    def serving(self, name, **fields):
        pass

    def serving_health(self, name, **fields):
        pass

    def reload(self, name, **fields):
        pass

    def fleet(self, name, **fields):
        pass

    def fleet_health(self, name, **fields):
        pass

    def aot_cache(self, name, **fields):
        pass

    def static_analysis(self, name, **fields):
        pass

    def trace(self, name, **fields):
        pass

    def rollup(self, name, **fields):
        pass

    def alert(self, name, **fields):
        pass

    def digest(self, name, **fields):
        pass

    def autoscale(self, name, **fields):
        pass

    def flush(self):
        pass

    def close(self):
        pass


class MetricsRecorder:
    """In-memory aggregating recorder (and the sink-backed recorders' base).

    Aggregation semantics:
    - ``counter``  monotonic per-name sum of increments;
    - ``gauge``    last value wins;
    - ``observe``  per-name sample list (a per-step histogram — the summary
                   reports count/min/max/mean);
    - ``timer``    a context manager whose wall-clock duration is observed
                   into the ``<name>.seconds`` histogram (+ a timer record);
    - ``span``     ``spans.Span`` bound to this recorder: wall-clock + a
                   ``jax.profiler.TraceAnnotation`` labeling profiler
                   captures; emits a span record with its nesting path;
    - ``event``    a free-form named record (arbitrary JSON-able fields) —
                   the shape the per-epoch training telemetry uses;
    - ``step``     one flight-recorder per-step sample (schema v2): free
                   fields like ``event`` under its own kind so step-level
                   streams are filterable without name conventions;
    - ``health``   one numerics-monitor finding (schema v2), named by the
                   check that fired (``non_finite``/``loss_divergence``/
                   ``grad_spike``);
    - ``audit``    one compiled-program audit (schema v3, kind
                   ``xla_audit``), named by the program it describes
                   (``epoch_program``/``run_program``): collective census,
                   memory analysis, comms-contract verdict
                   (observability/program_audit.py).
    """

    enabled = True

    def __init__(self):
        self.counters = {}
        self.gauges = {}
        self.histograms = {}
        self.spans = []  # (path, seconds) in completion order

    # -- recording surface --------------------------------------------------

    def counter(self, name, value=1.0):
        total = self.counters.get(name, 0.0) + value
        self.counters[name] = total
        self._emit({"kind": "counter", "name": name, "value": total, "inc": value})

    def gauge(self, name, value):
        self.gauges[name] = value
        self._emit({"kind": "gauge", "name": name, "value": value})

    def observe(self, name, value):
        self.histograms.setdefault(name, []).append(value)
        self._emit({"kind": "histogram", "name": name, "value": value})

    def timer(self, name):
        return _Timer(self, name)

    def span(self, name):
        return Span(name, metrics=self)

    def event(self, name, **fields):
        self._emit({"kind": "event", "name": name, **fields})

    def step(self, name, **fields):
        self._emit({"kind": "step", "name": name, **fields})

    def health(self, name, **fields):
        self._emit({"kind": "health", "name": name, **fields})

    def audit(self, name, **fields):
        self._emit({"kind": "xla_audit", "name": name, **fields})

    def checkpoint(self, name, **fields):
        self._emit({"kind": "checkpoint", "name": name, **fields})

    def recovery(self, name, **fields):
        self._emit({"kind": "recovery", "name": name, **fields})

    def request(self, name, **fields):
        self._emit({"kind": "request", "name": name, **fields})

    def serving(self, name, **fields):
        self._emit({"kind": "serving", "name": name, **fields})

    def serving_health(self, name, **fields):
        self._emit({"kind": "serving_health", "name": name, **fields})

    def reload(self, name, **fields):
        self._emit({"kind": "reload", "name": name, **fields})

    def fleet(self, name, **fields):
        self._emit({"kind": "fleet", "name": name, **fields})

    def fleet_health(self, name, **fields):
        self._emit({"kind": "fleet_health", "name": name, **fields})

    def aot_cache(self, name, **fields):
        self._emit({"kind": "aot_cache", "name": name, **fields})

    def static_analysis(self, name, **fields):
        self._emit({"kind": "static_analysis", "name": name, **fields})

    def trace(self, name, **fields):
        self._emit({"kind": "trace", "name": name, **fields})

    def rollup(self, name, **fields):
        self._emit({"kind": "rollup", "name": name, **fields})

    def alert(self, name, **fields):
        self._emit({"kind": "alert", "name": name, **fields})

    def digest(self, name, **fields):
        self._emit({"kind": "digest", "name": name, **fields})

    def autoscale(self, name, **fields):
        self._emit({"kind": "autoscale", "name": name, **fields})

    # -- recorder-internal hooks --------------------------------------------

    def _record_span(self, span):
        """Completion hook called by spans.Span.__exit__."""
        self.spans.append((span.path, span.seconds))
        self._emit(
            {
                "kind": "span",
                "name": span.name,
                "path": span.path,
                "depth": span.depth,
                "seconds": span.seconds,
            }
        )

    def _record_timer(self, name, seconds):
        self.histograms.setdefault(name + ".seconds", []).append(seconds)
        self._emit({"kind": "timer", "name": name, "seconds": seconds})

    def _emit(self, record):
        """Sink hook: the in-memory base discards (aggregation above already
        happened); JsonlMetrics overrides this with the JSONL write."""

    # -- inspection ---------------------------------------------------------

    def summary(self):
        """JSON-able aggregate snapshot of everything recorded so far."""
        return {
            "counters": dict(self.counters),
            "gauges": dict(self.gauges),
            "histograms": {
                name: {
                    "count": len(vs),
                    "min": min(vs),
                    "max": max(vs),
                    "mean": sum(vs) / len(vs),
                }
                for name, vs in self.histograms.items()
                if vs
            },
            "spans": [{"path": p, "seconds": s} for p, s in self.spans],
        }

    def flush(self):
        pass

    def close(self):
        pass


class _Timer:
    """Context manager recording one wall-clock duration into a recorder."""

    __slots__ = ("_metrics", "_name", "_t0", "seconds")

    def __init__(self, metrics, name):
        self._metrics = metrics
        self._name = name
        self.seconds = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        self.seconds = time.perf_counter() - self._t0
        self._metrics._record_timer(self._name, self.seconds)
        return False


def _json_safe(value):
    """Strict-JSON sanitizer: non-finite floats become the strings "NaN" /
    "Infinity" / "-Infinity" (recursively through dicts/lists). The step and
    health records exist precisely to carry blow-up evidence, and bare NaN
    tokens from ``json.dumps``'s default ``allow_nan=True`` would make
    exactly those lines unparseable to any strict-JSON consumer (jq on the
    live ``tail -f`` dashboard, non-Python ingests) — the one-JSON-object-
    per-line contract must hold hardest on the records that matter most.
    Consumers treat the strings as non-finite (the report does)."""
    if isinstance(value, float) and not math.isfinite(value):
        if math.isnan(value):
            return "NaN"
        return "Infinity" if value > 0 else "-Infinity"
    if isinstance(value, dict):
        return {k: _json_safe(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_json_safe(v) for v in value]
    return value


# public alias: every OTHER writer of record-shaped JSON (the report CLI's
# --format json, trace_stats' per-op lines, the bench records) shares the
# same sanitizer, so `json.dumps(..., allow_nan=False)` — which the
# house-rule linter now demands on metrics paths (rule SSP002) — can never
# crash on legitimately non-finite evidence values
json_safe = _json_safe


class JsonlMetrics(MetricsRecorder):
    """MetricsRecorder with a versioned append-only JSONL sink.

    Every record is one line, written (and by default flushed) immediately —
    a killed run keeps everything recorded up to the kill, and ``tail -f``
    on the file is a live dashboard. The first line is a ``meta`` header
    naming the schema; each record also carries ``"v": SCHEMA_VERSION`` so
    lines stay self-describing when files are concatenated.

    ``flush_every``: flush the OS buffer every N records (1 = every record;
    per-epoch recording volumes make this free either way).

    Multihost: under ``jax.process_count() > 1`` the path gains a
    ``.p{process_index}`` suffix — every host owns its shard, so
    concurrent processes can never interleave half-lines into one file
    (``self.path`` reports the EFFECTIVE path; ``read_jsonl`` reads the
    shard set back via glob or the automatic ``.p*`` fallback).
    """

    def __init__(self, path, mode="w", flush_every=1):
        super().__init__()
        self.path = _shard_path(path)
        self._flush_every = max(1, int(flush_every))
        self._since_flush = 0
        # one writer lock: the async checkpoint writer emits its completion
        # records from the background thread, and two half-interleaved
        # lines would break the one-JSON-object-per-line contract exactly
        # on the crash-evidence records that matter most
        self._write_lock = threading.Lock()
        self._f = open(self.path, mode, encoding="utf-8")
        self._emit(
            {
                "kind": "meta",
                "name": "metrics",
                "schema": SCHEMA_NAME,
                "created": time.strftime("%Y-%m-%d %H:%M:%S"),
            }
        )

    def _emit(self, record):
        line = json.dumps(
            _json_safe({"v": SCHEMA_VERSION, "ts": time.time(), **record}),
            allow_nan=False,  # enforced: every line is STRICT JSON
        )
        with self._write_lock:
            if self._f is None:
                raise ValueError(f"JsonlMetrics({self.path!r}) is closed")
            self._f.write(line + "\n")
            self._since_flush += 1
            if self._since_flush >= self._flush_every:
                self._f.flush()
                self._since_flush = 0

    def flush(self):
        with self._write_lock:
            if self._f is not None:
                self._f.flush()
                self._since_flush = 0

    def close(self):
        with self._write_lock:
            if self._f is not None:
                self._f.flush()
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


def _shard_path(path):
    """The process-local JSONL path: ``path.p{process_index}`` when more
    than one jax process is live (multihost runs must never share one
    append target), the path unchanged otherwise — including when jax is
    absent or uninitialized (the sink must not force a jax dependency).

    The probe checks the DISTRIBUTED runtime state first (multihost
    compat helper) and only asks ``jax.process_count()`` — which
    initializes the backend as a side effect — once distributed is known
    to be up. Consequence: construct the sink AFTER
    ``jax.distributed.initialize()`` / ``parallel.multihost.initialize()``
    — a sink constructed before it cannot see the process set and will
    not shard."""
    path = os.fspath(path)
    try:
        from shallowspeed_tpu.parallel.multihost import (
            _distributed_is_initialized,
        )

        if not _distributed_is_initialized():
            return path  # single-process, or distributed not up yet
        import jax

        if jax.process_count() > 1:
            return f"{path}.p{jax.process_index()}"
    except Exception:  # noqa: BLE001 — best-effort probe, never a crash
        pass
    return path


def replica_shard_path(path, replica_id):
    """The fleet worker's JSONL path: ``path.r{replica_id}`` — the
    multihost ``.p{process_index}`` convention reused for serving
    replicas, so N engine worker processes can never interleave writes
    into one file. The parent fleet process owns the bare ``path``;
    ``replica_id`` is the join key between its ``fleet``/``fleet_health``
    records and each shard's per-request stream."""
    return f"{os.fspath(path)}.r{int(replica_id)}"


def _expand_shards(path):
    """``read_jsonl`` path resolution: an existing file is read as-is
    (even when its name contains glob metacharacters); otherwise an
    explicit glob expands to its sorted matches, and a bare path falls
    back to its multihost ``.p*`` shards (what ``JsonlMetrics`` wrote
    under ``process_count() > 1``) or its fleet ``.r*`` shards (what the
    fleet workers wrote via ``replica_shard_path``)."""
    s = os.fspath(path)
    if os.path.exists(s):
        return [s]
    if any(c in s for c in "*?["):
        shards = sorted(_glob.glob(s))
        if not shards:
            raise FileNotFoundError(f"no metrics files match glob {s!r}")
        return shards
    # only writer-shaped shards (".p"/".r" + digits) — a neighbor like
    # "run.jsonl.partial" must never be silently merged as a shard
    shards = sorted(
        _glob.glob(_glob.escape(s) + ".p[0-9]*")
        + _glob.glob(_glob.escape(s) + ".r[0-9]*")
    )
    if shards:
        return shards
    return [s]


def read_jsonl(path, strict=True):
    """Load a metrics JSONL file back into a list of record dicts.

    ``path`` may be a single file, a glob (``run.jsonl.p*`` — multihost
    shards are read in sorted order and concatenated), or a bare path whose
    ``.p*`` shards exist (the multihost auto-fallback).

    ``strict=True`` (default) raises on records whose schema version is
    newer than this reader understands — refusing loudly beats silently
    misreading a future schema (the honesty rule every published record in
    this repo follows). Blank lines are skipped; malformed lines raise.
    """
    records = []
    for shard in _expand_shards(path):
        with open(shard, encoding="utf-8") as f:
            for i, line in enumerate(f):
                line = line.strip()
                if not line:
                    continue
                rec = json.loads(line)
                if strict and rec.get("v", 0) > SCHEMA_VERSION:
                    raise ValueError(
                        f"{shard}:{i + 1}: record schema v{rec.get('v')} is "
                        f"newer than this reader (v{SCHEMA_VERSION})"
                    )
                records.append(rec)
    return records
