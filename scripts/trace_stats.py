"""Thin CLI shim for shallowspeed_tpu.observability.trace_stats.

The analyzer was promoted into the package (importable + unit-tested); this
script keeps the historical command-line surface working:

    python scripts/trace_stats.py artifacts/tpu_trace
    python scripts/trace_stats.py path/to/xyz.trace.json.gz --json
"""

import sys
from pathlib import Path

try:
    from shallowspeed_tpu.observability import trace_stats as _trace_stats
except ImportError:  # direct script invocation without the repo on sys.path
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from shallowspeed_tpu.observability import trace_stats as _trace_stats

find_traces = _trace_stats.find_traces
summarize = _trace_stats.summarize
main = _trace_stats.main

if __name__ == "__main__":
    main()
