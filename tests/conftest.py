"""Test config: force an 8-device virtual CPU platform BEFORE jax imports.

This is how the reference's biggest testing gap (no distributed tests at all,
SURVEY §4) gets closed without a TPU pod: every DP/PP layout runs SPMD on
8 emulated host devices, so mesh/collective code paths are exercised for real.
"""

import os

# Keep the TPU tunnel plugin (axon) completely out of CPU test runs: its
# sitecustomize registration (gated on PALLAS_AXON_POOL_IPS) would dial the
# single-client TPU tunnel at backend init and serialize/hang parallel CPU
# processes.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (after env setup, before any test imports)

# If the plugin registered at interpreter startup it may have forced
# jax_platforms='axon,cpu'; pin it back so backends() never dials the tunnel.
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite's wall clock is dominated by
# repeated pipeline-step compiles (dozens of distinct mesh programs). With
# the cache warm, recompiles of unchanged programs are disk loads; measured
# ~5x on a representative pipeline-step compile. Keyed by HLO + compile
# options, so source changes re-compile exactly what they invalidate.
#
# jax 0.4.x ONLY: the persistent cache corrupts the CPU client's heap
# (reproducible `malloc(): invalid size` / segfaults once cached pipeline
# programs and donated sequential steps mix in one process — this was
# crashing the suite mid-run, truncating everything after test_executor),
# so it is gated to jax >= 0.5 where it is stable.
_jax_version = tuple(int(p) for p in jax.__version__.split(".")[:2])
if _jax_version >= (0, 5):
    _cache = os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`: the marker gates tests whose coverage is
    # duplicated by a Makefile smoke target (e.g. the CLI SIGKILL round
    # trip, recovery-smoke's in-suite twin) out of the bounded gate
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 gate (-m 'not slow')"
    )
    # fleet tests spawn real worker processes (multiprocessing spawn +
    # their own JAX runtimes); they skip-with-reason on platforms that
    # cannot spawn workers — mirroring the multihost collectives skip —
    # so tier-1 stays green on constrained runners
    config.addinivalue_line(
        "markers",
        "fleet: multi-process serving-fleet tests (skipped when the "
        "platform cannot spawn worker processes)",
    )
