"""MPMD per-stage runtime (parallel/mpmd.py): the lockstep twin parity
contract, the admission gate, runtime-independent checkpoints, and the
deferred-unstacking async snapshot.

The acceptance bar is BITWISE: the MPMD runtime reuses the lockstep
executor's per-slot expressions over the identical padded slot stacks
and accumulates gradients in the tick-table stream order, so every
trained weight must hash-equal the lockstep twin's — no tolerance, on
every lattice point (docs/numerics.md "Runtime equivalence")."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu.optimizer import SGD, Adam, MomentumSGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.observability.divergence import assert_models_equal
from shallowspeed_tpu.parallel import mpmd
from shallowspeed_tpu.parallel.lowering import lower_schedule
from shallowspeed_tpu.parallel.mesh import make_mesh

SIZES = (40, 36, 32, 28, 24, 20, 14, 10)

# the named lattice: every point the mpmd-smoke gate and the ISSUE call
# out — dp, the two flat schedules, the split backward, tensor
# parallelism, interleaved virtual stages, and a 3-axis composition
LATTICE = {
    # name -> (dp, pp, tp, V, schedule, backward_split, optimizer)
    "gpipe-pp4": (1, 4, 1, 1, S.GPipeSchedule, False, SGD(0.01)),
    "pipedream-pp4": (1, 4, 1, 1, S.PipeDreamFlushSchedule, False, SGD(0.01)),
    "dp2-gpipe": (2, 2, 1, 1, S.GPipeSchedule, False, MomentumSGD(0.005, 0.9)),
    "bsplit-pp4": (1, 4, 1, 1, S.GPipeSchedule, True, SGD(0.01)),
    "tp2-pp2": (1, 2, 2, 1, S.GPipeSchedule, False, SGD(0.01)),
    "interleaved-V2": (1, 2, 1, 2, S.InterleavedSchedule, False, SGD(0.01)),
    "dp2-pp2-tp2": (
        2, 2, 2, 1, S.PipeDreamFlushSchedule, False, MomentumSGD(0.005, 0.9),
    ),
}


def _train_pair(dp, pp, tp, V, sched, bsplit, opt, sizes=SIZES, M=4, B=32,
                batches=2, data_seed=0, recompute=False, act="relu"):
    """Train the same two batches through the lockstep executor and the
    MPMD runner; returns (lockstep_leaves, mpmd_leaves, runner)."""
    spec = Mo.make_model_spec(sizes, pp * V, B, act=act)
    mesh = make_mesh(dp, pp, tp=tp)
    order = E.interleave_order(pp * V, pp) if V > 1 else None
    prog = lower_schedule(
        sched, M, pp, virtual=V, backward_split=bsplit, recompute=recompute
    )
    rng = np.random.RandomState(data_seed)
    X = rng.randn(batches, B, sizes[0]).astype(np.float32)
    Y = np.eye(sizes[-1], dtype=np.float32)[
        rng.randint(0, sizes[-1], (batches, B))
    ]

    stacked, flags = E.init_stacked(spec, mesh, order=order)
    ost = opt.init(stacked)
    step = E.make_pipeline_step(mesh, spec, prog, B // dp // M, opt)
    for i in range(batches):
        stacked, ost, _ = step(
            stacked, flags, ost, jnp.asarray(X[i]), jnp.asarray(Y[i])
        )
    lock = jax.tree.leaves(jax.device_get(stacked))

    stacked2, flags2 = E.init_stacked(spec, mesh, order=order)
    ost2 = opt.init(stacked2)
    runner = mpmd.MpmdTrainRunner(mesh, spec, prog, B // dp // M, opt)
    stacked2, ost2, _ = runner.run(stacked2, flags2, ost2, X, Y)
    got = jax.tree.leaves(jax.device_get(stacked2))
    return lock, got, runner


@pytest.mark.parametrize("layout", sorted(LATTICE))
def test_mpmd_bitwise_identical_to_lockstep(layout):
    """Every lattice point: MPMD epoch weights are BIT-identical to the
    lockstep twin's — same math, same padded widths, same accumulation
    order, different runtime."""
    lock, got, runner = _train_pair(*LATTICE[layout])
    assert runner.dispatch_count > 0 and runner.admission["findings"] == 0
    for a, b in zip(lock, got):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=layout
        )


# recompute rides the MPMD runtime too: the fwd_ns/recompute stage roles
# must reproduce the lockstep recompute executor bit-for-bit, on the flat
# schedules recompute supports (interleaved is lowering-refused)
RECOMPUTE_LATTICE = {
    # name -> (dp, pp, tp, sched, bsplit, opt, act)
    "gpipe-pp4-recompute": (
        1, 4, 1, S.GPipeSchedule, False, SGD(0.01), "relu",
    ),
    "pd-pp4-split-recompute-gelu": (
        1, 4, 1, S.PipeDreamFlushSchedule, True, SGD(0.01), "gelu",
    ),
    "dp2-pp2-recompute": (
        2, 2, 1, S.GPipeSchedule, False, MomentumSGD(0.005, 0.9), "relu",
    ),
    "tp2-pp2-recompute-gelu": (
        1, 2, 2, S.GPipeSchedule, False, SGD(0.01), "gelu",
    ),
}


@pytest.mark.parametrize(
    "layout",
    # the flagship gpipe point keeps tier-1 coverage (recompute-smoke
    # drives the split twin end to end); the split/dp/tp compositions
    # ride the slow tier (1-core wall budget)
    [lay if lay.startswith("gpipe") else
     pytest.param(lay, marks=pytest.mark.slow)
     for lay in sorted(RECOMPUTE_LATTICE)],
)
def test_mpmd_recompute_bitwise_identical_to_lockstep(layout):
    """recompute=True lattice: the MPMD runner's no-stash forward +
    recompute roles train bit-identically to the lockstep recompute
    executor on every supported layout."""
    dp, pp, tp, sched, bsplit, opt, act = RECOMPUTE_LATTICE[layout]
    lock, got, runner = _train_pair(
        dp, pp, tp, 1, sched, bsplit, opt, recompute=True, act=act,
    )
    assert runner.dispatch_count > 0 and runner.admission["findings"] == 0
    for a, b in zip(lock, got):
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b), err_msg=layout
        )


@pytest.mark.parametrize(
    "seed",
    # seeds 1 and 4 are the two heaviest draws; they ride the slow tier
    # (1-core wall budget) while make mpmd-smoke and the recompute
    # lattice keep tier-1 mpmd coverage
    [s if s not in (1, 4) else pytest.param(s, marks=pytest.mark.slow)
     for s in range(6)],
)
def test_mpmd_fuzz_matches_lockstep(seed):
    """Random-lattice fuzz: runtime=mpmd as a fuzz dimension — random
    sizes, mesh shape, schedule, split backward and optimizer must stay
    bitwise against the lockstep twin, not just the handcrafted cases."""
    rng = np.random.RandomState(7000 + seed)
    dp, pp = [(2, 2), (1, 4), (2, 1)][seed % 3]
    tp = 2 if seed % 2 == 0 and dp * pp <= 4 else 1
    V = 2 if seed % 3 == 2 and pp > 1 else 1
    sched = (
        S.InterleavedSchedule
        if V > 1
        else [
            S.GPipeSchedule, S.PipeDreamFlushSchedule, S.NaiveParallelSchedule
        ][seed % 3]
    )
    bsplit = V == 1 and bool(seed % 2)
    opt = [SGD(0.01), MomentumSGD(0.005, 0.9), Adam(0.003)][seed % 3]
    n_sizes = pp * V * int(rng.randint(2, 4))
    widths = sorted(rng.randint(8, 48, size=n_sizes - 1).tolist(), reverse=True)
    sizes = tuple(widths) + (int(rng.randint(4, min(8, min(widths)) + 1)),)
    M = int(pp * rng.choice([1, 2]))
    B = int(dp * M * rng.choice([4, 8]))
    lock, got, _ = _train_pair(
        dp, pp, tp, V, sched, bsplit, opt, sizes=sizes, M=M, B=B,
        data_seed=8000 + seed,
    )
    label = f"seed={seed} dp={dp} pp={pp} tp={tp} V={V} bsplit={bsplit}"
    for a, b in zip(lock, got):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b), err_msg=label)


def test_tampered_tick_table_refused_before_any_dispatch(monkeypatch):
    """The admission gate: a tick program whose tables were tampered with
    is refused by the happens-before proof BEFORE any stage program is
    even BUILT (let alone compiled or dispatched) — the gate runs first
    in the runner constructor."""
    from shallowspeed_tpu.analysis import ProgramAnalysisError

    spec = Mo.make_model_spec(SIZES, 4, 32)
    mesh = make_mesh(1, 4)
    prog = lower_schedule(S.GPipeSchedule, 4, 4)
    # tamper: erase one forward send — its consumer's recv now has no
    # matching send, the exact corruption async dispatch would hang on
    send_fwd = np.array(prog.send_fwd)
    t, s = np.argwhere(send_fwd == 1)[0]
    send_fwd[t, s] = 0
    bad = dataclasses.replace(prog, send_fwd=send_fwd)

    def no_build(*a, **k):  # pragma: no cover - must never run
        raise AssertionError("stage programs built before the admission gate")

    monkeypatch.setattr(mpmd, "_StagePrograms", no_build)
    with pytest.raises(ProgramAnalysisError):
        mpmd.MpmdTrainRunner(mesh, spec, bad, 8, SGD(0.01))
    # the serving-side gate: an inference table with a clobbered recv
    # slot is refused before any stage program exists
    iprog = lower_schedule(S.InferenceSchedule, 2, 4, training=False)
    rf = np.array(iprog.read_fwd_slot)
    hit = np.argwhere(rf != iprog.n_fwd_slots)[0]
    rf[hit[0], hit[1]] = iprog.n_fwd_slots  # drop the consuming read
    bad_inf = dataclasses.replace(iprog, read_fwd_slot=rf)
    with pytest.raises(ProgramAnalysisError):
        mpmd.MpmdInferenceRunner(mesh, spec, bad_inf, 8)


@pytest.mark.parametrize("dp,tp", [(1, 1), (1, 2), (2, 2)])
def test_stage_programs_census_clean_and_permute_free(dp, tp):
    """The defining MPMD property, proven from the compiled HLO on every
    sub-mesh shape (incl. the Megatron tp axis, whose structural psum
    floor must tolerate the non-relaying first stage's dead dx psum):
    relays left the program — no stage program lowers a
    collective-permute, every program passes its per-stage census, and
    none donates a buffer (every stage program is a dispatch path)."""
    from shallowspeed_tpu.observability import program_audit

    spec = Mo.make_model_spec((24, 20, 18, 16), 2, 16 * dp)
    mesh = make_mesh(dp, 2, tp=tp)
    prog = lower_schedule(S.GPipeSchedule, 2, 2)
    runner = mpmd.MpmdTrainRunner(mesh, spec, prog, 8, SGD(0.01))
    stacked, flags = E.init_stacked(spec, mesh)
    ost = SGD(0.01).init(stacked)
    cache = {}
    for s, role, variant in runner.planned_programs():
        jit_fn = runner.programs.get(s, role, variant)
        args = runner.example_args(
            s, role, variant, stacked, flags, ost, cache=cache
        )
        compiled = jit_fn.lower(*args).compile()
        sends = variant[2] if role in ("bwd", "bwd_in") else True
        rec = program_audit.audit_compiled(
            compiled,
            expected=mpmd.expected_stage_comms(role, spec, dp, tp, sends=sends),
        )
        label = f"dp{dp}tp{tp}:" + runner.programs.label(s, role, variant)
        assert rec["census_ok"] is not False, (label, rec.get("mismatches"))
        assert rec["census"].get("collective_permute", {}).get("count", 0) == 0, label
        program_audit.verify_dispatch_safety(compiled, context=label)


@pytest.fixture(scope="module")
def mpmd_data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("mpmd_data")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 128), ("val", 64)):
        np.save(d / f"x_{suffix}.npy", rng.rand(n, 784).astype(np.float32))
        np.save(
            d / f"y_{suffix}.npy",
            np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)],
        )
    return d


def _session(data_dir, runtime, **kw):
    from shallowspeed_tpu.api import TrainingSession

    base = dict(
        pp=4, schedule="gpipe", global_batch_size=32, mubatches=4,
        data_dir=data_dir, runtime=runtime,
    )
    base.update(kw)
    return TrainingSession(**base)


def test_session_mpmd_hash_and_predict_parity(mpmd_data_dir):
    """TrainingSession(runtime='mpmd'): epoch weights hash-equal the
    lockstep twin's, and predict() — the serving dispatch path — is
    bitwise-equal row for row (the engine's parity contract holds across
    runtimes)."""
    a = _session(mpmd_data_dir, "lockstep")
    b = _session(mpmd_data_dir, "mpmd", audit=True)
    for _ in range(2):
        a.train_epoch()
        b.train_epoch()
    # digest-backed comparator: failure names the first divergent
    # (layer, tensor) instead of a bare cross-runtime hash mismatch
    assert_models_equal(a.params(), b.params(), "lockstep", "mpmd")
    x = np.random.RandomState(1).rand(50, 784).astype(np.float32)
    np.testing.assert_array_equal(a.predict(x), b.predict(x))
    # streaming submit returns the same rows as the blocking path
    one = x[:8]
    resolve = b.predict_async(one)
    np.testing.assert_array_equal(b.predict(one), resolve())


@pytest.mark.slow  # four full runs (both kill/resume directions) — slow
# tier per the 1-core wall budget; the per-runtime kill-resume legs and
# the lockstep-parity tests keep tier-1 coverage of each half
def test_kill_and_resume_is_runtime_independent(mpmd_data_dir, tmp_path):
    """Checkpoints are runtime-independent: a run killed under ONE
    runtime resumes under the OTHER and finishes on the uninterrupted
    twin's exact bits — both directions (the session state contract:
    the MPMD runner reassembles the same full-mesh arrays the lockstep
    program carries)."""
    from shallowspeed_tpu.faults import InjectedFault

    for killed_rt, resumed_rt in (("mpmd", "lockstep"), ("lockstep", "mpmd")):
        twin = _session(mpmd_data_dir, resumed_rt, optimizer="momentum")
        for _ in range(2):
            twin.train_epoch()

        ck = tmp_path / f"ck_{killed_rt}"
        run = _session(
            mpmd_data_dir, killed_rt, optimizer="momentum",
            checkpoint_dir=ck, faults="die@step=3",
        )
        with pytest.raises(InjectedFault):
            while run.epoch < 2:
                run.train_steps(2)
                run.save_step_checkpoint()
        res = _session(
            mpmd_data_dir, resumed_rt, optimizer="momentum",
            checkpoint_dir=ck, resume="auto",
        )
        assert res.resumed_from is not None and res.global_step == 3
        while res.epoch < 2:
            res.train_steps(2)
        assert_models_equal(
            res.params(), twin.params(),
            f"killed-{killed_rt}-resumed-{resumed_rt}", "twin",
        )


def test_async_checkpoint_defers_unstacking_bitwise(mpmd_data_dir, tmp_path):
    """The deferred-unstacking async save (ROADMAP item 5 follow-on):
    the writer-thread build produces a snapshot BYTE-identical to the
    synchronous on-path build — params AND optimizer state — so moving
    the logical reshaping off the step path changed cost, not content."""
    from shallowspeed_tpu.checkpoint import load_checkpoint

    paths = {}
    for name, async_ in (("sync", False), ("async", True)):
        run = _session(
            mpmd_data_dir, "mpmd", optimizer="momentum",
            checkpoint_dir=tmp_path / name, async_checkpoint=async_,
        )
        run.train_steps(2)
        paths[name] = run.save_step_checkpoint()
        run.drain_checkpoints()
        run.close()
    a = load_checkpoint(paths["sync"], 4, 32, with_opt_state=True)
    b = load_checkpoint(paths["async"], 4, 32, with_opt_state=True)
    for x, y in zip(jax.tree.leaves(a[0]), jax.tree.leaves(b[0])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(jax.tree.leaves(a[3]), jax.tree.leaves(b[3])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_mpmd_refuses_unsupported_knobs(mpmd_data_dir):
    """The feature envelope is enforced loudly at construction, and the
    fused-run contract is refused at call time."""
    from shallowspeed_tpu.api import TrainingSession

    base = dict(
        global_batch_size=32, mubatches=4, data_dir=mpmd_data_dir,
        runtime="mpmd",
    )
    with pytest.raises(ValueError, match="sequential"):
        TrainingSession(**base)  # dp=pp=tp=1
    for bad in (
        dict(pp=4, schedule="gpipe", zero1=True),
        dict(pp=4, schedule="gpipe", grad_bucket_bytes=1024),
        dict(pp=4, schedule="gpipe", clip_norm=0.1),
        dict(pp=4, schedule="gpipe", kernel_backend="pallas"),
        dict(pp=4, schedule="gpipe", record_steps=True),
    ):
        with pytest.raises(ValueError, match="mpmd"):
            TrainingSession(**base, **bad)
    run = _session(mpmd_data_dir, "mpmd")
    with pytest.raises(ValueError, match="train_epoch"):
        run.train_run(1)
    with pytest.raises(ValueError, match="per-stage"):
        run.warm_run(1)
