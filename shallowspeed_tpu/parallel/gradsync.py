"""Bucketed gradient synchronization: the DP sync as a PLANNED op sequence.

The reference's headline DP feature is a *computation-overlapped,
non-blocking* gradient all-reduce: its engine (pipe.py:302-327) issues one
MPI ``Iallreduce`` per parameter as soon as that parameter's backward
finishes, output layer first, and its docstring wishes it could bucket
small tensors together. Our executor historically collapsed all of that
into ONE whole-tree ``lax.psum`` at the ``BackwardGradAllReduce`` anchor —
correct, but a single fat dependency: XLA cannot start any gradient
communication until every leaf is ready, and nothing downstream (clip
norm, the optimizer update) can start until the whole sync returns.

This module restores the reference's structure in SPMD form. A
``BucketPlan`` greedily packs the per-device gradient leaves into byte-
bounded buckets in BACKWARD order (output layer first — the order the tick
loop finalizes them), and the emitters issue one collective per bucket:

- plain DP (``zero=0``): each bucket's leaves are flattened into one
  contiguous vector and ``lax.psum``'d — one all-reduce op per bucket in
  the compiled program (verified by the program audit's census contract).
  Buckets have no data dependence on each other, so XLA's latency-hiding
  scheduler is free to overlap bucket k's all-reduce with the consumers of
  already-synced buckets (norm partials, the elementwise update of their
  params);
- ZeRO-1: the padded flat gradient is viewed as a ``(dp, chunk)`` matrix
  (row d = the chunk replica d updates) and each bucket is a COLUMN range,
  reduce-scattered with ``scatter_dimension=0, tiled=False`` — every
  device receives exactly the same contiguous chunk slice the anchor
  layout gives it, so the optimizer-state layout, the checkpoint mapping
  and the single deferred ``all_gather`` of the updated chunk are all
  untouched by bucketing;
- ZeRO-2 (bucketed): asking for ``grad_bucket_bytes`` at stage 2 keeps
  the FULL-slab gradient accumulators through the scan (that is what
  keeps the tail sync bitwise-equal to zero-1 at any microbatch count)
  and buckets the tail reduce-scatter: each slot's slab deals into its
  own ``(dp, V*k)`` column-block matrix — executor's block-cyclic
  layout — so each bucket is a ``(slot, start, stop)`` column range of
  one slot's matrix, emitted in the same backward order. Concatenating a
  slot's bucket outputs reproduces the anchor shard segment exactly.
  The ANCHOR stage-2 program (no bucket plan) instead earns the grads÷dp
  residency row by reduce-scattering PER TICK into a persistent
  per-rank shard carry — sharing ZeRO-3's per-slot scatter emitter, and
  trading the reassociated (dp x microbatch) sum order for it (bitwise
  vs zero-1 only at ``mubatches=1``; see docs/performance.md);
- ZeRO-3 has nothing for this module to plan: the gradient reduce-scatter
  happens PER TICK inside the scan (one collective per layer slot as its
  backward finishes — the reference's per-parameter Iallreduce, finally
  literal), so the executor refuses ``grad_bucket_bytes`` at stage 3 and
  ``sync_comm_bytes`` prices the per-tick schedule analytically instead.

Numerics contract: ``psum``/``psum_scatter`` reduce ELEMENTWISE, and
flatten/concat/slice are exact data movement, so per-bucket sync is
**bitwise identical** to the same tail collective unbucketed — the
NumPy-oracle parity and cross-layout fuzz tests run unchanged over every
bucket size (tests/test_gradsync.py asserts the bit-equality directly).
At stage 2 the bucketed program's bitwise peer is ZERO-1 (both sum
dp-outer in full slabs), not the anchor stage-2 program, whose per-tick
scatter sums microbatch-outer. ``bucket_bytes
= 0`` disables planning entirely: the executor keeps its legacy anchor
collective, same program byte for byte.

The plan is pure host data (derived deterministically from the model spec
and the knob), so the executor, the TrainingSession audit contract
(observability/program_audit.expected_comms) and the bench rows all build
the SAME plan and can never disagree about bucket count or sizes.
"""

import dataclasses

import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class BucketLeaf:
    """One gradient leaf of the executor's per-device stacked tree."""

    kind: str  # "W" | "b"
    slot: int  # layer-slot index (executor.slot_shapes order)
    shape: tuple  # per-device stacked shape: (V, o, i) for W, (V, o) for b

    @property
    def size(self):
        n = 1
        for d in self.shape:
            n *= int(d)
        return n

    @property
    def nbytes(self):
        return 4 * self.size  # f32 gradients


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """A static bucketing of one layout's gradient sync.

    ``mode="dp"``: ``buckets`` is a tuple of leaf groups (each a tuple of
    ``BucketLeaf``), in backward order — the emitter issues one flat
    ``psum`` per group. ``mode="zero1"``: ``buckets`` is a tuple of
    ``(start, stop)`` column ranges over the per-replica chunk — the
    emitter issues one ``psum_scatter`` per range (``dp`` records the
    replica count the ranges were planned for). ``mode="zero2"``:
    ``buckets`` is a tuple of ``(slot_index, start, stop)`` column ranges
    over one slot's ``(dp, V*k)`` block-cyclic matrix
    (``executor.zero_block_slots`` order), emitted in backward order.
    """

    mode: str  # "dp" | "zero1" | "zero2"
    bucket_bytes: int  # the --grad-bucket-bytes knob that built the plan
    buckets: tuple
    dp: int = 1  # zero1/zero2: replicas (census result bytes = grad / dp)

    @property
    def num_buckets(self):
        return len(self.buckets)

    def bucket_grad_bytes(self):
        """Per-bucket synced-gradient payload in bytes (what the byte
        budget bounds): the full leaf bytes for DP buckets, ``dp x width``
        scattered columns for ZeRO-1/2 buckets."""
        if self.mode == "dp":
            return [sum(l.nbytes for l in group) for group in self.buckets]
        if self.mode == "zero2":
            return [4 * self.dp * (b - a) for _, a, b in self.buckets]
        return [4 * self.dp * (b - a) for a, b in self.buckets]

    def bucket_census_bytes(self):
        """Per-bucket expected HLO RESULT bytes — what the program audit
        matches against ``parse_collectives``: an all-reduce returns the
        full bucket on every device; a reduce-scatter returns 1/dp of it."""
        if self.mode == "dp":
            return self.bucket_grad_bytes()
        if self.mode == "zero2":
            return [4 * (b - a) for _, a, b in self.buckets]
        return [4 * (b - a) for a, b in self.buckets]

    def total_grad_bytes(self):
        return sum(self.bucket_grad_bytes())

    def describe(self):
        """JSON-able plan summary (metrics / bench record lines)."""
        return {
            "mode": self.mode,
            "grad_bucket_bytes": int(self.bucket_bytes),
            "num_buckets": self.num_buckets,
            "bucket_grad_bytes": self.bucket_grad_bytes(),
            "bucket_census_bytes": self.bucket_census_bytes(),
            "total_grad_bytes": self.total_grad_bytes(),
        }


def _stacked_leaves(spec, pp, tp=1):
    """The executor's per-device gradient leaves in BACKWARD order: the
    tick loop's ``_stage_bwd`` finalizes slot L-1 (the output layer) first
    and computes each slot's dW and db together, so the bucket order is
    [W_{L-1}, b_{L-1}, ..., W_0, b_0]. Under tp the leaves are this rank's
    Megatron shards (``executor.tp_local_dims``) — the dp sync moves 1/tp
    of the gradient per device, which is the TP memory/bandwidth story the
    comms model quotes."""
    from shallowspeed_tpu.parallel.executor import slot_shapes, tp_local_dims

    dims = slot_shapes(spec, tp)
    w_dims, b_widths, _, _ = tp_local_dims(dims, tp)
    V = spec.n_stages // pp
    leaves = []
    for l in reversed(range(len(dims))):
        o, i = w_dims[l]
        leaves.append(BucketLeaf("W", l, (V, o, i)))
        leaves.append(BucketLeaf("b", l, (V, b_widths[l])))
    return leaves


def plan_dp_buckets(spec, pp, bucket_bytes, tp=1):
    """Greedy byte-bounded bucketing of the stacked gradient tree for the
    plain-DP all-reduce. Returns None when ``bucket_bytes`` is falsy (the
    legacy whole-tree anchor psum). Every leaf lands in exactly one
    bucket; backward order is preserved; a bucket is closed as soon as
    adding the next leaf would exceed the budget (a single oversized leaf
    still gets its own bucket — the plan never splits a leaf)."""
    if not bucket_bytes:
        return None
    bucket_bytes = int(bucket_bytes)
    buckets, current, current_bytes = [], [], 0
    for leaf in _stacked_leaves(spec, pp, tp):
        if current and current_bytes + leaf.nbytes > bucket_bytes:
            buckets.append(tuple(current))
            current, current_bytes = [], 0
        current.append(leaf)
        current_bytes += leaf.nbytes
    if current:
        buckets.append(tuple(current))
    return BucketPlan(mode="dp", bucket_bytes=bucket_bytes, buckets=tuple(buckets))


def plan_zero1_buckets(spec, dp, pp, bucket_bytes, tp=1):
    """Byte-bounded bucketing of the ZeRO-1 reduce-scatter: column ranges
    over the per-replica chunk of the padded flat gradient. Each bucket
    covers ``dp x width`` gradient elements (one width-slice of EVERY
    replica's chunk), so the scatter's output concatenation reproduces the
    anchor chunk exactly. Returns None when ``bucket_bytes`` is falsy."""
    if not bucket_bytes:
        return None
    bucket_bytes = int(bucket_bytes)
    from shallowspeed_tpu.parallel.executor import stacked_flat_len

    csz = -(-stacked_flat_len(spec, pp, tp) // dp)
    width = max(1, bucket_bytes // (4 * dp))
    ranges = tuple(
        (a, min(a + width, csz)) for a in range(0, csz, width)
    )
    return BucketPlan(
        mode="zero1", bucket_bytes=bucket_bytes, buckets=ranges, dp=int(dp)
    )


def plan_zero2_buckets(spec, dp, pp, bucket_bytes, tp=1):
    """Byte-bounded bucketing of the ZeRO-2 per-slot reduce-scatters:
    ``(slot_index, start, stop)`` column ranges over each slot's
    ``(dp, V*k)`` block-cyclic matrix, in BACKWARD emission order (the
    tick loop finalizes slot L-1 first, dW and db together — the same
    order the DP planner walks). Each bucket scatters ``dp x width``
    gradient elements; concatenating a slot's bucket outputs in ascending
    range order reproduces its anchor shard segment exactly. Returns None
    when ``bucket_bytes`` is falsy."""
    if not bucket_bytes:
        return None
    bucket_bytes = int(bucket_bytes)
    from shallowspeed_tpu.parallel.executor import zero_block_slots

    slots, _ = zero_block_slots(spec, pp, dp, tp)
    L = len(slots) // 2
    width = max(1, bucket_bytes // (4 * dp))
    buckets = []
    for l in reversed(range(L)):
        for si in (l, L + l):  # W_l then b_l, mirroring _stacked_leaves
            cols = slots[si].rows * slots[si].k
            for a in range(0, cols, width):
                buckets.append((si, a, min(a + width, cols)))
    return BucketPlan(
        mode="zero2", bucket_bytes=bucket_bytes, buckets=tuple(buckets),
        dp=int(dp),
    )


def plan_buckets(spec, dp, pp, bucket_bytes, zero1=False, zero=None, tp=1):
    """The one layout->plan dispatch: the executor's emitters, the
    session's audit contract and the bench rows all plan through here, so
    they can never pick different planners for the same layout. ``zero``
    selects the dp stage (``zero1`` kept as the stage-1 alias); stage 3
    has no plan — its sync is per tick. Returns None when
    ``bucket_bytes`` is falsy (the legacy anchor sync)."""
    if zero is None:
        zero = 1 if zero1 else 0
    zero = int(zero)
    if zero == 3:
        if bucket_bytes:
            raise ValueError(
                "zero=3 syncs gradients per tick — there is no tail "
                "collective to bucket (grad_bucket_bytes must be 0)"
            )
        return None
    if zero == 2:
        return plan_zero2_buckets(spec, dp, pp, bucket_bytes, tp=tp)
    if zero == 1 or zero1:
        return plan_zero1_buckets(spec, dp, pp, bucket_bytes, tp=tp)
    return plan_dp_buckets(spec, pp, bucket_bytes, tp=tp)


def psum_bucketed(grads, plan, axis_name="dp"):
    """Per-bucket DP gradient sync: for each bucket, flatten its leaves
    into ONE contiguous vector, ``lax.psum`` it (one all-reduce op per
    bucket in the compiled program), and scatter the summed values back
    into the tree. Elementwise reduction + exact data movement = bitwise
    identical to the whole-tree anchor psum.

    ``grads``: the executor's per-device ``{"W": tuple, "b": tuple}``.
    Returns the same structure, fully summed over ``axis_name``.
    """
    out = {"W": list(grads["W"]), "b": list(grads["b"])}
    for group in plan.buckets:
        flat = jnp.concatenate(
            [grads[l.kind][l.slot].reshape(-1) for l in group]
        )
        summed = lax.psum(flat, axis_name)
        off = 0
        for l in group:
            out[l.kind][l.slot] = summed[off : off + l.size].reshape(l.shape)
            off += l.size
    return {"W": tuple(out["W"]), "b": tuple(out["b"])}


def psum_scatter_bucketed(gvec_padded, plan, axis_name="dp"):
    """Per-bucket ZeRO-1 gradient sync: view the padded flat gradient as
    ``(dp, chunk)`` — row d is the contiguous chunk replica d updates —
    and reduce-scatter each COLUMN range with ``scatter_dimension=0,
    tiled=False`` (one reduce-scatter op per bucket). Concatenating the
    per-bucket outputs reproduces this replica's anchor chunk exactly
    (same elements, same order), so the chunked update, the optimizer-
    state layout and the deferred all_gather are untouched by bucketing.
    """
    csz = gvec_padded.shape[0] // plan.dp
    mat = gvec_padded.reshape(plan.dp, csz)
    pieces = [
        lax.psum_scatter(
            mat[:, a:b], axis_name, scatter_dimension=0, tiled=False
        )
        for a, b in plan.buckets
    ]
    return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)


def sync_comm_bytes(
    spec, dp, pp, zero1=False, plan=None, tp=1, zero=None,
    mubatches=1, gather_passes=2,
):
    """The dp-axis leg of the analytical comms contract
    (observability/program_audit.expected_comms): ring-algorithm wire
    bytes PER DEVICE PER STEP for the gradient sync at every ZeRO stage,
    with the bucketing plan's per-collective breakdown when one is active.

    Stage 0 (plain DP): one all-reduce of the stacked gradient —
    ``2 (dp-1)/dp x 4*flat``. Stage 1 (ZeRO-1): reduce-scatter + deferred
    all-gather of the padded FLAT vector — the same ``2 (dp-1)/dp`` total
    over ``4*csz*dp`` (ring all-reduce IS RS+AG, so stages 0 and 1 tie on
    wire bytes). Stage 2 (ZeRO-2): the ANCHOR program reduce-scatters
    per tick into the persistent gradient shard (x ``mubatches``) and
    all-gathers the updated-param chunk once — the grad-sync leg proper
    moves HALF the anchor all-reduce's bytes per contribution (scatter
    results are 1/dp), paid once per microbatch; a BUCKETED stage-2 plan
    keeps the full-slab accumulators and the single byte-bucketed tail
    reduce-scatter (zero-1's wire total over the block-cyclic
    ``4*csz3*dp``). Stage 3 (ZeRO-3): the per-tick reduce-scatter plus
    ``gather_passes`` just-in-time param-gather sweeps per microbatch
    (forward + backward [+ recompute]) — the gather schedule MULTIPLIES
    dp traffic by the microbatch count, the price of never holding the
    params (quoted honestly; the win is memory, not wire bytes).

    Bucketing never changes a stage's TOTAL bytes — only how many ops
    carry them, which is exactly what the census contract verifies. Under
    tp each device syncs only its Megatron shard, so the dp payload
    shrinks by exactly tp (tensor parallelism composes with — never
    multiplies — the gradient-sync traffic).
    """
    from shallowspeed_tpu.parallel.executor import (
        stacked_flat_len,
        zero_block_slots,
    )

    if zero is None:
        zero = 1 if zero1 else 0
    zero = int(zero)
    flat = stacked_flat_len(spec, pp, tp)
    if zero >= 2:
        _, csz3 = zero_block_slots(spec, pp, dp, tp)
        payload = 4 * csz3 * dp  # the per-slot padded block-cyclic deal
        if zero == 3:
            M = int(mubatches)
            passes = int(gather_passes)
            rs_bytes = (dp - 1) / dp * M * payload
            ag_bytes = (dp - 1) / dp * M * passes * payload
            axis = {
                "kind": "reduce_scatter+all_gather",
                "algorithm": "ring",
                "grad_bytes_per_device": M * payload,
                "bytes_per_step_per_device": rs_bytes + ag_bytes,
                "reduce_scatter_bytes_per_step_per_device": rs_bytes,
                "scatter_schedule": "per_tick",
                "scatter_mubatches": M,
                "gather": {
                    "schedule": "per_tick",
                    "passes": passes,
                    "mubatches": M,
                    "bytes_per_step_per_device": ag_bytes,
                },
                # gathers live in distinct lax.switch branch computations
                # (forward / backward [/ recompute]) — XLA's combiners can
                # merge within a branch but never across branches, so the
                # compiled program must keep at least one per pass
                "hlo_min_all_gather_ops": passes,
            }
        elif plan is None:
            # anchor ZeRO-2: per-tick reduce-scatter into the persistent
            # shard (one contribution per microbatch), one deferred
            # all-gather of the updated-param chunk
            M = int(mubatches)
            rs_bytes = (dp - 1) / dp * M * payload
            ag_bytes = (dp - 1) / dp * payload
            axis = {
                "kind": "reduce_scatter+all_gather",
                "algorithm": "ring",
                "grad_bytes_per_device": M * payload,
                "bytes_per_step_per_device": rs_bytes + ag_bytes,
                "reduce_scatter_bytes_per_step_per_device": rs_bytes,
                "scatter_schedule": "per_tick",
                "scatter_mubatches": M,
            }
        else:
            # bucketed ZeRO-2: full-slab accumulators, one byte-bucketed
            # tail reduce-scatter + the deferred param all-gather —
            # zero-1's wire total over the block-cyclic payload
            axis = {
                "kind": "reduce_scatter+all_gather",
                "algorithm": "ring",
                "grad_bytes_per_device": payload,
                "bytes_per_step_per_device": 2 * (dp - 1) / dp * payload,
            }
    elif zero == 1:
        csz = -(-flat // dp)
        payload = 4 * csz * dp  # the padded flat vector
        axis = {
            "kind": "reduce_scatter+all_gather",
            "algorithm": "ring",
            "grad_bytes_per_device": payload,
            "bytes_per_step_per_device": 2 * (dp - 1) / dp * payload,
        }
    else:
        payload = 4 * flat  # this device's padded stacked gradient
        axis = {
            "kind": "all_reduce",
            "algorithm": "ring",
            "grad_bytes_per_device": payload,
            "bytes_per_step_per_device": 2 * (dp - 1) / dp * payload,
        }
    axis["zero"] = zero
    axis["mode"] = "anchor" if plan is None else "bucketed"
    if plan is not None:
        axis["grad_bucket_bytes"] = int(plan.bucket_bytes)
        axis["num_buckets"] = plan.num_buckets
        axis["bucket_grad_bytes"] = plan.bucket_grad_bytes()
        axis["bucket_census_bytes"] = plan.bucket_census_bytes()
    return axis
