"""SSP005 bad twin: an _emit record kind missing from SCHEMA_KINDS."""


class Recorder:
    def _emit(self, record):
        raise NotImplementedError

    def shiny_new(self, name, **fields):
        self._emit({"kind": "shiny_new_kind", "name": name, **fields})  # MARK
