"""Seeded diurnal traffic replay: the workload half of the capacity
scoreboard (ROADMAP item 4 / ISSUE 18).

A capacity decision can only be judged against a workload that can be
REPLAYED — the same arrival schedule, byte for byte, offered to a static
fleet, an autoscaled fleet, and the offline oracle. This module builds
that schedule: an inhomogeneous Poisson process whose rate follows a
compressed diurnal curve (a day's sinusoid squeezed into minutes of wall
time) with seeded flash-crowd spikes layered on top, realized by
Lewis-Shedler thinning so the arrivals are EXACTLY Poisson in the
modulated rate, not a per-bucket approximation.

Determinism contract (pinned by ``tests/test_replay.py``): the entire
trace — spike placement and the thinned arrival times — is drawn from
one ``np.random.RandomState(seed)``, so the same ``(seed, shape
parameters)`` produce a byte-identical ``arrivals`` array and rate
trace on every machine. The trace is driven through
``loadgen.run_open_loop``'s coordinated-omission-corrected backdating,
so a fleet that falls behind burns queued deadlines honestly instead of
silently throttling the offered load.

The RATE TRACE is the oracle's evidence: per-bucket analytic rate (the
modulation the arrivals were thinned against) plus the realized arrival
count. ``bench_replay`` computes the offline-oracle replica schedule
from this trace and the measured knee — the autoscaler never sees it.
"""

import math

import numpy as np

TRACE_VERSION = 1

# the real-world day the compressed trace stands for — recorded in the
# trace config so the scoreboard's "violation minutes" can be read in
# either clock (compressed wall seconds x compression = modeled seconds)
REAL_DAY_S = 86400.0


def diurnal_rate(t, day_s, base_rps, peak_rps, spikes=()):
    """The analytic modulation ``r(t)`` in requests/second: a raised
    cosine through one day (trough at ``t=0``, peak at ``t=day_s/2``)
    with each flash-crowd spike multiplying the rate over its
    ``[start, start+duration)`` window. ``spikes``: dicts with
    ``start``/``duration``/``mult``."""
    r = base_rps + (peak_rps - base_rps) * 0.5 * (
        1.0 - math.cos(2.0 * math.pi * t / day_s)
    )
    for sp in spikes:
        if sp["start"] <= t < sp["start"] + sp["duration"]:
            r *= sp["mult"]
    return r


def diurnal_trace(
    day_s=120.0,
    base_rps=20.0,
    peak_rps=120.0,
    seed=0,
    n_spikes=1,
    spike_mult=3.0,
    spike_duration_s=None,
    bucket_s=5.0,
):
    """Build one seeded replayable trace; returns a JSON-able dict:

    - ``arrivals``: ascending arrival times in ``[0, day_s)`` (numpy
      float64 — the schedule ``run_open_loop`` replays),
    - ``buckets``: the rate trace — per ``bucket_s`` window, the
      analytic mean rate (integrated, not point-sampled, so spikes
      shorter than a bucket still register) and the realized arrival
      count/rate,
    - ``config``: every shape parameter plus ``rate_max`` (the thinning
      bound) and ``compression`` (modeled day / compressed day).

    Spikes are placed in the busy half of the day (``[0.25, 0.75] x
    day_s``) so a flash crowd lands on top of real load — the case an
    autoscaler must survive — with duration defaulting to one tenth of
    the day. Thinning draws (exponential gaps at ``rate_max``, one
    uniform per candidate) come from the same ``RandomState`` as the
    spike placement: one seed, one byte stream, one trace."""
    if day_s <= 0:
        raise ValueError("day_s must be positive")
    if base_rps <= 0 or peak_rps < base_rps:
        raise ValueError("need 0 < base_rps <= peak_rps")
    if bucket_s <= 0:
        raise ValueError("bucket_s must be positive")
    rng = np.random.RandomState(seed)
    if spike_duration_s is None:
        spike_duration_s = day_s / 10.0
    spikes = []
    for _ in range(int(n_spikes)):
        start = float(
            rng.uniform(0.25 * day_s, 0.75 * day_s - spike_duration_s)
        )
        spikes.append(
            {
                "start": start,
                "duration": float(spike_duration_s),
                "mult": float(spike_mult),
            }
        )
    spikes.sort(key=lambda sp: sp["start"])
    # Lewis-Shedler thinning against a guaranteed envelope: the cosine
    # never exceeds peak_rps and spikes only multiply, so peak x the
    # largest mult dominates r(t) everywhere
    rate_max = peak_rps * max([sp["mult"] for sp in spikes] or [1.0])
    arrivals = []
    t = 0.0
    while True:
        t += rng.exponential(1.0 / rate_max)
        if t >= day_s:
            break
        if rng.uniform() * rate_max <= diurnal_rate(
            t, day_s, base_rps, peak_rps, spikes
        ):
            arrivals.append(t)
    arrivals = np.asarray(arrivals, np.float64)
    n_buckets = int(math.ceil(day_s / bucket_s))
    counts, _edges = np.histogram(
        arrivals, bins=n_buckets, range=(0.0, n_buckets * bucket_s)
    )
    buckets = []
    for b in range(n_buckets):
        t0, t1 = b * bucket_s, min((b + 1) * bucket_s, day_s)
        # integrate the analytic rate over the bucket on a fine grid
        # (closed form exists for the cosine but not across spike edges)
        grid = np.linspace(t0, t1, 33)
        mean_rate = float(
            np.mean(
                [diurnal_rate(g, day_s, base_rps, peak_rps, spikes) for g in grid]
            )
        )
        width = t1 - t0
        buckets.append(
            {
                "t0": t0,
                "t1": t1,
                "rate_rps": mean_rate,
                "arrivals": int(counts[b]),
                "offered_rps": (int(counts[b]) / width) if width > 0 else 0.0,
            }
        )
    return {
        "version": TRACE_VERSION,
        "arrivals": arrivals,
        "buckets": buckets,
        "config": {
            "day_s": float(day_s),
            "base_rps": float(base_rps),
            "peak_rps": float(peak_rps),
            "seed": int(seed),
            "spikes": spikes,
            "bucket_s": float(bucket_s),
            "rate_max": float(rate_max),
            "n_arrivals": int(arrivals.shape[0]),
            "compression": REAL_DAY_S / float(day_s),
        },
    }
