"""tpu_capture.main() plumbing test — the capture script runs at most once
per chip-claim window (the tunnel wedges for hours between them), so a
signature mismatch or key error anywhere in its phase sequence would burn
the round's only hardware window. This runs the REAL main() with every
heavy measurement stubbed: tier-0 banking, phase ordering,
checkpoint-after-every-phase, the per-phase budget containment and the
rename-into-place contract are exercised for real; only the
timing/convergence/trace work is faked.
"""

import json
import sys
import threading
import time
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture()
def capture_mod():
    added = []
    for p in (str(ROOT), str(ROOT / "scripts")):
        if p not in sys.path:
            sys.path.insert(0, p)
            added.append(p)
    import tpu_capture

    yield tpu_capture
    for p in added:
        sys.path.remove(p)


def test_capture_main_plumbing(tmp_path, monkeypatch, capture_mod):
    tc = capture_mod
    import bench
    import bench_tpu_matrix

    eq = {"max_abs_param_diff": 0.0, "loss_abs_diff": 0.0, "bitwise_equal": True}
    monkeypatch.setattr(
        bench, "_ensure_responsive_backend",
        lambda *a, **k: ("", {"probes": [{"outcome": "ok", "seconds": 1.0}]}),
    )
    monkeypatch.setattr(bench, "numpy_baseline_sps", lambda n_batches=40: 50.0)
    monkeypatch.setattr(
        bench, "jax_sps_many",
        lambda precisions, trials=2: {"default": 200.0, "highest": 100.0},
    )
    monkeypatch.setattr(
        tc, "epoch_kernel_vmem_analysis",
        lambda: {"epoch_kernel_vmem": {"sgd": {"compiled_ok": True}}},
    )
    monkeypatch.setattr(
        tc, "_kernel_variant_cells",
        lambda opt, precisions, key_fmt, nb, trials, label: (
            {"fused+default+xla": 1.0, "fused+default+mega": 2.0,
             "fused+default+epoch": 3.0},
            {},
            {"mega": eq, "epoch": eq},
        ),
    )
    monkeypatch.setattr(
        tc, "headline_sweep",
        lambda unrolls, trials, precision="highest": (
            {f"unroll={u}": 100.0 * u for u in unrolls}, {}
        ),
    )
    monkeypatch.setattr(
        tc, "megakernel_cells",
        lambda nb, trials: (
            {"fused+default+xla": 1.0, "fused+default+mega": 2.0,
             "fused+default+epoch": 3.0},
            {},
            {"mega": eq, "epoch": eq},
        ),
    )
    monkeypatch.setattr(
        tc, "convergence_run",
        lambda d, e: {"epochs": e, "final_val_accuracy": 0.99},
    )
    monkeypatch.setattr(
        tc, "megakernel_convergence",
        lambda d, e, variant="megakernel": {"variant": variant, "epochs": e},
    )
    monkeypatch.setattr(
        tc, "profile_one_epoch", lambda d, t: {"dir": str(t), "n_files": 1}
    )
    monkeypatch.setattr(
        tc, "profile_headline_epoch", lambda t: {"dir": str(t), "n_files": 1}
    )
    monkeypatch.setattr(
        bench_tpu_matrix, "run_matrix",
        lambda cells, nb, trials: {("fused", "default", "xla"): 123.0},
    )
    monkeypatch.setattr(
        tc, "executor_backend_cells",
        lambda nb, trials: ({"executor+default+xla": 1.0}, {}, eq),
    )
    monkeypatch.setattr(
        tc, "executor_backend_api_path",
        lambda d, epochs=2: {"hashes_match": True, "losses_match": True},
    )
    monkeypatch.setattr(
        tc, "adam_kernel_cells",
        lambda nb, trials: (
            {"adam+default+xla": 1.0}, {}, {"mega": eq, "epoch": eq}
        ),
    )
    monkeypatch.setattr(
        tc, "adam_epoch_kernel_convergence",
        lambda d: {"precision": "default", "loss": 0.1,
                   "val_accuracy": 0.99, "model_hash": "f" * 40},
    )

    out = tmp_path / "CAP.json"
    data_dir = tmp_path / "data"
    data_dir.mkdir()  # exists -> the prepare_data subprocess is skipped
    monkeypatch.setattr(
        sys, "argv",
        ["tpu_capture.py", "--quick", "--out", str(out),
         "--data-dir", str(data_dir)],
    )
    tc.main()

    assert out.is_file() and not Path(str(out) + ".partial").exists()
    result = json.loads(out.read_text())
    for key in (
        "info", "numpy_baseline_sps", "headline_sweep_default_precision",
        "headline_best_sps", "vs_baseline", "headline_sweep_fp32_highest",
        "megakernel_cells", "megakernel_onchip_equality", "convergence",
        "megakernel_convergence", "epoch_kernel_convergence", "trace",
        "trace_headline", "matrix", "matrix_full_epoch_fused",
        "executor_kernel_backends", "executor_onchip_equality",
        "executor_api_path", "adam_kernel_cells", "adam_onchip_equality",
        "adam_epoch_kernel_one_epoch", "completed_at",
    ):
        assert key in result, f"capture artifact missing {key!r}"
    assert result["epoch_kernel_convergence"]["variant"] == "epoch_kernel"
    assert result["megakernel_onchip_equality"]["epoch"]["bitwise_equal"]
    assert not result.get("phases_skipped_by_budget")

    # tier-0 artifact: banked as its own COMPLETE file before the full matrix
    t0 = tmp_path / "CAP_tier0.json"
    assert t0.is_file() and not Path(str(t0) + ".partial").exists()
    t0r = json.loads(t0.read_text())
    for key in (
        "info", "numpy_baseline_sps", "headline_pair", "headline_best_sps",
        "vs_baseline", "kernel_cells_default", "kernel_onchip_equality",
        "completed_at",
    ):
        assert key in t0r, f"tier-0 artifact missing {key!r}"
    assert t0r["tier"] == 0
    assert t0r["headline_pair"] == {"default": 200.0, "highest": 100.0}
    assert t0r["vs_baseline"] == 4.0  # 200 / 50


def test_capture_tier0_only_stops_after_banking(tmp_path, monkeypatch, capture_mod):
    tc = capture_mod
    import bench

    eq = {"max_abs_param_diff": 0.0, "loss_abs_diff": 0.0, "bitwise_equal": True}
    monkeypatch.setattr(
        bench, "_ensure_responsive_backend",
        lambda *a, **k: ("", {"probes": [{"outcome": "ok", "seconds": 1.0}]}),
    )
    monkeypatch.setattr(bench, "numpy_baseline_sps", lambda n_batches=40: 50.0)
    monkeypatch.setattr(
        bench, "jax_sps_many",
        lambda precisions, trials=2: {"default": 200.0, "highest": 100.0},
    )
    monkeypatch.setattr(
        tc, "epoch_kernel_vmem_analysis",
        lambda: {"epoch_kernel_vmem": {"sgd": {"compiled_ok": True}}},
    )
    monkeypatch.setattr(
        tc, "_kernel_variant_cells",
        lambda *a, **k: ({"fused+default+epoch": 3.0}, {}, {"epoch": eq}),
    )
    out = tmp_path / "CAP.json"
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    monkeypatch.setattr(
        sys, "argv",
        ["tpu_capture.py", "--tier0-only", "--out", str(out),
         "--data-dir", str(data_dir)],
    )
    tc.main()
    assert (tmp_path / "CAP_tier0.json").is_file()
    assert not out.exists()  # the full matrix never started


def test_capture_budget_skips_forward(tmp_path, monkeypatch, capture_mod):
    """A phase that hangs past its wall-clock budget is recorded as
    skipped-by-budget and every LATER phase still runs (round-4 verdict #6:
    one hung RPC must not consume the remaining window)."""
    tc = capture_mod
    import bench
    import bench_tpu_matrix

    eq = {"max_abs_param_diff": 0.0, "loss_abs_diff": 0.0, "bitwise_equal": True}
    monkeypatch.setattr(
        bench, "_ensure_responsive_backend",
        lambda *a, **k: ("", {"probes": [{"outcome": "ok", "seconds": 1.0}]}),
    )
    monkeypatch.setattr(bench, "numpy_baseline_sps", lambda n_batches=40: 50.0)
    monkeypatch.setattr(
        bench, "jax_sps_many",
        lambda precisions, trials=2: {"default": 200.0, "highest": 100.0},
    )
    monkeypatch.setattr(
        tc, "epoch_kernel_vmem_analysis",
        lambda: {"epoch_kernel_vmem": {"sgd": {"compiled_ok": True}}},
    )
    monkeypatch.setattr(
        tc, "_kernel_variant_cells",
        lambda *a, **k: ({"fused+default+epoch": 3.0}, {}, {"epoch": eq}),
    )
    monkeypatch.setattr(
        tc, "headline_sweep",
        lambda unrolls, trials, precision="highest": (
            {f"unroll={u}": 100.0 * u for u in unrolls}, {}
        ),
    )
    monkeypatch.setattr(
        tc, "megakernel_cells",
        lambda nb, trials: ({"fused+default+xla": 1.0}, {}, {"mega": eq, "epoch": eq}),
    )
    # phase 3 HANGS (simulated wedged RPC: uninterruptible sleep)
    hang = threading.Event()
    monkeypatch.setattr(
        tc, "convergence_run", lambda d, e: hang.wait(30) or {"epochs": e}
    )
    monkeypatch.setitem(tc.PHASE_BUDGET_S, "3-convergence", 0.3)
    monkeypatch.setattr(
        tc, "megakernel_convergence",
        lambda d, e, variant="megakernel": {"variant": variant, "epochs": e},
    )
    monkeypatch.setattr(
        tc, "profile_one_epoch", lambda d, t: {"dir": str(t), "n_files": 1}
    )
    monkeypatch.setattr(
        tc, "profile_headline_epoch", lambda t: {"dir": str(t), "n_files": 1}
    )
    monkeypatch.setattr(
        bench_tpu_matrix, "run_matrix",
        lambda cells, nb, trials: {("fused", "default", "xla"): 123.0},
    )
    monkeypatch.setattr(
        tc, "executor_backend_cells",
        lambda nb, trials: ({"executor+default+xla": 1.0}, {}, eq),
    )
    monkeypatch.setattr(
        tc, "executor_backend_api_path",
        lambda d, epochs=2: {"hashes_match": True, "losses_match": True},
    )
    monkeypatch.setattr(
        tc, "adam_kernel_cells",
        lambda nb, trials: ({"adam+default+xla": 1.0}, {}, {"epoch": eq}),
    )
    monkeypatch.setattr(
        tc, "adam_epoch_kernel_convergence", lambda d: {"val_accuracy": 0.99}
    )
    out = tmp_path / "CAP.json"
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    monkeypatch.setattr(
        sys, "argv",
        ["tpu_capture.py", "--quick", "--out", str(out), "--data-dir", str(data_dir)],
    )
    try:
        tc.main()
    finally:
        hang.set()  # release the hung worker thread
    # the full-capture rename gate (ADVICE r05, matching tier-0): a
    # budget-skipped phase keeps the artifact a .partial — tunnel_watch.sh
    # must keep watching and retry with --resume instead of exiting on a
    # wedged partial capture
    assert not out.exists()
    partial = Path(str(out) + ".partial")
    assert partial.is_file()
    result = json.loads(partial.read_text())
    assert "completed_at" not in result
    skipped = [e["phase"] for e in result["phases_skipped_by_budget"]]
    assert skipped == ["3-convergence"]
    assert "convergence" not in result
    # every LATER phase still ran
    for key in (
        "megakernel_convergence", "epoch_kernel_convergence", "trace",
        "trace_headline", "matrix", "matrix_full_epoch_fused",
        "executor_kernel_backends", "executor_api_path", "adam_kernel_cells",
    ):
        assert key in result, f"later phase result missing {key!r}"
    # honesty: every phase that ran while the abandoned worker was still
    # alive is flagged as potentially sharing the device with it
    flagged = result["phases_with_concurrent_abandoned_work"]
    assert flagged["3b-mega-convergence"] == ["3-convergence"]
    assert "6b-adam-convergence" in flagged


def test_capture_tier0_incomplete_stays_partial(tmp_path, monkeypatch, capture_mod):
    """A tier-0 whose phases errored must NOT be renamed into place with a
    completed_at marker — the banked-artifact contract means all three
    verdict cells delivered."""
    tc = capture_mod
    import bench

    monkeypatch.setattr(
        bench, "_ensure_responsive_backend",
        lambda *a, **k: ("", {"probes": [{"outcome": "ok", "seconds": 1.0}]}),
    )
    monkeypatch.setattr(bench, "numpy_baseline_sps", lambda n_batches=40: 50.0)
    monkeypatch.setattr(
        bench, "jax_sps_many",
        lambda precisions, trials=2: {"default": 200.0, "highest": 100.0},
    )
    monkeypatch.setattr(
        tc, "epoch_kernel_vmem_analysis",
        lambda: {"epoch_kernel_vmem": {"sgd": {"compiled_ok": True}}},
    )

    def boom(*a, **k):
        raise RuntimeError("mosaic compile failed")

    monkeypatch.setattr(tc, "_kernel_variant_cells", boom)
    out = tmp_path / "CAP.json"
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    monkeypatch.setattr(
        sys, "argv",
        ["tpu_capture.py", "--tier0-only", "--out", str(out),
         "--data-dir", str(data_dir)],
    )
    tc.main()
    t0 = tmp_path / "CAP_tier0.json"
    assert not t0.exists()
    partial = json.loads((tmp_path / "CAP_tier0.json.partial").read_text())
    assert "completed_at" not in partial
    assert partial["phase_errors"][0]["phase"] == "t0-kernel-cells"
    assert "mosaic compile failed" in partial["phase_errors"][0]["error"]


def test_phase_runner_late_merge(capture_mod):
    """An abandoned phase that completes after its budget is merged into the
    artifact before the final write, without clobbering later results."""
    tc = capture_mod
    result = {"existing": "kept"}
    runner = tc._PhaseRunner(result, lambda: None)
    release = threading.Event()
    done = threading.Event()

    def slow_phase():
        release.wait(10)
        done.set()
        return {"late_key": 42, "existing": "late-must-not-clobber"}

    tc.PHASE_BUDGET_S["unit-test-phase"] = 0.1
    try:
        ok = runner.run("unit-test-phase", slow_phase)
    finally:
        release.set()
    assert ok is False
    assert result["phases_skipped_by_budget"][0]["phase"] == "unit-test-phase"
    assert done.wait(10)
    time.sleep(0.3)  # let the worker finish the box assignment after fn returns
    runner.merge_late()
    assert result["late_key"] == 42
    assert result["existing"] == "kept"  # setdefault semantics: no clobber
    assert result["phases_late_completed"] == ["unit-test-phase"]
    tc.PHASE_BUDGET_S.pop("unit-test-phase", None)


def test_phase_runner_done_detection_requires_delivery(capture_mod):
    """Resume done-detection (ADVICE r05): a phase counts as captured only
    when its primary key is NON-EMPTY and no matching ``*_unresolved`` key
    exists; a clean re-run clears the stale unresolved marker, a still-
    unresolved re-run keeps its fresh one."""
    tc = capture_mod
    assert set(tc.PHASE_UNRESOLVED_KEYS) <= set(tc.PHASE_DONE_KEYS)
    calls = []

    def phase():
        calls.append(1)
        return {"adam_kernel_cells": {"adam+default+xla": 1.0}}

    # empty primary key (the phase ran but delivered nothing) -> re-run
    result = {"adam_kernel_cells": {}}
    runner = tc._PhaseRunner(result, lambda: None)
    assert runner.run("6-adam-cells", phase) is True
    assert calls == [1]
    assert result["adam_kernel_cells"] == {"adam+default+xla": 1.0}

    # unresolved marker present -> re-run; the clean re-run clears it
    calls.clear()
    result = {
        "adam_kernel_cells": {"adam+default+xla": 9.0},
        "adam_kernel_cells_unresolved": {"adam+default+mega": "timeout"},
    }
    runner = tc._PhaseRunner(result, lambda: None)
    assert runner.run("6-adam-cells", phase) is True
    assert calls == [1]
    assert "adam_kernel_cells_unresolved" not in result
    assert result["adam_kernel_cells"] == {"adam+default+xla": 1.0}

    # delivered + no unresolved marker -> skipped, not re-measured
    calls.clear()
    runner2 = tc._PhaseRunner(dict(result), lambda: None)
    assert runner2.run("6-adam-cells", phase) is True
    assert calls == []

    # a re-run that is STILL partially unresolved keeps its FRESH marker
    calls.clear()

    def phase_unresolved():
        calls.append(1)
        return {
            "adam_kernel_cells": {"adam+default+xla": 2.0},
            "adam_kernel_cells_unresolved": {"adam+default+epoch": "x"},
        }

    result = {
        "adam_kernel_cells": {"adam+default+xla": 9.0},
        "adam_kernel_cells_unresolved": {"old": "marker"},
    }
    runner3 = tc._PhaseRunner(result, lambda: None)
    assert runner3.run("6-adam-cells", phase_unresolved) is True
    assert calls == [1]
    assert result["adam_kernel_cells_unresolved"] == {"adam+default+epoch": "x"}


def test_capture_complete_gates_on_skips_and_unresolved(capture_mod):
    """The rename-into-place eligibility: budget skips and *_unresolved
    cell markers (both retryable via --resume) block the rename;
    deterministic phase errors do not."""
    tc = capture_mod
    assert tc.capture_complete({"matrix": {"a": 1.0}}) is True
    assert tc.capture_complete(
        {"phases_skipped_by_budget": [{"phase": "5-matrix"}]}
    ) is False
    assert tc.capture_complete(
        {"adam_kernel_cells": {}, "adam_kernel_cells_unresolved": {"c": "t"}}
    ) is False
    # errors alone do NOT gate: retrying them fails identically, and a
    # banked artifact with recorded errors beats an endless watch loop
    assert tc.capture_complete(
        {"phase_errors": [{"phase": "6b-adam-convergence", "error": "x"}]}
    ) is True


def test_capture_aborts_cleanly_on_wedged_tunnel(tmp_path, monkeypatch, capture_mod):
    """A wedged probe must exit 3 BEFORE touching the device or writing
    anything — the claim stays free for a retry."""
    tc = capture_mod
    import bench

    monkeypatch.setattr(
        bench, "_ensure_responsive_backend",
        lambda *a, **k: ("_CPU_FALLBACK_TUNNEL_UNRESPONSIVE",
                         {"probes": [{"outcome": "timeout", "seconds": 150.0}]}),
    )
    out = tmp_path / "CAP.json"
    monkeypatch.setattr(sys, "argv", ["tpu_capture.py", "--out", str(out)])
    with pytest.raises(SystemExit) as exc:
        tc.main()
    assert exc.value.code == 3
    assert not out.exists() and not Path(str(out) + ".partial").exists()


def test_epoch_kernel_vmem_analysis_real_body(capture_mod):
    """The REAL vmem-calibration body (tiny shapes, so CPU-fast) — every
    other capture test stubs this phase, and a capture phase covered only
    by stubs is exactly the signature-break class that burns chip windows."""
    tc = capture_mod
    out = tc.epoch_kernel_vmem_analysis(sizes=(20, 16, 10), B=8, M=2)
    rec = out["epoch_kernel_vmem"]
    for name in ("sgd", "adam"):
        assert rec[name]["compiled_ok"] is True
        assert rec[name]["fits_predicate"] is True
        assert rec[name]["predicted_kernel_bytes"] > 0
        # memory fields come through the SHARED program_audit.memory_stats
        # helper now — same field set as before plus the peak estimate
        assert rec[name]["peak_hbm_bytes"] > 0
    assert rec["adam"]["predicted_kernel_bytes"] > rec["sgd"]["predicted_kernel_bytes"]
    assert rec["budget_bytes"] > 0


def test_capture_resume_skips_captured_phases(tmp_path, monkeypatch, capture_mod):
    """--resume folds a previous run's .partial into the new run: phases
    whose primary keys are already captured are NOT re-measured, retried
    phases get fresh bookkeeping, and the prior run's flags move aside
    under prior_run."""
    tc = capture_mod
    import bench
    import bench_tpu_matrix

    eq = {"max_abs_param_diff": 0.0, "loss_abs_diff": 0.0, "bitwise_equal": True}
    out = tmp_path / "CAP.json"
    data_dir = tmp_path / "data"
    data_dir.mkdir()
    # a previous run measured the headline sweeps + kernel cells, then was
    # killed: phase 3 was skipped-by-budget; the trace completed LATE
    # (after its budget) so its result must be re-measured, not trusted
    (tmp_path / "CAP.json.partial").write_text(json.dumps({
        "info": {"platform": "tpu"},
        "capture_config": {"quick": True, "data_dir": str(data_dir)},
        "numpy_baseline_sps": 77.0,
        "headline_sweep_default_precision": {"unroll=8": 800.0},
        "headline_best_sps": 800.0,
        "vs_baseline": 10.39,
        "headline_sweep_fp32_highest": {"unroll=8": 400.0},
        "megakernel_cells": {"fused+default+epoch": 9.0},
        "megakernel_onchip_equality": {"epoch": eq},
        "trace": {"n_files": 99},
        "phases_skipped_by_budget": [{"phase": "3-convergence", "budget_s": 1500}],
        "phases_late_completed": ["4-trace"],
    }))

    calls = []
    monkeypatch.setattr(
        bench, "_ensure_responsive_backend",
        lambda *a, **k: ("", {"probes": [{"outcome": "ok", "seconds": 1.0}]}),
    )
    monkeypatch.setattr(
        bench, "numpy_baseline_sps",
        lambda n_batches=40: calls.append("baseline") or 50.0,
    )
    monkeypatch.setattr(
        bench, "jax_sps_many",
        lambda precisions, trials=2: {"default": 200.0, "highest": 100.0},
    )
    monkeypatch.setattr(
        tc, "_kernel_variant_cells",
        lambda *a, **k: ({"fused+default+epoch": 3.0}, {}, {"epoch": eq}),
    )
    monkeypatch.setattr(
        tc, "epoch_kernel_vmem_analysis",
        lambda: {"epoch_kernel_vmem": {"sgd": {"compiled_ok": True}}},
    )
    monkeypatch.setattr(
        tc, "headline_sweep",
        lambda *a, **k: calls.append("headline_sweep") or ({"unroll=1": 1.0}, {}),
    )
    monkeypatch.setattr(
        tc, "megakernel_cells",
        lambda nb, trials: calls.append("megakernel_cells") or ({}, {}, {}),
    )
    monkeypatch.setattr(
        tc, "convergence_run",
        lambda d, e: calls.append("convergence") or {"epochs": e},
    )
    monkeypatch.setattr(
        tc, "megakernel_convergence",
        lambda d, e, variant="megakernel": {"variant": variant},
    )
    monkeypatch.setattr(tc, "profile_one_epoch", lambda d, t: {"n_files": 1})
    monkeypatch.setattr(tc, "profile_headline_epoch", lambda t: {"n_files": 1})
    monkeypatch.setattr(
        bench_tpu_matrix, "run_matrix",
        lambda cells, nb, trials: {("fused", "default", "xla"): 123.0},
    )
    monkeypatch.setattr(
        tc, "executor_backend_cells", lambda nb, trials: ({}, {}, eq)
    )
    monkeypatch.setattr(
        tc, "executor_backend_api_path", lambda d, epochs=2: {"hashes_match": True}
    )
    monkeypatch.setattr(tc, "adam_kernel_cells", lambda nb, trials: ({}, {}, {}))
    monkeypatch.setattr(
        tc, "adam_epoch_kernel_convergence", lambda d: {"val_accuracy": 0.99}
    )
    monkeypatch.setattr(
        sys, "argv",
        ["tpu_capture.py", "--quick", "--resume", "--out", str(out),
         "--data-dir", str(data_dir)],
    )
    tc.main()

    result = json.loads(out.read_text())
    # captured phases were NOT re-measured in the full capture (tier-0 has
    # its own file and DID run its pair fresh; the baseline is also shared
    # into tier-0, so it ran at most once there, never for phase 1)
    assert "headline_sweep" not in calls
    assert "megakernel_cells" not in calls
    # the previously-skipped phase WAS retried this run
    assert "convergence" in calls
    assert result["convergence"] == {"epochs": 5}
    # prior values survive, prior bookkeeping moved aside, fresh run clean
    assert result["headline_best_sps"] == 800.0
    assert result["numpy_baseline_sps"] == 77.0
    assert result["prior_run"]["phases_skipped_by_budget"][0]["phase"] == "3-convergence"
    assert not result.get("phases_skipped_by_budget")
    assert "completed_at" in result
    # the prior run's device info is preserved, not discarded
    assert result["prior_run"]["info"] == {"platform": "tpu"}
    # the LATE-completed trace was invalidated and re-measured fresh
    assert result["trace"] == {"n_files": 1}

    # second --resume, now against the BANKED artifact: run 1 renamed the
    # .partial into CAP.json, so resume must load the final artifact too
    # (ADVICE r05 — previously only <out>.partial was consulted and a banked
    # capture was re-measured from scratch and overwritten)
    assert out.is_file() and not (tmp_path / "CAP.json.partial").is_file()
    calls.clear()
    tc.main()
    result2 = json.loads(out.read_text())
    # every phase captured in run 1 was loaded from the banked artifact,
    # not re-measured (convergence ran in run 1; it must not run again)
    assert "convergence" not in calls
    assert "headline_sweep" not in calls
    assert result2["convergence"] == {"epochs": 5}
    assert result2["headline_best_sps"] == 800.0


def test_resume_ignores_corrupt_and_mismatched_artifacts(tmp_path, capture_mod):
    """A truncated .partial (killed mid-checkpoint) or one captured under a
    different config must be skipped with a note, never crash or silently
    merge quick-config cells into a full-config artifact."""
    tc = capture_mod
    sig = {"quick": False, "data_dir": "/d"}
    # corrupt file: skipped, next path tried
    corrupt = tmp_path / "a.partial"
    corrupt.write_text('{"numpy_baseline_sps": 5')  # truncated
    good = tmp_path / "b.json"
    good.write_text(json.dumps({"capture_config": sig, "matrix": {"x": 1.0}}))
    result = {}
    tc._load_resume_state(result, (corrupt, good), sig)
    assert result["matrix"] == {"x": 1.0}
    assert str(corrupt) in result["resume_unreadable_artifacts"]
    # config mismatch: artifact ignored entirely, mismatch recorded
    result2 = {}
    other = tmp_path / "c.json"
    other.write_text(json.dumps(
        {"capture_config": {"quick": True, "data_dir": "/d"}, "matrix": {"y": 2.0}}
    ))
    tc._load_resume_state(result2, (other,), sig)
    assert "matrix" not in result2
    assert result2["resume_ignored_mismatched"][0]["capture_config"]["quick"] is True


def test_finalize_ratios_fills_cross_run_derivations(capture_mod):
    """vs_baseline must be computable when the baseline and the sweep came
    from DIFFERENT runs (resume), and never overwrite an existing value."""
    tc = capture_mod
    r = {"numpy_baseline_sps": 100.0, "headline_best_sps": 500.0,
         "headline_best_fp32_sps": 300.0}
    tc._finalize_ratios(r)
    assert r["vs_baseline"] == 5.0 and r["vs_baseline_fp32"] == 3.0
    r2 = {"numpy_baseline_sps": 100.0, "headline_pair": {"default": 250.0},
          "vs_baseline_fp32": 9.9}
    tc._finalize_ratios(r2)
    assert r2["vs_baseline"] == 2.5
    assert r2["vs_baseline_fp32"] == 9.9  # untouched


def test_phase_runner_suspect_budget_after_consecutive_skips(capture_mod, monkeypatch):
    """After two consecutive budget skips the tunnel is presumed wedged:
    later phases still run (each must be ATTEMPTED) but at the short
    suspect budget, and the first success restores normal budgets."""
    tc = capture_mod
    result = {}
    runner = tc._PhaseRunner(result, lambda: None)
    release = threading.Event()
    budgets_seen = []

    real_join = threading.Thread.join

    def spy_join(self, timeout=None):
        if self.name.startswith("phase-"):
            budgets_seen.append(timeout)
        return real_join(self, timeout)

    monkeypatch.setattr(threading.Thread, "join", spy_join)
    for label in ("hang-a", "hang-b", "after-wedge"):
        monkeypatch.setitem(tc.PHASE_BUDGET_S, label, 500)
    monkeypatch.setitem(tc.PHASE_BUDGET_S, "hang-a", 0.1)
    monkeypatch.setitem(tc.PHASE_BUDGET_S, "hang-b", 0.1)
    try:
        assert runner.run("hang-a", lambda: release.wait(30)) is False
        assert runner.run("hang-b", lambda: release.wait(30)) is False
        # third phase: budget clamped to SUSPECT_BUDGET_S, still attempted
        assert runner.run("after-wedge", lambda: {"ok": 1}) is True
        assert budgets_seen[-1] == tc.SUSPECT_BUDGET_S
        # success resets the wedge counter: full budget again
        monkeypatch.setitem(tc.PHASE_BUDGET_S, "recovered", 777)
        assert runner.run("recovered", lambda: {"ok2": 2}) is True
        assert budgets_seen[-1] == 777
    finally:
        release.set()
    assert [e["phase"] for e in result["phases_skipped_by_budget"]] == [
        "hang-a", "hang-b"
    ]
    assert result["ok"] == 1 and result["ok2"] == 2
