"""Cross-framework oracle: our hand-written-VJP training vs torch autograd.

The strongest equivalence evidence in the suite: an independent engine
(PyTorch autograd — no shared code with our backward pass) training the same
model from the same init on the same data must land on the same weights.
Plays the role of the reference's scripts/DDP_PyTorch_MNIST.py divergence
experiment, as a fast unit test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import trainer
from shallowspeed_tpu.init import linear_init
from shallowspeed_tpu.optimizer import SGD

SIZES = (20, 16, 12, 10)
B, M, LR = 32, 4, 0.01


def torch_train(X, Y, n_batches):
    params = []
    for i in range(len(SIZES) - 1):
        w, b = linear_init(SIZES[i], SIZES[i + 1])
        params.append(
            (torch.tensor(w, requires_grad=True), torch.tensor(b, requires_grad=True))
        )

    def forward(x):
        for i, (w, b) in enumerate(params):
            x = x @ w.T + b
            if i < len(params) - 1:
                x = torch.relu(x)
        ze = torch.exp(x - x.max())
        return ze / (ze.sum(dim=1, keepdim=True) + 1e-7)

    for bi in range(n_batches):
        for w, b in params:
            if w.grad is not None:
                w.grad.zero_()
                b.grad.zero_()
        for mb in range(M):
            x = torch.tensor(X[bi, mb])
            t = torch.tensor(Y[bi, mb])
            (((t - forward(x)) ** 2).sum() / B).backward()
        with torch.no_grad():
            for w, b in params:
                w -= LR * w.grad
                b -= LR * b.grad
    return [(w.detach().numpy(), b.detach().numpy()) for w, b in params]


def test_trajectory_matches_torch_autograd():
    rng = np.random.RandomState(0)
    NB = 5
    X = rng.randn(NB, M, B // M, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[
        rng.randint(0, SIZES[-1], (NB, M, B // M))
    ]

    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    step = trainer.make_train_step(spec, SGD(LR))
    st = ()
    for bi in range(NB):
        params, st = step(params, st, jnp.asarray(X[bi]), jnp.asarray(Y[bi]))

    want = torch_train(X, Y, NB)
    got = [l for s in params for l in s]
    for (tw, tb), jl in zip(want, got):
        np.testing.assert_allclose(np.asarray(jl["W"]), tw, rtol=2e-4, atol=2e-6)
        np.testing.assert_allclose(
            np.asarray(jl["b"]).reshape(1, -1), tb, rtol=2e-4, atol=2e-6
        )
