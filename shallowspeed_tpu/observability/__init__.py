"""Training telemetry: structured metrics, profiling spans, trace analysis.

The framework's north star is "as fast as the hardware allows" — which is
unclaimable without instrumentation. This package is the single home for
everything that *observes* a run, so every perf PR can ship a recomputable
evidence trail instead of prose:

- ``metrics``      the recording surface: ``MetricsRecorder`` (in-memory
                   counters / gauges / timers / per-step histograms),
                   ``JsonlMetrics`` (the versioned JSONL sink) and
                   ``NullMetrics`` (the zero-overhead default — recording
                   disabled costs nothing on the hot path);
- ``spans``        profiling spans: wall-clock + ``jax.profiler``
                   TraceAnnotation context managers (so host-side phases —
                   schedule lowering, jit compile, device put, epoch
                   execution — are labeled inside profiler captures AND
                   timed into the metrics stream), plus ``capture`` wrapping
                   ``jax.profiler.trace``;
- ``trace_stats``  the chrome-trace analyzer behind docs/performance.md's
                   roofline numbers (promoted from scripts/ to an importable,
                   tested module; the script remains as a thin shim).

Wiring: ``TrainingSession(metrics=JsonlMetrics(path))`` records per-epoch
training telemetry (loss, samples/s, grad-norm when clipping), compile-time
spans, and — on mesh layouts — the lowered pipeline program's static tick
stats (ticks, sends, stage occupancy, bubble fraction). The CLI flag is
``train.py --metrics-out FILE``. See docs/observability.md.
"""

from shallowspeed_tpu.observability.metrics import (
    SCHEMA_VERSION,
    JsonlMetrics,
    MetricsRecorder,
    NullMetrics,
    read_jsonl,
)
from shallowspeed_tpu.observability.spans import Span, capture, span

__all__ = [
    "SCHEMA_VERSION",
    "JsonlMetrics",
    "MetricsRecorder",
    "NullMetrics",
    "Span",
    "capture",
    "read_jsonl",
    "span",
]
