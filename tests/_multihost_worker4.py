"""Worker for the 4-process multihost test (spawned by test_multihost.py).

Four of these form a 4-device global runtime (ONE emulated CPU device per
process) and build a 2x2 mesh where EVERY mesh axis spans process
boundaries — the layout nothing in the 2-process test exercises:

  - the dp axis crosses processes {0,2} and {1,3}: the per-batch gradient
    psum is a true cross-process collective;
  - the pp axis crosses processes {0,1} and {2,3}: every tick's ppermute
    relay crosses a process boundary;
  - each process addresses exactly ONE device, so the LOCAL replica-sync
    assert can see nothing — only the cross-process check
    (utils.assert_dp_replicas_in_sync_global) actually compares replicas.

Phases: two momentum-SGD pipeline steps (state carried) with the global
sync assert after each; then a NEGATIVE control — a deliberately
process-divergent replicated array must make the global checker raise on
every process (a checker that can't detect desync proves nothing).

Prints one JSON line {"pid", "sync_ok", "desync_detected", "loss",
"loss2"}; any failure exits non-zero and fails the parent test.
"""

import json
import os
import sys


def main():
    pid, port = int(sys.argv[1]), int(sys.argv[2])
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "host_platform_device_count" not in f
    ]
    os.environ["XLA_FLAGS"] = " ".join(
        flags + ["--xla_force_host_platform_device_count=1"]
    )

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import jax

    jax.config.update("jax_platforms", "cpu")

    from shallowspeed_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=f"localhost:{port}", num_processes=4, process_id=pid
    )

    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu import utils
    from shallowspeed_tpu.optimizer import MomentumSGD
    from shallowspeed_tpu.parallel import executor as E
    from shallowspeed_tpu.parallel import lower_schedule, make_mesh

    assert jax.process_count() == 4, jax.process_count()
    assert len(jax.local_devices()) == 1
    devs = sorted(jax.devices(), key=lambda d: (d.process_index, d.id))
    assert len(devs) == 4
    # rows = dp, cols = pp: dp row 0 is processes {0,1}, row 1 is {2,3};
    # the dp collective pairs {0,2}/{1,3} and the pp relay pairs {0,1}/{2,3}
    # — every axis crosses processes
    mesh = make_mesh(2, 2, devices=devs)

    SIZES, B, M = (12, 10, 9, 8), 16, 2
    spec = Mo.make_model_spec(SIZES, 2, B)
    prog = lower_schedule(S.GPipeSchedule, M, 2)

    def put_global(x, pspec):
        sh = NamedSharding(mesh, pspec)
        return jax.make_array_from_callback(x.shape, sh, lambda idx: x[idx])

    st_np, fl_np = E.stack_params(Mo.init_model(spec), spec)
    stacked = jax.tree.map(lambda x: put_global(x, P("pp")), st_np)
    fl = jax.tree.map(lambda x: put_global(x, P("pp")), fl_np)

    rng = np.random.RandomState(0)
    X = rng.randn(B, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], B)]
    half = B // 2
    row = pid // 2  # this process's dp row
    xg = multihost.shard_batch_for_process(
        X[row * half : (row + 1) * half], mesh, P("dp")
    )
    yg = multihost.shard_batch_for_process(
        Y[row * half : (row + 1) * half], mesh, P("dp")
    )

    opt = MomentumSGD(0.05, 0.9)
    ost = opt.init({"W": stacked["W"], "b": stacked["b"]})
    step = E.make_pipeline_step(mesh, spec, prog, half // M, opt)

    # sync_ok is WIRED, not asserted-by-construction: a desync makes this
    # worker print sync_ok=false and exit non-zero (both visible upstream)
    sync_ok = True
    try:
        stacked, ost, loss = step(stacked, fl, ost, xg, yg)
        utils.assert_dp_replicas_in_sync_global(stacked)
        stacked, ost, loss2 = step(stacked, fl, ost, xg, yg)
        utils.assert_dp_replicas_in_sync_global(stacked)
        utils.assert_dp_replicas_in_sync_global(ost)  # momentum state too
    except ValueError as e:
        print(json.dumps({"pid": pid, "sync_ok": False, "error": str(e)}))
        sys.exit(1)

    # negative control: a "replicated" array whose process-3 copy diverges
    # MUST be caught (every device holds the full array = same shard index)
    bad_local = np.full((2, 3), 1.0 + (0.5 if pid == 3 else 0.0), np.float32)
    bad = multihost.shard_batch_for_process(bad_local, mesh, P())
    desync_detected = False
    try:
        utils.assert_dp_replicas_in_sync_global(bad)
    except ValueError:
        desync_detected = True

    print(
        json.dumps(
            {
                "pid": pid,
                "sync_ok": sync_ok,
                "desync_detected": desync_detected,
                "loss": float(loss),
                "loss2": float(loss2),
            }
        )
    )


if __name__ == "__main__":
    main()
