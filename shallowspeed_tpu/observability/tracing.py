"""Distributed request tracing: cross-process span chains + attribution.

A serving request crosses up to three processes — router → fleet queue →
worker process → engine queue → pack → rung dispatch → health verify →
ack — and before this module each left timestamps in its own JSONL shard
with its own ``time.perf_counter()`` origin and no causal linkage, so
"where did the p99 go" was unanswerable from the shards we already
write. This module is the request-level layer on top of the schema-v10
``trace`` record kind (docs/observability.md § Tracing):

- ``Tracer``             the emitter: one CLOSED span per record (a span
                         is emitted once, at its end, with both
                         endpoints — a killed process simply leaves the
                         spans it finished, never a half-open record),
                         with process-unique span ids and parent/child
                         linkage that survives the worker pipe (the
                         parent ships ``{"trace_id", "parent"}``
                         alongside the request; the worker ships its
                         last span id back with the response);
- ``clock_offsets``      the cross-process clock alignment: the fleet's
                         heartbeat handshake round-trips
                         ``clock_probe`` messages per worker and records
                         the classic NTP-style estimate — for a probe
                         sent at parent time ``t0``, answered at worker
                         time ``tw`` and received at parent time ``t1``,
                         ``offset = tw - (t0 + t1)/2`` with uncertainty
                         ``(t1 - t0)/2`` (the true offset lies inside
                         ``offset ± uncertainty`` whenever the two legs'
                         asymmetry is bounded by the round trip, which
                         one process on one host guarantees). The best
                         (lowest-uncertainty) estimate per replica wins;
- ``assemble_chains``    the reader: joins parent + ``.r{replica_id}``
                         shards into per-request chains keyed by
                         ``trace_id``, mapping every worker-clock
                         timestamp onto the parent timeline
                         (``parent_t = worker_t - offset``). A chain for
                         a TERMINAL request must be complete — every
                         span's parent present, a terminal span present
                         — and ``verify_terminal_chains(strict=True)``
                         REFUSES orphan/unclosed chains instead of
                         rendering half a story;
- ``attribution``        the scoreboard: per-phase latency attribution,
                         both mean and P99-CONDITIONAL (which phase
                         dominates the slowest 1% — the
                         makespan-quantization scoreboard the MPMD
                         per-stage runtime will be judged against), SLO
                         burn per phase, and per-request ``waterfall``
                         text for the worst-k requests.

Span taxonomy (all typed — the reader charges inter-span gaps by type):

    fleet.queue       fleet admission → first placement (parent clock)
    route             placement decision + pipe send; the forward pipe
                      hop lands in the gap charged to this phase
    worker.queue      engine admission → dispatch pop (worker clock)
    pack              slot packing + padding of the dispatch batch
    dispatch          the rung-program dispatch (predict call wall)
    verify            finiteness gate + optional bitwise parity check
    failover.requeue  a dead replica's un-acked request re-entering the
                      fleet queue head — links the dead replica's
                      partial chain to the surviving replica's spans
    ack               the terminal span (one per request): response
                      receipt + completion; the return pipe hop lands in
                      the gap charged to this phase

Clock-domain contract: every parent-side span and every request-record
timestamp is a PARENT-process ``perf_counter`` value; worker spans carry
``clock: "worker"`` raw values that only the recorded per-replica offset
can place on the parent timeline. A chain whose worker spans have no
offset record is flagged ``alignment: "missing"`` (rendered as degraded,
with the uncertainty shown when one exists) rather than silently joined
on incomparable clocks.
"""

import math
from collections import defaultdict

from shallowspeed_tpu.observability.stats import percentile

# the typed span alphabet (module docstring); "clock_offset" records ride
# the same kind but are alignment metadata, not spans. The two
# ``stage.*`` names are the MPMD runtime's training-side spans
# (parallel/mpmd.py): ``stage.dispatch`` is one stage program's host
# issue window (fields: stage/op/mb), ``stage.relay`` one
# device-to-device activation transfer (fields: stage/to_stage/
# direction/mb) — emitted for the first batch of each epoch dispatch so
# the Tracing attribution can show where MPMD wall goes vs lockstep
# without flooding the stream.
SPAN_NAMES = (
    "fleet.queue",
    "route",
    "worker.queue",
    "pack",
    "dispatch",
    "verify",
    "failover.requeue",
    "ack",
    "stage.dispatch",
    "stage.relay",
)

# gap charging: the idle time between two consecutive spans belongs to
# the phase that was "in flight" across it — the forward pipe hop before
# worker.queue is routing, the return hop before ack is acking, a
# re-queued wait before a later route is fleet queueing, the
# death-detection wait before a failover span is the failover's
GAP_CHARGE = {
    "worker.queue": "route",
    "ack": "ack",
    "route": "fleet.queue",
    "failover.requeue": "failover.requeue",
}


class TraceError(ValueError):
    """A terminal request's span chain is incomplete: orphan spans,
    no terminal span, or no chain at all for a traced request."""


class Tracer:
    """Span emitter bound to one metrics recorder and one process.

    ``process`` prefixes every span id (``"f"`` for the fleet parent,
    ``"e"`` for a standalone engine, ``"r{replica_id}"`` for a worker) so
    ids never collide across the processes whose shards one reader
    merges. ``clock_domain`` stamps which perf_counter origin the span
    endpoints live in; ``terminal_ack=False`` suppresses the terminal
    ``ack`` span (a fleet WORKER's completions are worker-terminal, not
    request-terminal — the parent owns the one ack per request).

    Disabled recorders cost one attribute check per call site:
    ``enabled`` mirrors the recorder's, ``new_trace`` is never called on
    the disabled path, and ``span`` returns ``None`` without emitting.
    """

    __slots__ = ("_metrics", "process", "replica_id", "clock_domain",
                 "terminal_ack", "enabled", "_n")

    def __init__(self, metrics, process="e", replica_id=None,
                 clock_domain="parent", terminal_ack=True):
        self._metrics = metrics
        self.process = str(process)
        self.replica_id = replica_id
        self.clock_domain = clock_domain
        self.terminal_ack = bool(terminal_ack)
        self.enabled = bool(getattr(metrics, "enabled", False))
        self._n = 0

    def new_trace(self, req_id):
        """The request's trace id, minted ONCE by the admitting process
        and shipped (never re-minted) across every hop after that."""
        return f"{self.process}-{int(req_id)}"

    def span(self, name, trace_id, t0, t1, parent=None, terminal=False,
             **fields):
        """Emit one closed span; returns its span id (``None`` when
        tracing is disabled or the request carries no trace id)."""
        if not self.enabled or trace_id is None:
            return None
        self._n += 1
        span_id = f"{self.process}.{self._n}"
        self._metrics.trace(
            name,
            trace_id=trace_id,
            span_id=span_id,
            parent_id=parent,
            t0=t0,
            t1=t1,
            clock=self.clock_domain,
            replica_id=self.replica_id,
            terminal=bool(terminal),
            **fields,
        )
        return span_id

    def clock_offset(self, replica_id, offset_s, rtt_s, uncertainty_s):
        """Record one per-replica clock-alignment estimate (module
        docstring). Callers emit only IMPROVED estimates, so the reader's
        last-record-wins fold always holds the best one."""
        if not self.enabled:
            return
        self._metrics.trace(
            "clock_offset",
            trace_id=None,
            span_id=None,
            parent_id=None,
            t0=None,
            t1=None,
            clock="parent",
            replica_id=replica_id,
            terminal=False,
            offset_s=offset_s,
            rtt_s=rtt_s,
            uncertainty_s=uncertainty_s,
        )


# ---------------------------------------------------------------------------
# the reader: shards -> aligned chains
# ---------------------------------------------------------------------------


def clock_offsets(records):
    """Per-replica clock alignment from the ``clock_offset`` trace
    records: ``{replica_id: {"offset_s", "rtt_s", "uncertainty_s"}}``.
    Last record wins — the emitter records improvements only, so last IS
    best."""
    out = {}
    for r in records:
        if r.get("kind") == "trace" and r.get("name") == "clock_offset":
            out[r.get("replica_id")] = {
                "offset_s": r.get("offset_s"),
                "rtt_s": r.get("rtt_s"),
                "uncertainty_s": r.get("uncertainty_s"),
            }
    return out


class Chain:
    """One request's span chain, clock-aligned onto the parent timeline.

    ``spans``: dicts with the raw record fields plus ``t0_aligned``/
    ``t1_aligned`` (parent-timeline endpoints; identity for parent-clock
    spans, ``t - offset`` for worker-clock spans). ``alignment``:
    ``"parent"`` (no cross-clock spans), ``"aligned"`` (worker spans
    mapped via a recorded offset), or ``"missing"`` (worker spans with NO
    offset record — their raw values are kept un-mapped and the chain is
    flagged, never silently joined)."""

    __slots__ = ("trace_id", "spans", "alignment", "uncertainty_s")

    def __init__(self, trace_id):
        self.trace_id = trace_id
        self.spans = []
        self.alignment = "parent"
        self.uncertainty_s = 0.0

    @property
    def terminal_span(self):
        for s in reversed(self.spans):
            if s.get("terminal"):
                return s
        return None

    @property
    def verdict(self):
        t = self.terminal_span
        return t.get("verdict") if t else None

    @property
    def t0(self):
        ts = [s["t0_aligned"] for s in self.spans if s["t0_aligned"] is not None]
        return min(ts) if ts else None

    @property
    def t_end(self):
        t = self.terminal_span
        if t is not None and t["t1_aligned"] is not None:
            return t["t1_aligned"]
        ts = [s["t1_aligned"] for s in self.spans if s["t1_aligned"] is not None]
        return max(ts) if ts else None

    @property
    def latency_s(self):
        if self.t0 is None or self.t_end is None:
            return None
        return self.t_end - self.t0

    @property
    def replicas(self):
        return sorted(
            {s["replica_id"] for s in self.spans if s.get("replica_id") is not None}
        )

    def problems(self):
        """Why this chain is NOT a complete request story: orphan spans
        (parent id absent from the chain), unclosed spans (an endpoint
        missing), or no terminal span. Alignment degradation is reported
        separately (``alignment``/``uncertainty_s``) — a mis-estimated
        clock skews durations but does not orphan causality."""
        out = []
        ids = {s["span_id"] for s in self.spans if s.get("span_id")}
        for s in self.spans:
            parent = s.get("parent_id")
            if parent is not None and parent not in ids:
                out.append(
                    f"{self.trace_id}: orphan span {s.get('name')} "
                    f"({s.get('span_id')}) — parent {parent} not in chain"
                )
            if s.get("t0") is None or s.get("t1") is None:
                out.append(
                    f"{self.trace_id}: unclosed span {s.get('name')} "
                    f"({s.get('span_id')})"
                )
        if self.terminal_span is None:
            out.append(f"{self.trace_id}: no terminal span")
        return out


def assemble_chains(records):
    """Join a merged record stream (parent JSONL + ``.r*`` shards — pass
    a glob to ``read_jsonl``) into ``{trace_id: Chain}``, with every
    worker-clock span mapped onto the parent timeline via the recorded
    per-replica offsets."""
    offsets = clock_offsets(records)
    chains = {}
    for r in records:
        if r.get("kind") != "trace" or r.get("name") == "clock_offset":
            continue
        tid = r.get("trace_id")
        if tid is None:
            continue
        chain = chains.get(tid)
        if chain is None:
            chain = chains[tid] = Chain(tid)
        span = dict(r)
        t0, t1 = r.get("t0"), r.get("t1")
        if r.get("clock") == "worker":
            off = offsets.get(r.get("replica_id"))
            if off is not None and off.get("offset_s") is not None:
                shift = off["offset_s"]
                t0 = None if t0 is None else t0 - shift
                t1 = None if t1 is None else t1 - shift
                if chain.alignment == "parent":
                    chain.alignment = "aligned"
                unc = off.get("uncertainty_s")
                if unc is not None:
                    chain.uncertainty_s = max(chain.uncertainty_s, unc)
            else:
                chain.alignment = "missing"
        span["t0_aligned"], span["t1_aligned"] = t0, t1
        chain.spans.append(span)
    for chain in chains.values():
        chain.spans.sort(
            key=lambda s: (
                s["t0_aligned"] if s["t0_aligned"] is not None else math.inf
            )
        )
    return chains


def traced_terminal_requests(records):
    """``{trace_id: verdict}`` from the terminal ``request`` records that
    carry a ``trace_id`` (schema v10 stamps it at admission). One trace
    can hold several request records — a worker-terminal ``error`` the
    fleet re-routed to an ``ok`` elsewhere — and shard concatenation
    order says nothing about causal order, so an ``ok`` wins outright
    (the exactly-one-terminal-verdict contract means a request some
    process served as ``ok`` IS ok); among non-ok records the last one
    read stands. The chain's terminal ``ack`` span stays the
    authoritative per-request fate."""
    out = {}
    for r in records:
        if r.get("kind") == "request" and r.get("trace_id") is not None:
            if out.get(r["trace_id"]) != "ok":
                out[r["trace_id"]] = r.get("name")
    return out


def verify_terminal_chains(records, chains=None, strict=False):
    """The completeness gate: every terminal request with a ``trace_id``
    must have a chain with no orphan/unclosed spans and a terminal span.
    Returns the list of problem strings (empty = every chain complete);
    ``strict=True`` raises ``TraceError`` instead of returning them."""
    if chains is None:
        chains = assemble_chains(records)
    problems = []
    for tid in sorted(traced_terminal_requests(records)):
        chain = chains.get(tid)
        if chain is None:
            problems.append(f"{tid}: terminal request has no span chain")
            continue
        problems.extend(chain.problems())
    if strict and problems:
        raise TraceError(
            f"{len(problems)} incomplete span chain problem(s): "
            + "; ".join(problems[:10])
        )
    return problems


# ---------------------------------------------------------------------------
# attribution: chains -> where the latency went
# ---------------------------------------------------------------------------


def causal_order(chain):
    """The chain's spans in CAUSAL order — a depth-first walk of the
    parent/child links from the roots, siblings by aligned start time.
    Span durations are clock-skew-invariant, but a residual alignment
    error (within the recorded uncertainty) can shuffle the
    CHRONOLOGICAL order across the process boundary — the causal links
    cannot be shuffled, so attribution walks them instead."""
    ids = {s["span_id"]: s for s in chain.spans if s.get("span_id")}
    children = defaultdict(list)
    roots = []
    for s in chain.spans:
        parent = s.get("parent_id")
        if parent is not None and parent in ids:
            children[parent].append(s)
        else:
            roots.append(s)

    def t_key(s):
        return s["t0_aligned"] if s["t0_aligned"] is not None else math.inf

    out = []
    stack = sorted(roots, key=t_key, reverse=True)
    while stack:
        s = stack.pop()
        out.append(s)
        stack.extend(
            sorted(children.get(s.get("span_id"), ()), key=t_key, reverse=True)
        )
    return out


def chain_phases(chain):
    """Per-phase seconds for one chain, on the aligned timeline. Each
    span's own duration is charged to its name; the gap between two
    CAUSALLY consecutive spans is charged by ``GAP_CHARGE`` (the forward
    pipe hop to ``route``, the return hop to ``ack``, re-queue waits to
    ``fleet.queue``, death-detection waits to ``failover.requeue``), so
    the phases sum to the chain's total latency. Residual
    clock-misalignment (within the recorded uncertainty) can make
    aligned spans overlap — negative gaps clamp to zero rather than
    subtracting phantom time, so attribution degrades by at most the
    uncertainty instead of inverting."""
    phases = defaultdict(float)
    prev_end = None
    for s in causal_order(chain):
        t0, t1 = s["t0_aligned"], s["t1_aligned"]
        if t0 is None or t1 is None:
            continue
        if prev_end is not None and t0 > prev_end:
            phases[GAP_CHARGE.get(s["name"], s["name"])] += t0 - prev_end
        phases[s["name"]] += max(0.0, t1 - t0)
        prev_end = t1 if prev_end is None else max(prev_end, t1)
    return dict(phases)


def attribution(chains, slo_ms=None, worst_k=3):
    """Aggregate phase attribution over complete chains:

    - ``phases_mean``: each phase's share of TOTAL latency across all
      chains (time-weighted — a phase that dominates the slow requests
      shows up even if the fast majority never enters it);
    - ``phases_p99``: the same shares CONDITIONED on the slowest 1% of
      chains (latency >= p99) — which phase the tail actually spends its
      time in. This is the makespan-quantization scoreboard: whole-rung
      dispatch shows up here as ``dispatch`` dominating the tail;
    - ``slo_burn``: for chains with an effective deadline (the ack
      span's own ``deadline_ms`` tag, else ``slo_ms``), each phase's
      mean share of the SLO budget — a phase burning >100% alone
      guarantees a violation;
    - ``worst``: the worst-``k`` chains by latency (render with
      ``waterfall``).
    """
    complete = [
        c for c in chains.values()
        if c.latency_s is not None and not c.problems()
    ]
    if not complete:
        return None
    lats = [c.latency_s for c in complete]
    p99 = percentile(lats, 99)
    tail = [c for c in complete if c.latency_s >= p99]
    per_chain = {c.trace_id: chain_phases(c) for c in complete}

    def shares(pool):
        total = sum(c.latency_s for c in pool)
        agg = defaultdict(float)
        for c in pool:
            for name, secs in per_chain[c.trace_id].items():
                agg[name] += secs
        if total <= 0:
            return {}
        return {name: secs / total for name, secs in sorted(agg.items())}

    p99_shares = shares(tail)
    burn = None
    with_slo = []
    for c in complete:
        term = c.terminal_span or {}
        bound = term.get("deadline_ms")
        if bound is None:
            bound = slo_ms
        if bound:
            with_slo.append((c, bound / 1000.0))
    if with_slo:
        agg = defaultdict(float)
        for c, budget in with_slo:
            for name, secs in per_chain[c.trace_id].items():
                agg[name] += secs / budget
        burn = {
            name: total / len(with_slo) for name, total in sorted(agg.items())
        }
    return {
        "chains": len(complete),
        "p99_latency_s": p99,
        "p99_chains": len(tail),
        "phases_mean": shares(complete),
        "phases_p99": p99_shares,
        "p99_dominant_phase": (
            max(p99_shares, key=p99_shares.get) if p99_shares else None
        ),
        "slo_burn": burn,
        "slo_chains": len(with_slo),
        "worst": sorted(complete, key=lambda c: -c.latency_s)[:worst_k],
    }


def waterfall(chain, width=40):
    """Text waterfall for one chain: each span as a bar positioned on the
    chain's aligned timeline, with its phase window in milliseconds.
    Worker spans are tagged with their replica; a degraded alignment is
    noted on the header line."""
    t0, total = chain.t0, chain.latency_s
    header = f"{chain.trace_id}  {total * 1e3:.1f} ms  {chain.verdict}"
    if len(chain.replicas) > 1:
        header += "  (replicas " + " -> ".join(f"r{r}" for r in chain.replicas) + ")"
    if chain.alignment == "missing":
        header += "  [ALIGNMENT MISSING: worker clocks unmapped]"
    elif chain.uncertainty_s:
        header += f"  [clock ±{chain.uncertainty_s * 1e3:.2f} ms]"
    lines = [header]
    for s in causal_order(chain):
        a, b = s["t0_aligned"], s["t1_aligned"]
        if a is None or b is None or total is None or total <= 0:
            continue
        lo = max(0, min(width - 1, int((a - t0) / total * width)))
        hi = max(lo + 1, min(width, int(math.ceil((b - t0) / total * width))))
        bar = " " * lo + "█" * (hi - lo) + " " * (width - hi)
        tag = f" r{s['replica_id']}" if s.get("replica_id") is not None else ""
        lines.append(
            f"  {s['name']:<16} |{bar}| "
            f"{(a - t0) * 1e3:8.2f} -> {(b - t0) * 1e3:8.2f} ms{tag}"
        )
    return lines
