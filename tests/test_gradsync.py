"""Bucketed gradient synchronization (parallel/gradsync.py).

The BucketPlan's structural guarantees — greedy byte budget, backward
(output-layer-first) order, every-leaf-exactly-once coverage — plus the
emitters' numerics contract: per-bucket collectives are BITWISE identical
to the anchor collective they replace, on both the plain-DP (psum) and
ZeRO-1 (psum_scatter) paths. The executor-level end-to-end bit-equality
lives in tests/test_fuzz_layouts.py; this file pins the layer itself.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu.parallel import gradsync
from shallowspeed_tpu.parallel.compat import shard_map
from shallowspeed_tpu.parallel.executor import slot_shapes
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

SIZES = (48, 40, 36, 32, 28, 24, 18, 10)


def _spec(pp=1, B=64):
    return Mo.make_model_spec(SIZES, pp, B)


# ---------------------------------------------------------------------------
# BucketPlan structure
# ---------------------------------------------------------------------------


def test_plan_dp_buckets_budget_order_and_coverage():
    """The greedy split honors the byte budget, preserves backward order
    (output layer first, W before b within a slot), and covers every
    stacked gradient leaf exactly once."""
    spec = _spec(pp=2)
    dims = slot_shapes(spec)
    L = len(dims)
    budget = 4096
    plan = gradsync.plan_dp_buckets(spec, 2, budget)
    assert plan.mode == "dp" and plan.bucket_bytes == budget
    assert plan.num_buckets >= 2  # this model does not fit one 4 KiB bucket

    flat_leaves = [l for group in plan.buckets for l in group]
    # coverage: every (kind, slot) exactly once
    assert sorted((l.kind, l.slot) for l in flat_leaves) == sorted(
        [("W", l) for l in range(L)] + [("b", l) for l in range(L)]
    )
    # backward order: slots descend; W precedes b within a slot
    keys = [(-l.slot, 0 if l.kind == "W" else 1) for l in flat_leaves]
    assert keys == sorted(keys)
    # budget: a multi-leaf bucket never exceeds it (an oversized single
    # leaf is allowed its own bucket — the plan never splits a leaf)
    for group, nbytes in zip(plan.buckets, plan.bucket_grad_bytes()):
        assert nbytes == sum(l.nbytes for l in group)
        if len(group) > 1:
            assert nbytes <= budget
    # totals: bucketing moves op granularity, never bytes
    total = sum(l.nbytes for l in flat_leaves)
    assert plan.total_grad_bytes() == total
    V = spec.n_stages // 2
    flat = sum(V * o * i for o, i in dims) + sum(V * o for o, _ in dims)
    assert total == 4 * flat


def test_plan_dp_buckets_edge_budgets():
    spec = _spec(pp=1)
    assert gradsync.plan_dp_buckets(spec, 1, 0) is None
    assert gradsync.plan_dp_buckets(spec, 1, None) is None
    # a 1-byte budget: every leaf its own bucket (never split, never drop)
    plan = gradsync.plan_dp_buckets(spec, 1, 1)
    assert all(len(g) == 1 for g in plan.buckets)
    assert plan.num_buckets == 2 * len(slot_shapes(spec))
    # a huge budget: one bucket holding everything
    plan = gradsync.plan_dp_buckets(spec, 1, 1 << 30)
    assert plan.num_buckets == 1


def test_plan_zero1_buckets_tile_the_chunk():
    """ZeRO-1 buckets are column ranges tiling [0, chunk) exactly; each
    covers dp x width gradient elements within the byte budget."""
    spec = _spec(pp=2)
    dp = 2
    dims = slot_shapes(spec)
    V = spec.n_stages // 2
    flat = sum(V * o * i for o, i in dims) + sum(V * o for o, _ in dims)
    csz = -(-flat // dp)
    budget = 4096
    plan = gradsync.plan_zero1_buckets(spec, dp, 2, budget)
    assert plan.mode == "zero1" and plan.dp == dp
    # ranges tile the chunk: contiguous, in order, no gaps or overlaps
    assert plan.buckets[0][0] == 0 and plan.buckets[-1][1] == csz
    for (a0, b0), (a1, b1) in zip(plan.buckets, plan.buckets[1:]):
        assert b0 == a1 and a0 < b0
    # budget bounds the synced gradient payload (dp x width x 4B)
    for nbytes in plan.bucket_grad_bytes():
        assert nbytes <= budget
    # census result bytes are the scatter's per-device output (1/dp)
    assert [g // dp for g in plan.bucket_grad_bytes()] == (
        plan.bucket_census_bytes()
    )
    assert plan.total_grad_bytes() == 4 * dp * csz
    assert gradsync.plan_zero1_buckets(spec, dp, 2, 0) is None


def test_plan_describe_is_json_able():
    spec = _spec(pp=1)
    for plan in (
        gradsync.plan_dp_buckets(spec, 1, 4096),
        gradsync.plan_zero1_buckets(spec, 2, 1, 4096),
    ):
        desc = json.loads(json.dumps(plan.describe()))
        assert desc["num_buckets"] == plan.num_buckets
        assert desc["grad_bucket_bytes"] == 4096
        assert sum(desc["bucket_grad_bytes"]) == desc["total_grad_bytes"]


# ---------------------------------------------------------------------------
# emitters: bitwise identity with the anchor collectives
# ---------------------------------------------------------------------------


def _dp_mesh(dp):
    return Mesh(np.array(jax.devices()[:dp]), ("dp",))


def test_psum_bucketed_bitwise_matches_anchor_psum():
    """One flat psum per bucket == the whole-tree anchor psum, bit for
    bit, on every leaf — the elementwise-reduction equivalence the whole
    feature rests on."""
    spec = _spec(pp=1)
    dims = slot_shapes(spec)
    dp = 4
    mesh = _dp_mesh(dp)
    rng = np.random.RandomState(0)
    gW = tuple(
        jnp.asarray(rng.randn(dp, 1, o, i).astype(np.float32)) for o, i in dims
    )
    gb = tuple(
        jnp.asarray(rng.randn(dp, 1, o).astype(np.float32)) for o, _ in dims
    )

    for budget in (1, 2048, 1 << 30):
        plan = gradsync.plan_dp_buckets(spec, 1, budget)

        def anchor(*leaves):
            nW = len(dims)
            tree = {
                "W": tuple(l[0] for l in leaves[:nW]),
                "b": tuple(l[0] for l in leaves[nW:]),
            }
            out = lax.psum(tree, "dp")
            return tuple(x[None] for x in out["W"] + out["b"])

        def bucketed(*leaves):
            nW = len(dims)
            tree = {
                "W": tuple(l[0] for l in leaves[:nW]),
                "b": tuple(l[0] for l in leaves[nW:]),
            }
            out = gradsync.psum_bucketed(tree, plan)
            return tuple(x[None] for x in out["W"] + out["b"])

        args = gW + gb
        specs = tuple(P("dp") for _ in args)
        run_a = jax.jit(
            shard_map(
                anchor, mesh=mesh, in_specs=specs, out_specs=specs,
                check_vma=False,
            )
        )
        run_b = jax.jit(
            shard_map(
                bucketed, mesh=mesh, in_specs=specs, out_specs=specs,
                check_vma=False,
            )
        )
        for a, b in zip(run_a(*args), run_b(*args)):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=f"budget={budget}"
            )


def test_psum_scatter_bucketed_bitwise_matches_anchor_scatter():
    """Per-bucket column scatters of the (dp, chunk) view reproduce the
    anchor's tiled flat scatter exactly — same elements, same order."""
    dp = 4
    mesh = _dp_mesh(dp)
    csz = 301
    rng = np.random.RandomState(1)
    g = jnp.asarray(rng.randn(dp, dp * csz).astype(np.float32))

    def anchor(x):
        return lax.psum_scatter(
            x[0], "dp", scatter_dimension=0, tiled=True
        )[None]

    for budget in (4 * dp * 1, 4 * dp * 64, 1 << 30):
        # a hand-built flat plan over the chunk (spec-independent)
        width = max(1, budget // (4 * dp))
        plan = gradsync.BucketPlan(
            mode="zero1",
            bucket_bytes=budget,
            buckets=tuple(
                (a, min(a + width, csz)) for a in range(0, csz, width)
            ),
            dp=dp,
        )

        def bucketed(x):
            return gradsync.psum_scatter_bucketed(x[0], plan)[None]

        run_a = jax.jit(
            shard_map(
                anchor, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
                check_vma=False,
            )
        )
        run_b = jax.jit(
            shard_map(
                bucketed, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"),
                check_vma=False,
            )
        )
        np.testing.assert_array_equal(
            np.asarray(run_a(g)), np.asarray(run_b(g)),
            err_msg=f"budget={budget}",
        )


# ---------------------------------------------------------------------------
# the comms-byte model
# ---------------------------------------------------------------------------


def test_sync_comm_bytes_totals_invariant_under_bucketing():
    """Bucketing changes op granularity, never wire bytes: the per-step
    totals match the anchor's for both sync flavors, and the bucketed
    entry carries the plan's breakdown."""
    spec = _spec(pp=2)
    for zero1 in (False, True):
        plan = (
            gradsync.plan_zero1_buckets(spec, 2, 2, 4096)
            if zero1
            else gradsync.plan_dp_buckets(spec, 2, 4096)
        )
        anchor = gradsync.sync_comm_bytes(spec, 2, 2, zero1=zero1, plan=None)
        bucketed = gradsync.sync_comm_bytes(spec, 2, 2, zero1=zero1, plan=plan)
        assert anchor["mode"] == "anchor" and bucketed["mode"] == "bucketed"
        assert (
            bucketed["bytes_per_step_per_device"]
            == anchor["bytes_per_step_per_device"]
        )
        assert bucketed["num_buckets"] == plan.num_buckets
        assert sum(bucketed["bucket_grad_bytes"]) == (
            bucketed["grad_bytes_per_device"]
        )


def test_zero1_plan_single_bucket_degenerates_cleanly():
    """A budget larger than the whole chunk yields one bucket whose
    scatter is the anchor scatter in (dp, chunk) form."""
    spec = _spec(pp=1)
    plan = gradsync.plan_zero1_buckets(spec, 2, 1, 1 << 30)
    assert plan.num_buckets == 1
    (a, b) = plan.buckets[0]
    assert a == 0 and b == plan.total_grad_bytes() // (4 * 2)
