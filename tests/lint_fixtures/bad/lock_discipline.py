"""SSP006 bad twin: a lock-guarded attribute touched outside the lock."""

import threading


class Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []

    def append(self, item):
        with self._lock:
            self._buf = self._buf + [item]

    def drain(self):
        out = self._buf  # MARK
        with self._lock:
            self._buf = []
        return out
