# Convenience targets. The CPU_MESH prefix runs any layout on 8 emulated
# devices (and keeps the TPU tunnel plugin out of CPU-only processes).
CPU_MESH = env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
           XLA_FLAGS=--xla_force_host_platform_device_count=8

# verify needs bash (pipefail / PIPESTATUS)
SHELL := /bin/bash

.PHONY: test verify lint analyze-smoke metrics-smoke report-smoke \
        audit-smoke overlap-smoke split-smoke tp-smoke recovery-smoke \
        diverge-smoke \
        aot-smoke serve-smoke chaos-smoke alerts-smoke fleet-smoke trace-smoke \
        mpmd-smoke bench-mpmd replay-smoke recompute-smoke \
        zero-smoke bench-zero \
        bench-serving bench-ckpt-aot data train train-mesh bench \
        bench-scaling schedules clean

test:
	python -m pytest tests/ -q

# the ROADMAP tier-1 command, verbatim — the gate every PR must keep green
verify:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# the house-rule linter (shallowspeed_tpu/analysis/lint.py,
# docs/static-analysis.md): repo-wide AST rules — justified broad
# excepts, strict-JSON metrics writes, the one-atomic-write discipline,
# the donation whitelist, the metrics schema-kind registry, lock
# discipline. Exit 0 clean / 2 with file:line findings; --format json
# is the stable machine-readable mode. Also run inside tier-1
# (tests/test_lint.py::test_repo_is_lint_clean).
lint:
	python -m shallowspeed_tpu.analysis.lint

# static program analysis end-to-end (docs/static-analysis.md): every
# training layout (seq, dp2, gpipe-pp4, zero1-dp2xpp2) compiled with
# --audit + one serving rung — the lowering-time passes (send/recv
# match, MPMD deadlock-freedom, stash lifetime) and the HLO donation
# dispatch-safety pass all green BEFORE first dispatch, the report CLI
# renders the Static checks row — then one deliberately-broken program
# per check class (unmatched send, leaked stash, cyclic wait, donating
# executable) each asserted REFUSED naming the offending tick/evidence
analyze-smoke:
	rm -rf /tmp/asmoke; mkdir -p /tmp/asmoke
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/asmoke/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	$(CPU_MESH) python scripts/analyze_smoke.py --phase clean \
	    --data-dir /tmp/asmoke/data --out-dir /tmp/asmoke
	$(CPU_MESH) python scripts/analyze_smoke.py --phase violate
	python -m shallowspeed_tpu.observability.report /tmp/asmoke/pp4.jsonl \
	    --format md > /tmp/asmoke/pp4.report.md
	grep -q "static checks" /tmp/asmoke/pp4.report.md
	@echo "analyze-smoke OK: four layouts + the serving rung ladder statically clean before dispatch, all injected violations refused, Static checks row rendered"

# telemetry end-to-end smoke: 1 CPU epoch with --metrics-out, then assert
# the file is non-empty valid JSONL with a per-epoch record (needs data:
# `make data` first, or point SHALLOWSPEED_DATA_DIR at a prepared dir)
metrics-smoke:
	rm -f /tmp/metrics.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --metrics-out /tmp/metrics.jsonl
	python -c "import json; lines = [json.loads(l) for l in open('/tmp/metrics.jsonl') if l.strip()]; assert lines, 'metrics file is empty'; assert any(r.get('kind') == 'event' and r.get('name') == 'epoch' for r in lines), 'no per-epoch record'; print(f'metrics-smoke OK: {len(lines)} valid JSONL records')"

# run-report end-to-end smoke: 1 CPU epoch with telemetry + health
# recording, then render the run report (throughput, MFU, span breakdown,
# step-loss sparkline, health verdict) — a nonzero report exit fails the
# target, which is the CI gate contract (needs data, like metrics-smoke)
report-smoke:
	rm -f /tmp/report_smoke.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --health record \
	    --metrics-out /tmp/report_smoke.jsonl
	python -m shallowspeed_tpu.observability.report /tmp/report_smoke.jsonl \
	    --format md

# XLA program audit end-to-end: 1 CPU epoch per layout (sequential, DP,
# gpipe pipeline, ZeRO-1) with --audit — train.py itself raises (nonzero
# exit) if the compiled collective census violates the layout contract —
# then assert the schema-v3 xla_audit record landed census-clean and the
# report CLI renders the Memory + Comms sections with exit 0 (needs data,
# like metrics-smoke)
audit-smoke:
	rm -f /tmp/audit_seq.jsonl /tmp/audit_dp.jsonl /tmp/audit_pp.jsonl \
	    /tmp/audit_z1.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --audit \
	    --metrics-out /tmp/audit_seq.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --audit --dp 2 \
	    --metrics-out /tmp/audit_dp.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --audit --pp 4 \
	    --schedule gpipe --metrics-out /tmp/audit_pp.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --audit --dp 2 --pp 2 \
	    --schedule gpipe --zero1 --metrics-out /tmp/audit_z1.jsonl
	set -e; for f in /tmp/audit_seq /tmp/audit_dp /tmp/audit_pp /tmp/audit_z1; do \
	  python -c "import json,sys; p=sys.argv[1]; recs=[json.loads(l) for l in open(p) if l.strip()]; a=[r for r in recs if r.get('kind')=='xla_audit']; assert a, p+': no xla_audit record'; assert all(r.get('census_ok') for r in a), p+': census mismatch'; print(p+': collective census matches the layout contract')" $$f.jsonl; \
	  python -m shallowspeed_tpu.observability.report $$f.jsonl --format md > $$f.report.md; \
	  grep -q "Memory (compiled program)" $$f.report.md; \
	  grep -q "Comms (XLA program audit)" $$f.report.md; \
	done
	@echo "audit-smoke OK: census + memory + comms sections on all 4 layouts"

# bucketed gradient-sync end-to-end: 1 CPU epoch each for DP=2 and ZeRO-1
# with --grad-bucket-bytes 65536 --audit — train.py aborts (nonzero exit)
# if the compiled program's bucket count / sizes violate the plan — then
# assert the census verdict is clean and the report renders the
# overlap-efficiency row + the bucketed sync line, exit 0 (needs data,
# like metrics-smoke)
overlap-smoke:
	rm -f /tmp/overlap_dp.jsonl /tmp/overlap_z1.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --audit --dp 2 \
	    --grad-bucket-bytes 65536 --metrics-out /tmp/overlap_dp.jsonl
	$(CPU_MESH) python train.py --epochs 1 --no-eval --audit --dp 2 --pp 2 \
	    --schedule gpipe --zero1 --grad-bucket-bytes 65536 \
	    --metrics-out /tmp/overlap_z1.jsonl
	set -e; for f in /tmp/overlap_dp /tmp/overlap_z1; do \
	  python -c "import json,sys; p=sys.argv[1]; recs=[json.loads(l) for l in open(p) if l.strip()]; a=[r for r in recs if r.get('kind')=='xla_audit']; assert a, p+': no xla_audit record'; assert all(r.get('census_ok') for r in a), p+': census mismatch'; dp=[r['expected']['axes']['dp'] for r in a][-1]; assert dp['mode']=='bucketed' and dp['num_buckets']>=2, p+': plan not bucketed'; plans=[r for r in recs if r.get('kind')=='event' and r.get('name')=='grad_sync_plan']; assert plans, p+': no grad_sync_plan event'; print(p+': bucketed census clean ('+str(dp['num_buckets'])+' buckets)')" $$f.jsonl; \
	  python -m shallowspeed_tpu.observability.report $$f.jsonl --format md > $$f.report.md; \
	  grep -q "overlap efficiency" $$f.report.md; \
	  grep -q "gradient sync: bucketed" $$f.report.md; \
	done
	@echo "overlap-smoke OK: bucketed census + overlap-efficiency row on dp2 and zero1"

# split-backward end-to-end: 1 CPU epoch each for pp4 gpipe and pp4
# pipedream with --backward-split --audit (train.py aborts nonzero if the
# split program's collective census violates the layout contract), plus an
# UNSPLIT twin of each — then assert the xla_audit census is clean, the
# pipeline_program record is backward_split with a weighted bubble strictly
# below the unsplit twin's, the report renders the weighted-bubble row, and
# the final model hash EQUALS the unsplit run's (the bitwise-parity
# contract), exit 0 (needs data, like metrics-smoke)
split-smoke:
	rm -f /tmp/split_gpipe.jsonl /tmp/split_pd.jsonl \
	    /tmp/split_gpipe_ref.jsonl /tmp/split_pd_ref.jsonl \
	    /tmp/split_gpipe.out /tmp/split_gpipe_ref.out \
	    /tmp/split_pd.out /tmp/split_pd_ref.out
	set -o pipefail; $(CPU_MESH) python train.py --epochs 1 --no-eval \
	    --audit --pp 4 --schedule gpipe --backward-split \
	    --metrics-out /tmp/split_gpipe.jsonl | tee /tmp/split_gpipe.out
	set -o pipefail; $(CPU_MESH) python train.py --epochs 1 --no-eval \
	    --pp 4 --schedule gpipe \
	    --metrics-out /tmp/split_gpipe_ref.jsonl | tee /tmp/split_gpipe_ref.out
	set -o pipefail; $(CPU_MESH) python train.py --epochs 1 --no-eval \
	    --audit --pp 4 --schedule pipedream --backward-split \
	    --metrics-out /tmp/split_pd.jsonl | tee /tmp/split_pd.out
	set -o pipefail; $(CPU_MESH) python train.py --epochs 1 --no-eval \
	    --pp 4 --schedule pipedream \
	    --metrics-out /tmp/split_pd_ref.jsonl | tee /tmp/split_pd_ref.out
	set -e; for f in /tmp/split_gpipe /tmp/split_pd; do \
	  split_h=$$(grep -o 'final model hash: [0-9a-f]*' $$f.out); \
	  ref_h=$$(grep -o 'final model hash: [0-9a-f]*' $${f}_ref.out); \
	  test -n "$$split_h" && test "$$split_h" = "$$ref_h" \
	    || { echo "$$f: HASH MISMATCH split [$$split_h] vs unsplit [$$ref_h]"; exit 1; }; \
	  echo "$$f: split hash == unsplit hash"; \
	  python -c "import json,sys; p=sys.argv[1]; recs=[json.loads(l) for l in open(p+'.jsonl') if l.strip()]; a=[r for r in recs if r.get('kind')=='xla_audit']; assert a, p+': no xla_audit record'; assert all(r.get('census_ok') for r in a), p+': census mismatch'; prog=[r for r in recs if r.get('kind')=='event' and r.get('name')=='pipeline_program'][-1]; assert prog['backward_split'], p+': program not split'; ref=[json.loads(l) for l in open(p+'_ref.jsonl') if l.strip()]; rprog=[r for r in ref if r.get('kind')=='event' and r.get('name')=='pipeline_program'][-1]; assert not rprog['backward_split']; assert prog['weighted_bubble_fraction'] < rprog['weighted_bubble_fraction'], p+': weighted bubble did not shrink (%.3f vs unsplit %.3f)' % (prog['weighted_bubble_fraction'], rprog['weighted_bubble_fraction']); print(p+': split census clean, weighted bubble %.1f%% < unsplit %.1f%%' % (100*prog['weighted_bubble_fraction'], 100*rprog['weighted_bubble_fraction']))" $$f; \
	  python -m shallowspeed_tpu.observability.report $$f.jsonl --format md > $$f.report.md; \
	  grep -q "weighted bubble" $$f.report.md; \
	done
	@echo "split-smoke OK: bitwise hash parity + clean census + weighted-bubble row on gpipe and pipedream"

# tensor-parallelism end-to-end (docs/performance.md "--tp"): 1 CPU epoch
# each for tp2 and dp2 x tp2 with --audit — train.py aborts nonzero if the
# compiled census violates the per-axis contract (the tp axis demands the
# Megatron all-reduce floor) — then assert the census landed clean with a
# tp axis + a mesh_layout provenance event, the report renders the per-axis
# Comms breakdown (tp next to dp/pp), the tp2 loss equals the sequential
# reference's within the documented cross-layout float tolerance (the tp
# psums reassociate split contractions — same tolerance class as a dp-width
# change, so HASH equality is deliberately NOT claimed across tp), and the
# tp=1 anchor holds EXACTLY: --dp 2 --tp 1 hashes byte-identically to the
# historical --dp 2 program (needs data, like metrics-smoke)
tp-smoke:
	rm -f /tmp/tp_seq.jsonl /tmp/tp_tp2.jsonl /tmp/tp_dp2tp2.jsonl \
	    /tmp/tp_seq.out /tmp/tp_tp2.out /tmp/tp_anchor1.out /tmp/tp_anchor2.out
	set -o pipefail; $(CPU_MESH) python train.py --epochs 1 --no-eval \
	    --metrics-out /tmp/tp_seq.jsonl | tee /tmp/tp_seq.out
	set -o pipefail; $(CPU_MESH) python train.py --epochs 1 --no-eval \
	    --audit --tp 2 --metrics-out /tmp/tp_tp2.jsonl | tee /tmp/tp_tp2.out
	$(CPU_MESH) python train.py --epochs 1 --no-eval --audit --dp 2 --tp 2 \
	    --metrics-out /tmp/tp_dp2tp2.jsonl
	set -o pipefail; $(CPU_MESH) python train.py --epochs 1 --no-eval --dp 2 \
	    | tee /tmp/tp_anchor1.out
	set -o pipefail; $(CPU_MESH) python train.py --epochs 1 --no-eval --dp 2 \
	    --tp 1 | tee /tmp/tp_anchor2.out
	set -e; for f in /tmp/tp_tp2 /tmp/tp_dp2tp2; do \
	  python -c "import json,sys; p=sys.argv[1]; recs=[json.loads(l) for l in open(p) if l.strip()]; a=[r for r in recs if r.get('kind')=='xla_audit']; assert a, p+': no xla_audit record'; assert all(r.get('census_ok') for r in a), p+': census mismatch'; tp=[r['expected']['axes'].get('tp') for r in a if r.get('name')=='epoch_program'][-1]; assert tp and tp['hlo_min_all_reduce_ops']==tp['sites_fwd']+tp['sites_bwd']>0, p+': no tp axis in the contract'; ml=[r for r in recs if r.get('kind')=='event' and r.get('name')=='mesh_layout']; assert ml and ml[-1]['layout'] in ('topology-aware','order-preserving'), p+': no mesh_layout provenance'; print(p+': tp census clean (%d Megatron sites, %s placement)' % (tp['hlo_min_all_reduce_ops'], ml[-1]['layout']))" $$f.jsonl; \
	  python -m shallowspeed_tpu.observability.report $$f.jsonl --format md > $$f.report.md; \
	  grep -q "Comms (XLA program audit)" $$f.report.md; \
	  grep -q "tp all_reduce" $$f.report.md; \
	done
	python -c "import json,re,sys; loss=lambda p: [r for r in (json.loads(l) for l in open(p) if l.strip()) if r.get('kind')=='event' and r.get('name')=='epoch'][-1]['loss']; s, t = loss('/tmp/tp_seq.jsonl'), loss('/tmp/tp_tp2.jsonl'); rel=abs(s-t)/max(abs(s),1e-12); assert rel < 1e-3, 'tp2 loss %r vs sequential %r (rel %g)' % (t, s, rel); print('tp2 loss == sequential reference within float tolerance (rel %.2e)' % rel)"
	set -e; h1=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/tp_anchor1.out); \
	  h2=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/tp_anchor2.out); \
	  test -n "$$h1" && test "$$h1" = "$$h2" \
	    || { echo "tp=1 ANCHOR BROKEN: --dp 2 [$$h1] vs --dp 2 --tp 1 [$$h2]"; exit 1; }; \
	  echo "tp=1 anchor holds: --tp 1 hash == historical 2-axis hash"
	@echo "tp-smoke OK: census-clean tp2 + dp2xtp2 with per-axis Comms, sequential-reference loss parity, tp=1 byte-anchor"

# fault-tolerant recovery end-to-end (docs/robustness.md): on a dp2 and a
# gpipe-pp4 layout, run an uninterrupted twin, then KILL a checkpointing run
# with a SIGKILL injected at step 11 via the fault harness
# (SHALLOWSPEED_FAULTS), resume it with --resume auto, and assert the final
# weight hash is BITWISE identical to the twin's. Then concatenate the
# killed + resumed telemetry and assert the report CLI renders the
# Reliability section with the recovery verdict and the measured
# steps-lost-to-replay (11 trained - resume@8 = 3), exit 0. Uses a tiny
# synthetic dataset (8 batches/epoch) so the whole smoke is CPU-fast.
recovery-smoke:
	rm -rf /tmp/rsmoke; mkdir -p /tmp/rsmoke
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/rsmoke/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	set -e; for lay in dp2 pp4; do \
	  if [ $$lay = dp2 ]; then LFLAGS="--dp 2 --mubatches 2"; \
	  else LFLAGS="--pp 4 --schedule gpipe --mubatches 4"; fi; \
	  COMMON="--data-dir /tmp/rsmoke/data --epochs 2 --global-batch-size 32 --no-eval"; \
	  $(CPU_MESH) python train.py $$COMMON $$LFLAGS \
	      > /tmp/rsmoke/$$lay.twin.out; \
	  $(CPU_MESH) env SHALLOWSPEED_FAULTS="die@step=11:mode=sigkill" \
	      python train.py $$COMMON $$LFLAGS \
	      --checkpoint-dir /tmp/rsmoke/ck_$$lay --checkpoint-every-steps 4 \
	      --metrics-out /tmp/rsmoke/$$lay.killed.jsonl \
	      > /tmp/rsmoke/$$lay.killed.out 2>&1 && \
	      { echo "$$lay: injected SIGKILL did not fire"; exit 1; } || true; \
	  test -f /tmp/rsmoke/ck_$$lay/step-00000008.npz \
	      || { echo "$$lay: no step-8 checkpoint survived the kill"; exit 1; }; \
	  $(CPU_MESH) python train.py $$COMMON $$LFLAGS \
	      --checkpoint-dir /tmp/rsmoke/ck_$$lay --checkpoint-every-steps 4 \
	      --resume auto --metrics-out /tmp/rsmoke/$$lay.resumed.jsonl \
	      > /tmp/rsmoke/$$lay.resumed.out; \
	  grep -q "resumed at epoch" /tmp/rsmoke/$$lay.resumed.out \
	      || { echo "$$lay: resume auto did not restore"; exit 1; }; \
	  twin_h=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/rsmoke/$$lay.twin.out); \
	  res_h=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/rsmoke/$$lay.resumed.out); \
	  test -n "$$twin_h" && test "$$twin_h" = "$$res_h" \
	      || { echo "$$lay: HASH MISMATCH resumed [$$res_h] vs twin [$$twin_h]"; exit 1; }; \
	  echo "$$lay: killed-and-resumed hash == uninterrupted twin hash"; \
	  cat /tmp/rsmoke/$$lay.killed.jsonl /tmp/rsmoke/$$lay.resumed.jsonl \
	      > /tmp/rsmoke/$$lay.combined.jsonl; \
	  python -m shallowspeed_tpu.observability.report \
	      /tmp/rsmoke/$$lay.combined.jsonl --format md \
	      > /tmp/rsmoke/$$lay.report.md; \
	  grep -q "## Reliability" /tmp/rsmoke/$$lay.report.md; \
	  grep -q "recovery: resumed from" /tmp/rsmoke/$$lay.report.md; \
	  grep -q "steps lost to replay: 3" /tmp/rsmoke/$$lay.report.md; \
	done
	@# the ASYNC leg (one layout keeps the smoke bounded; the in-suite
	@# fuzz lattice covers dp2/pp4/tp2): SIGKILL injected INSIDE the
	@# background writer's write/verify/rename window (die@save=2 fires
	@# after the temp file is durable, before the rename) — discovery
	@# must see only fully-verifying snapshots, resume must finish on
	@# the twin's exact bits, and the report must show the async saves
	set -e; \
	  $(CPU_MESH) env SHALLOWSPEED_FAULTS="die@save=2:mode=sigkill" \
	      python train.py --data-dir /tmp/rsmoke/data --epochs 2 \
	      --global-batch-size 32 --no-eval --dp 2 --mubatches 2 \
	      --checkpoint-dir /tmp/rsmoke/ck_async --checkpoint-every-steps 4 \
	      --async-checkpoint \
	      --metrics-out /tmp/rsmoke/async.killed.jsonl \
	      > /tmp/rsmoke/async.killed.out 2>&1 && \
	      { echo "async: injected in-window SIGKILL did not fire"; exit 1; } || true; \
	  python -c "import sys; sys.path.insert(0, '.'); from shallowspeed_tpu.checkpoint import find_latest_good, list_step_checkpoints; steps=[g for g,_ in list_step_checkpoints('/tmp/rsmoke/ck_async')]; assert steps==[4,8], 'visible snapshots %r (save 2 = step 12 must never rename)' % steps; p,_,skipped=find_latest_good('/tmp/rsmoke/ck_async'); assert p is not None and p.name=='step-00000008.npz' and skipped==[], 'discovery saw a torn/unverified snapshot: %r %r' % (p, skipped); print('async kill window: only fully-verifying snapshots discoverable (latest %s)' % p.name)"; \
	  $(CPU_MESH) python train.py --data-dir /tmp/rsmoke/data --epochs 2 \
	      --global-batch-size 32 --no-eval --dp 2 --mubatches 2 \
	      --checkpoint-dir /tmp/rsmoke/ck_async --checkpoint-every-steps 4 \
	      --async-checkpoint --resume auto \
	      --metrics-out /tmp/rsmoke/async.resumed.jsonl \
	      > /tmp/rsmoke/async.resumed.out; \
	  twin_h=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/rsmoke/dp2.twin.out); \
	  res_h=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/rsmoke/async.resumed.out); \
	  test -n "$$twin_h" && test "$$twin_h" = "$$res_h" \
	      || { echo "async: HASH MISMATCH resumed [$$res_h] vs twin [$$twin_h]"; exit 1; }; \
	  echo "async: SIGKILL-mid-save + resume auto == uninterrupted twin hash"; \
	  cat /tmp/rsmoke/async.killed.jsonl /tmp/rsmoke/async.resumed.jsonl \
	      > /tmp/rsmoke/async.combined.jsonl; \
	  python -m shallowspeed_tpu.observability.report \
	      /tmp/rsmoke/async.combined.jsonl --format md \
	      > /tmp/rsmoke/async.report.md; \
	  grep -q "async checkpointing: " /tmp/rsmoke/async.report.md; \
	  grep -q "recovery: resumed from" /tmp/rsmoke/async.report.md
	@echo "recovery-smoke OK: kill-at-step-11 + resume auto is bitwise identical to the uninterrupted twin on dp2 and gpipe-pp4 (plus SIGKILL-mid-async-save), Reliability section rendered"

# Numerics-provenance end-to-end (docs/numerics.md "Divergence
# debugging"): on dp2 and gpipe-pp4, train twin runs with --digests and
# assert the divergence CLI exits 0 (streams bitwise-equal), then inject
# a deterministic single-bit param flip (SHALLOWSPEED_FAULTS flip@step=11
# — finite, invisible to loss/health) and assert the CLI exits 2 naming
# EXACTLY (step 11, layer 0, W), that --bisect restores the last agreeing
# per-step snapshot, replays ONE step with the flip re-armed, and
# reproduces the same attribution with ULP evidence, and that the report
# CLI renders the Divergence section. Exit 0.
diverge-smoke:
	rm -rf /tmp/dsmoke; mkdir -p /tmp/dsmoke
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/dsmoke/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	set -e; for lay in dp2 pp4; do \
	  if [ $$lay = dp2 ]; then LFLAGS="--dp 2 --mubatches 2"; \
	  else LFLAGS="--pp 4 --schedule gpipe --mubatches 4"; fi; \
	  COMMON="--data-dir /tmp/dsmoke/data --epochs 2 --global-batch-size 32 --no-eval --digests --checkpoint-every-steps 1 --keep 20"; \
	  $(CPU_MESH) python train.py $$COMMON $$LFLAGS \
	      --checkpoint-dir /tmp/dsmoke/ck_$${lay}_a \
	      --metrics-out /tmp/dsmoke/$$lay.a.jsonl > /tmp/dsmoke/$$lay.a.out; \
	  $(CPU_MESH) python train.py $$COMMON $$LFLAGS \
	      --checkpoint-dir /tmp/dsmoke/ck_$${lay}_b \
	      --metrics-out /tmp/dsmoke/$$lay.b.jsonl > /tmp/dsmoke/$$lay.b.out; \
	  python -m shallowspeed_tpu.observability.divergence \
	      /tmp/dsmoke/$$lay.a.jsonl /tmp/dsmoke/$$lay.b.jsonl \
	      > /tmp/dsmoke/$$lay.twin.cmp; \
	  grep -q "IDENTICAL" /tmp/dsmoke/$$lay.twin.cmp \
	      || { echo "$$lay: twin streams not identical"; exit 1; }; \
	  echo "$$lay: twin digest streams bitwise-equal (exit 0)"; \
	  $(CPU_MESH) env SHALLOWSPEED_FAULTS="flip@step=11" \
	      python train.py $$COMMON $$LFLAGS \
	      --checkpoint-dir /tmp/dsmoke/ck_$${lay}_f \
	      --metrics-out /tmp/dsmoke/$$lay.f.jsonl > /tmp/dsmoke/$$lay.f.out; \
	  rc=0; python -m shallowspeed_tpu.observability.divergence \
	      /tmp/dsmoke/$$lay.a.jsonl /tmp/dsmoke/$$lay.f.jsonl \
	      > /tmp/dsmoke/$$lay.flip.cmp || rc=$$?; \
	  test $$rc -eq 2 \
	      || { echo "$$lay: flip compare exit $$rc, wanted 2"; exit 1; }; \
	  grep -q "first divergence: step 11 layer 0 tensor W" \
	      /tmp/dsmoke/$$lay.flip.cmp \
	      || { echo "$$lay: flip not attributed to (step 11, layer 0, W)"; \
	           cat /tmp/dsmoke/$$lay.flip.cmp; exit 1; }; \
	  echo "$$lay: injected flip named at exactly (step 11, layer 0, W) (exit 2)"; \
	  rc=0; $(CPU_MESH) python -m shallowspeed_tpu.observability.divergence \
	      /tmp/dsmoke/$$lay.a.jsonl /tmp/dsmoke/$$lay.f.jsonl \
	      --bisect /tmp/dsmoke/ck_$${lay}_a /tmp/dsmoke/ck_$${lay}_f \
	      > /tmp/dsmoke/$$lay.bisect.out || rc=$$?; \
	  test $$rc -eq 2 \
	      || { echo "$$lay: bisect exit $$rc, wanted 2"; exit 1; }; \
	  grep -q "divergence is INSIDE step 11" /tmp/dsmoke/$$lay.bisect.out \
	      || { echo "$$lay: bisect did not isolate step 11"; \
	           cat /tmp/dsmoke/$$lay.bisect.out; exit 1; }; \
	  grep -q "replay attribution MATCHES" /tmp/dsmoke/$$lay.bisect.out \
	      || { echo "$$lay: replay attribution mismatch"; \
	           cat /tmp/dsmoke/$$lay.bisect.out; exit 1; }; \
	  grep -q "max ulp 1" /tmp/dsmoke/$$lay.bisect.out \
	      || { echo "$$lay: expected a 1-ulp flip in the replay diff"; exit 1; }; \
	  echo "$$lay: bisect replay reproduced the flip (1 ulp at layer 0 W)"; \
	  python -m shallowspeed_tpu.observability.report \
	      /tmp/dsmoke/$$lay.f.jsonl --format md > /tmp/dsmoke/$$lay.report.md; \
	  grep -q "## Divergence" /tmp/dsmoke/$$lay.report.md \
	      || { echo "$$lay: report missing Divergence section"; exit 1; }; \
	done
	@echo "diverge-smoke OK: twin streams identical (exit 0), flip@step=11 named at (step 11, layer 0, W) (exit 2), bisect replay reproduces the 1-ulp flip, Divergence section rendered, on dp2 and gpipe-pp4"

# AOT executable cache end-to-end (docs/performance.md): cold-compile a
# dp2 rung ladder into the cache, RESTART the process and assert every
# rung is a cache hit re-verified by the audit census with ZERO jit
# compiles (pinned by the counter) and bitwise-equal predictions, then
# corrupt one cache entry on disk and assert a clean fallback-to-recompile
# with a recorded aot_cache corrupt event + a rewrite. Exit 0.
aot-smoke:
	rm -rf /tmp/aotsmoke; mkdir -p /tmp/aotsmoke
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/aotsmoke/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	$(CPU_MESH) python scripts/aot_smoke.py --phase cold \
	    --cache-dir /tmp/aotsmoke/aot --data-dir /tmp/aotsmoke/data \
	    --ref /tmp/aotsmoke/ref.npz --metrics-out /tmp/aotsmoke/cold.jsonl
	$(CPU_MESH) python scripts/aot_smoke.py --phase warm \
	    --cache-dir /tmp/aotsmoke/aot --data-dir /tmp/aotsmoke/data \
	    --ref /tmp/aotsmoke/ref.npz --metrics-out /tmp/aotsmoke/warm.jsonl
	python -c "import sys; sys.path.insert(0, '.'); from pathlib import Path; from shallowspeed_tpu import faults; entries=sorted(Path('/tmp/aotsmoke/aot').glob('*.aotx')); assert entries, 'no cache entries on disk'; faults.corrupt_checkpoint_bytes(entries[0], seed=5); print('corrupted %s' % entries[0].name)"
	$(CPU_MESH) python scripts/aot_smoke.py --phase corrupt \
	    --cache-dir /tmp/aotsmoke/aot --data-dir /tmp/aotsmoke/data \
	    --ref /tmp/aotsmoke/ref.npz --metrics-out /tmp/aotsmoke/corrupt.jsonl
	python -m shallowspeed_tpu.observability.report /tmp/aotsmoke/warm.jsonl \
	    --format md > /tmp/aotsmoke/warm.report.md
	grep -q "aot executable cache: " /tmp/aotsmoke/warm.report.md
	@echo "aot-smoke OK: restarted process warmed the ladder from cache with zero recompiles, every deserialized program re-audited, corrupt entry fell back to a clean recompile + rewrite"

# inference serving end-to-end (docs/serving.md): on a CPU dp2 and a
# gpipe-pp4 layout, drive 200 seeded Poisson requests through the serving
# engine with --verify (every response bitwise-equal to a direct predict()
# of the same rows) and --audit (every compiled inference program's
# collective census verified against the forward-only serving contract
# before it serves), assert zero dropped/incorrect responses and that the
# schema-v5 request/serving records landed, render the report CLI's
# Serving section with an SLO verdict, then emit the bench_serving
# offered-load sweep JSON (p50/p99 latency, goodput, queue depth,
# saturation knee), exit 0 (needs data, like metrics-smoke)
serve-smoke:
	rm -f /tmp/serve_dp.jsonl /tmp/serve_pp.jsonl /tmp/serve_tp.jsonl \
	    /tmp/serve_bench.json
	$(CPU_MESH) python -m shallowspeed_tpu.serving --dp 2 \
	    --requests 200 --rate 300 --seed 0 --slo-ms 2000 --verify --audit \
	    --metrics-out /tmp/serve_dp.jsonl
	$(CPU_MESH) python -m shallowspeed_tpu.serving --pp 4 --schedule gpipe \
	    --requests 200 --rate 300 --seed 0 --slo-ms 2000 --verify --audit \
	    --metrics-out /tmp/serve_pp.jsonl
	$(CPU_MESH) python -m shallowspeed_tpu.serving --tp 2 \
	    --requests 200 --rate 300 --seed 0 --slo-ms 2000 --verify --audit \
	    --metrics-out /tmp/serve_tp.jsonl
	set -e; for f in /tmp/serve_dp /tmp/serve_pp /tmp/serve_tp; do \
	  python -c "import json,sys; p=sys.argv[1]; recs=[json.loads(l) for l in open(p) if l.strip()]; reqs=[r for r in recs if r.get('kind')=='request']; assert len(reqs)==200, p+': %d request records' % len(reqs); assert all(r['name']=='ok' for r in reqs), p+': dropped/failed requests'; srv=[r for r in recs if r.get('kind')=='serving']; assert srv, p+': no serving summary'; a=[r for r in recs if r.get('kind')=='xla_audit']; assert a and all(r.get('census_ok') for r in a), p+': serving census not clean'; print(p+': 200 ok requests, clean serving census')" $$f.jsonl; \
	  python -m shallowspeed_tpu.observability.report $$f.jsonl --format md \
	      --slo-ms 2000 > $$f.report.md; \
	  grep -q "## Serving" $$f.report.md; \
	  grep -q "SLO" $$f.report.md; \
	done
	$(CPU_MESH) python -m shallowspeed_tpu.serving.bench_serving --dp 2 \
	    --rates 100,300 --requests 40 --seed 0 --slo-ms 2000 \
	    --out /tmp/serve_bench.json
	python -c "import json; rec=json.load(open('/tmp/serve_bench.json')); assert rec['bench']=='serving' and rec['bench_version']==1; rows=rec['sweep']; assert len(rows)==2 and all(r['p50_latency_s'] and r['p99_latency_s'] is not None and r['queue_depth_max'] is not None and r['goodput_rps'] is not None for r in rows), rows; print('bench_serving: %d-rate sweep, knee=%s' % (len(rows), rec['knee_rps']))"
	@echo "serve-smoke OK: 200 bitwise-verified Poisson requests on dp2, gpipe-pp4 and tp2, Serving section + SLO verdict rendered, bench_serving sweep recorded"

# serving-layer fault tolerance end-to-end (docs/robustness.md "Serving
# faults"): on a CPU dp2 and a gpipe-pp4 layout, train a short run that
# leaves step checkpoints behind, then serve its step-8 snapshot under a
# seeded chaos soak — error (dispatch raises -> re-queue + retry), slow
# (latency spike), die (dispatch-loop crash, operator re-enters), nan
# (poisoned weights -> unhealthy verdicts -> breaker -> breaker-triggered
# reload) — plus one mid-traffic WATCHER hot reload onto the newer step-16
# weights. Asserts zero silently-lost requests (every submitted id reaches
# a terminal verdict), bitwise parity of every "ok" response vs a direct
# predict() under the weights active at its dispatch, >=1 breaker trip
# with >=2 reloads and a measured recovery, ZERO recompiles across the hot
# swaps, and the report CLI rendering the Degradation subsection. Exit 0.
chaos-smoke:
	rm -rf /tmp/chaos; mkdir -p /tmp/chaos
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/chaos/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	set -e; for lay in dp2 pp4; do \
	  if [ $$lay = dp2 ]; then LFLAGS="--dp 2 --mubatches 2"; SFLAGS="--dp 2"; \
	  else LFLAGS="--pp 4 --schedule gpipe --mubatches 4"; SFLAGS="--pp 4 --schedule gpipe"; fi; \
	  $(CPU_MESH) python train.py --data-dir /tmp/chaos/data --epochs 2 \
	      --global-batch-size 32 --no-eval $$LFLAGS \
	      --checkpoint-dir /tmp/chaos/ck_$$lay --checkpoint-every-steps 8 \
	      > /tmp/chaos/$$lay.train.out; \
	  test -f /tmp/chaos/ck_$$lay/step-00000008.npz \
	      || { echo "$$lay: no step-8 checkpoint to serve"; exit 1; }; \
	  test -f /tmp/chaos/ck_$$lay/step-00000016.npz \
	      || { echo "$$lay: no step-16 checkpoint to hot-reload"; exit 1; }; \
	  $(CPU_MESH) python -m shallowspeed_tpu.serving.bench_serving $$SFLAGS \
	      --data-dir /tmp/chaos/data --global-batch-size 32 \
	      --checkpoint /tmp/chaos/ck_$$lay/step-00000008.npz \
	      --chaos "error@dispatch=2,slow@dispatch=3:ms=20,die@dispatch=4,nan@dispatch=6" \
	      --reload-dir /tmp/chaos/ck_$$lay --reload-at 5 --breaker 2 \
	      --retry-budget 2 --max-slots 2 --requests 60 --rates 300 \
	      --slo-ms 2000 --seed 0 \
	      --chaos-out /tmp/chaos/$$lay.chaos.json \
	      --metrics-out /tmp/chaos/$$lay.jsonl; \
	  python -c "import json,sys; p=sys.argv[1]; rec=json.load(open(p)); assert rec['bench']=='serving_chaos'; assert rec['silently_lost']==[], p+': LOST '+str(rec['silently_lost']); assert rec['parity_mismatches']==0, p+': parity mismatches'; assert rec['crashes_recovered']==1, p+': die leg did not fire/recover'; assert rec['breaker_trips']>=1 and rec['reloads']>=2, p+': no breaker-then-reload (%s trips, %s reloads)' % (rec['breaker_trips'], rec['reloads']); assert rec['recovery_s'] is not None and not rec['degraded_at_exit'], p+': did not recover'; assert rec['recompiles']==0 and rec['predict_cache_stable'], p+': hot reload recompiled'; assert rec['faults_unfired']==0, p+': unfired chaos faults'; v=rec['verdicts']; assert v.get('ok',0)>0, p+': nothing served'; print(p+': %d submitted, verdicts %s, availability %.1f%%, recovery %.0f ms' % (rec['submitted'], v, 100*rec['availability'], 1e3*rec['recovery_s']))" /tmp/chaos/$$lay.chaos.json; \
	  python -m shallowspeed_tpu.observability.report /tmp/chaos/$$lay.jsonl \
	      --format md --slo-ms 2000 > /tmp/chaos/$$lay.report.md; \
	  grep -q "### Degradation" /tmp/chaos/$$lay.report.md; \
	  grep -q "breaker: 1 trip" /tmp/chaos/$$lay.report.md; \
	  grep -q "availability" /tmp/chaos/$$lay.report.md; \
	done
	@echo "chaos-smoke OK: die/slow/nan/error + hot reload survived on dp2 and gpipe-pp4 — zero lost, bitwise parity, breaker recovered, zero recompiles, Degradation rendered"

# live-telemetry end-to-end (docs/observability.md "Live telemetry &
# alerting"): train a short dp2 run that leaves step checkpoints, then
# soak its step-8 snapshot under the seeded chaos schedule WITH a live
# background watcher tailing the metrics file as it is written. Asserts
# the injected breaker trip fires the breaker_open alert rule and that
# the SAME rule resolves after the breaker-triggered hot reload recovers
# (firing strictly before resolved in the stream); that rollup records
# stream alongside; that the live watcher's final --follow snapshot
# equals the --once snapshot over the finished file BYTE FOR BYTE (the
# determinism contract: windows close on record ts, never wall clock);
# that a chaos-free twin soak fires ZERO alerts (no false positives)
# while still emitting rollups + the sweep summary record; that --once
# on a missing run exits 1; and that the report CLI renders the Alerts
# section with a clean false-alert verdict. Exit 0.
alerts-smoke:
	rm -rf /tmp/alerts; mkdir -p /tmp/alerts
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/alerts/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	$(CPU_MESH) python train.py --data-dir /tmp/alerts/data --epochs 2 \
	    --global-batch-size 32 --no-eval --dp 2 --mubatches 2 \
	    --checkpoint-dir /tmp/alerts/ck --checkpoint-every-steps 8 \
	    > /tmp/alerts/train.out
	test -f /tmp/alerts/ck/step-00000008.npz \
	    || { echo "no step-8 checkpoint to serve"; exit 1; }
	set -e; \
	python -m shallowspeed_tpu.observability.watch \
	    '/tmp/alerts/chaos.jsonl*' --follow --format json \
	    --interval 0.2 --idle-exit 30 --max-wall 600 \
	    > /tmp/alerts/follow.json & WATCH=$$!; \
	$(CPU_MESH) python -m shallowspeed_tpu.serving.bench_serving --dp 2 \
	    --data-dir /tmp/alerts/data --global-batch-size 32 \
	    --checkpoint /tmp/alerts/ck/step-00000008.npz \
	    --chaos "error@dispatch=2,slow@dispatch=3:ms=20,die@dispatch=4,nan@dispatch=6" \
	    --reload-dir /tmp/alerts/ck --reload-at 5 --breaker 2 \
	    --retry-budget 2 --max-slots 2 --requests 60 --rates 300 \
	    --slo-ms 2000 --seed 0 \
	    --chaos-out /tmp/alerts/chaos.json \
	    --metrics-out /tmp/alerts/chaos.jsonl; \
	wait $$WATCH
	python -c "from shallowspeed_tpu.observability.metrics import read_jsonl; recs=read_jsonl('/tmp/alerts/chaos.jsonl'); alerts=[r for r in recs if r['kind']=='alert']; br=[(a['state'],a['t']) for a in alerts if a['name']=='breaker_open']; assert br, 'breaker tripped but no breaker_open alert fired: '+str([(a['name'],a['state']) for a in alerts]); states=[s for s,_ in br]; assert states[0]=='firing' and 'resolved' in states, 'breaker_open never resolved after hot reload: '+str(br); assert states.index('firing')<states.index('resolved'); rolls=[r for r in recs if r['kind']=='rollup']; assert rolls, 'no rollup records streamed'; assert any(r['name']=='serving' for r in rolls); print('chaos soak: %d alert transitions (%s), %d rollup windows' % (len(alerts), ','.join(sorted({a['name'] for a in alerts})), len(rolls)))"
	python -m shallowspeed_tpu.observability.watch '/tmp/alerts/chaos.jsonl*' \
	    --once --format json > /tmp/alerts/once.json
	cmp /tmp/alerts/follow.json /tmp/alerts/once.json \
	    || { echo "--follow and --once snapshots diverge"; exit 1; }
	$(CPU_MESH) python -m shallowspeed_tpu.serving.bench_serving --dp 2 \
	    --data-dir /tmp/alerts/data --global-batch-size 32 \
	    --checkpoint /tmp/alerts/ck/step-00000008.npz \
	    --requests 60 --rates 300 --slo-ms 2000 --seed 0 \
	    --out /tmp/alerts/clean_bench.json \
	    --metrics-out /tmp/alerts/clean.jsonl
	python -c "from shallowspeed_tpu.observability.metrics import read_jsonl; recs=read_jsonl('/tmp/alerts/clean.jsonl'); alerts=[r for r in recs if r['kind']=='alert']; assert alerts==[], 'clean twin fired FALSE alerts: '+str([(a['name'],a['state']) for a in alerts]); rolls=[r for r in recs if r['kind']=='rollup']; assert rolls, 'clean twin emitted no rollups'; sweeps=[r for r in recs if r['kind']=='serving' and r['name']=='sweep']; assert sweeps and 'knee_rps' in sweeps[0], 'no sweep summary record'; print('clean twin: 0 alerts, %d rollup windows, sweep knee=%s' % (len(rolls), sweeps[0]['knee_rps']))"
	python -m shallowspeed_tpu.observability.watch /tmp/alerts/clean.jsonl \
	    --once --format json > /tmp/alerts/clean_watch.json
	python -c "import json; s=json.load(open('/tmp/alerts/clean_watch.json')); assert s['alerts']['fired']==0 and s['alerts']['active']==[], s['alerts']; assert s['records']>0 and s['malformed']==0"
	! python -m shallowspeed_tpu.observability.watch \
	    /tmp/alerts/nonexistent.jsonl --once --format json > /dev/null 2>&1
	python -m shallowspeed_tpu.observability.report /tmp/alerts/chaos.jsonl \
	    --format md --slo-ms 2000 > /tmp/alerts/report.md
	grep -q "## Alerts" /tmp/alerts/report.md
	grep -q "every fired rule is backed by fault evidence" /tmp/alerts/report.md
	@echo "alerts-smoke OK: breaker_open fired and resolved under live watch, clean twin fired zero alerts, --follow == --once byte-for-byte, Alerts section rendered with clean false-alert verdict"

# serving-fleet end-to-end (docs/serving.md "Fleet", docs/robustness.md
# "Fleet failover"): train a short run that leaves step checkpoints, then
# serve its step-8 snapshot through a 3-replica fleet (separate worker
# processes, each its own JAX runtime, ladders warmed before traffic)
# under seeded Poisson load — and SIGKILL the busiest replica mid-soak.
# Asserts zero silently-lost requests (every admitted id reaches exactly
# one terminal verdict), zero worker-verified bitwise-parity mismatches,
# >=1 failover with its in-flight re-queued, a replacement scaled up from
# the newest good snapshot (ready time measured) without degrading the
# quorum, and the report CLI rendering the Fleet section from the merged
# parent + .r{replica_id} shard stream. Then the serve CLI's fleet path:
# a 2-replica clean run exits 0 with worker-side bitwise parity. Exit 0.
fleet-smoke:
	rm -rf /tmp/fleet; mkdir -p /tmp/fleet
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/fleet/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	$(CPU_MESH) python train.py --data-dir /tmp/fleet/data --epochs 2 \
	    --global-batch-size 32 --no-eval \
	    --checkpoint-dir /tmp/fleet/ck --checkpoint-every-steps 8 \
	    > /tmp/fleet/train.out
	test -f /tmp/fleet/ck/step-00000008.npz \
	    || { echo "no step-8 checkpoint to serve"; exit 1; }
	$(CPU_MESH) python -m shallowspeed_tpu.serving.bench_serving --fleet 3 \
	    --data-dir /tmp/fleet/data --global-batch-size 32 \
	    --checkpoint /tmp/fleet/ck/step-00000008.npz \
	    --reload-dir /tmp/fleet/ck --kill-after 15 \
	    --aot-cache /tmp/fleet/aot \
	    --requests 120 --rates 300 --slo-ms 2000 --seed 0 \
	    --fleet-out /tmp/fleet/FLEET_CHAOS.json \
	    --metrics-out /tmp/fleet/fleet.jsonl
	python -c "import json,sys; rec=json.load(open('/tmp/fleet/FLEET_CHAOS.json')); assert rec['bench']=='serving_fleet_chaos'; assert rec['silently_lost']==[], 'LOST '+str(rec['silently_lost']); assert rec['parity_mismatches']==0, 'parity mismatches'; assert rec['killed_replica'] is not None and rec['replicas_dead']>=1, 'SIGKILL never fired'; assert rec['failovers']>=1 or rec['killed_inflight']==0, 'kill destroyed in-flight work but no failover ran'; assert rec['scale_ups']==1 and rec['scale_up_s'] is not None, 'no measured scale-up'; assert rec['initial_ready_s_mean'] is not None, 'no cold ready baseline'; assert rec['recovery_s'] is not None, 'no measured recovery'; assert not rec['degraded_at_exit'], 'fleet degraded at exit'; v=rec['verdicts']; assert v.get('ok',0)>0, 'nothing served'; print('fleet chaos: %d submitted, verdicts %s, availability %.1f%%, kill stall %.1f ms, cache-warm replacement ready in %.2f s (initial cache-writing replicas: %.2f s mean)' % (rec['submitted'], v, 100*rec['availability'], 1e3*rec['kill_stall_s'], rec['scale_up_s'], rec['initial_ready_s_mean']))"
	ls /tmp/fleet/fleet.jsonl.r0 /tmp/fleet/fleet.jsonl.r1 \
	    /tmp/fleet/fleet.jsonl.r2 > /dev/null
	python -m shallowspeed_tpu.observability.report '/tmp/fleet/fleet.jsonl*' \
	    --format md --slo-ms 2000 > /tmp/fleet/report.md
	grep -q "## Fleet" /tmp/fleet/report.md
	grep -q "SIGKILL injected" /tmp/fleet/report.md
	grep -q "failover: " /tmp/fleet/report.md
	grep -q "elasticity: 1 scale-up(s)" /tmp/fleet/report.md
	grep -q "availability" /tmp/fleet/report.md
	$(CPU_MESH) python -m shallowspeed_tpu.serving --fleet 2 \
	    --data-dir /tmp/fleet/data --global-batch-size 32 \
	    --checkpoint /tmp/fleet/ck/step-00000008.npz \
	    --requests 60 --rate 300 --seed 0 --slo-ms 2000 --verify \
	    --metrics-out /tmp/fleet/serve_fleet.jsonl
	@echo "fleet-smoke OK: 3-replica fleet survived a mid-soak SIGKILL — zero lost, worker-verified parity, failover + measured scale-up recovery, Fleet section rendered"

# distributed request tracing end-to-end (docs/observability.md § Tracing):
# a 2-replica fleet soak under seeded Poisson load with one injected
# SIGKILL — every terminal request must leave a COMPLETE, clock-aligned
# span chain across the parent + .r{replica_id} shards (zero
# orphan/unclosed chains: the soak record's trace_problems field and an
# independent strict re-verification both gate it), and the report CLI
# must render the Tracing section (aggregate + p99-conditional phase
# attribution, per-replica clock alignment with uncertainty, worst-k
# request waterfalls). Then the measured op-issue roofline: a 1-epoch
# gpipe-pp4 training run with --dispatch-probe must leave a
# dispatch_overhead bench record (measured share + provenance — the
# number docs/performance.md's CPU caveats cite) and the report must
# render its row. Exit 0.
trace-smoke:
	rm -rf /tmp/tsmoke; mkdir -p /tmp/tsmoke
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/tsmoke/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	$(CPU_MESH) python -m shallowspeed_tpu.serving.bench_serving --fleet 2 \
	    --data-dir /tmp/tsmoke/data --global-batch-size 32 \
	    --kill-after 10 --requests 80 --rates 300 --slo-ms 2000 --seed 0 \
	    --fleet-out /tmp/tsmoke/FLEET_TRACE.json \
	    --metrics-out /tmp/tsmoke/trace.jsonl
	python -c "import json; rec=json.load(open('/tmp/tsmoke/FLEET_TRACE.json')); assert rec['silently_lost']==[], 'LOST '+str(rec['silently_lost']); assert rec['killed_replica'] is not None, 'SIGKILL never fired'; assert rec['trace_chains'] and rec['trace_chains']>0, 'no span chains recorded'; assert rec['trace_problems']==[], 'INCOMPLETE CHAINS: %s' % rec['trace_problems'][:5]; print('soak record: %d span chains, zero orphan/unclosed across the kill' % rec['trace_chains'])"
	python -c "from shallowspeed_tpu.observability.metrics import read_jsonl; from shallowspeed_tpu.observability import tracing; recs=read_jsonl('/tmp/tsmoke/trace.jsonl*'); chains=tracing.assemble_chains(recs); tracing.verify_terminal_chains(recs, chains, strict=True); offs=tracing.clock_offsets(recs); assert set(offs), 'no clock_offset records'; fo=[c for c in chains.values() if any(s['name']=='failover.requeue' for s in c.spans)]; att=tracing.attribution(chains, slo_ms=2000); assert att and att['phases_mean'], 'no attribution'; print('strict re-verify: %d chains complete, %d replicas aligned (max +/-%.2f ms), %d failover-linked chain(s)' % (len(chains), len(offs), 1e3*max(o['uncertainty_s'] for o in offs.values()), len(fo)))"
	python -m shallowspeed_tpu.observability.report '/tmp/tsmoke/trace.jsonl*' \
	    --format md --slo-ms 2000 > /tmp/tsmoke/trace.report.md
	grep -q "## Tracing" /tmp/tsmoke/trace.report.md
	grep -q "all terminal requests traced end to end" /tmp/tsmoke/trace.report.md
	grep -q "clock alignment: " /tmp/tsmoke/trace.report.md
	grep -q "phase attribution (mean): " /tmp/tsmoke/trace.report.md
	grep -q "p99-conditional" /tmp/tsmoke/trace.report.md
	grep -q "slowest requests:" /tmp/tsmoke/trace.report.md
	$(CPU_MESH) python train.py --data-dir /tmp/tsmoke/data --epochs 1 \
	    --global-batch-size 32 --no-eval --pp 4 --schedule gpipe --mubatches 4 \
	    --dispatch-probe --dispatch-probe-out /tmp/tsmoke/DISPATCH.json \
	    --metrics-out /tmp/tsmoke/train.jsonl
	python -c "import json; rec=json.load(open('/tmp/tsmoke/DISPATCH.json')); assert rec['bench']=='dispatch_overhead' and rec['bench_version']==1; v=rec['value']; assert v is not None and 0.0 <= v < 1.0, 'unmeasured share %r' % v; assert rec['op_events']>0 and rec['provenance'], 'no measurement evidence'; print('dispatch-overhead record: %.1f%% of epoch wall is host-side op issue (%d op events, %s)' % (100*v, rec['op_events'], rec['op_source']))"
	python -m shallowspeed_tpu.observability.report /tmp/tsmoke/train.jsonl \
	    --format md > /tmp/tsmoke/train.report.md
	grep -q "dispatch overhead" /tmp/tsmoke/train.report.md
	@echo "trace-smoke OK: 2-replica kill-injected soak left a complete clock-aligned span chain for every terminal request, Tracing attribution + waterfalls rendered, measured dispatch-overhead record written"

# capacity scoreboard end-to-end (docs/serving.md "Autoscaling & the
# capacity scoreboard", ROADMAP item 4): measure the single-replica
# saturation knee with the SAME engine knobs the autoscaler is armed with
# (--max-slots 4 --dispatch-floor-ms 40 — on this 1-core CPU host the
# service-time floor is what makes fleet capacity scale with replica
# count; on accelerators the model forward provides the floor natively),
# then replay ONE seeded compressed-diurnal trace (flash-crowd spike
# included) three ways — static fleet, autoscaled, autoscaled + SIGKILL
# chaos — and score every leg against the offline oracle. bench_replay
# itself exits 1 if any scoreboard verdict fails (autoscaled must beat
# static on BOTH SLO-violation minutes and wasted replica-hours, chaos
# must flap zero times); on top the target asserts the flash crowd
# provoked a scale_out inside the spike window, the trough a scale_in,
# the report CLI renders the Capacity section with the flap count, and
# the watch CLI folds the fleet size + latest autoscale decision. Exit 0.
replay-smoke:
	rm -rf /tmp/rpsmoke; mkdir -p /tmp/rpsmoke
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/rpsmoke/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',2048),('val',256))]"
	$(CPU_MESH) python -m shallowspeed_tpu.serving.bench_serving --dp 1 \
	    --data-dir /tmp/rpsmoke/data --global-batch-size 32 \
	    --rates 40,80,120,160,240 --requests 120 --seed 0 --slo-ms 250 \
	    --max-slots 4 --dispatch-floor-ms 40 --out /tmp/rpsmoke/sweep.json
	python -c "import json; rec=json.load(open('/tmp/rpsmoke/sweep.json')); assert rec['knee_rps'] is not None, 'sweep found no saturation knee'; print('sweep: knee at %s rps/replica' % rec['knee_rps'])"
	$(CPU_MESH) python -m shallowspeed_tpu.serving.bench_replay \
	    --data-dir /tmp/rpsmoke/data --global-batch-size 32 \
	    --max-slots 4 --dispatch-floor-ms 40 --aot-cache /tmp/rpsmoke/aot \
	    --knee-from /tmp/rpsmoke/sweep.json --day-s 40 \
	    --out /tmp/rpsmoke/AUTOSCALE_r01.json \
	    --metrics-out /tmp/rpsmoke/replay.jsonl
	python -c "import json; rec=json.load(open('/tmp/rpsmoke/AUTOSCALE_r01.json')); assert rec['bench']=='autoscale_scoreboard'; assert all(rec['verdicts'].values()), 'verdicts failed: %s' % [k for k,ok in rec['verdicts'].items() if not ok]; spike=rec['config']['trace']['spikes'][0]; a=rec['legs']['autoscaled']['decisions']; outs=[d for d in a if d['decision']=='scale_out']; ins=[d for d in a if d['decision']=='scale_in']; assert outs and ins, 'autoscaled leg missing scale_out/scale_in'; hit=[d for d in outs if spike['start']-2.0 <= d['t'] <= spike['start']+spike['duration']+2.0]; assert hit, 'no scale_out inside the flash-crowd window %r (outs at %r)' % (spike, [d['t'] for d in outs]); assert rec['legs']['chaos']['flaps']==0, 'chaos leg flapped'; print('scoreboard: flash crowd at t=%.1fs answered by scale_out at t=%.1fs, %d scale_in(s) on slack, chaos flaps=0' % (spike['start'], hit[0]['t'], len(ins)))"
	python -m shallowspeed_tpu.observability.report '/tmp/rpsmoke/replay.jsonl*' \
	    --format md --slo-ms 250 > /tmp/rpsmoke/report.md
	grep -q "## Capacity" /tmp/rpsmoke/report.md
	grep -q "flap count: 0" /tmp/rpsmoke/report.md
	python -m shallowspeed_tpu.observability.watch '/tmp/rpsmoke/replay.jsonl*' \
	    --once > /tmp/rpsmoke/watch.out
	grep -q "fleet: " /tmp/rpsmoke/watch.out
	@echo "replay-smoke OK: one seeded diurnal trace, three legs — every verdict true (autoscaled beat the static fleet on violation minutes AND wasted replica-hours), spike-window scale_out + slack scale_in, zero chaos flaps, Capacity section + watch fleet line rendered"

# MPMD runtime end-to-end (ROADMAP item 1, docs/performance.md "The MPMD
# runtime"): gpipe-pp4 + pipedream-pp4 + interleaved-pp2xV2 epochs under
# --runtime mpmd --audit — final weights HASH-EQUAL to the lockstep twin
# on every layout, the deadlock proof consulted before dispatch
# (static_analysis record, deadlock pass), every per-stage program's
# census clean (xla_audit mpmd_stage_program records, zero mismatches,
# no collective-permute), and the measured dispatch-probe row rendered
# by the report CLI
mpmd-smoke:
	rm -rf /tmp/msmoke; mkdir -p /tmp/msmoke
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/msmoke/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	set -e; for lay in gpipe pipedream interleaved; do \
	  if [ $$lay = interleaved ]; then \
	    LFLAGS="--pp 2 --schedule interleaved --virtual-stages 2 --mubatches 4"; \
	  else LFLAGS="--pp 4 --schedule $$lay --mubatches 4"; fi; \
	  COMMON="--data-dir /tmp/msmoke/data --epochs 2 --global-batch-size 32 --no-eval"; \
	  $(CPU_MESH) python train.py $$COMMON $$LFLAGS \
	      > /tmp/msmoke/$$lay.lock.out; \
	  if [ $$lay = gpipe ]; then PROBE="--dispatch-probe --dispatch-probe-out /tmp/msmoke/DISPATCH_MPMD.json"; \
	  else PROBE=""; fi; \
	  $(CPU_MESH) python train.py $$COMMON $$LFLAGS --runtime mpmd --audit \
	      --metrics-out /tmp/msmoke/$$lay.mpmd.jsonl $$PROBE \
	      > /tmp/msmoke/$$lay.mpmd.out; \
	  lock_h=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/msmoke/$$lay.lock.out); \
	  mpmd_h=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/msmoke/$$lay.mpmd.out); \
	  test -n "$$lock_h" && test "$$lock_h" = "$$mpmd_h" \
	      || { echo "$$lay: HASH MISMATCH mpmd [$$mpmd_h] vs lockstep [$$lock_h]"; exit 1; }; \
	  echo "$$lay: mpmd hash == lockstep twin hash"; \
	  python -c "import json,sys; lay='$$lay'; recs=[json.loads(l) for l in open('/tmp/msmoke/'+lay+'.mpmd.jsonl')]; sa=[r for r in recs if r.get('kind')=='static_analysis' and 'deadlock' in (r.get('passes') or [])]; assert sa and all(r.get('findings')==0 for r in sa), lay+': deadlock proof missing or found findings'; audits=[r for r in recs if r.get('kind')=='xla_audit' and r.get('name')=='mpmd_stage_program']; assert len(audits) >= 8, lay+': only %d stage-program audits' % len(audits); bad=[r for r in audits if r.get('census_ok') is not True]; assert not bad, lay+': census mismatches %r' % [b.get('mismatches') for b in bad][:3]; perm=[r for r in audits if (r.get('census') or {}).get('collective_permute',{}).get('count',0)]; assert not perm, lay+': a stage program lowered a collective-permute'; print(lay+': deadlock proof consulted, %d stage programs census-clean, zero relays in-program' % len(audits))"; \
	done
	python -c "import json; rec=json.load(open('/tmp/msmoke/DISPATCH_MPMD.json')); assert rec['bench']=='dispatch_overhead'; v=rec['value']; assert v is not None and 0.0 <= v < 1.0, 'unmeasured share %r' % v; assert rec.get('runtime')=='mpmd' and rec['op_events']>0; print('mpmd dispatch-overhead record: %.1f%% of epoch wall is host-side op issue (%d op events)' % (100*v, rec['op_events']))"
	python -m shallowspeed_tpu.observability.report /tmp/msmoke/gpipe.mpmd.jsonl \
	    --format md > /tmp/msmoke/gpipe.report.md
	grep -q "dispatch overhead" /tmp/msmoke/gpipe.report.md
	@echo "mpmd-smoke OK: three schedules hash-equal to lockstep twins under --runtime mpmd --audit, deadlock proof consulted, per-stage census clean, dispatch-probe row rendered"

# activation recompute end-to-end (docs/lowering.md "Recompute ticks"):
# 1 CPU epoch each for gpipe-pp4 and the split-backward pipedream-pp4
# with --recompute --audit vs their stashed twins — final hashes BITWISE
# equal (recompute is a memory knob, not a numerics knob), census clean,
# the pipeline_program record's measured stash peak strictly below the
# stashed twin's, the tick-table lifetime proof re-run standalone, and
# the report CLI's Memory section rendering the two peaks side by side
recompute-smoke:
	rm -rf /tmp/recsmoke; mkdir -p /tmp/recsmoke
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/recsmoke/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	set -e; for lay in gpipe pipedream; do \
	  if [ $$lay = pipedream ]; then SPLIT="--backward-split"; else SPLIT=""; fi; \
	  COMMON="--data-dir /tmp/recsmoke/data --epochs 1 --global-batch-size 32 --no-eval --pp 4 --mubatches 4 --schedule $$lay"; \
	  $(CPU_MESH) python train.py $$COMMON $$SPLIT \
	      > /tmp/recsmoke/$$lay.stashed.out; \
	  $(CPU_MESH) python train.py $$COMMON $$SPLIT --recompute --audit \
	      --metrics-out /tmp/recsmoke/$$lay.rec.jsonl \
	      > /tmp/recsmoke/$$lay.rec.out; \
	  st_h=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/recsmoke/$$lay.stashed.out); \
	  rec_h=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/recsmoke/$$lay.rec.out); \
	  test -n "$$st_h" && test "$$st_h" = "$$rec_h" \
	      || { echo "$$lay: HASH MISMATCH recompute [$$rec_h] vs stashed [$$st_h]"; exit 1; }; \
	  echo "$$lay: recompute hash == stashed twin hash"; \
	  python -c "import json,sys; lay='$$lay'; recs=[json.loads(l) for l in open('/tmp/recsmoke/'+lay+'.rec.jsonl')]; a=[r for r in recs if r.get('kind')=='xla_audit']; assert a and all(r.get('census_ok') for r in a), lay+': census mismatch'; prog=[r for r in recs if r.get('kind')=='event' and r.get('name')=='pipeline_program'][-1]; assert prog['recompute'], lay+': program not recompute'; peak, twin = prog['stash_bytes_peak'], prog['stash_bytes_peak_stashed_twin']; assert peak < twin, lay+': stash peak %d not below stashed twin %d' % (peak, twin); print(lay+': census clean, stash peak %d B < stashed twin %d B (%.0f%% smaller)' % (peak, twin, 100*(1-peak/twin)))"; \
	  python -m shallowspeed_tpu.observability.report \
	      /tmp/recsmoke/$$lay.rec.jsonl --format md \
	      > /tmp/recsmoke/$$lay.report.md; \
	  grep -q "activation stash" /tmp/recsmoke/$$lay.report.md; \
	done
	python -c "from shallowspeed_tpu import schedules as S; from shallowspeed_tpu.parallel.lowering import lower_schedule; from shallowspeed_tpu.analysis.stash import assert_recompute_peak_drop; [print(n, assert_recompute_peak_drop(lower_schedule(c, 4, 4, backward_split=b), lower_schedule(c, 4, 4, backward_split=b, recompute=True))) for n, c, b in (('gpipe', S.GPipeSchedule, False), ('pipedream-split', S.PipeDreamFlushSchedule, True))]"
	@echo "recompute-smoke OK: recompute hashes bitwise-equal to stashed twins on gpipe + split pipedream, census clean, measured stash peak strictly below the stashed twin's, Memory section rendered"

# ZeRO-2/3 end-to-end: CPU epochs at --zero 2 and --zero 3 with --audit
# (train.py aborts nonzero if the compiled census violates the per-stage
# comms contract — per-tick reduce-scatter, ZeRO-3's JIT gather floor),
# the fixed-layout hash pin (--zero 2 final hash == --zero 1 at
# --mubatches 1: one scatter contribution per shard element, so the
# per-tick psum_scatter value IS the psum chunk), and the report's
# ZeRO-forecast row rendering per-stage headroom + the stage ladder
zero-smoke:
	rm -rf /tmp/zsmoke; mkdir -p /tmp/zsmoke
	python -c "import numpy as np; from pathlib import Path; d=Path('/tmp/zsmoke/data'); d.mkdir(parents=True); rng=np.random.RandomState(0); [(np.save(d/('x_'+s+'.npy'), rng.rand(n,784).astype(np.float32)), np.save(d/('y_'+s+'.npy'), np.eye(10,dtype=np.float32)[rng.randint(0,10,n)])) for s,n in (('train',256),('val',96))]"
	set -e; COMMON="--data-dir /tmp/zsmoke/data --epochs 1 --global-batch-size 32 --no-eval --dp 2 --pp 2 --schedule gpipe --optimizer momentum"; \
	$(CPU_MESH) python train.py $$COMMON --mubatches 1 --zero 1 \
	    > /tmp/zsmoke/z1.out; \
	$(CPU_MESH) python train.py $$COMMON --mubatches 1 --zero 2 \
	    > /tmp/zsmoke/z2pin.out; \
	$(CPU_MESH) python train.py $$COMMON --mubatches 4 --zero 2 --audit \
	    --metrics-out /tmp/zsmoke/z2.jsonl > /tmp/zsmoke/z2.out; \
	$(CPU_MESH) python train.py $$COMMON --mubatches 4 --zero 3 --audit \
	    --metrics-out /tmp/zsmoke/z3.jsonl > /tmp/zsmoke/z3.out; \
	h1=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/zsmoke/z1.out); \
	h2=$$(grep -o 'final model hash: [0-9a-f]*' /tmp/zsmoke/z2pin.out); \
	test -n "$$h1" && test "$$h1" = "$$h2" \
	    || { echo "zero2 HASH MISMATCH [$$h2] vs zero1 [$$h1] at mubatches=1"; exit 1; }; \
	echo "zero2 hash == zero1 hash at the fixed layout (mubatches=1)"; \
	for f in /tmp/zsmoke/z2 /tmp/zsmoke/z3; do \
	  python -c "import json,sys; p=sys.argv[1]; recs=[json.loads(l) for l in open(p) if l.strip()]; a=[r for r in recs if r.get('kind')=='xla_audit']; assert a, p+': no xla_audit record'; assert all(r.get('census_ok') for r in a), p+': census mismatch'; exp=[r for r in a if r.get('name')=='epoch_program'][-1]['expected']; zf=exp['zero_forecast']['stages']; assert zf['2']['total_bytes'] < zf['1']['total_bytes'], p+': stage-2 forecast not below stage-1'; dp=exp['axes']['dp']; assert dp['scatter_schedule']=='per_tick', p+': no per-tick scatter schedule'; print(p+': census clean, zero stage '+str(dp['zero'])+' per-tick scatter contract enforced')" $$f.jsonl; \
	  python -m shallowspeed_tpu.observability.report $$f.jsonl --format md \
	      > $$f.report.md; \
	  grep -q "ZeRO forecast" $$f.report.md; \
	  grep -q "headroom" $$f.report.md; \
	  grep -q "stage ladder" $$f.report.md; \
	done
	grep -q "ZeRO stage 2" /tmp/zsmoke/z2.report.md
	grep -q "JIT param gather" /tmp/zsmoke/z3.report.md
	@echo "zero-smoke OK: zero2/zero3 census clean, mubatches=1 hash pin holds, ZeRO forecast + stage ladder + per-stage comms rendered"

# the ZeRO memory scoreboard (same-window zero1/zero2/zero3 epochs on the
# compute-bound flagship zoo model at dp2 and dp2 x pp2, measured
# peak_hbm_bytes ladder + analytical forecast + the mubatches=1 hash
# pin) — writes ZERO_r01.json at the repo root
bench-zero:
	$(CPU_MESH) python scripts/bench_zero.py

# the MPMD-vs-lockstep scoreboard (same-window epoch pair, dispatch-probe
# pair, serving burst p99) — writes MPMD_r01.json on the flagship data
bench-mpmd:
	$(CPU_MESH) python scripts/bench_mpmd.py

# the full offered-load sweep on the default layouts (see docs/serving.md)
bench-serving:
	$(CPU_MESH) python -m shallowspeed_tpu.serving.bench_serving --dp 2 \
	    --slo-ms 100
	$(CPU_MESH) python -m shallowspeed_tpu.serving.bench_serving --pp 4 \
	    --schedule gpipe --slo-ms 100

data:
	python prepare_data.py

train:
	python train.py --epochs 5

train-mesh:
	$(CPU_MESH) python train.py --dp 2 --pp 4 --schedule gpipe --epochs 2

bench:
	python bench.py

bench-scaling:
	$(CPU_MESH) python scripts/bench_scaling.py

# the two production-path-stall scoreboards (PR 12): step-time checkpoint
# overhead sync vs async (same-window interleaved legs), and fleet
# scale_up_s cold vs aot-cache-warm — writes CKPT_AOT_r01.json
bench-ckpt-aot:
	$(CPU_MESH) python scripts/bench_ckpt_aot.py

bench-matrix:
	python scripts/bench_tpu_matrix.py

# one-shot full TPU measurement (baseline, unroll sweeps at both precision
# classes, interleaved matrix + full-epoch pallas/xla cells, convergence,
# profiler trace) — run when the chip is healthy
tpu-capture:
	python scripts/tpu_capture.py

# bank only the tier-0 verdict cells (headline pair + kernel ladder +
# equality probes) — for a chip window too short for the full matrix
tpu-capture-tier0:
	python scripts/tpu_capture.py --tier0-only

# unattended: probe the tunnel every 10 min, run the resumable capture on
# the first healthy probe (see scripts/tunnel_watch.sh)
tpu-watch:
	bash scripts/tunnel_watch.sh

# the convergence-equivalence experiment behind the default-precision
# bench headline (20-epoch run at --precision default + same-window pair)
tpu-default-precision:
	python scripts/tpu_default_precision.py

schedules:
	$(CPU_MESH) python scripts/show_schedule.py --all

clean:
	rm -rf .pytest_cache */__pycache__ __pycache__ tests/__pycache__
