"""Device-mesh construction: the TPU replacement for the reference's two MPI
communicators (train.py:87-94 — dp_comm = Split(rank % PP), pp_comm =
Split(rank // PP)).

A 2-D ``jax.sharding.Mesh`` with axes ``('dp', 'pp')`` expresses the same
grid: rows are model replicas (the pp_comm groups), columns are same-stage
ranks across replicas (the dp_comm groups). Collectives over axis 'dp' =
Iallreduce over dp_comm; ppermute over axis 'pp' = the stage-relay Send/Recv
pairs. On a real slice the mesh rides ICI; on CPU tests it rides the
host-emulated devices from --xla_force_host_platform_device_count.
"""

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(dp: int, pp: int, devices=None) -> Mesh:
    """2-D (dp, pp) mesh. When devices aren't pinned explicitly, use JAX's
    topology-aware placement (jax.experimental.mesh_utils) so that on a real
    slice the ``pp`` neighbors — which exchange a ppermute payload every
    pipeline tick — sit on adjacent ICI links, and ``dp`` (one psum per
    batch) takes the outer dimension."""
    explicit = devices is not None
    if devices is None:
        devices = jax.devices()
    if dp * pp > len(devices):
        raise ValueError(
            f"need {dp * pp} devices for DP={dp} x PP={pp}, have {len(devices)}"
        )
    if not explicit and dp * pp == len(devices):
        try:
            from jax.experimental import mesh_utils

            return Mesh(mesh_utils.create_device_mesh((dp, pp)), ("dp", "pp"))
        except Exception:
            pass  # fall through to the order-preserving layout
    grid = np.asarray(devices[: dp * pp]).reshape(dp, pp)
    return Mesh(grid, ("dp", "pp"))
