"""Serving subsystem tests: slot geometry, ladder-bounded predict, engine
bitwise parity, loadgen determinism, the latency bench, and the report's
Serving section (docs/serving.md)."""

import json

import numpy as np
import pytest

from shallowspeed_tpu.api import TrainingSession
from shallowspeed_tpu.serving import slots as serving_slots
from shallowspeed_tpu.serving.engine import ServingEngine
from shallowspeed_tpu.serving import bench_serving, loadgen

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
N, GBS = 512, 64


@pytest.fixture()
def data_dir(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", N), ("val", 128)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)
    return tmp_path


def _session(data_dir, **kw):
    kw.setdefault("sizes", SIZES)
    kw.setdefault("global_batch_size", GBS)
    kw.setdefault("lr", 0.01)
    return TrainingSession(data_dir=data_dir, **kw)


# ---------------------------------------------------------------------------
# slot geometry
# ---------------------------------------------------------------------------


def test_slot_helpers():
    assert serving_slots.default_slot_rows(1) == 8
    assert serving_slots.default_slot_rows(2) == 8
    assert serving_slots.default_slot_rows(3) == 9  # dp multiple
    assert serving_slots.slots_needed(1, 8) == 1
    assert serving_slots.slots_needed(8, 8) == 1
    assert serving_slots.slots_needed(9, 8) == 2
    ladder = serving_slots.validate_ladder((1, 2, 4))
    assert serving_slots.rung_for(1, ladder) == 1
    assert serving_slots.rung_for(3, ladder) == 4
    with pytest.raises(ValueError, match="top rung"):
        serving_slots.rung_for(5, ladder)
    with pytest.raises(ValueError, match="increasing"):
        serving_slots.validate_ladder((2, 2))
    with pytest.raises(ValueError, match="at least one row"):
        serving_slots.slots_needed(0, 8)


def test_pack_unpack_roundtrip():
    rng = np.random.RandomState(3)
    for dp in (1, 2, 4):
        slots = rng.randn(3, 8, 5).astype(np.float32)
        packed = serving_slots.pack_slots(slots, dp)
        assert packed.shape == (24, 5)
        back = serving_slots.unpack_slots(packed, 3, dp)
        np.testing.assert_array_equal(back.reshape(3, 8, 5), slots)
    # the executor mapping: replica r's contiguous block holds rows
    # [r*S/dp:(r+1)*S/dp) of every slot in slot order
    slots = np.arange(2 * 4 * 1, dtype=np.float32).reshape(2, 4, 1)
    packed = serving_slots.pack_slots(slots, 2)
    assert packed[:, 0].tolist() == [0, 1, 4, 5, 2, 3, 6, 7]


# ---------------------------------------------------------------------------
# ladder-bounded predict + eval routing (satellites 1 and 2)
# ---------------------------------------------------------------------------


def test_predict_cache_bounded_by_ladder(data_dir):
    """Repeated odd-sized predict() calls compile at most len(ladder)
    programs — the fix for the unbounded per-row-count cache."""
    run = _session(data_dir, dp=2, pp=2, schedule="gpipe")
    rng = np.random.RandomState(7)
    for n in (1, 3, 5, 7, 9, 13, 17, 31, 33, 50, 63, 100, 129, 200):
        p = run.predict(rng.randn(n, SIZES[0]).astype(np.float32))
        assert p.shape == (n, SIZES[-1])
        np.testing.assert_allclose(p.sum(1), 1.0, rtol=1e-4)
    assert len(run._predict_cache) <= len(run.slot_ladder)
    # every cached key is a ladder rung, never a raw row count
    assert set(run._predict_cache) <= set(run.slot_ladder)


def test_predict_slot_aligned_stability(data_dir):
    """A slot's rows compute bitwise-identically whatever batch rides
    around them — the property the engine's parity contract rests on
    (slot-ALIGNED prefixes only: requests never share a slot)."""
    run = _session(data_dir, dp=2, pp=2, schedule="gpipe")
    rng = np.random.RandomState(11)
    S = run.slot_rows
    x = rng.randn(4 * S, SIZES[0]).astype(np.float32)
    whole = run.predict(x)
    np.testing.assert_array_equal(whole[:S], run.predict(x[:S]))
    np.testing.assert_array_equal(whole[: 2 * S], run.predict(x[: 2 * S]))
    # determinism of the same call
    np.testing.assert_array_equal(whole, run.predict(x))


def test_mesh_accuracy_routed_through_serving_path_unchanged(data_dir, tmp_path):
    """Mesh eval flows through the SAME ladder slot programs serving uses,
    and the accuracy value is unchanged vs the sequential reference on
    identical weights."""
    seq = _session(data_dir)
    seq.train_epoch()
    ck = tmp_path / "eval.npz"
    seq.save(ck)
    mesh = _session(data_dir, dp=2, pp=2, schedule="gpipe", resume=ck)
    assert mesh.model_hash() == seq.model_hash()
    assert mesh.accuracy() == seq.accuracy()
    # eval populated the predict cache with ladder rungs only — the shared
    # compiled path, not a whole-split one-off program
    assert set(mesh._predict_cache) <= set(mesh.slot_ladder)


def test_predict_slot_rows_validation(data_dir):
    with pytest.raises(ValueError, match="multiple of dp"):
        _session(data_dir, dp=2, predict_slot_rows=9)
    with pytest.raises(ValueError, match="increasing"):
        _session(data_dir, predict_slot_ladder=(4, 2))


# ---------------------------------------------------------------------------
# engine: continuous batching + bitwise parity
# ---------------------------------------------------------------------------


def test_engine_bitwise_equals_direct_predict(data_dir):
    """The acceptance contract: every response under packed continuous
    batching is bitwise-equal to a direct predict() of the same rows."""
    run = _session(data_dir, dp=2, pp=2, schedule="gpipe")
    eng = ServingEngine(run, slo_ms=10_000)
    rng = np.random.RandomState(5)
    payloads = [
        rng.randn(rows, SIZES[0]).astype(np.float32)
        for rows in (1, 3, 8, 9, 2, 17, 5, 4, 16, 7, 1, 33)
    ]
    for p in payloads:
        eng.submit(p)
    done = eng.drain()
    assert [r.id for r in done] == list(range(len(payloads)))  # FIFO
    for req in done:
        assert req.verdict == "ok"
        np.testing.assert_array_equal(req.result, run.predict(payloads[req.id]))
        assert req.enqueue_t <= req.dispatch_t <= req.complete_t
        assert req.latency_s >= req.queue_s >= 0
        assert req.slo_ok(10_000) is True


@pytest.mark.slow  # 1-core wall budget; make tp-smoke + serve-smoke drives this end to end
def test_engine_serves_tensor_parallel_layout(data_dir):
    """Serving under TP (satellite of the tp lattice): the rung programs
    route through the Megatron-sharded layers — strict audit enforces the
    forward-only contract (per-layer-pair tp all-reduces required,
    gradient collectives forbidden) before the first response, and every
    response stays bitwise-equal to a direct predict() of the same rows."""
    run = _session(data_dir, dp=2, tp=2, audit=True)
    eng = ServingEngine(run, slo_ms=10_000)
    rng = np.random.RandomState(6)
    payloads = [
        rng.randn(rows, SIZES[0]).astype(np.float32) for rows in (1, 9, 4, 17)
    ]
    for p in payloads:
        eng.submit(p)
    done = eng.drain()
    assert [r.verdict for r in done] == ["ok"] * len(payloads)
    for req in done:
        np.testing.assert_array_equal(req.result, run.predict(payloads[req.id]))


def test_engine_packing_capacity_and_accounting(data_dir):
    run = _session(data_dir, dp=2)  # pp=1: cheap programs
    S = run.slot_rows
    eng = ServingEngine(run, max_slots=4)
    rng = np.random.RandomState(9)
    for rows in (2 * S, S, 2 * S):  # 2 + 1 + 2 slots
        eng.submit(rng.randn(rows, SIZES[0]).astype(np.float32))
    first = eng.step()
    # 2+1 slots fit; adding the third request's 2 would exceed max_slots=4
    assert [r.id for r in first] == [0, 1]
    assert eng.queue_depth == 1
    second = eng.step()
    assert [r.id for r in second] == [2]
    st = eng.stats()
    assert st["completed"] == 3 and st["dispatches"] == 2
    # dispatch 1: 3 slots -> rung 4; dispatch 2: 2 slots -> rung 2
    assert st["slots_dispatched"] == 6
    assert st["useful_rows"] == 5 * S
    assert st["padding_waste"] == pytest.approx(1 - 5 / 6)
    assert st["queue_depth_max"] >= 2
    # oversized and malformed submissions are refused loudly
    with pytest.raises(ValueError, match="split it"):
        eng.submit(rng.randn(5 * S, SIZES[0]).astype(np.float32))
    with pytest.raises(ValueError, match="rows >= 1"):
        eng.submit(np.zeros((0, SIZES[0]), np.float32))
    # a packing capacity above the top rung has no program to dispatch on
    # — refused at configure time, not mid-traffic
    with pytest.raises(ValueError, match="top rung"):
        ServingEngine(run, max_slots=run.slot_ladder[-1] + 1)


def test_engine_admission_drop_and_sequential_parity(data_dir):
    """max_queue bounds admission (drops recorded, never silent), and the
    engine serves sequential sessions with the same parity contract."""
    run = _session(data_dir)  # sequential layout
    eng = ServingEngine(run, max_queue=2)
    rng = np.random.RandomState(13)
    payloads = [rng.randn(n, SIZES[0]).astype(np.float32) for n in (3, 1, 4)]
    reqs = [eng.submit(p) for p in payloads]
    assert [r.verdict for r in reqs] == ["queued", "queued", "dropped"]
    done = eng.drain()
    assert len(done) == 2
    for req in done:
        np.testing.assert_array_equal(req.result, run.predict(payloads[req.id]))
    st = eng.stats()
    assert st["dropped"] == 1 and st["completed"] == 2
    # sequential dispatches run only the OCCUPIED slots (no rung program
    # to round up to), so the padding accounting must not charge the rung
    # tail: 3 single-slot requests dispatch 3 slots, not rung_for(3)=4
    eng2 = ServingEngine(run)
    for p in payloads:
        eng2.submit(p)
    eng2.drain()
    st2 = eng2.stats()
    assert st2["dispatches"] == 1 and st2["slots_dispatched"] == 3
    S = run.slot_rows
    assert st2["padding_waste"] == pytest.approx(1 - (3 + 1 + 4) / (3 * S))
    # a long-lived engine keeps only scalar samples: completed Requests
    # (payloads + result arrays) belong to the caller, never the engine
    from collections import deque as _deque

    from shallowspeed_tpu.serving.engine import Request

    for v in vars(eng2).values():
        if isinstance(v, (list, _deque)):
            assert not any(isinstance(o, Request) for o in v)


def test_engine_emits_v5_records_and_queue_gauge(data_dir, tmp_path):
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    path = tmp_path / "serve.jsonl"
    m = JsonlMetrics(path)
    run = _session(data_dir, dp=2, metrics=m)
    eng = ServingEngine(run, slo_ms=10_000, metrics=m)
    rng = np.random.RandomState(1)
    for n in (1, 5, 9):
        eng.submit(rng.randn(n, SIZES[0]).astype(np.float32))
    eng.drain()
    eng.record_summary(offered_rps=123.0)
    m.close()
    recs = read_jsonl(path)
    reqs = [r for r in recs if r["kind"] == "request"]
    assert len(reqs) == 3 and all(r["name"] == "ok" for r in reqs)
    for r in reqs:
        assert r["latency_s"] > 0 and r["slots"] >= 1
        assert r["enqueue_ts"] <= r["dispatch_ts"] <= r["complete_ts"]
    summaries = [r for r in recs if r["kind"] == "serving"]
    assert len(summaries) == 1
    s = summaries[0]
    assert s["completed"] == 3 and s["offered_rps"] == 123.0
    assert s["p50_latency_s"] > 0 and s["latency_bound_s"] is not None
    assert any(
        r["kind"] == "gauge" and r["name"] == "serving.queue_depth"
        for r in recs
    )


# ---------------------------------------------------------------------------
# inference program stats + audit contract (satellite 3)
# ---------------------------------------------------------------------------


def test_inference_program_stats_per_rung():
    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.parallel import lower_schedule
    from shallowspeed_tpu.parallel.lowering import (
        program_comm_bytes,
        program_stats,
    )
    from shallowspeed_tpu.parallel.executor import relay_width

    spec = Mo.make_model_spec(SIZES, 4, GBS)
    mb = 8  # slot_rows at dp=1
    for rung in (1, 2, 4, 8):
        prog = lower_schedule(S.InferenceSchedule, rung, 4, training=False)
        st = program_stats(prog)
        assert st["is_training"] is False
        assert st["cells_fwd"] == 4 * rung  # every stage forwards every slot
        assert st["cells_bwd"] == st["cells_bwd_in"] == st["cells_bwd_w"] == 0
        assert st["num_ticks"] == rung + 3  # M + P - 1 relay ticks
        comm = program_comm_bytes(prog, spec, mb)
        assert comm["relay_payload_bytes"] == 4 * mb * relay_width(spec)
        assert (
            comm["wire_bytes_per_device"]
            == 2 * st["num_ticks"] * comm["relay_payload_bytes"]
        )


def test_compiled_serving_census_clean_at_pp4(data_dir, tmp_path):
    """The audit's expected_comms verified clean on COMPILED serving
    programs at pp=4 — and strict audit would have raised before any
    request was served."""
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    path = tmp_path / "audit.jsonl"
    m = JsonlMetrics(path)
    run = _session(
        data_dir, pp=4, schedule="gpipe", metrics=m, audit=True
    )
    rng = np.random.RandomState(2)
    run.predict(rng.randn(3, SIZES[0]).astype(np.float32))  # rung 1
    run.predict(rng.randn(3 * run.slot_rows, SIZES[0]).astype(np.float32))
    m.close()
    audits = [
        r
        for r in read_jsonl(path)
        if r["kind"] == "xla_audit" and r["name"] == "inference_program"
    ]
    assert len(audits) == 2  # one per rung, deduped per compile variant
    for rec in audits:
        assert rec["census_ok"] is True
        assert rec["expected"]["inference"] is True
        # the serving contract: one-direction relay + the preds psum, no
        # gradient-sync collectives
        assert rec["census"]["collective_permute"]["count"] >= 1
        assert rec["census"]["all_reduce"]["count"] >= 1
        assert "reduce_scatter" not in rec["census"]
        assert "all_gather" not in rec["census"]


def test_inference_contract_rejects_training_census():
    """A serving program that lowered a gradient collective fails its
    contract (the deliberate-mismatch leg)."""
    from shallowspeed_tpu import model as Mo
    from shallowspeed_tpu import schedules as S
    from shallowspeed_tpu.observability import program_audit
    from shallowspeed_tpu.parallel import lower_schedule

    spec = Mo.make_model_spec(SIZES, 4, GBS)
    prog = lower_schedule(S.InferenceSchedule, 2, 4, training=False)
    expected = program_audit.expected_comms(
        spec, 1, 4, prog=prog, mubatch_size=8
    )
    assert expected["inference"] is True
    good = {
        "collective_permute": {"count": 1, "bytes": 128},
        "all_reduce": {"count": 1, "bytes": 64},
    }
    assert program_audit.check_census(good, expected) == []
    leaked = dict(good, reduce_scatter={"count": 1, "bytes": 4096})
    assert any(
        "reduce_scatter" in msg
        for msg in program_audit.check_census(leaked, expected)
    )
    # a SECOND all-reduce beyond the preds psum reads as a leaked dp
    # gradient sync (the kind itself is lawful, so the count is the pin)
    doubled = dict(good, all_reduce={"count": 2, "bytes": 128})
    assert any(
        "at most ONE all-reduce" in msg
        for msg in program_audit.check_census(doubled, expected)
    )
    # a training program at the same layout still demands BOTH directions
    tprog = lower_schedule(S.SCHEDULES["gpipe"], 4, 4)
    texp = program_audit.expected_comms(spec, 1, 4, prog=tprog, mubatch_size=8)
    assert any(
        "BOTH directions" in msg
        for msg in program_audit.check_census(
            {"collective_permute": {"count": 1, "bytes": 128}}, texp
        )
    )


def test_inference_latency_bound(data_dir):
    run = _session(data_dir, pp=4, schedule="gpipe")
    bound = run.inference_latency_bound()
    # forward-only single-slot program: weighted makespan == tick count
    assert bound["ticks"] == 4 and bound["weighted_ticks"] == 4.0
    assert bound["seconds"] > 0 and "cpu" in bound["peak_source"]
    seq = _session(data_dir)
    sbound = seq.inference_latency_bound()
    assert sbound["ticks"] is None and sbound["seconds"] > 0


# ---------------------------------------------------------------------------
# loadgen
# ---------------------------------------------------------------------------


def test_loadgen_seeded_and_deterministic():
    a1 = loadgen.poisson_arrivals(100.0, 50, seed=4)
    a2 = loadgen.poisson_arrivals(100.0, 50, seed=4)
    np.testing.assert_array_equal(a1, a2)
    assert len(a1) == 50 and np.all(np.diff(a1) > 0)
    # mean interarrival ~ 1/rate (loose: 50 samples)
    assert 0.3 / 100 < np.diff(a1).mean() < 3.0 / 100
    p1 = loadgen.request_payloads(10, 24, seed=4, rows_choices=(1, 2, 4))
    p2 = loadgen.request_payloads(10, 24, seed=4, rows_choices=(1, 2, 4))
    assert all(np.array_equal(a, b) for a, b in zip(p1, p2))
    assert {p.shape[0] for p in p1} <= {1, 2, 4}
    pool = np.arange(12, dtype=np.float32).reshape(4, 3)
    from_pool = loadgen.request_payloads(5, 3, seed=0, data=pool)
    for p in from_pool:
        assert all(any(np.array_equal(row, r) for r in pool) for row in p)
    with pytest.raises(ValueError):
        loadgen.poisson_arrivals(0, 5)


def test_closed_vs_open_loop_deadline_accounting(data_dir, monkeypatch):
    """Satellite pin (loadgen.py docstrings): the open loop backdates
    enqueue to the SCHEDULED arrival, so deadlines burn against queue
    backlog (coordinated-omission corrected — a backlogged stream sheds /
    misses); the closed loop never backdates, so deadlines score pure
    service latency and the same stream meets them all."""
    run = _session(data_dir)
    orig = run.predict

    def slow_predict(x):
        import time as _t

        _t.sleep(0.02)  # one dispatch >= 20 ms, deterministic ordering
        return orig(x)

    monkeypatch.setattr(run, "predict", slow_predict)
    rng = np.random.RandomState(21)
    payloads = [rng.randn(2, SIZES[0]).astype(np.float32) for _ in range(6)]
    # open loop: all six arrive at t=0 but serve one per dispatch — the
    # tail's deadline (60 ms) is provably dead after three 20 ms dispatches
    eng_open = ServingEngine(run, max_slots=1)
    done_open = loadgen.run_open_loop(
        eng_open, payloads, arrivals=[0.0] * 6, deadline_ms=60.0
    )
    assert len(done_open) == 6
    open_missed = [
        r for r in done_open if r.verdict == "expired" or r.slo_ok() is False
    ]
    assert open_missed, "backlogged open-loop stream must miss deadlines"
    # every request's clock starts at the shared scheduled arrival
    assert len({r.enqueue_t for r in done_open}) == 1
    # closed loop, same stream and deadline: admission waits for a free
    # slot, so each request's 60 ms covers only its own ~20 ms dispatch
    eng_closed = ServingEngine(run, max_slots=1)
    done_closed = loadgen.run_closed_loop(
        eng_closed, payloads, concurrency=1, deadline_ms=60.0
    )
    assert len(done_closed) == 6
    assert all(r.verdict == "ok" and r.slo_ok() is True for r in done_closed)
    # submit-time clocks: strictly increasing, never backdated
    ts = [r.enqueue_t for r in sorted(done_closed, key=lambda r: r.id)]
    assert all(b > a for a, b in zip(ts, ts[1:]))


def test_loadgen_drivers_complete_all(data_dir):
    run = _session(data_dir, dp=2)
    payloads = loadgen.request_payloads(15, SIZES[0], seed=6)
    arrivals = loadgen.poisson_arrivals(2000.0, 15, seed=6)
    eng = ServingEngine(run, slo_ms=10_000)
    done = loadgen.run_open_loop(eng, payloads, arrivals)
    assert len(done) == 15 and eng.queue_depth == 0
    # open loop backdates enqueue to the scheduled arrival
    t0 = min(r.enqueue_t for r in done)
    for req, arr in zip(sorted(done, key=lambda r: r.id), arrivals):
        assert req.enqueue_t == pytest.approx(t0 + arr - arrivals[0], abs=1e-6)
    eng2 = ServingEngine(run)
    seen_depth = []
    orig_step = eng2.step

    def spy_step():
        seen_depth.append(eng2.queue_depth)
        return orig_step()

    eng2.step = spy_step
    done2 = loadgen.run_closed_loop(eng2, payloads, concurrency=3)
    assert len(done2) == 15
    assert max(seen_depth) <= 3  # the fixed in-flight population bound


# ---------------------------------------------------------------------------
# bench_serving
# ---------------------------------------------------------------------------


def test_find_knee():
    rows = [
        {"offered_rps": 50, "p99_latency_s": 0.01, "achieved_rps": 49.0},
        {"offered_rps": 100, "p99_latency_s": 0.2, "achieved_rps": 60.0},
        {"offered_rps": 200, "p99_latency_s": 0.9, "achieved_rps": 61.0},
    ]
    assert bench_serving.find_knee(rows, slo_ms=50.0) == 100  # p99 breach
    assert bench_serving.find_knee(rows, slo_ms=None) == 100  # achieved sag
    assert bench_serving.find_knee(rows[:1], slo_ms=50.0) is None


def test_bench_serving_sweep_record(data_dir):
    run = _session(data_dir, dp=2)
    rec = bench_serving.sweep(
        run, rates=[500.0, 2000.0], n_requests=10, seed=3, slo_ms=10_000
    )
    assert rec["bench"] == "serving" and rec["bench_version"] == 1
    assert rec["config"]["dp"] == 2 and rec["config"]["seed"] == 3
    assert rec["latency_bound_s"] is not None
    assert [row["offered_rps"] for row in rec["sweep"]] == [500.0, 2000.0]
    for row in rec["sweep"]:
        assert row["completed"] == 10 and row["dropped"] == 0
        assert row["p50_latency_s"] > 0 and row["p99_latency_s"] > 0
        assert row["queue_depth_max"] >= 0
        assert 0 <= row["padding_waste"] < 1
    json.dumps(rec)  # the record is strict-JSON-able as published


# ---------------------------------------------------------------------------
# serve CLI + report Serving section
# ---------------------------------------------------------------------------


def test_serve_cli_verify_and_report_section(data_dir, tmp_path, capsys):
    """The serve entry point end-to-end, in-process: seeded Poisson load on
    dp=2 with --verify (bitwise parity) and --audit, schema-v5 records in
    the JSONL, and the report CLI rendering the Serving section with an
    SLO verdict — the make serve-smoke contract in miniature."""
    from shallowspeed_tpu.observability import read_jsonl
    from shallowspeed_tpu.observability.report import main as report_main
    from shallowspeed_tpu.serving.__main__ import main as serve_main

    out = tmp_path / "serve.jsonl"
    rc = serve_main(
        [
            "--dp", "2", "--schedule", "gpipe",
            "--global-batch-size", str(GBS),
            "--data-dir", str(data_dir),
            "--requests", "12", "--rate", "2000", "--seed", "0",
            "--slo-ms", "10000", "--verify", "--audit",
            "--slot-ladder", "1,2,4",
            "--metrics-out", str(out),
        ]
    )
    assert rc == 0
    text = capsys.readouterr().out
    assert "12/12 responses bitwise-equal" in text
    recs = read_jsonl(out)
    reqs = [r for r in recs if r["kind"] == "request"]
    assert len(reqs) == 12 and all(r["name"] == "ok" for r in reqs)
    assert [r for r in recs if r["kind"] == "serving"]
    audits = [r for r in recs if r["kind"] == "xla_audit"]
    assert audits and all(r["census_ok"] for r in audits)
    rc = report_main([str(out), "--format", "md", "--slo-ms", "10000"])
    assert rc == 0
    rendered = capsys.readouterr().out
    assert "## Serving" in rendered
    assert "SLO MET" in rendered
    assert "model floor" in rendered


def test_report_serving_section_from_requests_only(tmp_path, capsys):
    """A killed run's request records alone still render the section
    (percentiles recomputed), and the SLO verdict flips with --slo-ms."""
    from shallowspeed_tpu.observability.report import build_report, render

    recs = [
        {
            "v": 5, "ts": 0.0, "kind": "request", "name": "ok", "id": i,
            "rows": 2, "slots": 1, "latency_s": 0.010 + 0.001 * i,
            "queue_s": 0.001,
        }
        for i in range(10)
    ] + [
        {"v": 5, "ts": 0.0, "kind": "request", "name": "dropped", "id": 10,
         "rows": 1, "slots": 1, "latency_s": None, "queue_s": None},
    ]
    rep = build_report(recs, source="x", slo_ms=50.0)
    srv = rep["serving"]
    assert srv["completed"] == 10 and srv["dropped"] == 1
    assert 0.010 <= srv["p50_latency_s"] <= 0.020
    assert srv["slo_verdict"].startswith("SLO MET")
    tight = build_report(recs, source="x", slo_ms=1.0)["serving"]
    assert tight["slo_verdict"].startswith("SLO VIOLATED")
    none = build_report(recs, source="x")["serving"]
    assert "no SLO threshold" in none["slo_verdict"]
    out = render(rep, "md")
    assert "## Serving" in out and "DROPPED" in out
    # pre-v5 streams omit the section entirely
    old = build_report(
        [{"v": 1, "ts": 0.0, "kind": "event", "name": "epoch", "loss": 1.0}],
        source="y",
    )
    assert old["serving"] is None
    assert "## Serving" not in render(old, "md")
