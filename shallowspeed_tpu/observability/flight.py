"""Step-level flight recorder: a bounded ring of per-step training samples.

The fused epoch programs (trainer.make_train_epoch / executor.
make_pipeline_epoch with ``with_step_stats=True``) return per-step scalars —
loss, pre-clip global gradient norm, post-update global parameter norm — as
ORDINARY scan outputs: data flow out of the one jitted program, never host
callbacks inside it, so instrumentation cannot break the single-program-per-
epoch property the whole framework is built on. The host reads those arrays
back once per epoch and feeds them here.

The ring is bounded (``capacity`` samples, oldest evicted first) so a
million-step run holds a constant-size in-memory record: the recorder is the
"what just happened" buffer the numerics health monitor and a post-mortem
read, while the JSONL stream (``MetricsRecorder.step`` records, schema v2)
is the unbounded on-disk history.

Each sample is one plain dict — JSON-able as-is and exactly the field set
the ``step`` record kind carries::

    {"step": global_step, "epoch": e, "loss": ...,
     "grad_norm": ...|None, "param_norm": ...|None}
"""

from collections import deque


class FlightRecorder:
    """Bounded ring buffer of per-step flight samples."""

    def __init__(self, capacity=4096):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._ring = deque(maxlen=self.capacity)
        self.total_steps = 0  # lifetime count (>= len(self) once evicting)

    def record_epoch(
        self, epoch, losses, grad_norms=None, param_norms=None, first_step=None
    ):
        """Append one epoch's per-step arrays; returns the new samples.

        ``losses`` is required (one entry per optimizer step, in step
        order); ``grad_norms``/``param_norms`` are optional parallel arrays
        (None when the layout cannot thread them — e.g. the Pallas kernel
        paths, where gradients never leave VMEM). ``first_step`` defaults to
        the recorder's lifetime step count, so back-to-back epochs number
        their steps globally and monotonically.
        """
        if first_step is None:
            first_step = self.total_steps
        samples = []
        for i, loss in enumerate(losses):
            samples.append(
                {
                    "step": int(first_step + i),
                    "epoch": int(epoch),
                    "loss": float(loss),
                    "grad_norm": (
                        None if grad_norms is None else float(grad_norms[i])
                    ),
                    "param_norm": (
                        None if param_norms is None else float(param_norms[i])
                    ),
                }
            )
        self._ring.extend(samples)
        self.total_steps += len(samples)
        return samples

    def last(self, n=None):
        """The most recent ``n`` samples (all retained samples if None)."""
        if n is None:
            return list(self._ring)
        if n <= 0:
            return []
        return list(self._ring)[-n:]

    def snapshot(self):
        """JSON-able copy of the retained window (oldest first)."""
        return [dict(s) for s in self._ring]

    def __len__(self):
        return len(self._ring)
