"""Shared summary statistics: the ONE percentile definition.

Three consumers quote latency percentiles — the serving engine's
``stats()`` summary, the fleet's fleet-wide summary, and the report CLI's
killed-run fallback (recomputing p50/p99 from raw ``request`` records when
no summary landed). Before this module each carried its own
implementation; two of them agreed only by co-incidence of method
(np.percentile's default linear interpolation vs a hand-rolled
re-derivation of it), which is exactly the kind of duplicated definition
that lets a report and an engine summary disagree on the same data by one
ULP and flip an SLO verdict.

``percentile`` is now the single definition: ``np.percentile`` on float64
with its default (linear-interpolation) method — so every consumer is
EQUAL to ``np.percentile`` by construction, and the unit test pins that
equality rather than approximates it. ``None`` samples are ignored (the
recorders use None for "not measured") and an empty sample set returns
``None``, never 0.0 — an unmeasured percentile must not read as a fast
one.
"""

import numpy as np


def percentile(values, q):
    """The shared percentile: ``np.percentile(values, q)`` (float64,
    linear interpolation) over the non-``None`` samples; ``None`` when no
    sample survives the filter."""
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    return float(np.percentile(np.asarray(vals, np.float64), q))
