"""Bounded exponential-backoff retry: the ONE retry policy for flaky host I/O.

Three consumers share this module so their retry behaviour can never drift:

- checkpoint writes (``checkpoint.save_checkpoint`` retries the atomic
  tmp-write + rename on transient ``OSError`` — a preemption-safe step
  checkpoint that dies to one flaky NFS write defeats its purpose);
- distributed init (``parallel.multihost.initialize`` with an EXPLICIT
  coordinator retries the join — the coordinator process races the workers
  up on real clusters);
- the TPU tunnel tooling (``scripts/tunnel_watch.sh`` asks the CLI below for
  its probe schedule; ``bench._ensure_responsive_backend`` — which
  ``scripts/tpu_capture.py`` fronts — sleeps ``backoff_delay`` between
  probes). The motivating incident: the tunnel watcher hammered a dead
  tunnel on a fixed 10-minute cadence for 48 consecutive probes; bounded
  growth + jitter probes often early and rarely late instead;
- the serving engine's dispatch recovery (``serving/engine.py`` re-queues
  a failed batch and retries each request under a ``RetryPolicy`` budget —
  the policy as a VALUE, for consumers that own their own retry loop —
  and its hot weight reload reads checkpoints through ``retry_call``).

Policy: delay for attempt ``i`` (0-based, i.e. before retry ``i+1``) is
``min(base * factor**i, max_delay)`` plus uniform jitter in
``[-jitter, +jitter] * delay``. Jitter is DETERMINISTIC given ``seed`` —
everything in this repo that can replay must replay (the same property the
checkpoints guarantee), and the tests pin the schedule.

CLI (for shell consumers — prints one delay per line, in seconds)::

    python -m shallowspeed_tpu.retry --attempts 8 --base 60 --max 1200
"""

import argparse
import random
import sys
import time


def backoff_delay(
    attempt, base=1.0, factor=2.0, max_delay=60.0, jitter=0.1, seed=None
):
    """Delay in seconds before retry ``attempt + 1`` (attempt is 0-based).

    Exponential growth capped at ``max_delay``, with deterministic uniform
    jitter of ±``jitter`` (a fraction of the delay) drawn from a string
    seed over (seed, attempt) — the same pair always produces the same
    delay (independent of PYTHONHASHSEED), so schedules are reproducible
    and testable. ``jitter=0`` disables it. Never returns a negative delay.
    """
    if attempt < 0:
        raise ValueError("attempt must be >= 0")
    if base < 0 or factor < 1.0 or max_delay < 0:
        raise ValueError("need base >= 0, factor >= 1, max_delay >= 0")
    if not 0 <= jitter < 1:
        raise ValueError("jitter must be in [0, 1)")
    delay = min(base * factor**attempt, max_delay)
    if jitter:
        rng = random.Random(f"{seed}:{attempt}")
        delay *= 1.0 + rng.uniform(-jitter, jitter)
    return max(0.0, delay)


def backoff_delays(attempts, **kwargs):
    """The full schedule: ``[backoff_delay(0), ..., backoff_delay(n-1)]``."""
    return [backoff_delay(i, **kwargs) for i in range(attempts)]


class RetryPolicy:
    """The backoff policy as a value: a bounded total-attempts budget plus
    the ``backoff_delay`` schedule, passable to consumers that own their
    own retry loop (the serving engine's dispatch recovery re-queues a
    failed batch and retries it on a LATER ``step()`` call, so it cannot
    hand control to ``retry_call`` — but its budget and delays must follow
    the same policy every other retry in this repo follows).

    ``attempts`` is the TOTAL budget, ``retry_call``'s exact contract: a
    unit of work may run at most ``attempts`` times, with ``delay(i)``
    seconds before retry ``i + 1``. ``base=0`` (the serving default) makes
    every delay 0 — bounded retries, no stall."""

    __slots__ = ("attempts", "base", "factor", "max_delay", "jitter", "seed")

    def __init__(
        self, attempts=3, base=0.1, factor=2.0, max_delay=5.0, jitter=0.1,
        seed=None,
    ):
        if attempts < 1:
            raise ValueError("attempts must be >= 1")
        self.attempts = int(attempts)
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self.seed = seed
        # validate eagerly — a bad policy must fail at configure time,
        # not on the first failure it was meant to absorb
        backoff_delay(
            0, base=base, factor=factor, max_delay=max_delay, jitter=jitter,
            seed=seed,
        )

    def delay(self, attempt):
        """Seconds to wait before retry ``attempt + 1`` (0-based)."""
        return backoff_delay(
            attempt, base=self.base, factor=self.factor,
            max_delay=self.max_delay, jitter=self.jitter, seed=self.seed,
        )

    def exhausted(self, attempts_used):
        """True once ``attempts_used`` has consumed the whole budget."""
        return attempts_used >= self.attempts

    def __repr__(self):
        return (
            f"RetryPolicy(attempts={self.attempts}, base={self.base}, "
            f"factor={self.factor}, max_delay={self.max_delay})"
        )


def retry_call(
    fn,
    *,
    attempts=3,
    base=0.1,
    factor=2.0,
    max_delay=5.0,
    jitter=0.1,
    seed=None,
    retry_on=(OSError,),
    on_retry=None,
    sleep=time.sleep,
):
    """Call ``fn()`` with bounded exponential-backoff retries.

    Retries only on exception types in ``retry_on`` (everything else —
    including the final failing attempt — propagates unwrapped, so callers'
    existing except clauses keep working). ``on_retry(attempt, exc, delay)``
    is the observability hook (attempt is 0-based); ``sleep`` is injectable
    for tests. ``attempts`` is the TOTAL call budget (>= 1), so the worst
    case is strictly bounded: ``attempts`` calls and ``attempts - 1`` sleeps.
    """
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    for attempt in range(attempts):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts - 1:
                raise
            delay = backoff_delay(
                attempt, base=base, factor=factor, max_delay=max_delay,
                jitter=jitter, seed=seed,
            )
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.retry",
        description="Print a bounded exponential-backoff schedule, one delay "
        "(integer seconds) per line — for shell consumers like "
        "scripts/tunnel_watch.sh.",
    )
    ap.add_argument("--attempts", type=int, default=8)
    ap.add_argument("--base", type=float, default=1.0)
    ap.add_argument("--factor", type=float, default=2.0)
    ap.add_argument("--max", dest="max_delay", type=float, default=60.0)
    ap.add_argument("--jitter", type=float, default=0.1)
    ap.add_argument(
        "--seed",
        type=int,
        default=0,
        help="jitter seed (schedules are deterministic per seed)",
    )
    args = ap.parse_args(argv)
    try:
        delays = backoff_delays(
            args.attempts, base=args.base, factor=args.factor,
            max_delay=args.max_delay, jitter=args.jitter, seed=args.seed,
        )
    except ValueError as e:
        print(f"retry: {e}", file=sys.stderr)
        return 1
    for d in delays:
        print(int(round(d)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
