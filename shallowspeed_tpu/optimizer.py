"""Optimizers over parameter pytrees, applied on-device inside the jitted step.

Capability parity: the reference ships plain stateless SGD
(/root/reference/shallowspeed/optimizer.py:4-13, ``param.data -= lr * grad``).
Here the update is a pytree map that XLA fuses into the training step — no
host round-trip per parameter — plus stateful optimizers (momentum, Adam)
the reference has no plumbing for.

State protocol: ``init(params)`` returns the state pytree (``()`` =
stateless); ``apply(params, grads, state) -> (new_params, new_state)`` must
be ELEMENTWISE over param leaves (that is what makes ZeRO-1 chunking and the
padded-stack executor exact); ``state_layout()`` names the state's parts for
layout-independent checkpointing — a dict mapping state key to kind:

    SGD      -> {}                                (no state)
    Momentum -> {"": "params"}                    (state IS one params mirror)
    Adam     -> {"m": "params", "v": "params", "t": "scalar"}

"params" parts mirror the param pytree (stored per logical layer, like the
weights); "scalar" parts are 0-d arrays (stored in checkpoint metadata,
replicated on every device).
"""

import dataclasses

import jax


@dataclasses.dataclass(frozen=True)
class SGD:
    """Stateless SGD. ``apply`` returns new params; grads are SUMS over the
    global batch (the loss is pre-scaled by the global batch size), so no
    averaging happens here — same ledger as the reference.

    ``weight_decay``: decoupled (applied directly to params, not through the
    gradient), so it stays elementwise — exact under padding and ZeRO-1
    chunking like the update itself. Default 0 = reference parity.
    """

    lr: float
    weight_decay: float = 0.0

    def init(self, params):
        return ()  # no optimizer state

    def state_layout(self):
        return {}

    def _decay(self, p):
        return p * _decay_factor(self.lr, self.weight_decay) if self.weight_decay else p

    def apply(self, params, grads, state=()):
        new = jax.tree.map(lambda p, g: self._decay(p) - self.lr * g, params, grads)
        return new, state


@dataclasses.dataclass(frozen=True)
class MomentumSGD:
    """Heavy-ball SGD: v <- mu*v + g; p <- p - lr*v.

    The reference ships only plain SGD; this exists to exercise (and prove)
    the optimizer-state plumbing: state is a pytree mirroring the params, it
    threads through the sequential trainer AND the pipeline executor
    identically, so stateful optimizers keep the distributed == sequential
    invariant (tests/test_optimizer_state.py)."""

    lr: float
    momentum: float = 0.9
    weight_decay: float = 0.0

    def init(self, params):
        import jax.numpy as jnp

        return jax.tree.map(lambda p: jnp.zeros(p.shape, p.dtype), params)

    def state_layout(self):
        return {"": "params"}

    def _decay(self, p):
        return p * _decay_factor(self.lr, self.weight_decay) if self.weight_decay else p

    def apply(self, params, grads, state):
        velocity = jax.tree.map(lambda v, g: self.momentum * v + g, state, grads)
        new = jax.tree.map(lambda p, v: self._decay(p) - self.lr * v, params, velocity)
        return new, velocity


@dataclasses.dataclass(frozen=True)
class Adam:
    """Adam (Kingma & Ba 2014), elementwise over param leaves.

    Grads in this framework are SUMS over the global batch (the loss is
    pre-scaled by the global batch size), identical on every layout, so the
    moment estimates are layout-independent too. State is a dict
    {"m", "v", "t"}: two params mirrors plus one shared step counter — the
    multi-part state that exercises the full state_layout protocol
    (checkpoints, stacked pp sharding, ZeRO-1 chunking)."""

    lr: float
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0  # decoupled (AdamW); 0 = plain Adam

    def init(self, params):
        import jax.numpy as jnp

        zeros = lambda: jax.tree.map(  # noqa: E731
            lambda p: jnp.zeros(p.shape, p.dtype), params
        )
        return {"m": zeros(), "v": zeros(), "t": jnp.zeros((), jnp.float32)}

    def state_layout(self):
        return {"m": "params", "v": "params", "t": "scalar"}

    def apply(self, params, grads, state):
        import jax.numpy as jnp

        t = state["t"] + 1.0
        m = jax.tree.map(
            lambda m_, g: self.b1 * m_ + (1 - self.b1) * g, state["m"], grads
        )
        v = jax.tree.map(
            lambda v_, g: self.b2 * v_ + (1 - self.b2) * g * g, state["v"], grads
        )
        c1 = 1.0 - self.b1**t
        c2 = 1.0 - self.b2**t
        wd = _decay_factor(self.lr, self.weight_decay) if self.weight_decay else 1.0
        new = jax.tree.map(
            lambda p, m_, v_: p * wd
            - self.lr * (m_ / c1) / (jnp.sqrt(v_ / c2) + self.eps),
            params,
            m,
            v,
        )
        return new, {"m": m, "v": v, "t": t}


def is_stateless(opt) -> bool:
    """True iff the optimizer carries no state (e.g. SGD). Answered by the
    state_layout() protocol — the single source of truth every call site
    branches on."""
    return not opt.state_layout()


def make_optimizer(name: str, lr: float, momentum: float = 0.9, weight_decay: float = 0.0):
    """Optimizer registry for the CLI/API surface (reference hardwires SGD,
    train.py:107). ``weight_decay`` is decoupled and UNIFORM over every
    param element including biases — uniformity is what keeps the update
    exact under ZeRO-1's flat chunking."""
    if weight_decay:
        _decay_factor(lr, weight_decay)  # validate eagerly, not at trace time
    if name == "sgd":
        return SGD(lr, weight_decay=weight_decay)
    if name == "momentum":
        return MomentumSGD(lr, momentum, weight_decay=weight_decay)
    if name == "adam":
        return Adam(lr, weight_decay=weight_decay)
    raise ValueError(
        f"optimizer must be one of ['adam', 'momentum', 'sgd'], got {name!r}"
    )


def clip_scale(grads_sq_sum, clip_norm):
    """Global-norm clip factor: min(1, clip/||g||) from the SUM OF SQUARES of
    the full gradient (callers supply the cross-device total where grads are
    sharded). One definition shared by every execution path."""
    import jax.numpy as jnp

    norm = jnp.sqrt(grads_sq_sum)
    return jnp.minimum(1.0, clip_norm / jnp.maximum(norm, 1e-12))


def tree_sq_sum(tree, cross_device_sum=None):
    """Sum of squares over every leaf of a pytree, optionally reduced by
    ``cross_device_sum`` (a callable, e.g. a psum over the axes the tree is
    sharded across). The shared input of both the clip factor and the
    grad-norm telemetry (observability aux outputs), so the two always agree
    on what "the global norm" means."""
    import jax
    import jax.numpy as jnp

    sq = sum(jnp.sum(g * g) for g in jax.tree.leaves(tree))
    if cross_device_sum is not None:
        sq = cross_device_sum(sq)
    return sq


def global_norm(tree, cross_device_sum=None):
    """Global L2 norm over every leaf of a pytree (see ``tree_sq_sum``)."""
    import jax.numpy as jnp

    return jnp.sqrt(tree_sq_sum(tree, cross_device_sum))


def clip_tree(grads, clip_norm, cross_device_sum=None):
    """Scale a gradient pytree by the global-norm clip factor. The local
    sum-of-squares is optionally reduced by ``cross_device_sum`` (a callable,
    e.g. a psum over the axes the gradient is sharded across) before the
    factor is computed — the ONE implementation behind the sequential,
    pipeline and ZeRO-1 paths (which differ only in that reduction)."""
    import jax

    sq = tree_sq_sum(grads, cross_device_sum)
    s = clip_scale(sq, clip_norm)
    return jax.tree.map(lambda g: g * s, grads)


def _decay_factor(lr, weight_decay):
    """Decoupled weight decay multiplier (1 - lr*wd); validated once here —
    the single definition all optimizers share."""
    if weight_decay < 0:
        raise ValueError(f"weight_decay must be >= 0, got {weight_decay}")
    f = 1.0 - lr * weight_decay
    if f <= 0:
        raise ValueError(
            f"lr * weight_decay = {lr * weight_decay} >= 1 would flip the "
            "decay factor's sign"
        )
    return f


def split_state(opt, state):
    """State pytree -> ({key: params-mirroring subtree}, {key: scalar}),
    keyed per ``state_layout()``. The inverse is ``join_state``."""
    layout = opt.state_layout()
    parts, scalars = {}, {}
    for key, kind in layout.items():
        sub = state if key == "" else state[key]
        (parts if kind == "params" else scalars)[key] = sub
    return parts, scalars


def join_state(opt, parts, scalars):
    """({key: subtree}, {key: scalar}) -> the state pytree ``apply`` expects."""
    layout = opt.state_layout()
    if not layout:
        return ()
    if set(layout) == {""}:
        return parts[""]
    return {
        key: (parts[key] if kind == "params" else scalars[key])
        for key, kind in layout.items()
    }
