"""AOT executable cache: cold starts deserialize instead of recompiling.

Every cold start — a serving replica, a fleet ``scale_up()`` replacement,
a resumed trainer — used to recompile its whole rung ladder from scratch,
so the fleet's measured ``scale_up_s`` was seconds-of-XLA instead of
milliseconds-of-deserialize (ROADMAP item 5a). This module is the cache
that removes that stall: compiled executables are serialized via
``jax.experimental.serialize_executable`` (the ``jax.stages`` export
surface) into an on-disk store this repo OWNS, and the compile sites
(``TrainingSession._inference_step``, the sequential slot-predict
program, the epoch audit probe — api.py) try it before ``.compile()``.

Design constraints, in contract order:

- **never serve an unaudited program**: a deserialized executable is
  re-verified by the existing audit-at-compile census
  (``program_audit.audit_compiled`` against the layout's forward-only
  contract) BEFORE its first dispatch — the caller (api.py) runs the
  audit and treats a mismatch like corruption: fall back to a clean
  recompile, record the cause;
- **never crash on a bad entry**: corruption, a stale backend
  fingerprint, a format-version bump, a deserialize failure — every one
  degrades to a recompile + rewrite with an ``aot_cache`` record naming
  the cause (``corrupt``/``stale``/``miss``/``fallback``), never an
  exception into the serving path;
- **own on-disk format, own write discipline**: one file per entry
  (``<key>.aotx``: magic + JSON header + pickled payload, the payload's
  sha256 in the header), written mkstemp -> fsync -> atomic rename —
  the checkpoint writer's discipline, so a killed process never leaves
  a torn rename-visible entry;
- **no jax global cache involvement**: this deliberately does NOT touch
  ``jax_compilation_cache_dir`` — the jax-0.4.x persistent cache
  corrupts the CPU client's heap once cached pipeline programs and
  donated sequential steps mix in one process (the PR 1 segfault gate,
  tests/conftest.py). The hazard class is PROVEN absent per program,
  not just avoided structurally: every executable this cache resolves
  for DISPATCH passes the HLO dispatch-safety check
  (``program_audit.verify_dispatch_safety`` parses
  ``input_output_alias`` from the compiled text and refuses any
  donation — api.py ``_aot_resolve(dispatch=True)``), while the one
  donating program it touches (the epoch audit probe) stays
  census-read only, never dispatched, and is resolved with
  ``dispatch=False``;
- **degrade to no-op, with a recorded reason**, on backends whose
  executables cannot serialize (``disabled`` event; ``supported``
  property) — the feature must never make a backend unusable.

Cache key = sha256 over (program label, layout tuple, rung geometry,
backend fingerprint, program CONTENT hash). The content hash covers the
lowered StableHLO text, so any change to the traced program — a source
edit, a flag flip, a shape change — changes the key and the stale entry
is simply never looked up again (and a fingerprint check inside the file
catches jaxlib/backend upgrades for keys that would otherwise collide
across versions).
"""

import hashlib
import json
import pickle
import struct
import time
from pathlib import Path

from shallowspeed_tpu.checkpoint import atomic_write
from shallowspeed_tpu.observability import NullMetrics

MAGIC = b"SSAOT1\n"
CACHE_FORMAT_VERSION = 1
_HEADER_LEN = struct.Struct(">I")


def backend_fingerprint(platform=None):
    """The (jax, jaxlib, backend platform/version) tuple a serialized
    executable is only valid under — XLA gives no ABI stability across
    versions, so a mismatch is ``stale``, never an attempted load."""
    import jax

    fp = {
        "jax": jax.__version__,
        "format": CACHE_FORMAT_VERSION,
    }
    try:
        import jaxlib

        fp["jaxlib"] = jaxlib.__version__
    except Exception:  # noqa: BLE001 — version probe only
        fp["jaxlib"] = None
    try:
        if platform is None:
            platform = jax.devices()[0].platform
        fp["platform"] = platform
        from jax.extend.backend import get_backend

        fp["platform_version"] = get_backend(platform).platform_version
    except Exception:  # noqa: BLE001 — fingerprint stays usable without it
        fp.setdefault("platform", platform)
        fp["platform_version"] = None
    return fp


def content_hash(lowered_text):
    """sha256 of the lowered (StableHLO) program text — the 'what program
    is this' half of the cache key. Tracing+lowering is milliseconds; the
    XLA compile behind it is the seconds this cache removes."""
    return hashlib.sha256(lowered_text.encode()).hexdigest()


def cache_key(program, layout, fingerprint, program_hash):
    """One stable hex key per (program label, layout tuple, backend
    fingerprint, program content hash) — the filename stem."""
    blob = json.dumps(
        {
            "program": program,
            "layout": list(layout),
            "fingerprint": fingerprint,
            "content": program_hash,
        },
        sort_keys=True,
    )
    return hashlib.sha256(blob.encode()).hexdigest()


class AotCache:
    """The on-disk executable store (module docstring).

    ``load``/``store`` never raise on cache-side failures: every outcome
    is recorded (an ``aot_cache`` metrics record + the ``counts`` dict)
    and a failed load returns ``None`` — the caller recompiles. The
    serializer probe is lazy: the first ``store`` on a backend whose
    executables cannot serialize flips the cache into a recorded
    no-op (``disabled_reason``)."""

    def __init__(self, cache_dir, metrics=None):
        self.dir = Path(cache_dir)
        self._metrics = metrics if metrics is not None else NullMetrics()
        self._fingerprint = None  # lazy: jax backend may not be up yet
        self.counts = {
            "hit": 0, "miss": 0, "store": 0, "stale": 0, "corrupt": 0,
            "audit_mismatch": 0, "fallback": 0, "disabled": 0,
        }
        self.disabled_reason = None

    # -- plumbing ------------------------------------------------------------

    def fingerprint(self):
        if self._fingerprint is None:
            self._fingerprint = backend_fingerprint()
        return self._fingerprint

    def key_for(self, program, layout, lowered_text):
        return cache_key(
            program, layout, self.fingerprint(), content_hash(lowered_text)
        )

    def entry_path(self, key):
        return self.dir / f"{key}.aotx"

    def record(self, event, program, key=None, wall_s=None, reason=None,
               **fields):
        self.counts[event] = self.counts.get(event, 0) + 1
        rec = dict(program=program, **fields)
        if key is not None:
            rec["key"] = key
        if wall_s is not None:
            rec["wall_s"] = wall_s
        if reason is not None:
            rec["reason"] = reason
        self._metrics.aot_cache(event, **rec)

    def _serializer(self):
        """The (serialize, deserialize_and_load) pair, or None with the
        reason recorded — import failure IS the unsupported-backend
        signal on jax builds without the experimental surface."""
        try:
            from jax.experimental import serialize_executable as se

            return se.serialize, se.deserialize_and_load
        except Exception as e:  # noqa: BLE001 — degrade, never crash
            self._disable(f"serialize_executable unavailable: {e}")
            return None

    def _disable(self, reason):
        if self.disabled_reason is None:
            self.disabled_reason = str(reason)[:200]
            self.record("disabled", program="*", reason=self.disabled_reason)

    @property
    def supported(self):
        """False once the cache degraded to a recorded no-op. Reading it
        runs the import-level serializer probe, so a jax build without
        the experimental surface answers False BEFORE the first
        store/load — callers can branch on it up front instead of
        discovering the disable after a phase of silent no-ops. (A
        serialize-time failure on an exotic executable kind still only
        shows at the first ``store``.)"""
        if self.disabled_reason is None:
            self._serializer()
        return self.disabled_reason is None

    # -- the store -----------------------------------------------------------

    def store(self, key, compiled, program="program"):
        """Serialize ``compiled`` under ``key`` (mkstemp -> fsync ->
        atomic rename). Returns the entry path, or None (recorded) when
        the backend cannot serialize or the write failed."""
        if self.disabled_reason is not None:
            return None
        ser = self._serializer()
        if ser is None:
            return None
        serialize, _ = ser
        t0 = time.perf_counter()
        try:
            payload, in_tree, out_tree = serialize(compiled)
            blob = pickle.dumps((payload, in_tree, out_tree), protocol=4)
        except Exception as e:  # noqa: BLE001 — unsupported executable kind
            self._disable(f"{type(e).__name__}: {e}")
            return None
        header = json.dumps(
            {
                "v": CACHE_FORMAT_VERSION,
                "key": key,
                "program": program,
                "fingerprint": self.fingerprint(),
                "sha256": hashlib.sha256(blob).hexdigest(),
                "created": time.strftime("%Y-%m-%d %H:%M:%S"),
            }
        ).encode()
        path = self.entry_path(key)

        def write_entry(f):
            f.write(MAGIC)
            f.write(_HEADER_LEN.pack(len(header)))
            f.write(header)
            f.write(blob)

        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            # the checkpoint module's ONE atomic-write sequence (mkstemp ->
            # fsync(file) -> rename -> fsync(dir), temp removed on failure)
            # — shared, not copied, so the disciplines cannot drift
            atomic_write(path, write_entry, suffix=".aotx.tmp")
        except OSError as e:
            self.record(
                "fallback", program=program, key=key,
                reason=f"store failed: {e}"[:200],
            )
            return None
        self.record(
            "store", program=program, key=key,
            wall_s=time.perf_counter() - t0, bytes=len(blob),
        )
        return path

    def load(self, key, program="program"):
        """Deserialize the entry under ``key``; returns the loaded
        executable or None — with the outcome recorded as ``hit``,
        ``miss`` (no entry), ``stale`` (fingerprint/format mismatch) or
        ``corrupt`` (torn file, checksum mismatch, deserialize failure).
        The caller still owes the audit census before first dispatch."""
        if self.disabled_reason is not None:
            return None
        ser = self._serializer()
        if ser is None:
            return None
        _, deserialize_and_load = ser
        path = self.entry_path(key)
        t0 = time.perf_counter()
        try:
            raw = path.read_bytes()
        except FileNotFoundError:
            self.record("miss", program=program, key=key)
            return None
        except OSError as e:
            self.record(
                "corrupt", program=program, key=key,
                reason=f"unreadable: {e}"[:200],
            )
            return None
        try:
            if not raw.startswith(MAGIC):
                raise ValueError("bad magic — not an aot cache entry")
            off = len(MAGIC)
            (hlen,) = _HEADER_LEN.unpack(raw[off : off + _HEADER_LEN.size])
            off += _HEADER_LEN.size
            header = json.loads(raw[off : off + hlen].decode())
            blob = raw[off + hlen :]
            if hashlib.sha256(blob).hexdigest() != header.get("sha256"):
                raise ValueError("payload sha256 mismatch — torn or bit-rotted")
        except Exception as e:  # noqa: BLE001 — any parse failure is corrupt
            self.record(
                "corrupt", program=program, key=key,
                reason=f"{type(e).__name__}: {e}"[:200],
            )
            return None
        if (
            header.get("v") != CACHE_FORMAT_VERSION
            or header.get("fingerprint") != self.fingerprint()
        ):
            self.record(
                "stale", program=program, key=key,
                reason="backend fingerprint / format version mismatch",
            )
            return None
        try:
            payload, in_tree, out_tree = pickle.loads(blob)
            compiled = deserialize_and_load(payload, in_tree, out_tree)
        except Exception as e:  # noqa: BLE001 — any load failure is corrupt
            self.record(
                "corrupt", program=program, key=key,
                reason=f"deserialize failed: {type(e).__name__}: {e}"[:200],
            )
            return None
        self.record(
            "hit", program=program, key=key,
            wall_s=time.perf_counter() - t0,
        )
        return compiled

    def stats(self):
        """The counts snapshot (+ hit rate over hit/miss lookups) — what
        the report's Reliability AOT row and the smoke harness read."""
        looked = self.counts["hit"] + self.counts["miss"]
        return {
            **self.counts,
            "lookups": looked,
            "hit_rate": (self.counts["hit"] / looked) if looked else None,
            "disabled_reason": self.disabled_reason,
        }
