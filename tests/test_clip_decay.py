"""Global-norm gradient clipping + decoupled weight decay.

Both compose elementwise with every execution path, so the bar is the usual
one: mesh layouts (incl. zero1 and interleaved) must match sequential
training with the same settings, and clipping must actually bound the norm.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer
from shallowspeed_tpu.optimizer import SGD, Adam, MomentumSGD, clip_scale
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
B, M, NB = 64, 4, 3
CLIP = 0.05  # far below this problem's natural grad norm -> always active


def _data(seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(NB, B, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, (NB, B))]
    return X, Y


def _sequential(opt, clip_norm):
    X, Y = _data()
    spec = Mo.make_model_spec(SIZES, 1, B)
    params = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    step = trainer.make_train_step(spec, opt, clip_norm=clip_norm)
    st = opt.init(params)
    for i in range(NB):
        params, st = step(
            params,
            st,
            jnp.asarray(X[i].reshape(M, B // M, -1)),
            jnp.asarray(Y[i].reshape(M, B // M, -1)),
        )
    return [l for s in params for l in s]


def _mesh(opt, clip_norm, dp, pp, zero1=False, virtual=1):
    X, Y = _data()
    mesh = make_mesh(dp, pp)
    spec = Mo.make_model_spec(SIZES, pp * virtual, B)
    order = E.interleave_order(pp * virtual, pp) if virtual > 1 else None
    sched = S.InterleavedSchedule if virtual > 1 else S.GPipeSchedule
    prog = lower_schedule(sched, M, pp, virtual=virtual)
    stacked, flags = E.init_stacked(spec, mesh, order=order)
    st = E.zero1_init_state(opt, spec, mesh) if zero1 else opt.init(stacked)
    step = E.make_pipeline_step(
        mesh, spec, prog, B // dp // M, opt, zero1=zero1, clip_norm=clip_norm
    )
    for i in range(NB):
        stacked, st, _ = step(stacked, flags, st, jnp.asarray(X[i]), jnp.asarray(Y[i]))
    return [l for s in E.unstack_params(stacked, spec, order=order) for l in s]


@pytest.mark.parametrize("zero1,virtual", [(False, 1), (True, 1), (True, 2)])
def test_clipping_mesh_matches_sequential(zero1, virtual):
    opt = MomentumSGD(0.01, 0.9)
    want = _sequential(opt, CLIP)
    got = _mesh(opt, CLIP, 2, 2, zero1=zero1, virtual=virtual)
    assert len(want) == len(got)
    for a, b in zip(want, got):
        np.testing.assert_allclose(np.asarray(a["W"]), b["W"], rtol=5e-4, atol=5e-6)
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1), rtol=5e-4, atol=5e-6
        )


def test_clipping_changes_training_and_bounds_step():
    """With clip far below the natural norm, the first update must have
    global norm exactly lr * CLIP (SGD), and differ from unclipped."""
    opt = SGD(0.01)
    spec = Mo.make_model_spec(SIZES, 1, B)
    X, Y = _data()
    p0 = jax.tree.map(jnp.asarray, Mo.init_model(spec))
    step_c = trainer.make_train_step(spec, opt, clip_norm=CLIP)
    step_u = trainer.make_train_step(spec, opt)
    xb = jnp.asarray(X[0].reshape(M, B // M, -1))
    yb = jnp.asarray(Y[0].reshape(M, B // M, -1))
    pc, _ = step_c(jax.tree.map(jnp.copy, p0), (), xb, yb)
    pu, _ = step_u(jax.tree.map(jnp.copy, p0), (), xb, yb)
    d_c = jax.tree.map(lambda a, b: a - b, pc, p0)
    step_norm = float(
        jnp.sqrt(sum(jnp.sum(g * g) for g in jax.tree.leaves(d_c)))
    )
    assert step_norm == pytest.approx(0.01 * CLIP, rel=1e-4)
    du = jax.tree.leaves(jax.tree.map(lambda a, b: jnp.abs(a - b).max(), pc, pu))
    assert max(float(x) for x in du) > 0


def test_clip_scale_definition():
    assert float(clip_scale(jnp.asarray(4.0), 1.0)) == pytest.approx(0.5)
    assert float(clip_scale(jnp.asarray(0.25), 1.0)) == 1.0  # under the cap


@pytest.mark.parametrize("opt_cls", [SGD, MomentumSGD, Adam])
def test_weight_decay_shrinks_weights(opt_cls):
    """Decoupled decay: same grads, decayed params strictly smaller in norm
    than the undecayed run after a step; padded stacked regions stay zero."""
    kw = {"lr": 0.01}
    opt_p = opt_cls(**kw)
    opt_d = opt_cls(weight_decay=0.1, **kw)
    want_p = _sequential(opt_p, None)
    want_d = _sequential(opt_d, None)
    n_p = sum(float(np.square(l["W"]).sum()) for l in want_p)
    n_d = sum(float(np.square(l["W"]).sum()) for l in want_d)
    assert n_d < n_p

    got_d = _mesh(opt_d, None, 2, 2, zero1=True)
    for a, b in zip(want_d, got_d):
        np.testing.assert_allclose(np.asarray(a["W"]), b["W"], rtol=5e-3, atol=5e-5)
        np.testing.assert_allclose(
            np.asarray(a["b"]).reshape(-1), b["b"].reshape(-1), rtol=5e-3, atol=5e-5
        )


def test_bad_weight_decay_rejected():
    from shallowspeed_tpu.optimizer import make_optimizer

    with pytest.raises(ValueError, match="weight_decay"):
        make_optimizer("sgd", 0.01, weight_decay=-0.1)
    with pytest.raises(ValueError, match="sign"):
        make_optimizer("sgd", 10.0, weight_decay=0.2)


def test_weight_decay_mismatch_on_resume_rejected(tmp_path):
    from shallowspeed_tpu.api import TrainingSession

    rng = np.random.RandomState(0)
    for suffix, n in (("train", 128), ("val", 32)):
        np.save(tmp_path / f"x_{suffix}.npy", rng.rand(n, SIZES[0]).astype(np.float32))
        np.save(
            tmp_path / f"y_{suffix}.npy",
            np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)],
        )
    kw = dict(sizes=SIZES, global_batch_size=B, data_dir=tmp_path)
    run = TrainingSession(weight_decay=0.01, **kw)
    run.train_epoch()
    ck = tmp_path / "wd.npz"
    run.save(ck)
    with pytest.raises(ValueError, match="weight_decay"):
        TrainingSession(resume=ck, **kw)
