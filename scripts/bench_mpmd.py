"""The MPMD-vs-lockstep scoreboard (ROADMAP item 1, the dispatch-roofline
payoff): three same-window measurements on the flagship gpipe-pp4 CPU
config, written as MPMD_r01.json beside the other bench records.

1. **Epoch pair** — the same training epochs dispatched through the
   lockstep SPMD program and the MPMD per-stage runtime, interleaved per
   trial (the BENCH_r0x protocol), per-leg minima. Both runtimes train
   the identical math (weights hash-equal — the in-suite lattice and
   ``make mpmd-smoke`` pin that bitwise), so the wall ratio is pure
   runtime cost.

2. **Dispatch probe pair** — ``measure_dispatch_overhead`` (PR 14) on
   both runtimes, over a BOUNDED 64-batch window where the profiler
   captures the full op-event stream (``events_per_batch`` is recorded
   as the saturation check). Running this bench surfaced a measurement
   caveat on DISPATCH_r01.json itself: over multi-second instrumented
   windows the profiler drops op events, collapsing the busy union and
   inflating the share — so the committed lockstep 0.728 overstates,
   and the full-epoch regime is recorded separately with its caveat.

3. **Serving burst p99** — R one-slot requests arriving at once, drained
   (a) through the lockstep rung program, one whole-rung makespan per
   request, vs (b) through the MPMD streaming chain (``predict_async``:
   request k enters stage 0 while request k-1 occupies a later stage).
   Latency is measured from the common arrival instant — the burst's
   p50/p99 show whether tail latency is makespan-quantized.

CPU-fallback caveat, as everywhere: emulated devices validate machinery
and RELATIVE ratios, not chip performance — but the dispatch-overhead
share is exactly the number that was eating the CPU wall, so CPU is the
honest place to measure its removal.
"""

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

BENCH_VERSION = 1


def _make_session(runtime, data_dir, epochs_data=None):
    from shallowspeed_tpu.api import TrainingSession

    return TrainingSession(
        pp=4, schedule="gpipe", global_batch_size=128, mubatches=4,
        data_dir=data_dir, runtime=runtime,
    )


def bench_epoch_pair(data_dir, trials):
    """Interleaved same-window lockstep/mpmd epochs; per-leg minima."""
    legs = {"lockstep": [], "mpmd": []}
    sessions = {rt: _make_session(rt, data_dir) for rt in legs}
    for rt, s in sessions.items():
        s.train_epoch()  # compile outside the measured window
    for _ in range(trials):
        for rt, s in sessions.items():
            t0 = time.perf_counter()
            s.train_epoch()
            legs[rt].append(time.perf_counter() - t0)
    samples = sessions["lockstep"].batches_per_epoch * 128
    out = {}
    for rt, walls in legs.items():
        best = min(walls)
        out[rt] = {
            "epoch_wall_s": best,
            "samples_per_sec": samples / best,
            "trials_s": walls,
        }
    out["speedup_mpmd_vs_lockstep"] = (
        out["lockstep"]["epoch_wall_s"] / out["mpmd"]["epoch_wall_s"]
    )
    # keep the trained sessions for the probe legs (weights advance —
    # the probe's documented contract)
    return out, sessions


def bench_dispatch_probes(data_dir, work, repeats, probe_samples=8192):
    """The probe pair runs on a BOUNDED shard of the same data (64
    batches at the flagship batch size): on multi-second instrumented
    windows the profiler's event buffer drops op events, which collapses
    the busy union and INFLATES the overhead share — the probe is only
    a valid measurement while the trace holds the full event stream
    (``events_per_batch`` is recorded per leg as the saturation check;
    this is also the retroactive caveat on DISPATCH_r01.json's 0.728,
    measured over a ~13 s window where events were dropped)."""
    import shutil

    from shallowspeed_tpu.api import TrainingSession

    src = Path(data_dir) if data_dir else None
    probe = Path(work) / "probe_data"
    probe.mkdir(parents=True, exist_ok=True)
    if src is None:
        from shallowspeed_tpu.data import default_data_dir

        src = Path(default_data_dir())
    x = np.load(src / "x_train.npy", mmap_mode="r")[:probe_samples]
    y = np.load(src / "y_train.npy", mmap_mode="r")[:probe_samples]
    np.save(probe / "x_train.npy", np.asarray(x))
    np.save(probe / "y_train.npy", np.asarray(y))
    for f in ("x_val.npy", "y_val.npy"):
        shutil.copy(src / f, probe / f)

    out = {}
    for rt in ("lockstep", "mpmd"):
        s = TrainingSession(
            pp=4, schedule="gpipe", global_batch_size=128, mubatches=4,
            data_dir=str(probe), runtime=rt,
        )
        rec = s.measure_dispatch_overhead(repeats=repeats)
        row = {
            k: rec[k]
            for k in (
                "dispatch_overhead", "dispatch_overhead_instrumented",
                "host_wall_s", "device_busy_s", "device_comm_s",
                "device_compute_s", "op_events", "op_source",
                "profiler_inflation", "repeats", "runtime",
                # the machine-checked validity guard (the record computes
                # its own saturation verdict now — PR 16)
                "events_per_batch", "window_valid",
                "window_invalid_reason",
            )
        }
        row["batches_per_epoch"] = s.batches_per_epoch
        out[rt] = row
    lock = out["lockstep"]["dispatch_overhead"]
    mp = out["mpmd"]["dispatch_overhead"]
    if lock is not None and mp is not None:
        out["overhead_drop_same_window"] = lock - mp
    out["probe_samples"] = probe_samples
    out["protocol_note"] = (
        "bounded window: full op-event capture (events_per_batch is the "
        "saturation check); long instrumented windows drop events and "
        "inflate the share — see full_epoch_probe for that regime"
    )
    return out


def bench_full_epoch_probes(sessions, repeats):
    """The DISPATCH_r01 protocol verbatim (full-epoch windows) — kept
    for continuity, with the saturation caveat measured into the record
    (events_per_batch far below the bounded-window density means the
    profiler dropped events and the share is NOT a valid lower bound)."""
    out = {}
    for rt, s in sessions.items():
        rec = s.measure_dispatch_overhead(repeats=repeats)
        out[rt] = {
            k: rec[k]
            for k in (
                "dispatch_overhead", "host_wall_s", "device_busy_s",
                "device_comm_s", "op_events", "profiler_inflation",
                "runtime", "events_per_batch", "window_valid",
                "window_invalid_reason",
            )
        }
    out["caveat"] = (
        "multi-second instrumented windows: the profiler buffer drops op "
        "events (compare events_per_batch against the bounded-window "
        "probe), so these shares OVERSTATE overhead — recorded for "
        "continuity with DISPATCH_r01.json, not as the headline"
    )
    return out


def bench_serving_burst(sessions, n_requests):
    """R one-slot requests arriving at one instant; latency from the
    common arrival. The lockstep leg drains one whole-rung dispatch per
    request; the MPMD leg submits every chain before resolving any."""
    from shallowspeed_tpu.observability.stats import percentile

    rng = np.random.RandomState(3)
    rows = sessions["lockstep"].slot_rows
    reqs = [
        rng.rand(rows, 784).astype(np.float32) for _ in range(n_requests)
    ]
    out = {}
    # warm both dispatch paths outside the measured burst
    sessions["lockstep"].predict(reqs[0])
    sessions["mpmd"].predict_async(reqs[0])()

    t0 = time.perf_counter()
    lock_lat, lock_res = [], []
    for x in reqs:
        lock_res.append(sessions["lockstep"].predict(x))
        lock_lat.append(time.perf_counter() - t0)
    t0 = time.perf_counter()
    handles = [sessions["mpmd"].predict_async(x) for x in reqs]
    mp_lat, mp_res = [], []
    for h in handles:
        mp_res.append(h())
        mp_lat.append(time.perf_counter() - t0)
    for a, b in zip(lock_res, mp_res):
        np.testing.assert_array_equal(a, b)  # the parity contract, asserted
    for name, lats in (("lockstep", lock_lat), ("mpmd", mp_lat)):
        out[name] = {
            "p50_ms": 1e3 * percentile(lats, 50),
            "p99_ms": 1e3 * percentile(lats, 99),
            "max_ms": 1e3 * max(lats),
            "burst_drain_s": max(lats),
        }
    out["n_requests"] = n_requests
    out["slot_rows"] = rows
    out["p99_speedup_mpmd_vs_lockstep"] = (
        out["lockstep"]["p99_ms"] / out["mpmd"]["p99_ms"]
    )
    out["responses_bitwise_equal"] = True
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default=None,
                    help="record path (default: MPMD_r01.json at the repo "
                    "root)")
    ap.add_argument("--data-dir", default=None)
    ap.add_argument("--trials", type=int, default=3)
    ap.add_argument("--probe-repeats", type=int, default=2)
    ap.add_argument("--requests", type=int, default=32)
    args = ap.parse_args(argv)

    import tempfile

    import jax

    work = Path(tempfile.mkdtemp(prefix="bench_mpmd_"))
    epoch_pair, sessions = bench_epoch_pair(args.data_dir, args.trials)
    probes = bench_dispatch_probes(args.data_dir, work, args.probe_repeats)
    full_probes = bench_full_epoch_probes(sessions, 1)
    serving = bench_serving_burst(sessions, args.requests)
    record = {
        "bench": "mpmd",
        "bench_version": BENCH_VERSION,
        "created": time.strftime("%Y-%m-%d %H:%M:%S"),
        "config": {
            "dp": 1, "pp": 4, "tp": 1, "schedule": "gpipe",
            "global_batch_size": 128, "mubatches": 4,
            "platform": jax.devices()[0].platform,
        },
        "cpu_fallback_caveat": (
            "emulated CPU devices: machinery + relative ratios, not chip "
            "performance; the dispatch-overhead share is the CPU-honest "
            "number (it measures the host-issue wall the MPMD refactor "
            "exists to remove)"
        ),
        "protocol": (
            "same-window: lockstep/mpmd epochs interleaved per trial, "
            "per-leg minima; probes run back-to-back on the same trained "
            "sessions; serving burst latencies measured from one common "
            "arrival instant with responses asserted bitwise-equal"
        ),
        "baseline_dispatch_overhead": {
            "source": "DISPATCH_r01.json (PR 14, lockstep flagship)",
            "value": 0.728454944852902,
            "caveat": (
                "measured over a ~13 s instrumented window where the "
                "profiler dropped op events (its events_per_batch is "
                "~5x below the bounded-window density), so 0.728 "
                "overstates the lockstep share; the honest same-window "
                "pair is dispatch_probe below"
            ),
        },
        "epoch_pair": epoch_pair,
        "dispatch_probe": probes,
        "full_epoch_probe": full_probes,
        "serving_burst": serving,
    }
    out = Path(
        args.out
        if args.out
        else Path(__file__).resolve().parent.parent / "MPMD_r01.json"
    )
    out.write_text(json.dumps(record, indent=2) + "\n")
    print(f"record written: {out}")
    ep = epoch_pair
    print(
        f"epoch wall: lockstep {ep['lockstep']['epoch_wall_s']:.2f}s -> "
        f"mpmd {ep['mpmd']['epoch_wall_s']:.2f}s "
        f"({ep['speedup_mpmd_vs_lockstep']:.2f}x)"
    )
    print(
        "dispatch overhead (bounded window, full event capture): lockstep "
        f"{probes['lockstep']['dispatch_overhead']:.3f} -> mpmd "
        f"{probes['mpmd']['dispatch_overhead']:.3f} "
        f"(events/batch {probes['lockstep']['events_per_batch']:.0f} vs "
        f"{probes['mpmd']['events_per_batch']:.0f})"
    )
    print(
        "full-epoch probe (event-dropping regime, continuity only): "
        f"lockstep {full_probes['lockstep']['dispatch_overhead']:.3f} -> "
        f"mpmd {full_probes['mpmd']['dispatch_overhead']:.3f}"
    )
    print(
        f"serving burst p99: lockstep {serving['lockstep']['p99_ms']:.1f} ms "
        f"-> mpmd {serving['mpmd']['p99_ms']:.1f} ms "
        f"({serving['p99_speedup_mpmd_vs_lockstep']:.2f}x)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
