"""Data-prep tool tests: offline source chain, preprocessing, determinism."""

import numpy as np
import pytest

import prepare_data
from shallowspeed_tpu.data import Dataset


@pytest.mark.slow  # `make data` drives prepare() end to end; the digits
# and determinism legs keep tier-1 coverage (1-core wall budget)
def test_synthetic_source_end_to_end(tmp_path):
    used = prepare_data.prepare(tmp_path / "d", source="synthetic")
    assert used == "synthetic"
    ds = Dataset(tmp_path / "d", 128, 32)
    ds.load(0, 1)
    assert ds.input_X.shape[1] == 784
    assert ds.target_y.shape[1] == 10
    # mean-centered features (reference preprocessing, download_dataset.py:12-13)
    assert abs(float(ds.input_X.mean())) < 0.05
    # one-hot targets
    np.testing.assert_allclose(ds.target_y.sum(axis=1), 1.0)


def test_digits_source_shapes(tmp_path):
    pytest.importorskip("sklearn")
    used = prepare_data.prepare(tmp_path / "d", source="digits")
    assert used == "digits"
    x = np.load(tmp_path / "d" / "x_train.npy")
    y = np.load(tmp_path / "d" / "y_train.npy")
    assert x.shape[1] == 784 and y.shape[1] == 10
    assert len(x) > 40000  # replicated to MNIST-like scale


@pytest.mark.slow  # the fallback chain re-runs a full prepare() — slow
# tier per the 1-core wall budget; the source legs above stay tier-1
def test_auto_falls_back_when_network_source_fails(tmp_path, monkeypatch):
    # deterministic offline simulation: the network source raises, the chain
    # lands on the next offline source (no real fetch, no retry stalls)
    def boom():
        raise OSError("no egress")

    monkeypatch.setattr(prepare_data, "_load_openml", boom)
    used = prepare_data.prepare(tmp_path / "d", source="auto")
    assert used in ("digits", "synthetic")


def test_split_is_deterministic_and_disjoint():
    x = np.arange(100, dtype=np.float32).reshape(100, 1)
    y = np.eye(10, dtype=np.float32)[np.arange(100) % 10]
    a = prepare_data._split(x, y)
    b = prepare_data._split(x, y)
    np.testing.assert_array_equal(a[0], b[0])
    assert len(a[1]) == 15  # 15% validation
    assert len(a[0]) + len(a[1]) == 100
    merged = np.sort(np.concatenate([a[0], a[1]]).reshape(-1))
    np.testing.assert_array_equal(merged, np.arange(100, dtype=np.float32))


def test_split_matches_reference_sklearn_permutation():
    """With sklearn present (it is, in this image), _split must reproduce the
    REFERENCE's exact validation membership: train_test_split(test_size=0.15,
    random_state=42) — /root/reference/download_dataset.py:16-18 — so
    cross-repo accuracy comparisons share sample-for-sample val sets."""
    from sklearn.model_selection import train_test_split

    x = np.arange(200, dtype=np.float32).reshape(200, 1)
    y = np.eye(10, dtype=np.float32)[np.arange(200) % 10]
    xt, xv, yt, yv, provenance = prepare_data._split(x, y)
    assert provenance.startswith("sklearn.train_test_split")
    xt_r, xv_r, yt_r, yv_r = train_test_split(x, y, test_size=0.15, random_state=42)
    np.testing.assert_array_equal(xt, xt_r)
    np.testing.assert_array_equal(xv, xv_r)
    np.testing.assert_array_equal(yt, yt_r)
    np.testing.assert_array_equal(yv, yv_r)


def test_openml_branch_executes_with_mocked_fetcher(tmp_path, monkeypatch):
    """The openml branch (the reference's REAL data path,
    download_dataset.py:9-23) must execute end-to-end — this environment has
    no egress, so the fetcher is mocked with a tiny MNIST-784-shaped frame
    (round-4 verdict #8: until now only the digits/synthetic branches ever
    ran)."""
    import sklearn.datasets

    def fake_fetch_openml(name, version, data_home, return_X_y, as_frame):
        assert name == "mnist_784" and version == 1 and not as_frame
        rng = np.random.RandomState(0)
        x = rng.randint(0, 256, (40, 784)).astype(np.float32)
        # fetch_openml returns string labels for mnist_784
        y = np.array([str(i % 10) for i in range(40)], dtype=object)
        return x, y

    monkeypatch.setattr(sklearn.datasets, "fetch_openml", fake_fetch_openml)
    used = prepare_data.prepare(tmp_path / "d", source="openml")
    assert used == "openml"
    x = np.load(tmp_path / "d" / "x_train.npy")
    y = np.load(tmp_path / "d" / "y_train.npy")
    assert x.shape == (34, 784) and y.shape == (34, 10)  # 85% of 40
    assert x.min() < 0 < x.max()  # /255 then mean-centered
    np.testing.assert_allclose(y.sum(axis=1), 1.0)
    import json

    meta = json.loads((tmp_path / "d" / "dataset_meta.json").read_text())
    assert meta["source"] == "openml"
    assert meta["split"].startswith("sklearn.train_test_split")


def test_fallback_split_warns_and_records_provenance(tmp_path, monkeypatch, capsys):
    """When sklearn is absent the NumPy fallback split must announce itself
    (stderr) and stamp its provenance into the dataset metadata — a silently
    different validation membership is invisible in the accuracy numbers."""
    import builtins

    real_import = builtins.__import__

    def no_sklearn(name, *a, **k):
        if name.startswith("sklearn.model_selection"):
            raise ImportError("mocked: no sklearn")
        return real_import(name, *a, **k)

    monkeypatch.setattr(builtins, "__import__", no_sklearn)
    x = np.arange(100, dtype=np.float32).reshape(100, 1)
    y = np.eye(10, dtype=np.float32)[np.arange(100) % 10]
    xt, xv, yt, yv, provenance = prepare_data._split(x, y)
    assert provenance.startswith("numpy.permutation_fallback")
    assert "NOT the reference" in capsys.readouterr().err
    assert len(xv) == 15 and len(xt) == 85
