from setuptools import find_packages, setup

setup(
    name="shallowspeed_tpu",
    version="0.1.0",
    description="TPU-native distributed-training framework (DP x PP on a JAX mesh)",
    packages=find_packages(include=["shallowspeed_tpu", "shallowspeed_tpu.*"]),
    python_requires=">=3.10",
    # 0.4.37 is the oldest runtime the compat layer supports
    # (parallel/compat.py maps jax.shard_map/check_vma onto the
    # jax.experimental spelling; multihost probes is_initialized)
    install_requires=["jax>=0.4.37", "numpy"],
)
