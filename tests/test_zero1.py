"""ZeRO-1 optimizer-state sharding tests.

Beyond the reference (its DP engine replicates the full update on every
rank, pipe.py:302-327): the gradient all-reduce becomes a reduce_scatter,
each dp replica updates 1/dp of the flattened params with its optimizer-state
shard, and an all_gather rebuilds the params. Chunking commutes with
elementwise optimizers, so the bar is BIT-identity with the plain path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu.api import TrainingSession
from shallowspeed_tpu.optimizer import SGD, Adam, MomentumSGD
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import lower_schedule, make_mesh

SIZES = (24, 20, 18, 16, 14, 12, 11, 10)
B, M, LR, NB = 64, 4, 0.01, 3


def _data(seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(NB, B, SIZES[0]).astype(np.float32)
    Y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, 10, (NB, B))]
    return X, Y


def _run(opt, dp, pp, zero1, virtual=1):
    X, Y = _data()
    mesh = make_mesh(dp, pp)
    spec = Mo.make_model_spec(SIZES, pp * virtual, B)
    order = E.interleave_order(pp * virtual, pp) if virtual > 1 else None
    sched = S.InterleavedSchedule if virtual > 1 else S.GPipeSchedule
    prog = lower_schedule(sched, M, pp, virtual=virtual)
    stacked, flags = E.init_stacked(spec, mesh, order=order)
    st = E.zero1_init_state(opt, spec, mesh) if zero1 else opt.init(stacked)
    step = E.make_pipeline_step(mesh, spec, prog, B // dp // M, opt, zero1=zero1)
    for i in range(NB):
        stacked, st, loss = step(stacked, flags, st, jnp.asarray(X[i]), jnp.asarray(Y[i]))
    flat = [l for s in E.unstack_params(stacked, spec, order=order) for l in s]
    return flat, st, float(loss), (spec, mesh, order)


@pytest.mark.parametrize("opt", [SGD(LR), MomentumSGD(LR, 0.9), Adam(LR)])
@pytest.mark.parametrize("dp,pp,virtual", [(2, 4, 1), (4, 2, 1), (2, 2, 2)])
def test_zero1_matches_plain(opt, dp, pp, virtual):
    """SGD/momentum updates (mul/add chains) compile identically chunked or
    stacked -> bitwise equality. Adam's sqrt/divide chain fuses differently
    per shape, so its chunked update may differ by ~1 ulp — mathematically
    the same chunking-commutes argument, checked at float-rounding tolerance."""
    plain, _, loss_p, _ = _run(opt, dp, pp, zero1=False, virtual=virtual)
    sharded, _, loss_z, _ = _run(opt, dp, pp, zero1=True, virtual=virtual)
    if isinstance(opt, Adam):
        assert loss_p == pytest.approx(loss_z, rel=1e-6)
        for a, b in zip(plain, sharded):
            np.testing.assert_allclose(a["W"], b["W"], rtol=1e-6, atol=1e-7)
            np.testing.assert_allclose(a["b"], b["b"], rtol=1e-6, atol=1e-7)
    else:
        assert loss_p == loss_z
        for a, b in zip(plain, sharded):
            np.testing.assert_array_equal(a["W"], b["W"])
            np.testing.assert_array_equal(a["b"], b["b"])


def test_zero1_state_is_actually_sharded():
    opt = MomentumSGD(LR, 0.9)
    _, st, _, (spec, mesh, _) = _run(opt, 4, 2, zero1=True)
    flat, csz = E.zero1_flat_len(spec, mesh)
    vel = st[""]  # momentum's single 'params' state part
    assert vel.shape == (2, 4 * csz)
    # each device holds exactly one (1, csz) block of the state
    assert all(s.data.shape == (1, csz) for s in vel.addressable_shards)
    # velocity is live after training
    assert float(jnp.abs(vel).sum()) > 0


@pytest.mark.parametrize("opt", [MomentumSGD(LR, 0.9), Adam(LR)])
def test_zero1_state_round_trip(opt):
    _, st, _, (spec, mesh, order) = _run(opt, 2, 4, zero1=True)
    logical = E.zero1_state_to_logical(st, opt, spec, mesh, order=order)
    assert logical is not None
    back = E.zero1_state_from_logical(logical, opt, spec, mesh, order=order)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(jax.device_get(a)), np.asarray(jax.device_get(b))
        ),
        st,
        back,
    )


def _write_dataset(tmp_path):
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 64)):
        x = rng.randn(n, SIZES[0]).astype(np.float32)
        y = np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)]
        np.save(tmp_path / f"x_{suffix}.npy", x)
        np.save(tmp_path / f"y_{suffix}.npy", y)


def test_zero1_session_resume_matches_plain(tmp_path):
    """TrainingSession surface: a zero1+momentum run checkpoints its sharded
    state logically and resumes — into a PLAIN momentum session — matching
    the uninterrupted plain run."""
    _write_dataset(tmp_path)
    kw = dict(
        sizes=SIZES, global_batch_size=B, lr=0.01, data_dir=tmp_path,
        optimizer="momentum", dp=2, pp=2, schedule="gpipe",
    )
    ref = TrainingSession(**kw)
    ref.train_epoch()
    ref.train_epoch()

    z = TrainingSession(zero1=True, **kw)
    z.train_epoch()
    ck = tmp_path / "z1.npz"
    z.save(ck)
    resumed = TrainingSession(resume=ck, **kw)
    resumed.train_epoch()
    assert resumed.model_hash() == ref.model_hash()


def test_zero1_rejected_on_sequential():
    with pytest.raises(ValueError, match="zero1"):
        TrainingSession(sizes=SIZES, zero1=True, data_dir="/nonexistent")


def test_zero1_fused_run_matches_epoch_loop(tmp_path):
    """The fused multi-epoch program composes with ZeRO-1: train_run(2) on a
    zero1 session equals two looped train_epoch() calls bit-for-bit."""
    _write_dataset(tmp_path)
    kw = dict(
        sizes=SIZES, global_batch_size=B, lr=0.01, data_dir=tmp_path,
        optimizer="momentum", dp=2, pp=2, schedule="gpipe", zero1=True,
    )
    looped = TrainingSession(**kw)
    loop_losses = [looped.train_epoch() for _ in range(2)]

    fused = TrainingSession(**kw)
    losses, accs = fused.train_run(2)
    assert np.allclose(losses, loop_losses, rtol=1e-6)
    assert len(accs) == 2 and all(np.isfinite(a) and 0.0 <= a <= 1.0 for a in accs)
    assert accs[-1] == pytest.approx(fused.accuracy(), abs=1e-6)
    assert fused.model_hash() == looped.model_hash()
