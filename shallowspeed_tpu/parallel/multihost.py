"""Multi-host wiring: the mesh-spanning equivalent of `mpirun` across nodes.

The reference runs multi-node by launching MPI ranks over TCP and splitting
COMM_WORLD (train.py:87-94 — its comment points at ``Split_type``/TYPE_SOCKET
for physically distributed runs). The JAX-native equivalent is one process
per host, ``jax.distributed.initialize`` to form the global runtime, and a
Mesh built over ``jax.devices()`` (which then spans every host's chips). All
executor code in this package is already global-mesh-ready: shard_map +
psum/ppermute compile to ICI collectives within a slice and DCN collectives
across hosts, with no code change — lay out ``pp`` along ICI-adjacent devices
and keep ``dp`` as the outer axis so the latency-sensitive stage relays stay
on ICI.

Single-host (or single-chip) runs never need this module.

Typical multi-host launch (same script on every host):

    from shallowspeed_tpu.parallel import multihost, make_mesh
    multihost.initialize()          # env-driven on TPU pods; explicit args OK
    mesh = make_mesh(dp, pp)        # uses all global devices
    # feed per-host data with jax.make_array_from_process_local_data(...)

CI coverage (emulated CPU devices, real ``jax.distributed`` runtimes):
tests/test_multihost.py runs a 2-process 4-device fleet (cross-process dp
psum, ZeRO-1 reduce_scatter/all_gather, interleaved relays, fused runs) and
a 4-process 2x2 mesh where BOTH axes cross process boundaries, with the
cross-process replica-sync check (utils.assert_dp_replicas_in_sync_global)
asserted after stateful training steps — plus a negative control proving
the checker detects an injected desync. Real multi-HOST hardware is not
available in this environment; the wrapper is deliberately thin so the
tested surface is the executor itself.
"""

import jax


def _distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` with a fallback for jax 0.4.x,
    where the predicate doesn't exist yet: the distributed client handle on
    ``jax._src.distributed.global_state`` (not re-exported at
    ``jax.distributed`` on those versions) is the same signal that function
    reads."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src.distributed import global_state
    except ImportError:  # pragma: no cover - neither API: assume fresh
        return False
    return getattr(global_state, "client", None) is not None


def _reset_half_initialized_state():
    """Best-effort teardown after a FAILED ``jax.distributed.initialize``
    so a retried join starts clean. ``jax.distributed.shutdown()`` is the
    public path, but it can itself raise on a never-connected client (and
    then leaves ``global_state.client`` set), so fall back to nulling the
    state fields directly — the same fields ``State.shutdown`` nulls."""
    try:
        jax.distributed.shutdown()
        return
    except (RuntimeError, ValueError, OSError) as e:
        # a never-connected client makes shutdown() itself raise; fall
        # through to nulling the state fields directly — but keep the
        # swallowed cause in the log (a teardown that fails for a NEW
        # reason should be debuggable, not invisible)
        import logging

        logging.getLogger(__name__).debug(
            "jax.distributed.shutdown() failed (%s: %s); clearing "
            "half-initialized state directly", type(e).__name__, e,
        )
    try:
        from jax._src.distributed import global_state
    except ImportError:  # pragma: no cover - no private state to clear
        return
    for field in ("client", "service", "preemption_sync_manager"):
        if hasattr(global_state, field):
            setattr(global_state, field, None)


def initialize(coordinator_address=None, num_processes=None, process_id=None):
    """Join the global JAX runtime; must run BEFORE any other JAX call that
    initializes a backend (jax.devices(), first jit, ...). No-op when the
    distributed runtime is already up, or — with no explicit coordinator —
    when no cluster environment is configured (single-process run).

    On TPU pods all three arguments are inferred from the environment
    (``jax.distributed.initialize()`` with no args); pass them explicitly for
    CPU/GPU clusters. With an EXPLICIT coordinator the join is retried with
    the shared bounded backoff (shallowspeed_tpu.retry): on real clusters
    the coordinator process races the workers up, and a worker that dials a
    not-yet-listening coordinator should wait out the race, not crash the
    fleet.
    """
    # NOTE: deliberately no jax.devices()/process_count() probe here — those
    # initialize the XLA backend and would make distributed init impossible.
    if _distributed_is_initialized():
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs = dict(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    def _join_once():
        # a failed connect leaves jax's global_state.client assigned (it is
        # set BEFORE the connect that can fail), and a second initialize
        # would then refuse with "should only be called once" — masking the
        # real error and defeating the retry. Tear the half-initialized
        # state down before re-raising so every retry is a fresh join.
        try:
            jax.distributed.initialize(**kwargs)
        except BaseException:
            _reset_half_initialized_state()
            raise

    try:
        if coordinator_address is not None:
            from shallowspeed_tpu import retry

            retry.retry_call(
                _join_once,
                attempts=4,
                base=0.5,
                max_delay=10.0,
                retry_on=(RuntimeError, ConnectionError, OSError),
            )
        else:
            jax.distributed.initialize(**kwargs)
    except (ValueError, RuntimeError) as e:
        # no coordinator given and none configured in the environment:
        # a plain single-process run — fine. Explicit args must not fail
        # silently (the retry budget above is already spent), and the cause
        # stays in the log either way.
        if coordinator_address is not None:
            raise
        import logging

        logging.getLogger(__name__).info(
            "jax.distributed.initialize skipped (%s); running single-process", e
        )


def shard_batch_for_process(x, mesh, spec):
    """Place a per-process batch shard into a global jax.Array for the mesh.

    Thin alias for ``jax.make_array_from_process_local_data`` so callers
    don't reach into jax internals; ``spec`` is the PartitionSpec the
    executor expects (P('dp') for batches).
    """
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(NamedSharding(mesh, spec), x)
