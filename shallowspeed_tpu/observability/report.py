"""Run report generator: render a metrics JSONL into a human/CI report.

    python -m shallowspeed_tpu.observability.report run.jsonl \
        [--baseline other.jsonl|BENCH.json] [--format md|text|json] \
        [--threshold 0.10]

Reads a schema-v1 or -v2 metrics stream (``read_jsonl`` — a v2 reader
accepts v1 files; see metrics.py's compatibility rules) and reports what a
human or a bench gate actually asks of a run:

- steady-state training throughput (epoch records flagged
  ``includes_compile`` are excluded — their wall clock is compile, not
  training; if ONLY such records exist the report says so rather than
  silently quoting a compile-polluted number);
- the compiled-program audit (schema-v3 ``xla_audit`` records,
  ``train.py --audit``): a MEMORY section (peak HBM vs per-chip capacity
  -> headroom, or an OOM forecast when the program exceeds it) and a
  COMMS section (collective census vs the layout contract, analytical
  bytes/step per device, bandwidth-bound lower-bound step time vs the
  compute lower bound -> comms- vs compute-bound verdict, the serial
  ``comm + compute`` vs overlapped ``max(comm, compute)`` step bounds,
  and the gradient-sync mode — anchor or N byte-buckets);
- an OVERLAP EFFICIENCY row — the hidden-comm share
  ``1 - exposed_comm / total_comm``: measured from a profiler trace's
  comm/compute split when ``--trace`` points at one
  (``observability.trace_stats``), else the comms model's
  perfect-overlap bound from the audit record;
- MFU + achieved FLOP/s and the cost-model cross-check (analytical vs
  XLA-reported FLOPs), with the peak's provenance so a nominal-CPU MFU
  cannot pass for a datasheet one;
- the span breakdown (where the host-side wall time went);
- the pipeline program's bubble fraction (mesh layouts) — equal-weight AND
  FLOP-weighted (the weighted row is what moves under ``--backward-split``:
  deferred B-weights pack into bubble ticks, see docs/lowering.md);
- a step-loss sparkline from the flight-recorder ``step`` records;
- the numerics health verdict (ok / N findings / halted-at-step);
- a RELIABILITY section (schema-v4 ``checkpoint``/``recovery`` records):
  checkpoint count + cadence + the overhead fraction (checkpoint wall
  over checkpoint + train-dispatch wall), and the recovery verdict —
  what was restored, every corrupt snapshot skipped, and the steps lost
  to replay when the stream holds the killed run's step records (feed
  the killed run's JSONL and the resumed run's concatenated, as
  ``make recovery-smoke`` does, and the loss is measured, not guessed);
- a SERVING section (schema-v5 ``request``/``serving`` records, the
  serving engine's evidence stream): completions + drops, p50/p99
  latency next to the analytical latency floor (inference ticks x
  per-tick cost), offered vs achieved vs goodput rates, queue depth,
  padding waste, and the SLO verdict against ``--slo-ms`` (or the
  summary record's own threshold) — plus a DEGRADATION subsection
  (schema-v6 ``serving_health``/``reload`` records and the terminal
  failure verdicts): shed/error/unhealthy counts, injected faults,
  breaker trips + hot reloads, the measured recovery time, and the
  availability verdict. Clean runs and pre-v6 files render unchanged;
- a FLEET section (schema-v7 ``fleet``/``fleet_health`` records, the
  serving fleet's evidence stream): replica lifecycle (started / died /
  retired, SIGKILLs injected by the chaos soak), failover count + the
  in-flight requests re-queued, verdict reroutes, elasticity (scale-ups
  with the measured ready time), per-replica routing counts + the
  routing skew, per-replica verdict rows (join the ``.r{replica_id}``
  JSONL shards on ``replica_id`` for each replica's own request
  stream — pass a glob like ``fleet.jsonl*`` to merge them), and the
  fleet availability verdict. Single-engine runs and pre-v7 files
  render unchanged;
- a TRACING section (schema-v10 ``trace`` records joined by
  ``observability.tracing``, docs/observability.md § Tracing): span
  chains assembled across the parent + ``.r*`` shards with the
  handshake-recorded per-replica clock offsets (shown with their
  uncertainty), the chain-completeness verdict (orphan/unclosed chains
  for terminal requests are NAMED, never glossed), aggregate phase
  attribution — mean and p99-CONDITIONAL (which phase dominates the
  slowest 1%, the makespan-quantization scoreboard) — SLO burn per
  phase, and per-request text waterfalls for the worst-k requests.
  Trace-free files render unchanged. A ``dispatch_overhead`` event (the
  ``train.py --dispatch-probe`` measured op-issue roofline) renders as
  its own summary row, flagged ``WINDOW INVALID`` when the probe's
  machine-checked validity guard refused the window (saturated trace
  buffer / no op events — the share must not be quoted clean);
- an ALERTS section (schema-v11 ``rollup``/``alert`` records,
  docs/observability.md § Live telemetry & alerting): the SLO alert
  firing→resolved timeline with peak burn rates and the still-firing
  set at end of stream, a FALSE-ALERT verdict (every fired rule is
  checked against the fault evidence that would justify it — chaos runs
  must alert, clean runs must not, and an unbacked firing is named),
  and rollup-backed trend sparklines (per-window throughput, p99
  latency, training loss). Pre-v11 files render unchanged.

``--baseline`` compares throughput against another run's JSONL or a
bench-style JSON record (``{"value": ..., "unit": "samples/s"}``, or a
tpu_capture artifact's ``headline_best_sps``). A regression beyond
``--threshold`` (default 10%) exits **2** — the CI/bench gate contract;
malformed inputs exit 1; a clean report exits 0.
"""

import argparse
import json
import math
import sys
from pathlib import Path

from shallowspeed_tpu.observability.metrics import json_safe, read_jsonl
from shallowspeed_tpu.observability.program_audit import format_bytes
from shallowspeed_tpu.observability.stats import percentile

BLOCKS = "▁▂▃▄▅▆▇█"  # ▁▂▃▄▅▆▇█


def _finite(v):
    return isinstance(v, (int, float)) and math.isfinite(v)


def _median(vals):
    s = sorted(vals)
    n = len(s)
    if n == 0:
        return None
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def sparkline(values, width=60):
    """Unicode sparkline, mean-pooled down to ``width`` buckets; non-finite
    samples render as ``x`` (a blown-up step must be visible, not blank)."""
    values = list(values)
    if not values:
        return ""
    if len(values) > width:
        # mean-pool each bucket; a bucket with any non-finite sample is x
        buckets = []
        for b in range(width):
            lo = b * len(values) // width
            hi = max(lo + 1, (b + 1) * len(values) // width)
            chunk = values[lo:hi]
            buckets.append(
                sum(chunk) / len(chunk) if all(_finite(v) for v in chunk)
                else float("nan")
            )
        values = buckets
    finite = [v for v in values if _finite(v)]
    if not finite:
        return "x" * len(values)
    vmin, vmax = min(finite), max(finite)
    span = vmax - vmin
    out = []
    for v in values:
        if not _finite(v):
            out.append("x")
        elif span <= 0:
            out.append(BLOCKS[3])
        else:
            out.append(BLOCKS[int((v - vmin) / span * (len(BLOCKS) - 1))])
    return "".join(out)


# ---------------------------------------------------------------------------
# report assembly
# ---------------------------------------------------------------------------


def build_report(records, source="", trace=None, slo_ms=None):
    """Fold a record stream into the JSON-able report dict every renderer
    (and the baseline comparison) consumes. ``trace``: an optional
    ``trace_stats.summarize`` dict — its measured comm/compute split
    upgrades the overlap-efficiency row from the model bound to a
    measurement. ``slo_ms``: the CLI's latency objective — overrides the
    serving summary's own threshold for the Serving section's SLO
    verdict."""
    epochs = [
        r for r in records if r.get("kind") == "event" and r.get("name") == "epoch"
    ]
    steady = [r for r in epochs if not r.get("includes_compile")]
    pool = steady or epochs
    sps = [r["samples_per_sec"] for r in pool if _finite(r.get("samples_per_sec"))]
    throughput = _median(sps)

    gauges = {}
    for r in records:
        if r.get("kind") == "gauge":
            gauges[r.get("name")] = r.get("value")  # last value wins

    spans = {}
    for r in records:
        if r.get("kind") == "span" and _finite(r.get("seconds")):
            agg = spans.setdefault(r.get("name"), {"count": 0, "total_s": 0.0})
            agg["count"] += 1
            agg["total_s"] += r["seconds"]
    span_rows = sorted(
        (
            {"name": n, "count": a["count"], "total_s": round(a["total_s"], 4)}
            for n, a in spans.items()
        ),
        key=lambda row: -row["total_s"],
    )

    steps = [r for r in records if r.get("kind") == "step"]
    step_losses = [r.get("loss") for r in steps]
    finite_losses = [v for v in step_losses if _finite(v)]

    cost = None
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "cost_model":
            cost = {
                k: v for k, v in r.items() if k not in ("v", "ts", "kind", "name")
            }

    audit = None
    audit_is_epoch = False
    for r in records:
        if r.get("kind") == "xla_audit":
            # last record wins, but prefer the epoch program over the fused
            # run (its census is the canonical per-step story): i.e. the
            # LAST epoch_program record, else the last audit of any name
            is_epoch = r.get("name") == "epoch_program"
            if is_epoch or not audit_is_epoch:
                audit = {k: v for k, v in r.items() if k not in ("v", "ts", "kind")}
                audit_is_epoch = audit_is_epoch or is_epoch

    prog = None
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "pipeline_program":
            prog = r
    bubble = (
        prog.get("bubble_fraction") if prog else gauges.get("pipeline.bubble_fraction")
    )
    # the FLOP-weighted bubble (PR5): the number that can see the
    # split-backward win — a combined backward tick costs 2x a forward's
    # work, so equal-weight cells under-state heavy-tick bubbles
    weighted_bubble = prog.get("weighted_bubble_fraction") if prog else None
    backward_split = bool(prog.get("backward_split")) if prog else False
    # the per-model activation-stash story (PR19): program_stats derives
    # the peak from the real spec's padded slot shapes and the actual tick
    # tables; a recompute run also carries its stashed twin's peak so the
    # Memory section can render the saving side by side from ONE stream
    stash_memory = None
    if prog and prog.get("stash_bytes_peak") is not None:
        stash_memory = {
            "model": prog.get("model"),
            "recompute": bool(prog.get("recompute")),
            "stash_slots": prog.get("stash_slots"),
            "xin_slots": prog.get("xin_slots"),
            "grad_stash_slots": prog.get("grad_stash_slots"),
            "stash_bytes_per_slot": prog.get("stash_bytes_per_slot"),
            "xin_bytes_per_slot": prog.get("xin_bytes_per_slot"),
            "stash_bytes_peak": prog.get("stash_bytes_peak"),
            "stash_bytes_peak_stashed_twin": prog.get(
                "stash_bytes_peak_stashed_twin"
            ),
            "stash_slots_stashed_twin": prog.get("stash_slots_stashed_twin"),
        }

    findings = [r for r in records if r.get("kind") == "health"]
    halted = [f for f in findings if f.get("action") == "halt"]
    by_check = {}
    for f in findings:
        by_check[f.get("name")] = by_check.get(f.get("name"), 0) + 1
    if halted:
        f = halted[0]
        where = f"epoch {f.get('epoch')}"
        if f.get("step") is not None:
            where += f", step {f.get('step')}"
        verdict = f"HALTED: {f.get('name')} at {where}"
    elif findings:
        verdict = f"{len(findings)} finding(s): " + ", ".join(
            f"{k} x{v}" for k, v in sorted(by_check.items())
        )
    else:
        verdict = "ok"

    # MFU: prefer the last steady epoch record's own field (per-epoch
    # truth), fall back to the last gauge; when only compile-polluted
    # records exist the MFU inherits their caveat (rendered alongside)
    mfu = None
    for r in pool:
        if _finite(r.get("mfu")):
            mfu = r["mfu"]
    if mfu is None and _finite(gauges.get("mfu")):
        mfu = gauges["mfu"]
    mfu_includes_compile = mfu is not None and bool(epochs) and not steady

    last_epoch = epochs[-1] if epochs else {}
    accuracy = last_epoch.get("accuracy")
    if accuracy is None:
        accuracy = gauges.get("val_accuracy")

    overlap = _overlap_info(audit, trace)
    reliability = _reliability_info(records, spans)
    serving = _serving_info(records, slo_ms)
    fleet = _fleet_info(records)
    static_analysis = _static_analysis_info(records)
    tracing_info = _tracing_info(records, slo_ms)
    alerts = _alerts_info(records)
    rollups = _rollups_info(records)
    divergence = _divergence_info(records)
    capacity = _capacity_info(records)

    dispatch_overhead = None
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "dispatch_overhead":
            dispatch_overhead = {
                k: v for k, v in r.items() if k not in ("v", "ts", "kind", "name")
            }

    return {
        "source": source,
        "schema_versions": sorted({r.get("v", 0) for r in records}),
        "epochs": len(epochs),
        "steady_epochs": len(steady),
        "throughput_samples_per_sec": throughput,
        "throughput_includes_compile": bool(epochs) and not steady,
        "final_loss": last_epoch.get("loss"),
        "final_accuracy": accuracy,
        "mfu": mfu,
        "mfu_includes_compile": mfu_includes_compile,
        "achieved_flops_per_sec": gauges.get("achieved_flops_per_sec"),
        "cost_model": cost,
        "xla_audit": audit,
        "overlap": overlap,
        "bubble_fraction": bubble,
        "weighted_bubble_fraction": weighted_bubble,
        "backward_split": backward_split,
        "stash_memory": stash_memory,
        "spans": span_rows,
        "steps": len(steps),
        "step_loss_sparkline": sparkline(step_losses) if steps else None,
        "step_loss": (
            {
                "first": step_losses[0],
                "last": step_losses[-1],
                "min": min(finite_losses) if finite_losses else None,
                "max": max(finite_losses) if finite_losses else None,
                "non_finite": len(step_losses) - len(finite_losses),
            }
            if steps
            else None
        ),
        "health": {
            "verdict": verdict,
            "findings": len(findings),
            "by_check": by_check,
            "halted": bool(halted),
        },
        "reliability": reliability,
        "serving": serving,
        "fleet": fleet,
        "static_analysis": static_analysis,
        "tracing": tracing_info,
        "alerts": alerts,
        "rollups": rollups,
        "divergence": divergence,
        "capacity": capacity,
        "dispatch_overhead": dispatch_overhead,
    }


# the fault evidence that JUSTIFIES each alert rule's firing: an alert
# with none of its evidence kinds anywhere in the stream is a FALSE
# alert (the alerts-smoke clean-twin contract — chaos runs must alert,
# clean runs must not, and a firing nobody can trace to a fault is
# named, never glossed). predicate(record) -> the record is evidence.
_ALERT_EVIDENCE = {
    "breaker_open": lambda r: (
        r.get("kind") == "serving_health" and r.get("name") == "breaker_open"
    ),
    "fleet_degraded": lambda r: (
        r.get("kind") == "fleet_health" and r.get("name") == "fleet_degraded"
    ),
    "error_burn": lambda r: (
        r.get("kind") == "request" and r.get("name") in ("error", "unhealthy")
    ),
    "p99_slo": lambda r: r.get("kind") == "request",
    "knee_proximity": lambda r: r.get("kind") == "request",
    "training_health": lambda r: r.get("kind") == "health",
    "checkpoint_overhead": lambda r: r.get("kind") == "checkpoint",
}


def _alerts_info(records):
    """Fold the schema-v11 ``alert`` records into the Alerts story; None
    when the run recorded none (pre-v11 files render exactly as
    before). The firing→resolved timeline, the still-firing set at end
    of stream (per rule + replica), the peak burn rates seen at any
    transition, and the false-alert verdict: every fired rule is checked
    against the fault evidence that would justify it."""
    alerts = [r for r in records if r.get("kind") == "alert"]
    if not alerts:
        return None
    timeline = []
    active = {}  # (rule, replica_id) -> last transition record
    fired = resolved = 0
    peak_fast = peak_slow = None
    for r in alerts:
        state = r.get("state")
        if state == "firing":
            fired += 1
        elif state == "resolved":
            resolved += 1
        for key, peak in (("burn_fast", "fast"), ("burn_slow", "slow")):
            v = r.get(key)
            if _finite(v):
                if peak == "fast":
                    peak_fast = v if peak_fast is None else max(peak_fast, v)
                else:
                    peak_slow = v if peak_slow is None else max(peak_slow, v)
        entry = {
            "rule": r.get("name"),
            "state": state,
            "severity": r.get("severity"),
            "t": r.get("t"),
            "value": r.get("value"),
            "threshold": r.get("threshold"),
            "reason": r.get("reason"),
            "replica_id": r.get("replica_id"),
        }
        timeline.append(entry)
        k = (entry["rule"], entry["replica_id"])
        if state == "firing":
            active[k] = entry
        else:
            active.pop(k, None)
    false_alerts = []
    for rule in sorted({e["rule"] for e in timeline if e["state"] == "firing"}):
        evidence = _ALERT_EVIDENCE.get(rule)
        if evidence is not None and not any(evidence(r) for r in records):
            false_alerts.append(rule)
    return {
        "transitions": len(timeline),
        "fired": fired,
        "resolved": resolved,
        "timeline": timeline,
        "still_firing": sorted(
            f"{rule}" + (f" (r{rid})" if rid is not None else "")
            for rule, rid in active
        ),
        "peak_burn_fast": peak_fast,
        "peak_burn_slow": peak_slow,
        "false_alerts": false_alerts,
    }


def _rollups_info(records):
    """Fold the schema-v11 ``rollup`` records into per-source trend
    series; None when the run recorded none. Sources are keyed
    ``name`` or ``name (rN)`` for replica-tagged shards; each carries
    the per-window terminal/step rate and p99 latency — the evidence
    behind the trend sparklines."""
    rollups = [r for r in records if r.get("kind") == "rollup"]
    if not rollups:
        return None
    by_source = {}
    for r in rollups:
        rid = r.get("replica_id")
        key = r.get("name", "?") + (f" (r{rid})" if rid is not None else "")
        by_source.setdefault(key, []).append(r)
    sources = {}
    for key, recs in sorted(by_source.items()):
        recs = sorted(
            recs, key=lambda r: (r.get("window_start") or 0, r.get("seq") or 0)
        )
        rates = []
        p99s = []
        losses = []
        for r in recs:
            rr = r.get("rates") or {}
            rate = (rr.get("terminal") or {}).get("rate")
            if rate is None:
                rate = (rr.get("steps") or {}).get("rate")
            rates.append(rate if _finite(rate) else 0.0)
            p99 = ((r.get("quantiles") or {}).get("latency_s") or {}).get(
                "p99"
            )
            if _finite(p99):
                p99s.append(p99)
            loss = ((r.get("gauges") or {}).get("loss") or {}).get("last")
            if _finite(loss):
                losses.append(loss)
        sources[key] = {
            "windows": len(recs),
            "window_s": recs[-1].get("window_s"),
            "late": sum(int(r.get("late") or 0) for r in recs),
            "rate_trend": rates,
            "p99_latency_s": (max(p99s) if p99s else None),
            "p99_trend": p99s or None,
            "loss_trend": losses or None,
        }
    return {"windows": len(rollups), "sources": sources}


def _tracing_info(records, slo_ms=None):
    """Fold the schema-v10 ``trace`` records into the Tracing story;
    None when the run recorded none (trace-free and pre-v10 files render
    exactly as before). Chains are assembled (and worker clocks aligned)
    by ``observability.tracing``; the report NAMES incomplete chains
    rather than rendering half a story as whole."""
    if not any(r.get("kind") == "trace" for r in records):
        return None
    from shallowspeed_tpu.observability import tracing

    chains = tracing.assemble_chains(records)
    problems = tracing.verify_terminal_chains(records, chains)
    att = tracing.attribution(chains, slo_ms=slo_ms)
    offsets = tracing.clock_offsets(records)
    degraded = sorted(
        {
            s.get("replica_id")
            for c in chains.values()
            if c.alignment == "missing"
            for s in c.spans
            if s.get("clock") == "worker"
        }
    )
    worst = []
    if att:
        worst = [
            {
                "trace_id": c.trace_id,
                "latency_s": c.latency_s,
                "verdict": c.verdict,
                "lines": tracing.waterfall(c),
            }
            for c in att.pop("worst")
        ]
    return {
        "spans": sum(
            1
            for r in records
            if r.get("kind") == "trace" and r.get("name") != "clock_offset"
        ),
        "chains": len(chains),
        "problems": problems,
        "alignment": {
            str(rid): off for rid, off in sorted(offsets.items(), key=lambda kv: str(kv[0]))
        },
        "alignment_missing_replicas": degraded,
        "attribution": att,
        "worst": worst,
    }


def _static_analysis_info(records):
    """Fold the schema-v9 ``static_analysis`` records into the one-line
    Static checks verdict; None when the run recorded none (pre-v9 files
    render exactly as before). One verdict per distinct program name —
    last record wins, so a refused-then-fixed rerun reads fixed."""
    by_program = {}
    for r in records:
        if r.get("kind") == "static_analysis":
            by_program[r.get("name")] = r
    if not by_program:
        return None
    passes = set()
    total = 0
    texts = []
    for name, r in sorted(by_program.items()):
        passes.update(r.get("passes") or ())
        n = int(r.get("findings") or 0)
        total += n
        if not n:
            continue
        # compile-time passes carry ONE refusal text ("finding"); a lint
        # run carries the per-finding lines ("finding_lines") — render
        # whichever evidence the record holds, never an unnamed count
        lines = r.get("finding_lines") or (
            [r["finding"]] if r.get("finding") else []
        )
        if lines:
            texts.extend(f"{name}: {line}" for line in lines)
        else:
            texts.append(f"{name}: {n} finding(s)")
    return {
        "programs": sorted(by_program),
        "passes": sorted(passes),
        "findings": total,
        "finding_text": texts,
    }


def _reliability_info(records, spans):
    """Fold the schema-v4 ``checkpoint``/``recovery`` records into the
    Reliability story; None when the run recorded neither (the section is
    then omitted — pre-v4 files render exactly as before).

    ``steps lost to replay`` is measured from EVIDENCE, never guessed: it
    needs the killed run's ``step`` records in the same stream before the
    recovery record (concatenate killed + resumed JSONL), and is the gap
    between the last step the dead run trained and the step the restore
    landed on. Without that evidence the field stays None (rendered as
    unknown)."""
    ckpts = [r for r in records if r.get("kind") == "checkpoint"]
    aot = _aot_cache_info(records)
    recoveries = []
    max_step_before = None
    last_step = None
    for r in records:
        if r.get("kind") == "step" and isinstance(r.get("step"), (int, float)):
            last_step = max(last_step or 0, int(r["step"]))
        elif r.get("kind") == "recovery":
            recoveries.append(r)
            max_step_before = last_step
    if not ckpts and not recoveries and aot is None:
        return None
    # for async saves (schema v8) wall_s is the ON-PATH cost only — the
    # snapshot + bounded-queue enqueue — so the overhead fraction below
    # automatically becomes the async scoreboard: same formula, the
    # off-path verify/write walls accounted separately
    ckpt_wall = sum(r["wall_s"] for r in ckpts if _finite(r.get("wall_s")))
    async_ckpts = [r for r in ckpts if r.get("async")]
    off_path_s = sum(
        (r.get("verify_s") or 0.0) + (r.get("write_s") or 0.0)
        for r in async_ckpts
        if _finite(r.get("verify_s")) or _finite(r.get("write_s"))
    )
    train_wall = sum(
        a["total_s"]
        for n, a in spans.items()
        if n in ("train_epoch", "train_steps", "train_run")
    )
    overhead = (
        ckpt_wall / (ckpt_wall + train_wall)
        if (ckpt_wall + train_wall) > 0
        else None
    )
    gsteps = sorted(
        int(r["global_step"]) for r in ckpts
        if isinstance(r.get("global_step"), (int, float))
    )
    cadence = None
    if len(gsteps) >= 2:
        deltas = [b - a for a, b in zip(gsteps, gsteps[1:])]
        cadence = _median(deltas)
    recovery = None
    if recoveries:
        rec = recoveries[-1]  # the decision that produced THIS run's state
        steps_lost = None
        resumed_at = rec.get("global_step")
        if isinstance(resumed_at, (int, float)) and max_step_before is not None:
            # the killed run's evidence IS in this stream — a kill that
            # landed exactly on a checkpointed step is a measured 0, not
            # unknown (clamped: a snapshot ahead of the step evidence can
            # never make the loss negative)
            steps_lost = max(0, int(max_step_before + 1 - resumed_at))
        recovery = {
            "verdict": rec.get("name"),
            "resumed_from": rec.get("resumed_from"),
            "epoch": rec.get("epoch"),
            "step_in_epoch": rec.get("step_in_epoch"),
            "global_step": resumed_at,
            "skipped": rec.get("skipped") or [],
            "steps_lost_to_replay": steps_lost,
        }
    return {
        "checkpoints": len(ckpts),
        "checkpoint_wall_s": round(ckpt_wall, 4),
        "checkpoint_overhead_fraction": overhead,
        "checkpoint_cadence_steps": cadence,
        "last_checkpoint_bytes": ckpts[-1].get("bytes") if ckpts else None,
        "checkpoints_async": len(async_ckpts),
        "checkpoint_off_path_s": round(off_path_s, 4),
        "aot_cache": aot,
        "recovery": recovery,
    }


def _aot_cache_info(records):
    """Fold the schema-v8 ``aot_cache`` records into the hit/miss story;
    None when the run recorded none (pre-v8 files render unchanged)."""
    recs = [r for r in records if r.get("kind") == "aot_cache"]
    if not recs:
        return None
    counts = {}
    for r in recs:
        counts[r.get("name")] = counts.get(r.get("name"), 0) + 1
    lookups = counts.get("hit", 0) + counts.get("miss", 0)
    hit_walls = [
        r["wall_s"] for r in recs
        if r.get("name") == "hit" and _finite(r.get("wall_s"))
    ]
    disabled = [r.get("reason") for r in recs if r.get("name") == "disabled"]
    return {
        "hits": counts.get("hit", 0),
        "misses": counts.get("miss", 0),
        "stores": counts.get("store", 0),
        "stale": counts.get("stale", 0),
        "corrupt": counts.get("corrupt", 0),
        "audit_mismatches": counts.get("audit_mismatch", 0),
        "fallbacks": counts.get("fallback", 0),
        "hit_rate": (counts.get("hit", 0) / lookups) if lookups else None,
        "hit_wall_s": sum(hit_walls) if hit_walls else None,
        "disabled_reason": disabled[0] if disabled else None,
    }


def _serving_info(records, slo_ms=None):
    """Fold the schema-v5 ``request``/``serving`` records into the Serving
    story; None when the run recorded neither (the section is then omitted
    — pre-v5 files render exactly as before).

    The LAST ``serving`` summary wins (the engine emits one per load run);
    percentiles are recomputed from the raw ``request`` records when no
    summary exists (a killed run keeps its per-request evidence). The SLO
    verdict scores p99 against ``slo_ms`` (the report CLI's ``--slo-ms``),
    falling back to the summary's own threshold; with neither, the verdict
    says "no SLO threshold" instead of guessing."""
    requests = [r for r in records if r.get("kind") == "request"]
    summary = None
    for r in records:
        if r.get("kind") == "serving":
            summary = {
                k: v for k, v in r.items() if k not in ("v", "ts", "kind", "name")
            }
    if summary is None and not requests:
        return None
    ok = [r for r in requests if r.get("name") == "ok"]
    dropped = [r for r in requests if r.get("name") == "dropped"]
    info = dict(summary) if summary else {}
    info.setdefault("completed", len(ok))
    info.setdefault("dropped", len(dropped))
    # the v6 terminal verdicts: prefer the summary's own counters, fall
    # back to counting raw request records (a killed run's evidence)
    for verdict in ("expired", "errors", "unhealthy"):
        name = verdict.rstrip("s") if verdict == "errors" else verdict
        if info.get(verdict) is None:
            n = sum(1 for r in requests if r.get("name") == name)
            info[verdict] = n
    info["degradation"] = _degradation_info(records, info)
    lats = [r["latency_s"] for r in ok if _finite(r.get("latency_s"))]
    if lats and info.get("p50_latency_s") is None:
        # the ONE shared percentile definition (observability.stats —
        # np.percentile, linear interpolation), so this killed-run
        # fallback can never disagree with the engine or fleet summary
        # on identical data; a rank index like int(0.99*n) would pick
        # the MAXIMUM for any n <= 100 and let one outlier flip the SLO
        # verdict
        info["p50_latency_s"] = percentile(lats, 50)
        info["p99_latency_s"] = percentile(lats, 99)
    eff_slo = slo_ms if slo_ms is not None else info.get("slo_ms")
    p99 = info.get("p99_latency_s")
    if eff_slo is None:
        verdict = "no SLO threshold (pass --slo-ms)"
    elif not _finite(p99):
        verdict = f"SLO {eff_slo:g} ms: no completed-request latencies"
    elif p99 <= eff_slo / 1000.0:
        verdict = f"SLO MET: p99 {p99 * 1e3:.2f} ms <= {eff_slo:g} ms"
    else:
        verdict = f"SLO VIOLATED: p99 {p99 * 1e3:.2f} ms > {eff_slo:g} ms"
    info["slo_effective_ms"] = eff_slo
    info["slo_verdict"] = verdict
    return info


def _degradation_info(records, srv):
    """Fold the schema-v6 ``serving_health``/``reload`` records plus the
    terminal failure verdicts into the Serving section's Degradation
    story; None when the run shows no degradation evidence at all (clean
    runs — and every pre-v6 file — render exactly as before).

    ``availability`` is ok / every-terminal-verdict; the recovery time
    prefers the engine's own measurement (breaker-open -> first served
    response, in the summary) and falls back to the record timestamps
    (first ``breaker_open`` -> first subsequent successful ``reload``)."""
    health = [r for r in records if r.get("kind") == "serving_health"]
    reloads = [r for r in records if r.get("kind") == "reload"]
    shed = srv.get("expired") or 0
    errors = srv.get("errors") or 0
    unhealthy = srv.get("unhealthy") or 0
    trips = srv.get("breaker_trips")
    if trips is None:
        trips = sum(1 for r in health if r.get("name") == "breaker_open")
    n_reloads = srv.get("reloads")
    if n_reloads is None:
        n_reloads = sum(1 for r in reloads if r.get("name") == "ok")
    if not (health or reloads or shed or errors or unhealthy):
        return None
    recovery_s = srv.get("recovery_s")
    opens = [r.get("ts") for r in health if r.get("name") == "breaker_open"]
    if recovery_s is None and opens and _finite(opens[0]):
        after = [
            r.get("ts")
            for r in reloads
            if r.get("name") == "ok"
            and _finite(r.get("ts"))
            and r["ts"] >= opens[0]
        ]
        if after:
            recovery_s = after[0] - opens[0]
    closed = [r for r in health if r.get("name") == "breaker_closed"]
    degraded = srv.get("degraded")
    if degraded is None:
        # record-order fallback: an open with no close after it
        last_open = max(
            (i for i, r in enumerate(health) if r.get("name") == "breaker_open"),
            default=None,
        )
        last_close = max(
            (i for i, r in enumerate(health) if r.get("name") == "breaker_closed"),
            default=None,
        )
        degraded = last_open is not None and (
            last_close is None or last_close < last_open
        )
    injected = sum(1 for r in health if r.get("name") == "fault_injected")
    avail = srv.get("availability")
    if avail is None:
        # killed-run fallback: fold availability from the raw verdict
        # counts when no serving summary landed
        ok_n = srv.get("completed") or 0
        terminal = ok_n + (srv.get("dropped") or 0) + shed + errors + unhealthy
        avail = ok_n / terminal if terminal else None
    if degraded:
        verdict = "DEGRADED at exit: breaker open, admission refused"
    elif trips:
        verdict = "recovered: breaker closed" + (
            f" ({_fmt_time_s(recovery_s)} to first served response)"
            if recovery_s is not None
            else ""
        )
    else:
        verdict = "no breaker trips"
    return {
        "shed_expired": shed,
        "errors": errors,
        "unhealthy": unhealthy,
        "retries": srv.get("retries"),
        "failed_dispatches": srv.get("failed_dispatches"),
        "faults_injected": injected,
        "breaker_trips": trips,
        "breaker_closed_events": len(closed),
        "reloads": n_reloads,
        # what the recovery wall actually spent verifying snapshots
        # (schema-v8 reload.verify_s — the single-verified-read path's
        # discovery cost, previously invisible inside wall_s)
        "reload_verify_s": (
            sum(
                r["verify_s"] for r in reloads
                if r.get("name") == "ok" and _finite(r.get("verify_s"))
            )
            if any(
                r.get("name") == "ok" and _finite(r.get("verify_s"))
                for r in reloads
            )
            else None
        ),
        "recovery_s": recovery_s,
        "availability": avail,
        "degraded_at_exit": bool(degraded),
        "verdict": verdict,
    }


def _fleet_info(records):
    """Fold the schema-v7 ``fleet``/``fleet_health`` records into the
    Fleet story; None when the run recorded neither (single-engine runs
    and every pre-v7 file render exactly as before).

    The LAST ``fleet`` summary wins (the fleet emits one per load run);
    the lifecycle counters fall back to counting ``fleet_health`` events
    when no summary landed (a killed PARENT keeps its per-event
    evidence, the same discipline as the Serving fallback). The
    ``replica_id`` on every event is the join key into the per-replica
    ``.r{id}`` JSONL shards."""
    health = [r for r in records if r.get("kind") == "fleet_health"]
    summary = None
    for r in records:
        if r.get("kind") == "fleet":
            summary = {
                k: v for k, v in r.items() if k not in ("v", "ts", "kind", "name")
            }
    if summary is None and not health:
        return None
    info = dict(summary) if summary else {}

    def count(name):
        return sum(1 for r in health if r.get("name") == name)

    if info.get("replicas_started") is None:
        info["replicas_started"] = count("replica_spawned")
    if info.get("replicas_dead") is None:
        info["replicas_dead"] = count("replica_dead")
    if info.get("replicas_retired") is None:
        info["replicas_retired"] = count("replica_retired")
    if info.get("failovers") is None:
        info["failovers"] = count("failover")
    if info.get("failover_requeued") is None:
        info["failover_requeued"] = sum(
            r.get("requeued") or 0 for r in health if r.get("name") == "failover"
        )
    if info.get("reroutes") is None:
        info["reroutes"] = count("reroute")
    if info.get("scale_ups") is None:
        info["scale_ups"] = count("scale_up")
    if info.get("scale_downs") is None:
        info["scale_downs"] = count("scale_down")
    info["sigkills_injected"] = count("replica_sigkill")
    degraded = info.get("degraded")
    if degraded is None:
        # record-order fallback: a fleet_degraded with no recovery after
        last_deg = max(
            (i for i, r in enumerate(health) if r.get("name") == "fleet_degraded"),
            default=None,
        )
        last_rec = max(
            (i for i, r in enumerate(health) if r.get("name") == "fleet_recovered"),
            default=None,
        )
        degraded = last_deg is not None and (
            last_rec is None or last_rec < last_deg
        )
    info["degraded_at_exit"] = bool(degraded)
    if info["degraded_at_exit"]:
        verdict = "FLEET DEGRADED at exit: quorum down, admission refused"
    elif info["replicas_dead"] or info["failovers"]:
        verdict = (
            f"recovered from {info['replicas_dead']} replica death(s): "
            f"{info['failovers']} failover(s)"
            + (
                f", {_fmt_time_s(info.get('recovery_s'))} to next served "
                "response"
                if info.get("recovery_s") is not None
                else ""
            )
        )
    else:
        verdict = "healthy: no replica deaths"
    info["verdict"] = verdict
    return info


def _overlap_info(audit, trace):
    """The overlap-efficiency story: hidden-comm share ``1 -
    exposed_comm / total_comm``. A measured trace split (trace_stats)
    wins; else the comms model's perfect-overlap bound from the audit's
    ``expected`` contract; None when neither source knows anything."""
    exp = (audit or {}).get("expected") or {}
    info = None
    if _finite(exp.get("model_hidden_comm_share")):
        axis = (exp.get("axes") or {}).get("dp") or {}
        info = {
            "source": "model",
            "hidden_comm_share": exp["model_hidden_comm_share"],
            "serial_bound_s": exp.get("serial_bound_s"),
            "overlapped_bound_s": exp.get("overlapped_bound_s"),
            "sync_mode": axis.get("mode"),
            "num_buckets": axis.get("num_buckets"),
        }
    if trace and _finite(trace.get("overlap_efficiency")):
        info = dict(info or {})
        info.update(
            source="measured",
            hidden_comm_share=trace["overlap_efficiency"],
            comm_ms=trace.get("comm_ms"),
            exposed_comm_ms=trace.get("exposed_comm_ms"),
            comm_fraction=trace.get("comm_fraction"),
        )
    return info


def baseline_throughput(path):
    """-> ``(samples_per_sec, label)`` from a baseline file, or ``(None,
    reason)``. ``.jsonl`` is another metrics stream (same steady-state
    rules; multihost shard names/globs like ``run.jsonl.p*`` count too);
    ``.json`` accepts a bench record (``value`` + samples/s unit)
    or a tpu_capture artifact (``headline_best_sps``)."""
    p = Path(path)
    if p.suffix == ".jsonl" or ".jsonl." in p.name:
        base = build_report(read_jsonl(p), source=str(p))
        tp = base["throughput_samples_per_sec"]
        if tp is None:
            return None, f"{p}: no epoch throughput records"
        if base["throughput_includes_compile"]:
            # refusing beats silently trusting an understated baseline: a
            # compile-polluted baseline would let real regressions pass
            return None, (
                f"{p}: only compile-polluted throughput records (no "
                "steady-state epoch) — not usable as a regression baseline"
            )
        return tp, f"{p} (median steady-state)"
    data = json.loads(p.read_text())
    if isinstance(data, dict):
        if _finite(data.get("value")) and data.get("unit") == "samples/s":
            return float(data["value"]), f"{p} ({data.get('metric', 'value')})"
        if _finite(data.get("headline_best_sps")):
            return float(data["headline_best_sps"]), f"{p} (headline_best_sps)"
        if _finite(data.get("samples_per_sec")):
            return float(data["samples_per_sec"]), f"{p} (samples_per_sec)"
    return None, f"{p}: no recognizable throughput field"


def compare(report, base_tp, base_label, threshold):
    """Throughput-vs-baseline verdict; ``regression`` drives the exit
    code. Positive ``delta_fraction`` = faster than baseline. A run whose
    only throughput records include compile time (a 1-epoch job) is NOT
    gated — compile wall clock vs a steady-state baseline would flag a
    spurious regression on every short run; the comparison is still
    rendered, marked ``compile_polluted``."""
    cur = report["throughput_samples_per_sec"]
    delta = (cur - base_tp) / base_tp if base_tp else None
    polluted = bool(report["throughput_includes_compile"])
    return {
        "baseline": base_label,
        "baseline_samples_per_sec": base_tp,
        "delta_fraction": delta,
        "threshold": threshold,
        "compile_polluted": polluted,
        "regression": not polluted and delta is not None and delta < -threshold,
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def _fmt_num(v, unit="", pct=False):
    if v is None:
        return "n/a"
    if not isinstance(v, (int, float)) or not math.isfinite(v):
        return str(v)  # the sink's sanitized non-finite markers ("NaN", ...)
    if pct:
        return f"{v * 100:.2f}%"
    if abs(v) >= 1e9:
        return f"{v / 1e9:,.2f} G{unit}"
    if abs(v) >= 1e6:
        return f"{v / 1e6:,.2f} M{unit}"
    return f"{v:,.2f} {unit}".rstrip()


def _rows(report):
    tp = report["throughput_samples_per_sec"]
    rows = [
        ("epochs recorded", str(report["epochs"])),
        (
            "throughput",
            _fmt_num(tp, "samples/s")
            + (
                "  (includes compile — no steady-state epoch recorded)"
                if report["throughput_includes_compile"]
                else ""
            ),
        ),
        (
            "MFU",
            _fmt_num(report["mfu"], pct=True)
            + (
                "  (includes compile)"
                if report.get("mfu_includes_compile")
                else ""
            ),
        ),
        ("achieved FLOP/s", _fmt_num(report["achieved_flops_per_sec"], "FLOP/s")),
        ("final loss", _fmt_num(report["final_loss"])),
    ]
    if report["final_accuracy"] is not None:
        rows.append(("final accuracy", _fmt_num(report["final_accuracy"], pct=True)))
    if report["bubble_fraction"] is not None:
        rows.append(("pipeline bubble", _fmt_num(report["bubble_fraction"], pct=True)))
    if report.get("weighted_bubble_fraction") is not None:
        rows.append(
            (
                "weighted bubble",
                _fmt_num(report["weighted_bubble_fraction"], pct=True)
                + (
                    "  (split backward: B-weights packed into bubbles)"
                    if report.get("backward_split")
                    else "  (FLOP-weighted ticks)"
                ),
            )
        )
    ov = report.get("overlap")
    if ov is not None:
        share = _fmt_num(ov.get("hidden_comm_share"), pct=True)
        if ov["source"] == "measured":
            detail = (
                f"{share} of comm hidden (measured: "
                f"{_fmt_num(ov.get('exposed_comm_ms'))} ms exposed of "
                f"{_fmt_num(ov.get('comm_ms'))} ms comm)"
            )
        else:
            mode = ov.get("sync_mode")
            sync = (
                f"{ov.get('num_buckets')} buckets" if mode == "bucketed"
                else "anchor sync"
            )
            detail = f"{share} of comm hideable (model bound; {sync})"
        rows.append(("overlap efficiency", detail))
    do = report.get("dispatch_overhead")
    if do is not None:
        share = do.get("dispatch_overhead")
        if share is None:
            detail = "unmeasurable — " + str(do.get("reason", "no op events"))
        else:
            detail = (
                f">= {_fmt_num(share, pct=True)} of {do.get('program')} "
                f"wall is host-side op issue (op busy "
                f"{_fmt_time_s(do.get('device_busy_s'))} of "
                f"{_fmt_time_s(do.get('host_wall_s'))} uninstrumented "
                f"wall; measured lower bound, {do.get('op_source')})"
            )
        if do.get("window_valid") is False:
            # the machine-checked probe-validity guard (api.py): an
            # invalid window's share is flagged, never quoted clean
            detail += "  [WINDOW INVALID: " + str(
                do.get("window_invalid_reason") or "unknown"
            ) + "]"
        rows.append(("dispatch overhead", detail))
    sa = report.get("static_analysis")
    if sa is not None:
        if sa["findings"]:
            detail = (
                f"{sa['findings']} finding(s) — " + "; ".join(sa["finding_text"])
            )
        else:
            detail = (
                f"{len(sa['programs'])} program(s) clean "
                f"({', '.join(sa['passes'])})"
            )
        rows.append(("static checks", detail))
    rows.append(("health", report["health"]["verdict"]))
    return rows


def _cost_lines(cost):
    if not cost:
        return ["cost model: not recorded"]
    lines = [
        f"cost model: {_fmt_num(cost.get('flops_per_sample'), 'FLOP')}/sample "
        f"analytical; peak {_fmt_num(cost.get('peak_flops_per_chip'), 'FLOP/s')}"
        f"/chip x {cost.get('n_devices')} ({cost.get('peak_source')})"
    ]
    ratio = cost.get("flops_ratio")
    if ratio is not None:
        lines.append(
            f"  XLA cross-check: {_fmt_num(cost.get('xla_flops_per_epoch'), 'FLOP')}"
            f"/epoch compiled = {ratio:.3g}x analytical (scan bodies counted "
            "once by XLA's analysis — watch for MOVES, not 1.0)"
        )
    if cost.get("padded_ratio") is not None:
        lines.append(f"  padding tax: {cost['padded_ratio']:.2f}x logical FLOPs")
    return lines


def _fmt_time_s(t):
    if t is None or not isinstance(t, (int, float)) or not math.isfinite(t):
        return "n/a"
    if t >= 1.0:
        return f"{t:.3f} s"
    if t >= 1e-3:
        return f"{t * 1e3:.2f} ms"
    return f"{t * 1e6:.1f} µs"


def _memory_lines(audit, md, stash=None):
    """The memory section: compiled-program peak HBM vs per-chip capacity
    -> headroom, or an OOM forecast when the program does not fit — plus
    the per-model activation-stash peak (PR19): a recompute run renders
    its peak NEXT TO its stashed twin's (both from real tick tables), and
    the OOM forecast says what the twin's extra stash would do to the
    compiled peak."""
    mem = (audit or {}).get("memory")
    if not mem and not stash:
        return []
    lines = ["## Memory (compiled program)" if md else "memory (compiled program):"]
    peak = (mem or {}).get("peak_hbm_bytes")
    if mem:
        cap = audit.get("hbm_per_chip")
        head = audit.get("hbm_headroom_fraction")
        # memory_analysis sizes are per device (the addressable shard), so
        # the peak compares against one chip's capacity directly
        line = f"peak HBM: {format_bytes(peak)} (per device)"
        if cap and head is not None:
            if head < 0:
                line += (
                    f" — OOM FORECAST: exceeds the {format_bytes(cap)}/chip "
                    f"capacity ({audit.get('hbm_source')}) by "
                    f"{format_bytes(-head * cap)}"
                )
            else:
                line += (
                    f" of {format_bytes(cap)}/chip ({audit.get('hbm_source')}) "
                    f"— {head * 100:.1f}% headroom"
                )
        lines.append(line)
        lines.append(
            "  args {a} + output {o} + temp {t} (aliased {al})".format(
                a=format_bytes(mem.get("argument_size_in_bytes")),
                o=format_bytes(mem.get("output_size_in_bytes")),
                t=format_bytes(mem.get("temp_size_in_bytes")),
                al=format_bytes(mem.get("alias_size_in_bytes")),
            )
        )
        # the per-stage ZeRO OOM forecast (program_audit.zero_peak_forecast):
        # the params+grads+state ÷ dp residency claim, scored against the
        # chip capacity next to the MEASURED compiled peak above
        exp = audit.get("expected") or {}
        zf = exp.get("zero_forecast")
        if zf and not exp.get("inference"):
            stage = str(exp.get("zero", 0))
            stages = zf.get("stages") or {}
            cur = stages.get(stage)
            if cur:
                line = (
                    f"ZeRO forecast [stage {stage}]: "
                    f"{format_bytes(cur['total_bytes'])}/device model state "
                    f"(params {format_bytes(cur['params_bytes'])} + grads "
                    f"{format_bytes(cur['grads_bytes'])} + opt state "
                    f"{format_bytes(cur['state_bytes'])}"
                )
                if cur.get("transient_bytes"):
                    line += (
                        f" + {format_bytes(cur['transient_bytes'])} "
                        "gathered-chunk transient"
                    )
                line += ")"
                cap = audit.get("hbm_per_chip")
                if cap:
                    frac = cur["total_bytes"] / cap
                    if frac > 1:
                        line += (
                            f" — OOM FORECAST: model state alone exceeds "
                            f"{format_bytes(cap)}/chip"
                        )
                    else:
                        line += (
                            f" — {(1 - frac) * 100:.1f}% headroom of "
                            f"{format_bytes(cap)}/chip"
                        )
                lines.append(line)
                lines.append(
                    "  stage ladder (model state/device): "
                    + " -> ".join(
                        f"z{k} {format_bytes(v['total_bytes'])}"
                        for k, v in sorted(stages.items())
                    )
                )
    if stash:
        model = stash.get("model") or "mnist-mlp"
        speak = stash.get("stash_bytes_peak")
        if stash.get("recompute"):
            twin = stash.get("stash_bytes_peak_stashed_twin")
            line = (
                f"activation stash [{model}]: peak {format_bytes(speak)}"
                f"/device under recompute ({stash.get('stash_slots')} "
                f"residual + {stash.get('xin_slots')} input slot(s)) vs "
                f"{format_bytes(twin)} stashed twin "
                f"({stash.get('stash_slots_stashed_twin')} slot(s))"
            )
            if twin and speak is not None and twin > 0:
                line += f" — {(1 - speak / twin) * 100:.0f}% smaller"
            lines.append(line)
            cap = (audit or {}).get("hbm_per_chip")
            if (
                twin
                and speak is not None
                and _finite(peak)
                and cap
            ):
                # what the stashed twin would cost THIS model on THIS
                # chip: the compiled peak plus the stash delta, scored
                # against capacity — the per-model OOM forecast
                would = peak + (twin - speak)
                frac = would / cap
                lines.append(
                    f"  stashed-twin forecast: peak HBM would be "
                    f"{format_bytes(would)} ({frac * 100:.1f}% of "
                    f"{format_bytes(cap)}/chip"
                    + (" — OOM FORECAST)" if frac > 1 else ")")
                )
        else:
            lines.append(
                f"activation stash [{model}]: peak {format_bytes(speak)}"
                f"/device ({stash.get('stash_slots')} slot(s), stashed — "
                "rerun with --recompute to trade FLOPs for this footprint)"
            )
    lines.append("")
    return lines


def _comms_lines(audit, md):
    """The comms section: the compiled program's collective census vs the
    layout contract, the analytical bytes/step, and the bandwidth-bound
    lower-bound verdict next to the compute bound."""
    if not audit:
        return []
    census = audit.get("census") or {}
    exp = audit.get("expected") or {}
    lines = ["## Comms (XLA program audit)" if md else "comms (XLA program audit):"]
    if census:
        kinds = ", ".join(
            f"{k} x{v['count']} ({format_bytes(v['bytes'])})"
            for k, v in sorted(census.items())
        )
    elif audit.get("hlo_available") is False:
        kinds = "unavailable (backend exposed no HLO text)"
    elif exp.get("sequential"):
        kinds = "none (sequential program)"
    else:
        kinds = "none"
    ok = audit.get("census_ok")
    if ok is True:
        verdict = "matches the layout contract"
    elif ok is False:
        verdict = "CONTRACT MISMATCH: " + "; ".join(audit.get("mismatches", ()))
    else:
        verdict = "contract not checked"
    lines.append(f"census [{audit.get('name', 'program')}]: {kinds} — {verdict}")
    if exp:
        parts = []
        for axis, a in sorted((exp.get("axes") or {}).items()):
            parts.append(
                f"{axis} {a.get('kind')} {format_bytes(a.get('bytes_per_step_per_device'))}"
            )
        total = exp.get("bytes_per_step_per_device")
        line = f"model: {format_bytes(total)}/step/device"
        if parts:
            line += " (" + " + ".join(parts) + ")"
        lines.append(line)
        dp_axis = (exp.get("axes") or {}).get("dp") or {}
        stage = dp_axis.get("zero") or 0
        if stage:
            # the per-stage dp-traffic shape: the sharded stages replace
            # the anchor all-reduce with gradient reduce-scatter (sharded
            # result) + a deferred all-gather of the updated-param chunk;
            # anchor zero-2 and zero-3 scatter PER TICK (one contribution
            # per microbatch into the persistent shard), and stage 3 adds
            # the JIT parameter-gather schedule on top
            rs = dp_axis.get(
                "reduce_scatter_bytes_per_step_per_device",
                (dp_axis.get("bytes_per_step_per_device") or 0) / 2,
            )
            line = (
                f"ZeRO stage {stage}: gradient reduce-scatter "
                f"{format_bytes(rs)}/step/device"
            )
            sched = dp_axis.get("scatter_schedule")
            if sched:
                line += (
                    f" ({sched} x {dp_axis.get('scatter_mubatches')} "
                    "microbatches into the persistent 1/dp shard)"
                )
            else:
                line += " (tail scatter; result is the 1/dp shard)"
            gather = dp_axis.get("gather")
            if gather:
                line += (
                    f" + JIT param gather {format_bytes(gather.get('bytes_per_step_per_device'))}"
                    f"/step/device ({gather.get('schedule')}: "
                    f"{gather.get('passes')} passes x "
                    f"{gather.get('mubatches')} microbatches)"
                )
            else:
                line += " + post-update param all-gather of the updated chunk"
            lines.append(line)
        if dp_axis.get("mode") == "bucketed":
            # "budget", not "<=": a single leaf larger than the budget
            # gets its own oversized bucket (the planner never splits one)
            sizes = dp_axis.get("bucket_grad_bytes") or []
            lines.append(
                f"gradient sync: bucketed — {dp_axis.get('num_buckets')} "
                f"collectives, budget "
                f"{format_bytes(dp_axis.get('grad_bucket_bytes'))}/bucket "
                f"(largest bucket "
                f"{format_bytes(max(sizes) if sizes else None)}); "
                "total bytes unchanged vs the anchor"
            )
        ct, xt = exp.get("comms_time_per_step_s"), exp.get("compute_time_per_step_s")
        if ct is not None or xt is not None:
            bound = exp.get("bound")
            lines.append(
                f"lower bounds: comms {_fmt_time_s(ct)} @ "
                f"{_fmt_num(exp.get('bandwidth_bytes_per_sec'), 'B/s')} "
                f"({exp.get('bandwidth_source')}) vs compute {_fmt_time_s(xt)}"
                + (f" — {bound}-bound" if bound else "")
            )
            st, ot = exp.get("serial_bound_s"), exp.get("overlapped_bound_s")
            if st is not None and ot is not None:
                lines.append(
                    f"step-time bounds: serial (anchor) {_fmt_time_s(st)} "
                    f"= comm + compute; overlapped (bucketed, perfect) "
                    f"{_fmt_time_s(ot)} = max(comm, compute)"
                )
    lines.append("")
    return lines


def _reliability_lines(rel, md):
    """The Reliability section: checkpoint overhead, cadence, and the
    recovery verdict with its evidence (skipped snapshots, replay loss)."""
    if not rel:
        return []
    lines = ["## Reliability" if md else "reliability:"]
    if rel["checkpoints"]:
        line = (
            f"checkpoints: {rel['checkpoints']} written "
            f"({_fmt_time_s(rel['checkpoint_wall_s'])} total"
        )
        if rel.get("checkpoint_overhead_fraction") is not None:
            line += (
                f" — {rel['checkpoint_overhead_fraction'] * 100:.1f}% "
                f"overhead vs train dispatch"
            )
        line += ")"
        if rel.get("checkpoint_cadence_steps") is not None:
            line += f", every ~{rel['checkpoint_cadence_steps']:.0f} steps"
        if rel.get("last_checkpoint_bytes") is not None:
            line += f", {format_bytes(rel['last_checkpoint_bytes'])} each"
        lines.append(line)
        if rel.get("checkpoints_async"):
            lines.append(
                f"async checkpointing: {rel['checkpoints_async']} of "
                f"{rel['checkpoints']} saves off-path (on-path wall is the "
                f"overhead above; verify+write "
                f"{_fmt_time_s(rel.get('checkpoint_off_path_s'))} ran in "
                "the background writer)"
            )
    aot = rel.get("aot_cache")
    if aot is not None:
        if aot.get("hit_rate") is not None:
            line = (
                f"aot executable cache: {aot['hits']} hit(s) / "
                f"{aot['misses']} miss(es) "
                f"(hit rate {aot['hit_rate'] * 100:.0f}%"
                + (
                    f", deserialize {_fmt_time_s(aot['hit_wall_s'])} vs "
                    "a cold recompile"
                    if aot.get("hit_wall_s") is not None
                    else ""
                )
                + ")"
            )
        else:
            line = "aot executable cache: no lookups"
        if aot.get("stores"):
            line += f", {aot['stores']} entr(ies) written"
        lines.append(line)
        bad = []
        if aot.get("stale"):
            bad.append(f"{aot['stale']} stale")
        if aot.get("corrupt"):
            bad.append(f"{aot['corrupt']} corrupt")
        if aot.get("audit_mismatches"):
            bad.append(f"{aot['audit_mismatches']} audit-mismatched")
        if bad:
            lines.append(
                "  " + ", ".join(bad)
                + " entr(ies) fell back to a clean recompile"
            )
        if aot.get("disabled_reason"):
            lines.append(
                f"  cache disabled on this backend: {aot['disabled_reason']}"
            )
    rec = rel.get("recovery")
    if rec is not None:
        if rec["verdict"] == "resumed":
            where = f"epoch {rec.get('epoch')}, step {rec.get('step_in_epoch')}"
            line = (
                f"recovery: resumed from {rec.get('resumed_from')} at {where} "
                f"(global step {rec.get('global_step')})"
            )
        else:
            line = "recovery: fresh start (no resumable snapshot found)"
        if rec["skipped"]:
            line += f"; {len(rec['skipped'])} corrupt snapshot(s) skipped"
        lines.append(line)
        for s in rec["skipped"]:
            lines.append(f"  skipped {s.get('path')}: {s.get('cause')}")
        lost = rec.get("steps_lost_to_replay")
        lines.append(
            f"steps lost to replay: "
            + (
                f"{lost} (re-trained after restore — bit-identical by contract)"
                if lost is not None
                else "unknown (killed run's step records not in this stream)"
            )
        )
    lines.append("")
    return lines


def _serving_lines(srv, md):
    """The Serving section: completions, latency percentiles vs the model
    floor, goodput vs offered load, queue depth, padding waste, and the
    SLO verdict (docs/serving.md)."""
    if not srv:
        return []
    lines = ["## Serving" if md else "serving:"]
    line = f"requests: {srv.get('completed')} completed"
    if srv.get("dropped"):
        line += f", {srv['dropped']} DROPPED"
    if srv.get("expired"):
        line += f", {srv['expired']} expired"
    if srv.get("errors"):
        line += f", {srv['errors']} ERRORED"
    if srv.get("unhealthy"):
        line += f", {srv['unhealthy']} UNHEALTHY"
    if srv.get("dispatches") is not None:
        line += (
            f" over {srv['dispatches']} dispatches "
            f"({srv.get('slots_dispatched')} slots)"
        )
    lines.append(line)
    lat = (
        f"latency: p50 {_fmt_time_s(srv.get('p50_latency_s'))}, "
        f"p99 {_fmt_time_s(srv.get('p99_latency_s'))}"
    )
    if srv.get("latency_bound_s") is not None:
        lat += (
            f" — model floor {_fmt_time_s(srv['latency_bound_s'])}"
            + (
                f" ({srv['latency_bound_ticks']} ticks, "
                f"{srv.get('latency_bound_source')})"
                if srv.get("latency_bound_ticks") is not None
                else f" ({srv.get('latency_bound_source')})"
            )
        )
    lines.append(lat)
    tp = []
    if _finite(srv.get("offered_rps")):
        tp.append(f"offered {srv['offered_rps']:g} rps")
    if _finite(srv.get("achieved_rps")):
        tp.append(f"achieved {srv['achieved_rps']:.1f} rps")
    if _finite(srv.get("goodput_rps")):
        tp.append(f"goodput {srv['goodput_rps']:.1f} rps (within SLO)")
    if tp:
        lines.append("throughput: " + ", ".join(tp))
    extras = []
    if _finite(srv.get("padding_waste")):
        extras.append(f"padding waste {srv['padding_waste'] * 100:.1f}%")
    if srv.get("queue_depth_max") is not None:
        extras.append(
            f"queue depth max {srv['queue_depth_max']}"
            + (
                f" (mean {srv['queue_depth_mean']:.1f})"
                if _finite(srv.get("queue_depth_mean"))
                else ""
            )
        )
    if extras:
        lines.append(", ".join(extras))
    lines.append(srv.get("slo_verdict", ""))
    deg = srv.get("degradation")
    if deg:
        lines.append("")
        lines.append("### Degradation" if md else "degradation:")
        counts = (
            f"shed (expired) {deg['shed_expired']}, errors {deg['errors']}, "
            f"unhealthy {deg['unhealthy']}"
        )
        if deg.get("retries"):
            counts += f", {deg['retries']} retried dispatch slot(s)"
        if deg.get("faults_injected"):
            counts += f", {deg['faults_injected']} fault(s) injected"
        lines.append(counts)
        breaker = (
            f"breaker: {deg['breaker_trips']} trip(s), "
            f"{deg['reloads']} hot reload(s)"
        )
        if deg.get("recovery_s") is not None:
            breaker += f", recovery {_fmt_time_s(deg['recovery_s'])}"
        if deg.get("reload_verify_s") is not None:
            breaker += (
                f" (snapshot verify {_fmt_time_s(deg['reload_verify_s'])}, "
                "single-read)"
            )
        lines.append(breaker)
        avail = deg.get("availability")
        lines.append(
            (
                f"availability {avail * 100:.1f}% — {deg['verdict']}"
                if _finite(avail)
                else deg["verdict"]
            )
        )
    lines.append("")
    return lines


def _fleet_lines(fl, md):
    """The Fleet section: replica lifecycle, routing skew, failover +
    elasticity accounting, per-replica verdict rows, and the fleet
    verdict (docs/serving.md "Fleet")."""
    if not fl:
        return []
    lines = ["## Fleet" if md else "fleet:"]
    line = (
        f"replicas: {fl.get('replicas_started')} started"
        + (
            f" (target {fl['replicas_target']}, {fl.get('replicas_ready')} "
            "ready at exit)"
            if fl.get("replicas_target") is not None
            else ""
        )
    )
    if fl.get("replicas_dead"):
        line += f", {fl['replicas_dead']} DIED"
        if fl.get("sigkills_injected"):
            line += f" ({fl['sigkills_injected']} SIGKILL injected)"
    if fl.get("replicas_retired"):
        line += f", {fl['replicas_retired']} retired"
    lines.append(line)
    fo = (
        f"failover: {fl.get('failovers', 0)} event(s), "
        f"{fl.get('failover_requeued', 0)} in-flight request(s) re-queued"
    )
    if fl.get("failover_exhausted"):
        fo += f", {fl['failover_exhausted']} budget-exhausted"
    if fl.get("reroutes"):
        fo += f"; {fl['reroutes']} verdict reroute(s)"
    lines.append(fo)
    if fl.get("scale_ups") or fl.get("scale_downs"):
        sc = (
            f"elasticity: {fl.get('scale_ups', 0)} scale-up(s), "
            f"{fl.get('scale_downs', 0)} scale-down(s)"
        )
        if fl.get("scale_up_s") is not None:
            sc += f", last replica ready in {_fmt_time_s(fl['scale_up_s'])}"
        lines.append(sc)
    routing = fl.get("routing") or {}
    if routing:
        parts = ", ".join(
            f"r{rid}: {n}" for rid, n in sorted(routing.items(), key=lambda kv: str(kv[0]))
        )
        skew = fl.get("routing_skew")
        lines.append(
            f"routing: {parts}"
            + (f" — skew {skew:.2f}x (max/mean)" if _finite(skew) else "")
        )
    per = fl.get("per_replica") or {}
    for rid in sorted(per, key=str):
        row = per[rid] or {}
        verdicts = row.get("verdicts") or {}
        vs = ", ".join(f"{k} {v}" for k, v in sorted(verdicts.items()))
        lines.append(
            f"  replica {rid} [{row.get('state')}]: routed "
            f"{row.get('routed')}, verdicts {{{vs}}}"
        )
    avail = fl.get("availability")
    lines.append(
        (
            f"availability {avail * 100:.1f}% — {fl['verdict']}"
            if _finite(avail)
            else fl["verdict"]
        )
    )
    lines.append("")
    return lines


def _tracing_lines(tr, md):
    """The Tracing section: chain completeness, clock alignment (offset ±
    uncertainty per replica), aggregate + p99-conditional phase
    attribution, SLO burn, and the worst-k request waterfalls
    (docs/observability.md § Tracing)."""
    if not tr:
        return []
    lines = ["## Tracing" if md else "tracing:"]
    line = f"span chains: {tr['chains']} ({tr['spans']} spans)"
    if tr["problems"]:
        line += f" — {len(tr['problems'])} INCOMPLETE:"
        lines.append(line)
        for p in tr["problems"][:10]:
            lines.append(f"  {p}")
    else:
        line += " — all terminal requests traced end to end"
        lines.append(line)
    if tr["alignment"]:
        parts = []
        for rid, off in tr["alignment"].items():
            if not _finite(off.get("offset_s")):
                parts.append(f"r{rid} unestimated")
                continue
            parts.append(
                f"r{rid} {off['offset_s'] * 1e3:+.3f} ms "
                f"(±{off['uncertainty_s'] * 1e3:.3f} ms)"
            )
        lines.append("clock alignment: " + ", ".join(parts))
    if tr["alignment_missing_replicas"]:
        lines.append(
            "ALIGNMENT DEGRADED: no clock offset recorded for replica(s) "
            + ", ".join(str(r) for r in tr["alignment_missing_replicas"])
            + " — their worker spans are unmapped"
        )
    att = tr.get("attribution")
    if att:

        def fmt_phases(ph):
            return ", ".join(
                f"{name} {share * 100:.1f}%"
                for name, share in sorted(
                    ph.items(), key=lambda kv: -kv[1]
                )
            )

        lines.append(
            "phase attribution (mean): " + fmt_phases(att["phases_mean"])
        )
        lines.append(
            f"phase attribution (p99-conditional, slowest "
            f"{att['p99_chains']} >= {_fmt_time_s(att['p99_latency_s'])}): "
            + fmt_phases(att["phases_p99"])
            + (
                f" — tail dominated by {att['p99_dominant_phase']}"
                if att.get("p99_dominant_phase")
                else ""
            )
        )
        if att.get("slo_burn"):
            lines.append(
                f"SLO burn per phase (mean share of the deadline budget, "
                f"{att['slo_chains']} tagged request(s)): "
                + ", ".join(
                    f"{name} {b * 100:.1f}%"
                    for name, b in sorted(
                        att["slo_burn"].items(), key=lambda kv: -kv[1]
                    )
                )
            )
    if tr["worst"]:
        lines.append("slowest requests:")
        for w in tr["worst"]:
            for wl in w["lines"]:
                lines.append("  " + wl)
    lines.append("")
    return lines


def _alerts_lines(alerts, rollups, md):
    """Render the Alerts section (schema v11): the firing→resolved
    timeline, peak burn rates, the false-alert verdict, and the
    rollup-backed trend sparklines. Runs with neither alerts nor
    rollups render nothing — pre-v11 files are untouched."""
    if alerts is None and rollups is None:
        return []
    lines = ["## Alerts" if md else "alerts:"]
    if alerts is None:
        lines.append("no alert transitions recorded")
    else:
        lines.append(
            f"{alerts['fired']} fired / {alerts['resolved']} resolved "
            f"({alerts['transitions']} transition(s))"
            + (
                "; STILL FIRING at end of stream: "
                + ", ".join(alerts["still_firing"])
                if alerts["still_firing"]
                else "; all resolved"
            )
        )
        for e in alerts["timeline"]:
            where = f" (r{e['replica_id']})" if e["replica_id"] is not None else ""
            t = f"t={e['t']:.3f}s " if _finite(e.get("t")) else ""
            lines.append(
                f"- {t}{e['rule']}{where} {e['state'].upper()} "
                f"[{e['severity']}]: {e.get('reason') or ''}"
            )
        if alerts["peak_burn_slow"] is not None:
            lines.append(
                f"peak burn rate: {alerts['peak_burn_slow']:.2f}x budget "
                f"(long window), {alerts['peak_burn_fast']:.2f}x (short) "
                "at the recorded transitions"
            )
        if alerts["false_alerts"]:
            lines.append(
                "FALSE ALERT(S): "
                + ", ".join(alerts["false_alerts"])
                + " fired with no supporting fault evidence in the stream"
            )
        else:
            lines.append(
                "false-alert check: every fired rule is backed by fault "
                "evidence in the stream"
            )
    if rollups is not None:
        lines.append(
            f"rollups: {rollups['windows']} window(s) across "
            f"{len(rollups['sources'])} source(s)"
        )
        for key, src in rollups["sources"].items():
            detail = (
                f"- {key}: {src['windows']} x {src['window_s']:g}s windows"
            )
            if src["late"]:
                detail += f", {src['late']} late sample(s)"
            lines.append(detail)
            if any(v for v in src["rate_trend"]):
                lines.append(
                    f"    rate     {sparkline(src['rate_trend'])}"
                )
            if src.get("p99_trend"):
                lines.append(
                    f"    p99      {sparkline(src['p99_trend'])}  "
                    f"(max {_fmt_time_s(src['p99_latency_s'])})"
                )
            if src.get("loss_trend"):
                lines.append(
                    f"    loss     {sparkline(src['loss_trend'])}"
                )
    lines.append("")
    return lines


def _divergence_info(records):
    """Fold the schema-v12 ``digest`` stream (numerics provenance,
    observability/divergence.py): how many per-step per-layer digest rows
    this run recorded and over which step window — the evidence that a
    first-divergence comparison against a twin run is possible. None when
    the run recorded no digests (section omitted)."""
    digs = [r for r in records if r.get("kind") == "digest"]
    if not digs:
        return None
    steps = sorted(int(r.get("step", 0)) for r in digs)
    flips = [
        r for r in records
        if r.get("kind") == "event" and r.get("name") == "digest_config"
        and r.get("faults")
    ]
    return {
        "records": len(digs),
        "layers": max(int(r.get("layers", 0)) for r in digs),
        "first_step": steps[0],
        "last_step": steps[-1],
        "faults": flips[0]["faults"] if flips else None,
    }


def _divergence_lines(info, md):
    if not info:
        return []
    lines = ["## Divergence" if md else "divergence:"]
    lines.append(
        f"- digest rows: {info['records']} steps "
        f"({info['first_step']}..{info['last_step']}) x "
        f"{info['layers']} layers (per-layer crc + param/grad norms)"
    )
    if info.get("faults"):
        lines.append(f"- fault plan recorded for replay: {info['faults']}")
    lines.append(
        "- compare twin runs: python -m "
        "shallowspeed_tpu.observability.divergence A.jsonl B.jsonl"
    )
    lines.append("")
    return lines


def _capacity_info(records):
    """Fold the schema-v13 capacity evidence (serving/autoscaler.py +
    bench_replay.py): every ``autoscale`` decision with its rule and
    fleet sizes, the replayed trace's offered-load curve
    (``replay_trace`` event), and the per-leg scoreboard rows
    (``replay_score`` events). None when the stream has no capacity
    records (section omitted)."""
    decisions = [r for r in records if r.get("kind") == "autoscale"]
    trace = None
    for r in records:
        if r.get("kind") == "event" and r.get("name") == "replay_trace":
            trace = r  # last wins
    scores = [
        r
        for r in records
        if r.get("kind") == "event" and r.get("name") == "replay_score"
    ]
    if not decisions and trace is None and not scores:
        return None
    by_leg = {}
    for d in decisions:
        by_leg.setdefault(d.get("leg") or "-", []).append(
            {
                k: d.get(k)
                for k in (
                    "name", "direction", "rule", "t", "replicas_before",
                    "replicas_after", "queue_depth", "value", "threshold",
                    "flap", "window_end", "reason",
                )
            }
        )
    for decs in by_leg.values():
        decs.sort(key=lambda d: (d.get("t") is None, d.get("t")))
    return {
        "decisions": len(decisions),
        "flaps": sum(1 for d in decisions if d.get("flap")),
        "by_leg": dict(sorted(by_leg.items())),
        "trace": (
            {
                "day_s": trace.get("day_s"),
                "knee_rps": trace.get("knee_rps"),
                "n_arrivals": trace.get("n_arrivals"),
                "compression": trace.get("compression"),
                "buckets": trace.get("buckets") or [],
                "spikes": trace.get("spikes") or [],
            }
            if trace is not None
            else None
        ),
        "scores": [
            {
                k: s.get(k)
                for k in (
                    "leg", "violation_s", "violation_minutes_modeled",
                    "wasted_replica_s", "wasted_replica_hours_modeled",
                    "flaps",
                )
            }
            for s in scores
        ],
    }


def _capacity_lines(info, md):
    if not info:
        return []
    lines = ["## Capacity" if md else "capacity:"]
    trace = info.get("trace")
    if trace and trace["buckets"]:
        lines.append(
            f"- replayed trace: {trace['n_arrivals']} arrivals over "
            f"{_fmt_num(trace['day_s'], 's')} "
            f"(1s here = {_fmt_num(trace['compression'])}s modeled), "
            f"knee {_fmt_num(trace['knee_rps'], 'rps')}, "
            f"{len(trace['spikes'])} flash-crowd spike(s)"
        )
        lines.append(
            "- offered load: "
            + sparkline([b.get("rate_rps") for b in trace["buckets"]])
        )
    for leg, decs in (info.get("by_leg") or {}).items():
        # the scale timeline against the curve above: each decision at
        # its trace time, with the rule that justified it
        sizes = " ".join(
            f"{_fmt_num(d['t'], 's')}:"
            f"{d['replicas_before']}→{d['replicas_after']}"
            for d in decs
            if d["name"] in ("scale_out", "scale_in")
        )
        lines.append(
            f"- {leg}: {len(decs)} decision(s)"
            + (f" | timeline {sizes}" if sizes else "")
        )
        # every sizing decision renders in full; the admission gate's
        # on/off toggles (direction hold, high-frequency while replicas
        # warm) collapse past the first few to keep the section readable
        bp_shown, bp_total = 0, sum(
            1 for d in decs if d["name"].startswith("backpressure")
        )
        for d in decs:
            is_bp = d["name"].startswith("backpressure")
            if is_bp and not d.get("flap"):
                bp_shown += 1
                if bp_shown > 3:
                    continue
            flap = " FLAP" if d.get("flap") else ""
            lines.append(
                f"  - [{_fmt_num(d['t'], 's')}] {d['name']} "
                f"(rule {d['rule']}, "
                f"{d['replicas_before']}→{d['replicas_after']}, queue "
                f"{d['queue_depth']}){flap} — {d.get('reason')}"
            )
        if bp_total > 3:
            lines.append(
                f"  - … {bp_total - 3} more backpressure toggle(s) "
                "while replacements warmed (admission gate, "
                "replica count unchanged)"
            )
    flaps = info.get("flaps", 0)
    lines.append(
        f"- flap count: {flaps}"
        + ("" if flaps == 0 else " — DIRECTION CHURN (policy bug)")
    )
    for s in info.get("scores") or []:
        lines.append(
            f"- score[{s['leg']}]: "
            f"{_fmt_num(s['violation_minutes_modeled'], 'modeled violation-min')}, "
            f"{_fmt_num(s['wasted_replica_hours_modeled'], 'wasted replica-h')}, "
            f"{s['flaps']} flap(s)"
        )
    lines.append("")
    return lines


def render(report, fmt, comparison=None):
    if fmt == "json":
        out = dict(report)
        if comparison is not None:
            out["baseline_comparison"] = comparison
        # strict JSON like every record line: non-finite stats (a blown-up
        # run's loss mean) become the sanitizer's string forms, never bare
        # NaN tokens a downstream jq/ingest would choke on
        return json.dumps(json_safe(out), indent=2, allow_nan=False)
    md = fmt == "md"
    lines = []
    title = f"Run report: {report['source']}"
    lines.append(f"# {title}" if md else title)
    lines.append("")
    if md:
        lines.append("| metric | value |")
        lines.append("|---|---|")
        lines.extend(f"| {k} | {v} |" for k, v in _rows(report))
    else:
        width = max(len(k) for k, _ in _rows(report))
        lines.extend(f"{k.ljust(width)}  {v}" for k, v in _rows(report))
    lines.append("")
    lines.extend(_cost_lines(report["cost_model"]))
    lines.append("")
    lines.extend(
        _memory_lines(
            report.get("xla_audit"), md, stash=report.get("stash_memory")
        )
    )
    lines.extend(_comms_lines(report.get("xla_audit"), md))
    lines.extend(_reliability_lines(report.get("reliability"), md))
    lines.extend(_serving_lines(report.get("serving"), md))
    lines.extend(_fleet_lines(report.get("fleet"), md))
    lines.extend(_tracing_lines(report.get("tracing"), md))
    lines.extend(
        _alerts_lines(report.get("alerts"), report.get("rollups"), md)
    )
    lines.extend(_divergence_lines(report.get("divergence"), md))
    lines.extend(_capacity_lines(report.get("capacity"), md))
    header = "## Span breakdown" if md else "span breakdown:"
    lines.append(header)
    if report["spans"]:
        for row in report["spans"]:
            lines.append(
                f"- {row['name']}: {row['total_s']:.3f}s over {row['count']} span(s)"
            )
    else:
        lines.append("- (no spans recorded)")
    lines.append("")
    if report["step_loss_sparkline"]:
        sl = report["step_loss"]
        lines.append("## Step loss" if md else "step loss:")
        lines.append(
            f"{report['steps']} steps, first {_fmt_num(sl['first'])} -> "
            f"last {_fmt_num(sl['last'])}"
            + (f", {sl['non_finite']} NON-FINITE" if sl["non_finite"] else "")
        )
        lines.append(report["step_loss_sparkline"])
        lines.append("")
    if comparison is not None:
        lines.append("## Baseline" if md else "baseline:")
        delta = comparison["delta_fraction"]
        if comparison.get("compile_polluted"):
            verdict = (
                "regression gate SKIPPED — this run's only throughput "
                "records include compile time"
            )
        elif comparison["regression"]:
            verdict = (
                f"REGRESSION beyond {comparison['threshold'] * 100:.0f}% threshold"
            )
        else:
            verdict = f"within {comparison['threshold'] * 100:.0f}% threshold"
        lines.append(
            f"vs {comparison['baseline']}: "
            f"{_fmt_num(comparison['baseline_samples_per_sec'], 'samples/s')} "
            f"baseline, {'+' if delta is not None and delta >= 0 else ''}"
            f"{_fmt_num(delta, pct=True)} ({verdict})"
        )
        lines.append("")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.observability.report",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("run", help="metrics JSONL of the run to report on")
    ap.add_argument(
        "--baseline",
        default=None,
        help="metrics JSONL or bench/capture JSON to compare throughput "
        "against (regression beyond --threshold exits 2)",
    )
    ap.add_argument(
        "--trace",
        default=None,
        help="a jax.profiler trace dir or *.trace.json.gz of this run "
        "(e.g. the --profile-dir artifact): its measured comm/compute "
        "split upgrades the overlap-efficiency row from the comms-model "
        "bound to a measurement",
    )
    ap.add_argument("--format", choices=("md", "text", "json"), default="md")
    ap.add_argument(
        "--slo-ms",
        type=float,
        default=None,
        help="latency objective for the Serving section's SLO verdict "
        "(overrides the serving summary record's own threshold)",
    )
    ap.add_argument(
        "--threshold",
        type=float,
        default=0.10,
        help="relative throughput-regression gate (default 0.10 = 10%%)",
    )
    args = ap.parse_args(argv)
    try:
        records = read_jsonl(args.run)
    except (OSError, ValueError) as e:
        print(f"report: cannot read {args.run}: {e}", file=sys.stderr)
        return 1
    trace = None
    if args.trace:
        from shallowspeed_tpu.observability import trace_stats

        traces = trace_stats.find_traces(args.trace)
        if not traces:
            print(
                f"report: no *.trace.json.gz under {args.trace}", file=sys.stderr
            )
            return 1
        # one capture = one trace; with several, the newest wins (the
        # capture helpers timestamp their subdirs)
        trace = trace_stats.summarize(traces[-1])
    report = build_report(records, source=args.run, trace=trace, slo_ms=args.slo_ms)
    comparison = None
    if args.baseline:
        try:
            base_tp, label = baseline_throughput(args.baseline)
        except (OSError, ValueError) as e:
            print(f"report: cannot read baseline {args.baseline}: {e}", file=sys.stderr)
            return 1
        if base_tp is None:
            print(f"report: {label}", file=sys.stderr)
            return 1
        if report["throughput_samples_per_sec"] is None:
            print(
                f"report: {args.run} has no throughput records to compare",
                file=sys.stderr,
            )
            return 1
        comparison = compare(report, base_tp, label, args.threshold)
    print(render(report, args.format, comparison))
    if comparison is not None and comparison.get("compile_polluted"):
        print(
            "report: regression gate skipped — no steady-state epoch record "
            "(this run's throughput includes compile time)",
            file=sys.stderr,
        )
    if comparison is not None and comparison["regression"]:
        print(
            f"report: THROUGHPUT REGRESSION beyond {args.threshold * 100:.0f}% "
            f"({comparison['delta_fraction'] * 100:.1f}% vs baseline)",
            file=sys.stderr,
        )
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
