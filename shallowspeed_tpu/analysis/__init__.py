"""Static program analysis: machine-checked contracts for the invariants
this repo used to enforce by convention.

Two coordinated halves (docs/static-analysis.md):

- **program-level passes** (``progcheck``/``stash``): run over LOWERED
  tick tables at lowering/compile time, before the first dispatch. The
  lowering simulator (parallel/lowering.py) *constructs* programs it
  believes are well-formed; these passes independently *prove* the
  properties the ROADMAP item-1 MPMD runtime will depend on — every
  ``SendActivations`` has a consuming recv on the peer stage, the
  happens-before graph stays acyclic WITHOUT the lockstep barrier (so
  per-stage streams dispatched asynchronously can never deadlock, even
  under bounded mailboxes), and every stash slot is written before read,
  freed by program end, with the measured peak equal to the allocated
  ``n_stash_slots``/``n_gstash_slots``. The simulator stays the spec;
  the analyzer is the proof that a given artifact satisfies it.
- **a house-rule AST linter** (``rules``/``lint``; stdlib ``ast``, zero
  new deps): ``python -m shallowspeed_tpu.analysis.lint`` encodes the
  rules generic linters can't — justified broad excepts, strict-JSON
  metrics writes, the one-atomic-write discipline, the donation
  whitelist, the metrics schema-kind registry, and lock discipline on
  lock-owning classes. ``make lint`` runs it repo-wide (exit 2 on
  findings, ``--format json`` for machines) and a tier-1 test keeps
  HEAD clean.

The third static check — the HLO dispatch-safety pass that refuses
deserialized/serving-path programs that donate their buffers — lives in
``observability/program_audit.py`` next to the collective census it
extends (``parse_input_output_aliases`` / ``verify_dispatch_safety``).
"""

from shallowspeed_tpu.analysis.progcheck import (
    ProgramAnalysisError,
    analyze_program,
    check_deadlock_free,
    check_send_recv,
)
from shallowspeed_tpu.analysis.stash import check_stash_lifetime

__all__ = [
    "ProgramAnalysisError",
    "analyze_program",
    "check_deadlock_free",
    "check_send_recv",
    "check_stash_lifetime",
]
