"""Pallas TPU kernels for the hot op: fused linear + ReLU, forward & backward.

The framework's compute path is XLA-compiled jax.numpy (ops.py) — for this
model class XLA already fuses bias-add and ReLU into the matmul. These Pallas
kernels exist for the cases XLA can't schedule as one unit and as the
framework's custom-kernel layer (per-stage tensors here are small enough that
a whole layer fits VMEM, so each kernel is a single block: HBM -> VMEM once,
matmul on the MXU with fp32 accumulation, activation + bitmask on the VPU,
one write back).

- ``linear_relu_fwd(x, w, b) -> (y, mask)``: y = relu(x @ w.T + b), mask the
  pre-activation sign bitmask the backward needs (reference semantics:
  layers.py:68-71 caches the same bitmask).
- ``linear_relu_bwd(g, mask, x, w) -> (dx, dw, db)``: all three gradients in
  one kernel from one VMEM residency of g/mask/x/w.

Enable with SHALLOWSPEED_PALLAS=1 (or ``ops.set_pallas(True)``); off-TPU the
kernels run in interpreter mode, so the same tests cover CPU CI and real
hardware. Scope note: the flag applies to the SEQUENTIAL model path
(model.stage_forward/backward). The pipeline executor keeps the pure-XLA
path: its layer loop selects relu/identity behavior with traced per-device
flags, so a statically-fused relu kernel cannot be slotted in without
specializing the program per stage.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mask_ref):
    z = (
        jnp.dot(x_ref[:], w_ref[:].T, preferred_element_type=jnp.float32)
        + b_ref[:]
    )
    mask_ref[:] = (z > 0.0).astype(jnp.float32)
    y_ref[:] = jnp.maximum(z, 0.0)


@functools.partial(jax.jit, static_argnames=())
def linear_relu_fwd(x, w, b):
    mb, din = x.shape
    dout = w.shape[0]
    y, mask = pl.pallas_call(
        _fwd_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((mb, dout), jnp.float32),
            jax.ShapeDtypeStruct((mb, dout), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(x, w, jnp.reshape(b, (1, -1)))
    return y, mask


def _bwd_kernel(g_ref, mask_ref, x_ref, w_ref, dx_ref, dw_ref, db_ref):
    ge = g_ref[:] * mask_ref[:]
    dx_ref[:] = jnp.dot(ge, w_ref[:], preferred_element_type=jnp.float32)
    dw_ref[:] = jnp.dot(ge.T, x_ref[:], preferred_element_type=jnp.float32)
    db_ref[:] = jnp.sum(ge, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=())
def linear_relu_bwd(g, mask, x, w):
    mb, dout = g.shape
    din = x.shape[1]
    dx, dw, db = pl.pallas_call(
        _bwd_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((mb, din), jnp.float32),
            jax.ShapeDtypeStruct((dout, din), jnp.float32),
            jax.ShapeDtypeStruct((1, dout), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 4,
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 3),
        interpret=_interpret(),
    )(g, mask, x, w)
    return dx, dw, db
