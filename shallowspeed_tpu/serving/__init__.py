"""Inference serving: request queue, continuous batching, load bench.

The subsystem behind the repo's second scoreboard — tail latency under load
(ROADMAP item 4; docs/serving.md):

- ``slots``          the shared dispatch geometry: fixed ``slot_rows``-row
                     microbatch slots + the ladder of slot counts that
                     bounds compilation AND makes per-slot compute
                     bitwise-stable across rung programs;
- ``engine``         ``ServingEngine``: deadline-tagged FIFO queue,
                     continuous batching into the session's cached
                     inference programs, per-request accounting, schema-v5
                     ``request``/``serving`` records + queue-depth gauge —
                     and the graceful-degradation layer (dispatch recovery
                     with a bounded retry budget, deadline shedding,
                     health-gated responses, a consecutive-failure breaker,
                     hot weight reload; docs/robustness.md "Serving
                     faults");
- ``loadgen``        seeded Poisson arrivals, open-loop (coordinated-
                     omission-corrected) and closed-loop drivers, each
                     with the graceful-drain ``should_stop`` hook — the
                     drivers duck-type over an engine OR a fleet;
- ``router``         fleet routing as pure logic: replica health state
                     (heartbeat-fed), the bounded fleet queue,
                     least-queue-depth / power-of-two-choices placement,
                     the quorum rule;
- ``fleet``          ``ServingFleet``: N engine replicas as spawned
                     worker processes (each its own JAX runtime +
                     checkpoint-loaded session + warmed ladder) behind
                     the router — heartbeats, failover requeue-at-head
                     under the shared retry budget, ``scale_up``/
                     ``scale_down``/``watch_reload`` elasticity,
                     schema-v7 ``fleet``/``fleet_health`` records and
                     per-replica ``.r{id}`` JSONL shards
                     (docs/serving.md "Fleet");
- ``bench_serving``  the offered-load sweep: p50/p99, goodput, queue depth,
                     padding waste, saturation knee — one versioned JSON
                     record beside ``bench_scaling``'s — plus the seeded
                     ``chaos_soak`` behind ``make chaos-smoke``;
- ``__main__``       the serve entry point
                     (``python -m shallowspeed_tpu.serving``): checkpoint
                     -> engine -> seeded load, with ``--verify`` bitwise
                     parity, ``--audit`` census enforcement, ``--faults``
                     chaos injection and SIGTERM/SIGINT graceful drain.
"""

from shallowspeed_tpu.serving.engine import Request, ServingEngine
from shallowspeed_tpu.serving.fleet import (
    FleetError,
    ServingFleet,
    fleet_workers_supported,
)
from shallowspeed_tpu.serving.router import FleetRequest, Router
from shallowspeed_tpu.serving.slots import (
    DEFAULT_SLOT_LADDER,
    DEFAULT_SLOT_ROWS,
    pack_slots,
    rung_for,
    slots_needed,
    unpack_slots,
)

__all__ = [
    "DEFAULT_SLOT_LADDER",
    "DEFAULT_SLOT_ROWS",
    "FleetError",
    "FleetRequest",
    "Request",
    "Router",
    "ServingEngine",
    "ServingFleet",
    "fleet_workers_supported",
    "pack_slots",
    "rung_for",
    "slots_needed",
    "unpack_slots",
]
