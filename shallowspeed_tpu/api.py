"""High-level programmatic API: one object that wires the whole framework.

The reference's user assembles communicators, model, dataset, optimizer and
Worker by hand in train.py (train.py:87-129); here the same wiring is a
library object, so notebooks/tests/benchmarks get everything the CLI does:

    from shallowspeed_tpu.api import TrainingSession

    run = TrainingSession(dp=2, pp=4, schedule="gpipe", data_dir="data/mnist_784")
    for _ in range(20):
        loss = run.train_epoch()
        print(run.epoch, loss, run.accuracy())
    run.save("ck.npz")

Layouts are uniform: dp=pp=tp=1 uses the fast sequential jitted path,
anything else the SPMD pipeline executor — same weights either way (tested
layout equivalence; ``tp`` adds the Megatron model axis, whose split
contractions carry the same cross-layout float tolerance a dp-width change
does, while tp=1 programs stay byte-identical to the pre-TP anchors).
"""

import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from shallowspeed_tpu import faults as F
from shallowspeed_tpu import model as Mo
from shallowspeed_tpu import schedules as S
from shallowspeed_tpu import trainer, utils
from shallowspeed_tpu.checkpoint import (
    AsyncCheckpointWriter,
    CheckpointError,
    assemble_checkpoint,
    build_snapshot,
    find_latest_good,
    load_checkpoint,
    run_save_stages,
    save_checkpoint,
    step_checkpoint_path,
)
from shallowspeed_tpu.data import Dataset, default_data_dir
from shallowspeed_tpu.observability import NullMetrics, costmodel, program_audit
from shallowspeed_tpu.observability.flight import FlightRecorder
from shallowspeed_tpu.observability.health import HealthError, make_monitor
from shallowspeed_tpu.observability.slo import (
    LiveTelemetry,
    default_training_rules,
)
from shallowspeed_tpu.optimizer import (
    is_stateless,
    join_state,
    make_optimizer,
    split_state,
)
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel import gradsync
from shallowspeed_tpu.parallel import lower_schedule
from shallowspeed_tpu.parallel.mesh import make_mesh_with_layout
from shallowspeed_tpu.parallel.lowering import program_flops, program_stats
from shallowspeed_tpu.serving import slots as serving_slots

# The reference's canonical training configuration (train.py:56-59,98,107) —
# the single source of truth for every benchmark script in this repo.
FLAGSHIP_SIZES = (784, 128, 127, 126, 125, 124, 123, 10)
FLAGSHIP_BATCH = 128
FLAGSHIP_MUBATCHES = 4
FLAGSHIP_LR = 0.006

# Matmul-precision names accepted everywhere a precision string is taken
# (TrainingSession, train.py --precision, bench.py) — single source of truth.
PRECISIONS = {
    "highest": lax.Precision.HIGHEST,
    "default": lax.Precision.DEFAULT,
}


class TrainingSession:
    """End-to-end training run: data + model + layout + optimizer + eval."""

    def __init__(
        self,
        sizes=FLAGSHIP_SIZES,
        model=None,
        dp=1,
        pp=1,
        tp=1,
        schedule="gpipe",
        global_batch_size=128,
        mubatches=4,
        lr=0.006,
        precision="highest",
        data_dir=None,
        resume=None,
        devices=None,
        fuse_mubatches=False,
        optimizer="sgd",
        momentum=0.9,
        virtual_stages=1,
        zero1=False,
        zero=None,
        grad_bucket_bytes=0,
        backward_split=False,
        recompute=False,
        scan_unroll=1,
        tick_unroll=1,
        weight_decay=0.0,
        clip_norm=None,
        megakernel=False,
        epoch_kernel=False,
        run_kernel=False,
        kernel_backend="xla",
        metrics=None,
        health=None,
        record_steps=None,
        digests=False,
        audit=False,
        checkpoint_dir=None,
        checkpoint_keep=3,
        async_checkpoint=False,
        checkpoint_queue=2,
        faults=None,
        aot_cache_dir=None,
        predict_slot_rows=None,
        predict_slot_ladder=None,
        runtime="lockstep",
    ):
        # telemetry hook (observability package): None -> the zero-overhead
        # null backend. Everything the session emits — construction spans,
        # jit-compile spans, per-epoch training records, per-step flight
        # records, MFU gauges, pipeline program stats — flows through this
        # one recorder (docs/observability.md).
        self._metrics = metrics if metrics is not None else NullMetrics()
        # live telemetry (schema v11, docs/observability.md § Live
        # telemetry & alerting): per-step loss/throughput/MFU rollup
        # windows plus the trainer rule set (health-event alerts, the
        # checkpoint-overhead fraction vs its budget). Fed only inside
        # metrics-enabled blocks — a NullMetrics session pays nothing.
        self._telemetry = LiveTelemetry(
            "train", metrics=self._metrics, rules=default_training_rules()
        )
        # compiled-program audit (observability/program_audit.py): with a
        # metrics recorder attached, the jit-time collective census +
        # memory analysis is ALWAYS recorded (schema-v3 xla_audit record).
        # ``audit=True`` additionally ENFORCES the layout's comms contract:
        # the epoch/run program is compiled (even without metrics) and an
        # AuditMismatchError is raised when its collective census violates
        # the layout's analytical contract.
        self._audit_strict = bool(audit)
        self._audit_done = set()  # program names already audited
        # numerics health monitor: None, a policy string ("record" / "warn"
        # / "halt"), or a HealthMonitor instance (observability/health.py).
        # Checks run on host against the fused per-step aux after each
        # epoch's readback; under "halt" a finding raises HealthError AFTER
        # the epoch's update has been applied (the monitor observes the
        # fused program's outputs, it cannot unwind them).
        self._health = make_monitor(health)
        # model zoo (model.MODEL_ZOO / train.py --model): a named
        # compute-bound configuration — (sizes, activation family) — that
        # overrides ``sizes``. The family is STATIC program structure
        # (relu traces the historical expressions byte-identically; the
        # gelu family adds residual slots and f32 grad-multiplier masks),
        # and every zoo model keeps the 784-wide MNIST input so the data
        # pipeline, checkpoints and serving slots compose unchanged.
        self.model_name = model
        if model is not None:
            sizes, act = Mo.resolve_model(model)
        else:
            act = "relu"
        self._act = act
        if global_batch_size % dp != 0:
            raise ValueError("global batch size must be divisible by dp")
        local_batch = global_batch_size // dp
        if local_batch % mubatches != 0:
            raise ValueError("mubatches must divide the local batch")
        if tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        self.dp, self.pp, self.tp = dp, pp, int(tp)
        self.B, self.M = global_batch_size, mubatches
        self.schedule = schedule
        if precision not in PRECISIONS:
            raise ValueError(
                f"precision must be one of {sorted(PRECISIONS)}, got {precision!r}"
            )
        self._precision_name = precision  # the MFU peak is precision-classed
        if schedule not in S.SCHEDULES:
            raise ValueError(
                f"schedule must be one of {sorted(S.SCHEDULES)}, got {schedule!r}"
            )
        self.precision = PRECISIONS[precision]
        if fuse_mubatches and not (
            dp == 1 and pp == 1 and virtual_stages == 1 and tp == 1
        ):
            raise ValueError(
                "fuse_mubatches applies to the sequential path only; in the "
                "pipeline executor microbatches are semantic (they ARE the "
                "pipeline's unit of work)"
            )
        if megakernel and not fuse_mubatches:
            raise ValueError(
                "megakernel runs the whole fused batch as one Pallas kernel; "
                "it requires fuse_mubatches=True (sequential path)"
            )
        if epoch_kernel and not fuse_mubatches:
            raise ValueError(
                "epoch_kernel runs the whole epoch as one Pallas kernel; "
                "it requires fuse_mubatches=True (sequential path)"
            )
        if run_kernel and not fuse_mubatches:
            raise ValueError(
                "run_kernel runs the whole multi-epoch run as one Pallas "
                "kernel; it requires fuse_mubatches=True (sequential path)"
            )
        if run_kernel and (megakernel or epoch_kernel):
            raise ValueError(
                "run_kernel subsumes the mega/epoch kernels; pass only "
                "run_kernel=True"
            )
        self._run_kernel = bool(run_kernel)
        if kernel_backend not in ("xla", "pallas"):
            raise ValueError(
                f"kernel_backend must be 'xla' or 'pallas', got {kernel_backend!r}"
            )
        if virtual_stages < 1:
            raise ValueError("virtual_stages must be >= 1")
        if virtual_stages > 1 and schedule != "interleaved":
            raise ValueError(
                "virtual_stages > 1 requires schedule='interleaved' (the flat "
                "schedules place exactly one stage per device)"
            )
        if scan_unroll < 1 or tick_unroll < 1:
            raise ValueError("scan_unroll/tick_unroll must be >= 1")
        self.V = virtual_stages
        self._sequential = dp == 1 and pp == 1 and virtual_stages == 1 and tp == 1
        self._kernel_backend = kernel_backend
        if kernel_backend == "pallas" and act != "relu":
            raise ValueError(
                "kernel_backend='pallas' hard-codes the relu/identity slot "
                "expressions; the gelu-family models (f32 grad-multiplier "
                "masks, residual adds) run the XLA backend only"
            )
        if kernel_backend == "pallas" and tp > 1:
            raise ValueError(
                "tensor parallelism (tp > 1) shards each slot's W across "
                "the tp axis; the fused pallas flag kernels compute whole "
                "slots — use kernel_backend='xla'"
            )
        if kernel_backend == "pallas" and self._sequential:
            raise ValueError(
                "kernel_backend='pallas' selects the pipeline executor's "
                "flag-operand kernels and needs a mesh layout (dp/pp > 1 or "
                "virtual_stages > 1); on the sequential path use "
                "megakernel=True or SHALLOWSPEED_PALLAS=1 instead"
            )
        if tick_unroll > 1 and self._sequential:
            raise ValueError(
                "tick_unroll unrolls the pipeline tick loop; the sequential "
                "path has no ticks — use scan_unroll"
            )
        # the dp-axis ZeRO stage (arXiv 2004.13336): ``zero`` in {0,1,2,3}
        # supersedes the historical ``zero1`` boolean — ``zero=1`` IS the
        # zero1 path, verbatim. Stage 2 shards gradients + optimizer state
        # (block-cyclic per-slot layout, bitwise-equal weights to stage 1
        # on clip-free runs); stage 3 additionally shards the params at
        # rest with just-in-time per-tick gathers.
        if zero is None:
            zero = 1 if zero1 else 0
        else:
            zero = int(zero)
            if zero not in (0, 1, 2, 3):
                raise ValueError(f"zero must be one of 0/1/2/3, got {zero}")
            if zero1 and zero != 1:
                raise ValueError(
                    f"conflicting dp-stage selectors: zero1=True but "
                    f"zero={zero} — pass only --zero"
                )
        self._zero = zero
        self._zero1 = zero == 1
        # ZeRO-3 eval view: the {W, b} stacked layout rebuilt from the
        # at-rest shards for inference programs, cached by identity
        self._eval_stacked_cache = None
        if self._zero and self._sequential:
            if self._zero1:
                raise ValueError(
                    "zero1 shards the optimizer update over the dp mesh "
                    "axis; the sequential path has no mesh — use dp/pp > 1"
                )
            raise ValueError(
                f"zero={zero} shards the update over the dp mesh axis; "
                "the sequential path has no mesh — use dp/pp > 1"
            )
        if self._zero >= 2 and digests:
            raise ValueError(
                "digests read the zero1 flat-chunk segment map; the "
                "block-cyclic shard layout of zero>=2 has no flat chunk — "
                "use --zero 1 or below with --digests"
            )
        if self._zero == 3 and kernel_backend == "pallas":
            raise ValueError(
                "zero=3 all-gathers parameter segments inside every tick "
                "branch; the fused pallas flag kernels take whole resident "
                "slots — use kernel_backend='xla' with --zero 3"
            )
        if self._zero == 3 and grad_bucket_bytes:
            raise ValueError(
                "zero=3 syncs gradients per tick (one reduce-scatter per "
                "layer slot inside the scan); grad_bucket_bytes shapes the "
                "tail sync only and has nothing to bucket at stage 3"
            )
        if grad_bucket_bytes is None:
            grad_bucket_bytes = 0
        grad_bucket_bytes = int(grad_bucket_bytes)
        if grad_bucket_bytes < 0:
            raise ValueError("grad_bucket_bytes must be >= 0 (0 = anchor sync)")
        if grad_bucket_bytes and self._sequential:
            raise ValueError(
                "grad_bucket_bytes buckets the dp-axis gradient collectives; "
                "the sequential path has no gradient sync — use dp/pp > 1 "
                "(0 keeps the legacy anchor psum on mesh layouts)"
            )
        self._backward_split = bool(backward_split)
        if self._backward_split:
            if self._sequential:
                raise ValueError(
                    "backward_split is a pipeline-schedule property (B-input "
                    "at the relay tick, B-weight deferred into bubbles); the "
                    "sequential path has no schedule — use dp/pp > 1"
                )
            if virtual_stages > 1:
                raise ValueError(
                    "backward_split is not supported with interleaved "
                    "virtual stages (the chunked steady state interleaves "
                    "its own bubbles; splitting its backward is future work)"
                )
            if kernel_backend == "pallas":
                raise ValueError(
                    "backward_split needs the XLA per-slot backward; the "
                    "fused pallas flag kernel has no split halves"
                )
        # activation recompute (docs/lowering.md "Recompute ticks"): drop
        # the forward's activation stashes, keep only the stage INPUT, and
        # re-run the stage forward inside the backward tick (OP_RECOMPUTE)
        # — a memory-for-FLOPs trade that shortens the stash lifetime from
        # fwd->bwd to recompute->bwd (arXiv 2004.09910's checkpointing,
        # tick-table form). Bitwise-identical training: the recompute
        # re-traces the character-identical forward expressions.
        self._recompute = bool(recompute)
        if self._recompute:
            if self._sequential:
                raise ValueError(
                    "recompute drops pipeline activation stashes and "
                    "re-runs the stage forward at the backward tick; the "
                    "sequential path holds no cross-tick stash — use "
                    "dp/pp > 1"
                )
            if virtual_stages > 1:
                raise ValueError(
                    "recompute is not supported with interleaved virtual "
                    "stages (the chunked stash rotation is its own "
                    "lifetime discipline; recomputing it is future work)"
                )
            if kernel_backend == "pallas":
                raise ValueError(
                    "recompute re-runs the XLA per-slot forward inside "
                    "the backward tick; the fused pallas flag kernel has "
                    "no recompute branch"
                )
        # pipeline runtime (docs/performance.md "The MPMD runtime"):
        # "lockstep" is the historical ONE-SPMD-program executor (the
        # correctness oracle); "mpmd" dispatches one compiled program per
        # stage role asynchronously from the host with device-to-device
        # relays (parallel/mpmd.py) — bitwise-identical weights, measured
        # lower op-issue overhead. The MPMD feature envelope is enforced
        # here: the knobs whose lockstep implementations live in the fused
        # program's tail (zero1, bucketed sync, the cross-stage clip norm,
        # the pallas tick backend, the per-step flight aux) stay
        # lockstep-only until the per-stage update learns their math.
        if runtime not in ("lockstep", "mpmd"):
            raise ValueError(
                f"runtime must be 'lockstep' or 'mpmd', got {runtime!r}"
            )
        self.runtime = runtime
        self._mpmd = None  # the train runner, built with the tick program
        self._mpmd_infer = None  # the streaming inference runner (lazy)
        if runtime == "mpmd":
            if self._sequential:
                raise ValueError(
                    "runtime='mpmd' dispatches one program per pipeline "
                    "stage; the sequential path has no stages — use a mesh "
                    "layout (dp/pp/tp > 1)"
                )
            if self._zero:
                raise ValueError(
                    f"runtime='mpmd' does not support zero (stage "
                    f"{self._zero}) yet: the ZeRO reduce-scatter/all-gather "
                    "update spans the whole sharded param layout, not one "
                    "stage — use runtime='lockstep'"
                )
            if grad_bucket_bytes:
                raise ValueError(
                    "runtime='mpmd' does not support grad_bucket_bytes: "
                    "bucketed sync overlaps collectives inside the lockstep "
                    "program's tail; the MPMD per-stage update is one psum "
                    "per stage already — use runtime='lockstep'"
                )
            if clip_norm is not None:
                raise ValueError(
                    "runtime='mpmd' does not support clip_norm yet: the "
                    "global norm spans every stage's gradient, which the "
                    "per-stage update programs cannot see — use "
                    "runtime='lockstep'"
                )
            if kernel_backend != "xla":
                raise ValueError(
                    "runtime='mpmd' uses the XLA per-slot stage functions; "
                    "kernel_backend='pallas' is lockstep-only"
                )
            if record_steps:
                raise ValueError(
                    "runtime='mpmd' does not thread the per-step flight aux "
                    "(loss/grad-norm/param-norm vectors ride the lockstep "
                    "epoch scan); pass record_steps=False or use "
                    "runtime='lockstep'"
                )
            record_steps = False
            if digests:
                raise ValueError(
                    "runtime='mpmd' does not thread the per-step digest aux "
                    "(the per-layer checksum grids ride the lockstep epoch "
                    "scan); pass digests=False or use runtime='lockstep'"
                )

        self.epoch = 0
        # step cursor within the current epoch: 0 except after a mid-epoch
        # resume / between train_steps() chunks. global_step (property) is
        # the run-lifetime optimizer-step count — the unit the step
        # checkpoints, fault injections and flight records all share.
        self.step_in_epoch = 0
        # fault-tolerance wiring (docs/robustness.md): the step-checkpoint
        # directory + retention, the fault-injection plan (explicit arg, or
        # the SHALLOWSPEED_FAULTS env spec), and what resume discovered
        if checkpoint_keep < 1:
            raise ValueError("checkpoint_keep must be >= 1")
        self._ckpt_dir = checkpoint_dir
        self._ckpt_keep = int(checkpoint_keep)
        # paths THIS session wrote with all_finite=True: rotation trusts
        # them without re-reading (their checksums were computed in-process)
        self._trusted_snapshots = set()
        self._faults = F.make_plan(faults)
        # async checkpointing (docs/robustness.md "The async writer"):
        # save_step_checkpoint(async_=True) — or async_checkpoint=True as
        # the session default — keeps only the device->host snapshot on
        # the step path and hands verify/write/fsync/rename/rotate to a
        # single background writer behind a bounded queue. The writer is
        # created lazily on the first async save; save_seq is the
        # @save=N fault anchor, counted over EVERY save this process
        # attempts (sync, async, halt flush) so a spec replays
        # deterministically whichever mode is active.
        if checkpoint_queue < 1:
            raise ValueError("checkpoint_queue must be >= 1")
        self._async_ckpt_default = bool(async_checkpoint)
        self._ckpt_queue = int(checkpoint_queue)
        self._ckpt_writer = None
        self._save_seq = 0
        # AOT executable cache (shallowspeed_tpu/aot_cache.py): compile
        # sites try it before .compile(); deserialized programs are
        # re-audited before first dispatch, every failure falls back to
        # a clean recompile + rewrite
        self._aot = None
        if aot_cache_dir is not None:
            from shallowspeed_tpu.aot_cache import AotCache

            self._aot = AotCache(aot_cache_dir, metrics=self._metrics)
        self._slot_predict = None  # sequential slot-shaped predict program
        self.resumed_from = None  # path of the restored snapshot, if any
        self._recovery = None  # the recovery record's fields, if resume ran
        # per-epoch aggregation across train_steps() chunks. steps_counted
        # tracks how many steps THIS process dispatched: after a mid-epoch
        # resume it is smaller than batches_per_epoch (the head of the
        # epoch ran in the dead process), and the completing epoch's
        # loss/throughput are reported over the counted steps only
        self._epoch_loss_sum = 0.0
        self._epoch_wall = 0.0
        self._epoch_steps_counted = 0
        self._epoch_first_dispatch = False

        data_dir = data_dir or default_data_dir()
        self._data_dir = data_dir
        self._train_ds = Dataset(data_dir, self.B, mubatch_size=local_batch // mubatches)
        self._train_ds.load(0, 1)
        # validation split is loaded lazily on the first accuracy() call, so
        # eval-free runs (train.py --no-eval, benchmarks) pay neither the host
        # load nor the device transfer
        self._vx = self._vy = None
        # inference slot geometry (serving/slots.py): predict(), mesh eval
        # and the serving engine all dispatch whole microbatch SLOTS of
        # ``slot_rows`` global rows, with per-dispatch slot counts rounded
        # up a fixed ladder — so the predict cache holds at most
        # len(ladder) compiled programs (one per rung) instead of one per
        # distinct row count, and a request slot computes bitwise-
        # identically in every rung program (docs/serving.md)
        if predict_slot_rows is None:
            self._slot_rows = serving_slots.default_slot_rows(dp)
        else:
            self._slot_rows = int(predict_slot_rows)
            if self._slot_rows < 1 or self._slot_rows % dp:
                raise ValueError(
                    f"predict_slot_rows must be a positive multiple of dp="
                    f"{dp}, got {predict_slot_rows}"
                )
        self._slot_ladder = serving_slots.validate_ladder(
            predict_slot_ladder
            if predict_slot_ladder is not None
            else serving_slots.DEFAULT_SLOT_LADDER
        )
        self._predict_cache = {}  # inference programs, keyed by ladder rung
        self._run_fns = {}  # fused multi-epoch programs, keyed by with_eval
        self._compiled_runs = {}  # AOT warm_run executables, keyed by (with_eval, epochs)

        nb = self._train_ds.get_num_batches()
        if nb == 0:
            raise ValueError(
                f"training split has {self._train_ds.raw_len} samples — fewer "
                f"than one global batch of {self.B}"
            )
        Xb, Yb = self._train_ds.epoch_arrays()
        if self.runtime == "mpmd":
            # the MPMD host scheduler feeds per-microbatch device_puts to
            # the endpoint stages' sub-meshes itself; the epoch arrays
            # stay host-side (numpy slices are the step-chunk unit)
            self._X = Xb.reshape(nb, self.B, Xb.shape[-1])
            self._Y = Yb.reshape(nb, self.B, Yb.shape[-1])
        else:
            with self._metrics.span("device_put"):
                self._X = jnp.asarray(Xb.reshape(nb, self.B, Xb.shape[-1]))
                self._Y = jnp.asarray(Yb.reshape(nb, self.B, Yb.shape[-1]))
        self.batches_per_epoch = nb

        n_model_stages = pp * virtual_stages
        self.spec = Mo.make_model_spec(sizes, n_model_stages, self.B, act=act)
        # device-major stage placement for virtual chunks (identity otherwise)
        self._order = (
            E.interleave_order(n_model_stages, pp) if virtual_stages > 1 else None
        )
        if clip_norm is not None and clip_norm <= 0:
            raise ValueError("clip_norm must be positive (or None to disable)")
        opt = self._opt = make_optimizer(optimizer, lr, momentum, weight_decay)
        self._opt_config = {
            "name": optimizer,
            "lr": lr,
            "momentum": momentum,
            "weight_decay": weight_decay,
        }

        host_opt_state = None  # logical (per-stage ragged) saved state, if any
        verified = None  # (meta, arrays) of the snapshot discovery verified
        if resume == "auto":
            # crash-recovery discovery: newest VERIFYING snapshot in the
            # checkpoint dir (corrupt/torn/non-finite ones are skipped with
            # their causes recorded); an empty/missing dir is a fresh start,
            # a dir with snapshots where NONE verifies is unrecoverable.
            # with_arrays: discovery's verified read IS the load's read —
            # one read, one checksum pass, and the discovery->load TOCTOU
            # window (the snapshot rotting or rotating away between the
            # verify and a re-read) is closed by construction instead of
            # by the re-verification `load` used to repeat
            if self._ckpt_dir is None:
                raise ValueError(
                    "resume='auto' discovers snapshots in the step-checkpoint "
                    "directory — pass checkpoint_dir"
                )
            path, vmeta, varrays, skipped = find_latest_good(
                self._ckpt_dir, with_arrays=True
            )
            if path is not None:
                verified = (vmeta, varrays)
            skipped_fields = [
                {"path": str(p), "cause": cause} for p, cause in skipped
            ]
            if path is None and skipped:
                # every candidate failed: corrupt/torn files, or non-finite
                # blow-up snapshots that discovery skips BY DESIGN — name
                # each cause so the operator can tell which they have
                raise CheckpointError(
                    self._ckpt_dir,
                    "no snapshot verifies: "
                    + "; ".join(f"{p.name}: {c}" for p, c in skipped)
                    + " (non-finite snapshots are skipped by design — "
                    "delete the directory to start fresh)",
                )
            if path is None:
                resume = None
                self._recovery = {
                    "verdict": "fresh_start",
                    "resumed_from": None,
                    "skipped": skipped_fields,
                }
            else:
                resume = path
                self._recovery = {
                    "verdict": "resumed",
                    "resumed_from": str(path),
                    "skipped": skipped_fields,
                }
        if resume is not None:
            if verified is not None:
                # resume-auto: assemble from the arrays discovery already
                # read and checksummed — `load` does not touch the file
                host_params, loaded_spec, meta, host_opt_state = (
                    assemble_checkpoint(
                        resume, verified[0], verified[1], n_model_stages,
                        self.B, with_opt_state=True,
                    )
                )
            else:  # explicit path: one read+verify via the loader
                host_params, loaded_spec, meta, host_opt_state = (
                    load_checkpoint(
                        resume, n_model_stages, self.B, with_opt_state=True
                    )
                )
            self.resumed_from = str(resume)
            if tuple(loaded_spec.sizes) != tuple(self.spec.sizes):
                raise ValueError(
                    f"checkpoint sizes {loaded_spec.sizes} do not match the "
                    f"requested model sizes {self.spec.sizes}"
                )
            if getattr(loaded_spec, "act", "relu") != self.spec.act:
                raise ValueError(
                    f"checkpoint activation family "
                    f"{getattr(loaded_spec, 'act', 'relu')!r} does not match "
                    f"the requested model's {self.spec.act!r} — the family "
                    f"is program structure, not a runtime knob"
                )
            saved_opt = meta.get("extra", {}).get("optimizer")
            if saved_opt is not None:
                # name must match, and for stateful optimizers so must the
                # coefficient the saved state was accumulated under — a
                # mismatch would silently reinterpret the velocity. lr is
                # deliberately free (changing it on resume is a schedule, not
                # a reinterpretation of saved state).
                if saved_opt["name"] != optimizer:
                    raise ValueError(
                        f"checkpoint was trained with optimizer "
                        f"{saved_opt['name']!r}; resuming with {optimizer!r} "
                        f"would silently change the trajectory — pass "
                        f"optimizer={saved_opt['name']!r} to continue it, or "
                        f"start a fresh run without resume"
                    )
                if optimizer == "momentum" and saved_opt.get("momentum") != momentum:
                    raise ValueError(
                        f"checkpoint velocity was accumulated with "
                        f"momentum={saved_opt.get('momentum')}; resuming with "
                        f"momentum={momentum} would reinterpret it — pass the "
                        f"saved coefficient"
                    )
                saved_wd = saved_opt.get("weight_decay", 0.0)
                if saved_wd != weight_decay:
                    raise ValueError(
                        f"checkpoint was trained with weight_decay={saved_wd}; "
                        f"resuming with weight_decay={weight_decay} would "
                        f"silently change the trajectory — pass the saved value"
                    )
            self.spec = loaded_spec
            if meta.get("step_in_epoch") is not None:
                # v2 step snapshot: ``epoch`` is the epoch IN PROGRESS and
                # the cursor restarts mid-epoch. The bit-identity contract
                # needs the identical deterministic data order, so the
                # global batch size must match the saved run exactly.
                if meta["global_batch_size"] != self.B:
                    raise ValueError(
                        f"mid-epoch resume needs the saved data order: "
                        f"checkpoint was taken at global_batch_size="
                        f"{meta['global_batch_size']}, this run uses {self.B}"
                    )
                if not 0 <= meta["step_in_epoch"] < max(nb, 1):
                    raise ValueError(
                        f"checkpoint step_in_epoch {meta['step_in_epoch']} "
                        f"out of range for {nb} batches/epoch — different "
                        f"dataset?"
                    )
                self.epoch = int(meta["epoch"])
                self.step_in_epoch = int(meta["step_in_epoch"])
            else:
                # legacy epoch-boundary snapshot: ``epoch`` is the last
                # COMPLETED epoch
                self.epoch = meta["epoch"] + 1
        else:
            host_params = Mo.init_model(self.spec)

        # telemetry aux: when recording AND clipping, the epoch/run programs
        # also return the pre-clip global gradient norm (ordinary fused
        # outputs — never host callbacks inside the scan). The kernel paths
        # keep gradients in VMEM, so the aux is unavailable there; both
        # layouts' fused runs thread it (trainer.make_train_run and
        # executor.make_pipeline_run).
        kernel_path = megakernel or epoch_kernel or run_kernel
        aux_gnorm = self._metrics.enabled and clip_norm is not None and not kernel_path
        self._epoch_aux = aux_gnorm
        self._run_aux = aux_gnorm
        # flight-recorder aux: per-step (per-batch) loss / pre-clip grad
        # norm / post-update param norm vectors out of the SAME fused epoch
        # program. ``record_steps=None`` (default) auto-enables whenever
        # anything will consume them (a metrics recorder or a health
        # monitor); ``False`` opts a metrics session back out (epoch-level
        # telemetry only — the PR1 cost profile: no per-step param-norm in
        # the program, no per-step JSONL lines; health falls back to
        # epoch-granular checks); ``True`` forces the flight ring on even
        # without a recorder. The NullMetrics default without a monitor
        # keeps the uninstrumented program, so recording disabled stays
        # zero-overhead on the hot path.
        if record_steps is None:
            record_steps = self._metrics.enabled or self._health is not None
        elif record_steps and kernel_path:
            raise ValueError(
                "record_steps is unavailable on the kernel paths: the "
                "gradient never leaves the Pallas kernel's VMEM"
            )
        # numerics-provenance aux (docs/numerics.md "Divergence
        # debugging"): per-step per-layer digest grids (uint32 bitcast
        # checksums + block norms) out of the SAME fused epoch program,
        # emitted as schema-v12 ``digest`` records. Opt-in only — the
        # default keeps today's programs byte-identical.
        if digests and kernel_path:
            raise ValueError(
                "digests is unavailable on the kernel paths: params/grads "
                "never leave the Pallas kernel's VMEM, so the per-layer "
                "digest aux cannot be threaded out"
            )
        self._digests = bool(digests)
        if self._digests and self._metrics.enabled:
            # replay provenance for the bisect CLI (observability/
            # divergence.py --bisect): everything needed to reconstruct a
            # numerically identical session and re-arm its injections —
            # ``die`` faults are stripped at replay time, step faults
            # (nan/flip) must fire again or the divergence won't reproduce
            self._metrics.event(
                "digest_config",
                sizes=list(sizes), model=model, dp=dp, pp=pp, tp=self.tp,
                schedule=schedule, global_batch_size=global_batch_size,
                mubatches=mubatches, lr=lr, precision=precision,
                optimizer=optimizer, momentum=momentum,
                virtual_stages=virtual_stages, zero1=zero1,
                zero=self._zero,
                grad_bucket_bytes=grad_bucket_bytes,
                backward_split=backward_split, recompute=recompute,
                scan_unroll=scan_unroll,
                tick_unroll=tick_unroll, weight_decay=weight_decay,
                clip_norm=clip_norm, fuse_mubatches=fuse_mubatches,
                data_dir=None if data_dir is None else str(data_dir),
                faults=",".join(repr(f) for f in self._faults.faults),
            )
        self._step_aux = bool(record_steps) and not kernel_path
        self.flight = FlightRecorder() if self._step_aux else None
        if self.flight is not None:
            # the metrics cursor: resumed step records continue the global
            # numbering instead of restarting at 0
            self.flight.total_steps = self.global_step
        self._epoch_compiled = False  # compile-span already recorded?
        self._epoch_dispatched = False  # first train_epoch includes compile
        self._cost_recorded = False  # cost_model event already emitted?
        self._cost_xla_recorded = False  # ... with the XLA cross-check leg?

        if self._sequential:
            with self._metrics.span("device_put"):
                self._params = jax.tree.map(jnp.asarray, host_params)
            if host_opt_state is not None and not is_stateless(opt):
                self._opt_state = join_state(
                    opt,
                    {
                        k: jax.tree.map(jnp.asarray, v)
                        for k, v in host_opt_state["parts"].items()
                    },
                    {
                        k: jnp.asarray(v, jnp.float32)
                        for k, v in host_opt_state["scalars"].items()
                    },
                )
            else:
                self._opt_state = opt.init(self._params)
            self._epoch_fn = trainer.make_train_epoch(
                self.spec, opt, precision=self.precision,
                fuse_mubatches=fuse_mubatches, unroll=scan_unroll,
                clip_norm=clip_norm, megakernel=megakernel,
                epoch_kernel=epoch_kernel or run_kernel,
                with_grad_norm=self._epoch_aux,
                with_step_stats=self._step_aux,
                with_digests=self._digests,
            )
            self._predict = trainer.make_predict(self.spec, precision=self.precision)
            self._run_kwargs = dict(
                precision=self.precision, fuse_mubatches=fuse_mubatches,
                unroll=scan_unroll, clip_norm=clip_norm, megakernel=megakernel,
                epoch_kernel=epoch_kernel or run_kernel,
            )
            self._Xe = self._X.reshape(nb, self.M, self.B // self.M, -1)
            self._Ye = self._Y.reshape(nb, self.M, self.B // self.M, -1)
            self._X = self._Y = None  # the microbatched views are the only users
        else:
            self.mesh, self._mesh_layout = make_mesh_with_layout(
                dp, pp, devices, tp
            )
            if self._metrics.enabled:
                # placement provenance (topology-aware vs order-preserving):
                # a bench record measured on one placement must say so —
                # the two differ materially on a real slice
                self._metrics.event(
                    "mesh_layout",
                    dp=dp, pp=pp, tp=self.tp, layout=self._mesh_layout,
                    n_devices=dp * pp * self.tp,
                )
            with self._metrics.span("schedule_lower"):
                prog = lower_schedule(
                    S.SCHEDULES[schedule], mubatches, pp, virtual=self.V,
                    backward_split=self._backward_split,
                    recompute=self._recompute,
                )
            if self._metrics.enabled or self._audit_strict:
                # program-level static analysis at lowering time, BEFORE
                # anything compiles or dispatches: send/recv match, MPMD
                # deadlock-freedom, stash lifetimes (analysis/;
                # docs/static-analysis.md) — the machine-checked form of
                # the invariants the lowering simulator constructs by
                # simulation (the simulator is the spec, this is the proof)
                self._record_static_analysis(prog, "epoch_program")
            if self._metrics.enabled:
                # per-tick program stats, recorded once at lowering time:
                # the executor's runtime tick behaviour is fully determined
                # by these static tables (ticks, sends, occupancy, bubble)
                stats = program_stats(
                    prog, spec=self.spec,
                    mubatch_size=local_batch // mubatches, tp=self.tp,
                )
                if self._recompute:
                    # the stashed twin's footprint, lowered alongside (pure
                    # Python, no compile): the report CLI's Memory section
                    # renders the two peaks side by side from ONE stream —
                    # the saving is an artifact of both real tick tables,
                    # not a formula
                    twin = program_stats(
                        lower_schedule(
                            S.SCHEDULES[schedule], mubatches, pp,
                            virtual=self.V,
                            backward_split=self._backward_split,
                            recompute=False,
                        ),
                        spec=self.spec,
                        mubatch_size=local_batch // mubatches, tp=self.tp,
                    )
                    stats["stash_bytes_peak_stashed_twin"] = twin[
                        "stash_bytes_peak"
                    ]
                    stats["stash_slots_stashed_twin"] = twin["stash_slots"]
                self._metrics.event(
                    "pipeline_program",
                    schedule=schedule, dp=dp, pp=pp, tp=self.tp,
                    virtual=self.V, model=self.model_name, **stats,
                )
                self._metrics.gauge(
                    "pipeline.bubble_fraction", stats["bubble_fraction"]
                )
            with self._metrics.span("device_put"):
                stacked_np, flags_np = E.stack_params(
                    host_params, self.spec, order=self._order, tp=self.tp
                )
                if self._zero == 3:
                    # ZeRO-3 params at rest: one (pp*tp, dp*csz3)
                    # block-cyclic array, each device holding only its own
                    # 1/dp shard — the {W,b} stacked layout never lands on
                    # device (predict/save rebuild it on demand)
                    self._stacked = {
                        "P": jax.device_put(
                            E.zero_block_flatten_rows(
                                stacked_np, self.spec, self.mesh
                            ),
                            E.zero1_part_sharding(self.mesh),
                        )
                    }
                    self._flags = E.put_pp(flags_np, self.mesh)
                else:
                    self._stacked, self._flags = E.put_stacked(
                        stacked_np, flags_np, self.mesh
                    )
            if self._zero >= 2:
                self._opt_state = E.zero_block_state_from_logical(
                    host_opt_state, opt, self.spec, self.mesh, order=self._order
                )
            elif self._zero1:
                self._opt_state = E.zero1_state_from_logical(
                    host_opt_state, opt, self.spec, self.mesh, order=self._order
                )
            elif host_opt_state is not None and not is_stateless(opt):
                # stack + place each state part exactly like the params it
                # mirrors (zero padding is consistent: padded grads are
                # exactly zero, so padded state stays zero); scalars replicate
                rep = NamedSharding(self.mesh, PartitionSpec())
                self._opt_state = join_state(
                    opt,
                    {
                        k: E.put_stacked_tree(
                            E.stack_params(
                                v, self.spec, order=self._order, tp=self.tp
                            )[0],
                            self.mesh,
                        )
                        for k, v in host_opt_state["parts"].items()
                    },
                    {
                        k: jax.device_put(np.float32(v), rep)
                        for k, v in host_opt_state["scalars"].items()
                    },
                )
            else:
                self._opt_state = opt.init(self._stacked)
            if self.runtime == "mpmd":
                from shallowspeed_tpu.observability.tracing import Tracer
                from shallowspeed_tpu.parallel import mpmd

                # the MPMD runner's constructor IS the admission gate:
                # analyze_program must prove the tick tables deadlock-free
                # before any stage program can be built or dispatched
                self._mpmd = mpmd.MpmdTrainRunner(
                    self.mesh, self.spec, prog, local_batch // mubatches,
                    opt, precision=self.precision,
                    tracer=Tracer(self._metrics, process="m"),
                )

                def _mpmd_epoch(stacked, flags, opt_state, X, Y):
                    return self._mpmd.run(
                        stacked, flags, opt_state, X, Y,
                        trace_id=f"mpmd-{self.global_step}",
                    )

                self._epoch_fn = _mpmd_epoch
            else:
                self._epoch_fn = E.make_pipeline_epoch(
                    self.mesh, self.spec, prog, local_batch // mubatches, opt,
                    precision=self.precision, zero=self._zero,
                    unroll=scan_unroll, tick_unroll=tick_unroll,
                    clip_norm=clip_norm, kernel_backend=kernel_backend,
                    with_grad_norm=self._epoch_aux,
                    with_step_stats=self._step_aux,
                    with_digests=self._digests,
                    grad_bucket_bytes=grad_bucket_bytes,
                )
            self._prog = prog
            self._mubatch_local = local_batch // mubatches
            self._run_kwargs = dict(
                precision=self.precision, unroll=scan_unroll,
                tick_unroll=tick_unroll, zero=self._zero,
                clip_norm=clip_norm, kernel_backend=kernel_backend,
                grad_bucket_bytes=grad_bucket_bytes,
            )

        # analytical cost model + MFU accounting (observability/costmodel):
        # the model-FLOP numerator is known at construction; the XLA
        # cost_analysis cross-check attaches at jit time
        # (_ensure_epoch_compiled / warm_run). On mesh layouts the padded
        # hardware FLOPs come from the lowered tick tables
        # (lowering.program_flops), so the padding tax is recorded per
        # layout, not guessed.
        if self._sequential:
            platform = jax.devices()[0].platform
            padded = None
            self._mesh_layout = None
        else:
            platform = self.mesh.devices.flat[0].platform
            padded = (
                program_flops(
                    self._prog, self.spec, self._mubatch_local, tp=self.tp
                )
                * dp
            )
        self._cost_model = costmodel.CostModel(
            sizes=self.spec.sizes,
            global_batch=self.B,
            batches_per_epoch=self.batches_per_epoch,
            n_devices=1 if self._sequential else dp * pp * self.tp,
            platform=platform,
            precision=self._precision_name,
            padded_flops_per_batch=padded,
        )
        # the layout's analytical comms contract (required/forbidden
        # collective kinds + bytes/step per mesh axis, derived from the
        # lowered tick tables) — what the compiled program's collective
        # census is audited against at jit time. The gradient-sync bucket
        # plan is rebuilt here through the SAME gradsync planners the
        # executor used, so contract and emitters can never disagree.
        self._sync_plan = None
        if grad_bucket_bytes and not self._sequential:
            self._sync_plan = gradsync.plan_buckets(
                self.spec, dp, pp, grad_bucket_bytes, zero=self._zero,
                tp=self.tp,
            )
            if self._metrics.enabled:
                # the plan is static telemetry, recorded once like the
                # pipeline program stats: bucket count + sizes make every
                # later throughput/audit record self-describing
                self._metrics.event(
                    "grad_sync_plan", dp=dp, pp=pp, tp=self.tp,
                    zero=self._zero, **self._sync_plan.describe(),
                )
        self._expected_comms = program_audit.expected_comms(
            self.spec,
            dp,
            pp,
            prog=None if self._sequential else self._prog,
            zero=self._zero,
            mubatch_size=None if self._sequential else self._mubatch_local,
            platform=platform,
            precision=self._precision_name,
            grad_bucket_plan=self._sync_plan,
            tp=self.tp,
            # only params-mirroring parts occupy per-layer bytes (Adam's
            # "t" is a scalar) — the forecast prices what actually shards
            opt_state_parts=sum(
                1 for v in opt.state_layout().values() if v == "params"
            ),
        )
        if self._recovery is not None and self._metrics.enabled:
            # one schema-v4 recovery record per resume decision: what was
            # restored (or that nothing was), where training restarts, and
            # every corrupt snapshot skipped on the way
            self._metrics.recovery(
                self._recovery["verdict"],
                resumed_from=self._recovery["resumed_from"],
                epoch=self.epoch,
                step_in_epoch=self.step_in_epoch,
                global_step=self.global_step,
                skipped=self._recovery["skipped"],
            )

    # -- training -----------------------------------------------------------

    def _epoch_args(self):
        """The layout's runtime argument tuple for one epoch."""
        if self._sequential:
            return (self._params, self._opt_state, self._Xe, self._Ye)
        return (self._stacked, self._flags, self._opt_state, self._X, self._Y)

    def _aot_layout(self):
        """The layout tuple half of the AOT cache key (the program CONTENT
        hash over the lowered StableHLO does the real invalidation work;
        this keeps distinct configurations from ever sharing a filename)."""
        return (
            tuple(self.spec.sizes), self._act, self.dp, self.pp, self.tp,
            self.V, self.schedule, self.B, self.M, self._precision_name,
            self._kernel_backend, self._slot_rows, self._recompute,
        )

    def _record_static_analysis(self, prog, program):
        """The program-level static passes (shallowspeed_tpu/analysis)
        over one lowered TickProgram: send/recv match & MPMD
        deadlock-freedom over the tables, stash-lifetime discipline.
        Run at lowering time — a violated contract raises
        ``ProgramAnalysisError`` BEFORE the program can compile or
        dispatch, with the evidence recorded first (schema-v9
        ``static_analysis`` record, findings count + the finding text),
        exactly the census's record-then-refuse shape."""
        from shallowspeed_tpu.analysis import (
            ProgramAnalysisError,
            analyze_program,
        )

        try:
            verdict = analyze_program(prog, program=program)
        except ProgramAnalysisError as e:
            if self._metrics.enabled:
                self._metrics.static_analysis(
                    program,
                    passes=["send_recv", "deadlock", "stash"],
                    findings=1,
                    finding=str(e),
                )
                self._metrics.flush()  # the refusal evidence hits disk first
            raise
        if self._metrics.enabled:
            self._metrics.static_analysis(
                program,
                **{k: v for k, v in verdict.items() if k != "program"},
            )
        return verdict

    def _aot_resolve(self, program, audit_label, jit_fn, args, expected,
                     dedup, dispatch=False):
        """Resolve one compiled program through the AOT executable cache
        (shallowspeed_tpu/aot_cache.py): lower (milliseconds — tracing, no
        XLA), key on (layout, backend fingerprint, lowered-program hash),
        try the cache, and fall back to a clean ``.compile()`` + store on
        any miss/stale/corrupt outcome.

        The audit-at-compile contract survives the cache: a DESERIALIZED
        program is censused against ``expected`` before this returns — it
        can never reach a dispatch un-audited — and a census mismatch is
        treated like corruption (recorded ``audit_mismatch`` + recompile),
        because a bad cache entry is not a mislowered program; the
        recompile re-audits under the normal strict rules. Returns
        ``(compiled, from_cache)``; only a real compile bumps the
        ``jit_compiles`` counter, which is how the zero-recompile warm
        start is pinned.

        ``dispatch=True`` declares that the RESOLVED EXECUTABLE is the
        dispatch path (the inference rungs, the sequential slot-predict
        program) — then the HLO dispatch-safety pass
        (``program_audit.verify_dispatch_safety``) additionally proves
        the program donates no buffers before it can ever run: a
        donating CACHE entry is treated like corruption (recorded
        ``audit_mismatch`` + clean recompile), and a donating RECOMPILE
        raises ``AuditMismatchError`` unlatched, because executing a
        deserialized donating program is the jax-0.4.x heap-corruption
        hazard and a donating serving program is a use-after-free (the
        PR 1/PR 12 rule, now proven instead of assumed; probe-only
        resolutions like the epoch audit probe keep ``dispatch=False``
        — they lawfully donate and are never executed)."""
        aot = self._aot
        lowered = jit_fn.lower(*args)
        key = aot.key_for(program, self._aot_layout(), lowered.as_text())
        compiled = aot.load(key, program=program)
        if compiled is not None:
            rec = program_audit.audit_compiled(
                compiled,
                expected=expected,
                platform=self._cost_model.platform,
                n_devices=self._cost_model.n_devices,
            )
            reason = None
            if rec.get("census_ok") is False:
                reason = "; ".join(rec.get("mismatches", ()))[:200]
            elif dispatch:
                try:
                    program_audit.verify_dispatch_safety(
                        compiled, context=program
                    )
                except program_audit.AuditMismatchError as e:
                    reason = f"dispatch-safety: {e}"[:200]
            if reason is not None:
                aot.record(
                    "audit_mismatch", program=program, key=key,
                    reason=reason,
                )
                aot.record(
                    "fallback", program=program, key=key,
                    reason="audit_mismatch",
                )
                compiled = None
            else:
                if self._metrics.enabled:
                    self._metrics.audit(audit_label, **rec)
                self._audit_done.add(dedup)
                return compiled, True
        with self._metrics.span("jit_compile"):
            compiled = lowered.compile()
        self._metrics.counter("jit_compiles")
        self._record_audit(compiled, audit_label, dedup=dedup,
                           expected=expected)
        if dispatch:
            # a freshly-compiled dispatch-path program that donates is a
            # real lowering bug, not a bad cache entry: refuse, unlatched
            program_audit.verify_dispatch_safety(compiled, context=program)
        aot.store(key, compiled, program=program)
        return compiled, False

    def _ensure_epoch_compiled(self):
        """With metrics enabled, compile the epoch program once inside a
        ``jit_compile`` span (trace + lowering + XLA compile, timed as a
        first-class record) before the first dispatch. Steady-state dispatch
        stays on the jit wrapper's C++ fast path — on this backend the AOT
        executable's Python dispatch costs ~2-3% per epoch, so the compiled
        object is only the timing probe, not the call path. The probe does
        NOT warm the jit wrapper's own call cache (verified on jax 0.4.x:
        the first jit call still compiles), so the first dispatch pays a
        second compile — a deliberate one-time cost for an isolated
        compile-time record, and the reason the first ``epoch`` event is
        stamped ``includes_compile`` (its wall/samples_per_sec are NOT
        steady-state; consumers must not read them as such).

        ``audit=True`` also forces this compile (even metrics-less): the
        program audit needs the compiled object to verify the layout's
        collective contract before the first dispatch.

        On the MPMD runtime the "epoch program" is the per-stage program
        set: the warm pass compiles (or AOT-loads) every planned stage
        program, censuses each against its per-stage contract
        (``mpmd.expected_stage_comms``) and proves it donation-free —
        then swaps the dispatch path onto the resolved executables, so a
        cache-warm MPMD start compiles zero stage programs."""
        if self.runtime == "mpmd":
            if self._epoch_compiled or not (
                self._metrics.enabled or self._audit_strict
                or self._aot is not None
            ):
                return
            self._mpmd.warm(
                self._stacked, self._flags, self._opt_state,
                self._mpmd_resolve,
            )
            self._epoch_compiled = True
            self._record_cost_model()
            return
        if self._epoch_compiled or not (self._metrics.enabled or self._audit_strict):
            return
        if self._aot is not None:
            # the audit probe rides the AOT cache: a warm start deserializes
            # the epoch program for its census + cost_analysis instead of
            # paying the probe's XLA compile. The deserialized object is
            # PROBE-ONLY — dispatch stays on the jit wrapper (which donates
            # its buffers; executing a deserialized donating program is the
            # jax-0.4.x hazard class this cache deliberately avoids)
            compiled, _ = self._aot_resolve(
                "epoch_probe", "epoch_program", self._epoch_fn,
                self._epoch_args(), expected=self._expected_comms,
                dedup="epoch_program",
            )
            self._cost_model.attach_compiled(compiled)
            self._epoch_compiled = True
            self._record_cost_model()
            return
        with self._metrics.span("jit_compile"):
            compiled = self._epoch_fn.lower(*self._epoch_args()).compile()
        self._metrics.counter("jit_compiles")
        # cost-model cross-check at jit time: pull the compiled epoch
        # program's XLA-reported FLOPs/bytes next to the analytical count
        self._cost_model.attach_compiled(compiled)
        # audit BEFORE latching the compiled flag: a strict mismatch must
        # leave the session un-warmed, so a caller that catches the error
        # and retries is re-audited (and re-refused), never silently
        # trained on the mislowered program
        self._record_audit(compiled, "epoch_program")
        self._epoch_compiled = True
        self._record_cost_model()

    def _mpmd_resolve(self, label, role, jit_fn, args, expected):
        """The MPMD warm pass's per-stage-program hook: AOT-resolve (when
        a cache is configured) or compile each stage program, census it
        against its per-stage contract, and prove it donation-free
        (``verify_dispatch_safety`` — every stage program IS a dispatch
        path). Returns the executable the runner should dispatch, or
        None to keep the plain jit wrapper (nothing to verify and no
        cache to serve)."""
        dedup = ("mpmd", label)
        if self._aot is not None:
            compiled, _ = self._aot_resolve(
                label, "mpmd_stage_program", jit_fn, args,
                expected=expected, dedup=dedup, dispatch=True,
            )
            return compiled
        if not (self._metrics.enabled or self._audit_strict):
            return None
        if dedup in self._audit_done:
            return None
        with self._metrics.span("jit_compile"):
            compiled = jit_fn.lower(*args).compile()
        self._metrics.counter("jit_compiles")
        self._record_audit(
            compiled, "mpmd_stage_program", dedup=dedup, expected=expected
        )
        # every stage program is a dispatch path: donation would be a
        # use-after-free against the next microbatch's read — proven
        # absent from the compiled HLO, unlatched like the census
        program_audit.verify_dispatch_safety(compiled, context=label)
        return compiled

    def _refuse_pending_faults(self, entry):
        """Injections fire at step boundaries, which only ``train_steps``
        has — a whole-epoch or fused-run dispatch would sail straight past
        them, and a recovery harness that expected the kill would conclude
        the crash/resume path works when nothing was injected. Refuse
        loudly instead of skipping silently."""
        if self._faults and self._faults.pending:
            raise ValueError(
                f"{entry}() cannot honor the pending fault injection(s) "
                f"{self._faults.pending!r}: injections land on step "
                "boundaries — drive this run with train_steps()"
            )

    def _ensure_chunk_audited(self, k0, k1):
        """Chunk-shaped sibling of ``_ensure_epoch_compiled``: a
        ``train_steps`` dispatch over batches [k0, k1) is a DISTINCT XLA
        program whenever the slice is shorter than the epoch, so the audit
        contract ("a mislowered layout never trains a step") must census
        that program, not the full-epoch one. Per distinct chunk length the
        sliced program is AOT-compiled once inside a ``jit_compile`` span
        and audited (the scan body — and therefore the collective census —
        is length-independent; only the trip count changes). Full-epoch
        slices take the normal epoch path; chunked-only sessions never pay
        the full-epoch compile their dispatches would not use."""
        if k1 - k0 == self.batches_per_epoch or self.runtime == "mpmd":
            # MPMD dispatches the same per-stage programs for any chunk
            # length (the host loop owns the batch axis), so there is no
            # distinct sliced program to audit
            self._ensure_epoch_compiled()
            return
        if not (self._metrics.enabled or self._audit_strict):
            return
        dedup = ("chunk", k1 - k0)
        if dedup in self._audit_done:
            return
        with self._metrics.span("jit_compile"):
            compiled = self._epoch_fn.lower(
                *self._sliced_epoch_args(k0, k1)
            ).compile()
        self._metrics.counter("jit_compiles")
        # audited (and marked done) only on a pass — same never-latch-a-
        # failure contract as the epoch path. No cost-model attach: the
        # cross-check is defined against the epoch program's shapes.
        self._record_audit(compiled, "chunk_program", dedup=dedup)
        self._record_cost_model()

    def _record_audit(self, compiled, program, dedup=None, expected=None):
        """Jit-time XLA program audit (observability/program_audit.py):
        census the compiled program's collectives, pull its memory
        analysis, and emit one schema-v3 ``xla_audit`` record per DISTINCT
        compiled program (``dedup`` names the compile variant; defaults to
        the program label). ``expected`` overrides the session's training
        contract — the inference programs audit against their own
        forward-only contract. Under ``audit=True`` a census that violates
        the layout's analytical comms contract raises AuditMismatchError —
        BEFORE the first dispatch, so a mislowered layout never trains a
        step (the program is marked audited only on a pass: a
        caught-and-retried failure re-audits and re-raises; its evidence
        records duplicate, which is the honest trade)."""
        dedup = dedup if dedup is not None else program
        if dedup in self._audit_done:
            return
        rec = program_audit.audit_compiled(
            compiled,
            expected=expected if expected is not None else self._expected_comms,
            platform=self._cost_model.platform,
            n_devices=self._cost_model.n_devices,
        )
        if self._metrics.enabled:
            self._metrics.audit(program, **rec)
            self._metrics.flush()  # the mismatch evidence must hit disk first
        if self._audit_strict and rec.get("census_ok") is False:
            raise program_audit.AuditMismatchError(
                f"{program}: compiled collective census disagrees with the "
                f"layout contract (dp={self.dp}, pp={self.pp}, "
                f"zero={self._zero}): " + "; ".join(rec["mismatches"])
            )
        self._audit_done.add(dedup)

    def _record_cost_model(self):
        """Emit the cost_model event + model_flops gauge. Emitted once per
        session — except that a record written BEFORE the XLA cross-check
        attached (a warm_run-first session) is re-emitted once the compiled
        epoch program's cost_analysis exists, so the flops_ratio signal is
        never silently lost (consumers keep the last event)."""
        if not self._metrics.enabled:
            return
        has_xla = self._cost_model.xla_flops_per_epoch is not None
        if self._cost_recorded and (self._cost_xla_recorded or not has_xla):
            return
        self._metrics.event("cost_model", **self._cost_model.as_record())
        self._metrics.gauge("model_flops", self._cost_model.flops_per_epoch)
        self._cost_recorded = True
        self._cost_xla_recorded = has_xla

    def _record_utilization(self, samples_per_sec):
        """Per-dispatch MFU accounting: achieved model-FLOP/s and MFU
        gauges (docs/observability.md). Returns the MFU (None when no peak
        is known for this platform)."""
        self._metrics.gauge(
            "achieved_flops_per_sec",
            self._cost_model.achieved_flops_per_sec(samples_per_sec),
        )
        mfu = self._cost_model.mfu(samples_per_sec)
        if mfu is not None:
            self._metrics.gauge("mfu", mfu)
        return mfu

    def _record_flight(self, epoch_index, aux):
        """Host side of the step-level flight recorder: read the fused
        per-step aux back (one readback per epoch, after the dispatch),
        ring-buffer it, stream schema-v2 ``step`` records, and run the
        numerics health checks (which may raise HealthError under
        policy='halt' — after this epoch's update was applied)."""
        losses = np.asarray(aux["step_loss"], np.float64)
        gns = np.asarray(aux["step_grad_norm"], np.float64)
        pns = np.asarray(aux["step_param_norm"], np.float64)
        first = self.flight.total_steps  # the ring owns the global numbering
        samples = self.flight.record_epoch(
            epoch_index, losses, gns, pns, first_step=first
        )
        if self._metrics.enabled:
            for s in samples:
                self._metrics.step("train", **s)
        if self._health is not None:
            findings = self._health.check_epoch(
                epoch_index, losses, gns, pns, first_step=first
            )
            self._note_health_findings(findings)
            self._health.dispatch(findings, self._metrics)

    def _record_digests(self, epoch_index, first_step, dig):
        """Host side of the numerics-provenance stream: read the fused
        per-step digest aux back (same single post-dispatch readback as
        the flight recorder) and emit one schema-v12 ``digest`` record per
        optimizer step, with the per-GLOBAL-layer checksum/norm lists in
        logical layer order on every layout (the mesh aux's (S, L) grids
        are indexed through the stacked-row permutation)."""
        host = {k: np.asarray(v) for k, v in dig.items()}
        rows = self._digest_layer_index()
        mesh = host["crc_w"].ndim == 3  # (nb, S, L) vs sequential (nb, L)
        nb = host["crc_w"].shape[0]
        for i in range(nb):
            fields = {}
            for k, a in host.items():
                col = a[i]
                vals = [col[r, l] for r, l in rows] if mesh else list(col)
                cast = int if k.startswith("crc") else float
                fields[k] = [cast(v) for v in vals]
            self._metrics.digest(
                "train",
                step=first_step + i,
                epoch=epoch_index,
                layers=len(rows),
                **fields,
            )

    def _digest_layer_index(self):
        """Per-global-layer (row, col) addresses into the digest aux's
        (S, L) grids, in logical layer order: stage s's layer l lives at
        row ``row_of[s]`` (the stacked-row permutation — identity unless
        virtual stages interleave) and column l. Sequential aux is already
        (L_total,) in logical order; the addresses still enumerate it."""
        idx = getattr(self, "_digest_rows", None)
        if idx is None:
            order = self._order or range(self.spec.n_stages)
            row_of = {s: r for r, s in enumerate(order)}
            idx = self._digest_rows = [
                (row_of[s], l)
                for s in range(self.spec.n_stages)
                for l in range(self.spec.stages[s].n_linears)
            ]
        return idx

    def _note_health_findings(self, findings):
        """Feed health findings to the alert rules BEFORE the policy
        dispatch: under ``halt`` the dispatch raises, and the
        ``training_health`` alert transition must already be in the
        stream when it does — the fleet surface watching many runs
        learns of the blow-up from the alert, not the stack trace."""
        if not findings:
            return
        t = time.perf_counter()
        for f in findings:
            self._telemetry.note_health(t, f["check"])

    @property
    def global_step(self):
        """Run-lifetime optimizer-step count — the unit step checkpoints,
        fault injections and flight-record numbering share."""
        return self.epoch * self.batches_per_epoch + self.step_in_epoch

    @property
    def faults_active(self):
        """True when a fault-injection plan is loaded (arg or env) — the
        driver must then use the step loop so injections land on their
        exact steps."""
        return bool(self._faults)

    def _sliced_epoch_args(self, k0, k1):
        """The layout's runtime argument tuple for batches [k0, k1) of the
        current epoch (the full-epoch tuple when k0=0, k1=nb)."""
        if self._sequential:
            return (self._params, self._opt_state, self._Xe[k0:k1], self._Ye[k0:k1])
        return (
            self._stacked, self._flags, self._opt_state,
            self._X[k0:k1], self._Y[k0:k1],
        )

    def train_steps(self, n):
        """Train up to ``n`` optimizer steps of the CURRENT epoch (clipped at
        the epoch boundary) — the preemption-safe unit: the epoch-scan
        program runs over a SLICE of the batch axis, so chunked dispatch
        applies the exact same per-batch updates in the exact same order as
        one whole-epoch dispatch (bitwise-identical weights; tested), while
        the host regains control between chunks to write step checkpoints.

        Fault-injection boundaries: when the active plan has a fault inside
        this chunk, the chunk is truncated so the fault's step starts the
        next call — ``die`` then kills the process (exception or SIGKILL)
        BEFORE that step trains, ``nan`` poisons the params so that step's
        gradients blow up.

        Returns ``(steps_trained, epoch_mean_loss_or_None)`` — the mean loss
        is reported once, on the call that completes the epoch (same
        definition as ``train_epoch``; the per-chunk means are recombined
        sample-weighted). After a mid-epoch resume the mean covers only the
        steps THIS process trained — the epoch's head belongs to the dead
        process's stream — and the epoch record carries ``steps_counted``
        to say so. Under health policy 'halt' a finding raises
        HealthError AFTER flushing a snapshot (when a checkpoint_dir is
        configured), so the blow-up is resumable.
        """
        nb = self.batches_per_epoch
        if n < 1:
            raise ValueError("n must be >= 1")
        k0 = self.step_in_epoch
        k1 = min(k0 + n, nb)
        g0 = self.epoch * nb + k0
        if self._faults:
            # EVERY un-fired fault scheduled at g0 fires before the dispatch
            # (same-step compositions like "nan@step=3,die@step=3" fire in
            # spec order — a single-shot check would leave the second one
            # pending forever, since later windows all start past g0); then
            # the next pending fault inside this chunk still truncates it,
            # or the chunk would dispatch straight past its step
            fault = self._faults.first_in(g0, g0 + (k1 - k0))
            while fault is not None and fault.step == g0:
                if fault.kind == "die":
                    self._faults.fire_die(fault)  # SIGKILL never returns
                elif fault.kind == "nan":
                    fault.fired = True
                    self.poison_weights()
                elif fault.kind == "flip":
                    fault.fired = True
                    self.flip_weights()
                fault = self._faults.first_in(g0, g0 + (k1 - k0))
            if fault is not None:
                k1 = k0 + (fault.step - g0)  # fault lands on a boundary
        epoch_index = self.epoch
        first_dispatch = self._metrics.enabled and not self._epoch_dispatched
        self._ensure_chunk_audited(k0, k1)
        t0 = time.perf_counter()
        with self._metrics.span("train_steps"):
            out = self._epoch_fn(*self._sliced_epoch_args(k0, k1))
            if self._sequential:
                self._params, self._opt_state, mean_loss = out[0], out[1], out[2]
            else:
                self._stacked, self._opt_state, mean_loss = out[0], out[1], out[2]
            loss = float(mean_loss)  # forces device completion
        wall = time.perf_counter() - t0
        aux = (
            out[3]
            if (self._epoch_aux or self._step_aux or self._digests)
            else None
        )
        if self._digests and self._metrics.enabled:
            self._record_digests(epoch_index, g0, aux["digests"])
        self._epoch_dispatched = True
        steps = k1 - k0
        self.step_in_epoch = k1
        self._epoch_loss_sum += loss * steps
        self._epoch_wall += wall
        self._epoch_steps_counted += steps
        self._epoch_first_dispatch = self._epoch_first_dispatch or first_dispatch
        if self._metrics.enabled:
            self._metrics.counter("samples_trained", steps * self.B)
        epoch_loss = None
        if k1 == nb:
            # loss/throughput over the steps THIS process dispatched: after
            # a mid-epoch resume that is the epoch's tail only (the head's
            # evidence lives in the dead process's record stream), so the
            # record says so instead of diluting the mean by the full nb
            # and inflating samples/s with samples it never trained
            counted = self._epoch_steps_counted
            epoch_loss = self._epoch_loss_sum / counted
            if self._metrics.enabled:
                samples = counted * self.B
                ew = self._epoch_wall
                sps = samples / ew if ew > 0 else 0.0
                record = dict(
                    epoch=epoch_index,
                    loss=epoch_loss,
                    samples_per_sec=sps,
                    wall_s=ew,
                    chunked=True,  # wall spans >= 1 dispatches + host gaps
                )
                if counted < nb:
                    record["steps_counted"] = counted  # mid-epoch resume
                if self._epoch_first_dispatch:
                    record["includes_compile"] = True
                mfu = self._record_utilization(sps)
                if mfu is not None:
                    record["mfu"] = mfu
                self._metrics.event("epoch", **record)
                self._metrics.counter("epochs_trained")
                self._telemetry.note_step(
                    time.perf_counter(), loss=epoch_loss, step_s=ew,
                    throughput=sps, mfu=mfu,
                )
            self.epoch += 1
            self.step_in_epoch = 0
            self._epoch_loss_sum = 0.0
            self._epoch_wall = 0.0
            self._epoch_steps_counted = 0
            self._epoch_first_dispatch = False
        # flight + health LAST: session state is consistent if 'halt' raises
        try:
            if self._step_aux:
                self._record_flight(epoch_index, aux)
            elif self._health is not None:
                findings = self._health.check_epoch(epoch_index, [loss])
                self._note_health_findings(findings)
                self._health.dispatch(findings, self._metrics)
        except HealthError:
            self._flush_halt_checkpoint()
            raise
        return steps, epoch_loss

    def save_step_checkpoint(self, reason="step", rotate=True, async_=None):
        """Write the resumable snapshot at the current ``global_step`` into
        the session's checkpoint directory (``step-<global_step>.npz``:
        params + optimizer state + step cursor + content checksum), rotate
        retention down to ``checkpoint_keep``, and emit a schema-v4
        ``checkpoint`` record. Returns the written path.

        ``async_`` (default: the session's ``async_checkpoint`` setting):
        keep only stage 1 — the device->host snapshot — on the step path
        and hand verification (sha256 + finiteness), the
        write-fsync-rename sequence and rotation to the background writer
        (``checkpoint.AsyncCheckpointWriter``), behind a bounded
        ``checkpoint_queue``-deep in-flight window whose ``submit``
        BLOCKS when full (backpressure — a snapshot is never silently
        dropped, which would widen the replay window past the configured
        cadence). The stage order — and therefore every crash window —
        is byte-identical to the synchronous path (shared
        ``run_save_stages``); the ``checkpoint`` record is emitted from
        the writer on completion with ``async: true``, the queue depth
        sampled at enqueue, and the off-path ``verify_s``/``write_s``
        costs, while ``wall_s`` is the ON-PATH cost only. A writer-side
        failure re-raises on this thread at the next save or
        ``drain_checkpoints()``.

        Rotation is skipped when ``rotate=False`` (the halt flush opts out)
        AND whenever the snapshot just written is non-finite: once a run
        blows up, every grid save carries ``all_finite: false``, and
        unconditional rotation would delete the last healthy snapshot
        within ``keep`` intervals — making ``resume='auto'`` (which skips
        non-finite snapshots by design) permanently unrecoverable. Instead
        the non-finite evidence accumulates unrotated until finiteness
        returns; recoverability beats disk tidiness on a blown-up run.
        (``rotate_step_checkpoints`` itself also ranks fully-verifying
        snapshots above non-finite/corrupt ones, so when rotation does
        fire it reclaims the stale unusable pile, never a healthy
        snapshot.)"""
        if self._ckpt_dir is None:
            raise ValueError(
                "no checkpoint_dir configured on this session"
            )
        if async_ is None:
            async_ = self._async_ckpt_default
        gs = self.global_step
        epoch, sie = self.epoch, self.step_in_epoch
        path = step_checkpoint_path(self._ckpt_dir, gs)
        save_seq = self._save_seq
        self._save_seq += 1
        rotate_dir = self._ckpt_dir if rotate else None
        t0 = time.perf_counter()
        if not async_:
            arrays, meta = build_snapshot(
                self.params(),
                self.spec,
                epoch,
                extra={"optimizer": self._opt_config},
                opt_state=self.opt_state_logical(),
                step_in_epoch=sie,
                global_step=gs,
            )

        def completion(result, on_path_wall, queue_depth=None):
            # runs inline (sync) or on the writer thread (async): update
            # the trusted set for rotation ranking, then emit the record.
            # "trusted" (not "all_finite"): a corrupt-injected snapshot is
            # finite in its metadata but can never verify — trusting it
            # would let rotation rank garbage above real fallbacks
            if result.get("trusted", result["all_finite"]):
                self._trusted_snapshots.add(str(path))
            if self._metrics.enabled:
                fields = dict(
                    path=str(path),
                    epoch=epoch,
                    step_in_epoch=sie,
                    global_step=gs,
                    bytes=result["bytes"],
                    wall_s=on_path_wall,
                    verify_s=result["verify_s"],
                    write_s=result["write_s"],
                )
                if queue_depth is not None:
                    fields["async"] = True
                    fields["queue_depth"] = queue_depth
                    fields["queued_s"] = result["queued_s"]
                    # the deferred logical-unstacking wall (off-path):
                    # what the step path stopped paying (ROADMAP item 5
                    # follow-on; CKPT_AOT_r01.json scoreboard)
                    fields["unstack_s"] = result.get("unstack_s", 0.0)
                else:
                    fields["async"] = False
                self._metrics.checkpoint(reason, **fields)

        # tuple(): an immutable point-in-time copy (a C-level, GIL-atomic
        # snapshot of the set). The writer thread's completion callbacks
        # keep adding to the live set while rotation — on EITHER thread —
        # iterates its trusted collection with syscalls in between; handing
        # rotation the live set would be a set-changed-during-iteration
        # crash waiting for a mixed sync/async save to land it.
        trusted_now = tuple(self._trusted_snapshots)
        if not async_:
            result = run_save_stages(
                path, arrays, meta,
                faults=self._faults, save_seq=save_seq,
                rotate_dir=rotate_dir, rotate_keep=self._ckpt_keep,
                trusted=trusted_now,
            )
            wall = time.perf_counter() - t0
            completion(result, wall)
            if self._metrics.enabled:
                self._telemetry.note_checkpoint(time.perf_counter(), wall)
            return path
        # async: the step path keeps ONLY the device->host readback (the
        # consistency point) — the logical unstacking (params()/
        # opt_state_logical's per-stage reshaping) and build_snapshot's
        # flattening run on the writer thread via the deferred build
        # (ROADMAP item 5 follow-on: it was the dominant on-path cost)
        raw_params, raw_state = self._snapshot_raw()
        spec, opt_cfg = self.spec, dict(self._opt_config)

        def build():
            params, opt_state = self._logical_from_raw(raw_params, raw_state)
            return build_snapshot(
                params, spec, epoch,
                extra={"optimizer": opt_cfg},
                opt_state=opt_state,
                step_in_epoch=sie,
                global_step=gs,
            )

        if self._ckpt_writer is None:
            self._ckpt_writer = AsyncCheckpointWriter(
                max_in_flight=self._ckpt_queue,
                faults=self._faults,
            )
        depth = self._ckpt_writer.queue_depth
        # on-path wall = snapshot + enqueue (the enqueue blocks only when
        # the bounded window is full — that stall IS the backpressure and
        # is charged honestly to the step path). The tiny event handshake
        # lets the writer-thread record carry the wall measured HERE,
        # without racing the submit return.
        wall_box = {}
        measured = threading.Event()

        def job_complete(result):
            measured.wait(timeout=60)
            completion(
                result, wall_box.get("wall", 0.0), queue_depth=depth
            )

        self._ckpt_writer.submit(
            path, None, None, save_seq,
            rotate_dir=rotate_dir, rotate_keep=self._ckpt_keep,
            trusted=trusted_now, on_complete=job_complete, build=build,
        )
        wall_box["wall"] = time.perf_counter() - t0
        measured.set()
        if self._metrics.enabled:
            # the ON-PATH wall only (snapshot + enqueue) — the overhead
            # fraction budgets what the step path pays, and this thread
            # owns the telemetry state (the writer thread must not)
            self._telemetry.note_checkpoint(
                time.perf_counter(), wall_box["wall"]
            )
        return path

    def drain_checkpoints(self):
        """Block until every async snapshot in flight is durable on disk
        (rename + fsync complete); writer-side failures re-raise here.
        No-op when nothing was ever saved asynchronously. ``close()``,
        the halt flush and ``train.py``'s exit all run this, so no exit
        path can leave a snapshot half-owned by a daemon thread."""
        if self._ckpt_writer is not None:
            self._ckpt_writer.drain()

    def close(self):
        """Release the session's background resources: drain + stop the
        async checkpoint writer (re-raising any writer failure) and flush
        the metrics sink. Idempotent; the session remains usable for
        dispatch afterwards (a later async save just restarts a writer)."""
        if self._ckpt_writer is not None:
            writer, self._ckpt_writer = self._ckpt_writer, None
            writer.close()
        # close the trailing partial rollup window before the flush, so
        # the last training records are on disk with everything else
        self._telemetry.flush()
        self._metrics.flush()

    def _flush_halt_checkpoint(self):
        """The health monitor's halt policy flushes a snapshot BEFORE the
        HealthError propagates (when a checkpoint directory is configured):
        a finite finding (grad spike, divergence) is resumable from the
        halt step itself; a non-finite one writes an ``all_finite: false``
        snapshot that resume discovery SKIPS, landing on the last healthy
        step instead. Best-effort — a failing flush never masks the halt.

        Stays SYNCHRONOUS regardless of the session's async-checkpoint
        setting: the process is about to unwind, so the flush must be
        durable before the HealthError leaves this frame — a snapshot
        parked in a daemon writer's queue would die with the process.
        Any async saves already in flight are drained first (best-effort)
        so the halt snapshot can never rename ahead of an older one."""
        if self._ckpt_dir is None:
            return
        try:
            self.drain_checkpoints()
        except Exception as e:  # noqa: BLE001 — never mask the HealthError
            print(f"halt checkpoint drain failed: {e}", file=sys.stderr)
        try:
            self.save_step_checkpoint(reason="halt", rotate=False, async_=False)
            self._metrics.flush()
        except Exception as e:  # noqa: BLE001 — never mask the HealthError
            print(f"halt checkpoint flush failed: {e}", file=sys.stderr)

    def train_epoch(self) -> float:
        """One epoch over the training shard; returns the mean batch training
        loss (same definition on both layouts: global-batch-scaled MSE of each
        batch under its pre-update params, averaged over the epoch).

        With a metrics recorder attached, emits one ``epoch`` event per call
        (epoch index, loss, samples/s, wall seconds — plus the mean pre-clip
        grad norm when clipping) and a ``train_epoch`` span. The first
        recorded epoch carries ``includes_compile: true`` — the jit call
        cache is cold on the first dispatch, so that record's wall clock
        includes compilation and must not be read as steady-state."""
        if self.step_in_epoch != 0:
            raise ValueError(
                f"epoch {self.epoch} is mid-flight at step "
                f"{self.step_in_epoch} (resumed or chunked) — use "
                f"train_steps() to finish it"
            )
        self._refuse_pending_faults("train_epoch")
        first_dispatch = self._metrics.enabled and not self._epoch_dispatched
        self._ensure_epoch_compiled()
        epoch_index = self.epoch
        t0 = time.perf_counter()
        with self._metrics.span("train_epoch"):
            out = self._epoch_fn(*self._epoch_args())
            if self._sequential:
                self._params, self._opt_state, mean_loss = out[0], out[1], out[2]
            else:
                self._stacked, self._opt_state, mean_loss = out[0], out[1], out[2]
            loss = float(mean_loss)  # forces device completion
        aux = (
            out[3]
            if (self._epoch_aux or self._step_aux or self._digests)
            else None
        )
        if self._digests and self._metrics.enabled:
            self._record_digests(
                epoch_index, epoch_index * self.batches_per_epoch,
                aux["digests"],
            )
        if self._metrics.enabled:
            wall = time.perf_counter() - t0
            samples = self.batches_per_epoch * self.B
            sps = samples / wall if wall > 0 else 0.0
            record = dict(
                epoch=epoch_index,
                loss=loss,
                samples_per_sec=sps,
                wall_s=wall,
            )
            if self._epoch_aux:
                record["grad_norm"] = float(aux["grad_norm"])
            if first_dispatch:
                # the jit call cache was cold: this wall includes compile
                record["includes_compile"] = True
            mfu = self._record_utilization(sps)
            if mfu is not None:
                # stamped on the record too, so per-epoch MFU survives the
                # gauge's last-value-wins semantics (the first record's MFU
                # inherits its includes_compile caveat)
                record["mfu"] = mfu
            self._metrics.event("epoch", **record)
            if not first_dispatch:  # steady-state only, per the histogram's use
                self._metrics.observe("epoch.seconds", wall)
            self._metrics.counter("epochs_trained")
            self._metrics.counter("samples_trained", samples)
            self._telemetry.note_step(
                time.perf_counter(), loss=loss, step_s=wall,
                throughput=sps, mfu=mfu,
            )
        self._epoch_dispatched = True
        self.epoch += 1
        # flight recording + health checks LAST: session state is already
        # consistent when a 'halt' policy raises out of here (and the halt
        # path flushes a snapshot first, so the blow-up is resumable)
        try:
            if self._step_aux:
                self._record_flight(epoch_index, aux)
            elif self._health is not None:
                # no per-step aux (kernel paths can't thread it — gradients
                # never leave VMEM — or record_steps=False opted out): fall
                # back to epoch-granular loss checks
                findings = self._health.check_epoch(epoch_index, [loss])
                self._note_health_findings(findings)
                self._health.dispatch(findings, self._metrics)
        except HealthError:
            self._flush_halt_checkpoint()
            raise
        return loss

    def train_run(self, epochs: int, with_eval: bool = True):
        """Train ``epochs`` epochs; returns ``(losses, accuracies)`` as lists
        of floats (``accuracies`` is None when ``with_eval=False``).

        The ENTIRE run — every epoch and (when ``with_eval``) its full-split
        accuracy — is one on-device XLA program on EVERY layout
        (trainer.make_train_run sequentially, executor.make_pipeline_run on
        the mesh): zero host round-trips, which on a remote-tunneled chip
        removes an ~epoch-count × RTT readback cost. Matches the reference's
        epoch structure, train.py:132-137.
        """
        if epochs <= 0:
            raise ValueError("epochs must be positive")
        if self.runtime == "mpmd":
            raise ValueError(
                "train_run() is the fused ONE-on-device-program contract, "
                "which the MPMD runtime (host-scheduled per-stage programs) "
                "deliberately does not have — drive MPMD sessions with "
                "train_epoch()/train_steps()"
            )
        if self.step_in_epoch != 0:
            raise ValueError(
                f"epoch {self.epoch} is mid-flight at step "
                f"{self.step_in_epoch} (resumed or chunked) — finish it with "
                f"train_steps() before a fused train_run()"
            )
        self._refuse_pending_faults("train_run")
        if self._digests:
            raise ValueError(
                "digests ride the epoch/step scan aux, which the fused "
                "multi-epoch run program does not thread — drive digest "
                "sessions with train_epoch()/train_steps()"
            )
        if with_eval and self._vx is None:
            self._load_val()
        if self._metrics.enabled or self._audit_strict:
            # AOT-compile first (inside warm_run's jit_compile span) so the
            # recorded dispatch wall time is steady-state execution — and,
            # under audit=True, so the run program's collective census is
            # verified before it ever dispatches
            self.warm_run(epochs, with_eval=with_eval)
        start = self.epoch
        t0 = time.perf_counter()
        with self._metrics.span("train_run"):
            compiled = self._compiled_runs.get((with_eval, epochs))
            if compiled is not None:
                out = compiled(*self._fused_run_args(with_eval))
            else:
                out = self._fused_run_fn(with_eval)(
                    *self._fused_run_args(with_eval), epochs
                )
            if self._run_aux:
                out, aux = out[:-1], out[-1]
            else:
                aux = None
            if with_eval:
                state, opt_state, losses, accs = out
            else:
                state, opt_state, losses = out
                accs = None
            losses = [float(v) for v in np.asarray(losses)]  # forces completion
            accs_f = [float(v) for v in np.asarray(accs)] if with_eval else None
        if self._sequential:
            self._params = state
        else:
            self._stacked = state
        self._opt_state = opt_state
        self.epoch += epochs
        gns = None if aux is None else np.asarray(aux["grad_norm"])
        if self._metrics.enabled:
            wall = time.perf_counter() - t0
            samples = self.batches_per_epoch * self.B
            # one fused dispatch -> per-epoch wall clocks don't exist; the
            # run-mean samples/s is attributed to every epoch record
            sps = epochs * samples / wall if wall > 0 else 0.0
            mfu = self._record_utilization(sps)
            for e, loss in enumerate(losses):
                record = dict(
                    epoch=start + e,
                    loss=loss,
                    samples_per_sec=sps,
                    wall_s=wall / epochs,
                    fused_run=True,
                )
                if accs_f is not None:
                    record["accuracy"] = accs_f[e]
                if gns is not None:
                    record["grad_norm"] = float(gns[e])
                if mfu is not None:
                    record["mfu"] = mfu
                self._metrics.event("epoch", **record)
                self._telemetry.note_step(
                    time.perf_counter(), loss=loss, step_s=wall / epochs,
                    throughput=sps, mfu=mfu,
                )
            self._metrics.observe("run.seconds", wall)
            self._metrics.counter("epochs_trained", epochs)
            self._metrics.counter("samples_trained", epochs * samples)
        if self._health is not None:
            # the fused run returns in one dispatch: epoch-granular checks
            # (per-epoch mean loss + mean grad norm when threaded)
            findings = self._health.check_run(
                start, losses, None if gns is None else [float(v) for v in gns]
            )
            self._note_health_findings(findings)
            self._health.dispatch(findings, self._metrics)
        return losses, accs_f

    def warm_run(self, epochs: int, with_eval: bool = True):
        """AOT-compile the fused ``train_run`` program without executing it.

        The compiled executable is cached and reused by the next
        ``train_run(epochs, with_eval)``, so e.g. a profiler trace around
        that call captures steady-state device execution, not compilation.
        """
        if epochs <= 0:
            raise ValueError("epochs must be positive")
        if self.runtime == "mpmd":
            raise ValueError(
                "warm_run() AOT-compiles the fused run program, which the "
                "MPMD runtime does not dispatch — the per-stage programs "
                "warm through the audit/AOT pass on the first epoch"
            )
        if with_eval and self._vx is None:
            self._load_val()
        key = (with_eval, epochs)
        if key not in self._compiled_runs:
            with self._metrics.span("jit_compile"):
                compiled = (
                    self._fused_run_fn(with_eval)
                    .lower(*self._fused_run_args(with_eval), epochs)
                    .compile()
                )
            self._metrics.counter("jit_compiles")
            # run-program audit BEFORE caching the executable: same layout
            # contract as the epoch program (the fused run is the same
            # collectives scanned over epochs, plus the eval relay) — a
            # fused-run-only session still gets its census verified, and a
            # strict mismatch leaves nothing cached for a retry to dispatch.
            # Dedup per (with_eval, epochs) VARIANT: each distinct compile
            # is a distinct program and every one that can dispatch must
            # have been audited
            self._record_audit(compiled, "run_program", dedup=("run", key))
            self._compiled_runs[key] = compiled
            # fused-run-only sessions still get the cost_model event (the
            # analytical leg; the XLA cross-check stays tied to the EPOCH
            # program so its per-epoch FLOPs aren't diluted by fused eval)
            self._record_cost_model()

    def _fused_run_fn(self, with_eval):
        """Build (once per with_eval) the layout's fused whole-run program."""
        if with_eval not in self._run_fns:
            if self._sequential:
                kwargs = dict(self._run_kwargs)
                if not with_eval and getattr(self, "_run_kernel", False):
                    # the eval-free run rides the whole-RUN kernel: one
                    # device op for all n_epochs (per-epoch eval needs
                    # per-epoch params, so the evaluated run keeps the
                    # epochs-outer scan over the epoch kernel)
                    kwargs["epoch_kernel"] = False
                    kwargs["run_kernel"] = True
                self._run_fns[with_eval] = trainer.make_train_run(
                    self.spec, self._opt, with_eval=with_eval,
                    with_grad_norm=self._run_aux, **kwargs
                )
            else:
                eval_kwargs = {}
                if with_eval:
                    rows = self._vx_padded.shape[0]
                    eval_kwargs = dict(
                        eval_prog=self._lower_inference_prog(),
                        eval_mubatch_size=rows // self.dp,
                    )
                self._run_fns[with_eval] = E.make_pipeline_run(
                    self.mesh, self.spec, self._prog, self._mubatch_local,
                    self._opt, with_grad_norm=self._run_aux,
                    **self._run_kwargs, **eval_kwargs,
                )
        return self._run_fns[with_eval]

    def _fused_run_args(self, with_eval):
        """The layout's runtime argument tuple for the fused run (everything
        except the static n_epochs)."""
        if self._sequential:
            base = (self._params, self._opt_state, self._Xe, self._Ye)
            return base + ((self._vx, self._vy) if with_eval else ())
        base = (self._stacked, self._flags, self._opt_state, self._X, self._Y)
        return base + ((self._vx_padded, self._vy_labels) if with_eval else ())

    # -- evaluation ---------------------------------------------------------

    def _load_val(self):
        """First-eval setup: load the split and (on mesh layouts) build ONE
        padded whole-split inference program instead of host-looping
        batch-sized steps — the full split flows through the pipeline in a
        single dispatch (the reference evaluates the whole split per epoch
        too, train.py:21-47, just one μbatch at a time)."""
        # global_batch_size=1 so drop-last keeps EVERY validation sample (the
        # reference's val loader silently drops the tail to a batch multiple;
        # we pad the ragged tail instead)
        val = Dataset(self._data_dir, 1, mubatch_size=1, validation=True)
        val.load(0, 1)
        self._vx = jnp.asarray(val.input_X)
        self._vy = jnp.asarray(val.target_y)
        if not self._sequential:
            n_val = self._vx.shape[0]
            # fused-run eval keeps its own whole-split program (one padded
            # microbatch inside the fused run — one row-shard per dp
            # replica); the interactive accuracy() path instead loops the
            # split through the SAME ladder-capped slot programs predict()
            # and the serving engine dispatch
            eval_rows = -(-n_val // self.dp) * self.dp
            self._vx_padded = jnp.pad(self._vx, ((0, eval_rows - n_val), (0, 0)))
            self._vy_labels = jnp.argmax(self._vy, 1)

    @property
    def sequential(self):
        """True on the single-device reference path (dp=pp=V=1) — no mesh,
        no tick programs; inference dispatches one fixed slot program per
        OCCUPIED slot (the serving engine's padding accounting keys off
        this: a sequential dispatch never pays the ladder rung tail)."""
        return self._sequential

    @property
    def slot_rows(self):
        """Global rows per inference microbatch slot (docs/serving.md)."""
        return self._slot_rows

    @property
    def slot_ladder(self):
        """Allowed slot counts per inference dispatch — the compile bound:
        at most len(slot_ladder) cached predict programs per session."""
        return self._slot_ladder

    def predict(self, x):
        """Softmax class probabilities for a (n, in_dim) batch on ANY layout
        (host numpy in, host numpy out). Rows are packed into fixed
        ``slot_rows``-row microbatch slots and dispatched through cached
        inference programs whose slot counts walk the ``slot_ladder`` —
        at most len(ladder) compiled programs ever, and each slot computes
        bitwise-identically in every rung program (the serving engine's
        parity contract rides on exactly this property)."""
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        out_dim = self.spec.out_dim
        if n == 0:
            return np.zeros((0, out_dim), np.float32)
        S_rows = self._slot_rows
        cap = self._slot_ladder[-1] * S_rows  # rows per ladder-capped chunk
        outs = []
        for i in range(0, n, cap):
            chunk = x[i : i + cap]
            m = serving_slots.slots_needed(chunk.shape[0], S_rows)
            if self._sequential:
                # one compiled (slot_rows, in_dim) program, dispatched per
                # slot: a fixed shape is what keeps each slot's rows
                # bitwise-stable against the batch around them. Only the m
                # OCCUPIED slots dispatch — the ladder round-up exists to
                # bound compiled-program count, and the sequential path has
                # exactly one program however many slots run, so the
                # pure-padding rung tail would be wasted work
                xb = np.pad(chunk, ((0, m * S_rows - chunk.shape[0]), (0, 0)))
                slot_fn = self._slot_predict_fn()
                preds = np.concatenate(
                    [
                        np.asarray(
                            slot_fn(
                                self._params,
                                jnp.asarray(xb[k * S_rows : (k + 1) * S_rows]),
                            )
                        )
                        for k in range(m)
                    ],
                    axis=0,
                )
            elif self.runtime == "mpmd":
                # MPMD streaming: each OCCUPIED slot is its own per-stage
                # chain — slot k enters stage 0 while slot k-1 occupies
                # stage 1 — so there is no rung program and therefore no
                # rung round-up (the compile bound is one fwd program per
                # stage, not one per ladder rung). Submit every slot
                # before materializing any: the chains pipeline.
                runner = self._mpmd_infer_runner()
                params, fls = self._mpmd_infer_views()
                xb = np.pad(chunk, ((0, m * S_rows - chunk.shape[0]), (0, 0)))
                handles = [
                    runner.submit(
                        params, fls, xb[k * S_rows : (k + 1) * S_rows]
                    )
                    for k in range(m)
                ]
                preds = np.concatenate(
                    [np.asarray(h) for h in handles], axis=0
                )
            else:
                rung = serving_slots.rung_for(m, self._slot_ladder)
                xb = np.pad(chunk, ((0, rung * S_rows - chunk.shape[0]), (0, 0)))
                step = self._inference_step(rung)
                packed = serving_slots.pack_slots(
                    xb.reshape(rung, S_rows, -1), self.dp
                )
                preds = serving_slots.unpack_slots(
                    np.asarray(
                        step(self._eval_stacked(), self._flags, jnp.asarray(packed))
                    ),
                    rung,
                    self.dp,
                )
            outs.append(preds[: chunk.shape[0], :out_dim])
        return np.concatenate(outs, axis=0)

    def _slot_predict_fn(self):
        """The sequential path's slot-shaped predict program — the one
        program ``predict()`` dispatches per occupied slot. Without an AOT
        cache this is just the jit wrapper (today's exact path); with one,
        the slot program rides the cache like the mesh rungs do, so a
        sequential serving replica (the fleet's default worker shape)
        cold-starts with zero compiles too — census-re-verified before
        first dispatch, like every deserialized program."""
        if self._slot_predict is None:
            if self._aot is None:
                self._slot_predict = self._predict
            else:
                x_shape = jax.ShapeDtypeStruct(
                    (self._slot_rows, self.spec.sizes[0]), jnp.float32
                )
                self._slot_predict, _ = self._aot_resolve(
                    "predict_seq", "inference_program", self._predict,
                    (self._params, x_shape),
                    expected=self._expected_comms,
                    dedup=("inference", "seq"),
                    dispatch=True,
                )
        return self._slot_predict

    def _lower_inference_prog(self, mubatches=1):
        """The layout's inference TickProgram (interleaved-aware) — shared by
        the cached predict/serving programs (``mubatches`` = the ladder
        rung's slot count) and the fused train_run eval (one whole-split
        microbatch)."""
        if self.V > 1:
            return lower_schedule(
                S.InterleavedInferenceSchedule, mubatches, self.pp,
                training=False, virtual=self.V,
            )
        return lower_schedule(
            S.InferenceSchedule, mubatches, self.pp, training=False
        )

    def _eval_stacked(self):
        """The {W, b} stacked params the forward-only programs consume.
        Identity on every layout except ZeRO-3, where params at rest are
        per-rank block-cyclic shards: the eval view is rebuilt on host
        (one gather) and cached by the live array's identity — a weight
        update invalidates it, repeat dispatches between updates reuse it
        (same pattern as the MPMD inference view cache)."""
        if self._zero != 3:
            return self._stacked
        cached = self._eval_stacked_cache
        if cached is not None and cached[0] is self._stacked:
            return cached[1]
        host = E.zero_block_unflatten_rows(
            np.asarray(jax.device_get(self._stacked["P"])),
            self.spec, self.mesh,
        )
        ev = E.put_stacked_tree(host, self.mesh)
        self._eval_stacked_cache = (self._stacked, ev)
        return ev

    def _inference_step(self, n_slots):
        """Cached inference program for a ladder rung of ``n_slots``
        microbatch slots (mesh layouts; shared by predict(), the mesh
        accuracy() path and the serving engine). With metrics or strict
        audit enabled the compiled program is censused against the
        forward-only inference contract BEFORE it is cached — a serving
        program that lowers a gradient collective never serves a request
        (and, like every audit, a failure is never latched)."""
        step = self._predict_cache.get(n_slots)
        if step is None:
            prog = self._lower_inference_prog(n_slots)
            need_audit = (
                self._aot is not None
                or self._metrics.enabled
                or self._audit_strict
            )
            if need_audit:
                # the serving rung's tick tables get the same lowering-
                # time static passes as the epoch program — a malformed
                # inference program never compiles, let alone serves
                self._record_static_analysis(prog, f"inference_r{n_slots}")
            step = E.make_pipeline_step(
                self.mesh, self.spec, prog,
                self._slot_rows // self.dp, precision=self.precision,
                kernel_backend=self._kernel_backend,
            )
            expected = None
            if need_audit:
                expected = program_audit.expected_comms(
                    self.spec,
                    self.dp,
                    self.pp,
                    prog=prog,
                    mubatch_size=self._slot_rows // self.dp,
                    platform=self._cost_model.platform,
                    precision=self._precision_name,
                    tp=self.tp,
                )
            x_shape = jax.ShapeDtypeStruct(
                (n_slots * self._slot_rows, self.spec.sizes[0]),
                jnp.float32,
            )
            if self._aot is not None:
                # the dispatch path itself becomes the resolved executable:
                # a warm start deserializes every rung with ZERO compiles
                # (inference programs donate nothing, so dispatching a
                # deserialized one stays clear of the jax-0.4.x hazard),
                # and the census re-verifies it before this cache entry
                # can serve a request
                step, _ = self._aot_resolve(
                    f"inference_r{n_slots}", "inference_program", step,
                    (self._eval_stacked(), self._flags, x_shape),
                    expected=expected, dedup=("inference", n_slots),
                    dispatch=True,
                )
            elif self._metrics.enabled or self._audit_strict:
                with self._metrics.span("jit_compile"):
                    compiled = step.lower(
                        self._eval_stacked(), self._flags, x_shape
                    ).compile()
                self._metrics.counter("jit_compiles")
                self._record_audit(
                    compiled,
                    "inference_program",
                    dedup=("inference", n_slots),
                    expected=expected,
                )
                # serving-path dispatch safety: the rung must donate
                # nothing (its params serve the very next request) —
                # proven from the compiled HLO, unlatched like the census
                program_audit.verify_dispatch_safety(
                    compiled, context=f"inference_r{n_slots}"
                )
            self._predict_cache[n_slots] = step
        return step

    def _mpmd_infer_runner(self):
        """The streaming MPMD inference runner (mesh mpmd sessions): ONE
        slot-shaped per-stage forward chain, admission-gated at build
        (``analyze_program`` over the inference tick tables) and — when
        metrics/audit/AOT are on — censused per stage program against
        the forward-only contract before the first request."""
        if self._mpmd_infer is None:
            from shallowspeed_tpu.parallel import mpmd

            prog = self._lower_inference_prog(1)
            runner = mpmd.MpmdInferenceRunner(
                self.mesh, self.spec, prog, self._slot_rows // self.dp,
                precision=self.precision,
            )
            if self._metrics.enabled or self._audit_strict or self._aot:
                runner.warm(self._stacked, self._flags, self._mpmd_resolve)
            self._mpmd_infer = runner
        return self._mpmd_infer

    def _mpmd_infer_views(self):
        """The streaming runner's per-stage param/flag views, cached per
        LIVE weight arrays: rebuilding (and re-packing) per request would
        tax every dispatch; a hot weight reload swaps ``self._stacked``
        to a new object, which invalidates the cache by identity."""
        cached = getattr(self, "_mpmd_infer_view_cache", None)
        if (
            cached is not None
            and cached[0] is self._stacked  # kept alive by the cache
            and cached[1] is self._flags
        ):
            return cached[2], cached[3]
        runner = self._mpmd_infer_runner()
        params, fls = runner.views(self._stacked, self._flags)
        self._mpmd_infer_view_cache = (self._stacked, self._flags, params, fls)
        return params, fls

    def predict_async(self, x):
        """MPMD streaming submit (mesh mpmd sessions): issue ONE request
        of up to ``slot_rows`` rows through the per-stage chain and
        return a zero-argument resolver. Nothing blocks at submit, so
        consecutive requests pipeline across stages — request k enters
        stage 0 while request k-1 occupies a later stage. This is the
        measured tail-latency payoff next to the rung program's
        makespan-quantized dispatch (MPMD_r01.json)."""
        if self._sequential or self.runtime != "mpmd":
            raise ValueError(
                "predict_async streams through the MPMD per-stage chain — "
                "construct the session with runtime='mpmd' (mesh layout)"
            )
        x = np.asarray(x, np.float32)
        n, out_dim = x.shape[0], self.spec.out_dim
        if n < 1 or n > self._slot_rows:
            raise ValueError(
                f"predict_async takes one slot (1..{self._slot_rows} rows); "
                f"got {n} — larger requests go through predict()"
            )
        runner = self._mpmd_infer_runner()
        params, fls = self._mpmd_infer_views()
        xb = np.pad(x, ((0, self._slot_rows - n), (0, 0)))
        handle = runner.submit(params, fls, xb)

        def resolve():
            return np.asarray(handle)[:n, :out_dim]

        return resolve

    def inference_latency_bound(self):
        """Analytical latency floor for one request slot through this
        layout's inference program: the lockstep tick model's weighted
        makespan (ticks x per-tick cost from
        ``costmodel.PIPELINE_OP_COSTS``) at the platform peak — the
        model-side number the serving bench and report quote next to the
        measured percentiles (docs/serving.md)."""
        return costmodel.serving_latency_bound(
            prog=None if self._sequential else self._lower_inference_prog(1),
            spec=self.spec,
            slot_rows=self._slot_rows,
            dp=self.dp,
            platform=self._cost_model.platform,
            precision=self._precision_name,
            tp=self.tp,
        )

    def measure_dispatch_overhead(self, repeats=2, program="epoch",
                                  profile_dir=None):
        """The measured op-issue roofline (docs/performance.md): dispatch
        the compiled program under ``jax.profiler`` and split the host
        wall into op-execution time vs everything else — scheduling,
        Python/jax dispatch, the per-tick ``lax.switch`` issue cost the
        lockstep executor pays. Returns (and records as a
        ``dispatch_overhead`` event) the share of wall NOT covered by op
        execution:

            dispatch_overhead = 1 - op_busy_union / host_wall

        where ``op_busy_union`` is ``trace_stats.dispatch_busy``'s
        interval union of device ops (real accelerators) or HLO thunk
        executions on the XLA executor threads (the CPU backend, which
        emits no device timeline) — with the same comm/compute split
        ``trace_stats.summarize`` applies. This is the number that turns
        the "op-issue-bound" reading of the CPU bench rows
        (split-backward 0.77x, tp2 0.45x) from a presumption into a
        measurement.

        The probe runs TWICE: once UNINSTRUMENTED (the honest wall —
        ``host_wall_s``) and once under the profiler (the op-busy
        evidence — ``host_wall_instrumented_s``). The profiler inflates
        the host side (measured ~2-4x on the flagship epoch:
        ``profiler_inflation`` records it), so the headline
        ``dispatch_overhead`` divides the PROFILED busy union by the
        UNPROFILED wall — instrumented ops only run longer, so this is a
        conservative LOWER bound on the true host-issue share; the
        in-window ``dispatch_overhead_instrumented`` is recorded beside
        it as the upper companion.

        ``program="epoch"``: the probe dispatches REAL training epochs —
        the epoch program donates its state, so a side-effect-free
        steady-state dispatch of it does not exist; callers own the fact
        that weights advance by (up to one warm-up +) ``2 x repeats``
        epochs. ``program="rung"``: dispatches the top inference rung on
        zeros instead — weights untouched (the serving-side probe).

        A trace with no attributable op events yields
        ``dispatch_overhead: None`` with the reason — never a fabricated
        0.

        VALIDITY GUARD (the DISPATCH_r01 caveat from
        ``scripts/bench_mpmd.py``, machine-checked): a long instrumented
        window can saturate the profiler's trace buffer — op events drop
        out of the tail, the busy union undercounts, and the "overhead"
        share inflates. The record therefore carries ``events_per_batch``
        (op events per dispatched batch — epoch programs normalize by
        ``repeats x batches_per_epoch``, rung probes by ``repeats``) and
        a ``window_valid`` flag: ``False``, with
        ``window_invalid_reason``, when the instrumented window exceeds
        the profiler budget or the trace attributed no ops at all. The
        report CLI renders the flag on its dispatch row; consumers must
        not quote an invalid window's share as a measurement."""
        import tempfile

        from shallowspeed_tpu.observability import trace_stats

        if repeats < 1:
            raise ValueError("repeats must be >= 1")
        if program not in ("epoch", "rung"):
            raise ValueError(f"program must be 'epoch' or 'rung', got {program!r}")

        def dispatch_epoch():
            self.train_epoch()

        S_rows = self._slot_rows
        top = self.slot_ladder[-1]
        probe_x = np.zeros((top * S_rows, self.spec.sizes[0]), np.float32)

        def dispatch_rung():
            self.predict(probe_x)

        if program == "epoch":
            dispatch, label = dispatch_epoch, "epoch_program"
            warm = not self._epoch_dispatched
        else:
            dispatch, label = dispatch_rung, "inference_rung"
            warm = True
        if warm:
            dispatch()  # compile outside the probe windows
        # the honest denominator: the SAME dispatch loop, uninstrumented
        t0 = time.perf_counter()
        for _ in range(repeats):
            dispatch()
        host_wall_s = time.perf_counter() - t0
        tmp = None
        if profile_dir is None:
            tmp = tempfile.TemporaryDirectory(prefix="dispatch_probe_")
            profile_dir = tmp.name
        try:
            with jax.profiler.trace(str(profile_dir)):
                t1 = time.perf_counter()
                for _ in range(repeats):
                    dispatch()
                wall_instrumented_s = time.perf_counter() - t1
            traces = trace_stats.find_traces(profile_dir)
            if not traces:
                busy = {"op_events": 0, "busy_union_s": None,
                        "comm_union_s": None, "compute_union_s": None,
                        "source": "no-trace"}
            else:
                busy = trace_stats.dispatch_busy(traces[-1])
        finally:
            if tmp is not None:
                tmp.cleanup()
        share = trace_stats.dispatch_overhead_share(
            busy["busy_union_s"], host_wall_s
        )
        # the validity guard (docstring): flag windows whose evidence
        # can't be trusted — never fabricate, never silently quote
        window_budget_s = 5.0  # past this the trace buffer may saturate
        batches = repeats * (
            self.batches_per_epoch if program == "epoch" else 1
        )
        events_per_batch = (
            busy["op_events"] / batches if batches else None
        )
        window_valid = True
        window_invalid_reason = None
        if not busy["op_events"]:
            window_valid = False
            window_invalid_reason = "trace holds no attributable op events"
        elif wall_instrumented_s > window_budget_s:
            window_valid = False
            window_invalid_reason = (
                f"instrumented window {wall_instrumented_s:.2f}s exceeds "
                f"the {window_budget_s:g}s profiler budget — the trace "
                f"buffer may have saturated (undercounted ops inflate "
                f"the overhead share)"
            )
        record = {
            "program": label,
            "runtime": self.runtime,
            "repeats": int(repeats),
            "host_wall_s": host_wall_s,
            "host_wall_instrumented_s": wall_instrumented_s,
            "profiler_inflation": (
                wall_instrumented_s / host_wall_s if host_wall_s else None
            ),
            "device_busy_s": busy["busy_union_s"],
            "device_comm_s": busy["comm_union_s"],
            "device_compute_s": busy["compute_union_s"],
            "op_events": busy["op_events"],
            "op_source": busy["source"],
            "events_per_batch": events_per_batch,
            "window_valid": window_valid,
            "window_invalid_reason": window_invalid_reason,
            # the headline: profiled op busy over the UNPROFILED wall — a
            # conservative lower bound (docstring); the in-window share
            # rides beside it
            "dispatch_overhead": share,
            "dispatch_overhead_instrumented": (
                trace_stats.dispatch_overhead_share(
                    busy["busy_union_s"], wall_instrumented_s
                )
            ),
            "platform": self._cost_model.platform,
            "provenance": (
                "jax.profiler trace; op-interval union via "
                "trace_stats.dispatch_busy over an uninstrumented wall "
                "(lower bound — instrumented ops only run longer)"
            ),
        }
        if share is None:
            record["reason"] = "trace holds no attributable op events"
        if self._metrics.enabled:
            self._metrics.event("dispatch_overhead", **record)
        return record

    def accuracy(self) -> float:
        """Argmax accuracy over the full validation split."""
        if self._vx is None:
            self._load_val()
        with self._metrics.span("eval"):
            if self._sequential:
                acc = trainer.accuracy(
                    self._predict, self._params, self._vx, self._vy
                )
            else:
                # the split flows through the SAME ladder-capped slot
                # programs predict() and the serving engine dispatch — eval
                # exercises exactly the compiled path serving exercises
                n_val = self._vx.shape[0]
                preds = self.predict(np.asarray(self._vx))
                correct = int(
                    (np.argmax(preds, 1) == np.asarray(self._vy_labels)).sum()
                )
                acc = correct / max(n_val, 1)
        if self._metrics.enabled:
            self._metrics.gauge("val_accuracy", acc)
        return acc

    # -- state --------------------------------------------------------------

    def params(self):
        """Logical per-stage params (host numpy), layout-independent order."""
        return self._logical_params_from_raw(
            self._params if self._sequential else self._stacked
        )

    def poison_weights(self):
        """Fault-injection hook (faults.py): NaN one element of this
        session's live weights — the deterministic blow-up behind the
        training ``nan@step=N`` injection and the serving
        ``nan@dispatch=N`` injection (both drive this one method, so the
        poisoned state is identical either way)."""
        if self._sequential:
            self._params = F.poison_nan(self._params)
        else:
            self._stacked = F.poison_nan(self._stacked)

    def flip_weights(self):
        """Fault-injection hook (faults.py): XOR the lowest mantissa bit
        of one element of this session's live weights — the training
        ``flip@step=N`` injection. The result stays finite, so nothing in
        the loss/health stream moves; only the per-layer digest stream
        (``digests=True``) can name the (step, layer) it happened at —
        exactly what ``make diverge-smoke`` verifies."""
        if self._sequential:
            self._params = F.poison_bitflip(self._params)
        else:
            self._stacked = F.poison_bitflip(self._stacked)

    def load_weights(self, path, verified=None):
        """HOT-swap this session's weights from a checkpoint, between
        dispatches, WITHOUT touching the compiled program caches: the new
        arrays have the same shapes/shardings as the old (enforced — a
        checkpoint of different sizes is refused), so every cached
        epoch/run/inference program keeps dispatching with ZERO recompiles
        — the serving engine's hot-reload contract (every response
        dispatched after the swap is bitwise-equal to a direct
        ``predict()`` under the new weights, and the rung program cache
        survives; docs/robustness.md "Serving faults").

        Deliberately weights-ONLY: the optimizer state, epoch/step cursor
        and metrics numbering are untouched — this is a serving-side swap,
        not a training resume (use ``resume=`` at construction for that).
        Returns the checkpoint's metadata dict. Unreadable / corrupt files
        raise ``CheckpointError`` before any state changes.

        ``verified=(meta, arrays)``: the pair a ``with_arrays=True``
        discovery (``find_latest_good`` / ``find_newer_good``) already
        read and checksummed — the swap then assembles from those arrays
        instead of re-reading the file, so a reload is ONE verified read
        and the discovery->load TOCTOU window (the serving engine's
        watcher polls a directory a concurrent trainer keeps rotating)
        is closed by construction."""
        if verified is not None:
            host_params, loaded_spec, meta = assemble_checkpoint(
                path, verified[0], verified[1], self.pp * self.V, self.B
            )
        else:
            host_params, loaded_spec, meta = load_checkpoint(
                path, self.pp * self.V, self.B
            )
        if tuple(loaded_spec.sizes) != tuple(self.spec.sizes):
            raise ValueError(
                f"checkpoint sizes {loaded_spec.sizes} do not match this "
                f"session's model sizes {self.spec.sizes} — a hot reload "
                "must preserve every compiled program's shapes"
            )
        if getattr(loaded_spec, "act", "relu") != self.spec.act:
            raise ValueError(
                f"checkpoint activation family "
                f"{getattr(loaded_spec, 'act', 'relu')!r} does not match "
                f"this session's {self.spec.act!r} — a hot reload must "
                "preserve every compiled program's structure"
            )
        with self._metrics.span("device_put"):
            if self._sequential:
                self._params = jax.tree.map(jnp.asarray, host_params)
            elif self._zero == 3:
                # re-shard into the session's at-rest block-cyclic layout
                stacked_np, _ = E.stack_params(
                    host_params, self.spec, order=self._order, tp=self.tp
                )
                self._stacked = {
                    "P": jax.device_put(
                        E.zero_block_flatten_rows(
                            stacked_np, self.spec, self.mesh
                        ),
                        E.zero1_part_sharding(self.mesh),
                    )
                }
                self._eval_stacked_cache = None
            else:
                # keep the session's existing flags array (identical
                # content) — only the weight planes swap
                self._stacked, _ = E.put_stacked(
                    *E.stack_params(
                        host_params, self.spec, order=self._order, tp=self.tp
                    ),
                    self.mesh,
                )
        return meta

    def model_hash(self) -> str:
        return utils.model_hash(self.params())

    def assert_replicas_in_sync(self):
        if not self._sequential and self._zero != 3:
            # ZeRO-3 keeps no dp-replicated params to cross-check: each
            # rank owns a disjoint 1/dp shard at rest by construction
            utils.assert_dp_replicas_in_sync(self._stacked)

    def _snapshot_raw(self):
        """Stage 1 of a snapshot, the ONLY part that must stay on the
        step path for consistency: the device->host readback of the live
        params + optimizer state, in their RAW (stacked/flat) layout.
        Returns immutable host copies safe to hand to the async writer
        (the training loop keeps mutating the device arrays)."""
        raw_params = jax.device_get(
            self._params if self._sequential else self._stacked
        )
        raw_state = (
            None if is_stateless(self._opt) else jax.device_get(self._opt_state)
        )
        return raw_params, raw_state

    def _logical_params_from_raw(self, raw_params):
        """Raw (stacked/sequential) param arrays -> the logical per-stage
        list. Pure numpy on host arrays (``device_get`` is the identity
        there), so under async saves it runs on the writer thread, OFF
        the step path. The ONE implementation behind ``params()`` and
        the async snapshot build — they cannot drift."""
        if self._sequential:
            return jax.device_get(raw_params)
        if self._zero == 3:
            # params at rest are one block-cyclic row plane — rebuild the
            # stacked {W, b} layout on host before unstacking
            raw_params = E.zero_block_unflatten_rows(
                np.asarray(jax.device_get(raw_params["P"])),
                self.spec, self.mesh,
            )
        return E.unstack_params(raw_params, self.spec, order=self._order)

    def _logical_state_from_raw(self, raw_state):
        """Raw optimizer-state arrays -> the layout-independent logical
        form (``opt_state_logical()``'s output, same single-owner rule
        as ``_logical_params_from_raw``). None stays None (stateless)."""
        if raw_state is None:
            return None
        if self._zero >= 2:
            return E.zero_block_state_to_logical(
                raw_state, self._opt, self.spec, self.mesh, order=self._order
            )
        if self._zero1:
            return E.zero1_state_to_logical(
                raw_state, self._opt, self.spec, self.mesh, order=self._order
            )
        parts, scalars = split_state(self._opt, raw_state)
        if self._sequential:
            parts = {k: jax.device_get(v) for k, v in parts.items()}
        else:
            parts = {
                k: E.unstack_params(v, self.spec, order=self._order)
                for k, v in parts.items()
            }
        scalars = {k: float(jax.device_get(v)) for k, v in scalars.items()}
        return {"parts": parts, "scalars": scalars}

    def _logical_from_raw(self, raw_params, raw_state):
        """Both halves of a raw snapshot in logical form (the async
        writer-thread build)."""
        return (
            self._logical_params_from_raw(raw_params),
            self._logical_state_from_raw(raw_state),
        )

    def opt_state_logical(self):
        """Stateful-optimizer state in layout-independent logical form:
        ``{"parts": {key: ragged_list mirroring params()}, "scalars":
        {key: float}}`` per the optimizer's state_layout(); None for
        stateless optimizers."""
        if is_stateless(self._opt):
            return None
        return self._logical_state_from_raw(self._opt_state)

    def save(self, path):
        save_checkpoint(
            path,
            self.params(),
            self.spec,
            self.epoch - 1,
            extra={"optimizer": self._opt_config},
            opt_state=self.opt_state_logical(),
        )
