"""Stateless op kernels: forward and hand-derived backward, in jax.numpy.

Capability parity with the reference's NumPy kernels
(/root/reference/shallowspeed/functional.py:4-44): relu, linear, softmax and
MSE-after-softmax loss, each with an explicit hand-written VJP. The backward
functions are part of the framework surface (we do NOT rely on jax.grad in the
training path; jax.grad serves as a test oracle instead — strictly stronger
than the reference's finite-difference tests).

TPU notes:
- everything is fp32; matmuls default to ``precision=HIGHEST`` so the loss
  trajectory is comparable float-for-float with a NumPy oracle. Callers that
  want raw MXU throughput can pass ``precision='default'`` to use bf16-input
  passes on the systolic array.
- ops are shape-polymorphic and padding-safe: zero-padded rows/columns stay
  exactly zero through linear/relu, and the softmax head takes an explicit
  validity mask so padded logits contribute nothing. This is what lets the
  SPMD pipeline executor run unequal stages as fixed-shape stacked params.
"""

import os
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

# Opt-in Pallas kernel path for the fused linear+relu hot op (pallas_ops.py);
# default is plain XLA, which already fuses well for this model class.
_PALLAS = os.environ.get("SHALLOWSPEED_PALLAS", "0") == "1"


def set_pallas(enabled: bool) -> None:
    """Select the kernel backend for functions built AFTER this call.

    The flag is read at TRACE time: step/predict functions that are already
    jitted keep whichever backend they were traced with (their compiled
    executables are cached). Rebuild the function (e.g. construct a new
    TrainingSession / call make_train_epoch again) after toggling.
    """
    global _PALLAS
    _PALLAS = bool(enabled)


def pallas_enabled() -> bool:
    return _PALLAS

# Matmul precision used across the framework. HIGHEST = fp32 accumulate with
# full-precision inputs (required for NumPy-trajectory parity tests); callers
# may override per-call.
DEFAULT_PRECISION = lax.Precision.HIGHEST

# Large-negative used to mask invalid logits. Not -inf: exp(-inf - -inf) would
# produce NaN when a fully-masked row meets the global max subtraction.
_NEG_MASK = -1e30


def relu(x):
    """max(x, 0). Reference: functional.py:4-5."""
    return jnp.maximum(x, 0.0)


def relu_grad(g, bitmask):
    """VJP of relu given the cached activation bitmask (out > 0).

    Reference: functional.py:8-10 (bitmask of the *input*; identical since the
    reference computes the mask on the relu input and we compute it on the
    pre-activation — same tensor).
    """
    return g * bitmask


_INV_SQRT2 = 0.7071067811865476
_INV_SQRT_2PI = 0.3989422804014327


def gelu(x):
    """Exact (erf) GELU: x * Phi(x) — the transformer-block activation of
    the model zoo. gelu(0) == 0, so zero-padded rows and columns stay
    exactly zero — the same padding invariant linear/relu keep, which the
    stacked SPMD executor relies on."""
    return 0.5 * x * (1.0 + lax.erf(x * _INV_SQRT2))


def gelu_grad_mult(z):
    """d gelu(z)/dz = Phi(z) + z * phi(z), from the pre-activation ``z``.

    The gelu analogue of relu's cached bitmask: the backward multiplies the
    incoming grad elementwise, ``g_eff = g * gelu_grad_mult(z)``. The value
    at z == 0 is 0.5 (not 0), but padded positions carry g == 0, so nothing
    leaks into padding.
    """
    phi = _INV_SQRT_2PI * jnp.exp(-0.5 * z * z)
    return 0.5 * (1.0 + lax.erf(z * _INV_SQRT2)) + z * phi


def gelu_grad(g, z):
    """VJP of gelu given the cached pre-activation z."""
    return g * gelu_grad_mult(z)


def linear(x, w, b, precision=DEFAULT_PRECISION):
    """y = x @ w.T + b with w: (out, in), b: (1, out) or (out,).

    Reference: functional.py:13-17.
    """
    return jnp.matmul(x, w.T, precision=precision) + jnp.reshape(b, (1, -1))


def linear_grad_input(g, w, precision=DEFAULT_PRECISION):
    """The relay-critical half of linear's VJP: dx = g @ w.

    This is the ONLY product the upstream pipeline stage needs — it sits on
    the inter-stage backward relay critical path (PipeDream, arxiv
    1806.03377), which is why the split-backward schedules run it at the
    tick the combined backward would have and defer the weight half.
    """
    return jnp.matmul(g, w, precision=precision)


def linear_grad_weight(g, x, precision=DEFAULT_PRECISION):
    """The deferrable half of linear's VJP: (dw, db) = (g.T @ x, sum_rows(g)).

    Consumes only the stashed activation ``x`` and the (stashed) output-grad
    ``g`` — nothing downstream of it relays anywhere, so a split schedule
    (2BP, arxiv 2405.18047) may pack it into otherwise-idle bubble ticks.
    """
    dw = jnp.matmul(g.T, x, precision=precision)
    db = g.sum(axis=0)
    return dw, db


def linear_grad(g, x, w, precision=DEFAULT_PRECISION):
    """VJP of linear: returns (dx, dw, db) = (g @ w, g.T @ x, sum_rows(g)).

    Reference: functional.py:20-21. Expressed as the composition of the
    split halves (``linear_grad_input`` + ``linear_grad_weight``) so the
    combined and two-stage backward paths can never disagree: they are the
    same expressions, executed at different ticks.
    """
    dx = linear_grad_input(g, w, precision=precision)
    dw, db = linear_grad_weight(g, x, precision=precision)
    return dx, dw, db


def linear_relu_grad_input(g, bitmask, w, precision=DEFAULT_PRECISION):
    """Split B-input of the linear+relu unit: dx from W and the relu mask
    (the stashed activation is NOT needed — only B-weight reads it)."""
    return linear_grad_input(relu_grad(g, bitmask), w, precision=precision)


def linear_relu_grad_weight(g, bitmask, x, precision=DEFAULT_PRECISION):
    """Split B-weight of the linear+relu unit: (dw, db) from the stashed
    activation and the stashed output-grad."""
    return linear_grad_weight(relu_grad(g, bitmask), x, precision=precision)


def linear_relu_fused(x, w, b, precision=DEFAULT_PRECISION):
    """Fused y = relu(x @ w.T + b); returns (y, pre-activation bitmask).

    XLA path by default; the Pallas kernel (pallas_ops.py) when enabled —
    same contract either way, so the model layer is backend-agnostic.
    """
    if _PALLAS:
        from shallowspeed_tpu import pallas_ops

        y, mask = pallas_ops.linear_relu_fwd(x, w, b, precision=precision)
        return y, mask > 0
    y = linear(x, w, b, precision=precision)
    return relu(y), y > 0


def linear_relu_grad_fused(g, bitmask, x, w, precision=DEFAULT_PRECISION):
    """Backward of linear_relu_fused: (dx, dw, db) in one fused unit."""
    if _PALLAS:
        from shallowspeed_tpu import pallas_ops

        dx, dw, db = pallas_ops.linear_relu_bwd(
            g, bitmask.astype(jnp.float32), x, w, precision=precision
        )
        return dx, dw, jnp.reshape(db, (-1,))
    return linear_grad(relu_grad(g, bitmask), x, w, precision=precision)


def _stability_max(z, group_rows):
    """The max subtracted for stability: over the WHOLE array (the
    reference's quirk), or — with ``group_rows`` — over each consecutive
    group of that many rows, reproducing exactly what a per-microbatch loop
    would have computed. Grouping matters because the ``+1e-7`` denominator
    breaks exact shift-invariance."""
    if group_rows is None:
        return jnp.max(z)
    g = z.reshape(-1, group_rows, z.shape[-1])
    m = jnp.max(g, axis=(1, 2), keepdims=True)
    return jnp.broadcast_to(m, g.shape).reshape(z.shape)


def softmax(z, valid_mask=None, group_rows=None):
    """Row softmax with the reference's exact quirks (functional.py:24-27):

    - the max subtracted for stability is the *global* max over the whole
      array (not per-row) — or per consecutive ``group_rows``-row group, for
      callers that fuse several microbatches into one call and need the
      per-microbatch semantics float-for-float,
    - the denominator gets ``+ 1e-7``.

    ``valid_mask`` (broadcastable to z, True = real logit) supports the padded
    SPMD layout: masked positions get probability exactly 0 and do not affect
    the max or the row sums.
    """
    if valid_mask is not None:
        z = jnp.where(valid_mask, z, _NEG_MASK)
    z_exp = jnp.exp(z - _stability_max(z, group_rows))
    return z_exp / (z_exp.sum(axis=1, keepdims=True) + 1e-7)


def softmax_grad(g, z, valid_mask=None, group_rows=None):
    """VJP of softmax, recomputing the forward from the cached *input* z.

    Recomputation instead of stashing the output is deliberate: on TPU the
    extra exp/sum fuses into the backward and saves HBM traffic — and it is
    also exactly what the reference does (functional.py:30-35).
    """
    out = softmax(z, valid_mask, group_rows)
    gz = out * g
    return gz - out * gz.sum(axis=-1, keepdims=True)


def mse_loss(p, t, batch_size):
    """sum((t - p)^2) / batch_size. Reference: functional.py:38-40.

    ``batch_size`` is the GLOBAL batch size: this single scaling is what makes
    microbatch gradient accumulation + DP SUM-reduction reproduce the serial
    full-batch gradient with no averaging anywhere (reference layers.py:160).
    """
    return ((t - p) ** 2).sum() / batch_size


def mse_loss_grad(p, t, batch_size):
    """dL/dp = -2 (t - p) / batch_size. Reference: functional.py:43-44."""
    return -2.0 * (t - p) / batch_size


@partial(jax.jit, static_argnames=("batch_size", "group_rows"))
def softmax_mse_head_grad(z, t, batch_size, valid_mask=None, group_rows=None):
    """Fused loss-head backward: d(MSE(softmax(z), t))/dz.

    The reference implements this as two chained Module backwards
    (MSELoss layers.py:157-163 then Softmax layers.py:89-93); fused here so
    XLA emits a single elementwise pipeline over the logits.
    """
    p = softmax(z, valid_mask, group_rows)
    g = mse_loss_grad(p, t, batch_size)
    return softmax_grad(g, z, valid_mask, group_rows)
