"""SLO alerting: multi-window multi-burn-rate rules, an alert
firing→resolved lifecycle emitted as schema-v11 ``alert`` records, and
the ``AlertSink`` hook the autoscaler (``serving/autoscaler.py``)
consumes.

BURN-RATE MATH (docs/observability.md § Live telemetry & alerting). An
SLO target of ``slo_target`` (say 99% of requests good) leaves an error
BUDGET of ``1 - slo_target``. The burn rate over a window is the
observed bad fraction divided by the budget: burn 1.0 spends the budget
exactly at the sustainable pace; burn 14.4 exhausts a 30-day budget in
~2 days. A single window is either too twitchy (short) or too slow to
resolve (long), so :class:`BurnRateRule` is the standard multi-window
form: it FIRES only when both a LONG window (sustained damage) and a
SHORT window (still happening right now) exceed the burn threshold,
and RESOLVES as soon as the short window drops back under — fast
resolution without flapping.

RULE LIFECYCLE. Every rule is a tiny state machine (``ok`` ⇄
``firing``). A transition — and only a transition — emits one ``alert``
record (kind ``alert``, named by the rule, ``state`` ``firing`` or
``resolved``) through the attached metrics recorder and calls every
attached :class:`AlertSink`. Steady state emits nothing: the alert
stream is an event log of edges, not a sampled signal.

THE ``AlertSink`` CONTRACT (the autoscaler hook): one method,
``alert(record: dict)``, called synchronously on every transition with
the same JSON-able dict the ``alert`` record carries (``name``/
``rule``/``state``/``severity``/``t``/``value``/``threshold``/
``burn_fast``/``burn_slow``/``reason``/``replica_id``). Sinks must not
raise (a broken consumer must not take down serving) and must not
block — hand off to a queue if reaction is slow. A sink sees edges
only; consumers needing current state call
:meth:`SloEvaluator.active`.

Rule families over the evidence stream:

- :class:`EventRule`        edge-triggered on named health events —
  ``breaker_open`` fires, ``breaker_closed`` resolves. Deterministic
  (no clock windows), which is why ``make alerts-smoke`` gates on it.
- :class:`BurnRateRule`     multi-window burn over a good/bad request
  stream (error/unhealthy verdict fraction vs the SLO budget).
- :class:`ThresholdRule`    fires when a value extracted from each
  CLOSED rollup window breaches a threshold for ``for_windows``
  consecutive windows, resolves after ``clear_windows`` clean ones —
  p99-vs-SLO, admitted-rate-vs-knee, checkpoint overhead fraction.

:func:`default_serving_rules` / :func:`default_training_rules` build
the standard set; :class:`LiveTelemetry` is the one-object glue a
telemetry source (engine / fleet / training session) owns: a
:class:`~shallowspeed_tpu.observability.rollup.RollupBuilder` whose
closed windows feed a :class:`SloEvaluator`, with ``note_*`` feed
methods and a ``snapshot()`` the ``status()`` surfaces return.
"""

from collections import deque

from shallowspeed_tpu.observability.rollup import (
    DEFAULT_WINDOW_S,
    RollupBuilder,
)

# verdicts that spend error budget (terminal but not the service's fault
# — "dropped"/"expired" under overload are capacity, not correctness;
# the knee/queue rules cover those)
BAD_VERDICTS = ("error", "unhealthy")

# the achieved-rate slack the ONE breach definition below tolerates
# before calling a window saturated (the historic find_knee default)
SLO_ACHIEVED_FRACTION = 0.9


def slo_breach(
    p99_latency_s,
    offered_rps,
    achieved_rps,
    slo_ms,
    achieved_fraction=SLO_ACHIEVED_FRACTION,
):
    """THE SLO-breach predicate — the single definition shared by
    ``bench_serving.find_knee`` (so ``knee_rps`` is "the first offered
    rate that breaches") and the capacity scoreboard's violation-minute
    scorer (``serving/bench_replay.py``), so the knee and the scoreboard
    can never disagree about what a violation is.

    A window/row breaches when its p99 latency exceeds the SLO, or its
    achieved rate falls below ``achieved_fraction`` x the offered rate
    (saturation: the service is silently shedding the difference into
    the backlog). Returns the breach reason (``"p99_above_slo"`` /
    ``"achieved_below_offered"``) or ``None`` — callers needing a bool
    truth-test the return. ``None`` inputs abstain rather than guess:
    a missing p99 (no completions) only breaches through the achieved
    test, and with no evidence at all the verdict is "no breach"."""
    if slo_ms is not None and p99_latency_s is not None:
        if p99_latency_s > slo_ms / 1000.0:
            return "p99_above_slo"
    if achieved_rps is not None and offered_rps:
        if achieved_rps < achieved_fraction * offered_rps:
            return "achieved_below_offered"
    return None


class AlertSink:
    """The alert-consumer contract (module docstring): override
    ``alert``. The base class is a no-op, so a consumer can subclass
    and override only what it needs."""

    def alert(self, record):
        """Called synchronously on every firing→resolved edge with the
        JSON-able alert dict. Must not raise, must not block."""


class AlertRule:
    """Base rule: a named ``ok`` ⇄ ``firing`` state machine. Subclasses
    implement the ``on_*`` hooks they consume and return either ``None``
    (no opinion) or a decision dict ``{"state": "ok"|"firing", "value":
    ..., "threshold": ..., "reason": ...}``; the evaluator turns state
    CHANGES into alert records."""

    def __init__(self, name, severity="ticket"):
        self.name = name
        self.severity = severity
        self.state = "ok"

    def on_request(self, t, verdict):
        return None

    def on_event(self, t, name, fields):
        return None

    def on_window(self, summary):
        return None


class EventRule(AlertRule):
    """Edge-triggered rule over named health events: any event in
    ``fire_on`` fires, any in ``resolve_on`` resolves."""

    def __init__(self, name, fire_on, resolve_on, severity="page"):
        super().__init__(name, severity=severity)
        self.fire_on = tuple(fire_on)
        self.resolve_on = tuple(resolve_on)

    def on_event(self, t, name, fields):
        if name in self.fire_on:
            return {
                "state": "firing",
                "value": name,
                "threshold": None,
                "reason": f"health event {name!r}",
            }
        if name in self.resolve_on:
            return {
                "state": "ok",
                "value": name,
                "threshold": None,
                "reason": f"health event {name!r}",
            }
        return None


class BurnRateRule(AlertRule):
    """Multi-window multi-burn-rate rule (module docstring): fires when
    the bad-request fraction burns the error budget faster than
    ``burn`` over BOTH the long and the short window; resolves when the
    short window recovers."""

    def __init__(
        self,
        name,
        budget=0.01,
        long_s=300.0,
        short_s=60.0,
        burn=6.0,
        bad_verdicts=BAD_VERDICTS,
        min_samples=10,
        severity="page",
    ):
        super().__init__(name, severity=severity)
        if budget <= 0:
            raise ValueError(f"error budget must be positive, got {budget!r}")
        if short_s >= long_s:
            raise ValueError(
                f"short window ({short_s}s) must be shorter than long "
                f"({long_s}s)"
            )
        self.budget = float(budget)
        self.long_s = float(long_s)
        self.short_s = float(short_s)
        self.burn = float(burn)
        self.bad_verdicts = tuple(bad_verdicts)
        self.min_samples = int(min_samples)
        self._samples = deque()  # (t, is_bad) — pruned past long_s

    def _burn_over(self, t, horizon):
        bad = total = 0
        for st, is_bad in self._samples:
            if st > t - horizon:
                total += 1
                bad += is_bad
        if total < self.min_samples:
            return None, total
        return (bad / total) / self.budget, total

    def on_request(self, t, verdict):
        self._samples.append((t, 1 if verdict in self.bad_verdicts else 0))
        while self._samples and self._samples[0][0] <= t - self.long_s:
            self._samples.popleft()
        burn_long, n_long = self._burn_over(t, self.long_s)
        burn_short, _ = self._burn_over(t, self.short_s)
        if burn_long is None or burn_short is None:
            return None  # not enough evidence to change state either way
        fired = burn_long >= self.burn and burn_short >= self.burn
        if self.state == "firing":
            fired = burn_short >= self.burn  # short-window recovery resolves
        return {
            "state": "firing" if fired else "ok",
            "value": burn_long,
            "threshold": self.burn,
            "burn_fast": burn_short,
            "burn_slow": burn_long,
            "reason": (
                f"bad-verdict burn rate {burn_long:.2f}x budget over "
                f"{self.long_s:g}s ({burn_short:.2f}x over {self.short_s:g}s, "
                f"{n_long} samples, budget {self.budget:g})"
            ),
        }


class ThresholdRule(AlertRule):
    """Consecutive-window threshold rule over CLOSED rollup windows:
    ``value_fn(summary)`` breaching ``threshold`` for ``for_windows``
    windows in a row fires; ``clear_windows`` clean ones resolve.
    ``value_fn`` returning ``None`` (metric absent from the window)
    leaves the streak — and the state — untouched."""

    def __init__(
        self,
        name,
        value_fn,
        threshold,
        for_windows=2,
        clear_windows=2,
        comparison="gt",
        reason=None,
        severity="ticket",
    ):
        super().__init__(name, severity=severity)
        self.value_fn = value_fn
        self.threshold = float(threshold)
        self.for_windows = int(for_windows)
        self.clear_windows = int(clear_windows)
        self.comparison = comparison
        self.reason = reason or name
        self._bad_streak = 0
        self._good_streak = 0

    def _breached(self, value):
        if self.comparison == "gt":
            return value > self.threshold
        if self.comparison == "lt":
            return value < self.threshold
        raise ValueError(f"unknown comparison {self.comparison!r}")

    def on_window(self, summary):
        value = self.value_fn(summary)
        if value is None:
            return None
        if self._breached(value):
            self._bad_streak += 1
            self._good_streak = 0
        else:
            self._good_streak += 1
            self._bad_streak = 0
        state = self.state
        if self.state == "ok" and self._bad_streak >= self.for_windows:
            state = "firing"
        elif self.state == "firing" and self._good_streak >= self.clear_windows:
            state = "ok"
        return {
            "state": state,
            "value": value,
            "threshold": self.threshold,
            "reason": (
                f"{self.reason}: {value:.6g} "
                f"{'>' if self.comparison == 'gt' else '<'} "
                f"{self.threshold:.6g} "
                f"({self._bad_streak} breaching window(s))"
            ),
        }


class SloEvaluator:
    """Drives a rule set over the evidence stream and owns the alert
    lifecycle: state transitions become ``alert`` records + sink calls;
    everything else is silence."""

    def __init__(self, rules, metrics=None, sinks=(), replica_id=None):
        self.rules = list(rules)
        self.metrics = metrics
        self.sinks = list(sinks)
        self.replica_id = replica_id
        self.history = []  # every transition record, in order
        self.fired = 0
        self.resolved = 0

    # -- feeds --------------------------------------------------------------

    def note_request(self, t, verdict):
        for rule in self.rules:
            self._apply(rule, t, rule.on_request(t, verdict))

    def note_event(self, t, name, **fields):
        for rule in self.rules:
            self._apply(rule, t, rule.on_event(t, name, fields))

    def note_window(self, summary):
        t = summary.get("window_end")
        for rule in self.rules:
            self._apply(rule, t, rule.on_window(summary))

    # -- lifecycle ----------------------------------------------------------

    def _apply(self, rule, t, decision):
        if decision is None:
            return
        new_state = decision.pop("state")
        if new_state == rule.state:
            return
        rule.state = new_state
        edge = "firing" if new_state == "firing" else "resolved"
        record = {
            "rule": rule.name,
            "state": edge,
            "severity": rule.severity,
            "t": t,
            "burn_fast": decision.get("burn_fast"),
            "burn_slow": decision.get("burn_slow"),
            "replica_id": self.replica_id,
            **decision,
        }
        if edge == "firing":
            self.fired += 1
        else:
            self.resolved += 1
        self.history.append({"name": rule.name, **record})
        if self.metrics is not None:
            self.metrics.alert(rule.name, **record)
        for sink in self.sinks:
            try:
                sink.alert({"name": rule.name, **record})
            except Exception:  # noqa: BLE001 — a broken alert consumer must never take down serving (the sink contract)
                pass

    # -- inspection ---------------------------------------------------------

    def active(self):
        """Currently-firing rules: ``{rule_name: severity}``."""
        return {r.name: r.severity for r in self.rules if r.state == "firing"}

    def snapshot(self):
        return {
            "rules": [
                {"name": r.name, "state": r.state, "severity": r.severity}
                for r in self.rules
            ],
            "active": self.active(),
            "fired": self.fired,
            "resolved": self.resolved,
        }


# -- default rule sets -------------------------------------------------------


def _quantile(summary, metric, q):
    qs = (summary.get("quantiles") or {}).get(metric) or {}
    return qs.get(q)


def default_serving_rules(
    slo_ms=None,
    knee_rps=None,
    slo_target=0.99,
    long_s=30.0,
    short_s=5.0,
    burn=6.0,
):
    """The standard serving rule set. ``slo_ms``-dependent and
    ``knee_rps``-dependent rules are only built when the evidence
    exists — an alert against a hand-guessed constant is worse than no
    alert (the knee threshold comes from ``bench_serving``'s measured
    sweep record, satellite of the same PR)."""
    rules = [
        EventRule(
            "breaker_open",
            fire_on=("breaker_open",),
            resolve_on=("breaker_closed",),
            severity="page",
        ),
        EventRule(
            "fleet_degraded",
            fire_on=("fleet_degraded",),
            resolve_on=("fleet_recovered",),
            severity="page",
        ),
        BurnRateRule(
            "error_burn",
            budget=1.0 - slo_target,
            long_s=long_s,
            short_s=short_s,
            burn=burn,
        ),
    ]
    if slo_ms is not None:
        rules.append(
            ThresholdRule(
                "p99_slo",
                value_fn=lambda s: _quantile(s, "latency_s", "p99"),
                threshold=slo_ms / 1000.0,
                reason="window p99 latency above SLO",
            )
        )
    if knee_rps is not None:
        rules.append(
            ThresholdRule(
                "knee_proximity",
                value_fn=lambda s: (s.get("rates") or {})
                .get("admitted", {})
                .get("rate"),
                threshold=0.9 * knee_rps,
                reason=(
                    f"admitted rate within 10% of the measured saturation "
                    f"knee ({knee_rps:g} rps)"
                ),
            )
        )
    return rules


def default_training_rules(ckpt_overhead_max=0.25):
    """The trainer rule set: health events (non-finite loss halts the
    run anyway — the alert is for the fleet surface watching many runs)
    and the checkpoint overhead fraction vs the reliability budget."""

    def ckpt_fraction(summary):
        counters = summary.get("counters") or {}
        ckpt = counters.get("checkpoint_wall_s")
        train = counters.get("train_wall_s")
        if not ckpt or not train:
            return None
        return ckpt / (ckpt + train)

    return [
        EventRule(
            "training_health",
            fire_on=("non_finite", "loss_divergence", "grad_spike"),
            resolve_on=(),
            severity="page",
        ),
        ThresholdRule(
            "checkpoint_overhead",
            value_fn=ckpt_fraction,
            threshold=ckpt_overhead_max,
            for_windows=1,
            clear_windows=1,
            reason="checkpoint wall fraction of train wall above budget",
        ),
    ]


class LiveTelemetry:
    """The one-object sensor a telemetry source owns (module docstring):
    rollup builder + SLO evaluator, wired so every closed window feeds
    the threshold rules, with ``note_*`` feeds shaped for the engine,
    fleet and training session call sites."""

    def __init__(
        self,
        source,
        metrics=None,
        window_s=DEFAULT_WINDOW_S,
        rules=None,
        sinks=(),
        replica_id=None,
        slo_ms=None,
        knee_rps=None,
    ):
        if rules is None:
            rules = default_serving_rules(slo_ms=slo_ms, knee_rps=knee_rps)
        self.evaluator = SloEvaluator(
            rules, metrics=metrics, sinks=sinks, replica_id=replica_id
        )
        self.rollup = RollupBuilder(
            source,
            window_s=window_s,
            metrics=metrics,
            replica_id=replica_id,
            on_close=self.evaluator.note_window,
        )

    # -- serving feeds ------------------------------------------------------

    def note_admit(self, t):
        self.rollup.count(t, "admitted")

    def note_request(self, t, verdict, latency_s=None, queue_s=None):
        self.rollup.count(t, verdict)
        self.rollup.count(t, "terminal")
        if latency_s is not None:
            self.rollup.observe(t, "latency_s", latency_s)
        if queue_s is not None:
            self.rollup.observe(t, "queue_s", queue_s)
        self.evaluator.note_request(t, verdict)

    def note_queue_depth(self, t, depth):
        self.rollup.gauge(t, "queue_depth", depth)

    def note_health(self, t, name, **fields):
        self.rollup.count(t, "health_events")
        self.evaluator.note_event(t, name, **fields)

    # -- trainer feeds ------------------------------------------------------

    def note_step(
        self, t, loss=None, step_s=None, throughput=None, mfu=None
    ):
        self.rollup.count(t, "steps")
        if step_s is not None:
            self.rollup.observe(t, "step_s", step_s)
            self.rollup.count(t, "train_wall_s", step_s)
        if loss is not None:
            self.rollup.gauge(t, "loss", loss)
        if throughput is not None:
            self.rollup.gauge(t, "throughput", throughput)
        if mfu is not None:
            self.rollup.gauge(t, "mfu", mfu)

    def note_checkpoint(self, t, wall_s):
        self.rollup.count(t, "checkpoints")
        if wall_s is not None:
            self.rollup.count(t, "checkpoint_wall_s", wall_s)

    # -- lifecycle ----------------------------------------------------------

    def flush(self):
        self.rollup.flush()

    def snapshot(self):
        return {
            "rollup": self.rollup.snapshot(),
            "alerts": self.evaluator.snapshot(),
        }
