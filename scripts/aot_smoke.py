"""aot-smoke driver: prove the AOT executable cache across REAL process
restarts (`make aot-smoke`).

Three phases, each a fresh interpreter so the jit call cache genuinely
dies between them (the in-suite tests cover the same machinery
in-process; this is the cross-process leg):

  cold     build a dp2 session over an empty cache dir, warm the whole
           rung ladder (every ladder rung compiled + stored), record the
           predictions and the jit-compile count;
  warm     RESTARTED process, same cache dir: every rung must come back
           as a cache hit with ZERO jit compiles (pinned by the counter),
           every deserialized program re-verified by the audit census
           before first dispatch (pinned by the xla_audit records), and
           the predictions bitwise-equal to the cold phase's;
  corrupt  one cache entry is corrupted on disk by the Makefile between
           phases; the run must fall back to exactly one clean recompile
           with a recorded `aot_cache` corrupt event, rewrite the entry,
           and still serve bitwise-equal predictions — exit 0.

Usage:
  python scripts/aot_smoke.py --phase cold|warm|corrupt
      --cache-dir D --data-dir DD --ref R.npz --metrics-out M.jsonl
"""

import argparse
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

LADDER = (1, 2, 4)
DP = 2


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--phase", choices=["cold", "warm", "corrupt"],
                    required=True)
    ap.add_argument("--cache-dir", required=True)
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--ref", required=True,
                    help="npz of reference predictions (written by cold, "
                    "compared bitwise by warm/corrupt)")
    ap.add_argument("--metrics-out", default=None)
    args = ap.parse_args(argv)

    from shallowspeed_tpu.api import TrainingSession
    from shallowspeed_tpu.observability import JsonlMetrics, read_jsonl

    metrics = JsonlMetrics(args.metrics_out) if args.metrics_out else None
    session = TrainingSession(
        dp=DP,
        global_batch_size=32,
        mubatches=2,
        data_dir=args.data_dir,
        metrics=metrics,
        aot_cache_dir=args.cache_dir,
        predict_slot_ladder=LADDER,
    )
    if not session._aot.supported:
        # the documented degrade-to-no-op: a backend that cannot
        # serialize must not fail the smoke — it must RECORD why.
        # (reading .supported runs the import-level probe, so a jax
        # build without serialize_executable lands here up front)
        print(
            f"aot-smoke: backend cannot serialize "
            f"({session._aot.disabled_reason}) — cache is a recorded no-op"
        )
        return 0
    rng = np.random.RandomState(7)
    rows = LADDER[-1] * session.slot_rows
    X = rng.rand(rows, session.spec.sizes[0]).astype(np.float32)
    # warm the whole ladder: one dispatch per rung (smallest to largest
    # row counts walk every rung program)
    preds = {}
    for rung in LADDER:
        n = rung * session.slot_rows
        preds[f"r{rung}"] = session.predict(X[:n])
    stats = session._aot.stats()
    compiles = int(
        getattr(session._metrics, "counters", {}).get("jit_compiles", 0)
    )
    print(f"phase {args.phase}: jit_compiles={compiles}, aot={stats}")
    if metrics is not None:
        metrics.close()
    if stats["disabled"]:
        # serialize-time disable (import probe passed, the executable
        # kind itself cannot serialize): still the documented no-op exit
        print(
            f"aot-smoke: backend disabled the cache mid-run "
            f"({stats['disabled_reason']}) — recorded no-op"
        )
        return 0

    n_programs = len(LADDER)
    fail = []
    if args.phase == "cold":
        np.savez(args.ref, **preds)
        if stats["store"] < n_programs:
            fail.append(
                f"expected >= {n_programs} stores, got {stats['store']}"
            )
        if compiles < n_programs:
            fail.append(f"cold phase compiled only {compiles}")
    else:
        ref = np.load(args.ref)
        for k, v in preds.items():
            if not np.array_equal(ref[k], v):
                fail.append(f"prediction {k} differs from the cold phase")
        if args.phase == "warm":
            if compiles != 0:
                fail.append(
                    f"warm start recompiled ({compiles} jit compiles)"
                )
            if stats["hit"] < n_programs or stats["miss"]:
                fail.append(f"expected {n_programs} pure hits, got {stats}")
        else:  # corrupt
            if stats["corrupt"] != 1:
                fail.append(f"expected 1 corrupt event, got {stats}")
            if compiles != 1:
                fail.append(
                    f"expected exactly 1 fallback recompile, got {compiles}"
                )
            if stats["store"] != 1:
                fail.append("the corrupted entry was not rewritten")
        # never serve an unaudited program: every deserialized rung must
        # carry a clean census in the metrics stream
        if args.metrics_out:
            recs = read_jsonl(args.metrics_out)
            audits = [
                r for r in recs
                if r.get("kind") == "xla_audit"
                and r.get("name") == "inference_program"
            ]
            hits = sum(
                1 for r in recs
                if r.get("kind") == "aot_cache" and r.get("name") == "hit"
            )
            if len(audits) < n_programs:
                fail.append(
                    f"{len(audits)} audit records for {n_programs} programs"
                )
            if not all(r.get("census_ok") for r in audits):
                fail.append("a deserialized program failed its census")
            if args.phase == "warm" and hits < n_programs:
                fail.append(f"only {hits} recorded hits in the JSONL")
    if fail:
        print("aot-smoke FAILED: " + "; ".join(fail), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
