"""Multi-host tests: single-process no-op semantics AND real multi-process
``jax.distributed`` runs (localhost coordinator, CPU backend) — a 2-process
fleet exercising cross-process collectives + the pipeline executor, and a
4-process 2x2 mesh where every axis crosses process boundaries with
cross-process replica-sync verification. The environment's stand-in for the
reference's ``mpirun -n N`` multi-process mode (reference train.py:87-94)."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from shallowspeed_tpu.parallel import make_mesh, multihost


def test_initialize_is_noop_single_process():
    multihost.initialize()  # must not raise without a coordinator
    assert jax.process_count() == 1


def test_shard_batch_for_process_places_on_mesh():
    mesh = make_mesh(2, 4)
    x = np.arange(16 * 3, dtype=np.float32).reshape(16, 3)
    arr = multihost.shard_batch_for_process(x, mesh, P("dp"))
    assert arr.shape == (16, 3)
    np.testing.assert_array_equal(np.asarray(arr), x)
    # sharded over dp, replicated over pp: 8 devices, 2 distinct row-shards
    # (keyed by str: shard.index is a tuple of slices, unhashable < py3.12)
    assert len({str(s.index) for s in arr.addressable_shards}) == 2


def _run_worker_fleet(worker, n_procs, timeout=240):
    """Spawn ``n_procs`` cooperating jax.distributed workers on a fresh
    localhost coordinator port and collect one JSON line from each; retries
    on the (racy) port pick. Returns (outs, errs); outs is None on failure."""
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}

    def attempt():
        # bind-close-reuse port picking is racy on a busy host; the caller
        # retries with a fresh port if the coordinator loses the race
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(pid), str(port)],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                env=env,
                text=True,
            )
            for pid in range(n_procs)
        ]
        outs, errs = [], []
        try:
            for p in procs:
                try:
                    out, err = p.communicate(timeout=timeout)
                except subprocess.TimeoutExpired:
                    # e.g. workers connected to a port-race winner and hung —
                    # kill and let the caller retry on a fresh port
                    errs.append("worker timed out (port race?)")
                    return None, errs
                errs.append(err)
                if p.returncode != 0:
                    return None, errs
                outs.append(json.loads(out.strip().splitlines()[-1]))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.terminate()
                    p.wait(timeout=30)
        return outs, errs

    outs = None
    for _ in range(3):
        outs, errs = attempt()
        if outs is not None:
            break
        # old jaxlib (< 0.5): the CPU backend has no cross-process
        # collectives at all — the capability under test does not exist in
        # this environment, so skipping (with the backend's own words) is
        # the honest outcome; on a capable jaxlib the fleet still runs
        if any(
            "Multiprocess computations aren't implemented on the CPU backend"
            in (e or "")
            for e in errs
        ):
            pytest.skip(
                "this jaxlib's CPU backend does not implement multiprocess "
                "collectives (XlaRuntimeError: 'Multiprocess computations "
                "aren't implemented on the CPU backend')"
            )
    assert outs is not None, f"workers failed 3x:\n{errs[-1][-3000:]}"
    return outs


def test_two_process_distributed_training_step():
    """Spawn 2 cooperating processes that form a 4-device global runtime and
    run a cross-process psum + pipeline training steps (flat GPipe and
    interleaved virtual stages — see _multihost_worker.py). Verifies
    multihost.initialize, process-local batch feeding, and that both
    processes agree on the (replicated) losses."""
    outs = _run_worker_fleet(Path(__file__).parent / "_multihost_worker.py", 2)
    assert all(o["psum_ok"] for o in outs)
    for key in ("loss", "loss_z", "loss_i", "loss_run", "loss_pallas"):
        losses = sorted((o["pid"], o[key]) for o in outs)
        assert losses[0][1] == pytest.approx(losses[1][1], rel=1e-6)
        assert np.isfinite(losses[0][1]) and losses[0][1] > 0


def test_four_process_2x2_mesh_cross_process_sync():
    """4 processes x 1 device: a 2x2 mesh where BOTH axes cross process
    boundaries (dp psum across {0,2}/{1,3}, tick ppermutes across
    {0,1}/{2,3}) — the layout a real pod runs. Two stateful training steps
    with utils.assert_dp_replicas_in_sync_global after each (each process
    sees one device, so only the cross-process check compares anything),
    plus the negative control: an injected process-divergent array must be
    DETECTED by the checker on every process (see _multihost_worker4.py)."""
    outs = _run_worker_fleet(
        Path(__file__).parent / "_multihost_worker4.py", 4, timeout=300
    )
    assert len(outs) == 4
    assert all(o["sync_ok"] for o in outs)
    assert all(o["desync_detected"] for o in outs)
    for key in ("loss", "loss2"):
        vals = [o[key] for o in outs]
        assert all(v == pytest.approx(vals[0], rel=1e-6) for v in vals)
        assert np.isfinite(vals[0]) and vals[0] > 0
    assert outs[0]["loss2"] < outs[0]["loss"]  # training actually progressed
