"""AOT executable cache tests (shallowspeed_tpu/aot_cache.py): the store's
own format + write discipline, the key scheme, every degraded outcome
(miss / stale / corrupt / disabled) falling back to a clean recompile, and
the session-level contract — a warm start serves every rung from the cache
with ZERO jit compiles (pinned by the counter), every deserialized program
re-audited before first dispatch, bitwise-equal predictions across the
cache boundary. The cross-PROCESS restart leg lives in `make aot-smoke`;
these tests pin the same machinery in-process.
"""

import numpy as np
import pytest

from shallowspeed_tpu import aot_cache as AC
from shallowspeed_tpu import faults
from shallowspeed_tpu.api import TrainingSession
from shallowspeed_tpu.observability import (
    SCHEMA_VERSION,
    MetricsRecorder,
    read_jsonl,
)

SIZES = (24, 20, 18, 16)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("aot_data")
    rng = np.random.RandomState(0)
    for suffix, n in (("train", 256), ("val", 96)):
        np.save(d / f"x_{suffix}.npy", rng.randn(n, SIZES[0]).astype(np.float32))
        np.save(
            d / f"y_{suffix}.npy",
            np.eye(SIZES[-1], dtype=np.float32)[rng.randint(0, SIZES[-1], n)],
        )
    return d


def _session(data_dir, cache_dir, **kw):
    kw.setdefault("sizes", SIZES)
    kw.setdefault("global_batch_size", 64)
    return TrainingSession(
        data_dir=data_dir, aot_cache_dir=cache_dir, **kw
    )


# ---------------------------------------------------------------------------
# the store itself
# ---------------------------------------------------------------------------


def test_cache_key_is_stable_and_input_sensitive():
    fp = {"jax": "0.0.0", "jaxlib": "0.0.0", "platform": "cpu"}
    k1 = AC.cache_key("p", (1, 2), fp, AC.content_hash("module {}"))
    k2 = AC.cache_key("p", (1, 2), fp, AC.content_hash("module {}"))
    assert k1 == k2 and len(k1) == 64
    # every key ingredient matters
    assert k1 != AC.cache_key("q", (1, 2), fp, AC.content_hash("module {}"))
    assert k1 != AC.cache_key("p", (1, 4), fp, AC.content_hash("module {}"))
    assert k1 != AC.cache_key("p", (1, 2), fp, AC.content_hash("module {x}"))
    assert k1 != AC.cache_key(
        "p", (1, 2), {**fp, "jaxlib": "9.9.9"}, AC.content_hash("module {}")
    )


def test_store_load_roundtrip_and_failure_modes(tmp_path):
    """Entry round trip on a real compiled program, then every defence:
    miss, torn/corrupt payload, stale fingerprint — each recorded, each
    returning None (the caller recompiles), never raising."""
    import jax
    import jax.numpy as jnp

    compiled = (
        jax.jit(lambda x: x * 2.0).lower(jnp.ones((4,), jnp.float32)).compile()
    )
    cache = AC.AotCache(tmp_path / "aot")
    key = cache.key_for("p", (1,), "module {}")
    assert cache.load(key, program="p") is None  # miss
    assert cache.counts["miss"] == 1
    path = cache.store(key, compiled, program="p")
    if not cache.supported:  # backend cannot serialize: recorded no-op
        assert cache.counts["disabled"] == 1
        return
    assert path is not None and path.exists()
    loaded = cache.load(key, program="p")
    assert loaded is not None
    np.testing.assert_array_equal(
        np.asarray(loaded(jnp.ones((4,), jnp.float32))),
        np.asarray(compiled(jnp.ones((4,), jnp.float32))),
    )
    # corruption: flip payload bytes -> sha mismatch -> recorded + None
    faults.corrupt_checkpoint_bytes(path, seed=1)
    assert cache.load(key, program="p") is None
    assert cache.counts["corrupt"] == 1
    # a rewrite heals it
    cache.store(key, compiled, program="p")
    assert cache.load(key, program="p") is not None
    # truncation is also corrupt, not a crash
    path.write_bytes(path.read_bytes()[:16])
    assert cache.load(key, program="p") is None
    assert cache.counts["corrupt"] == 2
    # stale fingerprint: same key on disk, different backend identity
    cache.store(key, compiled, program="p")
    other = AC.AotCache(tmp_path / "aot")
    other._fingerprint = {**cache.fingerprint(), "jaxlib": "0.0.0-other"}
    assert other.load(key, program="p") is None
    assert other.counts["stale"] == 1
    stats = cache.stats()
    assert stats["lookups"] >= 2 and stats["disabled_reason"] is None


# ---------------------------------------------------------------------------
# the session-level contract
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "kw", [dict(), dict(dp=2)], ids=["seq", "dp2"]
)
def test_warm_start_serves_ladder_with_zero_compiles(data_dir, tmp_path, kw):
    """The cold session compiles + stores; a second session over the same
    cache dir serves the SAME rows bitwise-identically with jit_compiles
    == 0 (the counter pin) and a recorded hit per program — and the
    xla_audit record proves the deserialized program was censused before
    its first dispatch."""
    cache = tmp_path / "aot"
    X = np.random.RandomState(7).rand(20, SIZES[0]).astype(np.float32)

    m1 = MetricsRecorder()
    cold = _session(data_dir, cache, metrics=m1, **kw)
    p_cold = cold.predict(X)
    if not cold._aot.supported:
        pytest.skip(f"backend cannot serialize: {cold._aot.disabled_reason}")
    assert m1.counters.get("jit_compiles", 0) >= 1
    assert cold._aot.counts["store"] >= 1

    m2 = MetricsRecorder()
    audits = []
    m2.audit = lambda name, **f: audits.append((name, f))
    warm = _session(data_dir, cache, metrics=m2, **kw)
    p_warm = warm.predict(X)
    assert m2.counters.get("jit_compiles", 0) == 0, "warm start recompiled"
    assert warm._aot.counts["hit"] >= 1 and warm._aot.counts["miss"] == 0
    np.testing.assert_array_equal(p_cold, p_warm)
    # never serve an unaudited program: the deserialized rung was censused
    assert audits and all(n == "inference_program" for n, _ in audits)
    assert all(f.get("census_ok") for _, f in audits)


def test_corrupt_entry_falls_back_to_clean_recompile(data_dir, tmp_path):
    """A deliberately corrupted on-disk entry must cost a recompile, a
    recorded corrupt event and a rewrite — never a crash, never served."""
    cache = tmp_path / "aot"
    X = np.random.RandomState(7).rand(8, SIZES[0]).astype(np.float32)
    cold = _session(data_dir, cache, metrics=MetricsRecorder())
    p0 = cold.predict(X)
    if not cold._aot.supported:
        pytest.skip(f"backend cannot serialize: {cold._aot.disabled_reason}")
    entry = sorted((tmp_path / "aot").glob("*.aotx"))[0]
    faults.corrupt_checkpoint_bytes(entry, seed=3)
    m = MetricsRecorder()
    s = _session(data_dir, cache, metrics=m)
    p1 = s.predict(X)
    assert s._aot.counts["corrupt"] == 1
    assert s._aot.counts["store"] == 1  # rewritten after the fallback
    assert m.counters.get("jit_compiles", 0) == 1
    np.testing.assert_array_equal(p0, p1)
    # and the healed entry serves the next session from cache again
    m3 = MetricsRecorder()
    s3 = _session(data_dir, cache, metrics=m3)
    s3.predict(X)
    assert m3.counters.get("jit_compiles", 0) == 0


def test_aot_events_land_in_jsonl_with_schema_v8(data_dir, tmp_path):
    """The aot_cache records flow through the JSONL sink self-describing:
    kind aot_cache, v8 stamp, program + key + outcome names."""
    from shallowspeed_tpu.observability import JsonlMetrics

    cache = tmp_path / "aot"
    jl = tmp_path / "m.jsonl"
    X = np.random.RandomState(7).rand(8, SIZES[0]).astype(np.float32)
    with JsonlMetrics(jl) as m:
        s = _session(data_dir, cache, metrics=m)
        s.predict(X)
    recs = [r for r in read_jsonl(jl) if r["kind"] == "aot_cache"]
    if not s._aot.supported:
        assert [r["name"] for r in recs] == ["disabled"]
        return
    names = [r["name"] for r in recs]
    assert "miss" in names and "store" in names
    # the live stamp follows SCHEMA_VERSION (the exact-version pin lives
    # with the newest schema's test in test_observability.py)
    assert all(r["v"] == SCHEMA_VERSION and r.get("program") for r in recs)


def test_epoch_audit_probe_rides_the_cache_probe_only(data_dir, tmp_path):
    """The trainer's cold-start leg: with metrics on, the epoch AUDIT
    probe (census + cost_analysis) deserializes from the cache on a warm
    start instead of paying its XLA compile — while dispatch stays on
    the jit wrapper (the deserialized object is probe-only: executing a
    deserialized DONATING program is the jax-0.4.x hazard class the
    cache avoids structurally). Training math is unchanged either way."""
    cache = tmp_path / "aot"
    ref = TrainingSession(
        sizes=SIZES, global_batch_size=64, data_dir=data_dir
    )
    ref_loss = ref.train_epoch()

    m1 = MetricsRecorder()
    cold = _session(data_dir, cache, metrics=m1)
    cold_loss = cold.train_epoch()
    if not cold._aot.supported:
        pytest.skip(f"backend cannot serialize: {cold._aot.disabled_reason}")
    assert cold._aot.counts["store"] >= 1  # the probe was stored
    # probe compile + the jit wrapper's own first-dispatch compile
    cold_compiles = m1.counters.get("jit_compiles", 0)
    assert cold_compiles >= 1

    m2 = MetricsRecorder()
    warm = _session(data_dir, cache, metrics=m2)
    warm_loss = warm.train_epoch()
    assert warm._aot.counts["hit"] >= 1  # the probe came from cache
    # the probe's compile disappeared; dispatch still jit-compiles once,
    # so the counter drops by exactly the probe
    assert m2.counters.get("jit_compiles", 0) == 0
    assert warm_loss == cold_loss == ref_loss
    assert warm.model_hash() == cold.model_hash() == ref.model_hash()
