"""Model layer: stage partitioning + explicit forward/backward over pytrees.

Capability parity with the reference's Module/Sequential/MLP stack
(/root/reference/shallowspeed/layers.py), re-designed functionally for JAX:

- parameters are a pytree ``[{"W": (out,in), "b": (1,out)}, ...]`` per stage —
  no Parameter objects, no mutable .grad fields;
- the per-microbatch activation caches (reference ``Module._cache`` keyed by
  mubatch_id, layers.py:70,86,117) become *residuals returned by the forward
  pass* and threaded explicitly into the backward pass — idiomatic JAX, and
  what lets the whole step jit/scan cleanly;
- gradient accumulation (reference ``param.grad +=``, layers.py:135-136) is a
  pytree add performed by the caller (a lax.scan carry), not hidden state.

Stage partitioning semantics match reference layers.py:236-270 ("MLP"):
``len(sizes) % n_stages == 0``; stage i owns the sizes slice
``[i*ss : i*ss+ss+1]`` (overlapping boundary entry) giving ``len(local)-1``
Linear layers; every Linear has a fused ReLU except the last Linear of the
last stage; the last stage appends the softmax + MSE loss head. Stages are
deliberately UNEQUAL (e.g. 2/2/2/1 Linears at PP=4) — the SPMD executor
handles that via zero-padded stacked params (see parallel/executor.py).

Faithful reference quirk: when the last stage owns ZERO Linears (e.g. 8
sizes at PP=8), the no-relu-on-final-Linear rule never fires — the global
final Linear (owned by the second-to-last stage) keeps its ReLU, so that
layout is architecturally DIFFERENT from the sequential model. This matches
the reference exactly (layers.py:253-257); layout/sequential equivalence
holds whenever the last stage has at least one Linear.
"""

import dataclasses
from typing import Sequence

import jax.numpy as jnp

from shallowspeed_tpu import ops
from shallowspeed_tpu.init import linear_init


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """Static description of one pipeline stage (trace-time constant)."""

    local_sizes: tuple  # activation dims owned by this stage, len = n_linears+1
    relu_flags: tuple  # per-Linear fused-activation flag (act names which one)
    has_head: bool  # softmax + MSE head lives on the last stage
    global_batch_size: int
    act: str = "relu"  # activation family: "relu" (MLP) or "gelu" (block zoo)
    residual_flags: tuple = ()  # per-Linear: output += the PREVIOUS Linear's
    # input (the transformer-style skip over one up/down projection pair);
    # () means no residuals (every relu-family spec)

    @property
    def n_linears(self):
        return len(self.local_sizes) - 1

    @property
    def res_flags(self):
        """residual_flags normalized to one bool per Linear."""
        if len(self.residual_flags) == self.n_linears:
            return self.residual_flags
        return (False,) * self.n_linears

    @property
    def in_dim(self):
        return self.local_sizes[0]

    @property
    def out_dim(self):
        # softmax & loss head do not change the output dim (layers.py:268-270)
        return self.local_sizes[-1]


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """Static description of the whole (possibly pipelined) model."""

    sizes: tuple
    n_stages: int
    global_batch_size: int
    stages: tuple  # tuple[StageSpec]
    act: str = "relu"

    @property
    def in_dim(self):
        return self.sizes[0]

    @property
    def out_dim(self):
        return self.sizes[-1]

    @property
    def has_residual(self):
        return any(any(s.res_flags) for s in self.stages)


def partition_sizes(sizes: Sequence[int], n_stages: int):
    """Slice the global layer-size list into per-stage local size lists.

    Same arithmetic as reference layers.py:242-250, including the overlapping
    boundary entry and the possibility of a 0-Linear trailing stage.
    """
    sizes = tuple(int(s) for s in sizes)
    if len(sizes) % n_stages != 0:
        raise ValueError(
            f"len(sizes)={len(sizes)} must be divisible by n_stages={n_stages}"
        )
    stage_size = len(sizes) // n_stages
    return [
        sizes[i * stage_size : min(len(sizes), i * stage_size + stage_size + 1)]
        for i in range(n_stages)
    ]


def make_model_spec(sizes, n_stages, global_batch_size, act="relu") -> ModelSpec:
    if act not in ("relu", "gelu"):
        raise ValueError(f"unknown activation family {act!r} (relu|gelu)")
    locals_ = partition_sizes(sizes, n_stages)
    stage_size = len(sizes) // n_stages
    n_lin_total = len(sizes) - 1
    if act == "gelu" and n_stages > 1 and stage_size % 2 != 0:
        # the gelu family assigns activation/residual by GLOBAL Linear
        # parity; an odd per-stage slice would flip local parity stage to
        # stage, breaking the even/odd slot contract tp sharding and the
        # stacked executor's static slot loop key off
        raise ValueError(
            f"gelu-family models need an even per-stage slice so local slot "
            f"parity equals global Linear parity; len(sizes)={len(sizes)} "
            f"over {n_stages} stages gives {stage_size}"
        )
    if act == "relu" and len(locals_[-1]) == 1:
        import warnings

        warnings.warn(
            f"the last of {n_stages} pipeline stages owns no Linear under "
            "this partitioning, so the 'no relu on the final Linear' rule "
            "never fires and the trained MODEL differs from shallower "
            "partitionings (faithful reference quirk, layers.py:253-257) — "
            "expect worse accuracy; prefer a size list that gives every "
            "stage a Linear",
            stacklevel=2,
        )
    stages = []
    for i, loc in enumerate(locals_):
        is_last = i == n_stages - 1
        n_lin = len(loc) - 1
        if act == "relu":
            # last Linear of last stage has no activation (layers.py:253-257)
            act_flags = tuple(
                not (is_last and l == n_lin - 1) for l in range(n_lin)
            )
            res_flags = ()
        else:
            # transformer-style block family: per global Linear index g,
            # even g is the up-projection (gelu), odd g the down-projection
            # (no activation) whose output takes the block-input residual
            # whenever the dims agree; the GLOBAL final Linear feeds the
            # softmax head raw
            act_flags = []
            res_flags = []
            for l in range(n_lin):
                g = i * stage_size + l
                act_flags.append(g % 2 == 0 and g != n_lin_total - 1)
                res_flags.append(
                    g % 2 == 1 and sizes[g - 1] == sizes[g + 1]
                )
            act_flags = tuple(act_flags)
            res_flags = tuple(res_flags)
        stages.append(
            StageSpec(
                local_sizes=tuple(loc),
                relu_flags=act_flags,
                has_head=is_last,
                global_batch_size=global_batch_size,
                act=act,
                residual_flags=res_flags,
            )
        )
    return ModelSpec(
        sizes=tuple(int(s) for s in sizes),
        n_stages=n_stages,
        global_batch_size=global_batch_size,
        stages=tuple(stages),
        act=act,
    )


# ---------------------------------------------------------------------------
# Model zoo: named compute-bound configurations, all flowing through the
# same ops/schedules/lowering/executor stack (docs/performance.md "--model").
# ``mnist-mlp`` is the flagship reference model (api.FLAGSHIP_SIZES aliases
# it); the others exist to make per-tick compute dominate dispatch on hosts
# where the flagship epoch is op-issue-bound (DISPATCH_r01).
# ---------------------------------------------------------------------------

MODEL_ZOO = {
    # the reference ShallowSpeed MNIST MLP (uneven stages at pp4 by design)
    "mnist-mlp": dict(sizes=(784, 128, 127, 126, 125, 124, 123, 10), act="relu"),
    # compute-bound MLP: ~10.5 MFLOP/sample forward+backward, same depth /
    # pp divisibility as the flagship — the bench default for COMPUTE_r01
    "mlp-wide": dict(sizes=(784, 512, 512, 512, 512, 512, 512, 10), act="relu"),
    # showcase depth: 23 Linears x 2048 wide (~0.5 GFLOP/sample) — the
    # stash-peak-bound regime where recompute pays (24 sizes: pp 2/3/4/6/8)
    "mlp-deep": dict(sizes=(784,) + (2048,) * 22 + (10,), act="relu"),
    # transformer-style blocks: 256-wide trunk, 1024-wide gelu up/down
    # projections with residual adds on every dim-matched block
    "transformer": dict(
        sizes=(784, 1024, 256, 1024, 256, 1024, 256, 10), act="gelu"
    ),
}


def resolve_model(name):
    """MODEL_ZOO name -> (sizes, act)."""
    try:
        entry = MODEL_ZOO[name]
    except KeyError:
        raise ValueError(
            f"unknown model {name!r}; zoo: {', '.join(sorted(MODEL_ZOO))}"
        ) from None
    return tuple(entry["sizes"]), entry["act"]


def init_stage_params(spec: StageSpec):
    """Host-side deterministic init for one stage; list of {"W","b"} numpy."""
    return [
        dict(zip(("W", "b"), linear_init(spec.local_sizes[l], spec.local_sizes[l + 1])))
        for l in range(spec.n_linears)
    ]


def init_model(spec: ModelSpec):
    """Per-stage parameter pytrees (host numpy; caller device_puts/shards)."""
    return [init_stage_params(s) for s in spec.stages]


# ---------------------------------------------------------------------------
# Forward / backward. Pure functions; residuals are explicit.
#
# Residuals structure per stage (static given the spec):
#   (layer_caches, z)
#     layer_caches: tuple per Linear of (x_in, relu_bitmask)  — bitmask is a
#                   zero-size placeholder for no-relu layers
#     z:            head-input logits if has_head else zero-size placeholder
# ---------------------------------------------------------------------------


def _placeholder(dtype=jnp.float32):
    return jnp.zeros((0,), dtype)


def stage_forward(
    params, spec: StageSpec, x, precision=ops.DEFAULT_PRECISION, head_group_rows=None
):
    """Run one stage's Linears (+head); return (out, residuals).

    In training the caller keeps residuals; for inference discard them (XLA
    dead-code-eliminates the cache outputs under jit).

    ``head_group_rows``: when several microbatches are fused into one call,
    the softmax head's stability max is taken per group of this many rows so
    the result is float-identical to a per-microbatch loop.

    Mirrors reference Sequential.forward + Linear.forward + head modules
    (layers.py:115-122,152-155,176-180) with caches made explicit.
    """
    caches = []
    if spec.act == "gelu":
        res = spec.res_flags
        x_prev = None  # input of the PREVIOUS Linear (the block input)
        for l in range(spec.n_linears):
            y = ops.linear(x, params[l]["W"], params[l]["b"], precision=precision)
            if spec.relu_flags[l]:
                caches.append((x, ops.gelu_grad_mult(y)))
                y_act = ops.gelu(y)
            else:
                caches.append((x, _placeholder()))
                y_act = y
            if res[l]:
                y_act = y_act + x_prev
            x_prev = x
            x = y_act
    else:
        for l in range(spec.n_linears):
            if spec.relu_flags[l]:
                y, mask = ops.linear_relu_fused(
                    x, params[l]["W"], params[l]["b"], precision=precision
                )
                caches.append((x, mask))
                x = y
            else:
                y = ops.linear(x, params[l]["W"], params[l]["b"], precision=precision)
                caches.append((x, _placeholder(jnp.bool_)))
                x = y
    if spec.has_head:
        z = x
        out = ops.softmax(z, group_rows=head_group_rows)
        return out, (tuple(caches), z)
    return x, (tuple(caches), _placeholder())


def stage_backward(
    params,
    spec: StageSpec,
    residuals,
    dout,
    precision=ops.DEFAULT_PRECISION,
    head_group_rows=None,
):
    """Backward through one stage; returns (dx, grads) with grads ≅ params.

    Contract matches the reference Worker: for the head stage ``dout`` is the
    TARGET microbatch (the reference loads targets into the output buffer and
    MSELoss.backward consumes them, pipe.py:361-365 + layers.py:157-163);
    for other stages it is the gradient w.r.t. this stage's output.
    """
    caches, z = residuals
    if spec.has_head:
        g = ops.softmax_mse_head_grad(
            z, dout, spec.global_batch_size, group_rows=head_group_rows
        )
    else:
        g = dout
    grads = [None] * spec.n_linears
    if spec.act == "gelu":
        res = spec.res_flags
        g_prev = None  # incoming grad at the previously-processed Linear l+1
        for l in reversed(range(spec.n_linears)):
            x_in, dact = caches[l]
            g_in = g
            g_pre = g_in * dact if spec.relu_flags[l] else g_in
            g, dw, db = ops.linear_grad(
                g_pre, x_in, params[l]["W"], precision=precision
            )
            if l + 1 < spec.n_linears and res[l + 1]:
                # residual at l+1 adds this Linear's INPUT to y_{l+1}: the
                # incoming grad there flows straight into dx here
                g = g + g_prev
            grads[l] = {"W": dw, "b": jnp.reshape(db, (1, -1))}
            g_prev = g_in
    else:
        for l in reversed(range(spec.n_linears)):
            x_in, bitmask = caches[l]
            if spec.relu_flags[l]:
                g, dw, db = ops.linear_relu_grad_fused(
                    g, bitmask, x_in, params[l]["W"], precision=precision
                )
            else:
                g, dw, db = ops.linear_grad(g, x_in, params[l]["W"], precision=precision)
            grads[l] = {"W": dw, "b": jnp.reshape(db, (1, -1))}
    return g, grads


def model_forward(
    params_list, spec: ModelSpec, x, precision=ops.DEFAULT_PRECISION, head_group_rows=None
):
    """Chain all stages (the sequential / single-process path)."""
    residuals = []
    for params, sspec in zip(params_list, spec.stages):
        x, res = stage_forward(
            params, sspec, x, precision=precision, head_group_rows=head_group_rows
        )
        residuals.append(res)
    return x, residuals


def model_backward(
    params_list,
    spec: ModelSpec,
    residuals,
    target,
    precision=ops.DEFAULT_PRECISION,
    head_group_rows=None,
):
    """Chain all stages backward; ``target`` feeds the head stage."""
    g = target
    grads_list = [None] * spec.n_stages
    for i in reversed(range(spec.n_stages)):
        g, grads_list[i] = stage_backward(
            params_list[i],
            spec.stages[i],
            residuals[i],
            g,
            precision=precision,
            head_group_rows=head_group_rows,
        )
    return g, grads_list
