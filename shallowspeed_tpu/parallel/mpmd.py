"""MPMD per-stage pipeline runtime: async host dispatch + device relays.

The lockstep executor (parallel/executor.py) runs the whole dp x pp x tp
lattice as ONE SPMD program: every tick costs the maximum op across
stages, pipeline bubbles are real ``lax.switch`` noop dispatches, and the
measured op-issue roofline (DISPATCH_r01.json: >= 72.8% of the flagship
gpipe-pp4 CPU epoch wall has NO op executing) eats every scheduling win.
This module is the MPMD form of arXiv 2412.14374 (Scaling Deep Learning
Training with MPMD Pipeline Parallelism): one compiled program per STAGE
ROLE — a stage's forward, its backward (or split B-input / B-weight
halves), its optimizer update — dispatched asynchronously from the host,
with activations relayed stage-to-stage by device-to-device transfers
(``jax.device_put`` onto the next stage's sub-mesh) instead of
in-program ``ppermute`` shifts:

- **no noop dispatches**: bubble cells of the tick table simply never
  dispatch anything — the op-issue cost of a bubble is zero, not a
  ``lax.switch`` entry into a masked branch;
- **no lockstep barrier**: each stage's device queue advances at its own
  pace; JAX's async dispatch issues the whole batch's per-stage streams
  ahead of execution and the data dependencies (relay payloads, stash
  reads) are what order the devices, so unequal stages run unpadded in
  TIME (a short stage never waits for the longest stage's tick);
- **the simulator stays the spec**: the host scheduler is driven
  directly by the lowered tick tables (``TickProgram``) — the SAME
  artifact the lockstep executor scans — and
  ``analysis.progcheck.analyze_program`` (the tick-free happens-before
  proof PR 13 built for exactly this runtime) is the admission gate:
  a program whose tables were tampered with is refused BEFORE any stage
  program dispatches;
- **bitwise parity is the contract**: every per-slot expression is the
  executor's own (``_stage_fwd`` / ``_stage_bwd`` / the tp and split
  variants), the per-slot zero-padded widths are retained (a different
  contraction length would re-block the fp sums — docs/numerics.md),
  and gradient accumulation order per stage is the tick-table stream
  order, so MPMD epoch weights hash-equal the lockstep twin's. The
  "unpadded" win is the TICK dimension (no max-over-stages, no noop
  cells), not the slot widths.

Feature envelope: the runtime refuses (loudly, at construction) the
knobs whose lockstep implementations live in the fused program's tail —
``zero1``, ``grad_bucket_bytes``, ``clip_norm`` (cross-stage global
norm), the pallas kernel backend, and the fused-run/step-stats aux.
Those stay lockstep-only until a follow-up teaches the per-stage update
their math; ``TrainingSession(runtime=...)`` enforces the envelope.

Serving rides the same machinery: ``MpmdInferenceRunner`` streams
request slots through per-stage forward programs — slot k enters stage 0
while slot k-1 occupies stage 1 — so a response is no longer quantized
to the whole rung program's makespan (the tail-latency payoff measured
in MPMD_r01.json).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shallowspeed_tpu import ops
from shallowspeed_tpu.parallel import executor as E
from shallowspeed_tpu.parallel.compat import shard_map
from shallowspeed_tpu.parallel.lowering import (
    OP_BWD,
    OP_BWD_W,
    OP_FWD,
    OP_NOOP,
    OP_RECOMPUTE,
)
from shallowspeed_tpu.parallel.mesh import mesh_tp


# ---------------------------------------------------------------------------
# Stage sub-meshes and zero-copy stage views
# ---------------------------------------------------------------------------


def stage_submeshes(mesh: Mesh):
    """One (dp, tp) sub-mesh per pp device column. The full mesh's device
    array is (dp, pp, tp); stage s's sub-mesh is the devices at pp
    coordinate s — the SAME physical devices the lockstep program uses
    for that stage, so stage views are zero-copy buffer reinterpretation,
    never data movement."""
    devs = mesh.devices  # (dp, pp) or (dp, pp, tp)
    if devs.ndim == 2:  # tp == 1 meshes carry no tp axis (mesh.py)
        devs = devs[:, :, None]
    return [Mesh(devs[:, s, :], ("dp", "tp")) for s in range(devs.shape[1])]


def _drop_pp(spec):
    """A full-mesh PartitionSpec with the leading 'pp' factor removed:
    the stage view's sharding over the (dp, tp) sub-mesh. P('pp') ->
    P(); P('pp', 'tp', None) -> P(None, 'tp', None)."""
    parts = tuple(spec)
    if not parts:
        return P()
    assert parts[0] == "pp", f"stage-axis spec must lead with 'pp': {spec}"
    return P(None, *parts[1:])


def _view(arr, shape, ns, rows=None):
    """Zero-copy reinterpretation of ``arr``'s device buffers under a new
    (global shape, sharding): the stage-view primitive. Every target
    device must already hold exactly its shard of the new view — true by
    construction for stage rows of a P('pp', ...)-sharded stack. Arrays
    that are not yet mesh-placed (a fresh ``opt.init`` state before its
    first dispatch) fall back to one explicit reshard copy (``rows``
    slices the stage block first); after the first update the
    reassembled state is mesh-placed and the fast path takes over."""
    by_dev = {s.device: s.data for s in arr.addressable_shards}
    target = list(ns.mesh.devices.flat)
    if len(target) == 1:
        # singleton fast path: the stage's buffer IS the view — return
        # the single-device array itself so every downstream program
        # sees one consistent sharding type (SingleDeviceSharding, the
        # type plain-jit outputs carry)
        dev = target[0]
        if dev in by_dev and by_dev[dev].shape == shape:
            return by_dev[dev]
        return jax.device_put(arr if rows is None else arr[rows], dev)
    if all(d in by_dev for d in target) and all(
        by_dev[d].shape == ns.shard_shape(shape) for d in target
    ):
        return jax.make_array_from_single_device_arrays(
            shape, ns, [by_dev[d] for d in target]
        )
    src = arr if rows is None else arr[rows]
    return jax.device_put(src, ns)


def stage_param_view(stacked, s, submesh, tp, V):
    """Stage s's (V, ...) rows of the full stacked {"W", "b"} tree as
    sub-mesh arrays (zero-copy; Megatron tp shards preserved)."""
    L = len(stacked["W"])
    specs = E.stacked_param_specs(tp, L)
    rows = slice(s * V, (s + 1) * V)
    out = {}
    for k in ("W", "b"):
        leaves = []
        for arr, sp in zip(stacked[k], specs[k]):
            ns = NamedSharding(submesh, _drop_pp(sp))
            leaves.append(_view(arr, (V,) + arr.shape[1:], ns, rows=rows))
        out[k] = tuple(leaves)
    return out


def stage_flags_view(flags, s, submesh, V):
    """Stage s's flag rows (active/relu/residual/head_mask), replicated
    over the sub-mesh like the lockstep per-device view."""
    rows = slice(s * V, (s + 1) * V)
    return {
        k: _view(
            flags[k], (V,) + flags[k].shape[1:],
            NamedSharding(submesh, P()), rows=rows,
        )
        for k in ("active", "relu", "residual", "head_mask")
    }


def stage_state_view(opt, state, s, submesh, tp, V):
    """Stage s's optimizer-state view: 'params' parts mirror the param
    stage view, 'scalar' parts replicate; () for stateless state."""
    if isinstance(state, tuple) and state == ():
        return ()
    from shallowspeed_tpu.optimizer import join_state, split_state

    parts, scalars = split_state(opt, state)
    return join_state(
        opt,
        {k: stage_param_view(v, s, submesh, tp, V) for k, v in parts.items()},
        {
            k: _view(v, v.shape, NamedSharding(submesh, P()))
            for k, v in scalars.items()
        },
    )


def full_from_stage(stage_arrs, mesh, full_shape, full_spec):
    """Reassemble one full-mesh array from its P per-stage views (the
    inverse of ``_view``, zero-copy): collect every stage array's device
    buffers and reinterpret them under the full sharding."""
    shards = []
    for arr in stage_arrs:
        shards.extend(s.data for s in arr.addressable_shards)
    return jax.make_array_from_single_device_arrays(
        full_shape, NamedSharding(mesh, full_spec), shards
    )


def full_param_from_stage(stage_params, mesh, S, tp):
    """Per-stage {"W","b"} views -> the full stacked tree (zero-copy),
    with the session's canonical shardings (``stacked_param_specs``)."""
    L = len(stage_params[0]["W"])
    specs = E.stacked_param_specs(tp, L)
    out = {}
    for k in ("W", "b"):
        leaves = []
        for l in range(len(stage_params[0][k])):
            arrs = [sp[k][l] for sp in stage_params]
            shape = (S,) + arrs[0].shape[1:]
            leaves.append(full_from_stage(arrs, mesh, shape, specs[k][l]))
        out[k] = tuple(leaves)
    return out


def full_state_from_stage(opt, stage_states, mesh, S, tp):
    """Per-stage optimizer-state views -> the full-mesh state tree."""
    if stage_states[0] == ():
        return ()
    from shallowspeed_tpu.optimizer import join_state, split_state

    split = [split_state(opt, st) for st in stage_states]
    parts = {
        k: full_param_from_stage([p[k] for p, _ in split], mesh, S, tp)
        for k in split[0][0]
    }
    scalars = {
        k: full_from_stage([sc[k] for _, sc in split], mesh, (), P())
        for k in split[0][1]
    }
    return join_state(opt, parts, scalars)


# ---------------------------------------------------------------------------
# Per-stage census contracts (the audit satellite)
# ---------------------------------------------------------------------------

_NEVER = ["collective_permute", "all_to_all", "reduce_scatter", "all_gather"]


def expected_stage_comms(role, spec, dp, tp, sends=True):
    """The per-stage-program collective contract ``check_census`` style:
    relays left the program, so a ``collective_permute`` ANYWHERE in a
    stage program is a contract violation (the defining MPMD property);
    the only lawful all-reduces are the Megatron tp psums inside compute
    roles and the dp gradient/loss psum inside the update/loss roles.

    ``sends`` (backward roles): whether this program RETURNS its dx
    relay payload. A non-relaying backward (the first pipeline stage)
    never consumes the dgrad chain's final value, so XLA dead-code
    eliminates the LAST column slot's dx psum — the structural floor
    must not demand an op the compiler lawfully removed."""
    required, forbidden = [], list(_NEVER)
    axes = {}
    # the recompute roles run the SAME stage forward expression as "fwd"
    # (fwd_ns: the no-stash forward at the fwd tick; recompute: the
    # re-materializing forward at the backward tick), so their collective
    # contract is the forward's — the tp psum count doubles per (chunk,
    # microbatch) only because the forward runs twice
    if role in ("fwd", "fwd_ns", "recompute", "bwd", "bwd_in"):
        fwd_like = role in ("fwd", "fwd_ns", "recompute")
        if tp > 1:
            fwd_w, bwd_w = E.tp_allreduce_sites(spec, tp, training=True)
            sites = len(fwd_w) if fwd_like else len(bwd_w)
            if role in ("bwd", "bwd_in") and not sends:
                # slot 0's dx psum feeds only the (unreturned) relay
                sites -= 1
            if sites > 0:
                required.append("all_reduce")
                axes["tp"] = {
                    "kind": "all_reduce",
                    "sites_fwd": sites if fwd_like else 0,
                    "sites_bwd": 0 if fwd_like else sites,
                    "hlo_min_all_reduce_ops": sites,
                }
            # sites == 0: the one potential psum is dead code — whether
            # the backend actually elides it is its business, so the
            # kind is neither required nor forbidden
        else:
            forbidden.append("all_reduce")
    elif role == "bwd_w":
        # the deferred wgrads are collective-free at every tp degree
        forbidden.append("all_reduce")
    elif role in ("pack", "unpack", "state_pack", "state_unpack"):
        # pure data movement at the run boundary — no collective, ever
        forbidden.append("all_reduce")
    elif role in ("update", "loss_sync"):
        if dp > 1:
            required.append("all_reduce")
    elif role == "infer_fwd":
        if tp > 1:
            fwd_w, _ = E.tp_allreduce_sites(spec, tp, training=False)
            if fwd_w:
                required.append("all_reduce")
                axes["tp"] = {
                    "kind": "all_reduce",
                    "sites_fwd": len(fwd_w),
                    "sites_bwd": 0,
                    "hlo_min_all_reduce_ops": len(fwd_w),
                }
        else:
            forbidden.append("all_reduce")
    else:
        raise ValueError(f"unknown stage-program role {role!r}")
    return {
        "dp": int(dp),
        "tp": int(tp),
        "zero1": False,
        "inference": False,
        "mpmd_role": role,
        "required": required,
        "forbidden": forbidden,
        "axes": axes,
    }


# ---------------------------------------------------------------------------
# The tick-table-driven host plan
# ---------------------------------------------------------------------------


def stage_cells(prog):
    """The per-stage MPMD streams, read directly from the lowered tick
    tables (the simulator is the spec): a list over ticks of the ACTIVE
    cells only — noop cells produce nothing, which is the whole point.
    Each cell carries the static facts a dispatch needs; mailbox slot
    numbers are deliberately absent (host dataflow is keyed by
    (chunk, microbatch); the slot discipline was proven by progcheck)."""
    P_ = prog.num_stages
    out = []
    for t in range(prog.num_ticks):
        row = []
        for s in range(P_):
            op = int(prog.op[t, s])
            if op == OP_NOOP:
                continue
            row.append(
                dict(
                    s=s,
                    op=op,
                    mb=int(prog.mb[t, s]),
                    v=int(prog.chunk[t, s]) if prog.chunk is not None else 0,
                    load=bool(prog.load_in[t, s]),
                    head=bool(prog.is_head[t, s]),
                    send_fwd=bool(prog.send_fwd[t, s]),
                    send_bwd=bool(prog.send_bwd[t, s]),
                )
            )
        if row:
            out.append(row)
    return out


class _StagePrograms:
    """Lazily-built jitted per-stage programs for one (mesh, spec, prog)
    triple. Programs are keyed ``(stage, role, variant)``; ``resolve``
    (optional) intercepts compilation — the session points it at the AOT
    cache + per-stage audit, so a warm MPMD start compiles zero stage
    programs and every one is census/donation-verified before its first
    dispatch."""

    def __init__(self, mesh, spec, prog, mubatch_size, opt=None,
                 precision=ops.DEFAULT_PRECISION):
        self.mesh = mesh
        self.spec = spec
        self.prog = prog
        self.tp = mesh_tp(mesh)
        self.dp = mesh.shape["dp"]
        self.V = prog.num_chunks
        self.opt = opt
        self.precision = precision
        self.submeshes = stage_submeshes(mesh)
        # the activation family is STATIC (model.py): it picks which
        # per-slot expressions the stage programs trace, exactly like the
        # lockstep executor — and the mask stash dtype follows it (relu
        # stashes sign bits; the gelu family stashes the f32 grad
        # multiplier, docs/lowering.md)
        self.act = getattr(spec, "act", "relu")
        self.mask_dtype = jnp.bool_ if self.act == "relu" else jnp.float32
        self.rec = bool(getattr(prog, "recompute", False))
        # singleton-axis fast path: with dp == tp == 1 each stage's
        # sub-mesh is ONE device, every collective in the stage programs
        # is a 1-member group (bitwise identity), and shard_map buys
        # nothing but Python dispatch cost — so the programs compile as
        # plain jit over committed single-device arrays (the C++
        # fast-path dispatch, ~5x cheaper per call) and relays target
        # the device directly. Multi-member axes keep shard_map (the
        # psums are real).
        self.single = self.dp == 1 and self.tp == 1
        # packed mode rides the singleton path: per-program latency on
        # the XLA CPU client scales with BUFFER COUNT (measured ~535us
        # per chained link at ~55 buffers vs ~145us at 4, same bytes),
        # so the per-stage params/grads/stashes travel as ONE flat
        # buffer each and the programs slice static views out (exact:
        # reshape/slice reproduce the leaves bit for bit, and the
        # optimizer math is elementwise — the same flat-vector trick
        # ZeRO-1's chunked update already pins bitwise). Multi-member
        # axes keep the per-leaf representation (their shard_map specs
        # are per-leaf, and dispatch cost is not their binding tax).
        self.packed = self.single
        self.stage_device = [m.devices.flat[0] for m in self.submeshes]
        self.dims = E.slot_shapes(spec, self.tp)
        self.L = len(self.dims)
        self.D_in = self.dims[0][1]
        self.D_out = self.dims[-1][0]
        self.W_rel = E.relay_width(spec)
        self.mb_sz = mubatch_size  # per-dp-replica rows per microbatch
        self.B_global = spec.global_batch_size
        self._fns = {}
        # per-slot stash specs over the (dp, tp) sub-mesh, in the exact
        # representation the lockstep carry uses (executor.tp_local_dims):
        # a column slot's input is full-width (tp-replicated), a row
        # slot's is the rank shard; masks mirror inversely
        if self.tp == 1:
            self._xs_specs = (P("dp"),) * self.L
            self._mask_specs = (P("dp"),) * self.L
        else:
            self._xs_specs = tuple(
                P("dp") if l % 2 == 0 else P("dp", "tp")
                for l in range(self.L)
            )
            self._mask_specs = tuple(
                P("dp", "tp") if l % 2 == 0 else P("dp")
                for l in range(self.L)
            )
        self._param_specs = {
            k: tuple(_drop_pp(sp) for sp in v)
            for k, v in E.stacked_param_specs(self.tp, self.L).items()
        }
        self._flag_specs = {
            "active": P(), "relu": P(), "residual": P(), "head_mask": P(),
        }
        if opt is not None:
            from shallowspeed_tpu.optimizer import (
                is_stateless,
                join_state,
                split_state,
            )

            if is_stateless(opt):
                self._state_specs = ()
            else:
                struct = jax.eval_shape(
                    opt.init,
                    {
                        "W": tuple(
                            jax.ShapeDtypeStruct((self.V, o, i), jnp.float32)
                            for o, i in self.dims
                        ),
                        "b": tuple(
                            jax.ShapeDtypeStruct((self.V, o), jnp.float32)
                            for o, _ in self.dims
                        ),
                    },
                )
                parts, scalars = split_state(opt, struct)
                self._state_specs = join_state(
                    opt,
                    {k: self._param_specs for k in parts},
                    {k: P() for k in scalars},
                )

    # -- packed-representation helpers (traced; packed mode only) -----------

    @property
    def plen(self):
        """Flat length of one stage's packed {W, b} vector (the zero1
        leaf order: every W slot raveled, then every b slot)."""
        V = self.V
        return sum(V * o * i for o, i in self.dims) + sum(
            V * o for o, _ in self.dims
        )

    def _unpack_wb(self, pvec):
        """Static slice+reshape views of the packed vector — the exact
        leaves, bit for bit."""
        V = self.V
        Ws, bs, off = [], [], 0
        for o, i in self.dims:
            n = V * o * i
            Ws.append(pvec[off : off + n].reshape(V, o, i))
            off += n
        for o, _ in self.dims:
            n = V * o
            bs.append(pvec[off : off + n].reshape(V, o))
            off += n
        return Ws, bs

    def _chunk_params(self, stacked, v):
        """Chunk v's (Ws, bs) rows from either representation (static
        selection — value-identical to the lockstep dynamic pick)."""
        if self.packed:
            Ws, bs = self._unpack_wb(stacked)
        else:
            Ws, bs = stacked["W"], stacked["b"]
        return [w[v] for w in Ws], [b[v] for b in bs]

    def _acc(self, grads, v, gW_d, gb_d):
        """Accumulate one cell's per-slot gradient contributions — the
        lockstep ``.at[v].add`` per leaf, expressed against either
        representation (same elements added, others copied: bitwise)."""
        if not self.packed:
            gW, gb = grads
            return (
                tuple(a.at[v].add(d) for a, d in zip(gW, gW_d)),
                tuple(a.at[v].add(d) for a, d in zip(gb, gb_d)),
            )
        gvec, off = grads, 0
        V = self.V
        for d, (o, i) in zip(gW_d, self.dims):
            n = o * i
            gvec = gvec.at[off + v * n : off + (v + 1) * n].add(d.reshape(-1))
            off += V * n
        for d, (o, _) in zip(gb_d, self.dims):
            gvec = gvec.at[off + v * o : off + (v + 1) * o].add(d.reshape(-1))
            off += V * o
        return gvec

    def _stash_out(self, xs, masks):
        """The stash representation a forward returns: per-slot tuples
        (shard_map path — the specs are per-leaf) or ONE concatenated
        buffer per stash (packed path)."""
        if not self.packed:
            return xs, masks
        return (
            jnp.concatenate(xs, axis=1),
            jnp.concatenate(masks, axis=1),
        )

    def _split_stash(self, cat, widths):
        """Inverse of the packed concat: static column slices — the
        original per-slot tensors, bit for bit."""
        if not self.packed:
            return cat
        out, off = [], 0
        for w in widths:
            out.append(cat[:, off : off + w])
            off += w
        return tuple(out)

    @property
    def _xs_widths(self):
        _, _, xs_w, _ = E.tp_local_dims(self.dims, self.tp)
        return xs_w

    @property
    def _mask_widths(self):
        _, _, _, mask_w = E.tp_local_dims(self.dims, self.tp)
        return mask_w

    # -- builders -----------------------------------------------------------

    def _jit(self, s, per_device, in_specs, out_specs):
        if self.single:
            # one device per stage: plain jit over committed arrays (the
            # C++ fast-path dispatch); the per-device body is identical —
            # its singleton collectives were already elided by the
            # builders below, which is bitwise-exact (a 1-member psum is
            # the identity in the lockstep program too)
            return jax.jit(per_device)
        return jax.jit(
            shard_map(
                per_device,
                mesh=self.submeshes[s],
                in_specs=in_specs,
                out_specs=out_specs,
                check_vma=False,
            )
        )

    def _build_fwd(self, s, v, load, head, send, training, stash=True):
        """The stage forward. Training signatures (``mb`` is a traced
        index into the ONE per-batch device-resident x/y stack — value-
        identical to a static slice, and it keeps program count
        M-independent):

            load+head: (params, flags, x_full, y_full, mb, loss_acc)
            load:      (params, flags, x_full, mb)
            head:      (params, flags, x_in, y_full, mb, loss_acc)
            neither:   (params, flags, x_in)

        ``stash=False`` (the fwd tick of a recompute program) drops the
        stash outputs — the host keeps only the stage-INPUT handle and
        the matching recompute program re-materializes the residuals;
        the loss (head) and the relay payload are still produced here,
        so the traced per-element expressions are character-identical.

        Inference keeps the direct per-slot signature
        ``(params, flags, x_in)``."""
        tp, dims, prec = self.tp, self.dims, self.precision
        act = self.act
        W_rel, D_in, D_out, B = self.W_rel, self.D_in, self.D_out, self.B_global

        def per_device(*args):
            it = iter(args)
            stacked, flags = next(it), next(it)
            if training and load:
                x_full = next(it)
            else:
                x_in = next(it)
            if training and head:
                y_full = next(it)
            if training and (load or head):
                mb = next(it)
            if training and head:
                loss_acc = next(it)
            Ws, bs = self._chunk_params(stacked, v)
            active = flags["active"][v]
            relu = flags["relu"][v]
            residual = flags["residual"][v]
            head_mask = flags["head_mask"][v]
            if training and load:
                x = lax.dynamic_index_in_dim(x_full, mb, 0, keepdims=False)
            elif load:
                x = x_in
            else:
                x = E._fit(x_in, D_in)
            if tp > 1:
                tp_idx = lax.axis_index("tp")
                out, xs, masks = E._stage_fwd_tp(
                    Ws, bs, active, relu, dims, x, prec, tp_idx, tp,
                    act=act, residual=residual,
                )
            else:
                out, xs, masks = E._stage_fwd(
                    Ws, bs, active, relu, dims, x, prec,
                    act=act, residual=residual,
                )
            rets = []
            if send:
                rets.append(E._fit(out, W_rel))
            if training:
                if stash:
                    xs_o, masks_o = self._stash_out(xs, masks)
                    rets.append(xs_o)
                    rets.append(masks_o)
                if head:
                    y_mb = lax.dynamic_index_in_dim(
                        y_full, mb, 0, keepdims=False
                    )
                    p = ops.softmax(out, valid_mask=head_mask[None, :])
                    mb_loss = ops.mse_loss(p, y_mb, B)
                    if stash:
                        rets.append(out)  # the z stash (head-grad logits)
                    rets.append(loss_acc + mb_loss.reshape(1))
            elif head:
                rets.append(ops.softmax(out, valid_mask=head_mask[None, :]))
            return tuple(rets)

        in_specs = [self._param_specs, self._flag_specs]
        in_specs.append(P(None, "dp") if training and load else P("dp"))
        out_specs = []
        if send:
            out_specs.append(P("dp"))
        if training:
            if stash:
                out_specs.append(self._xs_specs)
                out_specs.append(self._mask_specs)
            if head:
                in_specs.append(P(None, "dp"))  # y_full
            if load or head:
                in_specs.append(P())  # mb index, replicated
            if head:
                in_specs.append(P("dp"))  # loss accumulator
                out_specs += [P("dp"), P("dp")] if stash else [P("dp")]
        elif head:
            out_specs.append(P("dp"))
        return self._jit(s, per_device, tuple(in_specs), tuple(out_specs))

    def _build_recompute(self, s, v, load, head):
        """The OP_RECOMPUTE stage program: re-run the stage forward from
        the kept INPUT (stage 0 reloads its microbatch from the device-
        resident batch stack — the HBM-reload exemption) and return the
        residual stashes the backward is about to consume. The forward
        expression is the shared builder's own (``_build_fwd`` traces
        the identical ``E._stage_fwd``/``_stage_fwd_tp`` call), so the
        stashes are bitwise the ones the stashed twin stored at the fwd
        tick. No relay (the output already traveled at the fwd tick) and
        no loss tally (counted once, at the fwd tick)."""
        tp, dims, prec = self.tp, self.dims, self.precision
        act = self.act
        D_in = self.D_in

        def per_device(*args):
            it = iter(args)
            stacked, flags = next(it), next(it)
            if load:
                x_full, mb = next(it), next(it)
                x = lax.dynamic_index_in_dim(x_full, mb, 0, keepdims=False)
            else:
                x = E._fit(next(it), D_in)
            Ws, bs = self._chunk_params(stacked, v)
            active = flags["active"][v]
            relu = flags["relu"][v]
            residual = flags["residual"][v]
            if tp > 1:
                out, xs, masks = E._stage_fwd_tp(
                    Ws, bs, active, relu, dims, x, prec,
                    lax.axis_index("tp"), tp, act=act, residual=residual,
                )
            else:
                out, xs, masks = E._stage_fwd(
                    Ws, bs, active, relu, dims, x, prec,
                    act=act, residual=residual,
                )
            xs_o, masks_o = self._stash_out(xs, masks)
            rets = [xs_o, masks_o]
            if head:
                rets.append(out)  # the z stash (head-grad logits)
            return tuple(rets)

        in_specs = [self._param_specs, self._flag_specs]
        if load:
            in_specs += [P(None, "dp"), P()]  # x stack, mb index
        else:
            in_specs.append(P("dp"))  # the kept stage-input handle
        out_specs = [self._xs_specs, self._mask_specs]
        if head:
            out_specs.append(P("dp"))
        return self._jit(s, per_device, tuple(in_specs), tuple(out_specs))

    def _build_bwd(self, s, v, head, send, split_input):
        """The combined backward, or — ``split_input=True`` — the split
        B-input half (dgrad chain + g_eff stash instead of the wgrad
        accumulation)."""
        tp, dims, prec = self.tp, self.dims, self.precision
        act = self.act
        W_rel, D_out, B = self.W_rel, self.D_out, self.B_global
        Wb = max(D_out, W_rel)

        def per_device(*args):
            if head:
                if split_input:
                    stacked, flags, masks, z, y_full, mb = args
                else:
                    stacked, flags, xs, masks, z, y_full, mb, grads = args
            else:
                if split_input:
                    stacked, flags, masks, g_relay = args
                else:
                    stacked, flags, xs, masks, g_relay, grads = args
            Ws, _ = self._chunk_params(stacked, v)
            active = flags["active"][v]
            relu = flags["relu"][v]
            residual = flags["residual"][v]
            head_mask = flags["head_mask"][v]
            masks = self._split_stash(masks, self._mask_widths)
            if not split_input:
                xs = self._split_stash(xs, self._xs_widths)
            if head:
                y_mb = lax.dynamic_index_in_dim(y_full, mb, 0, keepdims=False)
                g0 = ops.softmax_mse_head_grad(
                    z, y_mb, B, valid_mask=head_mask[None, :]
                )
                g_in = E._fit(g0, Wb)
            else:
                g_in = E._fit(g_relay, Wb)
            rets = []
            if split_input:
                if tp > 1:
                    dx, g_effs = E._stage_bwd_input_tp(
                        Ws, active, relu, dims, masks, g_in, prec,
                        lax.axis_index("tp"), tp,
                        act=act, residual=residual,
                    )
                else:
                    dx, g_effs = E._stage_bwd_input(
                        Ws, active, relu, dims, masks, g_in, prec,
                        act=act, residual=residual,
                    )
                if send:
                    rets.append(E._fit(dx, W_rel))
                if self.packed:
                    rets.append(jnp.concatenate(g_effs, axis=1))
                else:
                    rets.append(g_effs)
                return tuple(rets)
            if tp > 1:
                dx, gW_d, gb_d = E._stage_bwd_tp(
                    Ws, active, relu, dims, xs, masks, g_in, prec,
                    lax.axis_index("tp"), tp, act=act, residual=residual,
                )
            else:
                dx, gW_d, gb_d = E._stage_bwd(
                    Ws, active, relu, dims, xs, masks, g_in, prec,
                    act=act, residual=residual,
                )
            if send:
                rets.append(E._fit(dx, W_rel))
            rets.append(self._acc(grads, v, gW_d, gb_d))
            return tuple(rets)

        in_specs = [self._param_specs, self._flag_specs]
        if not split_input:
            in_specs.append(self._xs_specs)
        in_specs.append(self._mask_specs)
        if head:
            in_specs += [P("dp"), P(None, "dp"), P()]  # z stash, y stack, mb
        else:
            in_specs.append(P("dp"))  # relayed output-grad
        out_specs = [P("dp")] if send else []
        if split_input:
            out_specs.append(self._mask_specs)  # g_effs ride the mask repr
        else:
            grad_specs = (self._param_specs["W"], self._param_specs["b"])
            in_specs.append(grad_specs)
            out_specs.append(grad_specs)
        return self._jit(s, per_device, tuple(in_specs), tuple(out_specs))

    def _build_bwd_w(self, s, v):
        """The deferred B-weight half: wgrads from the two stashes,
        accumulated in tick-table (= B-input = combined) order."""
        tp, dims, prec = self.tp, self.dims, self.precision

        def per_device(flags, xs, g_effs, grads):
            active = flags["active"][v]
            xs = self._split_stash(xs, self._xs_widths)
            g_effs = self._split_stash(g_effs, self._mask_widths)
            if tp > 1:
                gW_d, gb_d = E._stage_bwd_weight_tp(
                    active, dims, xs, g_effs, prec, lax.axis_index("tp"), tp
                )
            else:
                gW_d, gb_d = E._stage_bwd_weight(active, dims, xs, g_effs, prec)
            return self._acc(grads, v, gW_d, gb_d)

        in_specs = (
            self._flag_specs, self._xs_specs, self._mask_specs,
            (self._param_specs["W"], self._param_specs["b"]),
        )
        out_specs = (self._param_specs["W"], self._param_specs["b"])
        return self._jit(s, per_device, in_specs, out_specs)

    def _build_update(self, s):
        """The per-stage optimizer tail: dp gradient psum (the lockstep
        anchor, per stage) + the on-device update of this stage's rows.
        On the singleton fast path the 1-member psum is elided (bitwise
        identity — the lockstep program's dp=1 psum is one too)."""
        opt = self.opt
        packed = self.packed

        def per_device(stacked, grads, state):
            if packed:
                # the flat-vector update: elementwise optimizer math on
                # the packed params/grads/state mirrors — per-element
                # expressions identical to the per-leaf apply (the
                # zero1 chunk update's established bitwise property)
                new_p, new_state = opt.apply(stacked, grads, state)
                return new_p, new_state
            gW, gb = grads
            g = {"W": lax.psum(gW, "dp"), "b": lax.psum(gb, "dp")}
            local = {"W": stacked["W"], "b": stacked["b"]}
            new_local, new_state = opt.apply(local, g, state)
            return new_local, new_state

        in_specs = (
            self._param_specs,
            (self._param_specs["W"], self._param_specs["b"]),
            self._state_specs,
        )
        out_specs = (self._param_specs, self._state_specs)
        return self._jit(s, per_device, in_specs, out_specs)

    def _build_loss_sync(self, s):
        single = self.single

        def per_device(loss_acc):
            if single:
                return loss_acc[0]
            return lax.psum(loss_acc[0], "dp")

        return self._jit(s, per_device, (P("dp"),), P())

    # -- packed-mode boundary programs (one dispatch per stage per run) -----

    def _build_pack(self, s):
        def per_device(stacked):
            return jnp.concatenate(
                [w.reshape(-1) for w in stacked["W"]]
                + [b.reshape(-1) for b in stacked["b"]]
            )

        return jax.jit(per_device)

    def _build_unpack(self, s):
        def per_device(pvec):
            Ws, bs = self._unpack_wb(pvec)
            return {"W": tuple(Ws), "b": tuple(bs)}

        return jax.jit(per_device)

    def _build_state_pack(self, s):
        opt = self.opt

        def per_device(state):
            from shallowspeed_tpu.optimizer import join_state, split_state

            parts, scalars = split_state(opt, state)
            packed = {
                k: jnp.concatenate(
                    [w.reshape(-1) for w in p["W"]]
                    + [b.reshape(-1) for b in p["b"]]
                )
                for k, p in parts.items()
            }
            return join_state(opt, packed, scalars)

        return jax.jit(per_device)

    def _build_state_unpack(self, s):
        opt = self.opt

        def per_device(state):
            from shallowspeed_tpu.optimizer import join_state, split_state

            parts, scalars = split_state(opt, state)
            unpacked = {}
            for k, vec in parts.items():
                Ws, bs = self._unpack_wb(vec)
                unpacked[k] = {"W": tuple(Ws), "b": tuple(bs)}
            return join_state(opt, unpacked, scalars)

        return jax.jit(per_device)

    # -- lookup -------------------------------------------------------------

    def get(self, s, role, variant=()):
        key = (s, role, variant)
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        if role == "fwd":
            v, load, head, send = variant
            fn = self._build_fwd(s, v, load, head, send, training=True)
        elif role == "fwd_ns":
            v, load, head, send = variant
            fn = self._build_fwd(
                s, v, load, head, send, training=True, stash=False
            )
        elif role == "recompute":
            v, load, head = variant
            fn = self._build_recompute(s, v, load, head)
        elif role == "infer_fwd":
            v, load, head, send = variant
            fn = self._build_fwd(s, v, load, head, send, training=False)
        elif role == "bwd":
            v, head, send = variant
            fn = self._build_bwd(s, v, head, send, split_input=False)
        elif role == "bwd_in":
            v, head, send = variant
            fn = self._build_bwd(s, v, head, send, split_input=True)
        elif role == "bwd_w":
            (v,) = variant
            fn = self._build_bwd_w(s, v)
        elif role == "update":
            fn = self._build_update(s)
        elif role == "loss_sync":
            fn = self._build_loss_sync(s)
        elif role == "pack":
            fn = self._build_pack(s)
        elif role == "unpack":
            fn = self._build_unpack(s)
        elif role == "state_pack":
            fn = self._build_state_pack(s)
        elif role == "state_unpack":
            fn = self._build_state_unpack(s)
        else:
            raise ValueError(f"unknown stage-program role {role!r}")
        self._fns[key] = fn
        return fn

    def label(self, s, role, variant=()):
        """Audit/AOT label for one stage program. The inference program
        set gets its own namespace — its pack programs are content-
        identical to the trainer's, but the session's audit dedup is
        label-keyed, and a shared label would skip the second runner's
        resolve-and-swap (leaving an un-audited jit wrapper on its
        dispatch path)."""
        kind = "mpmd" if self.prog.is_training else "mpmd_inf"
        tag = "".join(str(int(x)) for x in variant)
        return f"{kind}_s{s}_{role}" + (f"_{tag}" if tag else "")


def _resolve_program(programs, s, role, variant, args, expected, resolve):
    """The one resolve-and-swap step both runners' warm passes share:
    skip programs already swapped onto an executable, otherwise hand the
    jit wrapper to the session hook (audit/AOT) and install whatever it
    returns. Returns True when the hook ran."""
    key = (s, role, variant)
    fn = programs._fns.get(key)
    if fn is not None and not hasattr(fn, "lower"):
        return False  # already an executable
    compiled = resolve(
        programs.label(s, role, variant), role,
        programs.get(s, role, variant), args, expected,
    )
    if compiled is not None:
        programs._fns[key] = compiled
    return True


class MpmdTrainRunner:
    """The training-side MPMD runtime: per-stage programs + the
    tick-table-driven async host scheduler.

    ``run(stacked, flags, opt_state, X, Y)`` has the lockstep epoch
    program's exact signature and state contract — full-mesh stacked
    arrays in, full-mesh stacked arrays out (reassembled zero-copy from
    the per-stage views), so checkpoints, ``params()``, hot reloads and
    the serving engine are runtime-independent by construction.

    Construction runs the admission gate: ``analyze_program`` must prove
    the tick tables deadlock-free / send-recv-matched BEFORE any stage
    program is built or dispatched (``ProgramAnalysisError`` otherwise).
    """

    def __init__(self, mesh, spec, prog, mubatch_size, opt,
                 precision=ops.DEFAULT_PRECISION,
                 tracer=None, trace_batches=1):
        from shallowspeed_tpu.analysis import analyze_program

        # the admission gate: refuse a tampered/mislowered table BEFORE
        # anything compiles or dispatches (the happens-before proof is
        # exactly what asynchronous dispatch relies on)
        self.admission = analyze_program(prog, program="mpmd_train")
        if not prog.is_training:
            raise ValueError("MpmdTrainRunner needs a training TickProgram")
        self.mesh = mesh
        self.spec = spec
        self.prog = prog
        self.P = prog.num_stages
        self.V = prog.num_chunks
        self.S = spec.n_stages
        self.dp = mesh.shape["dp"]
        self.tp = mesh_tp(mesh)
        self.opt = opt
        self.split = bool(prog.backward_split)
        self.programs = _StagePrograms(
            mesh, spec, prog, mubatch_size, opt, precision
        )
        self.cells = stage_cells(prog)
        self.M = prog.num_micro_batches
        self.mb_sz = mubatch_size
        self.D_in = self.programs.D_in
        self.D_out = self.programs.D_out
        self._tracer = tracer
        self._trace_batches = int(trace_batches)
        self.dispatch_count = 0  # stage-program dispatches issued
        self.relay_count = 0  # device-to-device transfers issued
        # cached zero gradient accumulators / loss tally (never mutated:
        # every dispatch is functional, so one set serves every batch)
        subs = self.programs.submeshes
        dims = self.programs.dims
        single = self.programs.single
        devs = self.programs.stage_device
        # per-stage zero gradient accumulators, in the programs' grads
        # representation: one packed vector (singleton fast path) or the
        # ((gW leaves), (gb leaves)) pair (shard_map path). Never
        # mutated — every dispatch is functional, one set serves every
        # batch (0.0 + d == the lockstep .at[v].add from zeros, bitwise)
        self._zero_g = []
        pspecs = self.programs._param_specs
        for s in range(self.P):
            if self.programs.packed:
                self._zero_g.append(
                    jax.device_put(
                        np.zeros((self.programs.plen,), np.float32), devs[s]
                    )
                )
                continue

            def place(a, sp, s=s):
                return jax.device_put(a, NamedSharding(subs[s], sp))

            self._zero_g.append(
                (
                    tuple(
                        place(np.zeros((self.V, o, i), np.float32), sp)
                        for (o, i), sp in zip(dims, pspecs["W"])
                    ),
                    tuple(
                        place(np.zeros((self.V, o), np.float32), sp)
                        for (o, _), sp in zip(dims, pspecs["b"])
                    ),
                )
            )
        self._zero_loss = jax.device_put(
            np.zeros((self.dp,), np.float32),
            devs[self.P - 1] if single
            else NamedSharding(subs[self.P - 1], P("dp")),
        )
        # the per-batch x/y stacks ride ONE device_put each; load/head
        # cells index them with a pre-staged traced scalar (one device
        # array per microbatch id per endpoint stage — M-independent
        # program count, two host->device transfers per batch)
        self._x_sharding = (
            devs[0] if single else NamedSharding(subs[0], P(None, "dp"))
        )
        self._y_sharding = (
            devs[self.P - 1] if single
            else NamedSharding(subs[self.P - 1], P(None, "dp"))
        )
        self._mb_idx = {}
        for s in (0, self.P - 1):
            sh = devs[s] if single else NamedSharding(subs[s], P())
            self._mb_idx[s] = [
                jax.device_put(np.int32(m), sh) for m in range(self.M)
            ]

    # -- one batch ----------------------------------------------------------

    def _put_batch(self, xb, yb):
        """Host batch -> ONE (M, dp*mb, width) device stack for each
        endpoint: x on stage 0's sub-mesh, y on the head stage's, rows
        sharded over dp with rank r's microbatch rows exactly the
        lockstep shard's. Widths are padded to the executor's D_in/D_out
        here (host-side, exact zeros) — the lockstep program applies the
        identical ``_fit`` on device."""

        def stack(a, w, sharding):
            a = np.asarray(a, np.float32).reshape(a.shape[0], -1)
            if a.shape[-1] != w:
                a = np.pad(a, ((0, 0), (0, w - a.shape[-1])))
            dp, M, mb = self.dp, self.M, self.mb_sz
            a = np.ascontiguousarray(
                a.reshape(dp, M, mb, w).transpose(1, 0, 2, 3)
            ).reshape(M, dp * mb, w)
            return jax.device_put(a, sharding)

        return (
            stack(xb, self.D_in, self._x_sharding),
            stack(yb, self.D_out, self._y_sharding),
        )

    def _span(self, spans, name, t0, **fields):
        if spans is not None:
            spans.append((name, t0, time.perf_counter(), fields))

    def run_batch(self, params, flags, state, xb, yb, spans=None):
        """Dispatch one global batch through the per-stage streams; pure
        issue — nothing here blocks on device execution. Returns the new
        per-stage (params, state) plus the un-synced loss handle."""
        progs = self.programs
        rec = progs.rec
        x_full, y_full = self._put_batch(xb, yb)
        mail = {}
        stash = [dict() for _ in range(self.P)]
        gstash = [dict() for _ in range(self.P)]
        # recompute programs: the stage-INPUT handles kept from the fwd
        # tick (stage 0 exempt — its recompute reloads from the batch
        # stack), freed by the OP_RECOMPUTE dispatch that consumes them
        xin = [dict() for _ in range(self.P)]
        grads = list(self._zero_g)
        loss_acc = self._zero_loss
        subs = progs.submeshes
        single = progs.single
        idx = self._mb_idx

        def relay(direction, src, payload, key):
            dst = (src + 1) % self.P if direction == "fwd" else (src - 1) % self.P
            v, mb = key
            if direction == "fwd" and src == self.P - 1:
                v += 1
            elif direction == "bwd" and src == 0:
                v -= 1
            t0 = time.perf_counter()
            moved = jax.device_put(
                payload,
                progs.stage_device[dst] if single
                else NamedSharding(subs[dst], P("dp")),
            )
            self.relay_count += 1
            self._span(
                spans, "stage.relay", t0, stage=src, to_stage=dst,
                direction=direction, mb=mb,
            )
            mail[(direction, dst, (v, mb))] = moved

        for row in self.cells:
            for c in row:
                s, v, mb = c["s"], c["v"], c["mb"]
                key = (v, mb)
                t0 = time.perf_counter()
                if c["op"] == OP_FWD:
                    fn = c.get("_fn")
                    if fn is None:
                        fn = c["_fn"] = progs.get(
                            s, "fwd_ns" if rec else "fwd",
                            (v, c["load"], c["head"], c["send_fwd"]),
                        )
                    args = (params[s], flags[s])
                    if c["load"]:
                        args += (x_full,)
                    else:
                        x_in = mail.pop(("fwd", s, key))
                        if rec:
                            xin[s][key] = x_in  # kept for the recompute
                        args += (x_in,)
                    if c["head"]:
                        args += (y_full, idx[s][mb], loss_acc)
                    elif c["load"]:
                        args += (idx[s][mb],)
                    outs = fn(*args)
                    i = 1 if c["send_fwd"] else 0
                    if rec:
                        if c["head"]:
                            loss_acc = outs[i]
                    elif c["head"]:
                        stash[s][key] = (outs[i], outs[i + 1], outs[i + 2])
                        loss_acc = outs[i + 3]
                    else:
                        stash[s][key] = (outs[i], outs[i + 1], None)
                    self.dispatch_count += 1
                    self._span(
                        spans, "stage.dispatch", t0, stage=s, op="fwd", mb=mb
                    )
                    if c["send_fwd"]:
                        relay("fwd", s, outs[0], key)
                elif c["op"] == OP_RECOMPUTE:
                    fn = c.get("_fn")
                    if fn is None:
                        fn = c["_fn"] = progs.get(
                            s, "recompute", (v, c["load"], c["head"])
                        )
                    args = (params[s], flags[s])
                    if c["load"]:
                        args += (x_full, idx[s][mb])
                    else:
                        args += (xin[s].pop(key),)
                    outs = fn(*args)
                    stash[s][key] = (
                        outs[0], outs[1], outs[2] if c["head"] else None
                    )
                    self.dispatch_count += 1
                    self._span(
                        spans, "stage.dispatch", t0, stage=s, op="recompute",
                        mb=mb,
                    )
                elif c["op"] == OP_BWD and self.split:
                    xs, masks, z = stash[s][key]  # peek (B-weight frees)
                    fn = c.get("_fn")
                    if fn is None:
                        fn = c["_fn"] = progs.get(
                            s, "bwd_in", (v, c["head"], c["send_bwd"])
                        )
                    if c["head"]:
                        outs = fn(
                            params[s], flags[s], masks, z, y_full, idx[s][mb]
                        )
                    else:
                        g_in = mail.pop(("bwd", s, key))
                        outs = fn(params[s], flags[s], masks, g_in)
                    gstash[s][key] = outs[-1]
                    self.dispatch_count += 1
                    self._span(
                        spans, "stage.dispatch", t0, stage=s, op="bwd_in", mb=mb
                    )
                    if c["send_bwd"]:
                        relay("bwd", s, outs[0], key)
                elif c["op"] == OP_BWD:
                    xs, masks, z = stash[s].pop(key)
                    fn = c.get("_fn")
                    if fn is None:
                        fn = c["_fn"] = progs.get(
                            s, "bwd", (v, c["head"], c["send_bwd"])
                        )
                    if c["head"]:
                        outs = fn(
                            params[s], flags[s], xs, masks, z, y_full,
                            idx[s][mb], grads[s],
                        )
                    else:
                        g_in = mail.pop(("bwd", s, key))
                        outs = fn(
                            params[s], flags[s], xs, masks, g_in, grads[s]
                        )
                    grads[s] = outs[-1]
                    self.dispatch_count += 1
                    self._span(
                        spans, "stage.dispatch", t0, stage=s, op="bwd", mb=mb
                    )
                    if c["send_bwd"]:
                        relay("bwd", s, outs[0], key)
                else:  # OP_BWD_W
                    xs, masks, _ = stash[s].pop(key)
                    g_effs = gstash[s].pop(key)
                    fn = c.get("_fn")
                    if fn is None:
                        fn = c["_fn"] = progs.get(s, "bwd_w", (v,))
                    grads[s] = fn(flags[s], xs, g_effs, grads[s])
                    self.dispatch_count += 1
                    self._span(
                        spans, "stage.dispatch", t0, stage=s, op="bwd_w", mb=mb
                    )

        assert not mail, "undelivered relay payloads (tables violated)"
        assert not any(xin), "unconsumed recompute input handles"
        # the per-stage optimizer tail: dp psum + update, one dispatch per
        # stage (the lockstep program's exact reduction and update math,
        # stage-local)
        new_params, new_state = [], []
        for s in range(self.P):
            t0 = time.perf_counter()
            p_new, st_new = progs.get(s, "update")(
                params[s], grads[s], state[s]
            )
            self.dispatch_count += 1
            self._span(spans, "stage.dispatch", t0, stage=s, op="update")
            new_params.append(p_new)
            new_state.append(st_new)
        loss = progs.get(self.P - 1, "loss_sync")(loss_acc)
        self.dispatch_count += 1
        return new_params, new_state, loss

    def run(self, stacked, flags, opt_state, X, Y, trace_id=None):
        """The epoch-shaped entry point (lockstep signature): loop the
        batches of ``X``/``Y`` (host arrays, (nb, B, ...)) through
        ``run_batch`` and reassemble the full-mesh state. Returns
        ``(stacked, opt_state, mean_loss)``."""
        subs = self.programs.submeshes
        progs = self.programs
        params = [
            stage_param_view(stacked, s, subs[s], self.tp, self.V)
            for s in range(self.P)
        ]
        flag_views = [
            stage_flags_view(flags, s, subs[s], self.V) for s in range(self.P)
        ]
        states = [
            stage_state_view(self.opt, opt_state, s, subs[s], self.tp, self.V)
            for s in range(self.P)
        ]
        stateful = not (isinstance(states[0], tuple) and states[0] == ())
        if progs.packed:
            # enter the packed representation once per run call (one
            # pack dispatch per stage; the inverse pair runs at the end
            # — the whole batch loop stays flat-buffer)
            params = [
                progs.get(s, "pack")(params[s]) for s in range(self.P)
            ]
            if stateful:
                states = [
                    progs.get(s, "state_pack")(states[s])
                    for s in range(self.P)
                ]
        losses = []
        nb = len(X)
        for k in range(nb):
            spans = None
            if (
                self._tracer is not None
                and self._tracer.enabled
                and k < self._trace_batches
            ):
                spans = []
            params, states, loss = self.run_batch(
                params, flag_views, states, X[k], Y[k], spans=spans
            )
            if spans is not None:
                # one chain per traced batch; the final update span is
                # the terminal so the chain is COMPLETE and the Tracing
                # attribution can aggregate it (the chain's timeline is
                # the HOST ISSUE window of the batch — where MPMD
                # dispatch wall goes, the number judged against the
                # lockstep op-issue roofline)
                tid = trace_id or "mpmd"
                for i, (name, t0, t1, fields) in enumerate(spans):
                    last = i == len(spans) - 1
                    self._tracer.span(
                        name, f"{tid}-b{k}", t0, t1, terminal=last,
                        **(dict(fields, verdict="ok") if last else fields),
                    )
            losses.append(loss)
        mean_loss = float(np.mean([float(v) for v in losses])) if nb else 0.0
        if progs.packed:
            params = [
                progs.get(s, "unpack")(params[s]) for s in range(self.P)
            ]
            if stateful:
                states = [
                    progs.get(s, "state_unpack")(states[s])
                    for s in range(self.P)
                ]
        new_stacked = full_param_from_stage(params, self.mesh, self.S, self.tp)
        new_state = full_state_from_stage(
            self.opt, states, self.mesh, self.S, self.tp
        )
        # gate on FULL completion before returning: the loss only
        # depends on the head stage's chain, so without this the
        # caller's float(loss) would close its timing window while the
        # other stages' final updates still execute (the lockstep
        # epoch's loss output gates everything; the timing contract
        # must match across runtimes)
        jax.block_until_ready(jax.tree.leaves(new_stacked))
        return new_stacked, new_state, np.float32(mean_loss)

    # -- warm / audit -------------------------------------------------------

    def planned_programs(self):
        """Every (stage, role, variant) the plan can dispatch — the
        enumeration the warm/audit pass compiles, so a warm start covers
        exactly the dispatch surface."""
        seen = {}
        rec = self.programs.rec
        for row in self.cells:
            for c in row:
                s, v = c["s"], c["v"]
                if c["op"] == OP_FWD:
                    role = "fwd_ns" if rec else "fwd"
                    seen[(s, role, (v, c["load"], c["head"], c["send_fwd"]))] = c
                elif c["op"] == OP_RECOMPUTE:
                    seen[(s, "recompute", (v, c["load"], c["head"]))] = c
                elif c["op"] == OP_BWD and self.split:
                    seen[(s, "bwd_in", (v, c["head"], c["send_bwd"]))] = c
                elif c["op"] == OP_BWD:
                    seen[(s, "bwd", (v, c["head"], c["send_bwd"]))] = c
                else:
                    seen[(s, "bwd_w", (v,))] = c
        keys = list(seen)
        for s in range(self.P):
            keys.append((s, "update", ()))
        keys.append((self.P - 1, "loss_sync", ()))
        if self.programs.packed:
            from shallowspeed_tpu.optimizer import is_stateless

            roles = ["pack", "unpack"]
            if not is_stateless(self.opt):
                roles += ["state_pack", "state_unpack"]
            for s in range(self.P):
                for r in roles:
                    keys.append((s, r, ()))
        return keys

    def example_args(self, s, role, variant, stacked, flags, opt_state,
                     cache=None):
        """Shape-correct example arguments for one planned program (the
        lower/compile inputs of the warm/audit/AOT pass). ``cache`` (a
        dict the warm loop owns) memoizes the per-stage views and pack
        dispatches across the ~6 planned programs of each stage."""
        subs = self.programs.submeshes
        progs = self.programs
        # the pack-boundary roles take the RAW views (building the shared
        # cache entry would dispatch the very programs being resolved —
        # warm() resolves these two first for exactly that reason)
        if role == "pack":
            return (stage_param_view(stacked, s, subs[s], self.tp, self.V),)
        if role == "state_pack":
            return (
                stage_state_view(
                    self.opt, opt_state, s, subs[s], self.tp, self.V
                ),
            )
        entry = cache.get(s) if cache is not None else None
        if entry is None:
            pv_leaves = stage_param_view(stacked, s, subs[s], self.tp, self.V)
            pv = (
                progs.get(s, "pack")(pv_leaves) if progs.packed else pv_leaves
            )
            fv = stage_flags_view(flags, s, subs[s], self.V)
            st = stage_state_view(
                self.opt, opt_state, s, subs[s], self.tp, self.V
            )
            if progs.packed and not (isinstance(st, tuple) and st == ()):
                st = progs.get(s, "state_pack")(st)
            entry = (pv, fv, st)
            if cache is not None:
                cache[s] = entry
        pv, fv, st_packed = entry
        mb_rows = self.dp * self.mb_sz
        # on the singleton fast path every struct carries the stage
        # device's sharding: the lowered executable must expect EXACTLY
        # the committed single-device arrays dispatch will pass (the
        # shard_map path infers placement from its in_specs instead)
        sds = None
        if self.programs.single:
            from jax.sharding import SingleDeviceSharding

            sds = SingleDeviceSharding(self.programs.stage_device[s])

        def struct(shape, dtype=jnp.float32):
            if sds is not None:
                return jax.ShapeDtypeStruct(shape, dtype, sharding=sds)
            return jax.ShapeDtypeStruct(shape, dtype)

        f32 = struct

        def stash_structs():
            _, _, xs_w, mask_w = E.tp_local_dims(self.programs.dims, self.tp)
            mdt = self.programs.mask_dtype
            if progs.packed:  # one concatenated buffer per stash
                return (
                    f32((mb_rows, sum(xs_w))),
                    struct((mb_rows, sum(mask_w)), mdt),
                )
            # global widths: tp-local widths x tp where the spec shards
            xs = tuple(
                f32((mb_rows, w * (self.tp if l % 2 else 1)))
                for l, w in enumerate(xs_w)
            )
            masks = tuple(
                struct((mb_rows, w * (1 if l % 2 else self.tp)), mdt)
                for l, w in enumerate(mask_w)
            )
            return xs, masks

        mb_i = (
            self._mb_idx[s][0] if s in self._mb_idx
            else jax.ShapeDtypeStruct((), jnp.int32)
        )
        if role in ("fwd", "fwd_ns", "infer_fwd"):
            training = role != "infer_fwd"
            v, load, head, send = variant
            if training and load:
                args = (pv, fv, f32((self.M, mb_rows, self.D_in)))
            elif load:
                args = (pv, fv, f32((mb_rows, self.spec.sizes[0])))
            else:
                args = (pv, fv, f32((mb_rows, self.programs.W_rel)))
            if training and head:
                args += (
                    f32((self.M, mb_rows, self.D_out)), mb_i, self._zero_loss,
                )
            elif training and load:
                args += (mb_i,)
            return args
        if role == "recompute":
            v, load, head = variant
            if load:
                return (pv, fv, f32((self.M, mb_rows, self.D_in)), mb_i)
            return (pv, fv, f32((mb_rows, self.programs.W_rel)))
        if role in ("bwd", "bwd_in"):
            v, head, send = variant
            xs, masks = stash_structs()
            args = (pv, fv) + (() if role == "bwd_in" else (xs,)) + (masks,)
            if head:
                args += (
                    f32((mb_rows, self.D_out)),
                    f32((self.M, mb_rows, self.D_out)),
                    mb_i,
                )
            else:
                args += (f32((mb_rows, self.programs.W_rel)),)
            if role == "bwd":
                args += (self._zero_g[s],)
            return args
        if role == "bwd_w":
            xs, masks = stash_structs()
            if progs.packed:
                g_effs = f32(masks.shape)
            else:
                g_effs = tuple(f32(m.shape) for m in masks)
            return (fv, xs, g_effs, self._zero_g[s])
        if role in ("update", "state_unpack", "unpack"):
            if role == "unpack":
                return (pv,)
            if role == "state_unpack":
                return (st_packed,)
            return (pv, self._zero_g[s], st_packed)
        if role == "loss_sync":
            return (f32((self.dp,)),)
        raise ValueError(f"unknown role {role!r}")

    def warm(self, stacked, flags, opt_state, resolve):
        """Compile (or AOT-load) + audit every planned stage program and
        swap the dispatch path onto the resolved executables. ``resolve``
        is the session's hook ``(label, role, jit_fn, args, expected) ->
        compiled`` — it owns the AOT cache, the per-stage census and the
        donation-safety proof. Returns the number of programs resolved."""
        n = 0
        view_cache = {}
        planned = sorted(
            self.planned_programs(),
            # pack/state_pack first: every other role's example args are
            # built THROUGH them, and a warm start must not compile them
            # implicitly via the jit wrapper
            key=lambda k: 0 if k[1] in ("pack", "state_pack") else 1,
        )
        for s, role, variant in planned:
            args = self.example_args(
                s, role, variant, stacked, flags, opt_state, cache=view_cache
            )
            # a non-relaying backward's contract drops the dead dx psum
            sends = variant[2] if role in ("bwd", "bwd_in") else True
            expected = expected_stage_comms(
                role, self.spec, self.dp, self.tp, sends=sends
            )
            if _resolve_program(
                self.programs, s, role, variant, args, expected, resolve
            ):
                n += 1
        # drop any per-cell dispatch caches so the next batch picks up
        # the resolved executables
        for row in self.cells:
            for c in row:
                c.pop("_fn", None)
        return n


class MpmdInferenceRunner:
    """Forward-only MPMD streaming: per-stage inference programs fed by
    the lowered inference tick tables, one microbatch SLOT per stream
    entry. ``submit()`` issues a slot's whole stage chain asynchronously
    and returns a handle; consecutive submits pipeline — slot k enters
    stage 0 while slot k-1 occupies stage 1 — so a response is bound by
    its own chain, not by the rung program's makespan. Admission-gated
    like the trainer (``analyze_program`` before anything dispatches)."""

    def __init__(self, mesh, spec, prog, mubatch_size,
                 precision=ops.DEFAULT_PRECISION):
        from shallowspeed_tpu.analysis import analyze_program

        self.admission = analyze_program(prog, program="mpmd_infer")
        if prog.is_training:
            raise ValueError("MpmdInferenceRunner needs an inference program")
        self.mesh = mesh
        self.spec = spec
        self.prog = prog
        self.P = prog.num_stages
        self.V = prog.num_chunks
        self.dp = mesh.shape["dp"]
        self.tp = mesh_tp(mesh)
        self.programs = _StagePrograms(
            mesh, spec, prog, mubatch_size, None, precision
        )
        self.mb_sz = mubatch_size
        self.dispatch_count = 0
        # ONE slot's per-stage chain, from the tables: the per-slot cell
        # sequence is identical for every slot (the inference schedule is
        # a straight pipeline), so the M-slot table collapses to the
        # chain of stage hops for slot 0
        chain = []
        for row in stage_cells(prog):
            for c in row:
                if c["mb"] == 0:
                    chain.append(c)
        self.chain = chain
        self._x_sharding = NamedSharding(
            self.programs.submeshes[0], P("dp")
        )

    def submit(self, params, flag_views, x_slot):
        """Issue one slot (``(slot_rows, in_dim)`` host rows) through the
        stage chain; returns the async head-output array (materialize
        with ``np.asarray``). Nothing blocks here."""
        subs = self.programs.submeshes
        single = self.programs.single
        x = jax.device_put(
            np.ascontiguousarray(np.asarray(x_slot, np.float32)),
            self.programs.stage_device[0] if single else self._x_sharding,
        )
        preds = None
        for c in self.chain:
            s, v = c["s"], c["v"]
            fn = c.get("_fn")
            if fn is None:
                fn = c["_fn"] = self.programs.get(
                    s, "infer_fwd", (v, c["load"], c["head"], c["send_fwd"])
                )
            outs = fn(params[s], flag_views[s], x)
            self.dispatch_count += 1
            if c["head"]:
                preds = outs[-1]
            if c["send_fwd"]:
                dst = (s + 1) % self.P
                x = jax.device_put(
                    outs[0],
                    self.programs.stage_device[dst] if single
                    else NamedSharding(subs[dst], P("dp")),
                )
        return preds

    def warm(self, stacked, flags, resolve):
        """Resolve (audit/AOT) every program this chain can dispatch —
        the pack boundary first, then each chain cell — and swap the
        dispatch path onto the executables; the serving-side mirror of
        ``MpmdTrainRunner.warm``. Returns the number resolved."""
        n = 0
        if self.programs.packed:
            # pack first: views() dispatches it, and a warm start must
            # not compile it implicitly through the jit wrapper
            for s in range(self.P):
                leaves = stage_param_view(
                    stacked, s, self.programs.submeshes[s], self.tp, self.V
                )
                if _resolve_program(
                    self.programs, s, "pack", (), (leaves,),
                    expected_stage_comms("pack", self.spec, self.dp, self.tp),
                    resolve,
                ):
                    n += 1
        params, fls = self.views(stacked, flags)
        for c in self.chain:
            s, v = c["s"], c["v"]
            variant = (v, c["load"], c["head"], c["send_fwd"])
            if _resolve_program(
                self.programs, s, "infer_fwd", variant,
                self.example_args(c, params, fls),
                expected_stage_comms(
                    "infer_fwd", self.spec, self.dp, self.tp
                ),
                resolve,
            ):
                c.pop("_fn", None)
                n += 1
        return n

    def example_args(self, c, params, flag_views):
        """Shape/sharding-correct lower() arguments for one chain cell's
        program (the warm/audit/AOT pass)."""
        s = c["s"]
        width = self.spec.sizes[0] if c["load"] else self.programs.W_rel
        shape = (self.dp * self.mb_sz, width)
        if self.programs.single:
            from jax.sharding import SingleDeviceSharding

            x = jax.ShapeDtypeStruct(
                shape, jnp.float32,
                sharding=SingleDeviceSharding(self.programs.stage_device[s]),
            )
        else:
            x = jax.ShapeDtypeStruct(shape, jnp.float32)
        return (params[s], flag_views[s], x)

    def views(self, stacked, flags):
        """Per-stage param/flag views of the session's full-mesh arrays
        (zero-copy, plus one pack dispatch per stage in packed mode;
        rebuild after a hot weight reload)."""
        subs = self.programs.submeshes
        params = [
            stage_param_view(stacked, s, subs[s], self.tp, self.V)
            for s in range(self.P)
        ]
        if self.programs.packed:
            params = [
                self.programs.get(s, "pack")(params[s])
                for s in range(self.P)
            ]
        fls = [stage_flags_view(flags, s, subs[s], self.V) for s in range(self.P)]
        return params, fls
