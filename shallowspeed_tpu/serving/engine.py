"""Inference serving engine: request queue + continuous batching into slots.

The ROADMAP's "millions of users" north star is a latency problem — requests
arrive one at a time and must be packed into the executor's fixed microbatch
slots on the fly, the same on-the-fly packing torchgpipe applies to training
microbatches (arXiv 2004.09910). ``ServingEngine`` owns that loop on top of
``TrainingSession``'s cached inference programs:

- **queue**: deadline-tagged requests of variable row counts, FIFO (packing
  is order-preserving so responses complete in arrival order — the
  determinism the bitwise-parity contract needs; deadlines tag accounting,
  they do not reorder);
- **continuous batching**: each ``step()`` packs the queue's head into the
  next dispatch — whole ``slot_rows``-row microbatch slots per request
  (requests never share a slot), up to ``max_slots`` slots, the slot count
  then rounded up the session's fixed ladder so at most ``len(ladder)``
  inference programs are ever compiled;
- **bitwise parity**: a slot's compute has one fixed shape in every rung
  program, so each response is bitwise-equal to a direct
  ``session.predict()`` of the same rows (measured, and asserted by
  ``make serve-smoke`` under seeded Poisson load);
- **steady-state weights**: every dispatch reads the SAME device-resident
  stacked params the session holds — weights are staged once at session
  construction and never re-transferred per request. Donation is
  deliberately NOT used here: the params are reused by the very next
  dispatch (and by training), so donating their buffers would be a
  use-after-free, not an optimization — steady-state residency comes from
  holding the arrays, the executor aliases them read-only. Since PR 13
  that is a PROVEN property, not a convention: every rung program's
  compiled HLO passes the dispatch-safety check
  (``program_audit.verify_dispatch_safety`` refuses any
  ``input_output_alias`` on the serving path BEFORE a request is
  served — docs/static-analysis.md), and the ``donate_argnums``
  whitelist lint rule keeps donation out of this module at the source
  level;
- **accounting**: per-request enqueue -> dispatch -> complete timestamps,
  queue wait, padding waste, and a bounded queue-depth ring (the flight-
  recorder pattern) — emitted as schema-v5 ``request`` records plus a
  ``serving`` summary and a ``serving.queue_depth`` gauge when a metrics
  recorder is attached (docs/serving.md, docs/observability.md). The
  engine itself retains only SCALAR samples (latencies, waits, deadline
  tags) between ``reset_stats()`` calls — completed ``Request`` objects,
  with their input payloads and result arrays, are handed back to the
  caller by ``step()``/``drain()`` and never kept, so a long-lived engine
  does not grow with the traffic it has served.

Graceful degradation (docs/robustness.md "Serving faults") — every
submitted request reaches exactly one TERMINAL verdict, never silence:

- **dispatch recovery**: ``step()`` wraps the session dispatch; a raised
  exception re-queues the popped batch at the queue HEAD in its original
  order (packing stays order-preserving, so the bitwise-parity contract
  holds across retries) under a bounded per-request ``retry.RetryPolicy``
  budget — exhausted requests complete with verdict ``"error"``;
- **deadline shedding**: at pack time, a head request whose deadline
  already passed — or provably cannot be met even dispatching NOW (the
  analytical latency floor exceeds the time remaining) — completes as
  ``"expired"`` before costing a slot; ``shed_on_submit=True`` applies
  the same estimate at admission (queue slots ahead x the costmodel
  floor) as optional backpressure;
- **health-gated responses**: every dispatch's predictions are
  finiteness-checked per request BEFORE unpacking; a non-finite slice
  completes as ``"unhealthy"`` with no result — poisoned weights never
  serve a response with verdict ``"ok"``;
- **breaker**: ``breaker_threshold`` CONSECUTIVE failed dispatches
  (exceptions or unhealthy predictions) flip the engine into a degraded
  state that refuses admission (verdict ``"dropped"``, reason
  ``"degraded"``), emits a schema-v6 ``serving_health`` record, and —
  when ``reload_dir`` is configured — triggers a hot weight reload;
- **hot weight reload**: ``reload()`` swaps verified checkpoint weights
  between dispatches without touching the queue
  (``TrainingSession.load_weights`` — same shapes, so every cached rung
  program survives with ZERO recompiles); ``watch_reload()`` polls the
  directory for snapshots newer than the one served
  (``checkpoint.find_newer_good``). A successful reload closes the
  breaker;
- **chaos**: a ``faults=`` plan (the PR6 grammar, ``@dispatch=N``
  anchors) injects ``die``/``slow``/``nan``/``error`` faults into the
  dispatch loop deterministically — ``bench_serving``'s chaos soak and
  ``make chaos-smoke`` drive it;
- **dispatch floor**: ``dispatch_floor_ms`` pads every successful
  dispatch up to a fixed service-time floor (the worker sleeps out the
  remainder). On accelerators the model forward provides this floor
  naturally; on a shared/single-core CPU testbed the knob makes a
  replica's capacity slot-concurrency-bound (``max_slots / floor``)
  instead of bound by the host core, so a fleet's capacity scales with
  replica count and a measured single-engine knee transfers to the
  fleet path — what ``bench_replay``'s capacity scoreboard needs to
  judge horizontal scaling honestly (the knob is recorded as a caveat
  in its committed artifact).

Clock-domain contract (docs/observability.md § Tracing): every request
timestamp this engine records — ``enqueue_t``/``dispatch_t``/
``complete_t`` on the ``Request``, the queue-depth ring samples, the
schema-v5 ``request`` record fields — is a value of THIS process's
``engine.clock`` (``time.perf_counter`` unless injected), so durations
are exact and timestamps from two engines are NOT comparable. Standalone,
that process is the one the caller lives in ("parent" clock); inside a
fleet worker it is the WORKER's clock, and only the fleet handshake's
recorded per-replica offset estimate places these values on the parent
timeline (``observability.tracing``).

Tracing (schema v10): with a metrics recorder attached, every request
leaves a span chain — ``worker.queue`` (admission → dispatch pop),
``pack``, ``dispatch``, ``verify``, and (standalone engines only) the
terminal ``ack`` — keyed by a ``trace_id`` minted at submit, or carried
in from the fleet router with the parent span id so chains stay linked
across the pipe. Spans are emitted CLOSED, at the request's completion:
a killed process leaves exactly the spans it finished.

Live telemetry (schema v11, docs/observability.md § Live telemetry &
alerting): the engine owns a ``slo.LiveTelemetry`` sensor — every
terminal verdict, queue-depth sample and health event feeds tumbling
rollup windows (closed on ENGINE-CLOCK timestamps, emitted as
``rollup`` records) and the SLO rule set (``breaker_open`` event rule,
error burn rate, p99-vs-SLO and knee-proximity threshold rules when
the evidence exists), whose firing→resolved transitions emit ``alert``
records and call any attached ``AlertSink``. ``status()`` is the live
snapshot surface ``observability.watch`` and ROADMAP item 4's
autoscaler read.
"""

import time
from collections import deque

import numpy as np

from shallowspeed_tpu import faults as F
from shallowspeed_tpu import retry as R
from shallowspeed_tpu.checkpoint import (
    CheckpointError,
    find_latest_good,
    find_newer_good,
)
from shallowspeed_tpu.observability import NullMetrics
from shallowspeed_tpu.observability.slo import LiveTelemetry
from shallowspeed_tpu.observability.stats import ThroughputWindow, percentile
from shallowspeed_tpu.observability.tracing import Tracer
from shallowspeed_tpu.serving import slots as serving_slots

# terminal request verdicts — every submitted request ends on exactly one
# (the state machine documented in docs/robustness.md "Serving faults")
TERMINAL_VERDICTS = ("ok", "dropped", "expired", "error", "unhealthy")


class Request:
    """One queued inference request and its full accounting."""

    __slots__ = (
        "id",
        "x",
        "rows",
        "slots",
        "deadline_ms",
        "enqueue_t",
        "dispatch_t",
        "complete_t",
        "result",
        "verdict",
        "attempts",
        "trace_id",
        "trace_parent",
        "last_span_id",
    )

    def __init__(self, req_id, x, slots, deadline_ms, enqueue_t):
        self.id = req_id
        self.x = x
        self.rows = int(x.shape[0])
        self.slots = int(slots)
        self.deadline_ms = deadline_ms
        self.enqueue_t = enqueue_t
        self.dispatch_t = None
        self.complete_t = None
        self.result = None  # (rows, out_dim) softmax probabilities; only "ok"
        # queued -> ok | dropped | expired | error | unhealthy (terminal)
        self.verdict = "queued"
        self.attempts = 0  # failed dispatch attempts consumed so far
        # distributed-tracing context (schema v10): the chain id minted at
        # submit (or shipped in from the fleet router), the incoming
        # parent span id, and the last span THIS engine emitted — what a
        # fleet worker ships back so the parent's ack links to it
        self.trace_id = None
        self.trace_parent = None
        self.last_span_id = None

    @property
    def latency_s(self):
        """enqueue -> complete wall seconds (None until completed)."""
        if self.complete_t is None:
            return None
        return self.complete_t - self.enqueue_t

    @property
    def queue_s(self):
        """enqueue -> dispatch wall seconds (None until dispatched)."""
        if self.dispatch_t is None:
            return None
        return self.dispatch_t - self.enqueue_t

    def slo_ok(self, slo_ms=None):
        """Did this request meet its deadline (its own tag, else the
        engine-level SLO)? None when neither threshold exists or the
        request never completed."""
        bound = self.deadline_ms if self.deadline_ms is not None else slo_ms
        if bound is None or self.latency_s is None:
            return None
        return self.latency_s <= bound / 1000.0


class ServingEngine:
    """Continuous-batching serving loop over a session's inference programs.

    ``session``: a ``TrainingSession`` on any layout (its ``slot_rows`` /
    ``slot_ladder`` fix the dispatch geometry). ``max_slots``: packing
    capacity per dispatch (default: the ladder's top rung). ``slo_ms``: the
    engine-level latency objective requests are scored against when they
    carry no deadline of their own. ``max_queue``: admission bound —
    submissions beyond it are DROPPED (recorded, returned with verdict
    "dropped", never silently discarded); None = unbounded. ``clock`` is
    injectable for tests.

    Fault tolerance (module docstring): ``retry`` is the per-request
    dispatch budget — an int (total attempts, no backoff) or a
    ``retry.RetryPolicy``; ``breaker_threshold`` consecutive failed
    dispatches open the breaker; ``reload_dir`` names the step-checkpoint
    directory ``reload()``/``watch_reload()`` restore verified weights
    from (``loaded_step`` seeds the watcher's freshness floor when the
    session was constructed from a step snapshot); ``shed_on_submit``
    turns the analytical-wait deadline estimate into admission
    backpressure; ``faults`` is a chaos plan (spec string / FaultPlan;
    only ``@dispatch=`` anchors are consulted here — defaults to the
    ``SHALLOWSPEED_FAULTS`` environment plan, like the session).

    Live telemetry (module docstring): ``telemetry_window_s`` sets the
    tumbling rollup width; ``knee_rps`` (a MEASURED ``bench_serving``
    sweep result) arms the knee-proximity alert rule; ``alert_rules``
    overrides the default rule set (``[]`` disables alerting);
    ``alert_sinks`` is the ``slo.AlertSink`` consumer list;
    ``replica_id`` tags this engine's rollup/alert records inside a
    fleet worker (the shard join key).
    """

    def __init__(
        self,
        session,
        max_slots=None,
        slo_ms=None,
        max_queue=None,
        metrics=None,
        clock=time.perf_counter,
        depth_ring=4096,
        retry=2,
        breaker_threshold=3,
        reload_dir=None,
        loaded_step=None,
        shed_on_submit=False,
        faults=None,
        dispatch_floor_ms=0.0,
        tracer=None,
        telemetry_window_s=1.0,
        knee_rps=None,
        alert_rules=None,
        alert_sinks=(),
        replica_id=None,
    ):
        self._session = session
        self._slot_rows = session.slot_rows
        self._ladder = session.slot_ladder
        self._max_slots = (
            int(max_slots) if max_slots is not None else self._ladder[-1]
        )
        if self._max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if self._max_slots > self._ladder[-1]:
            # a dispatch larger than the top rung has no program to run on:
            # step() packs up to max_slots and then rounds up the ladder,
            # so admitting this would crash mid-traffic, not at configure
            # time
            raise ValueError(
                f"max_slots {self._max_slots} exceeds the slot ladder's top "
                f"rung {self._ladder[-1]} — extend the ladder instead"
            )
        self._slo_ms = slo_ms
        self._max_queue = max_queue
        self._metrics = metrics if metrics is not None else NullMetrics()
        self.clock = clock
        # the shared retry policy (retry.py): an int is the common case —
        # a total-attempts budget with zero backoff (re-dispatch happens on
        # a later step(), stalling the serving loop helps nobody)
        if isinstance(retry, R.RetryPolicy):
            self._retry = retry
        else:
            self._retry = R.RetryPolicy(attempts=int(retry), base=0.0, jitter=0)
        if breaker_threshold < 1:
            raise ValueError("breaker_threshold must be >= 1")
        self._breaker_threshold = int(breaker_threshold)
        self._reload_dir = reload_dir
        self._loaded_step = loaded_step  # watcher freshness floor
        self._shed_on_submit = bool(shed_on_submit)
        if dispatch_floor_ms < 0:
            raise ValueError("dispatch_floor_ms must be >= 0")
        self._dispatch_floor_s = float(dispatch_floor_ms) / 1000.0
        self._faults = F.make_plan(faults)
        # request tracing (module docstring): a standalone engine owns
        # its requests end to end — it mints trace ids and emits the
        # terminal ack itself; a fleet worker passes its own tracer
        # (worker clock domain, no terminal ack — the parent owns that)
        self._tracer = (
            tracer if tracer is not None else Tracer(self._metrics, process="e")
        )
        # live telemetry (module docstring): rollup windows + SLO rules,
        # fed from the terminal-verdict/queue/health call sites below.
        # knee_rps comes from a measured bench_serving sweep record (the
        # knee-proximity rule refuses hand-copied constants by absence);
        # alert_rules=None builds the default serving set, [] disables.
        self._telemetry = LiveTelemetry(
            "serving",
            metrics=self._metrics,
            window_s=telemetry_window_s,
            rules=alert_rules,
            sinks=alert_sinks,
            replica_id=replica_id,
            slo_ms=slo_ms,
            knee_rps=knee_rps,
        )
        self._latency_floor = None  # lazy: inference_latency_bound seconds
        # sequential sessions dispatch only the OCCUPIED slots (one fixed
        # program per slot — no rung program to round up to), so the
        # padding accounting must not charge them the rung tail
        self._sequential = bool(getattr(session, "sequential", False))
        self._queue = deque()
        self._next_id = 0
        # attempted-dispatch sequence (failures included): the one counter
        # the chaos plan's @dispatch= anchors key off, so an injection
        # lands deterministically whatever succeeded before it
        self._dispatch_seq = 0
        # breaker state (operational — survives reset_stats)
        self._consecutive_failures = 0
        self._degraded = False
        self._breaker_opened_t = None
        # the flight-recorder pattern: a bounded ring of (t, queue_depth)
        # samples, one per submit/dispatch — the engine's constant-size
        # "what just happened" buffer behind the queue-depth stats
        self._depths = deque(maxlen=int(depth_ring))
        # scalar accounting only: one (latency_s, queue_s, deadline_ms)
        # sample per completion — never the Request itself, whose payload
        # and result arrays belong to the caller
        self._samples = []
        # the shared first-enqueue -> last-complete window definition
        # (observability/stats.py — the fleet folds through the same one)
        self._window = ThroughputWindow()
        self._dropped = 0
        self._expired = 0
        self._errors = 0
        self._unhealthy = 0
        self._retries = 0
        self._failed_dispatches = 0
        self._breaker_trips = 0
        self._reloads = 0
        self._last_recovery_s = None
        self._dispatches = 0
        self._slots_dispatched = 0  # dispatched slots (rung-rounded on mesh)
        self._useful_rows = 0

    def warm_ladder(self, rungs=None):
        """Compile (and dispatch once, warming the jit call cache) every
        ladder rung's inference program before traffic arrives — the
        serving counterpart of ``TrainingSession.warm_run``: without it the
        first requests to hit each rung pay its compile inside their
        latency, and a load run's percentiles measure XLA, not serving."""
        S_rows = self._slot_rows
        in_dim = self._session.spec.sizes[0]
        for rung in rungs if rungs is not None else self._ladder:
            self._session.predict(np.zeros((rung * S_rows, in_dim), np.float32))

    # -- queue --------------------------------------------------------------

    @property
    def queue_depth(self):
        return len(self._queue)

    @property
    def degraded(self):
        """True while the breaker is open: admission refused until a
        successful reload (or explicit ``close_breaker()``)."""
        return self._degraded

    @property
    def dispatch_seq(self):
        """Attempted-dispatch count so far (failures included) — the
        sequence chaos ``@dispatch=N`` anchors and drivers key off."""
        return self._dispatch_seq

    def _record_depth(self, t):
        self._depths.append((t, len(self._queue)))
        self._metrics.gauge("serving.queue_depth", len(self._queue))
        self._telemetry.note_queue_depth(t, len(self._queue))

    def _floor_s(self):
        """The analytical per-dispatch latency floor (lazy — one
        inference_latency_bound call per engine), the lower bound the
        deadline estimates multiply: a dispatch can never return faster."""
        if self._latency_floor is None:
            self._latency_floor = float(
                self._session.inference_latency_bound()["seconds"]
            )
        return self._latency_floor

    def submit(self, x, deadline_ms=None, arrival_t=None, trace=None):
        """Enqueue one request of ``(rows, in_dim)`` inputs; returns its
        ``Request``. ``arrival_t`` backdates the enqueue timestamp to the
        request's scheduled arrival (the open-loop driver uses it so
        latency counts from ARRIVAL, not from when a busy host got around
        to submitting — the coordinated-omission correction). A request
        larger than one dispatch (``max_slots`` slots) is refused; beyond
        ``max_queue`` — or while the breaker is open — it is dropped and
        returned with verdict "dropped"; under ``shed_on_submit`` a
        deadline the analytical wait estimate provably cannot meet is
        refused with verdict "expired" before costing queue space.

        ``trace``: incoming trace context from the fleet router —
        ``{"trace_id": ..., "parent": <route span id>}`` — so this
        engine's spans link into the request's cross-process chain;
        without it a tracing-enabled standalone engine mints its own
        trace id here.

        Timeline consistency: the queue-depth ring samples at the SAME
        timestamp the request's own timeline uses (the backdated
        ``arrival_t`` when given), so depth samples and request records
        join on one clock."""
        x = np.asarray(x, np.float32)
        if x.ndim != 2 or x.shape[0] < 1:
            raise ValueError(f"request must be (rows >= 1, in_dim), got {x.shape}")
        n_slots = serving_slots.slots_needed(x.shape[0], self._slot_rows)
        if n_slots > self._max_slots:
            raise ValueError(
                f"request of {x.shape[0]} rows needs {n_slots} slots — more "
                f"than one dispatch ({self._max_slots} slots); split it"
            )
        # coerce to a plain float: a numpy scalar arrival (e.g. straight
        # from poisson_arrivals) would otherwise poison every downstream
        # timestamp and fail the strict-JSON metrics sink
        t = self.clock() if arrival_t is None else float(arrival_t)
        req = Request(self._next_id, x, n_slots, deadline_ms, t)
        self._next_id += 1
        if trace is not None:
            req.trace_id = trace.get("trace_id")
            req.trace_parent = trace.get("parent")
        elif self._tracer.enabled and self._tracer.terminal_ack:
            # only the request's OWNER mints ids: a fleet WORKER
            # (terminal_ack=False) traces solely under shipped context —
            # a self-minted worker chain could never get its terminal ack
            # and would read as incomplete
            req.trace_id = self._tracer.new_trace(req.id)
        if self._degraded:
            req.verdict = "dropped"
            self._dropped += 1
            self._record_request(req, reason="degraded")
            self._trace_ack(req, reason="degraded")
            return req
        if self._max_queue is not None and len(self._queue) >= self._max_queue:
            req.verdict = "dropped"
            self._dropped += 1
            self._record_request(req, reason="queue_full")
            self._trace_ack(req, reason="queue_full")
            return req
        if (
            self._shed_on_submit
            and deadline_ms is not None
            and self._admission_hopeless(req, t)
        ):
            req.verdict = "expired"
            req.complete_t = self.clock()
            self._expired += 1
            self._record_request(req, reason="admission_estimate")
            self._trace_ack(req, reason="admission_estimate")
            return req
        self._queue.append(req)
        self._telemetry.note_admit(t)
        self._record_depth(t)
        return req

    def _admission_hopeless(self, req, t):
        """Provable-at-admission deadline miss: queued slots ahead need at
        least ``slots_ahead // max_slots`` whole dispatches before this
        request's own, each no faster than the analytical latency floor —
        a LOWER bound, so a True here is a certainty, not a heuristic."""
        deadline = t + req.deadline_ms / 1000.0
        slots_ahead = sum(r.slots for r in self._queue)
        floor = self._floor_s()
        min_complete = (
            self.clock() + (slots_ahead // self._max_slots) * floor + floor
        )
        return min_complete > deadline

    def _deadline_hopeless(self, req, now):
        """Pack-time shed test: the deadline already passed, or even a
        dispatch starting NOW cannot beat the analytical floor to it."""
        if req.deadline_ms is None:
            return False
        deadline = req.enqueue_t + req.deadline_ms / 1000.0
        return now >= deadline or now + self._floor_s() > deadline

    # -- continuous batching ------------------------------------------------

    def step(self):
        """Pack the queue's head into the next inference dispatch and run
        it; returns the completed requests ([] when the queue is empty).

        Packing is FIFO and slot-granular: requests join until the next
        one would overflow ``max_slots``, the packed slot count is rounded
        up the ladder, and every request's rows land in its OWN slots —
        which is why each response is bitwise-equal to a direct
        ``predict()`` of the same rows.

        Failure semantics: expired head requests are shed (verdict
        "expired") before costing a slot; a dispatch exception re-queues
        the popped batch at the HEAD in original order and retries under
        the engine's retry budget (exhausted requests complete as
        "error"); non-finite predictions complete as "unhealthy". A
        chaos ``die`` fault (mode=exc) raises ``InjectedFault`` BEFORE
        any request is popped — the queue is intact when the operator
        loop catches it and re-enters."""
        if not self._queue:
            return []
        t_d = self.clock()
        seq = self._dispatch_seq
        self._dispatch_seq += 1
        # chaos faults anchored at (or before — a same-dispatch die may
        # have consumed an anchor) this attempted dispatch, in spec order
        pending_faults = self._faults.due_at_dispatch(seq)
        for f in pending_faults:
            if f.kind == "die":
                self._record_health(
                    "fault_injected", dispatch=seq, fault=repr(f)
                )
                self._metrics.flush()
                self._faults.fire_die(f)  # sigkill never returns; exc raises
        done = []
        batch, used = [], 0
        while self._queue:
            head = self._queue[0]
            # deadline shedding at pack time: a hopeless head completes as
            # "expired" before costing a slot
            if self._deadline_hopeless(head, t_d):
                self._queue.popleft()
                self._complete_terminal(head, "expired", t_d, reason="deadline")
                self._trace_queue_only(head, t_d, reason="deadline")
                done.append(head)
                continue
            if batch and used + head.slots > self._max_slots:
                break
            self._queue.popleft()
            head.dispatch_t = t_d
            batch.append(head)
            used += head.slots
        if not batch:  # everything at the head was shed
            self._record_depth(t_d)
            return done
        rung = serving_slots.rung_for(used, self._ladder)
        S_rows = self._slot_rows
        flat = np.concatenate(
            [
                np.pad(r.x, ((0, r.slots * S_rows - r.rows), (0, 0)))
                for r in batch
            ],
            axis=0,
        )
        t_pack = self.clock()  # pack span boundary: slots packed + padded
        try:
            for f in pending_faults:
                if f.fired:
                    continue
                if f.kind == "slow":
                    f.fired = True
                    self._record_health(
                        "fault_injected", dispatch=seq, fault=repr(f)
                    )
                    time.sleep(f.ms / 1000.0)
                elif f.kind == "nan":
                    f.fired = True
                    self._record_health(
                        "fault_injected", dispatch=seq, fault=repr(f)
                    )
                    self._session.poison_weights()
                elif f.kind == "error":
                    f.fired = True
                    self._record_health(
                        "fault_injected", dispatch=seq, fault=repr(f)
                    )
                    raise F.InjectedFault(f"injected fault: {f!r}")
            # the session pads the tail up to the rung and dispatches the
            # cached rung program — the same call path predict() takes
            preds = self._session.predict(flat)
        except Exception as e:  # noqa: BLE001 — ANY dispatch failure recovers
            done.extend(self._recover_failed_dispatch(batch, seq, e))
            self._record_depth(self.clock())
            return done
        if self._dispatch_floor_s:
            # service-time floor: pad the dispatch up to the configured
            # wall (constructor docstring) — sleeping, so co-located
            # replicas serve their floors concurrently
            spent = self.clock() - t_d
            if spent < self._dispatch_floor_s:
                time.sleep(self._dispatch_floor_s - spent)
        t_preds = self.clock()  # dispatch span boundary: rung program done
        t_c = self.clock()
        off = 0
        any_unhealthy = False
        for r in batch:
            result = preds[off : off + r.rows]
            off += r.slots * S_rows
            # health gate: a non-finite slice must never be served as "ok"
            if not np.isfinite(result).all():
                any_unhealthy = True
                self._complete_terminal(r, "unhealthy", t_c)
                self._trace_dispatch_chain(r, t_d, t_pack, t_preds, rung)
                done.append(r)
                continue
            r.result = result
            r.complete_t = t_c
            r.verdict = "ok"
            self._record_request(r)
            self._trace_dispatch_chain(r, t_d, t_pack, t_preds, rung)
            done.append(r)
            self._samples.append((r.latency_s, r.queue_s, r.deadline_ms))
            self._window.note_enqueue(r.enqueue_t)
            self._window.note_complete(t_c)
            self._useful_rows += r.rows
            # recovery time: breaker opened, then a response served again
            if self._breaker_opened_t is not None and not self._degraded:
                self._last_recovery_s = t_c - self._breaker_opened_t
                self._breaker_opened_t = None
        self._dispatches += 1
        # mesh dispatches pay the rung program's full slot count; a
        # sequential dispatch runs exactly the occupied slots
        self._slots_dispatched += used if self._sequential else rung
        if any_unhealthy:
            self._record_health(
                "unhealthy_dispatch",
                dispatch=seq,
                consecutive_failures=self._consecutive_failures + 1,
            )
            self._note_failure(seq)
        else:
            self._consecutive_failures = 0
        self._record_depth(t_c)
        return done

    def _recover_failed_dispatch(self, batch, seq, exc):
        """Dispatch recovery (tentpole item 1): re-queue the popped batch
        at the queue HEAD in its original order — packing determinism is
        preserved, so the retried dispatch serves bitwise-identical
        responses — under the bounded per-request retry budget. Requests
        whose budget is exhausted complete with verdict "error"; nothing
        ever vanishes with verdict "queued"."""
        self._failed_dispatches += 1
        t = self.clock()
        terminal = []
        keep = []
        for r in batch:
            r.dispatch_t = None
            r.attempts += 1
            if self._retry.exhausted(r.attempts):
                self._complete_terminal(
                    r, "error", t, reason=f"{type(exc).__name__}: {exc}"[:200]
                )
                self._trace_queue_only(
                    r, t, reason=f"{type(exc).__name__}"[:80]
                )
                terminal.append(r)
            else:
                keep.append(r)
        for r in reversed(keep):  # head insertion preserves original order
            self._queue.appendleft(r)
        self._retries += len(keep)
        self._record_health(
            "dispatch_error",
            dispatch=seq,
            error=f"{type(exc).__name__}: {exc}"[:200],
            requeued=len(keep),
            exhausted=len(terminal),
            consecutive_failures=self._consecutive_failures + 1,
        )
        self._note_failure(seq)
        if keep and self._retry.base:
            # the shared backoff schedule — opt-in (base > 0): serving
            # retries default to immediate re-dispatch on the next step()
            time.sleep(self._retry.delay(min(r.attempts for r in keep) - 1))
        return terminal

    def _note_failure(self, seq):
        """One failed dispatch toward the breaker; at the threshold the
        engine degrades (refuses admission) and — with a reload directory
        configured — attempts the hot weight reload that recovery needs."""
        self._consecutive_failures += 1
        if (
            not self._degraded
            and self._consecutive_failures >= self._breaker_threshold
        ):
            self._degraded = True
            self._breaker_trips += 1
            self._breaker_opened_t = self.clock()
            self._record_health(
                "breaker_open",
                dispatch=seq,
                consecutive_failures=self._consecutive_failures,
            )
            self._metrics.flush()
            if self._reload_dir is not None:
                self._try_reload(reason="breaker")

    # -- hot weight reload ---------------------------------------------------

    def reload(self, path=None, reason="manual", verified=None,
               verify_s=None):
        """Hot-swap the served weights from ``path`` (default: the newest
        VERIFYING snapshot in ``reload_dir`` via ``find_latest_good`` —
        including the one already loaded, whose in-memory copy may be
        poisoned). The queue is untouched; every response dispatched after
        the swap is bitwise-equal to a direct ``predict()`` under the new
        weights, and the cached rung programs survive (same shapes — zero
        recompiles, auditable via the ``jit_compiles`` counter and the
        per-rung ``xla_audit`` dedup). A successful reload closes the
        breaker. Raises ``CheckpointError``/``ValueError`` when the swap
        is impossible (no snapshot verifies, sizes differ); returns the
        loaded checkpoint's metadata.

        Single-verified-read: discovery reads each candidate WITH its
        arrays (``with_arrays=True``), and the swap assembles from
        exactly those bytes — the snapshot is read and checksummed once,
        and the discovery->load TOCTOU window (a concurrent trainer
        rotating the file away, or bit-rot between verify and a re-read)
        is closed by construction. The discovery's verification time is
        recorded as ``verify_s`` in the ``reload`` record, so the
        Degradation subsection's recovery accounting can see what
        verification costs instead of it hiding inside ``wall_s``.
        ``verified``/``verify_s``: a caller (``watch_reload``) that
        already ran a verified discovery passes its result through —
        ``wall_s`` stays end-to-end (discovery + verify + swap) either
        way."""
        t0 = self.clock()
        pre_verified_s = verify_s or 0.0  # discovery ran before t0
        step = None
        if path is None:
            if self._reload_dir is None:
                raise ValueError(
                    "reload() needs a path, or a reload_dir on the engine"
                )
            tv = self.clock()
            found, meta, arrays, skipped = find_latest_good(
                self._reload_dir, with_arrays=True
            )
            verify_s = self.clock() - tv
            pre_verified_s = 0.0  # this discovery is inside t0's window
            if found is None:
                raise CheckpointError(
                    self._reload_dir,
                    "no snapshot verifies for hot reload: "
                    + ("; ".join(f"{p.name}: {c}" for p, c in skipped) or "empty"),
                )
            path = found
            step = meta.get("global_step")
            verified = (meta, arrays)
        if verified is not None:
            # the verified arrays are in memory: the swap is pure
            # assembly, no second read — nothing to retry
            meta = self._session.load_weights(path, verified=verified)
        else:
            # explicit-path reload: ONE read+verify through the loader;
            # transient read errors retry under the shared policy, a
            # deterministic CheckpointError (corruption) surfaces
            meta = R.retry_call(
                lambda: self._session.load_weights(path),
                attempts=2,
                retry_on=(OSError,),
            )
        wall = self.clock() - t0 + pre_verified_s
        if step is None:
            step = meta.get("global_step")
        if step is not None:
            self._loaded_step = int(step)
        self._reloads += 1
        self._metrics.reload(
            "ok",
            path=str(path),
            step=step,
            reason=reason,
            wall_s=wall,
            verify_s=verify_s,
            programs_cached=len(getattr(self._session, "_predict_cache", ())),
        )
        self.close_breaker()
        return meta

    def _try_reload(self, reason):
        """Best-effort internal reload (breaker trigger): a failure is
        recorded — the engine stays degraded — never raised into the
        serving loop."""
        try:
            self.reload(reason=reason)
        except (CheckpointError, ValueError, OSError) as e:
            self._metrics.reload(
                "failed", path=str(self._reload_dir), reason=reason,
                error=str(e)[:200],
            )
            self._metrics.flush()

    def watch_reload(self):
        """The checkpoint-dir watcher leg: pick up a snapshot STRICTLY
        newer than the one currently served (``find_newer_good``) and
        hot-swap it. Returns the new global step, or None when nothing
        newer verifies (newer-but-corrupt candidates are recorded).

        Contained like the breaker leg: the watcher polls a directory a
        CONCURRENT training run keeps writing and rotating, so a snapshot
        can vanish (or rot) between the verify and the load re-read — a
        failed swap is recorded, the engine keeps serving the weights it
        has, and the next poll tries again; it never kills the dispatch
        loop."""
        if self._reload_dir is None:
            raise ValueError("watch_reload() needs a reload_dir on the engine")
        tv = self.clock()
        step, path, meta, arrays, skipped = find_newer_good(
            self._reload_dir, than_step=self._loaded_step, with_arrays=True
        )
        verify_s = self.clock() - tv
        if path is None:
            if skipped:
                self._metrics.reload(
                    "none_newer",
                    path=str(self._reload_dir),
                    reason="watch",
                    verify_s=verify_s,
                    skipped=[
                        {"path": str(p), "cause": c} for p, c in skipped
                    ],
                )
            return None
        try:
            # the watcher's single verified read rides through: the swap
            # assembles the arrays discovery just checksummed, so the
            # snapshot a concurrent trainer is free to rotate away can no
            # longer vanish between the verify and the load
            self.reload(
                path=path, reason="watch", verified=(meta, arrays),
                verify_s=verify_s,
            )
        except (CheckpointError, ValueError, OSError) as e:
            self._metrics.reload(
                "failed", path=str(path), reason="watch", error=str(e)[:200],
            )
            self._metrics.flush()
            return None
        self._loaded_step = int(step)
        return int(step)

    def close_breaker(self):
        """Re-admit traffic after recovery (reload() calls this on
        success; operators may also close it by hand after an external
        fix). The open-timestamp survives until the next served response
        so ``recovery_s`` measures breaker-open -> first "ok"."""
        self._consecutive_failures = 0
        if self._degraded:
            self._degraded = False
            self._record_health(
                "breaker_closed", dispatch=self._dispatch_seq,
                consecutive_failures=0,
            )

    def drain(self):
        """Serve until the queue is empty; returns everything completed.
        Bounded by construction: every queued request either completes
        (ok/unhealthy/expired) or exhausts its finite retry budget
        ("error") — a permanently-failing dispatch cannot loop forever."""
        done = []
        while self._queue:
            done.extend(self.step())
        return done

    def _complete_terminal(self, req, verdict, t, reason=None):
        """Complete ``req`` with a non-"ok" terminal verdict + accounting."""
        req.verdict = verdict
        req.complete_t = t
        if verdict == "expired":
            self._expired += 1
        elif verdict == "error":
            self._errors += 1
        elif verdict == "unhealthy":
            self._unhealthy += 1
        self._record_request(req, reason=reason)

    def _record_request(self, req, reason=None):
        fields = dict(
            id=req.id,
            rows=req.rows,
            slots=req.slots,
            enqueue_ts=req.enqueue_t,
            dispatch_ts=req.dispatch_t,
            complete_ts=req.complete_t,
            latency_s=req.latency_s,
            queue_s=req.queue_s,
            deadline_ms=req.deadline_ms,
            slo_ok=req.slo_ok(self._slo_ms),
            attempts=req.attempts,
        )
        if req.trace_id is not None:
            # the v10 join key from this terminal verdict to its span chain
            fields["trace_id"] = req.trace_id
        if reason is not None:
            fields["reason"] = reason
        self._metrics.request(req.verdict, **fields)
        # one telemetry sample per terminal verdict — this is the single
        # choke point every terminal path (ok, shed, drop, error) crosses
        t = req.complete_t if req.complete_t is not None else req.enqueue_t
        self._telemetry.note_request(
            t, req.verdict, latency_s=req.latency_s, queue_s=req.queue_s
        )

    # -- tracing (schema v10; module docstring span taxonomy) ---------------

    def _trace_dispatch_chain(self, req, t_d, t_pack, t_preds, rung):
        """The dispatched request's worker-side chain: worker.queue ->
        pack -> dispatch -> verify (+ the terminal ack when this engine
        owns the request end to end). The verify span covers the
        finiteness gate; a fleet worker's bitwise-parity re-predict adds
        its own verify span after this one."""
        if req.trace_id is None:
            return
        tr = self._tracer
        wq = tr.span(
            "worker.queue", req.trace_id, req.enqueue_t, t_d,
            parent=req.trace_parent,
        )
        pk = tr.span("pack", req.trace_id, t_d, t_pack, parent=wq)
        dp = tr.span(
            "dispatch", req.trace_id, t_pack, t_preds, parent=pk,
            rung=rung, slots=req.slots,
        )
        req.last_span_id = tr.span(
            "verify", req.trace_id, t_preds, req.complete_t, parent=dp,
            healthy=req.verdict != "unhealthy",
        )
        self._trace_ack(req)

    def _trace_queue_only(self, req, t, reason=None):
        """A request that terminated without a dispatch of its own (shed
        at pack time, retry budget exhausted): its chain is the queue
        wait plus the terminal ack."""
        if req.trace_id is None:
            return
        req.last_span_id = self._tracer.span(
            "worker.queue", req.trace_id, req.enqueue_t, t,
            parent=req.trace_parent, reason=reason,
        )
        self._trace_ack(req)

    def _trace_ack(self, req, reason=None):
        """The terminal span — standalone engines only (``terminal_ack``);
        a fleet worker ships ``last_span_id`` back instead and the parent
        emits the one ack per request."""
        if req.trace_id is None or not self._tracer.terminal_ack:
            return
        t = req.complete_t if req.complete_t is not None else self.clock()
        self._tracer.span(
            "ack", req.trace_id, t, t,
            parent=req.last_span_id or req.trace_parent,
            terminal=True, verdict=req.verdict,
            deadline_ms=req.deadline_ms, reason=reason,
        )

    def _record_health(self, name, **fields):
        self._metrics.serving_health(name, **fields)
        self._telemetry.note_health(self.clock(), name, **fields)

    # -- accounting ---------------------------------------------------------

    def status(self):
        """The LIVE snapshot surface (module docstring): operational
        state + the current/last rollup window + active alerts — cheap,
        JSON-able, and callable mid-traffic (everything here is the
        engine's own single-threaded state). This is what
        ``observability.watch`` renders and what ROADMAP item 4's
        autoscaler polls between ``AlertSink`` edges."""
        return {
            "queue_depth": len(self._queue),
            "degraded": self._degraded,
            "dispatch_seq": self._dispatch_seq,
            "dispatches": self._dispatches,
            "consecutive_failures": self._consecutive_failures,
            "breaker_trips": self._breaker_trips,
            "reloads": self._reloads,
            "loaded_step": self._loaded_step,
            "alerts_active": self._telemetry.evaluator.active(),
            "telemetry": self._telemetry.snapshot(),
        }

    def stats(self):
        """Aggregate accounting over everything served since the last
        ``reset_stats()`` — the field set of the ``serving`` summary
        record (all plain scalars, folded from the per-completion scalar
        samples; no served payload is retained). Latency percentiles and
        the window cover "ok" completions; the terminal-failure counts
        (dropped/expired/error/unhealthy) carry the degradation story,
        folded into ``availability`` = ok / all-terminal."""
        lats = [lat for lat, _, _ in self._samples]
        queues = [q for _, q, _ in self._samples]
        # per-request deadline tag wins over the engine SLO; with neither,
        # the verdict is None — Request.slo_ok's exact semantics
        slo_flags = []
        for lat, _, dl in self._samples:
            bound = dl if dl is not None else self._slo_ms
            slo_flags.append(
                None if bound is None or lat is None else lat <= bound / 1000.0
            )
        window = self._window.window_s
        padded_rows = self._slots_dispatched * self._slot_rows
        depths = [d for _, d in self._depths]
        met = sum(1 for ok in slo_flags if ok)
        ok_n = len(self._samples)
        terminal = (
            ok_n + self._dropped + self._expired + self._errors
            + self._unhealthy
        )
        return {
            "completed": ok_n,
            "dropped": self._dropped,
            "expired": self._expired,
            "errors": self._errors,
            "unhealthy": self._unhealthy,
            "retries": self._retries,
            "failed_dispatches": self._failed_dispatches,
            "breaker_trips": self._breaker_trips,
            "reloads": self._reloads,
            "degraded": self._degraded,
            "recovery_s": self._last_recovery_s,
            "availability": (ok_n / terminal) if terminal else None,
            "dispatches": self._dispatches,
            "slots_dispatched": self._slots_dispatched,
            "useful_rows": self._useful_rows,
            "padding_waste": (
                1.0 - self._useful_rows / padded_rows if padded_rows else None
            ),
            "p50_latency_s": percentile(lats, 50),
            "p99_latency_s": percentile(lats, 99),
            "max_latency_s": max(lats) if lats else None,
            "mean_queue_s": (sum(queues) / len(queues)) if queues else None,
            "window_s": window,
            "achieved_rps": (
                len(self._samples) / window if window else None
            ),
            # goodput: completions that met their deadline/SLO, per second
            # of the serving window (None when no threshold exists — an
            # unmeasured goodput must not read as a perfect one)
            "goodput_rps": (
                met / window
                if window and any(ok is not None for ok in slo_flags)
                else None
            ),
            "slo_ms": self._slo_ms,
            "slo_met": met if any(ok is not None for ok in slo_flags) else None,
            "queue_depth_max": max(depths) if depths else 0,
            "queue_depth_mean": (
                sum(depths) / len(depths) if depths else 0.0
            ),
        }

    def record_summary(self, offered_rps=None, name="summary"):
        """Emit (and return) the ``serving`` summary record: ``stats()``
        plus the offered load and the analytical latency floor
        (``costmodel.serving_latency_bound`` — ticks x per-tick cost).
        The live-telemetry window still open at summary time is flushed
        first, so the trailing partial ``rollup`` record lands before
        the summary it feeds."""
        self._telemetry.flush()
        rec = self.stats()
        rec["offered_rps"] = offered_rps
        rec["slot_rows"] = self._slot_rows
        rec["max_slots"] = self._max_slots
        bound = self._session.inference_latency_bound()
        rec["latency_bound_s"] = bound["seconds"]
        rec["latency_bound_ticks"] = bound["ticks"]
        rec["latency_bound_source"] = bound["peak_source"]
        self._metrics.serving(name, **rec)
        return rec

    def reset_stats(self):
        """Clear the accounting (the bench sweep's per-rate boundary);
        queued requests — and the OPERATIONAL breaker/watcher state
        (degraded flag, consecutive-failure count, loaded step, dispatch
        sequence) — are unaffected."""
        self._samples = []
        self._window.reset()
        self._depths.clear()
        self._dropped = 0
        self._expired = 0
        self._errors = 0
        self._unhealthy = 0
        self._retries = 0
        self._failed_dispatches = 0
        self._breaker_trips = 0
        self._reloads = 0
        self._last_recovery_s = None
        self._dispatches = 0
        self._slots_dispatched = 0
        self._useful_rows = 0
