"""Fleet routing: replica health state, placement policies, bounded queue.

The fleet layer (``serving/fleet.py``) splits cleanly into process
plumbing (spawn pipes, detect deaths) and ROUTING — which replica gets
the next request, when the fleet refuses admission, what "quorum down"
means. This module is the routing half, kept free of processes so every
placement and failover decision is unit-testable with plain
``ReplicaInfo`` values (the same separation the schedule lowerer keeps
from the executor: policy as data, plumbing elsewhere).

Pieces:

- ``ReplicaInfo``   the parent-side view of one replica, fed by worker
                    heartbeats (queue depth, breaker state, last health
                    event) and by the parent's own bookkeeping (un-acked
                    in-flight count, lifecycle state). ``routable()`` is
                    the single definition of "may take traffic": ready,
                    breaker closed, not draining;
- ``FleetRequest``  one fleet-level request and its accounting — the
                    fleet mirror of ``engine.Request``, with routing
                    fields (which replica, how many placements) instead
                    of slot fields. Same terminal-verdict alphabet, same
                    coordinated-omission ``arrival_t`` backdating;
- ``Router``        the bounded fleet queue plus placement:
                    ``least_queue`` (min outstanding load, replica id as
                    the deterministic tie-break) or ``p2c``
                    (power-of-two-choices: two seeded random candidates,
                    the less-loaded wins — the classic
                    Azar/Mitzenmacher result that two choices already
                    collapse the max-load gap, at O(1) instead of a full
                    scan);
- ``quorum``        the degraded-fleet threshold: the fleet refuses
                    admission (and the serve CLI exits 3) when fewer
                    than a majority of its TARGET replicas are healthy —
                    a dead minority degrades capacity, a dead majority
                    degrades the fleet.

Load scoring counts BOTH sides of the pipe: the replica's last
heartbeated queue depth (work it has admitted) plus the parent's
un-acked in-flight count (work on the wire the heartbeat cannot see
yet). In-flight alone would let a burst overfill one replica between
heartbeats; heartbeat depth alone is stale by one round trip.
"""

from collections import deque

import numpy as np

# replica lifecycle (parent-side): spawned -> warming (compiling its
# ladder) -> ready -> [draining ->] retired, with "dead" reachable from
# anywhere (SIGKILL respects no state machine)
REPLICA_STATES = ("starting", "ready", "draining", "retired", "dead")

ROUTING_POLICIES = ("least_queue", "p2c")


def quorum(target_replicas):
    """Healthy replicas required for the fleet to accept traffic: a
    strict majority of the TARGET size (1 -> 1, 2 -> 2, 3 -> 2, 4 -> 3).
    Below it the fleet is degraded — admission refused, serve CLI exit
    3 — while already-admitted work still drains through whatever
    replicas survive."""
    return int(target_replicas) // 2 + 1


class ReplicaInfo:
    """Parent-side replica state: lifecycle + the last heartbeat."""

    __slots__ = (
        "replica_id",
        "state",
        "queue_depth",
        "degraded",
        "consecutive_failures",
        "inflight",
        "routed",
        "served",
        "verdicts",
        "last_heartbeat_t",
        "last_health",
        "spawn_t",
        "ready_t",
        "loaded_step",
    )

    def __init__(self, replica_id, spawn_t=None):
        self.replica_id = int(replica_id)
        self.state = "starting"
        self.queue_depth = 0  # worker-side, from the last heartbeat
        self.degraded = False  # worker breaker state, from heartbeats
        self.consecutive_failures = 0
        self.inflight = 0  # parent-side: routed, no response yet
        self.routed = 0  # total requests ever placed here
        self.served = 0  # "ok" responses received from here
        self.verdicts = {}  # terminal verdict -> count, from responses
        self.last_heartbeat_t = None
        self.last_health = None  # last serving_health event name heard
        self.spawn_t = spawn_t
        self.ready_t = None
        self.loaded_step = None

    @property
    def alive(self):
        return self.state in ("starting", "ready", "draining")

    def routable(self):
        """May this replica take NEW traffic? Ready (ladder warmed),
        breaker closed, not draining toward retirement."""
        return self.state == "ready" and not self.degraded

    def load(self):
        """Placement score: heartbeated queue depth + un-acked in-flight
        (module docstring — each alone is blind to half the pipeline)."""
        return self.queue_depth + self.inflight

    def note_verdict(self, verdict):
        self.verdicts[verdict] = self.verdicts.get(verdict, 0) + 1
        if verdict == "ok":
            self.served += 1

    def snapshot(self):
        """JSON-able per-replica stats row (the fleet summary embeds one
        per replica — the report's per-replica verdict table)."""
        return {
            "state": self.state,
            "degraded": self.degraded,
            "routed": self.routed,
            "served": self.served,
            "verdicts": dict(self.verdicts),
            "queue_depth": self.queue_depth,
            "inflight": self.inflight,
            "loaded_step": self.loaded_step,
            # spawn -> ready wall (None until ready): the per-replica
            # cold-start cost — the scoreboard the AOT executable cache
            # moves (cache-warm replicas ready in a fraction of the
            # cold-compile wall; docs/performance.md)
            "ready_wall_s": (
                self.ready_t - self.spawn_t
                if self.ready_t is not None and self.spawn_t is not None
                else None
            ),
        }


class FleetRequest:
    """One fleet-level request: payload + routing + terminal accounting.

    The verdict alphabet is the engine's (``TERMINAL_VERDICTS`` — every
    admitted request ends on exactly one, never silence), lifted one
    level: a worker-terminal ``error``/``dropped``/``unhealthy`` verdict
    is not necessarily FLEET-terminal — the router may re-place the
    request on another replica while its routing budget lasts.
    ``attempts`` counts placements (the budget ``retry.RetryPolicy``
    bounds); ``replicas_tried`` records where it went, in order."""

    __slots__ = (
        "id",
        "x",
        "rows",
        "deadline_ms",
        "enqueue_t",
        "route_t",
        "complete_t",
        "result",
        "verdict",
        "reason",
        "replica_id",
        "attempts",
        "replicas_tried",
        "parity_ok",
        "worker_latency_s",
        "admitted",
        "trace_id",
        "trace_root",
        "trace_tail",
    )

    def __init__(self, req_id, x, deadline_ms, enqueue_t):
        self.id = req_id
        self.x = x
        self.rows = int(x.shape[0])
        self.deadline_ms = deadline_ms
        self.enqueue_t = enqueue_t
        self.route_t = None  # last placement time
        self.complete_t = None
        self.result = None  # (rows, out_dim) probabilities; only "ok"
        self.verdict = "queued"
        self.reason = None
        self.replica_id = None  # where it is (or last was) placed
        self.attempts = 0  # placements consumed so far
        self.replicas_tried = []
        self.parity_ok = None  # worker-side bitwise parity vs predict()
        self.worker_latency_s = None  # engine-side latency of the final try
        self.admitted = False  # entered the fleet queue (vs refused at submit)
        # distributed-tracing context (schema v10): the chain id minted at
        # fleet submit, the root fleet.queue span (emitted at first
        # placement), and the span the NEXT hop parents to — a route span
        # after placement, the worker's last span after a response, a
        # failover.requeue span after a replica death
        self.trace_id = None
        self.trace_root = None
        self.trace_tail = None

    @property
    def latency_s(self):
        """Fleet enqueue -> complete wall seconds (None until terminal).
        Measured on the PARENT clock end to end, so fleet queueing, the
        pipe hop and any failover re-placements are all inside it."""
        if self.complete_t is None:
            return None
        return self.complete_t - self.enqueue_t

    @property
    def queue_s(self):
        """Fleet enqueue -> last placement (None until routed)."""
        if self.route_t is None:
            return None
        return self.route_t - self.enqueue_t

    def slo_ok(self, slo_ms=None):
        """Deadline (its own tag, else the fleet SLO) verdict — None when
        neither threshold exists or the request never completed."""
        bound = self.deadline_ms if self.deadline_ms is not None else slo_ms
        if bound is None or self.latency_s is None:
            return None
        return self.latency_s <= bound / 1000.0

    def remaining_deadline_ms(self, now):
        """Deadline budget left at ``now`` (None when untagged) — what the
        worker is told, so its pack-time shedding scores the time the
        request ALREADY burned in the fleet queue, not a fresh clock."""
        if self.deadline_ms is None:
            return None
        return self.deadline_ms - (now - self.enqueue_t) * 1000.0


class Router:
    """Bounded fleet queue + placement policy (pure logic, no I/O)."""

    def __init__(self, policy="least_queue", max_queue=None, seed=0):
        if policy not in ROUTING_POLICIES:
            raise ValueError(
                f"unknown routing policy {policy!r} (have {ROUTING_POLICIES})"
            )
        self.policy = policy
        self.max_queue = max_queue
        self.queue = deque()
        # p2c candidate draws are seeded: the same request stream against
        # the same heartbeat history places identically — every decision
        # in this repo that can replay must replay
        self._rng = np.random.RandomState(seed)

    def __len__(self):
        return len(self.queue)

    def admit(self, req):
        """Append ``req`` to the fleet queue; False when the bound is hit
        (the caller completes it as "dropped"/queue_full — admission
        refusal is a terminal verdict, never silence)."""
        if self.max_queue is not None and len(self.queue) >= self.max_queue:
            return False
        self.queue.append(req)
        return True

    def requeue_head(self, reqs):
        """Failover re-admission: push ``reqs`` (original submit order)
        back at the queue HEAD — the engine's requeue-at-head contract
        lifted one level, so re-routed requests keep their place ahead of
        later arrivals and ordering stays deterministic."""
        for r in reversed(list(reqs)):
            self.queue.appendleft(r)

    def place(self, replicas):
        """Pick the routable replica for the queue's head request, or
        None when nothing can take traffic. ``replicas``: an iterable of
        ``ReplicaInfo``. Ties break by a draw from the SEEDED stream —
        a fixed tie-break (e.g. lowest id) would pin every low-load
        request to replica 0 and read as pathological routing skew;
        a seeded draw spreads ties while staying replayable given the
        same request/heartbeat history."""
        candidates = [r for r in replicas if r.routable()]
        if not candidates:
            return None
        if self.policy == "p2c" and len(candidates) > 2:
            i, j = self._rng.choice(len(candidates), size=2, replace=False)
            candidates = [candidates[int(i)], candidates[int(j)]]
        lo = min(r.load() for r in candidates)
        best = [r for r in candidates if r.load() == lo]
        if len(best) == 1:
            return best[0]
        return best[int(self._rng.randint(len(best)))]


def routing_skew(routed_counts):
    """Imbalance of the placement policy: max routed / mean routed over
    the replicas that were ever routed to (1.0 = perfectly even; None
    when nothing was routed). The report's Fleet section renders it so a
    policy regression shows up as a number, not an anecdote."""
    counts = [c for c in routed_counts if c > 0]
    if not counts:
        return None
    return max(counts) / (sum(counts) / len(counts))
