"""A tiny, freshly-written NumPy training oracle for parity tests.

Implements the same math contract as the framework (MLP with fused
linear+relu layers, softmax + MSE head with global-batch loss scaling,
microbatch gradient accumulation, SGD) in plain NumPy, so the JAX path can be
checked against an independent CPU implementation float-for-float (within
reassociation tolerance). This plays the role the reference's NumPy engine
plays for its own equivalence story — written from the math, not copied.
"""

import numpy as np

from shallowspeed_tpu.init import linear_init


def init_params(sizes):
    return [linear_init(sizes[i], sizes[i + 1]) for i in range(len(sizes) - 1)]


def forward(params, x):
    """Returns (softmax_probs, caches). Last linear has no relu."""
    caches = []
    n = len(params)
    for i, (w, b) in enumerate(params):
        z = x @ w.T + b
        if i < n - 1:
            caches.append((x, z > 0))
            x = np.maximum(z, 0.0)
        else:
            caches.append((x, None))
            x = z
    z_exp = np.exp(x - np.max(x))
    probs = z_exp / (z_exp.sum(axis=1, keepdims=True) + 1e-7)
    return probs, (caches, x)


def backward(params, caches_z, probs, target, global_batch):
    caches, z = caches_z
    g = -2.0 * (target - probs) / global_batch  # d mse / d probs
    gz = probs * g  # softmax VJP (recompute style)
    g = gz - probs * gz.sum(axis=1, keepdims=True)
    grads = [None] * len(params)
    for i in reversed(range(len(params))):
        x_in, mask = caches[i]
        if mask is not None:
            g = g * mask
        w, _ = params[i]
        grads[i] = (g.T @ x_in, g.sum(axis=0, keepdims=True))
        g = g @ w
    return grads


def train_step(params, xb, yb, lr, global_batch):
    """One batch: accumulate grads over microbatches (leading axis), SGD."""
    acc = [(np.zeros_like(w), np.zeros_like(b)) for w, b in params]
    for x, y in zip(xb, yb):
        probs, caches_z = forward(params, x)
        grads = backward(params, caches_z, probs, y, global_batch)
        acc = [(aw + gw, ab + gb) for (aw, ab), (gw, gb) in zip(acc, grads)]
    return [
        ((w - lr * gw).astype(np.float32), (b - lr * gb).astype(np.float32))
        for (w, b), (gw, gb) in zip(params, acc)
    ]
