"""SSP002 bad twin: a metrics-path json.dumps without allow_nan=False."""

import json


def emit(record, f):
    f.write(json.dumps(record) + "\n")  # MARK
