"""Prepare the MNIST-784-format dataset (parquet/npy) for training.

Capability parity with /root/reference/download_dataset.py (OpenML fetch,
/255 normalize, mean-center, one-hot targets, 85/15 split with seed 42,
parquet + npy on disk), with two offline fallbacks because TPU pods commonly
run with zero egress:

1. ``--source openml``  — real MNIST-784 via sklearn's fetch_openml (network).
2. ``--source digits``  — sklearn's bundled 8x8 digits dataset upscaled to
   28x28 (no network; same 784-dim feature shape, 10 classes, so every model,
   schedule and benchmark runs unchanged).
3. ``--source synthetic`` — deterministic Gaussian class clusters (no deps at
   all; 60k samples like MNIST).

Default: try openml, fall back to digits, then synthetic.
"""

import argparse
import json
import sys
from pathlib import Path

import numpy as np


def _one_hot(y, n_classes=10):
    return np.eye(n_classes, dtype=np.float32)[np.asarray(y, dtype=np.int64)]


def _split(x, y, seed=42, test_frac=0.15):
    """85/15 split, sample-for-sample the REFERENCE's split when sklearn is
    present: train_test_split(test_size=0.15, random_state=42) — the exact
    call in /root/reference/download_dataset.py:16-18 — so cross-repo
    accuracy comparisons share identical validation membership. NumPy
    fallback (deterministic, but its OWN permutation — validation
    membership, and hence reported accuracies, differ from the reference's)
    when sklearn is unavailable. Returns ``(x_train, x_val, y_train, y_val,
    provenance)``; the fallback warns on stderr and the provenance string is
    recorded in the saved dataset's metadata so a cross-environment accuracy
    comparison can check which split produced it."""
    try:
        from sklearn.model_selection import train_test_split

        parts = train_test_split(x, y, test_size=test_frac, random_state=seed)
        return (*parts, f"sklearn.train_test_split(test_size={test_frac}, "
                        f"random_state={seed})")
    except ImportError:
        print(
            "prepare_data: sklearn unavailable — using the NumPy fallback "
            "split (deterministic but NOT the reference's validation "
            "membership; accuracies are not sample-for-sample comparable)",
            file=sys.stderr,
        )
        rng = np.random.RandomState(seed)
        idx = rng.permutation(len(x))
        n_val = int(round(len(x) * test_frac))
        val, train = idx[:n_val], idx[n_val:]
        return (
            x[train], x[val], y[train], y[val],
            f"numpy.permutation_fallback(seed={seed}, test_frac={test_frac})",
        )


def _load_openml():
    from sklearn.datasets import fetch_openml

    x, y = fetch_openml(
        "mnist_784", version=1, data_home="data_cache", return_X_y=True, as_frame=False
    )
    # raw pixels are 0..255; normalize into [0,1] like the other loaders
    # (reference download_dataset.py:12 does x /= 255.0 before centering)
    return x.astype(np.float32) / 255.0, _one_hot(y.astype(np.int64))


def _load_digits_upscaled(n_repeat=34):
    """sklearn's bundled digits (1797 samples, 8x8) → 784-dim, replicated with
    small deterministic noise to reach MNIST-like sample counts."""
    from sklearn.datasets import load_digits

    d = load_digits()
    imgs = d.images.astype(np.float32) / 16.0  # (N, 8, 8) in [0,1]
    up = np.kron(imgs, np.ones((1, 3, 3), dtype=np.float32))  # (N, 24, 24)
    up = np.pad(up, ((0, 0), (2, 2), (2, 2)))  # (N, 28, 28)
    x = up.reshape(len(up), 784)
    y = _one_hot(d.target)
    rng = np.random.RandomState(0)
    xs, ys = [x], [y]
    for _ in range(n_repeat - 1):
        xs.append(np.clip(x + rng.normal(0, 0.02, x.shape).astype(np.float32), 0, 1))
        ys.append(y)
    return np.concatenate(xs), np.concatenate(ys)


def _load_synthetic(n=60000, dim=784, n_classes=10):
    rng = np.random.RandomState(0)
    centers = rng.normal(0, 1.0, (n_classes, dim)).astype(np.float32)
    labels = rng.randint(0, n_classes, n)
    x = centers[labels] + rng.normal(0, 2.0, (n, dim)).astype(np.float32)
    x = (x - x.min()) / (x.max() - x.min())  # into [0,1] like pixel data
    return x.astype(np.float32), _one_hot(labels)


def prepare(save_dir: Path, source: str = "auto") -> str:
    orders = {"auto": ["openml", "digits", "synthetic"]}.get(source, [source])
    loaders = {
        "openml": _load_openml,
        "digits": _load_digits_upscaled,
        "synthetic": _load_synthetic,
    }
    x = y = used = None
    last_err = None
    for name in orders:
        try:
            x, y = loaders[name]()
            used = name
            break
        except Exception as e:  # noqa: BLE001 — any loader failure (offline, missing sklearn) falls through to the next source; the last cause is re-raised when all fail
            last_err = e
    if x is None:
        raise RuntimeError(f"all data sources failed; last error: {last_err}")

    # reference preprocessing: /255-equivalent normalization then mean-center
    # (download_dataset.py:12-13). Our loaders already emit [0,1]; just center.
    x = x - x.mean()
    x_train, x_val, y_train, y_val, split_provenance = _split(x, y)

    save_dir.mkdir(parents=True, exist_ok=True)
    np.save(save_dir / "x_train.npy", x_train)
    np.save(save_dir / "x_val.npy", x_val)
    np.save(save_dir / "y_train.npy", y_train)
    np.save(save_dir / "y_val.npy", y_val)
    # split provenance rides with the dataset: an accuracy measured on a
    # fallback-split val set is not sample-for-sample comparable with the
    # reference's, and the consumer can only know that if the dataset says so
    (save_dir / "dataset_meta.json").write_text(
        json.dumps({"source": used, "split": split_provenance}, indent=2) + "\n"
    )
    try:  # also write parquet for byte-format parity with the reference
        import pandas as pd

        pd.DataFrame(x_train).to_parquet(save_dir / "x_train.parquet")
        pd.DataFrame(x_val).to_parquet(save_dir / "x_val.parquet")
    except Exception:  # noqa: BLE001 — parquet parity is best-effort (pandas/pyarrow are optional); the .npy files above are the real dataset
        pass
    print(
        f"wrote {save_dir} from source={used}: "
        f"train={x_train.shape}, val={x_val.shape}"
    )
    return used


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--save-dir", type=Path, default=Path("data/mnist_784"))
    ap.add_argument(
        "--source",
        choices=["auto", "openml", "digits", "synthetic"],
        default="auto",
    )
    args = ap.parse_args()
    prepare(args.save_dir, args.source)
