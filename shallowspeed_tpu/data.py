"""Data layer: MNIST-784 parquet/npy loading with DP sharding + microbatching.

Capability parity with /root/reference/shallowspeed/dataset.py: same on-disk
format (``x_{train,val}.parquet`` + ``y_{train,val}.npy``), same drop-last to a
multiple of the global batch size (dataset.py:52), same strided DP shard
``X[rank : full : size]`` with a contiguous copy (dataset.py:57-58), same
microbatch slicing arithmetic (dataset.py:66-80), same divisibility asserts,
and deliberately NO shuffling — determinism is part of the correctness story
("distributed == sequential" is checked float-for-float).

TPU additions: ``epoch_arrays()`` materializes the whole local shard as
``(num_batches, M, mubatch, dim)`` host arrays so the training loop can feed
jitted steps (or a whole-epoch lax.scan) without per-microbatch host slicing —
the reference's per-instruction ``load_micro_batch_*`` host copies would
serialize a TPU pipeline on dispatch overhead.
"""

import os
from pathlib import Path

import numpy as np


def _read_features(save_dir: Path, suffix: str) -> np.ndarray:
    pq = save_dir / f"x_{suffix}.parquet"
    npy = save_dir / f"x_{suffix}.npy"
    if pq.exists():
        import pandas as pd

        return pd.read_parquet(pq).to_numpy(dtype=np.float32)
    if npy.exists():
        return np.load(npy).astype(np.float32)
    raise FileNotFoundError(
        f"No features found at {pq} or {npy}. Run `python prepare_data.py` first."
    )


class Dataset:
    """One split (train or val) of the MNIST-784-format dataset.

    Construction mirrors the reference's signature
    (dataset.py:19-31): ``mubatch_size`` is the per-DP-replica microbatch and
    must divide the local batch ``global_batch_size // DP_size``.
    """

    def __init__(self, save_dir, global_batch_size, mubatch_size, validation=False):
        self.save_dir = Path(save_dir)
        if not self.save_dir.is_dir():
            raise FileNotFoundError(
                f"{self.save_dir} is not a directory — run `python prepare_data.py`"
            )
        self.global_batch_size = int(global_batch_size)
        self.mubatch_size = int(mubatch_size)
        self.local_batch_size = None
        self._val = validation
        self.input_X = None
        self.target_y = None

    # -- loading ------------------------------------------------------------

    def load(self, DP_rank=0, DP_size=1):
        if not (0 <= DP_rank < DP_size):
            raise ValueError(f"DP_rank {DP_rank} out of range for DP_size {DP_size}")
        if self.global_batch_size % DP_size != 0:
            raise ValueError("global batch size must be divisible by DP size")
        self.local_batch_size = self.global_batch_size // DP_size
        if self.local_batch_size % self.mubatch_size != 0:
            raise ValueError("microbatch size must divide the local batch size")

        suffix = "val" if self._val else "train"
        X = _read_features(self.save_dir, suffix)
        y = np.load(self.save_dir / f"y_{suffix}.npy").astype(np.float32)
        if len(X) != len(y):
            raise ValueError("feature/target length mismatch")

        # drop-last so every batch is exactly global_batch_size long — keeps
        # training equivalent across microbatch counts (dataset.py:49-52)
        self.raw_len = len(X)  # pre-drop-last size, for diagnostics
        full = len(X) - (len(X) % self.global_batch_size)
        # strided DP shard; contiguous copy for clean host->device transfers
        self.input_X = np.ascontiguousarray(X[DP_rank:full:DP_size])
        self.target_y = np.ascontiguousarray(y[DP_rank:full:DP_size])

    def _require_loaded(self):
        if self.input_X is None:
            raise RuntimeError("Dataset not loaded — call .load(DP_rank, DP_size) first")

    def __len__(self):
        self._require_loaded()
        return len(self.input_X)

    # -- reference-parity microbatch access (dataset.py:66-86) --------------

    def _mubatch_slice(self, batch_id, mubatch_id):
        self._require_loaded()
        assert batch_id < self.get_num_batches()
        assert mubatch_id < self.get_num_mubatches()
        start = batch_id * self.local_batch_size + mubatch_id * self.mubatch_size
        return slice(start, start + self.mubatch_size)

    def load_micro_batch_input(self, batch_id, mubatch_id):
        return self.input_X[self._mubatch_slice(batch_id, mubatch_id)]

    def load_micro_batch_target(self, batch_id, mubatch_id):
        return self.target_y[self._mubatch_slice(batch_id, mubatch_id)]

    def get_num_batches(self):
        return len(self) // self.local_batch_size

    def get_num_mubatches(self):
        return self.local_batch_size // self.mubatch_size

    # -- TPU-friendly bulk access -------------------------------------------

    def epoch_arrays(self):
        """Whole local shard as (num_batches, M, mubatch, dim) fp32 arrays.

        Row order is identical to sequential microbatch iteration, so feeding
        these to a scanned step reproduces the reference's data order exactly.
        """
        self._require_loaded()
        nb, M, mb = self.get_num_batches(), self.get_num_mubatches(), self.mubatch_size
        X = self.input_X[: nb * self.local_batch_size]
        y = self.target_y[: nb * self.local_batch_size]
        return (
            X.reshape(nb, M, mb, X.shape[-1]),
            y.reshape(nb, M, mb, y.shape[-1]),
        )


def default_data_dir() -> Path:
    return Path(os.environ.get("SHALLOWSPEED_DATA_DIR", "data/mnist_784"))
