"""SSP003 bad twin: a non-atomic write in a durable-format module."""

import json


def save_entry(path, record):
    with open(path, "w", encoding="utf-8") as f:  # MARK
        json.dump(record, f)
