"""Sequential (single-device) training path: jitted step with microbatch scan.

Reference equivalent: running train.py with DP=1, PP=1, where the Worker
interprets [ZeroGrad, {Load, Forward, Load, Backward} x M, OptimizerStep] per
batch (/root/reference/shallowspeed/pipe.py:184-222 with one stage). Here the
whole batch — M microbatch forward+backward passes with gradient accumulation,
plus the SGD update — is ONE jitted XLA computation: the microbatch loop is a
``lax.scan`` whose carry is the gradient pytree, and ``train_epoch`` scans that
step over every batch of the epoch so an epoch is a single device program with
no host round-trips.

Gradient-correctness ledger (identical to the reference, SURVEY §3.3): the
loss gradient is scaled once by the GLOBAL batch size; each Linear backward
sums over its microbatch rows; the scan sums over microbatches; (under DP
the executor sums over replicas — either one whole-tree psum at the
gradient-sync anchor or, with ``grad_bucket_bytes > 0``, one psum per
backward-ordered byte-bucket; both are elementwise sums and therefore the
same ledger entry bit for bit — parallel/gradsync.py). Three sums, no
averaging — bitwise the same ledger as sequential full-batch training. The
sequential path itself has no replicas and no collectives, so the bucketing
knob is a mesh-layout concept only.
"""

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

from shallowspeed_tpu import ops
from shallowspeed_tpu.model import ModelSpec, model_backward, model_forward


def _digest_aux(params, grads):
    """The sequential per-layer digest vectors (numerics provenance): for
    every logical (W, b) block, the uint32 wrap-around checksum of the
    POST-update float32 bits (bitcast, never a float sum — bit-identical
    runs produce bit-identical checksums), the post-update param L2 norm,
    and the post-sync PRE-clip grad L2 norm. Same block definition and
    order as ``utils.iter_param_blocks`` (global layer order), so the
    stream joins against the host digests and ``model_hash``'s blocks.
    Ordinary data flow inside the fused step — no host callbacks."""
    cw, cb, pw, pb, gw, gb = [], [], [], [], [], []
    for stage_p, stage_g in zip(params, grads):
        for lay_p, lay_g in zip(stage_p, stage_g):
            for key, crcs, pns, gns in (
                ("W", cw, pw, gw), ("b", cb, pb, gb),
            ):
                p32 = lay_p[key].astype(jnp.float32)
                crcs.append(
                    jnp.sum(
                        lax.bitcast_convert_type(p32, jnp.uint32),
                        dtype=jnp.uint32,
                    )
                )
                pns.append(jnp.sqrt(jnp.sum(p32 * p32)))
                g32 = lay_g[key].astype(jnp.float32)
                gns.append(jnp.sqrt(jnp.sum(g32 * g32)))
    return {
        "crc_w": jnp.stack(cw), "crc_b": jnp.stack(cb),
        "pnorm_w": jnp.stack(pw), "pnorm_b": jnp.stack(pb),
        "gnorm_w": jnp.stack(gw), "gnorm_b": jnp.stack(gb),
    }


def _make_batch_step(
    spec: ModelSpec, opt, precision, fuse_mubatches=False, clip_norm=None,
    megakernel=False, with_grad_norm=False, with_digests=False,
):
    """The shared per-batch body: microbatch gradient accumulation + optimizer
    apply. Used by both the per-batch step and the epoch scan.
    ``clip_norm``: optional global-norm gradient clipping (over ALL params)
    applied to the accumulated batch gradient before the optimizer.
    ``with_grad_norm``: also return the PRE-clip global gradient norm as a
    fourth output — an aux scalar for training telemetry (it rides the scan
    as data flow, never a host callback, so jit fusion is untouched).

    ``fuse_mubatches=True`` computes the whole batch in ONE forward/backward
    instead of scanning microbatches. This is the same training computation:
    the loss is a sum scaled by the global batch size, so the full-batch
    gradient IS the sum of microbatch gradients (the ledger the reference
    builds its equivalence on, SURVEY §3.3), and the softmax head's
    stability-max quirk is evaluated per microbatch-row-group
    (``head_group_rows``) so even that grouping-sensitive detail matches the
    scanned path float-for-float. The fused path feeds the MXU
    microbatch-count-times larger matmuls; the microbatch path exists for
    mechanism parity with the reference and for the pipeline executor, where
    microbatches are semantic.

    ``megakernel=True`` (requires ``fuse_mubatches``, a kernel-supported
    optimizer, a single-stage spec) runs the ENTIRE batch — forward,
    head, backward, (optional global-norm clip), update — as ONE Pallas
    kernel (pallas_ops.fused_train_call). Identical float math; exists
    because the epoch is op-issue-latency bound (docs/performance.md
    roofline) and one op per batch is the shortest possible serial chain.
    """
    if megakernel:
        if with_grad_norm or with_digests:
            raise ValueError(
                "with_grad_norm/with_digests are unavailable on the kernel "
                "paths: the gradient never leaves the Pallas kernel's VMEM"
            )
        sspec = _validate_megakernel(spec, opt, fuse_mubatches)

        def mega_step(params, opt_state, xb, yb):
            rows = xb.shape[1]
            x = xb.reshape(-1, xb.shape[-1])
            y = yb.reshape(-1, yb.shape[-1])
            return _fused_kernel_call(
                spec, sspec, opt, precision, params, opt_state, x, y,
                epoch_mode=False, group_rows=rows, clip_norm=clip_norm,
            )

        return mega_step

    def clipped(grads):
        if clip_norm is None:
            return grads
        from shallowspeed_tpu.optimizer import clip_tree

        return clip_tree(grads, clip_norm)

    def finish(params, opt_state, grads, loss):
        """Shared tail: (optional) pre-clip norm aux, clip, apply. With
        ``with_digests`` the per-layer digest dict of the NEW params (and
        the pre-clip grads) rides as the LAST output."""
        if with_grad_norm:
            from shallowspeed_tpu.optimizer import global_norm

            gnorm = global_norm(grads)
            new_params, opt_state = opt.apply(
                params, clipped(grads), opt_state
            )
            outs = (new_params, opt_state, loss, gnorm)
        else:
            new_params, opt_state = opt.apply(
                params, clipped(grads), opt_state
            )
            outs = (new_params, opt_state, loss)
        if with_digests:
            outs += (_digest_aux(new_params, grads),)
        return outs

    def batch_step(params, opt_state, xb, yb):
        """Returns (params, opt_state, batch_loss) — the loss is the global-
        batch-scaled MSE of the batch under the pre-update params. With
        ``with_grad_norm`` a fourth output carries the pre-clip global
        gradient norm."""
        if fuse_mubatches:
            rows = xb.shape[1]
            x = xb.reshape(-1, xb.shape[-1])
            y = yb.reshape(-1, yb.shape[-1])
            out, res = model_forward(
                params, spec, x, precision=precision, head_group_rows=rows
            )
            _, grads = model_backward(
                params, spec, res, y, precision=precision, head_group_rows=rows
            )
            loss = ops.mse_loss(out, y, spec.global_batch_size)
            return finish(params, opt_state, grads, loss)

        def accumulate(carry, mxy):
            acc, loss = carry
            x, y = mxy
            out, res = model_forward(params, spec, x, precision=precision)
            _, grads = model_backward(params, spec, res, y, precision=precision)
            loss = loss + ops.mse_loss(out, y, spec.global_batch_size)
            return (jax.tree.map(jnp.add, acc, grads), loss), None

        zeros = jax.tree.map(jnp.zeros_like, params)
        (grads, loss), _ = lax.scan(
            accumulate, (zeros, jnp.zeros(())), (xb, yb)
        )
        return finish(params, opt_state, grads, loss)

    return batch_step


def _kernel_opt_descriptor(opt):
    """Map a framework optimizer onto the unified kernel's descriptor
    (pallas_ops._train_kernel_body's ``opt``), or None if the kernels don't
    support it. The descriptor's kind keys _OPT_GEOMETRY (state mirrors +
    scalar slots), so the VMEM accounting and operand assembly stay in
    lockstep with this one mapping."""
    from shallowspeed_tpu.optimizer import SGD, Adam, MomentumSGD

    if type(opt) is SGD:
        return {"kind": "sgd"}
    if type(opt) is MomentumSGD:
        return {"kind": "momentum", "mu": opt.momentum}
    if type(opt) is Adam:
        return {"kind": "adam", "b1": opt.b1, "b2": opt.b2, "eps": opt.eps}
    return None


def _validate_megakernel(spec, opt, fuse_mubatches, name="megakernel"):
    """The mega-kernel constraint set, shared by the per-batch and whole-epoch
    variants: fused microbatches, a kernel-supported optimizer (SGD,
    momentum, adam), single stage, within the variant's VMEM budget (each
    optimizer state mirror — momentum's velocity, adam's m and v — adds a
    params-sized in+out pair to the footprint; the epoch kernel
    additionally holds the double-buffered streamed x/y blocks). Global-
    norm clipping is supported: the gradient sums are live in VMEM, so the
    norm is one scalar reduction inside the kernel (pallas_ops._batch_grads).
    Returns the single stage's spec."""
    from shallowspeed_tpu import pallas_ops

    if not fuse_mubatches:
        raise ValueError(f"{name} requires fuse_mubatches=True")
    if getattr(spec, "act", "relu") != "relu":
        # the fused kernels hard-code the relu/identity slot expressions
        # (pallas_ops fused units); the gelu family's f32 grad-multiplier
        # masks and residual adds have no kernel path
        raise ValueError(
            f"{name} supports the relu activation family only "
            f"(model act={spec.act!r})"
        )
    desc = _kernel_opt_descriptor(opt)
    if desc is None:
        raise ValueError(
            f"{name} supports the (decaying) SGD, momentum and adam "
            f"optimizers only"
        )
    if spec.n_stages != 1 or not spec.stages[0].has_head:
        raise ValueError(f"{name} runs the single-stage sequential path only")
    sspec = spec.stages[0]
    # the run kernel streams x/y per grid step exactly like the epoch
    # kernel (the extra epoch axis adds no VMEM), so it shares that budget
    fits = (
        pallas_ops.train_epoch_kernel_fits
        if name in ("epoch_kernel", "run_kernel")
        else pallas_ops.train_step_kernel_fits
    )
    n_mirrors, _ = pallas_ops._OPT_GEOMETRY[desc["kind"]]
    if not fits(
        spec.global_batch_size, sspec.local_sizes, state_mirrors=n_mirrors
    ):
        raise ValueError(f"model + batch exceed the {name} VMEM budget")
    return sspec


def _make_epoch_kernel_core(spec, opt, precision, fuse_mubatches, clip_norm):
    """Whole-epoch mega-kernel core (pallas_ops.fused_train_call with
    epoch_mode=True): the
    batch axis becomes the Pallas grid, params stay VMEM-resident across the
    epoch, and the per-epoch serial op chain drops from one kernel per batch
    to ONE kernel total. Same signature as _make_epoch_core's result; batch
    expressions and loss-mean order are bit-identical to scanning the
    per-batch mega-kernel (tested)."""
    sspec = _validate_megakernel(spec, opt, fuse_mubatches, name="epoch_kernel")

    def epoch_core(params, opt_state, X, Y):
        nb, M_, mb, din = X.shape
        x = X.reshape(nb, M_ * mb, din)
        y = Y.reshape(nb, M_ * mb, Y.shape[-1])
        return _fused_kernel_call(
            spec, sspec, opt, precision, params, opt_state, x, y,
            epoch_mode=True, group_rows=mb, clip_norm=clip_norm,
        )

    return epoch_core


def _fused_kernel_call(
    spec, sspec, opt, precision, params, opt_state, x, y, *, epoch_mode,
    group_rows, clip_norm=None, n_epochs=None,
):
    """The one trainer->pallas_ops bridge for every mega/epoch-kernel
    variant: maps the framework optimizer state onto the kernel's mirror
    groups + scalar slots and back. Returns ``(params, opt_state, loss)``.
    State mapping: SGD () stays (); momentum's params-mirror rides as one
    mirror group; adam's {"m", "v", "t"} rides as two mirror groups + the
    t scalar slot."""
    from shallowspeed_tpu import pallas_ops

    desc = _kernel_opt_descriptor(opt)
    kind = desc["kind"]
    if kind == "momentum":
        mirrors, scalars = (opt_state[0],), ()
    elif kind == "adam":
        mirrors = (opt_state["m"][0], opt_state["v"][0])
        scalars = (opt_state["t"],)
    else:
        mirrors, scalars = (), ()
    new_stage, new_mirrors, new_scalars, loss = pallas_ops.fused_train_call(
        params[0], x, y,
        epoch_mode=epoch_mode,
        relu_flags=sspec.relu_flags,
        group_rows=group_rows,
        batch_size=spec.global_batch_size,
        lr=opt.lr,
        weight_decay=opt.weight_decay,
        precision=precision,
        opt=desc, mirrors=mirrors, scalars=scalars, clip_norm=clip_norm,
        n_epochs=n_epochs,
    )
    if kind == "momentum":
        new_state = [new_mirrors[0]]
    elif kind == "adam":
        new_state = {
            "m": [new_mirrors[0]], "v": [new_mirrors[1]], "t": new_scalars[0]
        }
    else:
        new_state = opt_state
    return [new_stage], new_state, loss


def make_train_step(
    spec: ModelSpec,
    opt,
    precision=ops.DEFAULT_PRECISION,
    fuse_mubatches=False,
    clip_norm=None,
    megakernel=False,
):
    """Returns jitted ``step(params, opt_state, xb, yb) -> (params, opt_state)``.

    ``xb``: (M, mubatch, in_dim); ``yb``: (M, mubatch, out_dim) one-hot.
    """
    batch_step = _make_batch_step(
        spec, opt, precision, fuse_mubatches, clip_norm, megakernel
    )

    def step(params, opt_state, xb, yb):
        params, opt_state, _ = batch_step(params, opt_state, xb, yb)
        return params, opt_state

    return jax.jit(step, donate_argnums=(0, 1))


def make_train_epoch(
    spec: ModelSpec,
    opt,
    precision=ops.DEFAULT_PRECISION,
    fuse_mubatches=False,
    unroll=1,
    clip_norm=None,
    megakernel=False,
    epoch_kernel=False,
    with_grad_norm=False,
    with_step_stats=False,
    with_digests=False,
):
    """Whole-epoch scan: ``epoch(params, opt_state, X, Y) -> (params,
    opt_state, mean_loss)`` with X: (num_batches, M, mubatch, in_dim). One
    XLA program per epoch; mean_loss is the true mean batch training loss
    (same definition as the pipeline executor's).

    ``unroll``: lax.scan unroll factor over batches — for this model each
    batch body is a handful of small matmuls, so unrolling amortizes the
    per-iteration loop overhead (a throughput knob; identical numerics).
    ``megakernel``: run each batch as one Pallas kernel (see
    _make_batch_step; identical numerics, shortest serial op chain per
    batch). ``epoch_kernel``: run the ENTIRE epoch as one Pallas kernel
    (the batch axis is the kernel grid, params stay VMEM-resident — see
    _make_epoch_kernel_core; identical numerics, one op per epoch).
    ``with_grad_norm``: telemetry aux — the epoch returns a FOURTH output,
    an aux dict ``{"grad_norm": mean pre-clip global grad norm}``. The aux
    is an ordinary scan output (data flow, not a host callback), so the
    epoch stays one fused XLA program; unavailable on the kernel paths
    (the gradient never leaves VMEM there).
    ``with_step_stats``: the flight-recorder aux — the aux dict also
    carries per-STEP (per-batch) vectors ``step_loss`` /
    ``step_grad_norm`` (pre-clip) / ``step_param_norm`` (post-update), as
    ordinary stacked scan outputs of the same fused program. Same kernel-
    path restriction as ``with_grad_norm``.
    ``with_digests``: the numerics-provenance aux — the aux dict also
    carries per-step per-layer digest vectors under ``"digests"`` (each
    leaf stacked to ``(num_batches, n_layers)``: bitcast-uint32 checksums
    ``crc_w``/``crc_b`` of the post-update params plus param/pre-clip-grad
    L2 norms — see ``_digest_aux``). Same kernel-path restriction.
    """
    if epoch_kernel:
        if megakernel:
            raise ValueError("megakernel and epoch_kernel are exclusive")
        if with_grad_norm or with_step_stats or with_digests:
            raise ValueError(
                "with_grad_norm/with_step_stats/with_digests are "
                "unavailable on the kernel paths: the gradient never "
                "leaves the Pallas kernel's VMEM"
            )
        epoch_core = _make_epoch_kernel_core(
            spec, opt, precision, fuse_mubatches, clip_norm
        )
    else:
        batch_step = _make_batch_step(
            spec, opt, precision, fuse_mubatches, clip_norm, megakernel,
            with_grad_norm or with_step_stats, with_digests,
        )
        epoch_core = _make_epoch_core(
            batch_step, unroll, with_grad_norm, with_step_stats, with_digests
        )
    return jax.jit(epoch_core, donate_argnums=(0, 1))


def _make_epoch_core(
    batch_step, unroll, with_grad_norm=False, with_step_stats=False,
    with_digests=False,
):
    """The one epoch-scan body shared by make_train_epoch and make_train_run:
    ``core(params, opt_state, X, Y) -> (params, opt_state, mean_loss)`` —
    plus an aux dict when instrumented: ``{"grad_norm": mean}`` under
    ``with_grad_norm``, and per-step stacked vectors ``step_loss`` /
    ``step_grad_norm`` / ``step_param_norm`` under ``with_step_stats``
    (ordinary scan ys — data flow, never host callbacks, so the epoch stays
    one fused XLA program). One scan body serves every arity: the grad-norm
    slot always rides the carry (zero when the aux is off) and XLA
    dead-code-eliminates it from the uninstrumented program."""
    track_gn = with_grad_norm or with_step_stats

    def epoch_core(params, opt_state, X, Y):
        def body(carry, xy):
            params, opt_state, loss_sum, gn_sum = carry
            out = batch_step(params, opt_state, *xy)
            params, opt_state, loss = out[0], out[1], out[2]
            gn = out[3] if track_gn else jnp.zeros(())
            carry = (params, opt_state, loss_sum + loss, gn_sum + gn)
            ys = ()
            if with_step_stats:
                from shallowspeed_tpu.optimizer import global_norm

                # post-update param norm: the "did the step blow the
                # weights up" scalar the health monitor watches
                ys += (loss, gn, global_norm(params))
            if with_digests:
                ys += (out[-1],)  # the digest dict rides last (see finish)
            return carry, (ys if ys else None)

        (params, opt_state, loss_sum, gn_sum), ys = lax.scan(
            body,
            (params, opt_state, jnp.zeros(()), jnp.zeros(())),
            (X, Y),
            unroll=unroll,
        )
        nb = X.shape[0]
        if not (with_grad_norm or with_step_stats or with_digests):
            return params, opt_state, loss_sum / nb
        aux = {}
        if with_grad_norm:
            aux["grad_norm"] = gn_sum / nb
        if with_step_stats:
            aux["step_loss"], aux["step_grad_norm"], aux["step_param_norm"] = (
                ys[0], ys[1], ys[2]
            )
        if with_digests:
            aux["digests"] = ys[-1]
        return params, opt_state, loss_sum / nb, aux

    return epoch_core


def make_train_run(
    spec: ModelSpec,
    opt,
    precision=ops.DEFAULT_PRECISION,
    fuse_mubatches=False,
    unroll=1,
    clip_norm=None,
    with_eval=True,
    megakernel=False,
    epoch_kernel=False,
    run_kernel=False,
    with_grad_norm=False,
):
    """Whole-RUN scan: every epoch (and its validation accuracy) in ONE program.

    ``run(params, opt_state, X, Y, vx, vy, n_epochs) -> (params, opt_state,
    losses[n_epochs], accs[n_epochs])`` — an epochs-outer scan around the
    shared epoch core, with the full-split argmax accuracy computed on-device
    after each epoch. Zero host round-trips for the whole training run; on a
    remote-tunneled device this removes n_epochs readback RTTs (~80 ms each
    here — the dominant cost of a 20-epoch convergence run on this model).

    ``with_eval=False`` drops the vx/vy arguments and the accuracy output:
    ``run(params, opt_state, X, Y, n_epochs) -> (params, opt_state, losses)``.

    Same math as looping ``make_train_epoch`` + ``accuracy``: the reference's
    epoch structure (train then validate, /root/reference/train.py:132-137)
    expressed as data flow instead of a host loop. ``n_epochs`` is static
    (one compile per value). vx: (n_val, in_dim); vy: (n_val, out_dim)
    one-hot.

    ``run_kernel=True`` (requires the epoch-kernel constraint set and
    ``with_eval=False``) runs the ENTIRE multi-epoch training run as ONE
    Pallas kernel: the grid is (n_epochs, batches), params + optimizer
    state stay VMEM-resident for the whole run, and the per-epoch mean
    losses come back as the losses vector — the last rung of the
    batch -> epoch -> run dispatch-collapse ladder (one device op for the
    reference's whole outermost loop). Bit-identical to looping the epoch
    kernel. Per-epoch eval needs per-epoch params, so the evaluated run
    keeps the epochs-outer scan.

    ``with_grad_norm=True`` (telemetry aux, scan paths only): the run
    returns one EXTRA trailing output, an aux dict whose ``"grad_norm"``
    is the (n_epochs,) vector of per-epoch mean pre-clip global gradient
    norms — ordinary scan outputs, so the run stays one fused program.
    """
    if with_grad_norm and (megakernel or epoch_kernel or run_kernel):
        raise ValueError(
            "with_grad_norm is unavailable on the kernel paths: the "
            "gradient never leaves the Pallas kernel's VMEM"
        )
    if run_kernel:
        if megakernel or epoch_kernel:
            raise ValueError(
                "run_kernel already subsumes the epoch/mega kernels; pass "
                "only run_kernel=True"
            )
        if with_eval:
            raise ValueError(
                "run_kernel supports with_eval=False only (per-epoch eval "
                "needs per-epoch params outside the kernel)"
            )
        sspec = _validate_megakernel(spec, opt, fuse_mubatches, name="run_kernel")

        @partial(jax.jit, static_argnums=(4,), donate_argnums=(0, 1))
        def run(params, opt_state, X, Y, n_epochs):
            # static check at trace time: a (0, nb) grid never writes the
            # output blocks, so n_epochs=0 would return undefined buffers
            # where the scan path returns the inputs unchanged
            if n_epochs < 1:
                raise ValueError("run_kernel requires n_epochs >= 1")
            nb, M_, mb, din = X.shape
            x = X.reshape(nb, M_ * mb, din)
            y = Y.reshape(nb, M_ * mb, Y.shape[-1])
            return _fused_kernel_call(
                spec, sspec, opt, precision, params, opt_state, x, y,
                epoch_mode=True, group_rows=mb, clip_norm=clip_norm,
                n_epochs=n_epochs,
            )

        return run

    if epoch_kernel:
        if megakernel:
            raise ValueError("megakernel and epoch_kernel are exclusive")
        epoch_core = _make_epoch_kernel_core(
            spec, opt, precision, fuse_mubatches, clip_norm
        )
    else:
        batch_step = _make_batch_step(
            spec, opt, precision, fuse_mubatches, clip_norm, megakernel,
            with_grad_norm,
        )
        epoch_core = _make_epoch_core(batch_step, unroll, with_grad_norm)

    def run_epoch(params, opt_state, X, Y):
        """Uniform (params, opt_state, loss, gnorm) view of the epoch core
        (gnorm 0 when the aux is off — dropped again before returning)."""
        if with_grad_norm:
            params, opt_state, mean_loss, aux = epoch_core(params, opt_state, X, Y)
            return params, opt_state, mean_loss, aux["grad_norm"]
        params, opt_state, mean_loss = epoch_core(params, opt_state, X, Y)
        return params, opt_state, mean_loss, jnp.zeros(())

    if with_eval:

        @partial(jax.jit, static_argnums=(6,), donate_argnums=(0, 1))
        def run(params, opt_state, X, Y, vx, vy, n_epochs):
            def epoch_body(carry, _):
                params, opt_state, mean_loss, gn = run_epoch(*carry, X, Y)
                preds, _ = model_forward(params, spec, vx, precision=precision)
                acc = jnp.mean(
                    (jnp.argmax(preds, axis=1) == jnp.argmax(vy, axis=1)).astype(
                        jnp.float32
                    )
                )
                return (params, opt_state), (mean_loss, acc, gn)

            (params, opt_state), (losses, accs, gns) = lax.scan(
                epoch_body, (params, opt_state), None, length=n_epochs
            )
            if with_grad_norm:
                return params, opt_state, losses, accs, {"grad_norm": gns}
            return params, opt_state, losses, accs

    else:

        @partial(jax.jit, static_argnums=(4,), donate_argnums=(0, 1))
        def run(params, opt_state, X, Y, n_epochs):
            def epoch_body(carry, _):
                params, opt_state, mean_loss, gn = run_epoch(*carry, X, Y)
                return (params, opt_state), (mean_loss, gn)

            (params, opt_state), (losses, gns) = lax.scan(
                epoch_body, (params, opt_state), None, length=n_epochs
            )
            if with_grad_norm:
                return params, opt_state, losses, {"grad_norm": gns}
            return params, opt_state, losses

    return run


def make_predict(spec: ModelSpec, precision=ops.DEFAULT_PRECISION):
    """Jitted inference: softmax predictions for a (batch, in_dim) array."""

    @jax.jit
    def predict(params, x):
        out, _ = model_forward(params, spec, x, precision=precision)
        return out

    return predict


def make_loss_fn(spec: ModelSpec, precision=ops.DEFAULT_PRECISION):
    """Monitoring-only loss (the reference never computes the training loss,
    layers.py:150-155; we expose it as an opt-in observability feature)."""

    @jax.jit
    def loss_fn(params, x, y):
        out, _ = model_forward(params, spec, x, precision=precision)
        return ops.mse_loss(out, y, spec.global_batch_size)

    return loss_fn


def accuracy(predict, params, X, Y, batch_size=1024):
    """Host-side argmax accuracy over a full split (reference train.py:21-47).

    Evaluates every sample: the ragged tail chunk runs at its natural size
    (it only triggers one extra XLA specialization).
    """
    correct = total = 0
    for i in range(0, len(X), batch_size):
        xb, yb = X[i : i + batch_size], Y[i : i + batch_size]
        preds = predict(params, xb)
        correct += int((jnp.argmax(preds, axis=1) == jnp.argmax(yb, axis=1)).sum())
        total += len(xb)
    return correct / max(total, 1)
