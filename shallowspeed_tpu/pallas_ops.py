"""Pallas TPU kernels for the hot op: fused linear + ReLU, forward & backward.

The framework's compute path is XLA-compiled jax.numpy (ops.py) — for this
model class XLA already fuses bias-add and ReLU into the matmul. These Pallas
kernels exist for the cases XLA can't schedule as one unit and as the
framework's custom-kernel layer.

Two regimes, auto-selected per shape at trace time:

- **single block** (the flagship model's regime): every operand of a layer
  fits VMEM at once, so each kernel is one block — HBM -> VMEM once, matmul
  on the MXU with fp32 accumulation, activation + bitmask on the VPU, one
  write back.
- **grid-tiled** (shapes beyond the VMEM budget): every dimension —
  including the contraction — is tiled, so per-block VMEM is ~4 tile^2
  floats (~4 MiB at tile=512) regardless of layer size. The innermost grid
  dimension accumulates partial products into the revisited output block:
  the forward accumulates z over contraction tiles and runs the
  bias+relu+mask epilogue on the final one; the backward splits into a dx
  kernel (accumulating over out-col tiles) and a dw/db kernel (accumulating
  over row tiles; db adds only on the first in-col tile so column tiling
  never double-counts it). Tiles are multiples of the 128-lane MXU width;
  ragged edges are zero-padded in the wrapper and sliced off after (exact:
  padded rows/cols contribute zeros).

- ``linear_relu_fwd(x, w, b) -> (y, mask)``: y = relu(x @ w.T + b), mask the
  pre-activation sign bitmask the backward needs (reference semantics:
  layers.py:68-71 caches the same bitmask).
- ``linear_relu_bwd(g, mask, x, w) -> (dx, dw, db)``: all three gradients
  from one VMEM residency of g/mask/x/w per block.

Enable with SHALLOWSPEED_PALLAS=1 (or ``ops.set_pallas(True)``); off-TPU the
kernels run in interpreter mode, so the same tests cover CPU CI and real
hardware. The flag applies to the SEQUENTIAL model path
(model.stage_forward/backward).

The PIPELINE EXECUTOR has its own kernel pair (``linear_flag_fwd`` /
``linear_flag_bwd``): its layer loop selects relu/identity behavior with
TRACED per-device flags (flags["relu"] picked per virtual chunk), so the
statically-fused relu kernels above can't be slotted in. The flag kernels
are branch-free — the relu flag rides in as an SMEM scalar operand and the
activation is ``where(flag, max(z, 0), z)`` on the VPU — so ONE compiled
kernel serves every stage, chunk and schedule. Like the relu pair, the flag
kernels auto-dispatch between single-block and grid-tiled per shape.
Executor opt-in: ``make_pipeline_step(..., kernel_backend="pallas")``, or
through the product surface: ``TrainingSession(kernel_backend="pallas")`` /
``train.py --kernel-backend pallas``.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# VMEM is ~16 MiB/core; a single-block kernel must hold every operand at
# once, so leave generous headroom for double-buffering and the compiler.
SINGLE_BLOCK_BUDGET_BYTES = 8 * 1024 * 1024
TILE = 512  # grid tile edge (multiple of the 128-lane MXU width)


def _fwd_bytes(mb, din, dout):
    """f32 VMEM footprint of a single-block forward: x, w, b, y, mask."""
    return 4 * (mb * din + dout * din + dout + 2 * mb * dout)


def _bwd_bytes(mb, din, dout):
    """f32 VMEM footprint of a single-block backward: g, mask, x, w, dx, dw, db."""
    return 4 * (3 * mb * dout + mb * din + 2 * dout * din + dout)


def _pad_to(a, axis, mult):
    n = a.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[axis] = (0, rem)
    return jnp.pad(a, widths)


def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mask_ref, *, precision):
    z = (
        jnp.dot(
            x_ref[:], w_ref[:].T,
            precision=precision, preferred_element_type=jnp.float32,
        )
        + b_ref[:]
    )
    mask_ref[:] = (z > 0.0).astype(jnp.float32)
    y_ref[:] = jnp.maximum(z, 0.0)


def _linear_relu_fwd_single(x, w, b2, precision):
    mb, _ = x.shape
    dout = w.shape[0]
    return pl.pallas_call(
        functools.partial(_fwd_kernel, precision=precision),
        out_shape=(
            jax.ShapeDtypeStruct((mb, dout), jnp.float32),
            jax.ShapeDtypeStruct((mb, dout), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(x, w, b2)


def linear_relu_fwd_tiled(x, w, b2, tile=TILE, precision=None):
    """Grid-tiled forward: every dim tiled (rows x out-cols x contraction),
    so per-block VMEM is ~4 tile^2 floats regardless of shape. Ragged edges
    zero-padded, sliced off after (exact: pads contribute zeros). The
    tiling plumbing exists ONCE, in the flag variant — relu is the flag
    pinned to 1 (``where(1, max(z, 0), z) == relu(z)``, value-exact)."""
    return linear_flag_fwd_tiled(x, w, b2, jnp.int32(1), tile=tile, precision=precision)


@functools.partial(jax.jit, static_argnames=("precision",))
def linear_relu_fwd(x, w, b, precision=None):
    """``precision`` is the MXU dot precision (lax.Precision; None = the
    backend default, a single bf16-input pass). The framework's ops layer
    passes its caller's precision through, so HIGHEST really means the
    multi-pass fp32-class dot inside the kernel too — without this the
    'pallas' and 'xla' backends would silently measure different math."""
    mb, din = x.shape
    dout = w.shape[0]
    b2 = jnp.reshape(b, (1, -1))
    if _fwd_bytes(mb, din, dout) <= SINGLE_BLOCK_BUDGET_BYTES:
        return _linear_relu_fwd_single(x, w, b2, precision)
    return linear_relu_fwd_tiled(x, w, b2, tile=TILE, precision=precision)


def _bwd_kernel(g_ref, mask_ref, x_ref, w_ref, dx_ref, dw_ref, db_ref, *, precision):
    ge = g_ref[:] * mask_ref[:]
    dx_ref[:] = jnp.dot(
        ge, w_ref[:], precision=precision, preferred_element_type=jnp.float32
    )
    dw_ref[:] = jnp.dot(
        ge.T, x_ref[:], precision=precision, preferred_element_type=jnp.float32
    )
    db_ref[:] = jnp.sum(ge, axis=0, keepdims=True)


def _linear_relu_bwd_single(g, mask, x, w, precision):
    mb, dout = g.shape
    din = x.shape[1]
    return pl.pallas_call(
        functools.partial(_bwd_kernel, precision=precision),
        out_shape=(
            jax.ShapeDtypeStruct((mb, din), jnp.float32),
            jax.ShapeDtypeStruct((dout, din), jnp.float32),
            jax.ShapeDtypeStruct((1, dout), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 4,
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 3),
        interpret=_interpret(),
    )(g, mask, x, w)


def linear_relu_bwd_tiled(g, mask, x, w, tile=TILE, precision=None):
    """Grid-tiled backward, two kernels, every dim tiled (per-block VMEM is
    ~4 tile^2 floats regardless of shape): dx on a (row x in-col x out-col)
    grid accumulating over the innermost out-col/contraction tiles; dw/db on
    a (out-col x in-col x row) grid accumulating over the innermost row
    tiles. Delegates to the flag variant with the flag pinned to 1 (the
    relu-mask multiply applied) — one tiling implementation."""
    return linear_flag_bwd_tiled(g, mask, x, w, jnp.int32(1), tile=tile, precision=precision)


@functools.partial(jax.jit, static_argnames=("precision",))
def linear_relu_bwd(g, mask, x, w, precision=None):
    """See linear_relu_fwd: ``precision`` makes the kernel's dots match the
    caller's precision class instead of silently using the backend default."""
    mb, dout = g.shape
    din = x.shape[1]
    if _bwd_bytes(mb, din, dout) <= SINGLE_BLOCK_BUDGET_BYTES:
        return _linear_relu_bwd_single(g, mask, x, w, precision)
    return linear_relu_bwd_tiled(g, mask, x, w, tile=TILE, precision=precision)


# ---------------------------------------------------------------------------
# Flag-operand kernels for the pipeline executor (traced relu selection)
# ---------------------------------------------------------------------------


def _flag_fwd_kernel(flag_ref, x_ref, w_ref, b_ref, y_ref, mask_ref, *, precision):
    # branch-free relu selection: flag is an SMEM scalar, the select runs on
    # the VPU — one compiled kernel serves relu AND identity layers, which is
    # what lets the executor's chunk-uniform layer loop call it with a
    # traced per-(stage, slot) flag
    z = (
        jnp.dot(
            x_ref[:], w_ref[:].T,
            precision=precision, preferred_element_type=jnp.float32,
        )
        + b_ref[:]
    )
    mask_ref[:] = (z > 0.0).astype(jnp.float32)
    y_ref[:] = jnp.where(flag_ref[0] != 0, jnp.maximum(z, 0.0), z)


def linear_flag_fwd(x, w, b2, flag, precision=None):
    """Executor forward unit: ``(y, mask)`` with ``y = relu(z) if flag else
    z``, ``z = x @ w.T + b``, ``mask = z > 0`` (f32). ``flag`` is a TRACED
    scalar (the executor's per-slot relu flag picked per virtual chunk).
    Auto-selects single-block (the flagship regime) or the grid-tiled
    variant per shape, like linear_relu_fwd."""
    mb, din = x.shape
    dout = w.shape[0]
    if _fwd_bytes(mb, din, dout) > SINGLE_BLOCK_BUDGET_BYTES:
        # tile=TILE at CALL time (not the def-time default) so the module
        # knob governs the flag path exactly like the relu dispatchers
        return linear_flag_fwd_tiled(x, w, b2, flag, tile=TILE, precision=precision)
    return pl.pallas_call(
        functools.partial(_flag_fwd_kernel, precision=precision),
        out_shape=(
            jax.ShapeDtypeStruct((mb, dout), jnp.float32),
            jax.ShapeDtypeStruct((mb, dout), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(jnp.reshape(flag, (1,)).astype(jnp.int32), x, w, b2)


def _flag_fwd_tiled_kernel(flag_ref, x_ref, w_ref, b_ref, y_ref, mask_ref, *, precision):
    # grid = (row tiles i, out-col tiles j, contraction tiles c); c is
    # INNERMOST: the revisited y block accumulates partial products, and the
    # bias/activation/mask epilogue runs once on the final contraction step.
    # The flag rides in SMEM with a constant index map (every grid step sees
    # the same scalar) and selects relu vs identity in the epilogue.
    c = pl.program_id(2)
    nc = pl.num_programs(2)
    partial = jnp.dot(
        x_ref[:], w_ref[:].T,
        precision=precision, preferred_element_type=jnp.float32,
    )

    @pl.when(c == 0)
    def _init():
        y_ref[:] = partial

    @pl.when(c != 0)
    def _acc():
        y_ref[:] += partial

    @pl.when(c == nc - 1)
    def _epilogue():
        z = y_ref[:] + b_ref[:]
        mask_ref[:] = (z > 0.0).astype(jnp.float32)
        y_ref[:] = jnp.where(flag_ref[0] != 0, jnp.maximum(z, 0.0), z)


def linear_flag_fwd_tiled(x, w, b2, flag, tile=TILE, precision=None):
    """Grid-tiled flag forward — linear_relu_fwd_tiled's tiling (rows x
    out-cols x contraction, ragged edges zero-padded and sliced) with the
    traced relu flag as an SMEM operand, so the executor's oversize slots
    run on the pallas backend instead of being rejected at build time."""
    mb, din = x.shape
    dout = w.shape[0]
    xp = _pad_to(_pad_to(x, 0, tile), 1, tile)
    wp = _pad_to(_pad_to(w, 0, tile), 1, tile)
    bp = _pad_to(b2, 1, tile)
    mbp, dinp = xp.shape
    doutp = wp.shape[0]
    y, mask = pl.pallas_call(
        functools.partial(_flag_fwd_tiled_kernel, precision=precision),
        grid=(mbp // tile, doutp // tile, dinp // tile),
        out_shape=(
            jax.ShapeDtypeStruct((mbp, doutp), jnp.float32),
            jax.ShapeDtypeStruct((mbp, doutp), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec((1,), lambda i, j, c: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, tile), lambda i, j, c: (i, c), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, tile), lambda i, j, c: (j, c), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda i, j, c: (0, j), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((tile, tile), lambda i, j, c: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, tile), lambda i, j, c: (i, j), memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(jnp.reshape(flag, (1,)).astype(jnp.int32), xp, wp, bp)
    return y[:mb, :dout], mask[:mb, :dout]


def _flag_bwd_kernel(
    flag_ref, g_ref, mask_ref, x_ref, w_ref, dx_ref, dw_ref, db_ref, *, precision
):
    ge = jnp.where(flag_ref[0] != 0, g_ref[:] * mask_ref[:], g_ref[:])
    dx_ref[:] = jnp.dot(
        ge, w_ref[:], precision=precision, preferred_element_type=jnp.float32
    )
    dw_ref[:] = jnp.dot(
        ge.T, x_ref[:], precision=precision, preferred_element_type=jnp.float32
    )
    db_ref[:] = jnp.sum(ge, axis=0, keepdims=True)


def linear_flag_bwd(g, mask, x, w, flag, precision=None):
    """Executor backward unit: ``(dx, dw, db)`` of linear_flag_fwd — the
    relu-mask multiply is applied iff ``flag`` (traced), then all three
    gradients come from one VMEM residency. Auto-selects single-block or
    the grid-tiled variant per shape, like linear_relu_bwd."""
    mb, dout = g.shape
    din = x.shape[1]
    if _bwd_bytes(mb, din, dout) > SINGLE_BLOCK_BUDGET_BYTES:
        return linear_flag_bwd_tiled(g, mask, x, w, flag, tile=TILE, precision=precision)
    return pl.pallas_call(
        functools.partial(_flag_bwd_kernel, precision=precision),
        out_shape=(
            jax.ShapeDtypeStruct((mb, din), jnp.float32),
            jax.ShapeDtypeStruct((dout, din), jnp.float32),
            jax.ShapeDtypeStruct((1, dout), jnp.float32),
        ),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
        + [pl.BlockSpec(memory_space=pltpu.VMEM)] * 4,
        out_specs=tuple([pl.BlockSpec(memory_space=pltpu.VMEM)] * 3),
        interpret=_interpret(),
    )(jnp.reshape(flag, (1,)).astype(jnp.int32), g, mask, x, w)


def _flag_bwd_dx_kernel(flag_ref, g_ref, mask_ref, w_ref, dx_ref, *, precision):
    # grid = (row tiles i, in-col tiles j, out-col/contraction tiles c);
    # c INNERMOST accumulates into the revisited dx block; the relu-mask
    # multiply is flag-selected
    c = pl.program_id(2)
    ge = jnp.where(flag_ref[0] != 0, g_ref[:] * mask_ref[:], g_ref[:])
    partial = jnp.dot(
        ge, w_ref[:], precision=precision, preferred_element_type=jnp.float32
    )

    @pl.when(c == 0)
    def _init():
        dx_ref[:] = partial

    @pl.when(c != 0)
    def _acc():
        dx_ref[:] += partial


def _flag_bwd_dw_kernel(
    flag_ref, g_ref, mask_ref, x_ref, dw_ref, db_ref, *, precision
):
    # grid = (out-col tiles j, in-col tiles k, row tiles i); i is INNERMOST
    # so the revisited dw block accumulates partial products over row tiles;
    # db is independent of the in-col tiling and accumulates on k == 0 only
    k = pl.program_id(1)
    i = pl.program_id(2)
    ge = jnp.where(flag_ref[0] != 0, g_ref[:] * mask_ref[:], g_ref[:])
    contrib = jnp.dot(
        ge.T, x_ref[:], precision=precision, preferred_element_type=jnp.float32
    )

    @pl.when(i == 0)
    def _init():
        dw_ref[:] = contrib

    @pl.when(i != 0)
    def _acc():
        dw_ref[:] += contrib

    dbc = jnp.sum(ge, axis=0, keepdims=True)

    @pl.when((k == 0) & (i == 0))
    def _db_init():
        db_ref[:] = dbc

    @pl.when((k == 0) & (i != 0))
    def _db_acc():
        db_ref[:] += dbc


def linear_flag_bwd_tiled(g, mask, x, w, flag, tile=TILE, precision=None):
    """Grid-tiled flag backward — linear_relu_bwd_tiled's two-kernel tiling
    with the traced relu flag as an SMEM operand on both kernels."""
    mb, dout = g.shape
    din = x.shape[1]
    fl = jnp.reshape(flag, (1,)).astype(jnp.int32)
    gp = _pad_to(_pad_to(g, 0, tile), 1, tile)
    mp = _pad_to(_pad_to(mask, 0, tile), 1, tile)
    xp = _pad_to(_pad_to(x, 0, tile), 1, tile)
    wp = _pad_to(_pad_to(w, 0, tile), 1, tile)
    mbp, doutp = gp.shape
    dinp = xp.shape[1]
    dx = pl.pallas_call(
        functools.partial(_flag_bwd_dx_kernel, precision=precision),
        grid=(mbp // tile, dinp // tile, doutp // tile),
        out_shape=jax.ShapeDtypeStruct((mbp, dinp), jnp.float32),
        in_specs=[
            pl.BlockSpec((1,), lambda i, j, c: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, tile), lambda i, j, c: (i, c), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, tile), lambda i, j, c: (i, c), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, tile), lambda i, j, c: (c, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (tile, tile), lambda i, j, c: (i, j), memory_space=pltpu.VMEM
        ),
        interpret=_interpret(),
    )(fl, gp, mp, wp)
    dw, db = pl.pallas_call(
        functools.partial(_flag_bwd_dw_kernel, precision=precision),
        grid=(doutp // tile, dinp // tile, mbp // tile),
        out_shape=(
            jax.ShapeDtypeStruct((doutp, dinp), jnp.float32),
            jax.ShapeDtypeStruct((1, doutp), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec((1,), lambda j, k, i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((tile, tile), lambda j, k, i: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, tile), lambda j, k, i: (i, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, tile), lambda j, k, i: (i, k), memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec((tile, tile), lambda j, k, i: (j, k), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tile), lambda j, k, i: (0, j), memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(fl, gp, mp, xp)
    return dx[:mb, :din], dw[:dout, :din], db[:, :dout]


def flag_kernels_fit(mb, din, dout):
    """True when a (mb, din) x (dout, din) layer fits the single-block
    budget for BOTH flag kernels. No longer a rejection gate: oversize
    slots auto-dispatch to the grid-tiled flag kernels — kept as the
    introspection helper that says which regime a slot selects."""
    return (
        _fwd_bytes(mb, din, dout) <= SINGLE_BLOCK_BUDGET_BYTES
        and _bwd_bytes(mb, din, dout) <= SINGLE_BLOCK_BUDGET_BYTES
    )


# ---------------------------------------------------------------------------
# Whole-training-step mega-kernel (sequential fused path)
# ---------------------------------------------------------------------------
#
# Motivation (docs/performance.md roofline): the flagship epoch is op-issue
# bound — ~40 small XLA ops per batch retiring at ~240 ns each, serialized by
# SGD's step-to-step dependence. The model's ENTIRE working set (724 KB
# params + ~1 MB activations/masks) fits VMEM, so the whole per-batch
# computation — L-layer forward, grouped-softmax MSE head, backward, SGD
# update — can be ONE kernel: one op per batch on the serial chain instead
# of ~40, attacking the binding roofline directly. The expression is
# identical to the fused XLA path (same dots at the same precision, same
# grouped stability max, same 1e-7 softmax quirk, same update expression),
# INTERPRETER-verified bit-for-bit in tests/test_pallas_ops.py; on real
# hardware Mosaic's lowering is not guaranteed bitwise-equal to XLA's, so
# scripts/tpu_capture.py phase 2c measures the on-chip divergence before
# timing instead of assuming zero.


def _batch_grads(
    x, y, ws, bs, *, relu_flags, group_rows, batch_size, precision,
    clip_norm=None,
):
    """The per-batch gradient math shared by every training kernel, on param
    VALUES (already read from refs): L-layer forward with live
    activations/masks, the reference-quirk softmax-MSE head, backward.
    Returns ``(dws, dbs, loss)`` — gradient SUMS over the batch (the loss
    is pre-scaled by the global batch size, the reference's ledger). ONE
    definition so the bit-identity contract (fused XLA == step kernel ==
    epoch kernel, any optimizer variant) cannot drift between kernels.

    ``clip_norm``: optional global-norm gradient clipping, applied to the
    batch gradient before it is returned — the same point in the math where
    the XLA path applies ``optimizer.clip_tree`` to the accumulated batch
    gradient. The clip goes through ``optimizer.clip_tree`` itself (on the
    in-kernel gradient VALUES, arranged in the same per-layer {"W","b"}
    tree shape), so leaf order, accumulation and scale are identical to
    the XLA path's by construction."""
    L = len(ws)

    # ---- forward (activations/masks stay live in VMEM) ----
    a = x
    acts, masks = [], [None] * L
    for l in range(L):
        acts.append(a)
        z = (
            jnp.dot(
                a, ws[l].T, precision=precision,
                preferred_element_type=jnp.float32,
            )
            + bs[l]
        )
        if relu_flags[l]:
            masks[l] = (z > 0.0).astype(jnp.float32)
            a = jnp.maximum(z, 0.0)
        else:
            a = z

    # ---- head: softmax with the reference's quirks (ops.softmax) ----
    # stability max per consecutive group_rows-row group (the fused-microbatch
    # semantics, ops._stability_max) via STATIC row slices — scalar max +
    # broadcast per group, no 3-D reshapes (Mosaic-friendly)
    z_head = a
    rows = z_head.shape[0]
    parts = []
    for g0 in range(0, rows, group_rows):
        blk = z_head[g0 : g0 + group_rows, :]
        parts.append(jnp.full_like(blk, jnp.max(blk)))
    m = jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
    ze = jnp.exp(z_head - m)
    p = ze / (ze.sum(axis=1, keepdims=True) + 1e-7)

    loss = jnp.sum((y - p) ** 2) / batch_size
    # d(MSE)/dp then softmax VJP (ops.mse_loss_grad + ops.softmax_grad,
    # same expression order for float identity)
    gl = -2.0 * (y - p) / batch_size
    gz = p * gl
    g = gz - p * gz.sum(axis=-1, keepdims=True)

    # ---- backward (dx from the PRE-update weights) ----
    dws, dbs = [None] * L, [None] * L
    for l in reversed(range(L)):
        ge = g * masks[l] if relu_flags[l] else g
        dws[l] = jnp.dot(
            ge.T, acts[l], precision=precision, preferred_element_type=jnp.float32
        )
        dbs[l] = jnp.sum(ge, axis=0, keepdims=True)  # b is stored (1, out)
        if l > 0:
            g = jnp.dot(
                ge, ws[l], precision=precision,
                preferred_element_type=jnp.float32,
            )

    if clip_norm is not None:
        from shallowspeed_tpu.optimizer import clip_tree

        clipped = clip_tree(
            [{"W": dws[l], "b": dbs[l]} for l in range(L)], clip_norm
        )
        dws = [layer["W"] for layer in clipped]
        dbs = [layer["b"] for layer in clipped]
    return dws, dbs, loss


def _sgd_batch_math(
    x, y, ws, bs, *, relu_flags, group_rows, batch_size, lr, decay, precision,
    clip_norm=None,
):
    """_batch_grads + the (decaying) SGD update: ``(new_ws, new_bs, loss)``.
    Same elementwise update expression as optimizer.SGD.apply."""
    dws, dbs, loss = _batch_grads(
        x, y, ws, bs, relu_flags=relu_flags, group_rows=group_rows,
        batch_size=batch_size, precision=precision, clip_norm=clip_norm,
    )
    L = len(ws)
    new_ws = [ws[l] * decay - lr * dws[l] for l in range(L)]
    new_bs = [bs[l] * decay - lr * dbs[l] for l in range(L)]
    return new_ws, new_bs, loss


def _momentum_batch_math(
    x, y, ws, bs, vws, vbs, *, relu_flags, group_rows, batch_size, lr, mu,
    decay, precision, clip_norm=None,
):
    """_batch_grads + the heavy-ball update (optimizer.MomentumSGD.apply:
    ``v <- mu*v + g; p <- decay(p) - lr*v``): returns ``(new_ws, new_bs,
    new_vws, new_vbs, loss)``."""
    dws, dbs, loss = _batch_grads(
        x, y, ws, bs, relu_flags=relu_flags, group_rows=group_rows,
        batch_size=batch_size, precision=precision, clip_norm=clip_norm,
    )
    L = len(ws)
    new_vws = [mu * vws[l] + dws[l] for l in range(L)]
    new_vbs = [mu * vbs[l] + dbs[l] for l in range(L)]
    new_ws = [ws[l] * decay - lr * new_vws[l] for l in range(L)]
    new_bs = [bs[l] * decay - lr * new_vbs[l] for l in range(L)]
    return new_ws, new_bs, new_vws, new_vbs, loss


def _adam_batch_math(
    x, y, ws, bs, mws, mbs, vws, vbs, t, *, relu_flags, group_rows,
    batch_size, lr, b1, b2, eps, decay, precision, clip_norm=None,
):
    """_batch_grads + the Adam/AdamW update (optimizer.Adam.apply: same
    expression order — ``m <- b1*m + (1-b1)*g; v <- b2*v + (1-b2)*g*g;
    p <- decay(p) - lr*(m/c1)/(sqrt(v/c2)+eps)`` with bias corrections
    ``c = 1 - beta**t``): returns ``(new_ws, new_bs, new_mws, new_mbs,
    new_vws, new_vbs, t_new, loss)``. ``t`` is the traced step counter."""
    dws, dbs, loss = _batch_grads(
        x, y, ws, bs, relu_flags=relu_flags, group_rows=group_rows,
        batch_size=batch_size, precision=precision, clip_norm=clip_norm,
    )
    L = len(ws)
    t_new = t + 1.0
    new_mws = [b1 * mws[l] + (1 - b1) * dws[l] for l in range(L)]
    new_mbs = [b1 * mbs[l] + (1 - b1) * dbs[l] for l in range(L)]
    new_vws = [b2 * vws[l] + (1 - b2) * dws[l] * dws[l] for l in range(L)]
    new_vbs = [b2 * vbs[l] + (1 - b2) * dbs[l] * dbs[l] for l in range(L)]
    c1 = 1.0 - b1**t_new
    c2 = 1.0 - b2**t_new
    new_ws = [
        ws[l] * decay - lr * (new_mws[l] / c1) / (jnp.sqrt(new_vws[l] / c2) + eps)
        for l in range(L)
    ]
    new_bs = [
        bs[l] * decay - lr * (new_mbs[l] / c1) / (jnp.sqrt(new_vbs[l] / c2) + eps)
        for l in range(L)
    ]
    return new_ws, new_bs, new_mws, new_mbs, new_vws, new_vbs, t_new, loss


# per-optimizer operand geometry: (param-mirror state groups, scalar slots)
_OPT_GEOMETRY = {"sgd": (0, 0), "momentum": (1, 0), "adam": (2, 1)}


def _train_kernel_body(
    x_ref, y_ref, *refs, L, relu_flags, group_rows, batch_size, lr, opt, decay,
    precision, epoch_mode, run_mode=False, clip_norm=None,
):
    """THE training kernel body — every public variant (step/epoch/run x
    sgd/momentum/adam) compiles from this one definition so the plumbing
    cannot drift:

    - ``opt``: {"kind": "sgd"} | {"kind": "momentum", "mu": f} |
      {"kind": "adam", "b1": f, "b2": f, "eps": f}. The operand list
      carries one params-mirror group per state mirror (momentum: velocity;
      adam: m then v) and one (1, 1) block per scalar slot (adam: the step
      counter t), per _OPT_GEOMETRY.
    - ``epoch_mode``: False = one batch per launch (refs are plain in/out);
      True = the grid is the batch axis — inputs seed the REVISITED output
      blocks at grid step 0, which then hold the live params + state in
      VMEM for the whole epoch, and the loss block accumulates the
      per-batch losses before a final divide (matching the epoch scan's
      sum-then-divide order exactly).
    - ``run_mode`` (requires ``epoch_mode``): the grid is (epochs, batches)
      — the ENTIRE multi-epoch run is one kernel. Params + state seed at
      the very first grid step and stay VMEM-resident for the whole run;
      the loss block's index map follows the epoch axis, so each epoch
      accumulates its own mean into ``losses[e]`` with the same
      zero/sum/divide order as the single-epoch kernel.

    Operand layout: ``[x, y] + ins + outs + [loss]`` where ``ins``/``outs``
    are ``w*L + b*L`` then mirror groups (each ``w*L + b*L``-shaped) then
    scalar (1, 1) blocks.
    """
    kind = opt["kind"]
    n_mirrors, n_scalars = _OPT_GEOMETRY[kind]
    n = 2 * L * (1 + n_mirrors) + n_scalars
    ins = refs[:n]
    outs = refs[n : 2 * n]
    loss_ref = refs[2 * n]

    if epoch_mode:
        if run_mode:
            e_idx, b_idx = pl.program_id(0), pl.program_id(1)
            nb = pl.num_programs(1)
            first_step = (e_idx == 0) & (b_idx == 0)
        else:
            b_idx = pl.program_id(0)
            nb = pl.num_programs(0)
            first_step = b_idx == 0

        @pl.when(first_step)
        def _init():
            for i in range(n):
                outs[i][:] = ins[i][:]

        # the loss block is revisited per epoch in run_mode (its index map
        # follows the epoch axis), so it zeroes at the START of every epoch
        # — for the single-epoch kernel this is the same b == 0 step _init
        # runs on, preserving the exact zero/sum/divide order
        @pl.when(b_idx == 0)
        def _zero_loss():
            loss_ref[0, 0] = 0.0

        src = outs  # current params + state live in the revisited out blocks
    else:
        src = ins

    ws = [src[i][:] for i in range(L)]
    bs = [src[L + i][:] for i in range(L)]
    common = dict(
        relu_flags=relu_flags, group_rows=group_rows, batch_size=batch_size,
        lr=lr, decay=decay, precision=precision, clip_norm=clip_norm,
    )
    if kind == "sgd":
        new_ws, new_bs, loss = _sgd_batch_math(
            x_ref[:], y_ref[:], ws, bs, **common
        )
        new_vals = new_ws + new_bs
    elif kind == "momentum":
        vws = [src[2 * L + i][:] for i in range(L)]
        vbs = [src[3 * L + i][:] for i in range(L)]
        new_ws, new_bs, new_vws, new_vbs, loss = _momentum_batch_math(
            x_ref[:], y_ref[:], ws, bs, vws, vbs, mu=opt["mu"], **common
        )
        new_vals = new_ws + new_bs + new_vws + new_vbs
    else:  # adam
        mws = [src[2 * L + i][:] for i in range(L)]
        mbs = [src[3 * L + i][:] for i in range(L)]
        vws = [src[4 * L + i][:] for i in range(L)]
        vbs = [src[5 * L + i][:] for i in range(L)]
        t = src[6 * L][0, 0]
        new_ws, new_bs, new_mws, new_mbs, new_vws, new_vbs, t_new, loss = (
            _adam_batch_math(
                x_ref[:], y_ref[:], ws, bs, mws, mbs, vws, vbs, t,
                b1=opt["b1"], b2=opt["b2"], eps=opt["eps"], **common,
            )
        )
        new_vals = new_ws + new_bs + new_mws + new_mbs + new_vws + new_vbs
        outs[6 * L][0, 0] = t_new
    for i, v in enumerate(new_vals):
        outs[i][:] = v

    if epoch_mode:
        loss_ref[0, 0] += loss

        @pl.when(b_idx == nb - 1)
        def _final():
            loss_ref[0, 0] = loss_ref[0, 0] / nb

    else:
        loss_ref[0, 0] = loss


# ---------------------------------------------------------------------------
# Whole-RUN mega-kernel: (epochs x batches) as the Pallas grid
# ---------------------------------------------------------------------------
#
# The epoch kernel collapses an epoch to one device op, but a 20-epoch
# convergence run is still ~20 serial dispatches (plus scan bookkeeping) on
# the op-issue-bound critical path. In run_mode the grid gains an OUTER
# epoch axis: TPU grid steps execute row-major (epoch-major), params and
# optimizer state seed once and live in the revisited output blocks for the
# WHOLE run, x/y blocks re-stream each epoch (their index map ignores the
# epoch axis), and the per-epoch mean losses land in a (n_epochs, 1) output
# whose block follows the epoch axis. The entire training RUN — the
# reference's outermost loop — becomes ONE device op. Bit-identical to
# looping the epoch kernel (tested); eval stays outside (per-epoch
# accuracies need per-epoch params, so the evaluated run keeps the
# epochs-outer scan).


def fused_train_call(
    stage_params, x, y, *, epoch_mode, relu_flags, group_rows,
    batch_size, lr, weight_decay, precision, opt=None, mirrors=(), scalars=(),
    clip_norm=None, n_epochs=None,
):
    """THE public entry point for every fused-training kernel variant
    (step/epoch x sgd/momentum/adam — trainer._fused_kernel_call is the
    sole caller and owns the optimizer-state mapping): assembles the flat
    operand list (params, then one mirror group per optimizer state
    mirror, then (1, 1) scalar slots), the (optional) batch-axis grid with
    constant-index blocks, and unpacks the outputs. ``opt`` is the
    kernel-body optimizer descriptor (default plain SGD; see
    _train_kernel_body); ``mirrors``/``scalars`` must match its
    _OPT_GEOMETRY. ``epoch_mode=False`` takes x: (B, in), y: (B, out) and
    runs one batch; ``epoch_mode=True`` takes X: (nb, B, in), Y: (nb, B,
    out) and runs the whole epoch as one kernel; with ``n_epochs`` set
    (requires epoch_mode) the grid is (n_epochs, nb) and the ENTIRE run is
    one kernel — ``loss`` comes back as the (n_epochs,) per-epoch means.
    ``clip_norm``: optional global-norm gradient clipping inside the
    kernel (see _batch_grads — bit-identical to the XLA path's
    optimizer.clip_tree). Returns ``(new_stage_params, new_mirrors,
    new_scalars, loss)``."""
    from shallowspeed_tpu.optimizer import _decay_factor

    opt = opt or {"kind": "sgd"}
    # explicit raise, not assert: the geometry contract must hold under
    # ``python -O`` too — a mismatched call would otherwise silently
    # mis-slice the flat operand list
    if _OPT_GEOMETRY[opt["kind"]] != (len(mirrors), len(scalars)):
        raise ValueError(
            f"optimizer kind {opt['kind']!r} expects "
            f"{_OPT_GEOMETRY[opt['kind']]} (mirror, scalar) operand groups, "
            f"got ({len(mirrors)}, {len(scalars)})"
        )
    L = len(stage_params)

    def flat_group(group):
        return [sp["W"] for sp in group] + [
            jnp.reshape(sp["b"], (1, -1)) for sp in group
        ]

    flat = flat_group(stage_params)
    for mirror in mirrors:
        flat += flat_group(mirror)
    flat += [jnp.reshape(jnp.asarray(s, jnp.float32), (1, 1)) for s in scalars]
    decay = _decay_factor(lr, weight_decay) if weight_decay else 1.0
    if n_epochs is not None and not epoch_mode:
        raise ValueError("n_epochs requires epoch_mode=True")
    kernel = functools.partial(
        _train_kernel_body,
        L=L, relu_flags=tuple(relu_flags), group_rows=group_rows,
        batch_size=batch_size, lr=lr, opt=opt, decay=decay,
        precision=precision, epoch_mode=epoch_mode,
        run_mode=n_epochs is not None, clip_norm=clip_norm,
    )
    loss_shape = (1, 1) if n_epochs is None else (n_epochs, 1)
    out_shape = tuple(
        [jax.ShapeDtypeStruct(a.shape, jnp.float32) for a in flat]
        + [jax.ShapeDtypeStruct(loss_shape, jnp.float32)]
    )
    if epoch_mode:
        nb, B_, din = x.shape
        dout = y.shape[-1]
        x = jnp.reshape(x, (nb * B_, din))
        y = jnp.reshape(y, (nb * B_, dout))
        if n_epochs is None:
            const = lambda shape: pl.BlockSpec(  # noqa: E731
                shape, lambda b: tuple(0 for _ in shape),
                memory_space=pltpu.VMEM,
            )
            xy_specs = [
                pl.BlockSpec((B_, din), lambda b: (b, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((B_, dout), lambda b: (b, 0), memory_space=pltpu.VMEM),
            ]
            loss_spec = const((1, 1))
            grid = (nb,)
        else:
            # epoch-major grid; x/y index maps ignore the epoch axis (the
            # same data re-streams every epoch), the loss block follows it
            const = lambda shape: pl.BlockSpec(  # noqa: E731
                shape, lambda e, b: tuple(0 for _ in shape),
                memory_space=pltpu.VMEM,
            )
            xy_specs = [
                pl.BlockSpec((B_, din), lambda e, b: (b, 0), memory_space=pltpu.VMEM),
                pl.BlockSpec((B_, dout), lambda e, b: (b, 0), memory_space=pltpu.VMEM),
            ]
            loss_spec = pl.BlockSpec(
                (1, 1), lambda e, b: (e, 0), memory_space=pltpu.VMEM
            )
            grid = (n_epochs, nb)
        call_kwargs = dict(
            grid=grid,
            in_specs=xy_specs + [const(a.shape) for a in flat],
            out_specs=tuple([const(a.shape) for a in flat] + [loss_spec]),
        )
    else:
        call_kwargs = dict(
            in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * (2 + len(flat)),
            out_specs=tuple(
                [pl.BlockSpec(memory_space=pltpu.VMEM)] * (len(flat) + 1)
            ),
        )
    outs = pl.pallas_call(
        kernel, out_shape=out_shape, interpret=_interpret(), **call_kwargs
    )(x, y, *flat)

    def unflat_group(g):
        base = 2 * L * g
        return [{"W": outs[base + l], "b": outs[base + L + l]} for l in range(L)]

    new_params = unflat_group(0)
    new_mirrors = [unflat_group(1 + i) for i in range(len(mirrors))]
    sc_base = 2 * L * (1 + len(mirrors))
    new_scalars = [
        jnp.reshape(outs[sc_base + i], ()) for i in range(len(scalars))
    ]
    loss_out = outs[len(flat)]
    loss = loss_out[0, 0] if n_epochs is None else jnp.reshape(loss_out, (-1,))
    return new_params, new_mirrors, new_scalars, loss


# ---------------------------------------------------------------------------
# Whole-EPOCH mega-kernel: the batch dimension as the Pallas grid
# ---------------------------------------------------------------------------
#
# The step mega-kernel collapses ~40 XLA ops per batch into 1, but an epoch
# is still a lax.scan issuing one kernel per batch (~464 serial dispatches
# for the flagship dataset) — each paying the measured ~240 ns op-issue
# floor plus scan bookkeeping. In epoch_mode the GRID is the batch
# dimension: TPU grid steps execute sequentially, so the params (and
# velocity) live in the revisited output blocks (constant index maps keep
# them VMEM-resident across the whole grid; x/y stream in per-batch with
# Pallas's automatic double buffering) and the ENTIRE epoch is ONE kernel
# launch. Expressions are identical to the step variant per batch and the
# loss-mean accumulation matches the epoch scan's order, so the result is
# bit-identical to the scan-of-megakernel path (interpreter-verified;
# on-chip equality measured by capture phase 2c).


def train_step_kernel_fits(batch_rows, sizes, state_mirrors=0):
    """Conservative VMEM feasibility check for the mega-kernel: params (x2
    for the updated copies, plus in+out copies of each optimizer state
    mirror — momentum: 1 velocity mirror, adam: m and v), activations +
    masks at ``batch_rows``, and the input batch, against the single-block
    budget."""
    return (
        _kernel_bytes(batch_rows, sizes, state_mirrors)
        <= SINGLE_BLOCK_BUDGET_BYTES
    )


def train_epoch_kernel_fits(batch_rows, sizes, state_mirrors=0):
    """VMEM feasibility for the whole-EPOCH kernel: the step kernel's
    working set PLUS a second copy of the streamed x/y blocks — Pallas
    double-buffers the per-grid-step input fetches, so two batches' worth
    of x/y can be resident at once.

    ADVISORY, not a guarantee: the model counts operands and the streaming
    double-buffer but cannot see scratch/staging Mosaic may add for the
    revisited constant-index param blocks, so on a REAL TPU backend a
    12.5% safety margin is held back from the budget. In interpreter mode
    (CPU CI) there is no VMEM and the full budget applies — the margin
    must not reject configs that always worked off-chip. The margin (and
    the byte model itself) is to be calibrated against a real Mosaic
    compile log at flagship shapes when the chip answers (round-4 verdict
    #5; capture phase t0-vmem records compiled-or-failed + the compiler's
    memory analysis) — until then a config that passes here can still OOM
    at compile time on hardware; the capture records that as a phase
    error rather than assuming the predicate. The step kernel keeps the
    full budget: its single-block operand accounting is exact, while the
    margin covers specifically the epoch kernel's streaming/staging
    unknowns."""
    widths = list(sizes)
    stream_extra = 4 * batch_rows * (widths[0] + widths[-1])
    budget = SINGLE_BLOCK_BUDGET_BYTES
    if not _interpret():
        budget -= SINGLE_BLOCK_BUDGET_BYTES // 8
    return (
        _kernel_bytes(batch_rows, sizes, state_mirrors) + stream_extra
        <= budget
    )


def _kernel_bytes(batch_rows, sizes, state_mirrors=0):
    widths = list(sizes)
    params = sum(widths[i] * widths[i + 1] + widths[i + 1] for i in range(len(widths) - 1))
    state = 2 * params * state_mirrors  # in + out copies per state mirror
    acts = batch_rows * sum(widths)  # layer inputs
    masks = batch_rows * sum(widths[1:-1])
    io = batch_rows * (widths[0] + widths[-1])
    return 4 * (2 * params + state + acts + masks + io)
