#!/bin/bash
# Tunnel watcher: probe the axon TPU tunnel and run the one-claim capture the
# moment it answers. The capture itself is wedge-contained (tier-0 banking,
# per-phase budgets, --resume), so the watcher's only jobs are (1) never miss
# a healthy window, (2) retry a killed capture WITH --resume so completed
# phases are never re-measured, (3) stop when the full artifact exists.
#
# Probe cadence: bounded exponential backoff with jitter from the shared
# retry helper (python -m shallowspeed_tpu.retry — the same policy the
# checkpoint writer and bench's probe loop use), NOT a fixed interval: the
# r05 watcher hammered a dead tunnel on a fixed 10-minute cadence for 48
# consecutive probes. Delays grow 120 s -> 1800 s cap (±20% jitter) while
# the tunnel stays dead, and reset to the base the moment a probe succeeds.
#
# Usage: scripts/tunnel_watch.sh [OUT_JSON] [WINDOW_SECONDS]
#   OUT_JSON        capture artifact path (default TPU_CAPTURE_r05.json)
#   WINDOW_SECONDS  how long to keep watching (default 39600 = 11 h)
# Logs to /tmp/tunnel_probe.log; capture output to /tmp/capture_watch.log.
OUT=${1:-TPU_CAPTURE_r05.json}
END=$(( $(date +%s) + ${2:-39600} ))
LOG=/tmp/tunnel_probe.log
SEED=${TUNNEL_BACKOFF_SEED:-$$}
ATTEMPT=0
cd "$(dirname "$0")/.."
while [ "$(date +%s)" -lt "$END" ]; do
  if [ -f "$OUT" ]; then
    echo "$(date -u +%FT%TZ) full artifact exists; watcher done" >> "$LOG"
    exit 0
  fi
  T0=$(date +%s)
  timeout 150 python -c "import jax; jax.devices()" >/dev/null 2>&1
  RC=$?
  echo "$(date -u +%FT%TZ) rc=$RC dt=$(( $(date +%s) - T0 ))s" >> "$LOG"
  if [ "$RC" = "0" ]; then
    ATTEMPT=0
    echo "$(date -u +%FT%TZ) TUNNEL HEALTHY -> capture (--resume)" >> "$LOG"
    timeout 10800 python scripts/tpu_capture.py --resume --out "$OUT" \
      >> /tmp/capture_watch.log 2>&1
    echo "$(date -u +%FT%TZ) capture rc=$?" >> "$LOG"
    [ -f "$OUT" ] && exit 0
    sleep 300
  else
    DELAY=$(python -m shallowspeed_tpu.retry --attempts $(( ATTEMPT + 1 )) \
      --base 120 --max 1800 --jitter 0.2 --seed "$SEED" | tail -1)
    [ -n "$DELAY" ] || DELAY=600  # helper unavailable: old fixed cadence
    ATTEMPT=$(( ATTEMPT + 1 ))
    echo "$(date -u +%FT%TZ) backoff attempt=$ATTEMPT sleep=${DELAY}s" >> "$LOG"
    sleep "$DELAY"
  fi
done
echo "$(date -u +%FT%TZ) watch window ended" >> "$LOG"
