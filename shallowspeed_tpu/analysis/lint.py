"""The house-rule linter CLI: ``python -m shallowspeed_tpu.analysis.lint``.

Runs the AST rules in ``analysis/rules.py`` over the repo (or explicit
paths) and reports findings as ``path:line:col: RULE message``. Exit
codes follow the gate contract ``make lint`` relies on:

- 0  no findings;
- 1  the linter itself failed (unreadable path, broken registry);
- 2  findings — one line each, file:line named, so CI output is
     actionable without re-running anything.

``--format json`` emits the stable machine-readable report instead
(``lint_report_version`` pins the shape): ``{"lint_report_version": 1,
"files_scanned": n, "findings": [{rule, path, line, col, message}...],
"counts": {rule: n}}``.

Default targets (repo-root-relative): the ``shallowspeed_tpu`` package,
``scripts/``, and the top-level entry points — NOT ``tests/`` (the
fixture corpus under ``tests/lint_fixtures/`` exists to violate the
rules, and test code legitimately asserts on broad exception classes).
"""

import argparse
import json
import sys
from pathlib import Path

from shallowspeed_tpu.analysis.rules import (
    RULE_IDS,
    lint_file,
    load_schema_kinds,
)

DEFAULT_TARGETS = (
    "shallowspeed_tpu",
    "scripts",
    "train.py",
    "bench.py",
    "prepare_data.py",
    "setup.py",
)

LINT_REPORT_VERSION = 1


def _repo_root():
    """The repo root: the directory holding the ``shallowspeed_tpu``
    package this module was imported from."""
    return Path(__file__).resolve().parents[2]


def iter_target_files(paths=None, root=None):
    """Expand targets into the sorted list of .py files to lint."""
    root = Path(root) if root is not None else _repo_root()
    if not paths:
        paths = [root / t for t in DEFAULT_TARGETS]
    files = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(
                f for f in p.rglob("*.py") if "__pycache__" not in f.parts
            )
        elif p.exists():
            files.append(p)
        else:
            raise FileNotFoundError(f"lint target does not exist: {p}")
    return sorted(set(files))


def lint_paths(paths=None, root=None):
    """Lint the target set; returns ``(findings, files_scanned)``."""
    kinds = load_schema_kinds()
    findings = []
    files = iter_target_files(paths, root=root)
    for f in files:
        findings.extend(lint_file(f, schema_kinds=kinds))
    return findings, len(files)


def report(findings, files_scanned, fmt="text"):
    """Render the findings; returns the report string."""
    if fmt == "json":
        counts = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return json.dumps(
            {
                "lint_report_version": LINT_REPORT_VERSION,
                "files_scanned": files_scanned,
                "findings": [f.as_dict() for f in findings],
                "counts": counts,
            },
            indent=2,
            sort_keys=True,
            allow_nan=False,
        )
    lines = [f.format() for f in findings]
    verdict = (
        f"{len(findings)} finding(s) in {files_scanned} file(s)"
        if findings
        else f"clean: 0 findings in {files_scanned} file(s)"
    )
    return "\n".join([*lines, verdict])


def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.analysis.lint",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument(
        "paths", nargs="*",
        help="files/directories to lint (default: the repo's lintable set)",
    )
    ap.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="report format (json is the stable machine-readable shape)",
    )
    ap.add_argument(
        "--metrics-out", default=None,
        help="also record the verdict as a schema-v9 static_analysis "
        "JSONL record (name: 'lint', per-rule finding counts)",
    )
    args = ap.parse_args(argv)
    try:
        findings, n_files = lint_paths(args.paths or None)
    except (OSError, ValueError) as e:
        print(f"lint: error: {e}", file=sys.stderr)
        return 1
    if args.metrics_out:
        from shallowspeed_tpu.observability import JsonlMetrics

        counts = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        with JsonlMetrics(args.metrics_out) as m:
            m.static_analysis(
                "lint",
                passes=sorted(RULE_IDS),
                findings=len(findings),
                by_rule=counts,
                files_scanned=n_files,
                finding_lines=[f.format() for f in findings[:50]],
            )
    print(report(findings, n_files, fmt=args.format))
    return 2 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
