"""Op-kernel tests: hand-written VJPs vs the jax.grad autodiff oracle.

Strictly stronger than the reference's finite-difference checks
(/root/reference/tests/test_functional.py): jax.grad of the same forward is
exact to float rounding, and we also verify the padding-safety contract the
SPMD executor relies on.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shallowspeed_tpu import ops

RNG = np.random.RandomState(0)


def r(*shape):
    return jnp.asarray(RNG.randn(*shape).astype(np.float32))


class TestShapes:
    def test_linear(self):
        x, w, b = r(8, 5), r(3, 5), r(1, 3)
        assert ops.linear(x, w, b).shape == (8, 3)
        dx, dw, db = ops.linear_grad(r(8, 3), x, w)
        assert dx.shape == (8, 5) and dw.shape == (3, 5) and db.shape == (3,)

    def test_softmax(self):
        z = r(8, 10)
        p = ops.softmax(z)
        assert p.shape == (8, 10)
        np.testing.assert_allclose(p.sum(1), 1.0, atol=1e-4)
        assert (p >= 0).all()

    def test_softmax_shift_invariance(self):
        z = r(4, 10)
        np.testing.assert_allclose(
            ops.softmax(z), ops.softmax(z + 3.0), rtol=1e-4, atol=1e-6
        )

    def test_softmax_group_rows_matches_per_group_loop(self):
        """group_rows=k must reproduce exactly what softmax-per-k-row-chunk
        computes — including under adversarial magnitude spread where the
        +1e-7 denominator makes the grouping observable."""
        z = np.array(r(12, 10))  # writable host copy
        z[4:8] += 40.0  # one group's logits dwarf the others
        z = jnp.asarray(z)
        got = ops.softmax(z, group_rows=4)
        want = jnp.concatenate([ops.softmax(z[i : i + 4]) for i in range(0, 12, 4)])
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
        # and ungrouped genuinely differs here (the quirk is observable)
        assert not np.allclose(np.asarray(ops.softmax(z)), np.asarray(want))


class TestGradOracle:
    """Each hand-written backward must equal jax.grad of its forward."""

    def test_relu_grad(self):
        x, g = r(6, 7), r(6, 7)
        want = jax.vjp(ops.relu, x)[1](g)[0]
        got = ops.relu_grad(g, x > 0)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_linear_grad(self):
        x, w, b, g = r(8, 5), r(3, 5), r(1, 3), r(8, 3)
        _, vjp = jax.vjp(lambda x, w, b: ops.linear(x, w, b), x, w, b)
        wx, ww, wb = vjp(g)
        dx, dw, db = ops.linear_grad(g, x, w)
        np.testing.assert_allclose(dx, wx, atol=1e-5)
        np.testing.assert_allclose(dw, ww, atol=1e-5)
        np.testing.assert_allclose(db, jnp.reshape(wb, (-1,)), atol=1e-5)

    def test_linear_grad_is_composition_of_split_halves(self):
        """The combined backward IS the composition of the split halves —
        bit-for-bit, which is what makes the two-stage pipeline backward
        (B-input / B-weight) trivially bitwise-equal to the combined one."""
        x, w, g = r(8, 5), r(3, 5), r(8, 3)
        dx, dw, db = ops.linear_grad(g, x, w)
        dxi = ops.linear_grad_input(g, w)
        dww, dbw = ops.linear_grad_weight(g, x)
        np.testing.assert_array_equal(np.asarray(dx), np.asarray(dxi))
        np.testing.assert_array_equal(np.asarray(dw), np.asarray(dww))
        np.testing.assert_array_equal(np.asarray(db), np.asarray(dbw))
        # the fused relu-unit halves compose the same way
        mask = r(8, 3) > 0
        dxf, dwf, dbf = ops.linear_relu_grad_fused(g, mask, x, w)
        np.testing.assert_array_equal(
            np.asarray(dxf), np.asarray(ops.linear_relu_grad_input(g, mask, w))
        )
        dww2, dbw2 = ops.linear_relu_grad_weight(g, mask, x)
        np.testing.assert_array_equal(np.asarray(dwf), np.asarray(dww2))
        np.testing.assert_array_equal(np.asarray(dbf), np.asarray(dbw2))

    def test_softmax_grad(self):
        z, g = r(5, 10), r(5, 10)
        _, vjp = jax.vjp(ops.softmax, z)
        np.testing.assert_allclose(
            ops.softmax_grad(g, z), vjp(g)[0], atol=1e-5
        )

    def test_mse_grad(self):
        p, t = r(5, 10), r(5, 10)
        want = jax.grad(lambda p: ops.mse_loss(p, t, 128))(p)
        np.testing.assert_allclose(ops.mse_loss_grad(p, t, 128), want, atol=1e-6)

    def test_fused_head_grad(self):
        z, t = r(5, 10), r(5, 10)
        want = jax.grad(lambda z: ops.mse_loss(ops.softmax(z), t, 128))(z)
        got = ops.softmax_mse_head_grad(z, t, 128)
        np.testing.assert_allclose(got, want, atol=1e-6)


class TestPaddingSafety:
    """Zero-padded rows/cols must stay exactly zero through every op — the
    invariant the fixed-shape stacked-stage executor depends on."""

    def test_linear_padding(self):
        x, w, b = np.zeros((4, 8), np.float32), np.zeros((8, 8), np.float32), np.zeros(
            (1, 8), np.float32
        )
        x[:, :5] = RNG.randn(4, 5)
        w[:3, :5] = RNG.randn(3, 5)
        y = np.asarray(ops.linear(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b)))
        assert (y[:, 3:] == 0).all()
        dx, dw, db = ops.linear_grad(jnp.asarray(y), jnp.asarray(x), jnp.asarray(w))
        assert (np.asarray(dx)[:, 5:] == 0).all()
        assert (np.asarray(dw)[3:, :] == 0).all()
        assert (np.asarray(dw)[:, 5:] == 0).all()

    def test_masked_softmax_matches_sliced(self):
        z = r(6, 8)
        mask = jnp.arange(8) < 5
        full = ops.softmax(jnp.where(mask, z, 0.0), valid_mask=mask)
        sliced = ops.softmax(z[:, :5])
        np.testing.assert_allclose(full[:, :5], sliced, rtol=1e-4, atol=1e-6)
        assert (np.asarray(full)[:, 5:] == 0).all()

    def test_masked_head_grad_stays_in_block(self):
        z = jnp.zeros((4, 8)).at[:, :5].set(r(4, 5))
        t = jnp.zeros((4, 8)).at[:, :5].set(r(4, 5))
        mask = jnp.arange(8) < 5
        g = ops.softmax_mse_head_grad(z, t, 32, valid_mask=mask)
        assert (np.asarray(g)[:, 5:] == 0).all()
        want = ops.softmax_mse_head_grad(z[:, :5], t[:, :5], 32)
        np.testing.assert_allclose(g[:, :5], want, rtol=1e-4, atol=1e-6)
