"""Render a pipeline schedule's clock-tick program as an ASCII pebble diagram.

The reference's README illustrates its schedules with a pebble-graph GIF
(README.md:41) that is a static asset; here the diagram is generated from
the ACTUAL lowered tick program, so what you see is exactly what the SPMD
executor will run — forward cells, backward cells, and the bubbles.

    python scripts/show_schedule.py gpipe --mubatches 4 --stages 4
    python scripts/show_schedule.py --all

Legend: F<m> forward of microbatch m · B<m> backward · '.' bubble (noop tick).
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from shallowspeed_tpu import schedules as S  # noqa: E402
from shallowspeed_tpu.parallel.lowering import (  # noqa: E402
    OP_BWD,
    OP_FWD,
    lower_schedule,
)

ALL = {**S.SCHEDULES, "inference": S.InferenceSchedule}


def render(name, M, stages):
    prog = lower_schedule(ALL[name], M, stages)
    width = max(2, len(str(M - 1)) + 1)
    busy = 0
    lines = []
    for s in range(stages):
        cells = []
        for t in range(prog.num_ticks):
            op, mb = int(prog.op[t, s]), int(prog.mb[t, s])
            if op == OP_FWD:
                cells.append(f"F{mb}".ljust(width))
                busy += 1
            elif op == OP_BWD:
                cells.append(f"B{mb}".ljust(width))
                busy += 1
            else:
                cells.append(".".ljust(width))
        lines.append(f"stage {s} │ " + " ".join(cells))
    util = busy / (prog.num_ticks * stages)
    header = (
        f"{name}  M={M} S={stages}: {prog.num_ticks} ticks, "
        f"utilization {util * 100:.0f}% (bubbles {100 - util * 100:.0f}%)"
    )
    print(header)
    print("─" * len(header))
    tick_hdr = "        │ " + " ".join(str(t).ljust(width) for t in range(prog.num_ticks))
    print(tick_hdr)
    for line in lines:
        print(line)
    print()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("schedule", nargs="?", choices=sorted(ALL), default=None)
    ap.add_argument("--mubatches", "-m", type=int, default=4)
    ap.add_argument("--stages", "-s", type=int, default=4)
    ap.add_argument(
        "--all",
        action="store_true",
        help="render every schedule, including the forward-only inference relay",
    )
    args = ap.parse_args()
    if args.schedule and not args.all:
        names = [args.schedule]
    elif args.all:
        names = sorted(ALL)
    else:
        names = sorted(S.SCHEDULES)
    for name in names:
        render(name, args.mubatches, args.stages)


if __name__ == "__main__":
    main()
