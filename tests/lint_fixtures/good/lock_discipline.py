"""SSP006 good twin: every touch of the guarded attribute holds the lock."""

import threading


class Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self._buf = []

    def append(self, item):
        with self._lock:
            self._buf = self._buf + [item]

    def drain(self):
        with self._lock:
            out, self._buf = self._buf, []
        return out
